"""Serving chaos matrix: every injected fault, every request terminal.

The serving stack's robustness contract under the deterministic fault
harness (``mxnet_tpu.parallel.chaos``): for each serve fault mode —
``request_burst``, ``dispatch_stall``, ``executable_poison``,
``deadline_storm`` — every submitted request (synthetic burst clones
included) reaches a terminal outcome (result / timeout / reject) within
its deadline + grace, the server never deadlocks, and the shutdown is
clean.  Every scenario runs inside ``LockOrderSanitizer`` and must
satisfy the PR-7 static-vs-runtime contract: the observed
acquisition-order graph is cycle-free AND a subgraph of
``tools.lint.concurrency.static_lock_graph(mxnet_tpu/)``.

The graftlint side of the same coin: the serve threads are registered
in the package thread-entry model (conc-thread-lifecycle sees the stop
Event + joins), and the package gate keeps ZERO findings / an empty
baseline over mxnet_tpu/serve/.
"""
import collections
import os
import sys
import time

import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import serve, telemetry
from mxnet_tpu.parallel import chaos

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

from tools.lint.runtime_lockorder import LockOrderSanitizer  # noqa: E402

# package_lock_graph: session-scoped fixture from tests/conftest.py

FEAT = (8,)
W = onp.ones((8, 3), "float32")


def _fn(x):
    import jax.numpy as jnp
    return x @ jnp.asarray(W)


def _cfg(**kw):
    base = dict(buckets=(1, 2, 4), max_queue=8, batch_wait_ms=2.0,
                default_deadline_ms=400.0, dispatch_timeout_ms=80.0,
                watchdog_interval_ms=15.0)
    base.update(kw)
    return serve.ServeConfig(**base)


@pytest.fixture(autouse=True)
def _clean_faults():
    chaos.clear()
    yield
    chaos.clear()


GRACE_S = 1.5


def _drive(install_fault, package_lock_graph, n=8, deadline_ms=400.0,
           cfg=None, second_wave=0, wave2_delay=0.0):
    """One chaos scenario under the sanitizer.  Returns (terminal
    outcome counter over ALL requests incl. synthetic clones, the
    server, second-wave outcomes)."""
    with LockOrderSanitizer() as san:
        srv = serve.InferenceServer(_fn, feature_shape=FEAT,
                                    config=cfg or _cfg())
        srv.start()
        install_fault(srv)
        handles = [srv.submit(onp.full(FEAT, i, "float32"),
                              deadline_ms=deadline_ms)
                   for i in range(n)]
        wait = deadline_ms / 1e3 + GRACE_S
        outs = [h.outcome(timeout=wait) for h in handles]
        outs += [c.outcome(timeout=wait) for c in srv._synthetic]
        wave2 = []
        if second_wave:
            chaos.clear()
            if wave2_delay:
                time.sleep(wave2_delay)
            for i in range(second_wave):
                h = srv.submit(onp.full(FEAT, i, "float32"),
                               deadline_ms=deadline_ms)
                wave2.append(h.outcome(timeout=wait))
        assert srv.close(timeout=10.0)
    # the no-hangs invariant: EVERY request reached a terminal outcome
    # within deadline + grace
    assert all(o is not None for o in outs), \
        "requests with no terminal outcome under chaos"
    for t in (srv._batcher, srv._watchdog, srv._dispatcher):
        assert t is not None and not t.is_alive()
    san.assert_no_cycles()
    san.assert_subgraph_of(package_lock_graph)
    return (collections.Counter(o[0] for o in outs), srv,
            collections.Counter(o[0] for o in wave2 if o is not None))


def test_request_burst_backpressure_not_blocking(package_lock_graph):
    """A deterministic traffic spike: ONE real submission fans into 32
    admissions.  The bounded queue must shed the overflow as immediate
    rejects (never a blocked producer), serve what it admitted, and
    leave every clone terminal."""
    kinds, srv, _ = _drive(
        lambda s: chaos.install("request_burst", factor=32, times=1),
        package_lock_graph, n=2, deadline_ms=600.0)
    assert kinds["result"] >= 1
    assert kinds["reject"] >= 1          # queue_full backpressure fired
    assert sum(kinds.values()) == 2 + 31
    assert telemetry.counter("serve.rejects") > 0


def test_dispatch_stall_watchdog_respawns(package_lock_graph):
    """A hung dispatch (0.4 s stall vs an 80 ms dispatch timeout): the
    watchdog times the stuck batch out, respawns a dispatcher, and a
    second wave — submitted after the fault cleared — is served by the
    replacement."""
    fires0 = telemetry.counter("serve.watchdog_fires")
    kinds, srv, wave2 = _drive(
        lambda s: chaos.install("dispatch_stall", times=1, delay=0.4),
        package_lock_graph, n=6, deadline_ms=400.0, second_wave=3)
    assert kinds["timeout"] >= 1         # the stalled batch
    assert telemetry.counter("serve.watchdog_fires") > fires0
    assert srv.stats()["respawns"] >= 1
    assert wave2["result"] == 3          # the respawned dispatcher serves


def test_executable_poison_quarantine_and_fallback(package_lock_graph):
    """The b=4 executable is poisoned (fails every dispatch): after the
    bounded retry it is quarantined and the SAME requests complete on
    smaller buckets — graceful degradation, zero client-visible
    failures."""
    q0 = telemetry.counter("serve.quarantines")
    kinds, srv, _ = _drive(
        lambda s: chaos.install("executable_poison", bucket=4),
        package_lock_graph, n=8, deadline_ms=800.0)
    assert kinds["result"] == 8, kinds
    assert telemetry.counter("serve.quarantines") == q0 + 1
    assert srv.stats()["quarantined"] == [4]
    # operator runbook: reset re-admits the bucket
    assert srv.reset_quarantine() == [4]
    assert srv.stats()["quarantined"] == []


def test_poison_all_buckets_is_terminal_error(package_lock_graph):
    """Every bucket poisoned: requests must still terminate — as errors
    — and the server must degrade, not deadlock."""
    kinds, srv, _ = _drive(
        lambda s: chaos.install("executable_poison"),
        package_lock_graph, n=4, deadline_ms=600.0,
        cfg=_cfg(max_retries=0))
    assert kinds["result"] == 0
    assert kinds["error"] + kinds["timeout"] == 4, kinds
    assert set(srv.stats()["quarantined"]) <= {1, 2, 4}


def test_deadline_storm_expires_without_dispatch(package_lock_graph):
    """Every deadline collapses to 0: the whole queue must expire
    through the pre-dispatch drop path — terminal timeouts, zero
    executable dispatches wasted."""
    d0 = telemetry.counter("serve.dispatches")
    drops0 = telemetry.counter("serve.deadline_drops")
    kinds, srv, _ = _drive(
        lambda s: chaos.install("deadline_storm", deadline_ms=0),
        package_lock_graph, n=8)
    assert kinds["timeout"] == 8, kinds
    assert telemetry.counter("serve.dispatches") == d0
    assert telemetry.counter("serve.deadline_drops") >= drops0 + 8


def test_respawn_budget_exhausted_still_terminal(package_lock_graph):
    """Review hardening: with the respawn budget at ZERO and the only
    dispatcher wedged, batches piling into the dispatch queue must
    still reach terminal outcomes — the watchdog becomes the consumer
    of record (fail-fast terminal errors in the permanent-DEGRADED
    tail), never a hang."""
    kinds, srv, wave2 = _drive(
        lambda s: chaos.install("dispatch_stall", times=1, delay=0.4),
        package_lock_graph, n=6, deadline_ms=300.0,
        cfg=_cfg(max_respawns=0, dispatch_timeout_ms=60.0,
                 batch_wait_ms=1.0, buckets=(1, 2)),
        second_wave=3, wave2_delay=0.6)
    # every first-wave request terminal (stuck batch -> watchdog
    # timeout; queued batches -> watchdog drain errors) — NO hangs
    assert sum(kinds.values()) == 6
    assert kinds["timeout"] >= 1 and kinds["result"] == 0, kinds
    assert srv.stats()["respawns"] == 0
    # past the budget the server fails FAST and stays DEGRADED even
    # after the wedged worker's stall ends — restart territory
    assert wave2["error"] == 3, wave2


def test_config_rejects_unbounded_queue():
    with pytest.raises(mx.MXNetError):
        serve.ServeConfig(max_queue=0)
    with pytest.raises(mx.MXNetError):
        serve.ServeConfig(max_queue=-4)


# -- ISSUE 18: incident bundles + traced timelines under chaos ---------------

def _bundles(reason):
    from mxnet_tpu import flight_recorder
    base = flight_recorder.incident_dir()
    if not os.path.isdir(base):
        return []
    return sorted(os.path.join(base, d) for d in os.listdir(base)
                  if d.startswith("incident-")
                  and d.endswith("-" + reason))


def _load_journal(bundle):
    import json
    with open(os.path.join(bundle, "journal.jsonl")) as f:
        return [json.loads(ln) for ln in f if ln.strip()]


def test_poison_incident_bundle_recovers_the_story(package_lock_graph):
    """The acceptance postmortem: a poisoned-executable chaos run must
    leave ONE well-formed incident bundle from which the failing
    bucket, the quarantine + DEGRADED transition, and the affected
    requests' trace ids are all recoverable offline."""
    import json
    # the journal ring is process-global: start this postmortem from a
    # clean slate so earlier chaos tests' evicted-half stories don't
    # alias into the bundle
    telemetry.reset()
    kinds, srv, _ = _drive(
        lambda s: chaos.install("executable_poison", bucket=4),
        package_lock_graph, n=8, deadline_ms=800.0)
    assert kinds["result"] == 8, kinds
    bundles = _bundles("serve_quarantine")
    assert len(bundles) == 1, bundles     # fresh quarantine dumps ONCE
    b = bundles[0]
    assert sorted(os.listdir(b)) == [
        "config.json", "hbm.json", "histograms.json", "journal.jsonl",
        "lockgraph.json", "snapshot.json"]
    cfg = json.load(open(os.path.join(b, "config.json")))
    assert cfg["reason"] == "serve_quarantine"
    assert cfg["extra"]["bucket"] == 4
    assert "bucket 4 quarantined" in cfg["detail"]
    recs = _load_journal(b)
    # the failing bucket + transition, straight from the journal tail
    q = [r for r in recs if r.get("kind") == "serve"
         and r.get("name") == "quarantine"]
    assert q and q[-1]["bucket"] == 4
    states = [r for r in recs if r.get("kind") == "serve"
              and r.get("name") == "state"]
    assert any(r["state_to"] == "DEGRADED" for r in states), states
    # the affected requests: dispatch_error on bucket 4 names their
    # trace ids, and each one maps back to a submitted request
    errs = [r for r in recs if r.get("name") == "dispatch_error"
            and r.get("bucket") == 4]
    assert errs, [r.get("name") for r in recs]
    affected = {t for r in errs for t in r["traces"]}
    assert affected
    submitted = {r["trace"] for r in recs if r.get("name") == "request"}
    assert affected <= submitted
    # ... and in the LIVE journal every affected trace still reached a
    # terminal result on a fallback bucket (graceful degradation)
    live = telemetry.snapshot(events=telemetry.JOURNAL_MAXLEN)["events"]
    resolved = {r.get("trace") for r in live if r.get("name") == "outcome"
                and r.get("outcome") == "result"}
    assert affected <= resolved


def test_watchdog_fire_dumps_incident(package_lock_graph):
    import json
    kinds, srv, wave2 = _drive(
        lambda s: chaos.install("dispatch_stall", times=1, delay=0.4),
        package_lock_graph, n=6, deadline_ms=400.0, second_wave=3)
    bundles = _bundles("serve_watchdog")
    assert bundles, "watchdog fired but no incident bundle"
    cfg = json.load(open(os.path.join(bundles[0], "config.json")))
    assert cfg["extra"]["respawned"] is True
    assert cfg["extra"]["timed_out_requests"] >= 1
    assert cfg["extra"]["traces"], cfg["extra"]
    assert "dispatch stuck" in cfg["detail"]


def test_respawn_exhaustion_dumps_incident(package_lock_graph):
    import json
    kinds, srv, wave2 = _drive(
        lambda s: chaos.install("dispatch_stall", times=1, delay=0.4),
        package_lock_graph, n=6, deadline_ms=300.0,
        cfg=_cfg(max_respawns=0, dispatch_timeout_ms=60.0,
                 batch_wait_ms=1.0, buckets=(1, 2)),
        second_wave=3, wave2_delay=0.6)
    bundles = _bundles("serve_respawn_exhausted")
    assert bundles
    cfg = json.load(open(os.path.join(bundles[0], "config.json")))
    assert cfg["extra"]["respawned"] is False


def test_graceful_degradation_modes_dump_no_incidents(
        package_lock_graph):
    """Backpressure sheds and deadline expiry are the system WORKING —
    neither may burn an incident bundle (alert fatigue is how real
    flight recorders get disabled)."""
    from mxnet_tpu import flight_recorder
    _drive(lambda s: chaos.install("request_burst", factor=32, times=1),
           package_lock_graph, n=2, deadline_ms=600.0)
    _drive(lambda s: chaos.install("deadline_storm", deadline_ms=0),
           package_lock_graph, n=8)
    base = flight_recorder.incident_dir()
    dumped = [d for d in (os.listdir(base) if os.path.isdir(base)
                          else []) if d.startswith("incident-")]
    assert not dumped, dumped


def test_chaos_run_exports_collector_mergeable_timeline(
        tmp_path, package_lock_graph):
    """Satellite: the journal a chaos run leaves behind merges into a
    chrome-trace timeline (telemetry_collect) in which one request's
    submit -> queue_wait -> dispatch -> outcome story is followable by
    trace id, and the serve latency histograms ride along."""
    import json
    from mxnet_tpu import telemetry_collect
    telemetry.reset()
    kinds, srv, _ = _drive(
        lambda s: chaos.install("executable_poison", bucket=4),
        package_lock_graph, n=8, deadline_ms=800.0)
    export = str(tmp_path / "serve0.jsonl")
    telemetry.export_jsonl(export)
    meta = telemetry_collect.collect(
        [export], str(tmp_path / "merged.trace.json"),
        hist_out=str(tmp_path / "hist.json"))
    assert "serve.request" in meta["histograms"]
    trace = json.load(open(str(tmp_path / "merged.trace.json")))
    evs = trace["traceEvents"]
    spans = [e for e in evs if e["ph"] == "X"]
    waits = [e for e in spans if e["name"] == "serve.queue_wait"
             and e["args"].get("trace")]
    assert waits
    # follow ONE request end to end by its trace id
    tid = waits[0]["args"]["trace"]
    names = {e["name"] for e in evs
             if (e.get("args") or {}).get("trace") == tid}
    assert "serve.queue_wait" in names
    assert "serve:request" in names and "serve:outcome" in names
    # dispatch spans carry the whole part's traces
    assert any(e["name"] == "serve.dispatch"
               and tid in (e["args"].get("traces") or [])
               for e in spans)
    hists = json.load(open(str(tmp_path / "hist.json")))
    assert hists["serve.request"]["summary"]["count"] >= kinds["result"]
    assert hists["serve.queue_wait"]["hist"]["count"] >= kinds["result"]


# -- graftlint registration -------------------------------------------------

def test_serve_threads_in_lint_thread_entry_model():
    """CI/tooling satellite: the serve batcher/watchdog/dispatcher
    Thread(target=self._method) sites must resolve in the graftlint
    thread-entry model — that is what puts the serve stop/drain path
    under conc-thread-lifecycle (stop Event + join) and the other
    conc-* rules."""
    from tools.lint.core import ModuleInfo, collect_files
    from tools.lint.jitgraph import PackageIndex
    serve_dir = os.path.join(REPO, "mxnet_tpu", "serve")
    mods = []
    for p in collect_files([serve_dir]):
        rel = os.path.relpath(p, REPO).replace(os.sep, "/")
        mods.append(ModuleInfo(p, rel, open(p).read()))
    idx = PackageIndex(mods)
    entries = sorted(idx.thread_entries().values())
    server_rel = "mxnet_tpu/serve/server.py"
    assert sum(1 for e in entries if e.startswith(server_rel)) >= 3, \
        entries                      # batcher + watchdog + dispatcher
    # the loops those threads run are thread-context for the rules
    names = {fi.name for fi in idx.functions
             if id(fi.node) in idx.thread_reachable()}
    assert {"_batch_loop", "_watchdog_loop",
            "_dispatch_loop"} <= names, names


def test_serve_package_gate_zero_findings(package_scan):
    """The tier-1 gate satellite, made explicit for the new subsystem:
    mxnet_tpu/serve/ is scanned and contributes ZERO findings (and zero
    suppressions — the baseline stays empty)."""
    serve_files = [f for f in package_scan.files
                   if f.startswith("mxnet_tpu/serve/")]
    assert len(serve_files) >= 3, package_scan.files
    bad = [f for f in package_scan.new
           if f.path.startswith("mxnet_tpu/serve/")]
    assert not bad, "\n".join(f.render() for f in bad)
    suppressed = [f for f in package_scan.suppressed
                  if f.path.startswith("mxnet_tpu/serve/")]
    assert not suppressed, \
        "serve/ should need no suppressions: %r" % suppressed
