"""Serving chaos matrix: every injected fault, every request terminal.

The serving stack's robustness contract under the deterministic fault
harness (``mxnet_tpu.parallel.chaos``): for each serve fault mode —
``request_burst``, ``dispatch_stall``, ``executable_poison``,
``deadline_storm`` — every submitted request (synthetic burst clones
included) reaches a terminal outcome (result / timeout / reject) within
its deadline + grace, the server never deadlocks, and the shutdown is
clean.  Every scenario runs inside ``LockOrderSanitizer`` and must
satisfy the PR-7 static-vs-runtime contract: the observed
acquisition-order graph is cycle-free AND a subgraph of
``tools.lint.concurrency.static_lock_graph(mxnet_tpu/)``.

The graftlint side of the same coin: the serve threads are registered
in the package thread-entry model (conc-thread-lifecycle sees the stop
Event + joins), and the package gate keeps ZERO findings / an empty
baseline over mxnet_tpu/serve/.
"""
import collections
import os
import sys
import time

import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import serve, telemetry
from mxnet_tpu.parallel import chaos

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

from tools.lint.runtime_lockorder import LockOrderSanitizer  # noqa: E402

# package_lock_graph: session-scoped fixture from tests/conftest.py

FEAT = (8,)
W = onp.ones((8, 3), "float32")


def _fn(x):
    import jax.numpy as jnp
    return x @ jnp.asarray(W)


def _cfg(**kw):
    base = dict(buckets=(1, 2, 4), max_queue=8, batch_wait_ms=2.0,
                default_deadline_ms=400.0, dispatch_timeout_ms=80.0,
                watchdog_interval_ms=15.0)
    base.update(kw)
    return serve.ServeConfig(**base)


@pytest.fixture(autouse=True)
def _clean_faults():
    chaos.clear()
    yield
    chaos.clear()


GRACE_S = 1.5


def _drive(install_fault, package_lock_graph, n=8, deadline_ms=400.0,
           cfg=None, second_wave=0, wave2_delay=0.0):
    """One chaos scenario under the sanitizer.  Returns (terminal
    outcome counter over ALL requests incl. synthetic clones, the
    server, second-wave outcomes)."""
    with LockOrderSanitizer() as san:
        srv = serve.InferenceServer(_fn, feature_shape=FEAT,
                                    config=cfg or _cfg())
        srv.start()
        install_fault(srv)
        handles = [srv.submit(onp.full(FEAT, i, "float32"),
                              deadline_ms=deadline_ms)
                   for i in range(n)]
        wait = deadline_ms / 1e3 + GRACE_S
        outs = [h.outcome(timeout=wait) for h in handles]
        outs += [c.outcome(timeout=wait) for c in srv._synthetic]
        wave2 = []
        if second_wave:
            chaos.clear()
            if wave2_delay:
                time.sleep(wave2_delay)
            for i in range(second_wave):
                h = srv.submit(onp.full(FEAT, i, "float32"),
                               deadline_ms=deadline_ms)
                wave2.append(h.outcome(timeout=wait))
        assert srv.close(timeout=10.0)
    # the no-hangs invariant: EVERY request reached a terminal outcome
    # within deadline + grace
    assert all(o is not None for o in outs), \
        "requests with no terminal outcome under chaos"
    for t in (srv._batcher, srv._watchdog, srv._dispatcher):
        assert t is not None and not t.is_alive()
    san.assert_no_cycles()
    san.assert_subgraph_of(package_lock_graph)
    return (collections.Counter(o[0] for o in outs), srv,
            collections.Counter(o[0] for o in wave2 if o is not None))


def test_request_burst_backpressure_not_blocking(package_lock_graph):
    """A deterministic traffic spike: ONE real submission fans into 32
    admissions.  The bounded queue must shed the overflow as immediate
    rejects (never a blocked producer), serve what it admitted, and
    leave every clone terminal."""
    kinds, srv, _ = _drive(
        lambda s: chaos.install("request_burst", factor=32, times=1),
        package_lock_graph, n=2, deadline_ms=600.0)
    assert kinds["result"] >= 1
    assert kinds["reject"] >= 1          # queue_full backpressure fired
    assert sum(kinds.values()) == 2 + 31
    assert telemetry.counter("serve.rejects") > 0


def test_dispatch_stall_watchdog_respawns(package_lock_graph):
    """A hung dispatch (0.4 s stall vs an 80 ms dispatch timeout): the
    watchdog times the stuck batch out, respawns a dispatcher, and a
    second wave — submitted after the fault cleared — is served by the
    replacement."""
    fires0 = telemetry.counter("serve.watchdog_fires")
    kinds, srv, wave2 = _drive(
        lambda s: chaos.install("dispatch_stall", times=1, delay=0.4),
        package_lock_graph, n=6, deadline_ms=400.0, second_wave=3)
    assert kinds["timeout"] >= 1         # the stalled batch
    assert telemetry.counter("serve.watchdog_fires") > fires0
    assert srv.stats()["respawns"] >= 1
    assert wave2["result"] == 3          # the respawned dispatcher serves


def test_executable_poison_quarantine_and_fallback(package_lock_graph):
    """The b=4 executable is poisoned (fails every dispatch): after the
    bounded retry it is quarantined and the SAME requests complete on
    smaller buckets — graceful degradation, zero client-visible
    failures."""
    q0 = telemetry.counter("serve.quarantines")
    kinds, srv, _ = _drive(
        lambda s: chaos.install("executable_poison", bucket=4),
        package_lock_graph, n=8, deadline_ms=800.0)
    assert kinds["result"] == 8, kinds
    assert telemetry.counter("serve.quarantines") == q0 + 1
    assert srv.stats()["quarantined"] == [4]
    # operator runbook: reset re-admits the bucket
    assert srv.reset_quarantine() == [4]
    assert srv.stats()["quarantined"] == []


def test_poison_all_buckets_is_terminal_error(package_lock_graph):
    """Every bucket poisoned: requests must still terminate — as errors
    — and the server must degrade, not deadlock."""
    kinds, srv, _ = _drive(
        lambda s: chaos.install("executable_poison"),
        package_lock_graph, n=4, deadline_ms=600.0,
        cfg=_cfg(max_retries=0))
    assert kinds["result"] == 0
    assert kinds["error"] + kinds["timeout"] == 4, kinds
    assert set(srv.stats()["quarantined"]) <= {1, 2, 4}


def test_deadline_storm_expires_without_dispatch(package_lock_graph):
    """Every deadline collapses to 0: the whole queue must expire
    through the pre-dispatch drop path — terminal timeouts, zero
    executable dispatches wasted."""
    d0 = telemetry.counter("serve.dispatches")
    drops0 = telemetry.counter("serve.deadline_drops")
    kinds, srv, _ = _drive(
        lambda s: chaos.install("deadline_storm", deadline_ms=0),
        package_lock_graph, n=8)
    assert kinds["timeout"] == 8, kinds
    assert telemetry.counter("serve.dispatches") == d0
    assert telemetry.counter("serve.deadline_drops") >= drops0 + 8


def test_respawn_budget_exhausted_still_terminal(package_lock_graph):
    """Review hardening: with the respawn budget at ZERO and the only
    dispatcher wedged, batches piling into the dispatch queue must
    still reach terminal outcomes — the watchdog becomes the consumer
    of record (fail-fast terminal errors in the permanent-DEGRADED
    tail), never a hang."""
    kinds, srv, wave2 = _drive(
        lambda s: chaos.install("dispatch_stall", times=1, delay=0.4),
        package_lock_graph, n=6, deadline_ms=300.0,
        cfg=_cfg(max_respawns=0, dispatch_timeout_ms=60.0,
                 batch_wait_ms=1.0, buckets=(1, 2)),
        second_wave=3, wave2_delay=0.6)
    # every first-wave request terminal (stuck batch -> watchdog
    # timeout; queued batches -> watchdog drain errors) — NO hangs
    assert sum(kinds.values()) == 6
    assert kinds["timeout"] >= 1 and kinds["result"] == 0, kinds
    assert srv.stats()["respawns"] == 0
    # past the budget the server fails FAST and stays DEGRADED even
    # after the wedged worker's stall ends — restart territory
    assert wave2["error"] == 3, wave2


def test_config_rejects_unbounded_queue():
    with pytest.raises(mx.MXNetError):
        serve.ServeConfig(max_queue=0)
    with pytest.raises(mx.MXNetError):
        serve.ServeConfig(max_queue=-4)


# -- graftlint registration -------------------------------------------------

def test_serve_threads_in_lint_thread_entry_model():
    """CI/tooling satellite: the serve batcher/watchdog/dispatcher
    Thread(target=self._method) sites must resolve in the graftlint
    thread-entry model — that is what puts the serve stop/drain path
    under conc-thread-lifecycle (stop Event + join) and the other
    conc-* rules."""
    from tools.lint.core import ModuleInfo, collect_files
    from tools.lint.jitgraph import PackageIndex
    serve_dir = os.path.join(REPO, "mxnet_tpu", "serve")
    mods = []
    for p in collect_files([serve_dir]):
        rel = os.path.relpath(p, REPO).replace(os.sep, "/")
        mods.append(ModuleInfo(p, rel, open(p).read()))
    idx = PackageIndex(mods)
    entries = sorted(idx.thread_entries().values())
    server_rel = "mxnet_tpu/serve/server.py"
    assert sum(1 for e in entries if e.startswith(server_rel)) >= 3, \
        entries                      # batcher + watchdog + dispatcher
    # the loops those threads run are thread-context for the rules
    names = {fi.name for fi in idx.functions
             if id(fi.node) in idx.thread_reachable()}
    assert {"_batch_loop", "_watchdog_loop",
            "_dispatch_loop"} <= names, names


def test_serve_package_gate_zero_findings(package_scan):
    """The tier-1 gate satellite, made explicit for the new subsystem:
    mxnet_tpu/serve/ is scanned and contributes ZERO findings (and zero
    suppressions — the baseline stays empty)."""
    serve_files = [f for f in package_scan.files
                   if f.startswith("mxnet_tpu/serve/")]
    assert len(serve_files) >= 3, package_scan.files
    bad = [f for f in package_scan.new
           if f.path.startswith("mxnet_tpu/serve/")]
    assert not bad, "\n".join(f.render() for f in bad)
    suppressed = [f for f in package_scan.suppressed
                  if f.path.startswith("mxnet_tpu/serve/")]
    assert not suppressed, \
        "serve/ should need no suppressions: %r" % suppressed
