"""Round-5 gap closures: SVMOutput, IdentityAttachKLSparseReg,
ravel/unravel, linalg_gelqf, LibSVMIter, AttrScope/NameManager.

Reference parity targets: src/operator/svm_output.cc,
src/operator/identity_attach_KL_sparse_reg.cc, src/operator/tensor/
ravel.cc, src/operator/tensor/la_op.cc:752 (gelqf), src/io/iter_libsvm.cc,
python/mxnet/attribute.py:27, python/mxnet/name.py:25."""
import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd


# ---------------------------------------------------------------------------
# SVMOutput
# ---------------------------------------------------------------------------

def _svm_grad_oracle(x, label, margin, reg, use_linear):
    """Direct transcription of the reference L1_SVM/L2_SVM loops."""
    dst = onp.zeros_like(x)
    for y in range(x.shape[0]):
        k = int(label[y])
        for c in range(x.shape[1]):
            if use_linear:
                if c == k:
                    dst[y, k] = -float(margin > x[y, k]) * reg
                else:
                    dst[y, c] = float(margin > -x[y, c]) * reg
            else:
                if c == k:
                    dst[y, k] = 2 * (margin - x[y, k]) \
                        if margin > x[y, k] else 0.0
                    dst[y, k] *= -reg
                else:
                    dst[y, c] = -2 * (margin + x[y, c]) \
                        if margin > -x[y, c] else 0.0
                    dst[y, c] *= -reg
    return dst


@pytest.mark.parametrize("use_linear", [False, True])
def test_svm_output_forward_identity_and_grad(use_linear):
    rs = onp.random.RandomState(0)
    x = rs.randn(6, 5).astype("float32") * 2
    label = rs.randint(0, 5, (6,)).astype("float32")
    margin, reg = 1.0, 0.7

    a = mx.nd.array(x)
    a.attach_grad()
    with autograd.record():
        out = mx.nd.SVMOutput(a, mx.nd.array(label), margin=margin,
                              regularization_coefficient=reg,
                              use_linear=use_linear)
        s = out.sum()
    onp.testing.assert_allclose(out.asnumpy(), x, rtol=1e-6)   # identity fwd
    s.backward()
    want = _svm_grad_oracle(x, label, margin, reg, use_linear)
    onp.testing.assert_allclose(a.grad.asnumpy(), want, rtol=1e-5,
                                atol=1e-6)


def test_svm_output_symbol_path():
    data = mx.sym.Variable("data")
    lab = mx.sym.Variable("label")
    s = mx.sym.SVMOutput(data=data, label=lab, use_linear=True)
    out = s.eval(data=mx.nd.ones((2, 3)), label=mx.nd.zeros((2,)))[0]
    onp.testing.assert_allclose(out.asnumpy(), onp.ones((2, 3)))


# ---------------------------------------------------------------------------
# IdentityAttachKLSparseReg
# ---------------------------------------------------------------------------

def test_identity_attach_kl_sparse_reg_grad():
    rs = onp.random.RandomState(1)
    x = rs.uniform(0.05, 0.95, (8, 4)).astype("float32")
    rho, penalty, momentum = 0.2, 0.01, 0.9
    ma0 = onp.full((4,), 0.5, "float32")

    a = mx.nd.array(x)
    a.attach_grad()
    with autograd.record():
        out = mx.nd.IdentityAttachKLSparseReg(
            a, mx.nd.array(ma0), sparseness_target=rho, penalty=penalty,
            momentum=momentum)
        s = out.sum()
    onp.testing.assert_allclose(out.asnumpy(), x, rtol=1e-6)
    s.backward()
    avg = x.mean(axis=0)
    ma = momentum * ma0 + (1 - momentum) * avg
    kl = penalty * (-rho / ma + (1 - rho) / (1 - ma))
    want = onp.ones_like(x) + kl[None, :]
    onp.testing.assert_allclose(a.grad.asnumpy(), want, rtol=1e-5)


# ---------------------------------------------------------------------------
# ravel / unravel
# ---------------------------------------------------------------------------

def test_ravel_unravel_roundtrip_matches_numpy():
    shape = (3, 4, 5)
    rs = onp.random.RandomState(2)
    flat = rs.randint(0, 60, (17,)).astype("int64")
    multi = onp.stack(onp.unravel_index(flat, shape)).astype("float32")

    got_flat = mx.nd.ravel_multi_index(mx.nd.array(multi), shape=shape)
    onp.testing.assert_array_equal(got_flat.asnumpy().astype("int64"), flat)

    got_multi = mx.nd.unravel_index(
        mx.nd.array(flat.astype("float32")), shape=shape)
    onp.testing.assert_array_equal(got_multi.asnumpy(), multi)


# ---------------------------------------------------------------------------
# linalg_gelqf
# ---------------------------------------------------------------------------

def test_linalg_gelqf_reconstructs_with_conventions():
    rs = onp.random.RandomState(3)
    A = rs.randn(3, 5).astype("float32")
    Q, L = mx.nd.linalg_gelqf(mx.nd.array(A))
    Qn, Ln = Q.asnumpy(), L.asnumpy()
    onp.testing.assert_allclose(Ln @ Qn, A, atol=1e-5)           # A = L Q
    onp.testing.assert_allclose(Qn @ Qn.T, onp.eye(3), atol=1e-5)
    assert onp.allclose(Ln, onp.tril(Ln), atol=1e-6)             # lower tri
    assert (onp.diag(Ln) > 0).all()                              # sign conv
    # batched
    Ab = rs.randn(4, 2, 6).astype("float32")
    Qb, Lb = mx.nd.linalg_gelqf(mx.nd.array(Ab))
    onp.testing.assert_allclose(
        onp.einsum("bij,bjk->bik", Lb.asnumpy(), Qb.asnumpy()), Ab,
        atol=1e-5)


# ---------------------------------------------------------------------------
# LibSVMIter
# ---------------------------------------------------------------------------

def test_libsvm_iter_dense_values(tmp_path):
    p = tmp_path / "data.libsvm"
    p.write_text("1 0:1.5 3:-2\n"
                 "0 1:0.5\n"
                 "2 0:1 1:2 2:3 3:4\n"
                 "1 2:7\n"
                 "0\n")
    it = mx.io.LibSVMIter(data_libsvm=str(p), data_shape=(4,), batch_size=2)
    batches = list(it)
    assert len(batches) == 3          # 5 rows, round_batch pads the last
    d0 = batches[0].data[0].asnumpy()
    onp.testing.assert_allclose(
        d0, [[1.5, 0, 0, -2], [0, 0.5, 0, 0]])
    onp.testing.assert_allclose(batches[0].label[0].asnumpy(), [1, 0])
    onp.testing.assert_allclose(
        batches[1].data[0].asnumpy(), [[1, 2, 3, 4], [0, 0, 7, 0]])
    # empty-feature row decodes to zeros
    onp.testing.assert_allclose(batches[2].data[0].asnumpy()[0],
                                [0, 0, 0, 0])
    it.reset()
    again = next(iter(it)).data[0].asnumpy()
    onp.testing.assert_allclose(again, d0)


def test_libsvm_iter_separate_label_file(tmp_path):
    pd = tmp_path / "d.libsvm"
    pl = tmp_path / "l.libsvm"
    pd.write_text("0 0:1\n0 1:1\n")
    pl.write_text("0 0:0.5 1:0.25\n0 1:1.0\n")
    it = mx.io.LibSVMIter(data_libsvm=str(pd), data_shape=(2,),
                          label_libsvm=str(pl), label_shape=(2,),
                          batch_size=2)
    b = next(iter(it))
    onp.testing.assert_allclose(b.label[0].asnumpy(),
                                [[0.5, 0.25], [0.0, 1.0]])


def test_libsvm_iter_rejects_out_of_range_index(tmp_path):
    p = tmp_path / "bad.libsvm"
    p.write_text("1 4:1.0\n")
    with pytest.raises(ValueError, match="zero-based"):
        mx.io.LibSVMIter(data_libsvm=str(p), data_shape=(4,), batch_size=1)


# ---------------------------------------------------------------------------
# AttrScope / NameManager
# ---------------------------------------------------------------------------

def test_attr_scope_applies_and_nests():
    with mx.AttrScope(ctx_group="dev1", __lr_mult__="2"):
        a = mx.sym.Variable("a")
        with mx.AttrScope(ctx_group="dev2"):
            fc = mx.sym.FullyConnected(a, num_hidden=3, name="fc")
    b = mx.sym.Variable("b")
    assert a.attr("ctx_group") == "dev1"
    assert a.attr("__lr_mult__") == "2"
    assert fc.attr("ctx_group") == "dev2"       # inner scope wins
    assert fc.attr("__lr_mult__") == "2"        # outer attrs inherited
    assert b.attr("ctx_group") is None          # scope exited

    # metadata must NOT leak into kernel params: the symbol still evals
    out = fc.eval(a=mx.nd.ones((2, 4)), fc_weight=mx.nd.ones((3, 4)),
                  fc_bias=mx.nd.zeros((3,)))[0]
    assert out.shape == (2, 3)


def test_attr_scope_survives_json_roundtrip():
    with mx.AttrScope(ctx_group="dev3"):
        x = mx.sym.Variable("x")
        y = mx.sym.Activation(x, act_type="relu", name="act")
    z = mx.sym.load_json(y.tojson())
    assert z.attr("ctx_group") == "dev3"
    assert z.attr("act_type") == "relu"
    out = z.eval(x=mx.nd.array([[-1.0, 2.0]]))[0]
    onp.testing.assert_allclose(out.asnumpy(), [[0.0, 2.0]])


def test_attr_kwarg_merges_over_scope():
    with mx.AttrScope(ctx_group="dev1", tag="scope"):
        s = mx.sym.Activation(mx.sym.Variable("x"), act_type="relu",
                              attr={"tag": "call"})
    assert s.attr("tag") == "call"
    assert s.attr("ctx_group") == "dev1"


def test_name_manager_and_prefix():
    with mx.name.NameManager():
        a = mx.sym.Activation(mx.sym.Variable("x"), act_type="relu")
        b = mx.sym.Activation(mx.sym.Variable("y"), act_type="relu")
        assert a.name == "activation0"
        assert b.name == "activation1"
        with mx.name.Prefix("net_"):
            c = mx.sym.Activation(mx.sym.Variable("z"), act_type="relu")
            assert c.name == "net_activation0"
        # explicit names pass through untouched
        d = mx.sym.Activation(mx.sym.Variable("w"), act_type="relu",
                              name="mine")
        assert d.name == "mine"


# ---------------------------------------------------------------------------
# SoftmaxOutput knobs (grad_scale / ignore / normalization / smoothing)
# ---------------------------------------------------------------------------

def _smo_grad(x, label, **kw):
    a = mx.nd.array(x)
    a.attach_grad()
    with autograd.record():
        out = mx.nd.SoftmaxOutput(a, mx.nd.array(label), **kw)
        out.sum().backward()
    return a.grad.asnumpy(), out.asnumpy()


def test_softmax_output_grad_scale_and_batch_norm():
    rs = onp.random.RandomState(4)
    x = rs.randn(6, 5).astype("float32")
    label = rs.randint(0, 5, (6,)).astype("float32")
    g1, p = _smo_grad(x, label)
    oh = onp.eye(5, dtype="float32")[label.astype(int)]
    onp.testing.assert_allclose(g1, p - oh, rtol=1e-5, atol=1e-6)
    g2, _ = _smo_grad(x, label, grad_scale=0.5)
    onp.testing.assert_allclose(g2, 0.5 * g1, rtol=1e-5, atol=1e-6)
    g3, _ = _smo_grad(x, label, normalization="batch")
    onp.testing.assert_allclose(g3, g1 / 6.0, rtol=1e-5, atol=1e-6)


def test_softmax_output_ignore_and_valid_norm():
    rs = onp.random.RandomState(5)
    x = rs.randn(6, 4).astype("float32")
    label = onp.array([0, 1, -1, 2, -1, 3], "float32")
    g, p = _smo_grad(x, label, use_ignore=True, ignore_label=-1)
    onp.testing.assert_allclose(g[2], 0.0)          # ignored rows: zero
    onp.testing.assert_allclose(g[4], 0.0)
    oh = onp.zeros((6, 4), "float32")
    for i, l in enumerate(label):
        if l >= 0:
            oh[i, int(l)] = 1
    want = p - oh
    want[[2, 4]] = 0
    onp.testing.assert_allclose(g, want, rtol=1e-5, atol=1e-6)
    gv, _ = _smo_grad(x, label, use_ignore=True, ignore_label=-1,
                      normalization="valid")
    onp.testing.assert_allclose(gv, want / 4.0, rtol=1e-5, atol=1e-6)


def test_softmax_output_label_smoothing():
    rs = onp.random.RandomState(6)
    x = rs.randn(3, 5).astype("float32")
    label = onp.array([1, 0, 4], "float32")
    alpha = 0.2
    g, p = _smo_grad(x, label, smooth_alpha=alpha)
    want = p.copy()
    for i, l in enumerate(label):
        for c in range(5):
            if c == int(l):
                want[i, c] = p[i, c] - 1.0 + alpha
            else:
                want[i, c] = p[i, c] - alpha / 4.0
    onp.testing.assert_allclose(g, want, rtol=1e-5, atol=1e-6)


def test_softmax_output_multi_output_batch_norm_divides_by_batch():
    """multi_output + normalization='batch' divides by the TRUE batch
    size N (reference kBatch uses label.size(0)), not N*positions."""
    rs = onp.random.RandomState(7)
    x = rs.randn(2, 3, 4).astype("float32")      # (N=2, C=3, pos=4)
    label = rs.randint(0, 3, (2, 4)).astype("float32")
    a = mx.nd.array(x)
    a.attach_grad()
    with autograd.record():
        out = mx.nd.SoftmaxOutput(a, mx.nd.array(label), multi_output=True,
                                  normalization="batch")
        out.sum().backward()
    p = out.asnumpy()
    want = p.copy()
    for n in range(2):
        for pos in range(4):
            want[n, int(label[n, pos]), pos] -= 1.0
    onp.testing.assert_allclose(a.grad.asnumpy(), want / 2.0,
                                rtol=1e-5, atol=1e-6)
