"""ZeRO-style cross-replica sharded weight update (arxiv 2004.13336).

Covers the tentpole contract: ``shard_optimizer`` OFF keeps the
replicated path; ON produces the same trained parameters while holding
only 1/N of the optimizer state per chip — including the fp32 master
under ``multi_precision`` — and composes with donation, ``scan_steps``,
uneven leaf sizes, and the 1-device degenerate mesh (so the whole
matrix runs in tier-1 on the virtual 8-device CPU mesh).
"""
import os

import numpy as onp
import pytest

import jax
import jax.numpy as jnp

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

import mxnet_tpu as mx
from mxnet_tpu import gluon, parallel, telemetry
from mxnet_tpu.gluon import nn
from mxnet_tpu.gluon import loss as gloss
from mxnet_tpu.parallel import collectives as coll


@pytest.fixture
def mesh8():
    assert len(jax.devices()) == 8, "conftest must force 8 CPU devices"
    m = parallel.device_mesh((8,), ("dp",))
    old = parallel.get_mesh()
    parallel.set_mesh(m)
    yield m
    parallel.set_mesh(old)


# 9 in-units / 7 hidden: every weight and bias size is coprime with the
# 8-way dp axis, so each leaf exercises the zero-padded flat layout
_X = onp.random.RandomState(0).randn(16, 9).astype("float32")
_Y = onp.random.RandomState(1).randint(0, 4, 16).astype("float32")


def _build_step(mesh, shard, optimizer=None, bf16=False):
    onp.random.seed(42)
    mx.random.seed(42)
    net = nn.HybridSequential()
    net.add(nn.Dense(7, activation="relu"), nn.Dense(4))
    net.initialize(mx.init.Xavier())
    net(mx.nd.array(_X))
    if bf16:
        net.cast("bfloat16")
    L = gloss.SoftmaxCrossEntropyLoss()
    opt = optimizer() if optimizer else mx.optimizer.SGD(
        learning_rate=0.1, momentum=0.9)
    step = parallel.DataParallelStep(net, lambda o, l: L(o, l), opt,
                                     mesh=mesh, shard_optimizer=shard)
    return net, step


def _params_close(net_a, net_b, rtol=2e-5, atol=2e-6):
    for (ka, pa), (kb, pb) in zip(sorted(net_a.collect_params().items()),
                                  sorted(net_b.collect_params().items())):
        onp.testing.assert_allclose(
            pa.data().asnumpy().astype("float32"),
            pb.data().asnumpy().astype("float32"), rtol=rtol, atol=atol,
            err_msg=ka)


def test_sharded_matches_replicated_k_steps(mesh8):
    """Same parameters after k steps, uneven leaf sizes included."""
    net_a, st_a = _build_step(mesh8, False)
    net_b, st_b = _build_step(mesh8, True)
    for _ in range(5):
        la = float(st_a(mx.nd.array(_X), mx.nd.array(_Y)).asscalar())
        lb = float(st_b(mx.nd.array(_X), mx.nd.array(_Y)).asscalar())
    assert abs(la - lb) < 1e-5
    _params_close(net_a, net_b)
    # every slot sharded; state leaves are flat, dp-sharded, and 1/8
    # per chip
    assert all(st_b._shard_slots)
    leaf = st_b._opt_states[0][0]
    assert leaf.ndim == 1 and leaf.shape[0] % 8 == 0
    assert leaf.addressable_shards[0].data.shape[0] == leaf.shape[0] // 8
    assert st_b.optimizer_state_bytes(per_chip=True) * 8 == \
        st_b.optimizer_state_bytes(per_chip=False)
    assert st_b.optimizer_state_bytes(per_chip=True) < \
        st_a.optimizer_state_bytes(per_chip=True)


def test_sharded_multi_precision_master_and_resync(mesh8):
    """bf16 weights keep a SHARDED fp32 master as state leaf 0: training
    matches the replicated mp path, weights stay bf16, and an external
    set_data refreshes the sharded master (not reverted next step)."""
    make = lambda: mx.optimizer.Adam(learning_rate=2e-2,  # noqa: E731
                                     multi_precision=True)
    net_a, st_a = _build_step(mesh8, False, optimizer=make, bf16=True)
    net_b, st_b = _build_step(mesh8, True, optimizer=make, bf16=True)
    assert all(st_b._mp_slots) and all(st_b._shard_slots)
    for _ in range(6):
        st_a(mx.nd.array(_X), mx.nd.array(_Y))
        st_b(mx.nd.array(_X), mx.nd.array(_Y))
    for _, p in net_b.collect_params().items():
        assert p.data().dtype == onp.dtype("bfloat16")
    assert all(str(l.dtype) == "float32"
               for lv in st_b._opt_states for l in lv)
    _params_close(net_a, net_b, rtol=2e-2, atol=2e-2)

    loaded = onp.full(net_b[0].weight.shape, 0.25, "float32")
    net_b[0].weight.set_data(mx.nd.array(loaded, dtype="bfloat16"))
    st_b(mx.nd.array(_X), mx.nd.array(_Y))
    w = net_b[0].weight.data().asnumpy().astype("float32")
    assert onp.abs(w - loaded).max() < 0.1, w


def test_sharded_scan_steps_matches_per_call(mesh8):
    """k sharded steps through one compiled lax.scan == k per-call
    sharded steps (the sharded state leaves are donated scan carries)."""
    xs = onp.random.RandomState(3).randn(3, 16, 9).astype("float32")
    ys = onp.random.RandomState(4).randint(0, 4, (3, 16)).astype("float32")
    net_a, st_a = _build_step(mesh8, True)
    net_b, st_b = _build_step(mesh8, True)
    losses = st_a.scan_steps(mx.nd.array(xs), mx.nd.array(ys))
    seq = [float(st_b(mx.nd.array(x), mx.nd.array(y)).asscalar())
           for x, y in zip(xs, ys)]
    onp.testing.assert_allclose(losses.asnumpy(), seq, rtol=1e-5,
                                atol=1e-6)
    _params_close(net_a, net_b)


def test_sharded_with_batch_donation_refeed_guard(mesh8):
    """donate_batch composes with the sharded update, and the re-feed
    guard still fires on a donated buffer."""
    net, step = _build_step(mesh8, True)
    step._donate_batch = True
    # pre-placed batches (the DevicePrefetchIter layout) are donated
    # as-is, so re-feeding the same device buffer must raise
    x = parallel.shard_batch(mx.nd.array(_X), mesh8)
    y = parallel.shard_batch(mx.nd.array(_Y), mesh8)
    step(x, y)
    with pytest.raises(RuntimeError, match="donated"):
        step(x, parallel.shard_batch(mx.nd.array(_Y), mesh8))
    # fresh buffers keep working and the state stays sharded
    step(mx.nd.array(_X), mx.nd.array(_Y))
    assert step._opt_states[0][0].addressable_shards[0].data.shape[0] \
        == step._opt_states[0][0].shape[0] // 8


def test_one_device_degenerate_mesh():
    """shard_optimizer=True on a 1-device dp mesh is a working no-op
    layout (pad-to-1, slice-of-everything) — the CPU-only degenerate."""
    mesh1 = parallel.device_mesh((1,), ("dp",),
                                 devices=jax.devices()[:1])
    net_a, st_a = _build_step(mesh1, False)
    net_b, st_b = _build_step(mesh1, True)
    assert st_b._shard_n == 1 and all(st_b._shard_slots)
    for _ in range(3):
        st_a(mx.nd.array(_X), mx.nd.array(_Y))
        st_b(mx.nd.array(_X), mx.nd.array(_Y))
    _params_close(net_a, net_b)


def test_auto_knob_resolution(mesh8):
    """'auto' = on for dp>1, off for dp=1 or no mesh; True without a
    mesh warns and falls back."""
    _, st = _build_step(mesh8, "auto")
    assert st._shard_n == 8
    mesh1 = parallel.device_mesh((1,), ("dp",),
                                 devices=jax.devices()[:1])
    _, st1 = _build_step(mesh1, "auto")
    assert st1._shard_n == 0
    with pytest.raises(ValueError):
        _build_step(mesh8, "sometimes")


def test_auto_knob_without_mesh():
    """No mesh anywhere: 'auto' stays off, True warns and falls back."""
    old = parallel.get_mesh()
    parallel.set_mesh(None)
    try:
        _, st_none = _build_step(None, "auto")
        assert st_none._shard_n == 0
        with pytest.warns(UserWarning, match="shard_optimizer"):
            _, st_forced = _build_step(None, True)
        assert st_forced._shard_n == 0
    finally:
        parallel.set_mesh(old)


def test_shard_layout_telemetry(mesh8):
    """The per-chip state gauge and the collective-schedule journal
    event land at construction (docs/OBSERVABILITY.md contract)."""
    telemetry.reset()
    _, st = _build_step(mesh8, True)
    snap = telemetry.snapshot()
    per_chip = snap["gauges"]["parallel.optimizer_state_bytes_per_chip"]
    total = snap["gauges"]["parallel.optimizer_state_bytes_total"]
    assert per_chip * 8 == total
    evs = [e for e in snap["events"]
           if e["kind"] == "zero" and e["name"] == "shard_optimizer"]
    assert evs and evs[-1]["n_shards"] == 8
    assert evs[-1]["reduce_scatter_bytes"] > 0
    assert evs[-1]["all_gather_bytes"] > 0
    telemetry.reset()


# ---------------------------------------------------------------------------
# flat-layout collectives helpers
# ---------------------------------------------------------------------------

def test_flatten_pad_unflatten_roundtrip():
    for shape in ((3, 5), (7,), (), (8, 2)):
        x = onp.arange(max(1, int(onp.prod(shape))),
                       dtype="float32").reshape(shape)
        flat = coll.flatten_pad(jnp.asarray(x), 8)
        assert flat.ndim == 1 and flat.shape[0] % 8 == 0
        assert flat.shape[0] == coll.padded_size(x.size, 8)
        back = coll.unflatten(flat, shape)
        onp.testing.assert_array_equal(onp.asarray(back), x)
        # pad lanes are zero (numerics-neutral for wd/clip/moments)
        onp.testing.assert_array_equal(
            onp.asarray(flat)[x.size:], 0.0)


def test_reduce_scatter_padded_all_gather_unpad(mesh8):
    """Uneven leaf through the explicit shard_map spelling: N replicas
    each contribute, every replica ends with the summed full leaf."""
    from jax.sharding import PartitionSpec as P
    from mxnet_tpu.parallel.mesh import shard_map_compat

    shape = (3, 7)   # 21 elements: pads to 24 over 8 replicas
    base = onp.arange(21, dtype="float32").reshape(shape)

    def f(x):
        shard = coll.reduce_scatter_padded(x, "dp", axis_size=8)
        assert shard.shape == (coll.padded_size(21, 8) // 8,)
        return coll.all_gather_unpad(shard, shape, "dp")

    fn = shard_map_compat(f, mesh=mesh8, in_specs=P("dp"), out_specs=P())
    stacked = jnp.asarray(
        onp.stack([base * (r + 1) for r in range(8)]))  # (8, 3, 7)
    out = fn(stacked.reshape(8, -1))
    onp.testing.assert_allclose(onp.asarray(out), base * 36.0)

    with pytest.raises(ValueError, match="axis_size"):
        coll.reduce_scatter_padded(jnp.zeros(4), "dp")


# ---------------------------------------------------------------------------
# Trainer (_FusedUpdate) sharded path
# ---------------------------------------------------------------------------

def _trainer_setup(mesh, shard, donate_grads=False):
    onp.random.seed(42)
    mx.random.seed(42)
    net = nn.HybridSequential()
    net.add(nn.Dense(7, activation="relu"), nn.Dense(4))
    net.initialize(mx.init.Xavier())
    net(mx.nd.array(_X))
    if shard:
        for _, p in net.collect_params().items():
            p.set_data(parallel.replicate(p.data(), mesh))
    tr = gluon.Trainer(net.collect_params(), "adam",
                       {"learning_rate": 0.05},
                       donate_grads=donate_grads, shard_optimizer=shard)
    return net, tr


def _trainer_epoch(net, tr, mesh, shard, k=4):
    L = gloss.SoftmaxCrossEntropyLoss()
    for _ in range(k):
        if shard:
            xb = parallel.shard_batch(mx.nd.array(_X), mesh)
            yb = parallel.shard_batch(mx.nd.array(_Y), mesh)
        else:
            xb, yb = mx.nd.array(_X), mx.nd.array(_Y)
        with mx.autograd.record():
            l = L(net(xb), yb).mean()
        l.backward()
        tr.step(1)


def test_trainer_sharded_matches_replicated(mesh8):
    """Trainer(shard_optimizer=True) with mesh-replicated params: same
    trained parameters, state mirror dp-sharded, donate_grads composes.
    The sharded leg runs under the runtime numerics sanitizer — the
    ZeRO update must keep every param/grad leaf finite and
    dtype-stable across steps (the working-dtype contract's dynamic
    half)."""
    import sys
    sys.path.insert(0, REPO) if REPO not in sys.path else None
    from tools.lint.runtime_numerics import NumericsSanitizer
    na, ta = _trainer_setup(mesh8, False)
    nb, tb = _trainer_setup(mesh8, True, donate_grads=True)
    _trainer_epoch(na, ta, mesh8, False)
    san = NumericsSanitizer().attach(tb)
    try:
        _trainer_epoch(nb, tb, mesh8, True)
    finally:
        san.detach()
    _params_close(na, nb)
    fused = tb._kv_fused or tb._local_fused
    assert fused._sharded, "sharded mirror did not engage"
    leaf = next(iter(fused._sharded.values()))[0]
    assert leaf.ndim == 1 and \
        leaf.addressable_shards[0].data.shape[0] == leaf.shape[0] // 8
    assert san.observed, "sanitizer sweep never ran"
    san.assert_all_finite()
    san.assert_no_dtype_drift()


def test_trainer_sharded_state_serialization(mesh8, tmp_path):
    """save_states gathers the mirror (same bytes as replicated
    training); load_states invalidates it and training continues."""
    na, ta = _trainer_setup(mesh8, False)
    nb, tb = _trainer_setup(mesh8, True)
    _trainer_epoch(na, ta, mesh8, False)
    _trainer_epoch(nb, tb, mesh8, True)
    fa, fb = str(tmp_path / "a.states"), str(tmp_path / "b.states")
    ta.save_states(fa)
    tb.save_states(fb)
    ua = ta._kvstore._updater if ta._update_on_kvstore else ta._updaters
    ub = tb._kvstore._updater if tb._update_on_kvstore else tb._updaters
    la, _ = jax.tree_util.tree_flatten(
        ua.states, is_leaf=lambda z: isinstance(z, mx.nd.NDArray))
    lb, _ = jax.tree_util.tree_flatten(
        ub.states, is_leaf=lambda z: isinstance(z, mx.nd.NDArray))
    assert len(la) == len(lb) and len(la) > 0
    for a, b in zip(la, lb):
        onp.testing.assert_allclose(a.asnumpy(), b.asnumpy(),
                                    rtol=2e-5, atol=1e-6)
    nc, tc = _trainer_setup(mesh8, True)
    _trainer_epoch(nc, tc, mesh8, True, k=1)
    tc.load_states(fb)
    fused = tc._kv_fused or tc._local_fused
    assert not fused._sharded       # mirror dropped; rebuilt next step
    _trainer_epoch(nc, tc, mesh8, True, k=2)


def test_trainer_unplaced_weights_keep_replicated_update(mesh8):
    """shard_optimizer=True with single-device weights must NOT engage
    (silent migration of the user's training onto the mesh): the update
    stays replicated and training still works."""
    net, tr = _trainer_setup(None, False)
    tr._shard_optimizer = True
    tr._local_fused = tr._kv_fused = None   # rebuild with the knob on
    _trainer_epoch(net, tr, mesh8, False, k=2)
    fused = tr._kv_fused or tr._local_fused
    assert fused is not None and not fused._sharded
