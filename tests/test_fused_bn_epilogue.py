"""Fused BatchNorm→residual-add→ReLU epilogue tests.

Kernels run in interpret mode on CPU; the custom-vjp wrapper's fallback
path and the registered op / gluon layer / ResNet wiring are tested
against the unfused composition (reference discipline:
``check_consistency`` between the fused cuDNN BatchNormAddRelu and the
composed ops).
"""
import numpy as onp
import jax
import jax.numpy as jnp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd
from mxnet_tpu.gluon import nn
from mxnet_tpu.ops import pallas_fused_norm as FN
from mxnet_tpu.ops.nn import batch_norm, batch_norm_add_relu


def _rand(shape, seed, dtype="float32"):
    x = onp.random.RandomState(seed).uniform(-1, 1, shape).astype("float32")
    return jnp.asarray(x, jnp.dtype(dtype))


def _compose2d(x2d, s_row, t_row, r2d):
    y = (x2d.astype(jnp.float32) * s_row + t_row
         + r2d.astype(jnp.float32))
    return jnp.maximum(y, 0.0).astype(x2d.dtype)


def test_epilogue_fwd_kernel_matches_composition():
    # odd rows/cols exercise both padding paths
    rows, cols = 70, 200
    x = _rand((rows, cols), 0)
    r = _rand((rows, cols), 1)
    s = _rand((1, cols), 2)
    t = _rand((1, cols), 3)
    y = FN.pallas_epilogue_fwd(x, s, t, r, interpret=True)
    ref = _compose2d(x, s, t, r)
    assert float(jnp.max(jnp.abs(y - ref))) < 1e-6


def test_epilogue_bwd_kernel_matches_vjp():
    rows, cols = 48, 384       # multiple row blocks via small block pick
    x = _rand((rows, cols), 10)
    r = _rand((rows, cols), 11)
    s = _rand((1, cols), 12)
    t = _rand((1, cols), 13)
    ct = _rand((rows, cols), 14)
    y = FN.pallas_epilogue_fwd(x, s, t, r, interpret=True)
    dx, dr, ds, dt = FN.pallas_epilogue_bwd(x, s, y, ct, interpret=True)
    _, vjp = jax.vjp(_compose2d, x, s, t, r)
    rx, rs, rt, rr = vjp(ct)
    assert float(jnp.max(jnp.abs(dx - rx))) < 1e-5
    assert float(jnp.max(jnp.abs(dr - rr))) < 1e-5
    assert float(jnp.max(jnp.abs(ds - rs))) < 1e-4
    assert float(jnp.max(jnp.abs(dt - rt))) < 1e-4


def test_fused_scale_shift_add_relu_fallback_grads():
    """Off-TPU the custom-vjp wrapper runs the jnp path; grads for all
    four operands must match plain autodiff of the composition."""
    rows, cols = 32, 128
    x = _rand((rows, cols), 20)
    r = _rand((rows, cols), 21)
    s = _rand((cols,), 22)
    t = _rand((cols,), 23)

    def fused_loss(x, s, t, r):
        return jnp.sum(FN.fused_scale_shift_add_relu(x, s, t, r) ** 2)

    def ref_loss(x, s, t, r):
        return jnp.sum(_compose2d(x, s.reshape(1, -1),
                                  t.reshape(1, -1), r) ** 2)

    got = jax.grad(fused_loss, argnums=(0, 1, 2, 3))(x, s, t, r)
    want = jax.grad(ref_loss, argnums=(0, 1, 2, 3))(x, s, t, r)
    for g1, g2 in zip(got, want):
        assert float(jnp.max(jnp.abs(g1 - g2))) < 1e-4


def test_nd_entry_nchw_and_nhwc_match_composition():
    """fused_bn_add_relu_epilogue collapses channel+trailing dims into
    lanes for ANY axis — NCHW (axis=1) and NHWC (axis=3) must agree with
    the broadcast composition."""
    x = _rand((2, 6, 5, 7), 30)
    r = _rand((2, 6, 5, 7), 31)
    for axis in (1, 3):
        C = x.shape[axis]
        s = _rand((C,), 32)
        t = _rand((C,), 33)
        shp = [1] * 4
        shp[axis] = C
        ref = jnp.maximum(x * s.reshape(shp) + t.reshape(shp) + r, 0.0)
        got = FN.fused_bn_add_relu_epilogue(x, s, t, r, axis)
        assert float(jnp.max(jnp.abs(got - ref))) < 1e-5


@pytest.mark.parametrize("dtype", ["float32", "bfloat16"])
def test_bn_add_relu_op_matches_unfused_composition(dtype):
    """The registered op == BatchNorm → add → relu, fwd AND bwd."""
    x = _rand((4, 8, 6, 6), 40, dtype)
    res = _rand((4, 8, 6, 6), 41, dtype)
    gamma = _rand((8,), 42)
    beta = _rand((8,), 43)
    mm = jnp.zeros((8,), jnp.float32)
    mv = jnp.ones((8,), jnp.float32)
    kw = dict(eps=1e-5, fix_gamma=False, training=True)

    def fused(x, res, gamma, beta):
        return batch_norm_add_relu(x, res, gamma, beta, mm, mv, **kw)

    def composed(x, res, gamma, beta):
        out, mean, var = batch_norm(x, gamma, beta, mm, mv, **kw)
        return jnp.maximum(out + res, 0.0), mean, var

    o1, m1, v1 = fused(x, res, gamma, beta)
    o2, m2, v2 = composed(x, res, gamma, beta)
    # the fused epilogue holds f32 through the whole tail while the
    # composed path casts scale/shift to data dtype first — rounding-
    # level disagreement, not an error
    tol = 2e-2 if dtype == "bfloat16" else 1e-4
    assert float(jnp.max(jnp.abs(o1.astype(jnp.float32)
                                 - o2.astype(jnp.float32)))) < tol
    assert float(jnp.max(jnp.abs(m1 - m2))) == 0.0
    assert float(jnp.max(jnp.abs(v1 - v2))) == 0.0

    g1 = jax.grad(lambda *a: jnp.sum(fused(*a)[0].astype(jnp.float32) ** 2),
                  argnums=(0, 1, 2, 3))(x, res, gamma, beta)
    g2 = jax.grad(lambda *a: jnp.sum(composed(*a)[0].astype(jnp.float32)
                                     ** 2),
                  argnums=(0, 1, 2, 3))(x, res, gamma, beta)
    # relative comparison: the squared-sum loss makes |grad| large, and
    # the two paths accumulate bf16-rounded terms in different orders —
    # gamma/beta grads sum ~B*H*W such terms, so allow a few percent
    gtol = 0.05 if dtype == "bfloat16" else 1e-4
    for a, b in zip(g1, g2):
        b32 = b.astype(jnp.float32)
        err = float(jnp.max(jnp.abs(a.astype(jnp.float32) - b32)))
        assert err / (1.0 + float(jnp.max(jnp.abs(b32)))) < gtol


def test_batchnorm_add_relu_layer_matches_composed_layers():
    """The gluon layer == nn.BatchNorm + add + relu, including the
    moving-stats update and the backward through mx autograd."""
    mx.random.seed(0)
    bn = nn.BatchNorm(in_channels=4)
    fused = nn.BatchNormAddReLU(in_channels=4)
    bn.initialize()
    fused.initialize()
    rs = onp.random.RandomState(5)
    # identical (non-trivial) affine params on both layers
    g = rs.uniform(0.5, 1.5, (4,)).astype("float32")
    b = rs.uniform(-1, 1, (4,)).astype("float32")
    for layer in (bn, fused):
        layer.gamma.set_data(mx.nd.array(g))
        layer.beta.set_data(mx.nd.array(b))
    x = mx.nd.array(rs.uniform(-1, 1, (3, 4, 5, 5)).astype("float32"))
    r = mx.nd.array(rs.uniform(-1, 1, (3, 4, 5, 5)).astype("float32"))
    x1, r1 = x.copy(), r.copy()
    x.attach_grad()
    r.attach_grad()
    x1.attach_grad()
    r1.attach_grad()
    with autograd.record():
        y = fused(x, r)
    y.backward()
    with autograd.record():
        yref = mx.nd.relu(bn(x1) + r1)
    yref.backward()
    assert onp.abs(y.asnumpy() - yref.asnumpy()).max() < 1e-5
    assert onp.abs(x.grad.asnumpy() - x1.grad.asnumpy()).max() < 1e-5
    assert onp.abs(r.grad.asnumpy() - r1.grad.asnumpy()).max() < 1e-5
    # moving stats advanced identically
    assert onp.abs(fused.running_mean.data().asnumpy()
                   - bn.running_mean.data().asnumpy()).max() < 1e-6
    assert onp.abs(fused.running_var.data().asnumpy()
                   - bn.running_var.data().asnumpy()).max() < 1e-6


def test_resnet_v1_blocks_use_fused_epilogue():
    """Acceptance: the bench path (resnet50_v1 and friends) ends every
    v1 residual body with the fused BN+add+relu layer, at the SAME
    structural position/name a plain BatchNorm had."""
    from mxnet_tpu.gluon.model_zoo.vision.resnet import (BasicBlockV1,
                                                         BottleneckV1)
    for cls in (BasicBlockV1, BottleneckV1):
        blk = cls(64, 1, downsample=True, in_channels=32)
        tail = list(blk.body)[-1]
        assert isinstance(tail, nn.BatchNormAddReLU)
    net = mx.gluon.model_zoo.vision.resnet50_v1(classes=10)
    tails = [list(unit.body)[-1]
             for stage in list(net.features)[4:8] for unit in stage]
    assert tails and all(isinstance(t, nn.BatchNormAddReLU)
                         for t in tails)


def test_fused_residual_net_train_eval_consistency():
    """End-to-end: a stack of the actual fused ResNet v1 units trains
    (loss descends through autograd + Trainer) and the eval path (moving
    stats through the fused op's use_global branch) stays finite.  (The
    eager autograd/Trainer loop, not a donated DataParallelStep: a
    donated conv-net step jit trips a pre-existing jax-CPU persistent-
    cache deserialization bug unrelated to the epilogue — the donated
    on-chip resnet50 path is covered by bench.py.)"""
    from mxnet_tpu.gluon.model_zoo.vision.resnet import BottleneckV1
    mx.random.seed(0)
    net = nn.HybridSequential()
    net.add(BottleneckV1(16, 1, downsample=True, in_channels=3))
    net.add(BottleneckV1(16, 1, False, in_channels=16))
    net.add(nn.GlobalAvgPool2D(), nn.Dense(10))
    net.initialize(mx.init.Xavier())
    rs = onp.random.RandomState(0)
    x = mx.nd.array(rs.uniform(size=(2, 3, 16, 16)).astype("float32"))
    y = mx.nd.array(rs.randint(0, 10, (2,)).astype("float32"))
    net(x)        # materialize deferred shapes
    loss_fn = mx.gluon.loss.SoftmaxCrossEntropyLoss()
    trainer = mx.gluon.Trainer(net.collect_params(), "sgd",
                               {"learning_rate": 0.05})
    losses = []
    for _ in range(7):
        with autograd.record():
            loss = loss_fn(net(x), y).mean()
        loss.backward()
        trainer.step(batch_size=2)
        losses.append(float(loss.asnumpy()))
    assert losses[-1] < losses[0]
    out = net(x)      # eval path (moving stats)
    assert onp.isfinite(out.asnumpy()).all()
