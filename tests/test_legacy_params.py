"""Upstream binary .params format interop (reference
src/ndarray/ndarray.cc:1600 Save / :1826 list container): files written
in the reference's exact byte layout load through plain nd.load, and
save_legacy round-trips — so published MXNet checkpoints are usable."""
import struct

import numpy as onp

import mxnet_tpu as mx
from mxnet_tpu.ndarray import legacy_io


def _write_reference_bytes(fname, named):
    """Independent writer following src/ndarray/ndarray.cc byte-for-byte
    (separate from save_legacy so the test is not self-confirming)."""
    out = [struct.pack("<QQ", 0x112, 0), struct.pack("<Q", len(named))]
    for _, a in named:
        out += [struct.pack("<I", 0xF993FAC9),       # NDARRAY_V2_MAGIC
                struct.pack("<i", 0),                # kDefaultStorage
                struct.pack("<i", a.ndim),
                struct.pack("<%dq" % a.ndim, *a.shape),
                struct.pack("<ii", 1, 0),            # Context{kCPU, 0}
                struct.pack("<i", {onp.dtype("float32"): 0,
                                   onp.dtype("int64"): 6,
                                   onp.dtype("uint8"): 3}[a.dtype]),
                a.tobytes()]
    out.append(struct.pack("<Q", len(named)))
    for n, _ in named:
        raw = n.encode()
        out += [struct.pack("<Q", len(raw)), raw]
    with open(fname, "wb") as f:
        f.write(b"".join(out))


def test_reference_format_loads_via_nd_load(tmp_path):
    rs = onp.random.RandomState(0)
    named = [("arg:fc1_weight", rs.randn(4, 3).astype("float32")),
             ("arg:fc1_bias", rs.randn(4).astype("float32")),
             ("aux:ids", onp.arange(5, dtype="int64")),
             ("img", rs.randint(0, 255, (2, 2), dtype=onp.uint8))]
    path = str(tmp_path / "model-0000.params")
    _write_reference_bytes(path, named)
    assert legacy_io.is_legacy_file(path)
    loaded = mx.nd.load(path)
    assert set(loaded) == {n for n, _ in named}
    for n, a in named:
        onp.testing.assert_array_equal(loaded[n].asnumpy(), a)
        if a.dtype.itemsize < 8:   # 64-bit narrows (jax x64-off policy)
            assert loaded[n].dtype == a.dtype


def test_reference_format_unnamed_list(tmp_path):
    a = onp.arange(6, dtype="float32").reshape(2, 3)
    path = str(tmp_path / "plain.nd")
    out = [struct.pack("<QQ", 0x112, 0), struct.pack("<Q", 1),
           struct.pack("<I", 0xF993FAC9), struct.pack("<i", 0),
           struct.pack("<i", 2), struct.pack("<qq", 2, 3),
           struct.pack("<ii", 1, 0), struct.pack("<i", 0), a.tobytes(),
           struct.pack("<Q", 0)]
    with open(path, "wb") as f:
        f.write(b"".join(out))
    loaded = mx.nd.load(path)
    assert isinstance(loaded, list) and len(loaded) == 1
    onp.testing.assert_array_equal(loaded[0].asnumpy(), a)


def test_save_legacy_roundtrip(tmp_path):
    rs = onp.random.RandomState(1)
    d = {"w": mx.nd.array(rs.randn(3, 5).astype("float32")),
         "b": mx.nd.array(rs.randn(5).astype("float32"))}
    path = str(tmp_path / "out.params")
    legacy_io.save_legacy(path, d)
    back = mx.nd.load(path)
    for k in d:
        onp.testing.assert_allclose(back[k].asnumpy(), d[k].asnumpy())


def test_gluon_params_from_reference_format(tmp_path):
    """A reference-format checkpoint feeds load_parameters end to end."""
    from mxnet_tpu.gluon import nn
    net = nn.Dense(3, in_units=4, prefix="dense0_")
    net.initialize()
    w = onp.random.RandomState(2).randn(3, 4).astype("float32")
    b = onp.zeros(3, "float32")
    path = str(tmp_path / "net-0000.params")
    # gluon save_parameters uses structural names ("weight"/"bias")
    _write_reference_bytes(path, [("weight", w), ("bias", b)])
    net.load_parameters(path)
    x = onp.ones((2, 4), "float32")
    onp.testing.assert_allclose(net(mx.nd.array(x)).asnumpy(), x @ w.T,
                                rtol=1e-5)


def test_gluon_load_strips_arg_aux_prefixes(tmp_path):
    """Module-export-style names (arg:/aux:) load into gluon blocks
    (reference load_parameters strips them)."""
    from mxnet_tpu.gluon import nn
    net = nn.Dense(2, in_units=3)
    net.initialize()
    w = onp.random.RandomState(3).randn(2, 3).astype("float32")
    b = onp.ones(2, "float32")
    path = str(tmp_path / "mod-0000.params")
    _write_reference_bytes(path, [("arg:weight", w), ("arg:bias", b)])
    net.load_parameters(path)
    x = onp.ones((1, 3), "float32")
    onp.testing.assert_allclose(net(mx.nd.array(x)).asnumpy(),
                                x @ w.T + b, rtol=1e-5)


def test_v1_and_v3_magics_parse():
    """V1 (no stype field) and V3 (np-shape) entries parse correctly —
    the three version magics must not be confused."""
    import io as _io
    for magic, has_stype in ((0xF993FAC8, False), (0xF993FACA, True)):
        a = onp.arange(4, dtype="float32")
        chunks = [struct.pack("<QQ", 0x112, 0), struct.pack("<Q", 1),
                  struct.pack("<I", magic)]
        if has_stype:
            chunks.append(struct.pack("<i", 0))
        chunks += [struct.pack("<i", 1), struct.pack("<q", 4),
                   struct.pack("<ii", 1, 0), struct.pack("<i", 0),
                   a.tobytes(), struct.pack("<Q", 0)]
        import tempfile, os
        d = tempfile.mkdtemp()
        p = os.path.join(d, "x.nd")
        with open(p, "wb") as f:
            f.write(b"".join(chunks))
        out = legacy_io.load_legacy(p)
        onp.testing.assert_array_equal(out[0], a)


def test_save_legacy_rejects_scalars(tmp_path):
    import pytest
    from mxnet_tpu.base import MXNetError
    with pytest.raises(MXNetError):
        legacy_io.save_legacy(str(tmp_path / "s.nd"),
                              {"x": onp.float32(3.0).reshape(())})


def test_prefixed_format_with_arg_tags(tmp_path):
    """arg:-tagged prefixed names load into a multi-child block."""
    from mxnet_tpu.gluon import nn
    net = nn.HybridSequential(prefix="net_")
    with net.name_scope():
        net.add(nn.Dense(3, in_units=4))
    net.initialize()
    inner_prefix = net[0].prefix[len(net.prefix):]
    w = onp.random.RandomState(4).randn(3, 4).astype("float32")
    b = onp.zeros(3, "float32")
    path = str(tmp_path / "m-0000.params")
    _write_reference_bytes(path, [
        ("arg:%sweight" % inner_prefix, w),
        ("arg:%sbias" % inner_prefix, b)])
    net.load_parameters(path)
    x = onp.ones((2, 4), "float32")
    onp.testing.assert_allclose(net(mx.nd.array(x)).asnumpy(), x @ w.T,
                                rtol=1e-5)
