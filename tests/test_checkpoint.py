"""Async atomic sharded checkpoints (mxnet_tpu/checkpoint.py).

Covers the durability tier of the elastic protocol: the async writer's
clean thread lifecycle (under the runtime lock-order sanitizer vs the
package static graph — the PR-7 static-vs-runtime pattern), tmp +
os.replace atomicity under the chaos ``checkpoint_write_crash`` fault
(manager files, ``nd.save``, ``model.save_checkpoint``,
``Trainer.save_states``), the manifest commit point, and the headline
contract: a checkpoint saved at one world size restores into a
DIFFERENT world size with the materialized optimizer state bitwise
equal.
"""
import json
import os
import sys
import time

import numpy as onp
import pytest

import jax

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO) if REPO not in sys.path else None

import mxnet_tpu as mx
from mxnet_tpu import checkpoint, gluon, parallel, telemetry
from mxnet_tpu.base import MXNetError
from mxnet_tpu.gluon import nn
from mxnet_tpu.gluon import loss as gloss
from mxnet_tpu.parallel import chaos

_X = onp.random.RandomState(0).randn(16, 9).astype("float32")
_Y = onp.random.RandomState(1).randint(0, 4, 16).astype("float32")


@pytest.fixture(autouse=True)
def _clear_chaos():
    chaos.clear()
    yield
    chaos.clear()


def _mesh(n):
    return parallel.device_mesh((n,), ("dp",), devices=jax.devices()[:n])


def _build_step(mesh, optimizer=None, bf16=False):
    onp.random.seed(42)
    mx.random.seed(42)
    net = nn.HybridSequential()
    net.add(nn.Dense(7, activation="relu"), nn.Dense(4))
    net.initialize(mx.init.Xavier())
    net(mx.nd.array(_X))
    if bf16:
        net.cast("bfloat16")
    L = gloss.SoftmaxCrossEntropyLoss()
    opt = optimizer() if optimizer else mx.optimizer.Adam(
        learning_rate=1e-3)
    step = parallel.DataParallelStep(net, lambda o, l: L(o, l), opt,
                                     mesh=mesh, shard_optimizer=True)
    return net, step


def _run(step, k):
    return [float(step(mx.nd.array(_X), mx.nd.array(_Y)).asscalar())
            for _ in range(k)]


def _canonical_slots(st):
    """Slot indices in the net's graph order — the two steps' local
    name-sorted orders can differ when gluon's auto-naming counters
    straddle a digit boundary (the exact hazard checkpoint_state keys
    around)."""
    order = st._param_order()
    rank = {pi: k for k, pi in enumerate(order)}
    return sorted(range(len(st._opt_states)),
                  key=lambda s: rank[st._trainable[s]])


def _assert_states_bitwise(st_a, st_b):
    assert len(st_a._opt_states) == len(st_b._opt_states)
    for qa, qb in zip(_canonical_slots(st_a), _canonical_slots(st_b)):
        for la, lb in zip(st_a._materialize_slot(qa),
                          st_b._materialize_slot(qb)):
            onp.testing.assert_array_equal(la, lb)
    for ia, ib in zip(st_a._param_order(), st_b._param_order()):
        onp.testing.assert_array_equal(
            onp.asarray(st_a._params[ia]._data._data),
            onp.asarray(st_b._params[ib]._data._data))


# ---------------------------------------------------------------------------
# round-trips
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_sync_roundtrip_same_world_bitwise(tmp_path):
    # slow: same-world round-trip is a strict subset of the
    # changed-world acceptance test below, which stays tier-1
    net_a, st_a = _build_step(_mesh(8))
    _run(st_a, 3)
    mgr = checkpoint.CheckpointManager(str(tmp_path), st_a,
                                       async_write=False)
    mgr.save()
    net_b, st_b = _build_step(_mesh(8))
    assert checkpoint.restore_latest(str(tmp_path), st_b) == 3
    _assert_states_bitwise(st_a, st_b)
    # training continues identically from the restored state
    la, lb = _run(st_a, 2), _run(st_b, 2)
    onp.testing.assert_allclose(la, lb, rtol=1e-6, atol=1e-7)


def test_restore_into_smaller_world_bitwise(tmp_path):
    """The acceptance headline: a 4-way checkpoint restores into a
    2-way world with the materialized optimizer state (fp32 master
    included) bitwise equal — re-sharding on load is byte movement,
    never arithmetic."""
    mk = lambda: mx.optimizer.Adam(learning_rate=1e-3,  # noqa: E731
                                   multi_precision=True)
    net_a, st_a = _build_step(_mesh(4), optimizer=mk, bf16=True)
    _run(st_a, 3)
    checkpoint.CheckpointManager(str(tmp_path), st_a,
                                 async_write=False).save()
    net_b, st_b = _build_step(_mesh(2), optimizer=mk, bf16=True)
    assert checkpoint.restore_latest(str(tmp_path), st_b) == 3
    assert st_b._shard_n == 2
    leaf = st_b._opt_states[0][0]
    assert leaf.shape[0] % 2 == 0    # re-sharded to the new extent
    _assert_states_bitwise(st_a, st_b)
    # journal records the world transition
    ev = [e for e in telemetry.snapshot(events=256)["events"]
          if e["kind"] == "ckpt" and e["name"] == "restore"]
    assert ev and ev[-1]["world_from"] == 4 and ev[-1]["world_to"] == 2
    # and the restored job trains on (same math at any dp extent)
    la, lb = _run(st_a, 2), _run(st_b, 2)
    onp.testing.assert_allclose(la, lb, rtol=1e-5, atol=1e-6)


@pytest.mark.slow
def test_restore_into_larger_world_bitwise(tmp_path):
    net_a, st_a = _build_step(_mesh(2))
    _run(st_a, 2)
    checkpoint.CheckpointManager(str(tmp_path), st_a,
                                 async_write=False).save()
    net_b, st_b = _build_step(_mesh(8))
    checkpoint.restore_latest(str(tmp_path), st_b)
    assert st_b._shard_n == 8
    _assert_states_bitwise(st_a, st_b)


def test_async_cadence_hook_and_manifest(tmp_path):
    """attach(): every K-th step enqueues a snapshot; the manifest
    always points at a COMPLETE checkpoint; donation of the live
    buffers cannot corrupt an in-flight snapshot (device-side copies);
    pruning keeps the newest dirs."""
    net, st = _build_step(_mesh(8))
    w0 = telemetry.counter("ckpt.writes")
    mgr = checkpoint.CheckpointManager(str(tmp_path), st,
                                       every_n_steps=2, keep=2)
    mgr.attach()
    try:
        _run(st, 6)
        assert mgr.flush(30.0)
    finally:
        mgr.close()
    assert mgr.stats()["last_error"] is None
    man = checkpoint.read_manifest(str(tmp_path))
    assert man is not None and man["step"] == 6 and man["dp"] == 8
    assert telemetry.counter("ckpt.writes") - w0 >= 1
    stepdirs = sorted(d for d in os.listdir(str(tmp_path))
                      if d.startswith("step-"))
    assert man["dir"] in stepdirs and len(stepdirs) <= 2
    ev = [e for e in telemetry.snapshot(events=256)["events"]
          if e["kind"] == "ckpt" and e["name"] == "write"]
    assert ev and ev[-1]["bytes"] > 0 and ev[-1]["dur_ms"] >= 0


@pytest.mark.slow
def test_async_skip_when_write_in_flight(tmp_path, monkeypatch):
    """Backpressure: a snapshot arriving while the queue is full is
    dropped (counted + journaled), never queued behind — training must
    not stall on the disk.  The writer is slowed deterministically so
    the 2-deep queue is guaranteed full by the 4th save."""
    net, st = _build_step(_mesh(8))
    orig = checkpoint.CheckpointManager._write

    def slow_write(self, snap, t_enq):
        time.sleep(0.2)
        return orig(self, snap, t_enq)

    monkeypatch.setattr(checkpoint.CheckpointManager, "_write",
                        slow_write)
    mgr = checkpoint.CheckpointManager(str(tmp_path), st)
    s0 = telemetry.counter("ckpt.skipped")
    results = [mgr.save() for _ in range(5)]
    skipped = results.count(False)
    assert skipped >= 1
    assert mgr.flush(30.0)
    mgr.close()
    assert telemetry.counter("ckpt.skipped") - s0 == skipped
    ev = [e for e in telemetry.snapshot(events=256)["events"]
          if e["kind"] == "ckpt" and e["name"] == "skipped"]
    assert ev and ev[-1]["reason"]


# ---------------------------------------------------------------------------
# atomicity under the chaos write-crash fault
# ---------------------------------------------------------------------------

def test_manifest_survives_write_crash(tmp_path):
    """A crash mid-checkpoint (after some shard files, before the
    manifest flip) leaves the PREVIOUS manifest in force and the
    previous checkpoint fully restorable."""
    net_a, st_a = _build_step(_mesh(4))
    _run(st_a, 2)
    mgr = checkpoint.CheckpointManager(str(tmp_path), st_a,
                                       async_write=False)
    mgr.save()
    good = checkpoint.read_manifest(str(tmp_path))
    _run(st_a, 2)
    chaos.install("checkpoint_write_crash", times=1)
    with pytest.raises(chaos.ChaosError):
        mgr.save()
    assert checkpoint.read_manifest(str(tmp_path)) == good
    net_b, st_b = _build_step(_mesh(4))
    assert checkpoint.restore_latest(str(tmp_path), st_b) == 2
    # async mode: same crash is journaled, training never sees it
    chaos.install("checkpoint_write_crash", times=1)
    f0 = telemetry.counter("ckpt.write_failures")
    mgr2 = checkpoint.CheckpointManager(str(tmp_path), st_a)
    mgr2.save()
    assert mgr2.flush(30.0)
    mgr2.close()
    assert telemetry.counter("ckpt.write_failures") - f0 == 1
    assert mgr2.stats()["last_error"] is not None
    assert checkpoint.read_manifest(str(tmp_path)) == good


def test_nd_save_atomic_under_write_crash(tmp_path):
    """Satellite: ``nd.save`` (the .params writer under every gluon /
    model checkpoint) goes tmp + os.replace — the crash window leaves
    the previous file intact and parseable, and no torn file at the
    target path."""
    path = str(tmp_path / "w.params")
    mx.nd.save(path, {"a": mx.nd.array([1.0, 2.0])})
    chaos.install("checkpoint_write_crash", times=1)
    with pytest.raises(chaos.ChaosError):
        mx.nd.save(path, {"a": mx.nd.array([9.0, 9.0])})
    out = mx.nd.load(path)
    onp.testing.assert_array_equal(out["a"].asnumpy(), [1.0, 2.0])
    assert not [f for f in os.listdir(str(tmp_path)) if ".tmp." in f]
    # fresh-path crash: nothing appears at all (no torn new file)
    p2 = str(tmp_path / "fresh.params")
    chaos.install("checkpoint_write_crash", times=1)
    with pytest.raises(chaos.ChaosError):
        mx.nd.save(p2, {"a": mx.nd.array([1.0])})
    assert not os.path.exists(p2)


def test_model_save_checkpoint_atomic(tmp_path):
    """Satellite: model.save_checkpoint's params AND symbol-json
    writes survive an injected mid-write crash with the previous
    checkpoint intact."""
    from mxnet_tpu import model as model_mod
    from mxnet_tpu import symbol as sym
    x = sym.Variable("data")
    net = sym.FullyConnected(x, num_hidden=3, name="fc")
    prefix = str(tmp_path / "ck")
    arg = {"fc_weight": mx.nd.array(onp.ones((3, 4), "float32")),
           "fc_bias": mx.nd.array(onp.zeros((3,), "float32"))}
    model_mod.save_checkpoint(prefix, 1, net, arg, {})
    chaos.install("checkpoint_write_crash", times=1)
    with pytest.raises(chaos.ChaosError):
        model_mod.save_checkpoint(
            prefix, 1, net,
            {k: mx.nd.array(onp.full_like(v.asnumpy(), 7.0))
             for k, v in arg.items()}, {})
    _, arg2, _ = model_mod.load_checkpoint(prefix, 1)
    onp.testing.assert_array_equal(arg2["fc_weight"].asnumpy(),
                                   arg["fc_weight"].asnumpy())


def test_trainer_save_states_atomic(tmp_path):
    """Satellite: Trainer.save_states is tmp + os.replace on both the
    updater and kvstore paths."""
    onp.random.seed(0)
    net = nn.Dense(3)
    net.initialize(mx.init.Xavier())
    net(mx.nd.array(_X))
    tr = gluon.Trainer(net.collect_params(), "adam",
                       {"learning_rate": 0.01})
    L = gloss.SoftmaxCrossEntropyLoss()
    with mx.autograd.record():
        loss = L(net(mx.nd.array(_X)), mx.nd.array(_Y))
    loss.backward()
    tr.step(16)
    f = str(tmp_path / "trainer.states")
    tr.save_states(f)
    good = open(f, "rb").read()
    tr.step(16)
    chaos.install("checkpoint_write_crash", times=1)
    with pytest.raises(chaos.ChaosError):
        tr.save_states(f)
    assert open(f, "rb").read() == good      # previous file intact
    tr.load_states(f)                        # and still loadable


# ---------------------------------------------------------------------------
# writer-thread concurrency contracts (PR-7 static-vs-runtime pattern)
# ---------------------------------------------------------------------------

def test_writer_thread_lifecycle_and_lock_order(tmp_path,
                                                package_lock_graph):
    """The async writer under the LockOrderSanitizer vs the package
    static lock graph: no cycles, observed edges a subset of the
    static model, and close() joins promptly (stop Event + join — the
    conc-thread-lifecycle contract)."""
    from tools.lint.runtime_lockorder import LockOrderSanitizer
    net, st = _build_step(_mesh(8))
    with LockOrderSanitizer() as san:
        mgr = checkpoint.CheckpointManager(str(tmp_path), st,
                                           every_n_steps=2)
        mgr.attach()
        _run(st, 4)
        assert mgr.flush(30.0)
        t = mgr._thread
        t0 = time.monotonic()
        mgr.close()
        assert time.monotonic() - t0 < 5.0
        assert t is not None and not t.is_alive()
        mgr.close()                          # idempotent
    san.assert_no_cycles()
    san.assert_subgraph_of(package_lock_graph)


def test_manager_errors_without_target(tmp_path):
    mgr = checkpoint.CheckpointManager(str(tmp_path), async_write=False)
    with pytest.raises(MXNetError, match="no target"):
        mgr.save()


def test_read_manifest_tolerates_foreign_file(tmp_path):
    assert checkpoint.read_manifest(str(tmp_path)) is None
    (tmp_path / checkpoint.MANIFEST).write_text("not json {")
    assert checkpoint.read_manifest(str(tmp_path)) is None
    (tmp_path / checkpoint.MANIFEST).write_text(json.dumps([1, 2]))
    assert checkpoint.read_manifest(str(tmp_path)) is None
    with pytest.raises(MXNetError, match="manifest"):
        checkpoint.restore_latest(str(tmp_path), None)
