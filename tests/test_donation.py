"""Step-buffer donation semantics.

DataParallelStep always donates params/opt-state/step-counter/RNG;
``donate_batch=True`` additionally donates the data/label buffers (the
step is their last reader in a pipelined loop).  Safety contract under
test: re-feeding a donated buffer RAISES (instead of silently reading
freed memory — on backends where donation is a no-op the raise is the
only guard), and ``NDArray.mark_borrowed()`` opts a buffer out by
donating a private copy.  Reference analogue: the engine's write-after-
read dependency tracking that MXNet relies on for in-place update ops.
"""
import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import gluon
from mxnet_tpu.gluon import nn


def _tiny_step(donate_batch=False, seed=0):
    mx.random.seed(seed)
    net = nn.HybridSequential()
    net.add(nn.Dense(16, activation="relu"), nn.Dense(4))
    net.initialize()
    rs = onp.random.RandomState(seed)
    x = mx.nd.array(rs.uniform(-1, 1, (8, 12)).astype("float32"))
    y = mx.nd.array(rs.randint(0, 4, (8,)).astype("float32"))
    net(x)
    step = mx.parallel.DataParallelStep(
        net, gluon.loss.SoftmaxCrossEntropyLoss(),
        mx.optimizer.SGD(learning_rate=0.1), mesh=None,
        donate_batch=donate_batch)
    return step, x, y, rs


def _fresh_batch(rs):
    return (mx.nd.array(rs.uniform(-1, 1, (8, 12)).astype("float32")),
            mx.nd.array(rs.randint(0, 4, (8,)).astype("float32")))


def test_default_batch_reuse_is_fine():
    step, x, y, _ = _tiny_step(donate_batch=False)
    l1 = float(step(x, y).asnumpy())
    l2 = float(step(x, y).asnumpy())       # same buffers, no donation
    assert l2 < l1


def test_donated_then_reused_batch_raises():
    step, x, y, rs = _tiny_step(donate_batch=True)
    step(x, y)
    with pytest.raises(RuntimeError, match="donated"):
        step(x, y)                          # same buffer: must refuse


def test_donated_batch_from_earlier_step_still_raises():
    """The reuse guard remembers more than the last call: a buffer
    donated several steps ago must still be refused."""
    step, x, y, _ = _tiny_step(donate_batch=True)
    step(x, y)
    for _ in range(3):
        x2, y2 = _fresh_batch(onp.random.RandomState(3))
        step(x2, y2)
    with pytest.raises(RuntimeError, match="donated"):
        step(x, y)


def test_donate_batch_fresh_batches_train():
    step, x, y, rs = _tiny_step(donate_batch=True)
    losses = [float(step(x, y).asnumpy())]
    for _ in range(5):
        x, y = _fresh_batch(onp.random.RandomState(0))
        losses.append(float(step(x, y).asnumpy()))
    assert losses[-1] < losses[0]


def test_mark_borrowed_opts_buffer_out_of_donation():
    step, x, y, _ = _tiny_step(donate_batch=True)
    x.mark_borrowed()
    y.mark_borrowed()
    l1 = float(step(x, y).asnumpy())
    l2 = float(step(x, y).asnumpy())       # copies were donated, not x/y
    assert l2 < l1
    # and the borrowed buffers are still readable by the caller
    assert onp.isfinite(x.asnumpy()).all()


def test_donated_tuple_batch_entries_tracked():
    """Tuple-of-inputs steps track every donated leaf (None entries
    allowed), so reuse of ANY element raises."""
    mx.random.seed(1)

    class TwoIn(gluon.HybridBlock):
        def __init__(self):
            super().__init__()
            self.a = nn.Dense(8)
            self.b = nn.Dense(8)

        def hybrid_forward(self, F, x, z):
            return self.a(x) + self.b(z)

    net = TwoIn()
    net.initialize()
    rs = onp.random.RandomState(1)
    x = mx.nd.array(rs.uniform(-1, 1, (4, 6)).astype("float32"))
    z = mx.nd.array(rs.uniform(-1, 1, (4, 6)).astype("float32"))
    y = mx.nd.array(rs.randint(0, 8, (4,)).astype("float32"))
    net(x, z)
    step = mx.parallel.DataParallelStep(
        net, gluon.loss.SoftmaxCrossEntropyLoss(),
        mx.optimizer.SGD(learning_rate=0.1), mesh=None, donate_batch=True)
    step((x, z), y)
    x2 = mx.nd.array(rs.uniform(-1, 1, (4, 6)).astype("float32"))
    y2 = mx.nd.array(rs.randint(0, 8, (4,)).astype("float32"))
    with pytest.raises(RuntimeError, match="donated"):
        step((x2, z), y2)                   # z was donated last call


def test_trainer_donate_grads_updates_weights():
    """Trainer(donate_grads=True) threads gradient donation through the
    fused update and keeps training correct."""
    mx.random.seed(2)
    net = nn.Dense(3)
    net.initialize()
    rs = onp.random.RandomState(2)
    x = mx.nd.array(rs.uniform(-1, 1, (5, 4)).astype("float32"))
    net(x)
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.5}, donate_grads=True)
    w0 = net.weight.data().asnumpy().copy()
    for _ in range(2):
        with mx.autograd.record():
            loss = (net(x) ** 2).sum()
        loss.backward()
        trainer.step(batch_size=5)
    w1 = net.weight.data().asnumpy()
    assert not onp.allclose(w0, w1)
    assert onp.isfinite(w1).all()
