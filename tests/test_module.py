"""Module API tests (reference tests/python/unittest/test_module.py)."""
import os

import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import sym


def _mlp_sym(num_hidden=32, classes=4):
    data = sym.var("data")
    net = sym.FullyConnected(data, num_hidden=num_hidden, name="fc1")
    net = sym.Activation(net, act_type="relu")
    net = sym.FullyConnected(net, num_hidden=classes, name="fc2")
    return sym.SoftmaxOutput(net, name="softmax")


def _toy_data(n=256, d=8, classes=4, seed=0):
    rs = onp.random.RandomState(seed)
    X = rs.uniform(-1, 1, (n, d)).astype(onp.float32)
    W = rs.uniform(-1, 1, (d, classes)).astype(onp.float32)
    Y = (X @ W).argmax(axis=1).astype(onp.float32)
    return X, Y


def test_module_bind_and_shapes():
    net = _mlp_sym()
    mod = mx.mod.Module(net, context=mx.cpu())
    mod.bind(data_shapes=[("data", (16, 8))],
             label_shapes=[("softmax_label", (16,))])
    assert mod.binded
    assert mod.data_names == ["data"]
    assert mod.label_names == ["softmax_label"]
    assert set(mod._param_names) == {"fc1_weight", "fc1_bias",
                                     "fc2_weight", "fc2_bias"}


def test_module_fit_converges():
    X, Y = _toy_data()
    train = mx.io.NDArrayIter(X, Y, batch_size=32, shuffle=True,
                              label_name="softmax_label")
    mod = mx.mod.Module(_mlp_sym(), context=mx.cpu())
    mod.fit(train, num_epoch=12, kvstore="local",
            optimizer="sgd",
            optimizer_params={"learning_rate": 0.5,
                              "rescale_grad": 1.0 / 32},
            initializer=mx.init.Xavier())
    score = mod.score(train, "acc")
    assert score[0][1] > 0.90, score


def test_module_fit_kvstore_tpu_mesh():
    """The VERDICT north-star check: Module.fit with kvstore('tpu') over
    the 8-device mesh (contexts = all fake devices)."""
    import jax
    devs = jax.devices()
    if len(devs) < 2:
        pytest.skip("needs multi-device mesh")
    X, Y = _toy_data()
    train = mx.io.NDArrayIter(X, Y, batch_size=32,
                              label_name="softmax_label")
    ctxs = [mx.Context("cpu", i) for i in range(len(devs))]
    mod = mx.mod.Module(_mlp_sym(), context=ctxs)
    mod.fit(train, num_epoch=10, kvstore="tpu",
            optimizer="sgd", optimizer_params={"learning_rate": 0.5,
                              "rescale_grad": 1.0 / 32},
            initializer=mx.init.Xavier())
    score = mod.score(train, "acc")
    assert score[0][1] > 0.90, score


def test_module_predict_and_outputs():
    X, Y = _toy_data(n=64)
    it = mx.io.NDArrayIter(X, Y, batch_size=16,
                           label_name="softmax_label")
    mod = mx.mod.Module(_mlp_sym(), context=mx.cpu())
    mod.bind(data_shapes=it.provide_data, label_shapes=it.provide_label)
    mod.init_params(mx.init.Xavier())
    out = mod.predict(it)
    assert out.shape == (64, 4)
    onp.testing.assert_allclose(out.asnumpy().sum(axis=1), onp.ones(64),
                                rtol=1e-5)


def test_module_checkpoint_roundtrip(tmp_path):
    X, Y = _toy_data(n=64)
    it = mx.io.NDArrayIter(X, Y, batch_size=16, label_name="softmax_label")
    mod = mx.mod.Module(_mlp_sym(), context=mx.cpu())
    mod.bind(data_shapes=it.provide_data, label_shapes=it.provide_label)
    mod.init_params(mx.init.Xavier())
    prefix = os.path.join(str(tmp_path), "mlp")
    mod.save_checkpoint(prefix, 3)
    assert os.path.exists(prefix + "-symbol.json")
    assert os.path.exists(prefix + "-0003.params")

    mod2 = mx.mod.Module.load(prefix, 3, context=mx.cpu())
    mod2.bind(data_shapes=it.provide_data, label_shapes=it.provide_label)
    mod2.init_params()
    p1 = mod.predict(it).asnumpy()
    p2 = mod2.predict(it).asnumpy()
    onp.testing.assert_allclose(p1, p2, rtol=1e-5)


def test_module_input_grads():
    net = _mlp_sym()
    mod = mx.mod.Module(net, context=mx.cpu())
    mod.bind(data_shapes=[("data", (4, 8))],
             label_shapes=[("softmax_label", (4,))],
             inputs_need_grad=True)
    mod.init_params(mx.init.Xavier())
    batch = mx.io.DataBatch(
        data=[mx.nd.array(onp.ones((4, 8), onp.float32))],
        label=[mx.nd.array(onp.zeros(4, onp.float32))])
    mod.forward(batch, is_train=True)
    mod.backward()
    (gin,) = mod.get_input_grads()
    assert gin.shape == (4, 8)
    assert float(onp.abs(gin.asnumpy()).sum()) > 0


def test_bucketing_module():
    """Shared params across bucketed executors (reference
    test_module.test_bucket_module... simplified word-length buckets)."""
    def sym_gen(seq_len):
        # params must be seq-length-independent (as in an RNN LM):
        # per-step projection (flatten=False) then pool over time
        data = sym.var("data")
        net = sym.FullyConnected(data, num_hidden=8, flatten=False,
                                 name="fc_shared")
        net = net.sum(axis=1)
        net = sym.SoftmaxOutput(net, name="softmax")
        return net, ("data",), ("softmax_label",)

    mod = mx.mod.BucketingModule(sym_gen, default_bucket_key=10,
                                 context=mx.cpu())
    mod.bind(data_shapes=[("data", (4, 10, 6))],
             label_shapes=[("softmax_label", (4,))])
    mod.init_params(mx.init.Xavier())
    mod.init_optimizer(kvstore=None, optimizer="sgd",
                       optimizer_params={"learning_rate": 0.1})
    rs = onp.random.RandomState(0)
    for key in (10, 5, 10, 5):
        batch = mx.io.DataBatch(
            data=[mx.nd.array(
                rs.uniform(size=(4, key, 6)).astype("float32"))],
            label=[mx.nd.array(onp.zeros(4, onp.float32))],
            bucket_key=key,
            provide_data=[("data", (4, key, 6))],
            provide_label=[("softmax_label", (4,))])
        mod.forward(batch, is_train=True)
        mod.backward()
        mod.update()
    # the two buckets share fc_shared_weight storage
    w10 = mod._buckets[10]._exec.arg_dict["fc_shared_weight"]
    w5 = mod._buckets[5]._exec.arg_dict["fc_shared_weight"]
    assert w10 is w5


def test_module_reshape_on_batch_change():
    mod = mx.mod.Module(_mlp_sym(), context=mx.cpu())
    mod.bind(data_shapes=[("data", (16, 8))],
             label_shapes=[("softmax_label", (16,))])
    mod.init_params(mx.init.Xavier())
    for bs in (16, 7):
        batch = mx.io.DataBatch(
            data=[mx.nd.array(onp.ones((bs, 8), onp.float32))],
            label=[mx.nd.array(onp.zeros(bs, onp.float32))])
        mod.forward(batch, is_train=False)
        assert mod.get_outputs()[0].shape == (bs, 4)


def test_sequential_module_trains():
    """SequentialModule chains two Modules; gradients flow across the
    seam and the composite trains (reference test_module.py
    test_module_layout / sequential tests)."""
    import mxnet_tpu as mx
    from mxnet_tpu import sym, io
    from mxnet_tpu.module import SequentialModule

    rs = onp.random.RandomState(0)
    x = rs.randn(64, 6).astype("float32")
    y = (x[:, 0] * x[:, 1] > 0).astype("float32")

    d1 = sym.var("data")
    net1 = sym.FullyConnected(d1, num_hidden=16, name="m1fc")
    net1 = sym.Activation(net1, act_type="tanh")

    d2 = sym.var("m1_out")
    net2 = sym.FullyConnected(d2, num_hidden=2, name="m2fc")
    net2 = sym.SoftmaxOutput(net2, name="softmax")

    seq = SequentialModule()
    seq.add(mx.mod.Module(net1, data_names=["data"], label_names=None))
    seq.add(mx.mod.Module(net2, data_names=["m1_out"],
                          label_names=["softmax_label"]),
            take_labels=True, auto_wiring=True)

    train = io.NDArrayIter(x, y, batch_size=16, shuffle=True,
                           last_batch_handle="discard")
    seq.bind(data_shapes=train.provide_data,
             label_shapes=train.provide_label)
    seq.init_params(mx.init.Xavier())
    seq.init_optimizer(optimizer="sgd",
                       optimizer_params=(("learning_rate", 0.2),))
    m = mx.metric.Accuracy()
    for _ in range(30):
        train.reset()
        m.reset()
        for batch in train:
            seq.forward(batch, is_train=True)
            seq.backward()
            seq.update()
            seq.update_metric(m, batch.label)
    assert m.get()[1] > 0.8, m.get()
    # composite params gather from both children
    arg, _ = seq.get_params()
    assert "m1fc_weight" in arg and "m2fc_weight" in arg


def test_module_fit_elastic_midfit_shrink_resumes_epoch():
    """ROADMAP item-4 follow-up: Module.fit(elastic=ctx) consults the
    ElasticContext every batch — a mid-fit world shrink (liveness
    reports a departed worker at batch 3) re-forms the mesh, re-shards
    the context's target, and the SAME epoch resumes in place: fit
    finishes every epoch and still converges."""
    import jax
    from mxnet_tpu.parallel import get_mesh, set_mesh
    from mxnet_tpu.parallel.elastic import ElasticContext

    calls = {"probe": 0, "resharded": []}

    def liveness():
        calls["probe"] += 1
        # healthy for the first 3 batch probes, then one dead worker
        return 0 if calls["probe"] <= 3 else 1

    class StubTarget:
        _mesh = None

        def reshard(self, mesh):
            calls["resharded"].append(int(mesh.size))
            return 0

    X, Y = _toy_data()
    train = mx.io.NDArrayIter(X, Y, batch_size=32, shuffle=True,
                              label_name="softmax_label")
    ctx = ElasticContext(target=StubTarget(), liveness=liveness,
                         world_size=4)
    mod = mx.mod.Module(_mlp_sym(), context=mx.cpu())
    prev_mesh = get_mesh()
    try:
        mod.fit(train, num_epoch=12, kvstore="local",
                optimizer="sgd",
                optimizer_params={"learning_rate": 0.5,
                                  "rescale_grad": 1.0 / 32},
                initializer=mx.init.Xavier(),
                elastic=ctx)
    finally:
        set_mesh(prev_mesh)
    # the shrink happened mid-epoch (batch 4 of 8) and training went on
    assert calls["resharded"] == [len(jax.local_devices())]
    assert ctx.world == 3
    # every batch of every epoch was consulted — the epoch resumed
    assert calls["probe"] == 12 * 8
    score = mod.score(train, "acc")
    assert score[0][1] > 0.90, score
