"""Crash flight recorder (ISSUE 18): always-on postmortem bundles.

The bundle contract: ``dump_incident(reason)`` freezes the journal
tail, histograms, counter/span snapshot, lock-order edges, HBM
estimates and trigger config into one ``incident-<ts>-<reason>/``
directory — built in a dot-tmp and published with ONE ``os.replace``
(the ``incident_write_crash`` chaos fault fires inside exactly that
window and must leave NO committed bundle and NO tmp litter).
``dump_incident`` never raises: it runs on error paths.  Triggers
across the stack (serve quarantine/watchdog, elastic departure,
checkpoint write failure, numerics contract failure) are exercised in
their own suites; this one owns the recorder's own contract.
"""
import json
import os

import pytest

from mxnet_tpu import flight_recorder, telemetry
from mxnet_tpu.parallel import chaos


@pytest.fixture(autouse=True)
def _clean(tmp_path):
    telemetry.reset()
    telemetry.enable()
    chaos.clear()
    # _incident_sandbox (conftest) already routes bundles to tmp_path
    yield
    chaos.clear()
    telemetry.reset()


def _populate():
    telemetry.set_rank(1)
    with telemetry.trace() as tr:
        with telemetry.span("unit.step", hist=True):
            pass
        telemetry.event("serve", "outcome", outcome="timeout",
                        reason="deadline")
    telemetry.inc("unit.count", 2)
    telemetry.event("lockorder", "edge", src="a", dst="b")
    telemetry.set_rank(None)
    return tr.trace_id


def test_bundle_is_well_formed(tmp_path):
    trace_id = _populate()
    path = flight_recorder.dump_incident(
        "unit_test", detail="synthetic", extra={"model": "m"})
    assert path is not None and os.path.isdir(path)
    assert os.path.basename(path).startswith("incident-")
    assert os.path.basename(path).endswith("-unit_test")
    names = sorted(os.listdir(path))
    assert names == ["config.json", "hbm.json", "histograms.json",
                     "journal.jsonl", "lockgraph.json", "snapshot.json"]
    cfg = json.load(open(os.path.join(path, "config.json")))
    assert cfg["reason"] == "unit_test"
    assert cfg["detail"] == "synthetic"
    assert cfg["extra"] == {"model": "m"}
    assert cfg["pid"] == os.getpid()
    snap = json.load(open(os.path.join(path, "snapshot.json")))
    assert snap["counters"]["unit.count"] == 2
    assert snap["spans"]["unit.step"]["count"] == 1
    hists = json.load(open(os.path.join(path, "histograms.json")))
    assert hists["unit.step"]["count"] == 1
    lock = json.load(open(os.path.join(path, "lockgraph.json")))
    assert any(e.get("src") == "a" and e.get("dst") == "b" for e in lock)
    # the journal tail carries the trace — the postmortem can recover
    # the affected request/step end to end
    recs = [json.loads(ln) for ln in
            open(os.path.join(path, "journal.jsonl"))]
    traced = [r for r in recs if r.get("trace") == trace_id]
    assert any(r.get("kind") == "span" for r in traced)
    assert any(r.get("name") == "outcome" for r in traced)
    assert all(r.get("rank") == 1 for r in traced)
    # success is journaled
    evs = telemetry.snapshot()["events"]
    assert any(e["kind"] == "incident" and e["name"] == "dumped"
               and e["path"] == path for e in evs)


def test_incident_write_crash_is_atomic():
    """The chaos fault fires after the bundle is fully built but before
    the one os.replace: no committed bundle, no tmp litter, the failure
    journaled — and dump_incident does NOT raise (it runs on error
    paths)."""
    _populate()
    base = flight_recorder.incident_dir()
    chaos.install("incident_write_crash", times=1)
    path = flight_recorder.dump_incident("crashy")
    assert path is None
    entries = os.listdir(base) if os.path.isdir(base) else []
    assert not [e for e in entries if e.startswith("incident-")], entries
    assert not [e for e in entries if e.startswith(".tmp-")], entries
    evs = telemetry.snapshot()["events"]
    assert any(e["kind"] == "incident" and e["name"] == "dump_failed"
               and "incident_write_crash" in str(e.get("error"))
               for e in evs)
    assert flight_recorder.bundles_dumped() == 0
    # next dump (fault exhausted) commits normally
    path = flight_recorder.dump_incident("crashy")
    assert path is not None and os.path.isdir(path)
    assert flight_recorder.bundles_dumped() == 1


def test_per_process_cap():
    flight_recorder.configure(max_bundles=2)
    assert flight_recorder.dump_incident("one") is not None
    assert flight_recorder.dump_incident("two") is not None
    assert flight_recorder.dump_incident("three") is None
    assert flight_recorder.bundles_dumped() == 2
    evs = telemetry.snapshot()["events"]
    assert any(e["kind"] == "incident" and e["name"] == "skipped"
               and e["reason"] == "three" for e in evs)


def test_kill_switch(monkeypatch):
    monkeypatch.setenv("MXNET_TPU_FLIGHT_RECORDER", "0")
    assert flight_recorder.dump_incident("off") is None
    base = flight_recorder.incident_dir()
    assert not (os.path.isdir(base) and os.listdir(base))


def test_disabled_telemetry_means_no_bundle():
    with telemetry.disabled():
        assert flight_recorder.dump_incident("quiet") is None


def test_numerics_contract_failure_dumps_bundle():
    """A NumericsSanitizer contract violation freezes a bundle before
    the AssertionError propagates."""
    import numpy as onp
    from tools.lint.runtime_numerics import NumericsSanitizer

    san = NumericsSanitizer()
    san.observe("grad:w", onp.array([1.0, onp.inf], "float32"), step=3)
    with pytest.raises(AssertionError, match="non-finite"):
        san.assert_all_finite()
    base = flight_recorder.incident_dir()
    bundles = [e for e in os.listdir(base)
               if e.startswith("incident-")
               and e.endswith("numerics_nonfinite")]
    assert len(bundles) == 1
    cfg = json.load(open(os.path.join(base, bundles[0], "config.json")))
    assert "non-finite" in cfg["detail"]
    # the journal tail holds the numerics/observed narration
    recs = [json.loads(ln) for ln in
            open(os.path.join(base, bundles[0], "journal.jsonl"))]
    assert any(r.get("kind") == "numerics" and r.get("nonfinite")
               for r in recs)


def test_parse_log_incident_summary(capsys):
    """Satellite round-trip: tools/parse_log.py --incident renders a
    committed bundle."""
    import tools.parse_log as P

    _populate()
    telemetry.hist_observe("serve.request", 12.5)
    path = flight_recorder.dump_incident("render_me", detail="d")
    inc = P.parse_incident(path)
    assert inc["config"]["reason"] == "render_me"
    text = P.render_incident(inc)
    assert "render_me" in text
    assert "serve.request" in text
    assert "traces: 1 distinct" in text
    assert "serve/outcome" in text
