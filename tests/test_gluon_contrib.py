"""Gluon contrib tests (reference
``tests/python/unittest/test_gluon_contrib.py``)."""
import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import gluon
from mxnet_tpu.gluon import contrib


def test_concurrent():
    net = contrib.nn.HybridConcurrent(axis=1)
    net.add(gluon.nn.Dense(4), contrib.nn.Identity())
    net.initialize()
    x = mx.nd.array(onp.random.rand(2, 4).astype("float32"))
    out = net(x)
    assert out.shape == (2, 8)
    # identity branch passes input through unchanged
    assert onp.allclose(out.asnumpy()[:, 4:], x.asnumpy())


def test_identity():
    ident = contrib.nn.Identity()
    x = mx.nd.array(onp.random.rand(3, 5).astype("float32"))
    assert onp.allclose(ident(x).asnumpy(), x.asnumpy())


@pytest.mark.parametrize("factor,shape,expect", [
    (3, (2, 6, 5), (2, 2, 15)),
    (2, (2, 8, 3, 3), (2, 2, 6, 6)),
    ((1, 2, 2), (1, 8, 2, 3, 3), (1, 2, 2, 6, 6)),
])
def test_pixelshuffle_shapes(factor, shape, expect):
    ndim = len(shape) - 2
    cls = {1: contrib.nn.PixelShuffle1D, 2: contrib.nn.PixelShuffle2D,
           3: contrib.nn.PixelShuffle3D}[ndim]
    layer = cls(factor)
    x = mx.nd.array(onp.random.rand(*shape).astype("float32"))
    assert layer(x).shape == expect


def test_pixelshuffle2d_values():
    f = 2
    a = onp.random.rand(2, 8, 3, 3).astype("float32")
    got = contrib.nn.PixelShuffle2D(f)(mx.nd.array(a)).asnumpy()
    n, c, h, w = a.shape
    co = c // (f * f)
    want = a.reshape(n, co, f, f, h, w).transpose(0, 1, 4, 2, 5, 3) \
        .reshape(n, co, h * f, w * f)
    assert onp.allclose(got, want)


def test_sync_batchnorm_standalone_matches_bn():
    sbn = contrib.nn.SyncBatchNorm(in_channels=3)
    bn = gluon.nn.BatchNorm(in_channels=3)
    sbn.initialize()
    bn.initialize()
    x = mx.nd.array(onp.random.rand(4, 3, 5, 5).astype("float32"))
    with mx.autograd.record():
        o1 = sbn(x)
    with mx.autograd.record():
        o2 = bn(x)
    assert onp.allclose(o1.asnumpy(), o2.asnumpy(), atol=1e-5)


def test_sync_batchnorm_cross_device():
    """Stats must be the GLOBAL batch stats when run inside shard_map."""
    import jax
    import numpy as np
    from jax.sharding import Mesh, PartitionSpec as P
    from jax.experimental.shard_map import shard_map
    from mxnet_tpu.ops.nn import sync_batch_norm

    devs = np.array(jax.devices())
    mesh = Mesh(devs, ("dp",))
    x = onp.random.RandomState(0).rand(16, 3, 4, 4).astype("float32") * 5
    gamma = onp.ones(3, "float32")
    beta = onp.zeros(3, "float32")
    mm = onp.zeros(3, "float32")
    mv = onp.ones(3, "float32")

    def local(xs):
        return sync_batch_norm(xs, gamma, beta, mm, mv, fix_gamma=False,
                               key="dp", training=True)

    out, mean, var = shard_map(local, mesh=mesh, in_specs=(P("dp"),),
                               out_specs=(P("dp"), P(), P()))(x)
    gmean = x.mean(axis=(0, 2, 3))
    gvar = x.var(axis=(0, 2, 3))
    ref = (x - gmean.reshape(1, -1, 1, 1)) \
        / onp.sqrt(gvar.reshape(1, -1, 1, 1) + 1e-3)
    assert onp.allclose(onp.asarray(mean), gmean, atol=1e-5)
    assert onp.allclose(onp.asarray(out), ref, atol=1e-4)


def test_lstmp_cell():
    cell = contrib.rnn.LSTMPCell(8, 4)
    cell.initialize()
    xs = mx.nd.array(onp.random.rand(2, 5, 6).astype("float32"))
    out, states = cell.unroll(5, xs, merge_outputs=True)
    assert out.shape == (2, 5, 4)           # projected size
    assert states[0].shape == (2, 4)        # h: projection
    assert states[1].shape == (2, 8)        # c: hidden


def test_variational_dropout_cell():
    base = gluon.rnn.GRUCell(7)
    vd = contrib.rnn.VariationalDropoutCell(base, drop_inputs=0.5,
                                            drop_outputs=0.5)
    vd.initialize()
    x = mx.nd.array(onp.random.rand(2, 4, 5).astype("float32"))
    with mx.autograd.record():
        out, _ = vd.unroll(4, x, merge_outputs=True)
    assert out.shape == (2, 4, 7)
    # same mask every step: zeroed output channels are zero at EVERY step
    o = out.asnumpy()
    zero_cols = (o == 0).all(axis=1)
    assert zero_cols.any(), "expected some dropped output channels"


def test_conv_rnn_cells():
    c2 = contrib.rnn.Conv2DLSTMCell((3, 8, 8), 6, (3, 3), (3, 3),
                                    i2h_pad=(1, 1))
    c2.initialize()
    seq = mx.nd.array(onp.random.rand(2, 4, 3, 8, 8).astype("float32"))
    out, states = c2.unroll(4, seq, merge_outputs=True)
    assert out.shape == (2, 4, 6, 8, 8)
    assert states[0].shape == (2, 6, 8, 8)
    assert states[1].shape == (2, 6, 8, 8)

    cg = contrib.rnn.Conv1DGRUCell((2, 10), 4, 3, 3, i2h_pad=1)
    cg.initialize()
    out, _ = cg.unroll(3, mx.nd.array(
        onp.random.rand(2, 3, 2, 10).astype("float32")), merge_outputs=True)
    assert out.shape == (2, 3, 4, 10)

    cr = contrib.rnn.Conv3DRNNCell((2, 4, 4, 4), 3, 3, 3, i2h_pad=1)
    cr.initialize()
    out, _ = cr.unroll(2, mx.nd.array(
        onp.random.rand(1, 2, 2, 4, 4, 4).astype("float32")),
        merge_outputs=True)
    assert out.shape == (1, 2, 3, 4, 4, 4)


def test_deformable_convolution_zero_offset():
    """With zero offsets a deformable conv IS a regular conv."""
    dc = contrib.cnn.DeformableConvolution(5, kernel_size=(3, 3),
                                           padding=(1, 1), in_channels=4)
    dc.initialize()
    x = mx.nd.array(onp.random.rand(2, 4, 7, 7).astype("float32"))
    out = dc(x)
    ref = mx.nd.Convolution(x, dc.weight.data(), dc.bias.data(),
                            kernel=(3, 3), pad=(1, 1), num_filter=5)
    assert onp.allclose(out.asnumpy(), ref.asnumpy(), atol=1e-4)


def test_deformable_convolution_grad():
    dc = contrib.cnn.DeformableConvolution(
        2, kernel_size=(3, 3), padding=(1, 1), in_channels=3,
        offset_weight_initializer="normal")
    dc.initialize()
    x = mx.nd.array(onp.random.rand(1, 3, 5, 5).astype("float32"))
    x.attach_grad()
    with mx.autograd.record():
        out = dc(x)
        loss = out.sum()
    loss.backward()
    assert x.grad is not None
    assert onp.abs(x.grad.asnumpy()).sum() > 0


def test_interval_sampler():
    s = list(contrib.data.IntervalSampler(10, 3))
    assert s == [0, 3, 6, 9, 1, 4, 7, 2, 5, 8]
    s = list(contrib.data.IntervalSampler(10, 3, rollover=False))
    assert s == [0, 3, 6, 9]


def test_estimator_fit():
    import warnings
    rs = onp.random.RandomState(0)
    X = rs.rand(256, 10).astype("float32")
    W = rs.normal(size=(10, 3)).astype("float32")
    Y = (X @ W).argmax(1).astype("float32")
    ds = gluon.data.ArrayDataset(mx.nd.array(X), mx.nd.array(Y))
    dl = gluon.data.DataLoader(ds, batch_size=32)
    net = gluon.nn.Dense(3)
    net.initialize()
    est = contrib.estimator.Estimator(
        net, loss=gluon.loss.SoftmaxCrossEntropyLoss(),
        metrics=mx.metric.Accuracy(),
        trainer=gluon.Trainer(net.collect_params(), "sgd",
                              {"learning_rate": 0.5}))
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        est.fit(dl, val_data=dl, epochs=8)
    _, acc = est.train_metrics[0].get()
    assert acc > 0.8, acc


def test_estimator_early_stopping():
    import warnings
    rs = onp.random.RandomState(0)
    X = rs.rand(64, 5).astype("float32")
    Y = (X.sum(1) > 2.5).astype("float32")
    ds = gluon.data.ArrayDataset(mx.nd.array(X), mx.nd.array(Y))
    dl = gluon.data.DataLoader(ds, batch_size=16)
    net = gluon.nn.Dense(2)
    net.initialize()
    acc = mx.metric.Accuracy()
    handler = contrib.estimator.EarlyStoppingHandler(
        monitor=acc, patience=1, mode="max")
    est = contrib.estimator.Estimator(
        net, metrics=acc,
        trainer=gluon.Trainer(net.collect_params(), "sgd",
                              {"learning_rate": 0.0}))
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        est.fit(dl, epochs=50, event_handlers=[handler])
    # zero lr => no improvement => stops long before 50 epochs
    assert handler.stop_training
    assert handler.current_epoch < 10
