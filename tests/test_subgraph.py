"""Subgraph property framework: registry + the conv+BN inference fold
(reference ``src/operator/subgraph/subgraph_property.h`` and the mkldnn
conv+BN fusion it hosts)."""
import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import subgraph
from mxnet_tpu import symbol as sym


def _net():
    d = sym.var("data")
    x = sym.Convolution(data=d, num_filter=8, kernel=(3, 3), pad=(1, 1),
                        no_bias=True, name="conv1")
    x = sym.BatchNorm(data=x, fix_gamma=False, name="bn1")
    x = sym.Activation(data=x, act_type="relu", name="relu1")
    x = sym.Convolution(data=x, num_filter=4, kernel=(3, 3), pad=(1, 1),
                        no_bias=False, name="conv2")
    x = sym.BatchNorm(data=x, fix_gamma=True, name="bn2")
    x = sym.Pooling(data=x, global_pool=True, pool_type="avg", name="pool")
    x = sym.FullyConnected(data=x, num_hidden=3, name="fc")
    return x


def _random_params(net, data_shape):
    rs = onp.random.RandomState(0)
    arg_shapes, _, aux_shapes = net.infer_shape(data=data_shape)
    args, aux = {}, {}
    for name, shp in zip(net.list_arguments(), arg_shapes):
        if name == "data":
            continue
        args[name] = mx.nd.array(rs.uniform(-0.5, 0.5, shp)
                                 .astype("float32"))
    for name, shp in zip(net.list_auxiliary_states(), aux_shapes):
        if name.endswith("moving_var"):
            aux[name] = mx.nd.array(rs.uniform(0.5, 2.0, shp)
                                    .astype("float32"))
        else:
            aux[name] = mx.nd.array(rs.uniform(-0.5, 0.5, shp)
                                    .astype("float32"))
    return args, aux


def _run(net, args, aux, data):
    ex = net.bind(ctx=mx.cpu(),
                  args={**args, "data": data},
                  args_grad=None, grad_req="null",
                  aux_states=aux)
    return ex.forward(is_train=False)[0].asnumpy()


def test_conv_bn_fold_matches_and_removes_bn():
    net = _net()
    data = mx.nd.array(onp.random.RandomState(1)
                       .uniform(-1, 1, (2, 3, 16, 16)).astype("float32"))
    args, aux = _random_params(net, (2, 3, 16, 16))
    want = _run(net, args, aux, data)

    fused, fargs, faux = net.optimize_for("CONV_BN_FOLD", args, aux)
    # all BatchNorm nodes folded away, their params gone
    assert "BatchNorm" not in fused.tojson()
    assert not faux
    assert "conv1_folded_weight" in fargs and "conv2_folded_bias" in fargs
    assert len(fargs) < len(args)
    got = _run(fused, fargs, faux, data)
    onp.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-5)


def test_conv_bn_fold_op_count_reduced():
    net = _net()
    fused = net.get_backend_symbol("CONV_BN_FOLD")
    import json
    n_before = len([n for n in json.loads(net.tojson())["nodes"]
                    if n["op"] != "null"])
    n_after = len([n for n in json.loads(fused.tojson())["nodes"]
                   if n["op"] != "null"])
    assert n_after == n_before - 2        # two BN nodes gone


def test_shared_conv_output_not_folded():
    """A conv consumed by BN *and* another op must not be folded (the
    second consumer needs the un-normalized activation)."""
    d = sym.var("data")
    c = sym.Convolution(data=d, num_filter=4, kernel=(1, 1), no_bias=True,
                        name="conv")
    b = sym.BatchNorm(data=c, name="bn")
    out = b + c                            # second consumer of conv
    fused = out.get_backend_symbol("CONV_BN_FOLD")
    assert "BatchNorm" in fused.tojson()   # left untouched


def test_registry_api():
    assert "CONV_BN_FOLD" in subgraph.list_subgraph_properties()
    with pytest.raises(mx.MXNetError):
        subgraph.get_subgraph_property("NOPE")

    @subgraph.register_subgraph_property("TEST_IDENTITY")
    class Ident(subgraph.SubgraphProperty):
        def apply(self, s):
            return s

    net = _net()
    assert net.get_backend_symbol("test_identity") is not None
