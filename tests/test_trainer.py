"""Trainer + KVStore + metric tests (reference test_gluon_trainer.py /
test_kvstore.py strategy)."""
import os

import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon, kvstore, metric
from mxnet_tpu.gluon import nn
from mxnet_tpu.gluon import loss as gloss


def _make_net():
    net = nn.HybridSequential()
    net.add(nn.Dense(16, activation="relu"), nn.Dense(4))
    net.initialize()
    return net


def _train(net, trainer, n=8, bs=16):
    L = gloss.SoftmaxCrossEntropyLoss()
    onp.random.seed(0)
    x = mx.nd.array(onp.random.randn(bs, 8).astype("float32"))
    y = mx.nd.array(onp.random.randint(0, 4, bs).astype("float32"))
    losses = []
    for _ in range(n):
        with autograd.record():
            l = L(net(x), y)
        l.backward()
        trainer.step(bs)
        losses.append(float(l.mean().asscalar()))
    return losses


@pytest.mark.parametrize("kv", ["local", "device", None])
def test_trainer_descends(kv):
    net = _make_net()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.5, "momentum": 0.9},
                            kvstore=kv)
    losses = _train(net, trainer)
    assert losses[-1] < losses[0]


def test_trainer_update_on_kvstore_false():
    net = _make_net()
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": 0.05},
                            kvstore="local", update_on_kvstore=False)
    losses = _train(net, trainer)
    assert losses[-1] < losses[0]


def test_trainer_save_load_states(tmp_path):
    net = _make_net()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.1, "momentum": 0.9})
    _train(net, trainer, n=2)
    f = str(tmp_path / "trainer.states")
    trainer.save_states(f)
    trainer2 = gluon.Trainer(net.collect_params(), "sgd",
                             {"learning_rate": 0.1, "momentum": 0.9})
    trainer2.load_states(f)
    assert trainer2._optimizer.momentum == 0.9


def test_trainer_lr():
    net = _make_net()
    trainer = gluon.Trainer(net.collect_params(), "sgd", {"learning_rate": 0.3})
    assert abs(trainer.learning_rate - 0.3) < 1e-9
    trainer.set_learning_rate(0.1)
    assert abs(trainer.optimizer.lr - 0.1) < 1e-9


def test_kvstore_push_pull():
    kv = kvstore.create("local")
    kv.init(3, mx.nd.ones((2, 2)))
    kv.push(3, mx.nd.full((2, 2), 4.0))
    out = mx.nd.zeros((2, 2))
    kv.pull(3, out=out)
    onp.testing.assert_allclose(out.asnumpy(), onp.full((2, 2), 4.0))


def test_kvstore_aggregation():
    kv = kvstore.create("local")
    kv.init("w", mx.nd.zeros((3,)))
    # list push = multi-device gradient aggregation (reference Comm Reduce)
    kv.push("w", [mx.nd.ones((3,)), mx.nd.ones((3,)), mx.nd.ones((3,))])
    out = mx.nd.zeros((3,))
    kv.pull("w", out=out)
    onp.testing.assert_allclose(out.asnumpy(), onp.full((3,), 3.0))


def test_kvstore_updater():
    kv = kvstore.create("local")
    kv.set_optimizer(mx.optimizer.SGD(learning_rate=1.0))
    kv.init(0, mx.nd.zeros((2,)))
    kv.push(0, mx.nd.ones((2,)))
    out = mx.nd.zeros((2,))
    kv.pull(0, out=out)
    onp.testing.assert_allclose(out.asnumpy(), -onp.ones(2))


def test_kvstore_tpu_type():
    kv = kvstore.create("tpu")
    assert kv.rank == 0 and kv.num_workers == 1
    kv.init(0, mx.nd.ones((2,)))
    kv.push(0, mx.nd.full((2,), 2.0))
    out = mx.nd.zeros((2,))
    kv.pull(0, out=out)
    onp.testing.assert_allclose(out.asnumpy(), onp.full((2,), 2.0))


def test_kvstore_dist_async_rejected():
    with pytest.raises(mx.MXNetError):
        kvstore.create("dist_async")


def test_metrics():
    m = metric.Accuracy()
    pred = mx.nd.array(onp.array([[0.1, 0.9], [0.8, 0.2], [0.3, 0.7]]))
    label = mx.nd.array(onp.array([1, 0, 0]))
    m.update([label], [pred])
    name, acc = m.get()
    assert abs(acc - 2.0 / 3) < 1e-6

    m2 = metric.TopKAccuracy(top_k=2)
    pred = mx.nd.array(onp.random.rand(10, 5))
    label = mx.nd.array(onp.random.randint(0, 5, 10))
    m2.update([label], [pred])
    assert m2.get()[1] >= 0

    m3 = metric.MSE()
    m3.update([mx.nd.zeros((4, 1))], [mx.nd.ones((4, 1))])
    assert abs(m3.get()[1] - 1.0) < 1e-6

    comp = metric.create(["acc", "mse"])
    assert isinstance(comp, metric.CompositeEvalMetric)

    cus = metric.create(lambda l, p: onp.abs(l - p).mean())
    cus.update([mx.nd.zeros((2, 2))], [mx.nd.ones((2, 2))])
    assert abs(cus.get()[1] - 1.0) < 1e-6

    f1 = metric.F1()
    p = mx.nd.array(onp.array([[0.2, 0.8], [0.9, 0.1], [0.3, 0.7]]))
    l = mx.nd.array(onp.array([1.0, 0.0, 1.0]))
    f1.update([l], [p])
    assert f1.get()[1] == 1.0

    pp = metric.Perplexity(ignore_label=None)
    prob = mx.nd.array(onp.full((4, 3), 1.0 / 3))
    lbl = mx.nd.array(onp.array([0, 1, 2, 0]))
    pp.update([lbl], [prob])
    assert abs(pp.get()[1] - 3.0) < 1e-3


def test_trainer_multi_precision_bf16_master():
    """gluon.Trainer with multi_precision keeps bf16 params while the
    updater trains an fp32 master (reference update_multi_precision;
    extended to bf16, the TPU half tier)."""
    import numpy as onp
    from mxnet_tpu import autograd
    from mxnet_tpu.gluon import nn
    rs = onp.random.RandomState(3)
    net = nn.Dense(1)
    net.initialize(mx.init.Xavier())
    X = mx.nd.array(rs.rand(32, 4).astype("float32"))
    Yv = (X.asnumpy() @ onp.array([[1.0], [-2.0], [0.5], [3.0]],
                                  "float32")).astype("float32")
    Y = mx.nd.array(Yv)
    net(X)
    net.cast("bfloat16")
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": 0.05,
                             "multi_precision": True})
    loss_fn = gluon.loss.L2Loss()
    first = last = None
    for _ in range(30):
        with autograd.record():
            loss = loss_fn(net(X.astype("bfloat16")), Y.astype("bfloat16"))
        loss.backward()
        trainer.step(32)
        last = float(loss.mean().asscalar())
        if first is None:
            first = last
    assert net.weight.data().dtype == onp.dtype("bfloat16")
    assert last < first * 0.5, (first, last)
