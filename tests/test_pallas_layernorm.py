"""Fused LayerNorm Pallas kernels (CPU: interpret mode; the same kernels
run compiled on the real chip inside every transformer LN site).

Reference role: ``src/operator/nn/layer_norm.cc`` — the reference ships
a hand-fused LayerNorm for the same reason."""
import numpy as onp
import jax
import jax.numpy as jnp
import pytest

from mxnet_tpu.ops import pallas_layernorm as pln


def _mk(n, c, dtype, seed=0):
    rs = onp.random.RandomState(seed)
    x = jnp.asarray(rs.randn(n, c).astype("float32"), dtype)
    g = jnp.asarray((rs.rand(c) + 0.5).astype("float32"), dtype)
    b = jnp.asarray((rs.randn(c) * 0.1).astype("float32"), dtype)
    return x, g, b


def _f32_oracle(x, g, b, eps=1e-5):
    d = x.astype(jnp.float32)
    mu = d.mean(-1, keepdims=True)
    xc = d - mu
    var = (xc * xc).mean(-1, keepdims=True)
    return xc * jax.lax.rsqrt(var + eps) * g.astype(jnp.float32) \
        + b.astype(jnp.float32)


@pytest.mark.parametrize("dtype,tol", [(jnp.float32, 1e-5),
                                       (jnp.bfloat16, 0.05)])
@pytest.mark.parametrize("n", [64, 100])  # 100: padded final block
def test_fwd_kernel_matches_oracle(dtype, tol, n):
    x, g, b = _mk(n, 256, dtype)
    y, mu, rstd = pln.pallas_layer_norm_fwd(x, g, b, 1e-5, block_rows=32,
                                            interpret=True)
    ref = _f32_oracle(x, g, b)
    assert float(jnp.abs(y.astype(jnp.float32) - ref).max()) < tol
    assert mu.shape == (n, 1) and rstd.shape == (n, 1)


def test_bwd_kernel_matches_f32_vjp():
    """dx/dgamma/dbeta against an fp32 autodiff oracle on the SAME
    quantized inputs; dg/db accumulate in fp32 scratch so they match at
    fp32 precision even for bf16 operands."""
    x, g, b = _mk(100, 256, jnp.bfloat16, seed=1)
    ct = jnp.asarray(onp.random.RandomState(2).randn(100, 256)
                     .astype("float32"), jnp.bfloat16)
    xq, gq, bq, cq = (a.astype(jnp.float32) for a in (x, g, b, ct))
    _, vjp = jax.vjp(lambda d, gg, bb: _f32_oracle(d, gg, bb), xq, gq, bq)
    rdx, rdg, rdb = vjp(cq)

    y, mu, rstd = pln.pallas_layer_norm_fwd(x, g, b, 1e-5, block_rows=32,
                                            interpret=True)
    dx, dg, db = pln.pallas_layer_norm_bwd(x, g, mu, rstd, ct,
                                           block_rows=32, interpret=True)
    assert float(jnp.abs(dg - rdg).max()) / float(jnp.abs(rdg).max()) < 1e-5
    assert float(jnp.abs(db - rdb).max()) / float(jnp.abs(rdb).max()) < 1e-5
    assert float(jnp.abs(dx.astype(jnp.float32) - rdx).max()) < 0.05


def test_fused_layer_norm_grads_match_jnp_fallback():
    """The public custom-vjp op (jnp fallback off-TPU) differentiates
    like the plain composition."""
    x, g, b = _mk(24, 128, jnp.float32, seed=3)

    def fused(a, gg, bb):
        return jnp.sum(pln.fused_layer_norm(a, gg, bb, 1e-5) ** 2)

    def plain(a, gg, bb):
        return jnp.sum(pln._jnp_ln(a, gg, bb, 1e-5) ** 2)

    g1 = jax.grad(fused, argnums=(0, 1, 2))(x, g, b)
    g2 = jax.grad(plain, argnums=(0, 1, 2))(x, g, b)
    for a, bb in zip(g1, g2):
        assert float(jnp.abs(a - bb).max()) < 1e-4


def test_layer_norm_op_routes_axis_and_mean_var():
    """The registry op keeps the generic path for non-last axes."""
    from mxnet_tpu.ops.nn import layer_norm
    rs = onp.random.RandomState(5)
    x = jnp.asarray(rs.randn(4, 6, 8).astype("float32"))
    g = jnp.asarray(rs.rand(6).astype("float32") + 0.5)
    b = jnp.asarray(rs.randn(6).astype("float32"))
    out = layer_norm(x, g, b, axis=1)
    ref = _f32_oracle(jnp.swapaxes(x, 1, 2), g, b)
    assert float(jnp.abs(jnp.swapaxes(out, 1, 2) - ref).max()) < 1e-5


def test_huge_channel_falls_back_to_generic_path():
    """C too large for the VMEM budget routes to the jnp path instead of
    a Mosaic compile failure (block picker returns None)."""
    assert pln._pick_block_rows(768, rows=512) is not None
    assert pln._pick_block_rows(10 ** 6, rows=512) is None
    x = jnp.asarray(onp.random.RandomState(0).randn(4, 8).astype("f"))
    g = jnp.ones(8); b = jnp.zeros(8)
    out = pln.fused_layer_norm(x, g, b, 1e-5)  # CPU: fallback either way
    ref = pln._jnp_ln(x, g, b, 1e-5)
    assert float(jnp.abs(out - ref).max()) < 1e-6


def test_default_layer_norm_supports_forward_mode():
    """The default LayerNorm path must stay jvp-differentiable (the
    fused custom_vjp kernels are opt-in via MXNET_FUSED_LAYERNORM=1
    precisely because custom_vjp breaks forward mode)."""
    from mxnet_tpu.ops.nn import layer_norm
    x = jnp.asarray(onp.random.RandomState(0).randn(4, 16).astype("f"))
    g = jnp.ones(16)
    b = jnp.zeros(16)
    out, tangent = jax.jvp(lambda a: layer_norm(a, g, b), (x,),
                           (jnp.ones_like(x),))
    assert out.shape == tangent.shape == x.shape


def test_fused_kernels_mixed_dtype_promotes_like_composition():
    """bf16 data with fp32 affine params: the kernel's output dtype and
    values match the composed jnp expression (partial-AMP models)."""
    rs = onp.random.RandomState(4)
    x = jnp.asarray(rs.randn(16, 128).astype("float32"), jnp.bfloat16)
    g = jnp.asarray((rs.rand(128) + 0.5).astype("float32"))
    b = jnp.asarray(rs.randn(128).astype("float32"))
    y, _, _ = pln.pallas_layer_norm_fwd(x, g, b, 1e-5, block_rows=8,
                                        interpret=True)
    ref = pln._jnp_ln(x, g, b, 1e-5)
    assert y.dtype == ref.dtype == jnp.float32
    assert float(jnp.abs(y - ref).max()) < 0.02


def test_fused_env_knob_routes_to_kernels(monkeypatch):
    """MXNET_FUSED_LAYERNORM=1 flips the op onto the fused path (jnp
    fallback on CPU, same values)."""
    from mxnet_tpu.ops.nn import layer_norm
    monkeypatch.setenv("MXNET_FUSED_LAYERNORM", "1")
    rs = onp.random.RandomState(6)
    x = jnp.asarray(rs.randn(4, 32).astype("f"))
    g = jnp.asarray((rs.rand(32) + 0.5).astype("f"))
    b = jnp.asarray(rs.randn(32).astype("f"))
    out = layer_norm(x, g, b)
    ref = pln._jnp_ln(x, g, b, 1e-5)
    assert float(jnp.abs(out - ref).max()) < 1e-5
