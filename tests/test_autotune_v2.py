"""Autotuner v2 tests: learned cost model + whole-program schedule
search (CPU-safe, virtual 8-device mesh).

Covers the PR contract: deterministic seeded fits with built-in CV,
the hard ``usable`` fallback (empty/corrupt training data degrades to
v1's log-distance ordering, bit-exactly), interpret-sample exclusion on
real chips, model-ranked dispatch search timing strictly fewer
candidates than the v1 budget while never losing to the heuristic, the
miss -> ranked search -> persist round trip in interpret mode, the
lookup-only program-schedule families and their consumers
(``shard_optimizer="auto"`` measured vs heuristic, DevicePrefetchIter
depth, serving bucket menus under the HBM budget), and the
``tools/parse_log.py --jsonl`` v2 census round trip.
"""
import json
import os

import numpy as onp
import pytest

import jax

import mxnet_tpu as mx
from mxnet_tpu import telemetry, tune
from mxnet_tpu.gluon import nn
from mxnet_tpu.gluon import loss as gloss
from mxnet_tpu import parallel
from mxnet_tpu.tune import search
from mxnet_tpu.tune import model as M
from mxnet_tpu.tune import program as prog
from mxnet_tpu.tune import cost_table as ct


@pytest.fixture(autouse=True)
def _isolated_table(tmp_path, monkeypatch):
    """Own table path + reset singletons; autotune env starts unset."""
    monkeypatch.setenv("MXNET_AUTOTUNE_TABLE",
                       str(tmp_path / "cost_table.jsonl"))
    for var in ("MXNET_AUTOTUNE", "MXNET_AUTOTUNE_TRIALS",
                "MXNET_AUTOTUNE_CALLS", "MXNET_AUTOTUNE_INTERPRET",
                "MXNET_AUTOTUNE_MODEL", "MXNET_AUTOTUNE_MODEL_CV",
                "MXNET_AUTOTUNE_MODEL_TOPK", "MXNET_AUTOTUNE_SPANS",
                "MXNET_SERVE_HBM_BUDGET"):
        monkeypatch.delenv(var, raising=False)
    tune._reset_for_tests()
    yield
    tune._reset_for_tests()


@pytest.fixture
def mesh8():
    assert len(jax.devices()) == 8, "conftest must force 8 CPU devices"
    m = parallel.device_mesh((8,), ("dp",))
    old = parallel.get_mesh()
    parallel.set_mesh(m)
    yield m
    parallel.set_mesh(old)


_SHAPE = (512, 512, 64)


def _smooth_ms(cfg):
    """Multiplicative ground truth: log(ms) is linear in the log2
    features, so the ridge fit on log(ms) is near-exact and the CV
    gate passes with margin."""
    return cfg["block_q"] * cfg["block_k"] / 2.0 ** 17 + 0.25


def _attention_samples(shape=_SHAPE, dtype="bfloat16"):
    return [(M.featurize("attention", shape, dtype, cfg),
             _smooth_ms(cfg))
            for cfg in search.candidates("attention", shape, dtype)]


# --- CostModel -------------------------------------------------------------

def test_fit_deterministic_and_serializable():
    samples = _attention_samples()
    assert len(samples) >= M.MIN_SAMPLES
    a = M.CostModel("attention").fit(samples, seed=0)
    b = M.CostModel("attention").fit(samples, seed=0)
    assert a.trained and a.usable
    assert a.weights == b.weights
    assert a.cv_error == b.cv_error
    # serialization round trip predicts identically
    c = M.CostModel.from_dict(a.to_dict())
    cfg = {"block_q": 256, "block_k": 512}
    assert c.predict_config_ms(_SHAPE, "bfloat16", cfg) == \
        pytest.approx(a.predict_config_ms(_SHAPE, "bfloat16", cfg))
    with pytest.raises(ValueError):
        M.CostModel.from_dict({"schema": 999})


def test_under_min_samples_is_untrained_and_unusable():
    m = M.CostModel("attention").fit(_attention_samples()[:M.MIN_SAMPLES - 1])
    assert not m.trained and not m.usable
    with pytest.raises(RuntimeError):
        m.predict_ms([0.0])


def test_cv_gate_refuses_noisy_model(monkeypatch):
    """A model whose CV error exceeds MXNET_AUTOTUNE_MODEL_CV is not
    usable even though it trained."""
    rng = onp.random.RandomState(7)
    noisy = [(f, ms * float(rng.uniform(0.05, 20.0)))
             for f, ms in _attention_samples()]
    m = M.CostModel("attention").fit(noisy)
    assert m.trained
    monkeypatch.setenv("MXNET_AUTOTUNE_MODEL_CV", "0.0001")
    assert not m.usable


def test_get_model_empty_table_returns_none_and_counts_fallback(
        monkeypatch):
    assert M.get_model("attention") is None
    # the dispatch-side acquisition journals the degradation to v1
    monkeypatch.setenv("MXNET_AUTOTUNE_INTERPRET", "1")
    monkeypatch.setenv("MXNET_AUTOTUNE_TRIALS", "1")
    monkeypatch.setenv("MXNET_AUTOTUNE_CALLS", "1")
    before = telemetry.counter("autotune.model_fallback")
    res = tune._dispatch_search("layernorm", (64, 256), "float32")
    assert res is not None and not res["ranked"]
    assert telemetry.counter("autotune.model_fallback") == before + 1
    snap = telemetry.snapshot(events=64)
    assert any(e.get("name") == "model_fallback"
               and e.get("reason") == "untrained_or_cv"
               for e in snap["events"])


def test_training_samples_skip_corrupt_entries():
    t = tune.get_table()
    good = [{"config": {"block_q": 128 * (i + 1), "block_k": 512},
             "ms": 1.0 + i} for i in range(4)]
    bad = [{"config": {"block_q": 128}, "ms": 2.0},          # field missing
           {"config": None, "ms": 1.0},                       # no config
           {"config": {"block_q": 128, "block_k": 512}, "ms": -1.0},
           "not-a-dict"]
    t.record("attention", _SHAPE, "bfloat16",
             {"block_q": 128, "block_k": 512}, best_ms=1.0,
             results=good + bad)
    samples = M.training_samples(t, "attention")
    assert len(samples) == len(good)
    # unknown family contributes nothing rather than raising
    assert M.training_samples(t, "nosuch") == []


def test_interpret_samples_excluded_on_real_chip(monkeypatch):
    t = tune.get_table()
    t.record("attention", _SHAPE, "bfloat16",
             {"block_q": 128, "block_k": 512}, best_ms=1.0,
             interpret=True,
             results=[{"config": {"block_q": 128, "block_k": 512},
                       "ms": 1.0}])
    monkeypatch.setattr(ct, "_on_real_chip", lambda: True)
    assert M.training_samples(t, "attention") == []
    assert len(M.training_samples(t, "attention",
                                  include_interpret=True)) == 1
    monkeypatch.setattr(ct, "_on_real_chip", lambda: False)
    assert len(M.training_samples(t, "attention")) == 1


def test_get_model_retrains_when_table_grows():
    t = tune.get_table()
    cands = search.candidates("attention", _SHAPE, "bfloat16")
    t.record("attention", _SHAPE, "bfloat16", cands[0],
             best_ms=_smooth_ms(cands[0]),
             results=[{"config": c, "ms": _smooth_ms(c)} for c in cands])
    m1 = M.get_model("attention", table=t)
    assert m1 is not None and m1.usable
    assert M.get_model("attention", table=t) is m1     # cached
    t.record("attention", (1024, 1024, 64), "bfloat16", cands[0],
             best_ms=2.0)
    m2 = M.get_model("attention", table=t)
    assert m2 is not m1                                # generation moved


def test_model_kill_switch(monkeypatch):
    monkeypatch.setenv("MXNET_AUTOTUNE_MODEL", "0")
    assert not M.model_enabled()
    assert M.get_model("attention") is None


# --- model-ranked search ---------------------------------------------------

def test_ranked_search_times_strictly_fewer_than_v1_budget():
    """THE acceptance gate: with a usable model the search measures
    strictly fewer candidates than the v1 budget, keeps the heuristic
    as candidate #0, and the winner never loses to it."""
    model = M.CostModel("attention").fit(_attention_samples())
    assert model.usable
    space = len(search.candidates("attention", _SHAPE, "bfloat16"))
    budget = space                      # v1 would measure the full grid
    v1 = search.search_config("attention", _SHAPE, "bfloat16",
                              trials=budget, measure=_smooth_ms)
    assert v1["trials"] == budget and not v1["ranked"]
    before = telemetry.counter("autotune.model_rank")
    v2 = search.search_config("attention", _SHAPE, "bfloat16",
                              trials=budget, measure=_smooth_ms,
                              model=model)
    assert v2["ranked"]
    assert v2["trials"] < budget
    # heuristic is always candidate #0...
    heur = search.heuristic_config("attention", _SHAPE, "bfloat16")
    assert v2["results"][0]["config"] == heur
    # ...so the ranked winner can never lose to v1's baseline
    assert v2["best_ms"] <= _smooth_ms(heur)
    assert v2["best_ms"] == v1["best_ms"]     # found the same optimum
    assert all("pred_ms" in r for r in v2["results"] if "ms" in r)
    assert telemetry.counter("autotune.model_rank") == before + 1
    snap = telemetry.snapshot(events=256)
    ev = [e for e in snap["events"]
          if e.get("kind") == "autotune" and e.get("name") == "model"]
    assert ev and ev[-1]["n"] == v2["trials"]
    assert ev[-1]["mean_err_pct"] < 20.0      # near-exact ground truth


def test_unusable_model_is_bit_identical_to_v1():
    untrained = M.CostModel("attention")
    v1 = search.search_config("attention", _SHAPE, "bfloat16",
                              trials=6, measure=_smooth_ms)
    v2 = search.search_config("attention", _SHAPE, "bfloat16",
                              trials=6, measure=_smooth_ms,
                              model=untrained)
    assert v1 == v2


def test_raising_model_falls_back_to_v1():
    class Hostile(M.CostModel):
        usable = True

        def predict_config_ms(self, *a):
            raise RuntimeError("boom")
    v1 = search.search_config("attention", _SHAPE, "bfloat16",
                              trials=6, measure=_smooth_ms)
    v2 = search.search_config("attention", _SHAPE, "bfloat16",
                              trials=6, measure=_smooth_ms,
                              model=Hostile("attention"))
    assert v1 == v2


def test_topk_env_override(monkeypatch):
    monkeypatch.setenv("MXNET_AUTOTUNE_MODEL_TOPK", "1")
    model = M.CostModel("attention").fit(_attention_samples())
    res = search.search_config("attention", _SHAPE, "bfloat16",
                               trials=16, measure=_smooth_ms,
                               model=model)
    # k=1 keeps only the heuristic — still a valid (v1-baseline) result
    assert res["trials"] == 1
    assert res["config"] == search.heuristic_config(
        "attention", _SHAPE, "bfloat16")


def test_miss_ranked_search_persists_roundtrip_interpret(monkeypatch):
    """MXNET_AUTOTUNE=1 in interpret mode: a miss trains the model from
    the table, runs a RANKED search over fewer candidates than the
    budget, persists winner + per-candidate results, and the next
    dispatch is a pure table hit."""
    t = tune.get_table()
    n_seed = 0
    for shape_seed in ((128, 512), (256, 1024)):
        cands = search.candidates("layernorm", shape_seed, "float32")
        t.record("layernorm", shape_seed, "float32", cands[0],
                 best_ms=1.0, interpret=True,
                 results=[{"config": c,
                           "ms": 0.05 * c["block_rows"]}
                          for c in cands])
        n_seed += len(cands)
    assert n_seed >= M.MIN_SAMPLES
    monkeypatch.setenv("MXNET_AUTOTUNE", "1")
    monkeypatch.setenv("MXNET_AUTOTUNE_INTERPRET", "1")
    monkeypatch.setenv("MXNET_AUTOTUNE_TRIALS", "4")
    monkeypatch.setenv("MXNET_AUTOTUNE_CALLS", "1")
    tune._reset_for_tests()
    ranks = telemetry.counter("autotune.model_rank")
    miss_shape = (64, 256)
    cfg = tune.table_config("layernorm", miss_shape, "float32")
    assert cfg is not None and cfg["source"] == "searched"
    assert telemetry.counter("autotune.model_rank") == ranks + 1
    rec = tune.get_table().lookup("layernorm", miss_shape, "float32")
    assert rec is not None and rec["interpret"]
    assert rec["source"] == "searched"
    timed = [r for r in rec["results"] if "ms" in r]
    assert 0 < len(timed) < 4          # ranked: fewer than the budget
    snap = telemetry.snapshot(events=256)
    ev = [e for e in snap["events"] if e.get("kind") == "autotune"
          and e.get("name") == "search"
          and e.get("family") == "layernorm"]
    assert ev and ev[-1]["ranked"] is True and ev[-1]["interpret"]
    # and the persisted winner now serves as a plain hit
    hits = telemetry.counter("autotune.hit")
    again = tune.table_config("layernorm", miss_shape, "float32")
    assert again["source"] == "table"
    assert {k: again[k] for k in ("block_rows",)} == \
        {k: cfg[k] for k in ("block_rows",)}
    assert telemetry.counter("autotune.hit") == hits + 1


# --- whole-program schedule search ----------------------------------------

def test_program_config_is_lookup_only():
    miss = telemetry.counter("autotune.program_miss")
    searches = telemetry.counter("autotune.program_search")
    assert prog.program_config("prog_prefetch", (64,)) is None
    assert telemetry.counter("autotune.program_miss") == miss + 1
    assert telemetry.counter("autotune.program_search") == searches
    with pytest.raises(ValueError):
        prog.program_config("attention", (64,))


def test_program_knobs_roundtrip_and_default():
    assert prog.program_knobs("prog_prefetch", (64,),
                              default=(2, 1)) == (2, 1)
    tune.get_table().record("prog_prefetch", (64,), "float32",
                            {"depth": 4, "workers": 2}, best_ms=0.5,
                            source="searched")
    hits = telemetry.counter("autotune.program_hit")
    assert prog.program_knobs("prog_prefetch", (64,)) == (4, 2)
    assert telemetry.counter("autotune.program_hit") == hits + 1
    # single-field family returns the scalar; the package-level alias
    # goes through the same store
    tune.get_table().record("prog_scan", (32, 256), "float32",
                            {"k": 4}, best_ms=0.5, source="searched")
    assert tune.program_knobs("prog_scan", (32, 256), default=1) == 4


def test_invalid_program_entry_falls_back():
    tune.get_table().record("prog_prefetch", (64,), "float32",
                            {"depth": 999, "workers": 1}, best_ms=0.5)
    fb = telemetry.counter("autotune.program_fallback")
    assert prog.program_config("prog_prefetch", (64,)) is None
    assert telemetry.counter("autotune.program_fallback") == fb + 1


def test_search_program_deterministic_with_fake_measure():
    def fake(cfg, calls):
        return abs(cfg["k"] - 4) + 1.0
    a = prog.search_program("prog_scan", (32, 256), measure=fake)
    b = prog.search_program("prog_scan", (32, 256), measure=fake)
    assert a == b
    assert a["config"] == {"k": 4} and a["strategy"] in ("sh", "cd")
    # multi-axis grid goes through coordinate descent and converges in
    # fewer measurements than the full grid
    def fake2(cfg, calls):
        return abs(cfg["depth"] - 4) + abs(cfg["workers"] - 2) + 1.0
    r = prog.search_program("prog_prefetch", (64,), measure=fake2)
    assert r["config"] == {"depth": 4, "workers": 2}
    assert r["strategy"] == "cd"
    assert r["trials"] < r["space"] * 2


def test_bucket_menu_round_trip_and_hbm_validation():
    assert prog.menu_from_config({"max_bucket": 8, "levels": 3}) == \
        [2, 4, 8]
    assert prog.config_from_menu([2, 4, 8]) == \
        {"max_bucket": 8, "levels": 3}
    # over-budget menus drop the largest bucket first, never empty out:
    # in+out of buckets {2,4} at feat=1024 fp32 is 2*(2+4)*1024*4 bytes
    menu = prog.validate_menu([2, 4, 8], (1024,), "float32",
                              budget=2 * 6 * 1024 * 4)
    assert menu == [2, 4]
    tiny = prog.validate_menu([64], (1024 * 1024,), "float32", budget=1)
    assert tiny == [64]                          # never empties

    from mxnet_tpu.serve.buckets import default_bucket_menu
    menu, src = default_bucket_menu(max_batch=8, feature_shape=(16,))
    assert src == "heuristic" and menu[-1] == 8
    tune.get_table().record("prog_buckets", (8,), "float32",
                            {"max_bucket": 8, "levels": 2}, best_ms=1.0,
                            source="searched")
    menu, src = default_bucket_menu(max_batch=8, feature_shape=(16,))
    assert src == "table" and menu == [4, 8]
    # a non-power-of-two cap canonicalizes onto the same table key
    menu, src = default_bucket_menu(max_batch=6, feature_shape=(16,))
    assert src == "table" and menu == [4, 8]


def test_prefetch_iter_depth_from_table():
    from mxnet_tpu.io import DataBatch, DataDesc, DataIter
    from mxnet_tpu.io.device_prefetch import DevicePrefetchIter

    class TinyIter(DataIter):
        def __init__(self):
            super().__init__(64)
            self.i = 0

        @property
        def provide_data(self):
            return [DataDesc("data", (64, 4))]

        @property
        def provide_label(self):
            return [DataDesc("softmax_label", (64,))]

        def reset(self):
            self.i = 0

        def next(self):
            if self.i >= 2:
                raise StopIteration
            self.i += 1
            return DataBatch(
                [mx.nd.zeros((64, 4), dtype="uint8")],
                [mx.nd.zeros((64,))], pad=0)

    def probe(depth):
        feed = DevicePrefetchIter(TinyIter(), dtype="float32",
                                  depth=depth)
        try:
            return feed._depth, feed.tuner_source
        finally:
            feed.close()

    assert probe(None) == (2, "heuristic")
    tune.get_table().record("prog_prefetch", (64,), "float32",
                            {"depth": 4, "workers": 1}, best_ms=0.5,
                            source="searched")
    assert probe(None) == (4, "table")
    # explicit depth is untouched (bit-identical v1 behaviour)
    assert probe(3) == (3, "explicit")


# --- shard_optimizer="auto" ------------------------------------------------

def _auto_step(mesh):
    onp.random.seed(0)
    net = nn.HybridSequential()
    net.add(nn.Dense(7, activation="relu"), nn.Dense(4))
    net.initialize(mx.init.Xavier())
    net(mx.nd.array(onp.zeros((8, 9), "float32")))
    L = gloss.SoftmaxCrossEntropyLoss()
    return parallel.DataParallelStep(
        net, lambda o, l: L(o, l), mx.optimizer.SGD(learning_rate=0.1),
        mesh=mesh, shard_optimizer="auto")


def _last_zero_event():
    snap = telemetry.snapshot(events=256)
    evs = [e for e in snap["events"] if e.get("kind") == "zero"
           and e.get("name") == "auto_decision"]
    return evs[-1] if evs else None


def test_auto_shard_heuristic_path(mesh8):
    st = _auto_step(mesh8)
    assert st._shard_n == 8
    ev = _last_zero_event()
    assert ev and ev["path"] == "heuristic" and ev["shard"] is True
    assert ev["tuner_source"] == "heuristic" and ev["dp"] == 8
    assert ev["params"] > 0


def test_auto_shard_measured_veto(mesh8):
    """A measured prog_zero entry saying shard=0 overrides the
    heuristic — and the decision is journaled as measured."""
    pcount = 9 * 7 + 7 + 7 * 4 + 4          # the probe net's weights
    key = (prog.canon_param_count(pcount), 8)
    tune.get_table().record("prog_zero", key, "float32", {"shard": 0},
                            best_ms=1.0, source="searched")
    st = _auto_step(mesh8)
    assert st._shard_n == 0
    ev = _last_zero_event()
    assert ev and ev["path"] == "measured" and ev["shard"] is False
    assert ev["tuner_source"] == "table"
    # and the flipped table entry turns sharding back on
    tune.get_table().record("prog_zero", key, "float32", {"shard": 1},
                            best_ms=1.0, source="searched")
    st = _auto_step(mesh8)
    assert st._shard_n == 8
    ev = _last_zero_event()
    assert ev["path"] == "measured" and ev["shard"] is True


# --- parse_log --jsonl v2 census ------------------------------------------

def test_parse_log_renders_v2_census(tmp_path):
    import sys
    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))), "tools"))
    import parse_log

    # model-ranked search -> autotune/model error event + counter
    model = M.CostModel("attention").fit(_attention_samples())
    search.search_config("attention", _SHAPE, "bfloat16", trials=16,
                         measure=_smooth_ms, model=model)
    # program decisions: one miss, one hit
    prog.program_config("prog_scan", (32, 256))
    tune.get_table().record("prog_scan", (32, 256), "float32",
                            {"k": 4}, best_ms=0.5, source="searched")
    prog.program_config("prog_scan", (32, 256))
    # the consumer-side events the census also rows up (emitted by
    # DataParallelStep / InferenceServer in-process; synthesized here
    # so the round trip stays mesh-free)
    telemetry.event("zero", "auto_decision", path="measured",
                    shard=False, params=4096, dp=8, tuner_source="table")
    telemetry.event("serve", "bucket_menu", model="m", buckets=[4, 8],
                    tuner_source="table")

    path = str(tmp_path / "telemetry.jsonl")
    telemetry.export_jsonl(path)
    with open(path) as fh:
        agg = parse_log.parse_jsonl(fh)
    assert agg["model"]["errors"], "ranked search must journal an error row"
    err = agg["model"]["errors"][-1]
    assert err["family"] == "attention" and err["n"] > 0
    events = [(e["event"], e["source"]) for e in agg["program"]]
    assert ("program/miss", "heuristic") in events
    assert ("program/hit", "table") in events
    assert ("zero/auto_decision", "table") in events
    assert ("serve/bucket_menu", "table") in events

    text = parse_log.render_jsonl(agg)
    assert "autotune cost model (predicted vs measured" in text
    assert "model_rank=" in text
    assert "program schedule decisions:" in text
    assert "program/hit" in text and "k=4" in text
    assert "zero/auto_decision" in text and "shard=False" in text
    # tsv mode renders the same censuses without markdown pipes
    tsv = parse_log.render_jsonl(agg, fmt="tsv")
    assert "program/hit\tprog_scan" in tsv


def test_parse_log_model_fallback_tally(tmp_path):
    import sys
    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))), "tools"))
    import parse_log

    lines = [json.dumps({"kind": "autotune", "name": "model_fallback",
                         "reason": "untrained_or_cv"})] * 3
    agg = parse_log.parse_jsonl(lines)
    assert agg["model"]["fallbacks"] == {"untrained_or_cv": 3}
    assert "fallback[untrained_or_cv]=3" in parse_log.render_jsonl(agg)
