"""Runtime numerics sanitizer (tools.lint.runtime_numerics) tests.

The dynamic half of the num-* rule family, in the PR-6/7
static-vs-runtime pattern: observed dtypes must be consistent with the
static dtype-flow table, fp32 masters must stay float32, no tagged
leaf may drift dtypes or go non-finite.  The seeded-bug acceptance
here runs the SAME pristine/seeded pair of ``fx_zero_update.py``
modules the static half in tests/test_lint.py lints.
"""
import importlib.util
import logging
import os
import sys

import numpy as onp
import pytest

import jax
import jax.numpy as jnp

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon, telemetry
from mxnet_tpu.gluon import nn
from mxnet_tpu.gluon import loss as gloss

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXDIR = os.path.join(REPO, "tests", "lint_fixtures")
ZPATH = os.path.join(FIXDIR, "fx_zero_update.py")

sys.path.insert(0, REPO) if REPO not in sys.path else None

from tools.lint.numerics import static_dtype_flow  # noqa: E402
from tools.lint.runtime_numerics import NumericsSanitizer  # noqa: E402

ZERO_KEY = "tests/lint_fixtures/fx_zero_update.py:zero_momentum_step.body"


def _load(path, name):
    spec = importlib.util.spec_from_file_location(name, path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


# ---------------------------------------------------------------------------
# sanitizer unit tests
# ---------------------------------------------------------------------------

def test_observe_finite_and_journal():
    telemetry.reset()
    san = NumericsSanitizer()
    san.observe("t:leaf_ok", jnp.ones((4,), jnp.float32), step=0)
    san.assert_all_finite()
    san.observe("t:leaf_bad", jnp.asarray([1.0, onp.inf, onp.nan]),
                step=3)
    assert san.first_nonfinite == (3, "t:leaf_bad")
    with pytest.raises(AssertionError, match="non-finite"):
        san.assert_all_finite()
    # integer leaves record dtype only (no isfinite over ints)
    san.observe("t:leaf_int", jnp.arange(4), step=4)
    assert san.observed["t:leaf_int"]["nonfinite"] == 0
    events = [e for e in telemetry.snapshot(events=4096)["events"]
              if e.get("kind") == "numerics"]
    assert any(e["leaf"] == "t:leaf_bad" and e["nonfinite"] == 2 and
               e["step"] == 3 for e in events)


def test_dtype_drift_and_master_contract():
    san = NumericsSanitizer()
    san.observe("t:w", jnp.ones((2,), jnp.bfloat16))
    san.observe("t:w", jnp.ones((2,), jnp.bfloat16))
    san.assert_no_dtype_drift()
    san.observe("t:w", jnp.ones((2,), jnp.float32))   # live promotion
    with pytest.raises(AssertionError, match="drift"):
        san.assert_no_dtype_drift()
    san2 = NumericsSanitizer()
    san2.observe("t:m", jnp.ones((2,), jnp.float32), role="master")
    san2.assert_master_fp32()
    san2.observe("t:m2", jnp.ones((2,), jnp.bfloat16), role="master")
    with pytest.raises(AssertionError, match="master"):
        san2.assert_master_fp32()


def test_consistency_with_static_flow_table():
    flow = {"pkg/mod.py:fn": {"acc": "float32"}}
    san = NumericsSanitizer()
    san.observe("pkg/mod.py:fn:acc", jnp.ones((2,), jnp.float32))
    san.observe("pkg/mod.py:fn:other", jnp.ones((2,), jnp.bfloat16))
    san.assert_consistent_with(flow)      # unknown vars are not checked
    san.observe("pkg/mod.py:fn:acc", jnp.ones((2,), jnp.bfloat16))
    with pytest.raises(AssertionError, match="static float32"):
        san.assert_consistent_with(flow)


# ---------------------------------------------------------------------------
# seeded-bug acceptance: the SAME module pair as the static half
# ---------------------------------------------------------------------------

def _run_zero_step(mod):
    mesh = mod.make_mesh(onp.asarray(jax.devices()))
    rs = onp.random.RandomState(0)
    w = jnp.asarray(rs.randn(21).astype("float32"))
    # gradient magnitudes whose squares exceed the float16 range but
    # stay comfortably inside float32 — the fp32 upcast is what keeps
    # the grad-norm finite
    g = jnp.asarray(onp.full((21,), 300.0, "float32"))
    lr = jnp.asarray(0.1, jnp.float32)
    return mod.zero_momentum_step(mesh, w, g, lr)


def test_zero_update_pristine_consistent_with_static_flow():
    """The runtime-observed dtypes of the pristine ZeRO update match
    the static dtype-flow table of the same file, every value is
    finite, and the master shard is float32 — the PR-6/7
    static-vs-runtime contract, green on the pristine module."""
    flow = static_dtype_flow([ZPATH], root=REPO)
    assert flow[ZERO_KEY]["gnorm"] == "float32"
    assert flow[ZERO_KEY]["new_master"] == "float32"
    assert flow[ZERO_KEY]["half"] == "float16"
    mod = _load(ZPATH, "fx_zero_pristine")
    half, master, gnorm = _run_zero_step(mod)
    san = NumericsSanitizer()
    san.observe(ZERO_KEY + ":half", half, step=0)
    san.observe(ZERO_KEY + ":gnorm", gnorm, step=0)
    san.observe(ZERO_KEY + ":new_master", master, role="master", step=0)
    san.assert_all_finite()
    san.assert_no_dtype_drift()
    san.assert_master_fp32()
    san.assert_consistent_with(flow)


def test_zero_update_seeded_bug_trips_runtime_checks(tmp_path):
    """Acceptance (dynamic half): dropping the fp32 upcast — the same
    seed tests/test_lint.py proves trips num-lowprec-accum statically —
    must also trip the runtime sanitizer: the grad-norm is observed in
    float16 (inconsistent with the pristine static flow) AND overflows
    to inf (finite check)."""
    src = open(ZPATH).read()
    bugged = src.replace("g16.astype(jnp.float32)", "g16")
    assert bugged != src, "seeding site moved — update the test"
    p = tmp_path / "fx_zero_bug.py"
    p.write_text(bugged)
    flow = static_dtype_flow([ZPATH], root=REPO)   # PRISTINE contract
    mod = _load(str(p), "fx_zero_bug")
    half, master, gnorm = _run_zero_step(mod)
    san = NumericsSanitizer()
    san.observe(ZERO_KEY + ":gnorm", gnorm, step=0)
    assert san.dtypes()[ZERO_KEY + ":gnorm"] == "float16"
    with pytest.raises(AssertionError, match="static float32"):
        san.assert_consistent_with(flow)
    with pytest.raises(AssertionError, match="non-finite"):
        san.assert_all_finite()


# ---------------------------------------------------------------------------
# trainer sweep: params/grads/fp32 masters via the step hook
# ---------------------------------------------------------------------------

def _bf16_net_and_trainer():
    onp.random.seed(7)
    mx.random.seed(7)
    net = nn.HybridSequential()
    net.add(nn.Dense(5, activation="relu"), nn.Dense(3))
    net.initialize(mx.init.Xavier())
    net(mx.nd.array(onp.random.randn(4, 6).astype("float32")))
    net.cast("bfloat16")
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.05,
                             "multi_precision": True})
    return net, trainer


def _steps(net, trainer, n=3):
    L = gloss.SoftmaxCrossEntropyLoss()
    rs = onp.random.RandomState(1)
    x = mx.nd.array(rs.randn(4, 6).astype("float32")).astype("bfloat16")
    y = mx.nd.array(rs.randint(0, 3, 4).astype("float32"))
    for _ in range(n):
        with autograd.record():
            loss = L(net(x), y)
        loss.backward()
        trainer.step(4)


def test_sanitizer_attach_trainer_master_fp32():
    """attach(trainer): the hook sweep observes bf16 params/grads and
    the multi_precision fp32 master leaves; the master contract, the
    no-drift contract and finiteness all hold over a real bf16
    training run."""
    net, trainer = _bf16_net_and_trainer()
    san = NumericsSanitizer().attach(trainer)
    try:
        _steps(net, trainer, n=3)
    finally:
        san.detach()
    masters = [s for s, r in san.observed.items()
               if r["role"] == "master"]
    params = [s for s, r in san.observed.items() if r["role"] == "param"]
    grads = [s for s, r in san.observed.items() if r["role"] == "grad"]
    assert masters and params and grads, san.observed
    assert all(san.dtypes()[s] == "bfloat16" for s in params)
    san.assert_all_finite()
    san.assert_no_dtype_drift()
    san.assert_master_fp32()
    # every master got re-checked across steps, not just once
    assert all(san.observed[s]["checks"] >= 2 for s in masters)


def test_sanitizer_interval_skips_steps():
    net, trainer = _bf16_net_and_trainer()
    san = NumericsSanitizer(interval=2).attach(trainer)
    try:
        _steps(net, trainer, n=4)
    finally:
        san.detach()
    # steps 0 and 2 are due: exactly 2 sweeps per site
    assert all(r["checks"] == 2 for r in san.observed.values()), \
        {s: r["checks"] for s, r in san.observed.items()}


def test_numerics_events_journal_and_render(tmp_path):
    """numerics/observed events land in the telemetry journal (first
    sighting, dtype change, non-finite count) and tools/parse_log.py
    --jsonl renders the per-leaf dtype + finite-gauge table."""
    telemetry.reset()
    san = NumericsSanitizer()
    san.observe("t:acc", jnp.ones((3,), jnp.float32), step=0)
    san.observe("t:acc", jnp.ones((3,), jnp.float32), step=1)  # no event
    san.observe("t:acc", jnp.ones((3,), jnp.bfloat16), step=2)  # drift
    san.observe("t:bad", jnp.asarray([onp.inf, 1.0]), step=5)
    obs = [e for e in telemetry.snapshot(events=4096)["events"]
           if e.get("kind") == "numerics"]
    assert len(obs) == 3, obs          # fresh, drift, nonfinite
    sink = tmp_path / "journal.jsonl"
    telemetry.export_jsonl(str(sink))
    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        import parse_log
    finally:
        sys.path.pop(0)
    agg = parse_log.parse_jsonl(sink.read_text().splitlines())
    assert agg["numerics"]["t:acc"]["dtypes"] == ["float32", "bfloat16"]
    assert agg["numerics"]["t:bad"]["nonfinite"] == 1
    assert agg["numerics"]["t:bad"]["first_bad_step"] == 5
    rendered = parse_log.render_jsonl(agg)
    assert "numerics/observed" in rendered
    assert "float32 -> bfloat16" in rendered
    telemetry.reset()


# ---------------------------------------------------------------------------
# Monitor nan_guard
# ---------------------------------------------------------------------------

def test_monitor_nan_guard_warns_on_first_nonfinite(caplog):
    net, trainer = _bf16_net_and_trainer()
    telemetry.reset()
    mon = mx.monitor.Monitor(interval=1000, pattern=".*",
                             nan_guard=True).attach(trainer)
    try:
        with caplog.at_level(logging.WARNING):
            _steps(net, trainer, n=1)
            assert not [r for r in caplog.records
                        if "nan_guard" in r.message]
            # poison one weight, then step again: the guard must name
            # the leaf and the step index, once
            p = next(iter(net.collect_params().values()))
            bad = onp.array(p.data().asnumpy().astype("float32"))
            bad[0] = onp.nan
            p.set_data(mx.nd.array(bad).astype(p.dtype))
            _steps(net, trainer, n=2)
    finally:
        mon.detach()
    warns = [r.message for r in caplog.records if "nan_guard" in r.message]
    assert len(warns) == 1, warns        # warn-once
    # the warning names a leaf and the first offending step (the NaN
    # spreads through the step's update before the sweep runs, so the
    # named leaf is whichever poisoned leaf the sweep meets first —
    # same layer as the poisoned weight)
    assert "at step 1" in warns[0], warns[0]
    assert p.name.rsplit("_", 1)[0] in warns[0], (p.name, warns[0])
    # the sweep journaled the sanitizer-style numerics/observed event
    events = [e for e in telemetry.snapshot(events=4096)["events"]
              if e.get("kind") == "numerics"
              and e.get("role") == "nan_guard"]
    assert events and events[0]["nonfinite"] >= 1
