"""Dispatch heuristic + short-sequence flash kernel tests.

The dispatcher (``attention_dispatch``) picks short_seq / streaming /
dense_fallback per shape; the short-seq kernel is the single-pass
forward (no online-softmax streaming state) plus the no-scratch
single-block dqkv backward.  Numerics run in interpret mode on CPU —
the same kernels compile on a real TPU (bench.py attention records the
dispatch choice and gates flash_speedup >= 1.0 at S=512 on-chip).
"""
import numpy as onp
import jax
import jax.numpy as jnp
import pytest

from mxnet_tpu.ops import pallas_attention as P


def _rand(shape, seed, dtype="float32"):
    x = onp.random.RandomState(seed).uniform(-1, 1, shape).astype("float32")
    return jnp.asarray(x, jnp.dtype(dtype))


def _dense_masked(q, k, v, kv_lens=None, q_seg=None, kv_seg=None,
                  causal=False):
    d = q.shape[-1]
    tq, tk = q.shape[2], k.shape[2]
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k).astype(jnp.float32) * d ** -0.5
    mask = jnp.ones((q.shape[0], 1, tq, tk), bool)
    if kv_lens is not None:
        mask = mask & (jnp.arange(tk)[None, None, None, :]
                       < kv_lens[:, None, None, None])
    if q_seg is not None:
        mask = mask & (q_seg[:, None, :, None] == kv_seg[:, None, None, :])
    if causal:
        mask = mask & (jnp.arange(tq)[:, None] >= jnp.arange(tk)[None, :])
    s = jnp.where(mask, s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    p = jnp.where(jnp.any(mask, axis=-1, keepdims=True), p, 0.0)
    return jnp.einsum("bhqk,bhkd->bhqd", p.astype(q.dtype), v)


# --- dispatch heuristic ----------------------------------------------------

def test_dispatch_dense_fallback_off_tpu():
    # this suite runs on CPU: the public op must route dense
    assert P.attention_dispatch(512, 512, 64)["kernel"] == "dense_fallback"


def test_dispatch_table_on_tpu():
    d = lambda s: P.attention_dispatch(s, s, 64, "bfloat16", on_tpu=True)
    assert d(64)["kernel"] == "dense_fallback"      # tiny: dense wins
    p512 = d(512)
    assert p512["kernel"] == "short_seq"            # the BERT config shape
    assert p512["block_k"] == 512                   # whole K axis, one block
    assert d(384)["kernel"] == "short_seq"
    assert d(4096)["kernel"] == "streaming"


def test_dispatch_short_seq_blocks_cover_whole_k_axis():
    for s in (128, 256, 384, 512, 1000):
        plan = P.attention_dispatch(s, s, 64, "bfloat16", on_tpu=True)
        if plan["kernel"] == "short_seq":
            assert plan["block_k"] >= s


def test_dispatch_never_exceeds_vmem_clamp():
    """No dispatched kernel's padded blocks may exceed the VMEM clamp."""
    for s in (128, 384, 512, 1024, 2048, 4096, 8192):
        for d in (32, 64, 128, 256):
            for dt in ("float32", "bfloat16"):
                plan = P.attention_dispatch(s, s, d, dt, on_tpu=True)
                if plan["kernel"] == "dense_fallback":
                    continue
                Dp = d + (-d) % 64
                used = P._fwd_vmem_bytes(plan["block_q"], plan["block_k"],
                                         Dp, jnp.dtype(dt).itemsize)
                assert used <= P._VMEM_CLAMP, (s, d, dt, plan, used)


# --- short-seq kernel numerics --------------------------------------------

def _mask_operands(cfg, B, S, seed=99):
    kv_lens = q_seg = kv_seg = None
    causal = cfg == "causal"
    if cfg == "kv_lens":
        rs = onp.random.RandomState(seed)
        kv_lens = jnp.asarray(rs.randint(S // 3, S + 1, (B,)), jnp.int32)
    elif cfg == "segments":
        seg = onp.zeros((B, S), onp.int32)
        for b in range(B):
            seg[b, (S // 3) * (b + 1):] = 1
        q_seg = kv_seg = jnp.asarray(seg)
    return causal, kv_lens, q_seg, kv_seg


def _check_short_seq(S, cfg, dtype):
    B, H, D = 2, 2, 64
    q, k, v = (_rand((B, H, S, D), i, dtype) for i in range(3))
    do = _rand((B, H, S, D), 7, dtype)
    causal, kv_lens, q_seg, kv_seg = _mask_operands(cfg, B, S)
    bq, bk = P.tune_attention_blocks(S, S, D, dtype)
    assert bk >= S        # whole K axis: the single-pass kernel path
    kw = dict(causal=causal, kv_lens=kv_lens, q_segments=q_seg,
              kv_segments=kv_seg, interpret=True, block_q=bq, block_k=bk)
    out, lse = P.pallas_flash_attention(q, k, v, return_lse=True, **kw)
    dq, dk, dv = P.pallas_flash_attention_bwd(q, k, v, out, lse, do, **kw)
    _, vjp = jax.vjp(
        lambda a, b, c: _dense_masked(a, b, c, kv_lens=kv_lens,
                                      q_seg=q_seg, kv_seg=kv_seg,
                                      causal=causal), q, k, v)
    ref = _dense_masked(q, k, v, kv_lens=kv_lens, q_seg=q_seg,
                        kv_seg=kv_seg, causal=causal)
    rq, rk, rv = vjp(do)
    tol = 0.06 if dtype == "bfloat16" else 5e-5
    for name, got, want in (("out", out, ref), ("dq", dq, rq),
                            ("dk", dk, rk), ("dv", dv, rv)):
        err = float(jnp.max(jnp.abs(got.astype(jnp.float32)
                                    - want.astype(jnp.float32))))
        assert err < tol, (name, S, cfg, dtype, err)


def test_short_seq_kernel_numerics_fast():
    """Tier-1 representative of the sweep below: non-power-of-two S with
    kv_lens in fp32 (single-pass fwd + single-block dqkv bwd)."""
    _check_short_seq(384, "kv_lens", "float32")


@pytest.mark.slow
@pytest.mark.parametrize("dtype", ["float32", "bfloat16"])
@pytest.mark.parametrize("cfg", ["causal", "kv_lens", "segments"])
@pytest.mark.parametrize("S", [128, 384, 512])
def test_short_seq_kernel_numerics(S, cfg, dtype):
    _check_short_seq(S, cfg, dtype)


def test_single_pass_fwd_matches_streaming_fwd():
    """The single-pass kernel (block_k = whole axis) must agree with the
    streaming kernel (block_k < axis) bit-for-fp32-bit."""
    B, H, S, D = 2, 3, 256, 64
    q, k, v = (_rand((B, H, S, D), 20 + i) for i in range(3))
    o1, l1 = P.pallas_flash_attention(q, k, v, causal=True, return_lse=True,
                                      interpret=True, block_q=128,
                                      block_k=256)   # n_k=1: single-pass
    o2, l2 = P.pallas_flash_attention(q, k, v, causal=True, return_lse=True,
                                      interpret=True, block_q=128,
                                      block_k=128)   # n_k=2: streaming
    assert float(jnp.max(jnp.abs(o1 - o2))) < 2e-6
    assert float(jnp.max(jnp.abs(l1 - l2))) < 2e-5


def test_single_block_bwd_matches_fused_and_split():
    """n_q == n_k == 1 routes the no-scratch single-block dqkv kernel;
    it must match both the q-streaming fused kernel and the split
    kernels."""
    B, H, S, D = 2, 2, 128, 64
    q, k, v, do = (_rand((B, H, S, D), 30 + i) for i in range(4))
    kv_lens = jnp.asarray([128, 77], jnp.int32)
    kw = dict(causal=False, kv_lens=kv_lens, interpret=True)
    o, l = P.pallas_flash_attention(q, k, v, return_lse=True,
                                    block_q=128, block_k=128, **kw)
    g_single = P.pallas_flash_attention_bwd(q, k, v, o, l, do,
                                            block_q=128, block_k=128, **kw)
    g_fused = P.pallas_flash_attention_bwd(q, k, v, o, l, do,
                                           block_q=64, block_k=128, **kw)
    o2, l2 = P.pallas_flash_attention(q, k, v, return_lse=True,
                                      block_q=64, block_k=64, **kw)
    g_split = P.pallas_flash_attention_bwd(q, k, v, o2, l2, do,
                                           block_q=64, block_k=64, **kw)
    for a, b in zip(g_single, g_fused):
        assert float(jnp.max(jnp.abs(a - b))) < 2e-5
    for a, b in zip(g_single, g_split):
        assert float(jnp.max(jnp.abs(a - b))) < 2e-5


def test_full_block_predicate_with_kv_lens_matches_masked():
    """Satellite fix: blocks wholly inside min(kv_lens) take the
    mask-free fast path — results must be identical to the masked path
    (exercised with lens that leave interior blocks fully visible)."""
    B, H, S, D = 2, 2, 384, 32
    q, k, v = (_rand((B, H, S, D), 40 + i) for i in range(3))
    kv_lens = jnp.asarray([384, 300], jnp.int32)
    out = P.pallas_flash_attention(q, k, v, interpret=True, block_q=128,
                                   block_k=128, kv_lens=kv_lens)
    ref = _dense_masked(q, k, v, kv_lens=kv_lens)
    assert float(jnp.max(jnp.abs(out - ref))) < 2e-5
    # and causal + lens combined (both predicates must hold at once)
    out_c = P.pallas_flash_attention(q, k, v, causal=True, interpret=True,
                                     block_q=128, block_k=128,
                                     kv_lens=kv_lens)
    ref_c = _dense_masked(q, k, v, kv_lens=kv_lens, causal=True)
    assert float(jnp.max(jnp.abs(out_c - ref_c))) < 2e-5
