"""Expert parallelism: Switch-style MoE FFN over the ``ep`` mesh axis
(all_to_all token exchange) vs the single-device routing oracle, on the
virtual 8-device CPU mesh."""
import numpy as onp
import jax
import jax.numpy as jnp
import pytest
from jax.sharding import Mesh

from mxnet_tpu import parallel

pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 4, reason="needs >=4 devices (virtual CPU mesh)")


def _setup(ndev, E, N, H, F, seed=1):
    mesh = Mesh(onp.array(jax.devices()[:ndev]), ("ep",))
    params = parallel.moe_ffn_init(0, hidden=H, ffn=F, n_experts=E)
    x = jnp.asarray(onp.random.RandomState(seed).randn(N, H)
                    .astype("float32"))
    return mesh, params, x


@pytest.mark.parametrize("ndev,E,N,H,F", [
    (4, 8, 48, 8, 16),        # 2 experts per device
    (8, 8, 64, 16, 32),       # 1 expert per device
    (8, 16, 128, 32, 64),     # 2 experts per device, bigger
])
def test_moe_matches_oracle(ndev, E, N, H, F):
    if len(jax.devices()) < ndev:
        pytest.skip("not enough devices")
    mesh, params, x = _setup(ndev, E, N, H, F)
    got = parallel.moe_ffn_apply(params, x, mesh)
    want = parallel.moe_ffn_ref(params, x, n_shards=ndev)
    onp.testing.assert_allclose(onp.asarray(got), onp.asarray(want),
                                rtol=1e-5, atol=1e-6)


def test_moe_grads_match_oracle():
    ndev = min(8, len(jax.devices()))
    mesh, params, x = _setup(ndev, 8, 8 * ndev, 16, 32)

    g1 = jax.grad(lambda p: jnp.sum(
        parallel.moe_ffn_apply(p, x, mesh) ** 2))(params)
    g2 = jax.grad(lambda p: jnp.sum(
        parallel.moe_ffn_ref(p, x, ndev) ** 2))(params)
    for k in g1:
        onp.testing.assert_allclose(onp.asarray(g1[k]),
                                    onp.asarray(g2[k]),
                                    rtol=1e-4, atol=2e-4, err_msg=k)


def test_moe_capacity_drops_tokens():
    """Overflowing an expert's capacity zeroes the overflow tokens'
    output (they ride the residual), never crashes or reroutes."""
    ndev = 4
    if len(jax.devices()) < ndev:
        pytest.skip("not enough devices")
    mesh, params, x = _setup(ndev, 4, 32, 8, 16, seed=3)
    # capacity_factor so low every expert can hold only 1 token per shard
    got = parallel.moe_ffn_apply(params, x, mesh, capacity_factor=0.5)
    want = parallel.moe_ffn_ref(params, x, ndev, capacity_factor=0.5)
    onp.testing.assert_allclose(onp.asarray(got), onp.asarray(want),
                                rtol=1e-5, atol=1e-6)
    # some token rows must actually be zero (dropped)
    assert (onp.abs(onp.asarray(got)).sum(axis=1) == 0).any()


def test_moe_validation_errors():
    mesh, params, x = _setup(4, 8, 48, 8, 16)
    with pytest.raises(ValueError):
        parallel.moe_ffn_apply({**params,
                                "w1": params["w1"][:6],
                                "w2": params["w2"][:6]}, x, mesh)
    with pytest.raises(ValueError):
        parallel.moe_ffn_apply(params, x[:30], mesh)
