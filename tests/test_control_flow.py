"""Control-flow ops: foreach / while_loop / cond (+ gradients).

Reference behavior: tests/python/unittest/test_contrib_control_flow.py.
"""
import numpy as onp

import mxnet_tpu as mx
from mxnet_tpu.ndarray import contrib


def test_foreach_cumsum():
    data = mx.nd.array(onp.arange(12, dtype="float32").reshape(4, 3))
    init = mx.nd.zeros((3,))

    def body(x, state):
        new = state + x
        return new, new

    outs, final = contrib.foreach(body, data, init)
    expect = onp.cumsum(onp.arange(12).reshape(4, 3), axis=0)
    onp.testing.assert_allclose(outs.asnumpy(), expect, rtol=1e-6)
    onp.testing.assert_allclose(final.asnumpy(), expect[-1], rtol=1e-6)


def test_foreach_multi_state():
    data = mx.nd.array(onp.ones((3, 2), dtype="float32"))
    inits = [mx.nd.zeros((2,)), mx.nd.ones((2,))]

    def body(x, states):
        s0, s1 = states
        return x + s0, [s0 + x, s1 * 2]

    outs, finals = contrib.foreach(body, data, inits)
    assert outs.shape == (3, 2)
    onp.testing.assert_allclose(finals[0].asnumpy(), [3, 3])
    onp.testing.assert_allclose(finals[1].asnumpy(), [8, 8])


def test_foreach_grad():
    data = mx.nd.array(onp.arange(6, dtype="float32").reshape(3, 2))
    w = mx.nd.array(onp.array([2.0, 3.0], dtype="float32"))
    w.attach_grad()
    init = mx.nd.zeros((2,))

    def body(x, state):
        new = state + x * w
        return new, new

    with mx.autograd.record():
        outs, final = contrib.foreach(body, data, init)
        loss = final.sum()
    loss.backward()
    # d(sum_i sum_t x_t*w)/dw = sum_t x_t  (column sums)
    onp.testing.assert_allclose(w.grad.asnumpy(), [0 + 2 + 4, 1 + 3 + 5],
                                rtol=1e-6)


def test_while_loop():
    def cond_fn(i, s):
        return i < 5

    def func(i, s):
        return i * 2, [i + 1, s + i]

    outs, (i_f, s_f) = contrib.while_loop(
        cond_fn, func, [mx.nd.array([0.0]), mx.nd.array([0.0])],
        max_iterations=10)
    assert float(i_f.asnumpy()) == 5.0
    assert float(s_f.asnumpy()) == 10.0  # 0+1+2+3+4
    onp.testing.assert_allclose(outs.asnumpy().ravel(),
                                [0, 2, 4, 6, 8])


def test_while_loop_grad():
    x = mx.nd.array([2.0])
    x.attach_grad()

    def cond_fn(i, acc):
        return i < 3

    def func(i, acc):
        return acc, [i + 1, acc * x]

    with mx.autograd.record():
        _, (i_f, acc_f) = contrib.while_loop(
            cond_fn, func, [mx.nd.array([0.0]), mx.nd.ones((1,))],
            max_iterations=8)
        loss = acc_f.sum()  # x**3
    loss.backward()
    onp.testing.assert_allclose(x.grad.asnumpy(), [3 * 2.0 ** 2], rtol=1e-5)


def test_cond():
    x = mx.nd.array([3.0])
    y = mx.nd.array([5.0])
    out = contrib.cond((x < y).sum(), lambda: x * 2, lambda: y * 2)
    onp.testing.assert_allclose(out.asnumpy(), [6.0])
    out = contrib.cond((x > y).sum(), lambda: x * 2, lambda: y * 2)
    onp.testing.assert_allclose(out.asnumpy(), [10.0])


def test_cond_grad():
    x = mx.nd.array([3.0])
    x.attach_grad()
    with mx.autograd.record():
        out = contrib.cond((x < 10).sum(), lambda: x * x, lambda: x)
        out.backward()
    onp.testing.assert_allclose(x.grad.asnumpy(), [6.0])
