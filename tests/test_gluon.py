"""Gluon core tests: Parameter/Block/HybridBlock + layers.

Modelled on the reference's ``tests/python/unittest/test_gluon.py`` strategy:
construct, initialize, forward eager + hybridized, compare; parameter
management semantics; deferred shape inference; save/load round-trip.
"""
import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon
from mxnet_tpu.gluon import nn


def test_parameter():
    p = gluon.Parameter("weight", shape=(10, 10))
    p.initialize(init="xavier")
    assert p.data().shape == (10, 10)
    assert p.grad().shape == (10, 10)
    assert p.name == "weight"
    assert (p.grad().asnumpy() == 0).all()


def test_parameter_invalid_access():
    p = gluon.Parameter("weight", shape=(10, 10))
    with pytest.raises(RuntimeError):
        p.data()


def test_paramdict():
    params = gluon.ParameterDict("net_")
    w = params.get("weight", shape=(10, 10))
    assert w.name == "net_weight"
    assert list(params.keys()) == ["net_weight"]
    params.initialize(ctx=mx.cpu())
    params.zero_grad()


def test_constant():
    const_val = onp.ones((2, 3), dtype=onp.float32) * 7

    class Net(gluon.HybridBlock):
        def __init__(self, **kwargs):
            super().__init__(**kwargs)
            self.const = self.params.get_constant("const", const_val)

        def hybrid_forward(self, F, x, const):
            return x + const

    net = Net()
    net.initialize()
    x = mx.nd.ones((2, 3))
    out = net(x)
    assert (out.asnumpy() == 8).all()
    # constants take no gradient; grads flow to the input only
    x.attach_grad()
    with autograd.record():
        out = net(x)
    out.backward()
    assert (x.grad.asnumpy() == 1).all()


def test_dense_deferred_init():
    net = nn.Dense(8)
    net.initialize()
    x = mx.nd.ones((4, 17))
    y = net(x)
    assert y.shape == (4, 8)
    assert net.weight.shape == (8, 17)
    assert net.bias.shape == (8,)


def test_dense_in_units():
    net = nn.Dense(5, in_units=3, activation="relu")
    net.initialize()
    y = net(mx.nd.array(onp.random.randn(2, 3)))
    assert y.shape == (2, 5)
    assert (y.asnumpy() >= 0).all()


def test_sequential_and_naming():
    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Dense(16, activation="relu"), nn.Dense(4))
    net.initialize()
    y = net(mx.nd.ones((2, 10)))
    assert y.shape == (2, 4)
    names = list(net.collect_params().keys())
    assert len(names) == 4
    prefix = net.prefix
    assert all(n.startswith(prefix) for n in names)
    assert len(net) == 2
    assert isinstance(net[0], nn.Dense)


def test_hybridize_matches_eager():
    onp.random.seed(0)
    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Dense(32, activation="tanh"), nn.Dense(3))
    net.initialize(mx.init.Xavier())
    x = mx.nd.array(onp.random.randn(5, 7).astype(onp.float32))
    y_eager = net(x).asnumpy()
    net.hybridize()
    y_jit = net(x).asnumpy()
    onp.testing.assert_allclose(y_eager, y_jit, rtol=1e-5, atol=1e-6)
    # second call hits the compiled cache
    y_jit2 = net(x).asnumpy()
    onp.testing.assert_allclose(y_eager, y_jit2, rtol=1e-5, atol=1e-6)


def test_hybridize_grad_matches_eager():
    onp.random.seed(1)
    def build():
        net = nn.HybridSequential(prefix="net_")
        with net.name_scope():
            net.add(nn.Dense(16, activation="relu"), nn.Dense(1))
        return net

    netA = build()
    netA.initialize(mx.init.Constant(0.05))
    netB = build()
    netB.initialize(mx.init.Constant(0.05))
    netB.hybridize()

    x = mx.nd.array(onp.random.randn(4, 6).astype(onp.float32))
    grads = []
    for net in (netA, netB):
        with autograd.record():
            y = net(x)
            loss = (y * y).sum()
        loss.backward()
        grads.append({k: p.grad().asnumpy()
                      for k, p in net.collect_params().items()})
    for k in grads[0]:
        onp.testing.assert_allclose(grads[0][k], grads[1][k],
                                    rtol=1e-4, atol=1e-5)


def test_batchnorm_running_stats_update():
    bn = nn.BatchNorm(in_channels=4)
    bn.initialize()
    x = mx.nd.array(onp.random.randn(8, 4, 3, 3).astype(onp.float32) * 3 + 2)
    with autograd.record():
        bn(x)
    rm = bn.running_mean.data().asnumpy()
    rv = bn.running_var.data().asnumpy()
    assert not (rm == 0).all(), "running mean should move after a training fwd"
    assert not (rv == 1).all()
    # eval mode uses running stats; must not change them
    y = bn(x)
    onp.testing.assert_allclose(bn.running_mean.data().asnumpy(), rm)
    assert y.shape == x.shape


def test_batchnorm_running_stats_update_hybridized():
    bn = nn.BatchNorm(in_channels=4)
    bn.initialize()
    bn.hybridize()
    x = mx.nd.array(onp.random.randn(8, 4, 3, 3).astype(onp.float32) * 3 + 2)
    with autograd.record():
        bn(x)  # warm-up (eager — completes deferred)
    with autograd.record():
        bn(x)  # compiled path
    rm = bn.running_mean.data().asnumpy()
    assert not (rm == 0).all(), "hybridized BN must still update aux state"


def test_conv2d():
    net = nn.Conv2D(8, kernel_size=3, padding=1, activation="relu")
    net.initialize()
    x = mx.nd.ones((2, 3, 8, 8))
    y = net(x)
    assert y.shape == (2, 8, 8, 8)
    assert net.weight.shape == (8, 3, 3, 3)


def test_conv_transpose():
    net = nn.Conv2DTranspose(4, kernel_size=2, strides=2)
    net.initialize()
    x = mx.nd.ones((1, 3, 5, 5))
    y = net(x)
    assert y.shape == (1, 4, 10, 10)


def test_pooling_layers():
    x = mx.nd.array(onp.random.randn(2, 3, 8, 8).astype(onp.float32))
    assert nn.MaxPool2D(2)(x).shape == (2, 3, 4, 4)
    assert nn.AvgPool2D(2)(x).shape == (2, 3, 4, 4)
    assert nn.GlobalAvgPool2D()(x).shape == (2, 3, 1, 1)
    assert nn.GlobalMaxPool2D()(x).shape == (2, 3, 1, 1)


def test_dropout_train_vs_eval():
    do = nn.Dropout(0.5)
    x = mx.nd.ones((100, 100))
    y_eval = do(x)
    onp.testing.assert_allclose(y_eval.asnumpy(), x.asnumpy())
    with autograd.record():
        y_train = do(x)
    arr = y_train.asnumpy()
    assert (arr == 0).any(), "dropout should zero some entries in train mode"
    assert abs(arr.mean() - 1.0) < 0.1, "inverted dropout keeps the mean"


def test_embedding_flatten():
    emb = nn.Embedding(10, 4)
    emb.initialize()
    idx = mx.nd.array([[1, 2], [3, 4]])
    out = emb(idx)
    assert out.shape == (2, 2, 4)
    fl = nn.Flatten()
    assert fl(out).shape == (2, 8)


def test_layernorm_groupnorm():
    x = mx.nd.array(onp.random.randn(2, 6, 4).astype(onp.float32))
    ln = nn.LayerNorm()
    ln.initialize()
    y = ln(x).asnumpy()
    onp.testing.assert_allclose(y.mean(-1), 0, atol=1e-5)
    gn = nn.GroupNorm(num_groups=3)
    gn.initialize()
    assert gn(x).shape == x.shape


def test_activations_layers():
    x = mx.nd.array(onp.random.randn(3, 4).astype(onp.float32))
    for layer in [nn.LeakyReLU(0.1), nn.ELU(), nn.SELU(), nn.GELU(), nn.Swish()]:
        assert layer(x).shape == x.shape
    pr = nn.PReLU()
    pr.initialize()
    assert pr(x).shape == x.shape


def test_save_load_parameters(tmp_path):
    net = nn.HybridSequential(prefix="model_")
    with net.name_scope():
        net.add(nn.Dense(8, in_units=4), nn.Dense(2, in_units=8))
    net.initialize(mx.init.Xavier())
    x = mx.nd.ones((1, 4))
    y0 = net(x).asnumpy()
    f = str(tmp_path / "model.params")
    net.save_parameters(f)

    net2 = nn.HybridSequential(prefix="model2_")
    with net2.name_scope():
        net2.add(nn.Dense(8, in_units=4), nn.Dense(2, in_units=8))
    net2.load_parameters(f)
    y1 = net2(x).asnumpy()
    onp.testing.assert_allclose(y0, y1, rtol=1e-6)


def test_custom_hybrid_block():
    class MLP(gluon.HybridBlock):
        def __init__(self, **kwargs):
            super().__init__(**kwargs)
            with self.name_scope():
                self.fc1 = nn.Dense(16)
                self.fc2 = nn.Dense(2)

        def hybrid_forward(self, F, x):
            return self.fc2(F.relu(self.fc1(x)))

    net = MLP()
    net.initialize()
    y = net(mx.nd.ones((3, 5)))
    assert y.shape == (3, 2)
    net.hybridize()
    y2 = net(mx.nd.ones((3, 5)))
    onp.testing.assert_allclose(y.asnumpy(), y2.asnumpy(), rtol=1e-5)


def test_lambda_blocks():
    lam = nn.Lambda("relu")
    x = mx.nd.array([[-1.0, 2.0]])
    assert (lam(x).asnumpy() == [[0.0, 2.0]]).all()
    hl = nn.HybridLambda(lambda F, a: a * 2)
    assert (hl(x).asnumpy() == [[-2.0, 4.0]]).all()


def test_collect_params_select():
    net = nn.HybridSequential(prefix="sel_")
    with net.name_scope():
        net.add(nn.Dense(4, in_units=4), nn.Dense(4, in_units=4))
    weights = net.collect_params(".*weight")
    assert len(weights) == 2
    assert all(k.endswith("weight") for k in weights.keys())


def test_grad_req_null():
    net = nn.Dense(3, in_units=3)
    net.initialize()
    net.collect_params().setattr("grad_req", "null")
    x = mx.nd.ones((2, 3))
    with autograd.record():
        y = net(x)
    # no grads attached → backward on params not possible, but forward fine
    assert y.shape == (2, 3)
