"""Fault-injection coverage auditor (tools.lint.chaos_coverage).

Tier-1 half: the REAL package must audit clean — every statically
enumerated fault point (os.replace commit windows, thread entries, KV
ops) has a chaos injection or a load-bearing waiver, every registered
mode is consulted by a seam and installed by a test.  Synthetic-tree
halves: each closure violation class is detected, and the waiver
machinery cannot rot.
"""
import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO) if REPO not in sys.path else None

from tools.lint import chaos_coverage  # noqa: E402


# -- tier-1: the real package ------------------------------------------------

def test_package_chaos_coverage_ok():
    res = chaos_coverage.audit()
    assert res.ok, "\n".join(res.problems)
    # the registry covers the full failure-model surface
    for mode in ("kill_worker", "drop_heartbeat", "kv_garble",
                 "kv_stall", "checkpoint_write_crash",
                 "incident_write_crash", "artifact_write_crash",
                 "request_burst", "dispatch_stall", "executable_poison",
                 "deadline_storm"):
        assert mode in res.registry, mode
        assert res.consultations.get(mode), "mode %s never consulted" % mode
        assert res.tests.get(mode), "mode %s has no installing test" % mode
    assert not [p for p in res.points if p.status == "uncovered"]
    # the phase-5 fsutil commit window is enumerated and injected
    assert any(p.path.endswith("fsutil.py")
               and p.kind == "commit-window"
               and p.status == "covered"
               and "artifact_write_crash" in p.modes
               for p in res.points), [p.to_dict() for p in res.points]
    # checkpoint commit window rides its own mode
    assert any(p.path.endswith("checkpoint.py")
               and p.kind == "commit-window"
               and "checkpoint_write_crash" in p.modes
               for p in res.points)


def test_audit_chaos_cli_json():
    env = dict(os.environ, PYTHONPATH=REPO + os.pathsep
               + os.environ.get("PYTHONPATH", ""))
    res = subprocess.run(
        [sys.executable, "-m", "tools.lint", "--audit-chaos",
         "--format", "json"],
        cwd=REPO, env=env, capture_output=True, text=True)
    assert res.returncode == 0, res.stdout[-2000:] + res.stderr[-2000:]
    data = json.loads(res.stdout)
    assert data["ok"] is True
    assert data["modes"]["artifact_write_crash"]["tests"]
    kinds = {p["kind"] for p in data["fault_points"]}
    assert kinds >= {"commit-window", "thread-entry", "kv-op"}, kinds


# -- synthetic trees: each violation class -----------------------------------

_CHAOS_OK = ("MODES = {'write_crash': 'writer.commit window'}\n"
             "\n"
             "\n"
             "def should_fire(mode, **kw):\n"
             "    return False\n")

_WRITER_OK = ("import os\n"
              "\n"
              "from .parallel import chaos\n"
              "\n"
              "\n"
              "def commit(tmp, path):\n"
              "    if chaos.should_fire('write_crash'):\n"
              "        raise RuntimeError('injected')\n"
              "    os.replace(tmp, path)\n")

_TEST_OK = "chaos.install(\"write_crash\", times=1)\n"


def _tree(tmp_path, chaos_src=_CHAOS_OK, writer_src=_WRITER_OK,
          test_src=_TEST_OK, extra=None):
    pkg = tmp_path / "pkg"
    (pkg / "parallel").mkdir(parents=True)
    (pkg / "__init__.py").write_text("")
    (pkg / "parallel" / "__init__.py").write_text("")
    (pkg / "parallel" / "chaos.py").write_text(chaos_src)
    (pkg / "writer.py").write_text(writer_src)
    for relname, src in (extra or {}).items():
        dest = pkg / relname
        dest.parent.mkdir(parents=True, exist_ok=True)
        dest.write_text(src)
    tdir = tmp_path / "tests"
    tdir.mkdir()
    (tdir / "test_seeded.py").write_text(test_src)
    return chaos_coverage.audit(paths=[str(pkg)], root=str(tmp_path),
                                tests_dir=str(tdir))


def test_clean_synthetic_tree_audits_ok(tmp_path):
    res = _tree(tmp_path)
    assert res.ok, "\n".join(res.problems)
    assert [p.kind for p in res.points] == ["commit-window"]
    assert res.points[0].status == "covered"
    assert res.points[0].modes == ("write_crash",)


def test_uncovered_commit_window_fails(tmp_path):
    # the os.replace window lost its consultation; the mode is still
    # consulted elsewhere so ONLY the fault-point problem fires
    bugged = ("import os\n"
              "\n"
              "from .parallel import chaos\n"
              "\n"
              "\n"
              "def commit(tmp, path):\n"
              "    os.replace(tmp, path)\n"
              "\n"
              "\n"
              "def elsewhere():\n"
              "    return chaos.should_fire('write_crash')\n")
    res = _tree(tmp_path, writer_src=bugged)
    assert not res.ok
    assert any("commit-window" in p and "no chaos injection" in p
               for p in res.problems), res.problems


def test_uncovered_thread_entry_fails(tmp_path):
    spawner = ("import threading\n"
               "\n"
               "\n"
               "def _loop():\n"
               "    return None\n"
               "\n"
               "\n"
               "def start():\n"
               "    threading.Thread(target=_loop, daemon=True).start()\n")
    res = _tree(tmp_path, extra={"spawner.py": spawner})
    assert not res.ok
    assert any("thread-entry" in p and "_loop" in p
               for p in res.problems), res.problems


def test_mode_without_installing_test_fails(tmp_path):
    res = _tree(tmp_path, test_src="def test_nothing():\n    pass\n")
    assert not res.ok
    assert any("no installing test" in p for p in res.problems), \
        res.problems


def test_consultation_missing_from_registry_fails(tmp_path):
    ghost = _WRITER_OK + ("\n"
                          "\n"
                          "def spooky():\n"
                          "    return chaos.should_fire('ghost_mode')\n")
    res = _tree(tmp_path, writer_src=ghost)
    assert not res.ok
    assert any("ghost_mode" in p and "missing from the MODES registry"
               in p for p in res.problems), res.problems


def test_registered_mode_never_consulted_fails(tmp_path):
    chaos_src = _CHAOS_OK.replace(
        "MODES = {'write_crash': 'writer.commit window'}",
        "MODES = {'write_crash': 'writer.commit window',\n"
        "         'dead_mode': 'nothing consults this'}")
    test_src = _TEST_OK + "chaos.install(\"dead_mode\")\n"
    res = _tree(tmp_path, chaos_src=chaos_src, test_src=test_src)
    assert not res.ok
    assert any("dead_mode" in p and "no seam consults it" in p
               for p in res.problems), res.problems


def test_missing_registry_fails(tmp_path):
    res = _tree(tmp_path, chaos_src="def should_fire(m, **kw):\n"
                                    "    return False\n")
    assert not res.ok
    assert any("no MODES registry" in p for p in res.problems)


def test_stale_waiver_detected(tmp_path):
    # a file matching a waiver suffix exists but contains no matching
    # fault point: the waiver is stale and must fail the audit
    res = _tree(tmp_path, extra={
        "native/__init__.py": "def _build():\n    return None\n"})
    assert not res.ok
    assert any("stale waiver" in p and "native/__init__.py" in p
               for p in res.problems), res.problems


def test_waiver_covers_matching_site(tmp_path):
    # the same file WITH the waived fault point: waived, audit ok
    native = ("import os\n"
              "\n"
              "\n"
              "def _build(tmp, path):\n"
              "    os.replace(tmp, path)\n")
    res = _tree(tmp_path, extra={"native/__init__.py": native})
    assert res.ok, "\n".join(res.problems)
    waived = [p for p in res.points if p.status == "waived"]
    assert len(waived) == 1 and waived[0].context == "_build"
    assert "fall" in waived[0].note or waived[0].note
