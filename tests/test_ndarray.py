"""NDArray core tests (reference: tests/python/unittest/test_ndarray.py)."""
import os
import tempfile

import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu.test_utils import assert_almost_equal, same


def test_creation():
    a = mx.nd.zeros((2, 3))
    assert a.shape == (2, 3) and a.dtype == onp.float32
    assert same(a, onp.zeros((2, 3)))
    b = mx.nd.ones((4,), dtype=onp.int32)
    assert b.dtype == onp.int32
    c = mx.nd.full((2, 2), 7.0)
    assert same(c, onp.full((2, 2), 7.0, onp.float32))
    d = mx.nd.array([[1, 2], [3, 4]])
    assert d.shape == (2, 2)
    e = mx.nd.arange(0, 10, 2)
    assert same(e, onp.arange(0, 10, 2, dtype=onp.float32))
    f = mx.nd.eye(3)
    assert same(f, onp.eye(3, dtype=onp.float32))
    g = mx.nd.linspace(0, 1, 5)
    assert_almost_equal(g, onp.linspace(0, 1, 5, dtype=onp.float32))


def test_arithmetic():
    a = mx.nd.array([[1.0, 2.0], [3.0, 4.0]])
    b = mx.nd.array([[5.0, 6.0], [7.0, 8.0]])
    assert same(a + b, onp.array([[6, 8], [10, 12]], onp.float32))
    assert same(a - b, -(b - a))
    assert same(a * 2, onp.array([[2, 4], [6, 8]], onp.float32))
    assert same(2 * a, a * 2)
    assert_almost_equal(1.0 / a, onp.array([[1, 0.5], [1 / 3, 0.25]], onp.float32))
    assert same(a ** 2, a * a)
    assert same(a // 2, onp.array([[0, 1], [1, 2]], onp.float32))
    assert same(-a, 0 - a)
    assert same(abs(-a), a)
    c = a.copy()
    c += b
    assert same(c, a + b)
    c = a.copy()
    c *= 3
    assert same(c, a * 3)


def test_comparison():
    a = mx.nd.array([1.0, 2.0, 3.0])
    b = mx.nd.array([3.0, 2.0, 1.0])
    assert same(a == b, onp.array([0, 1, 0], onp.float32))
    assert same(a > b, onp.array([0, 0, 1], onp.float32))
    assert same(a <= b, onp.array([1, 1, 0], onp.float32))


def test_reshape_special_codes():
    a = mx.nd.zeros((2, 3, 4))
    assert a.reshape((4, 6)).shape == (4, 6)
    assert a.reshape((-1,)).shape == (24,)
    assert a.reshape((0, -1)).shape == (2, 12)
    assert a.reshape((-2,)).shape == (2, 3, 4)
    assert a.reshape((0, -2)).shape == (2, 3, 4)
    assert a.reshape((-3, 4)).shape == (6, 4)
    assert a.reshape((0, -4, 1, 3, 0)).shape == (2, 1, 3, 4)
    assert a.reshape((-4, 1, 2, -2)).shape == (1, 2, 3, 4)
    b = mx.nd.zeros((8, 3, 3, 3))
    # reverse=True: infer from the right
    assert b.reshape((-4, -1, 2, 0, 0, 0), reverse=False).shape == (4, 2, 3, 3, 3)


def test_indexing():
    a = mx.nd.array(onp.arange(24).reshape(2, 3, 4))
    assert same(a[0], onp.arange(12).reshape(3, 4))
    assert same(a[1, 2], onp.array([20, 21, 22, 23]))
    assert same(a[:, 1], onp.arange(24).reshape(2, 3, 4)[:, 1])
    assert same(a[0, 1:3], onp.arange(24).reshape(2, 3, 4)[0, 1:3])
    idx = mx.nd.array([1, 0], dtype=onp.int32)
    assert same(a[idx], onp.arange(24).reshape(2, 3, 4)[[1, 0]])
    a[0, 0, 0] = 99
    assert a[0, 0, 0].asscalar() == 99
    a[1] = 0
    assert same(a[1], onp.zeros((3, 4)))
    b = mx.nd.zeros((3,))
    b[:] = 5
    assert same(b, onp.full((3,), 5, onp.float32))


def test_astype_copy_context():
    a = mx.nd.array([1.5, 2.5])
    b = a.astype(onp.int32)
    assert b.dtype == onp.int32 and same(b, onp.array([1, 2], onp.int32))
    c = a.copy()
    c[0] = 9
    assert a[0].asscalar() == 1.5  # copy is deep
    d = a.as_in_context(mx.cpu(0))
    assert d.context == mx.cpu(0)
    e = mx.nd.zeros((2,))
    a.copyto(e)
    assert same(e, a)


def test_scalar_conversions():
    a = mx.nd.array([3.5])
    assert a.asscalar() == 3.5
    assert float(a) == 3.5
    assert int(a) == 3
    assert bool(a)
    assert len(mx.nd.zeros((5, 2))) == 5
    with pytest.raises(ValueError):
        mx.nd.zeros((2, 2)).asscalar()


def test_concat_stack_split():
    a = mx.nd.ones((2, 3))
    b = mx.nd.zeros((2, 3))
    c = mx.nd.concat(a, b, dim=0)
    assert c.shape == (4, 3)
    d = mx.nd.concat(a, b, dim=1)
    assert d.shape == (2, 6)
    e = mx.nd.stack(a, b, axis=0)
    assert e.shape == (2, 2, 3)
    parts = mx.nd.split(c, 2, axis=0)
    assert len(parts) == 2 and same(parts[0], onp.ones((2, 3)))
    s = mx.nd.add_n(a, a, a)
    assert same(s, onp.full((2, 3), 3, onp.float32))


def test_save_load(tmp_path):
    fname = str(tmp_path / "arrs")
    a = mx.nd.array([1.0, 2.0])
    b = mx.nd.ones((2, 2))
    mx.nd.save(fname, [a, b])
    loaded = mx.nd.load(fname)
    assert isinstance(loaded, list) and same(loaded[0], a) and same(loaded[1], b)
    mx.nd.save(fname, {"x": a, "y": b})
    d = mx.nd.load(fname)
    assert isinstance(d, dict) and same(d["x"], a) and same(d["y"], b)


def test_mutation_does_not_corrupt_tape():
    x = mx.nd.array([1.0, 2.0])
    x.attach_grad()
    with mx.autograd.record():
        y = (x * x).sum()
    x[:] = 100.0  # mutate after record — tape captured values
    y.backward()
    assert_almost_equal(x.grad, onp.array([2.0, 4.0]))


def test_waitall_and_context():
    a = mx.nd.ones((4,))
    a.wait_to_read()
    mx.nd.waitall()
    assert mx.cpu(0) == mx.cpu(0)
    assert mx.cpu(0) != mx.cpu(1)
    with mx.Context("cpu", 0):
        b = mx.nd.ones((2,))
    assert b.context.device_type == "cpu"
