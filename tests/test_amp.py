"""AMP tests (reference tests/python/gpu/test_contrib_amp.py)."""
import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu.contrib import amp


@pytest.fixture
def amp_off():
    yield
    amp.disable()


def test_policy_casts_target_ops(amp_off):
    amp.init(target_dtype="bfloat16")
    a = mx.nd.ones((4, 8))
    w = mx.nd.ones((3, 8))
    out = mx.nd.FullyConnected(a, w, no_bias=True, num_hidden=3)
    assert str(out.dtype) == "bfloat16"


def test_policy_keeps_fp32_ops(amp_off):
    amp.init(target_dtype="bfloat16")
    x = mx.nd.ones((4, 8)).astype("bfloat16")
    out = mx.nd.softmax(x)
    assert str(out.dtype) == "float32"


def test_widest_type_promotion(amp_off):
    amp.init(target_dtype="bfloat16")
    a = mx.nd.ones((4,)).astype("bfloat16")
    b = mx.nd.ones((4,))  # float32
    out = mx.nd.broadcast_add(a, b)
    assert str(out.dtype) == "float32"


def test_amp_gluon_training_descends(amp_off):
    from mxnet_tpu import gluon, autograd
    amp.init(target_dtype="bfloat16")
    net = gluon.nn.Sequential()
    net.add(gluon.nn.Dense(16, activation="relu"))
    net.add(gluon.nn.Dense(1))
    net.initialize(mx.init.Xavier())
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.1})
    amp.init_trainer(trainer)
    loss_fn = gluon.loss.L2Loss()
    rs = onp.random.RandomState(0)
    X = rs.uniform(-1, 1, (64, 4)).astype(onp.float32)
    Y = (X.sum(axis=1, keepdims=True) * 0.5).astype(onp.float32)
    losses = []
    for _ in range(30):
        xb, yb = mx.nd.array(X), mx.nd.array(Y)
        with autograd.record():
            out = net(xb)
            loss = loss_fn(out, yb)
        with amp.scale_loss(loss, trainer) as scaled:
            scaled.backward()
        trainer.step(64)
        losses.append(float(loss.mean().asscalar()))
    assert losses[-1] < losses[0] * 0.5, losses


def test_loss_scaler_dynamics():
    s = amp.LossScaler(init_scale=1024.0, scale_factor=2.0, scale_window=4)
    inf_grad = mx.nd.array(onp.array([onp.inf, 1.0], onp.float32))
    ok_grad = mx.nd.array(onp.array([1.0, 2.0], onp.float32))
    assert s.has_overflow([inf_grad])
    s.update_scale(True)
    assert s.loss_scale == 512.0
    for _ in range(4):
        assert not s.has_overflow([ok_grad])
        s.update_scale(False)
    assert s.loss_scale == 1024.0


def test_fp16_trainer_skips_update_on_overflow(amp_off):
    from mxnet_tpu import gluon, autograd
    amp.init(target_dtype="float16")
    net = gluon.nn.Dense(1)
    net.initialize(mx.init.Constant(1.0))
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.1})
    amp.init_trainer(trainer)
    x = mx.nd.ones((2, 3))
    with autograd.record():
        loss = net(x).sum()
    loss.backward()
    # poison the gradient with inf: update must be skipped, scale halved
    w = [p for p in trainer._params if p.grad_req != "null"][0]
    g = w.grad()
    g[:] = onp.inf
    before = w.data().asnumpy().copy()
    scale0 = trainer._amp_loss_scaler.loss_scale
    trainer.step(1)
    after = w.data().asnumpy()
    onp.testing.assert_allclose(before, after)
    assert trainer._amp_loss_scaler.loss_scale == scale0 / 2


def test_convert_symbol_inserts_casts(amp_off):
    from mxnet_tpu import sym
    net = sym.FullyConnected(sym.var("data"), num_hidden=4, name="fc")
    net = sym.SoftmaxOutput(net, name="softmax")
    conv = amp.convert_symbol(net, target_dtype="bfloat16")
    ops = [n.op for n in conv._topo() if n.op is not None]
    assert "amp_cast" in ops
    # executor runs and FC math is bf16 while output stays fp32 (softmax)
    exe = conv.simple_bind(ctx=mx.cpu(), data=(2, 3))
    exe.arg_dict["fc_weight"][:] = onp.ones((4, 3), onp.float32)
    exe.forward(is_train=False)
    assert str(exe.outputs[0].dtype) == "float32"
    onp.testing.assert_allclose(exe.outputs[0].asnumpy().sum(axis=1),
                                onp.ones(2), rtol=1e-3)


def test_convert_model_casts_params(amp_off):
    from mxnet_tpu import sym
    net = sym.FullyConnected(sym.var("data"), num_hidden=4, name="fc")
    arg = {"fc_weight": mx.nd.ones((4, 3)), "fc_bias": mx.nd.zeros((4,))}
    s2, a2, x2 = amp.convert_model(net, arg, {},
                                   target_dtype="bfloat16",
                                   cast_optional_params=True)
    assert str(a2["fc_weight"].dtype) == "bfloat16"
