"""StableHLO export/import (deployment interchange; the reference's ONNX
role, contrib/onnx/mx2onnx/export_onnx.py)."""
import numpy as onp

import mxnet_tpu as mx
from mxnet_tpu.contrib import stablehlo
from mxnet_tpu.gluon import nn
from mxnet_tpu.gluon.model_zoo import vision
from mxnet_tpu.gluon.utils import materialize_params


def test_export_reload_matches_small_net(tmp_path):
    net = nn.HybridSequential()
    net.add(nn.Conv2D(8, 3, padding=1), nn.BatchNorm(),
            nn.Activation("relu"), nn.GlobalAvgPool2D(), nn.Dense(5))
    net.initialize(mx.init.Xavier())
    x = onp.random.RandomState(0).randn(2, 3, 12, 12).astype("float32")
    want = net(mx.nd.array(x)).asnumpy()

    prefix = str(tmp_path / "smallnet")
    path = stablehlo.export_block(prefix, net, (2, 3, 12, 12))
    assert path.endswith("-stablehlo.bin")
    fn = stablehlo.import_block(prefix)
    got = fn(mx.nd.array(x)).asnumpy()
    onp.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


def test_export_reload_matches_resnet(tmp_path):
    net = vision.get_model("resnet18_v1", classes=10)
    net.initialize(mx.init.Xavier())
    materialize_params(net, mx.nd.zeros((1, 3, 32, 32)))
    x = onp.random.RandomState(1).randn(2, 3, 32, 32).astype("float32")
    want = net(mx.nd.array(x)).asnumpy()

    prefix = str(tmp_path / "resnet18")
    stablehlo.export_block(prefix, net, (2, 3, 32, 32))
    fn = stablehlo.import_block(prefix)
    got = fn(x).asnumpy()
    onp.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-5)
