"""Vision + contrib operator tests (reference
``tests/python/unittest/test_operator.py`` vision sections and
``test_contrib_operator.py``)."""
import numpy as onp
import pytest

import mxnet_tpu as mx


def _arr(a):
    return mx.nd.array(onp.asarray(a, "float32"))


# ---------------------------------------------------------------------------
# sampling ops
# ---------------------------------------------------------------------------

def test_bilinear_sampler_identity():
    x = onp.random.RandomState(0).rand(2, 3, 5, 7).astype("float32")
    H, W = 5, 7
    ys = onp.linspace(-1, 1, H)
    xs = onp.linspace(-1, 1, W)
    gx, gy = onp.meshgrid(xs, ys)
    grid = onp.stack([gx, gy])[None].repeat(2, 0).astype("float32")
    out = mx.nd.BilinearSampler(_arr(x), _arr(grid)).asnumpy()
    assert onp.allclose(out, x, atol=1e-5)


def test_bilinear_sampler_halfpixel_shift():
    # shifting the grid by one pixel left reads the next column
    x = onp.arange(2 * 1 * 3 * 4, dtype="float32").reshape(2, 1, 3, 4)
    ys = onp.linspace(-1, 1, 3)
    xs = onp.linspace(-1, 1, 4) + 2.0 / 3  # +1 pixel in x
    gx, gy = onp.meshgrid(xs, ys)
    grid = onp.stack([gx, gy])[None].repeat(2, 0).astype("float32")
    out = mx.nd.BilinearSampler(_arr(x), _arr(grid)).asnumpy()
    assert onp.allclose(out[:, :, :, :-1], x[:, :, :, 1:], atol=1e-4)
    # out-of-range reads are zero-padded
    assert onp.allclose(out[:, :, :, -1], 0.0, atol=1e-4)


def test_grid_generator_affine_identity():
    theta = onp.array([[1, 0, 0, 0, 1, 0]], "float32")
    grid = mx.nd.GridGenerator(_arr(theta), transform_type="affine",
                               target_shape=(3, 4)).asnumpy()
    ys = onp.linspace(-1, 1, 3)
    xs = onp.linspace(-1, 1, 4)
    gx, gy = onp.meshgrid(xs, ys)
    assert onp.allclose(grid[0, 0], gx, atol=1e-6)
    assert onp.allclose(grid[0, 1], gy, atol=1e-6)


def test_grid_generator_warp_zero_flow():
    flow = onp.zeros((1, 2, 3, 4), "float32")
    grid = mx.nd.GridGenerator(_arr(flow), transform_type="warp").asnumpy()
    ys = onp.linspace(-1, 1, 3)
    xs = onp.linspace(-1, 1, 4)
    gx, gy = onp.meshgrid(xs, ys)
    assert onp.allclose(grid[0, 0], gx, atol=1e-6)
    assert onp.allclose(grid[0, 1], gy, atol=1e-6)


def test_spatial_transformer_identity():
    x = onp.random.RandomState(1).rand(2, 3, 6, 6).astype("float32")
    theta = onp.tile(onp.array([1, 0, 0, 0, 1, 0], "float32"), (2, 1))
    out = mx.nd.SpatialTransformer(_arr(x), _arr(theta),
                                   target_shape=(6, 6)).asnumpy()
    assert onp.allclose(out, x, atol=1e-5)


def test_spatial_transformer_grad():
    x = _arr(onp.random.rand(1, 2, 5, 5))
    theta = _arr([[1, 0, 0.1, 0, 1, -0.1]])
    x.attach_grad()
    theta.attach_grad()
    with mx.autograd.record():
        out = mx.nd.SpatialTransformer(x, theta, target_shape=(5, 5))
        loss = (out ** 2).sum()
    loss.backward()
    assert onp.abs(x.grad.asnumpy()).sum() > 0
    assert onp.abs(theta.grad.asnumpy()).sum() > 0


# ---------------------------------------------------------------------------
# ROI ops
# ---------------------------------------------------------------------------

def _roi_pool_ref(data, rois, pooled, scale):
    R = rois.shape[0]
    N, C, H, W = data.shape
    PH, PW = pooled
    out = onp.zeros((R, C, PH, PW), "float32")
    for r in range(R):
        b = int(rois[r, 0])
        x1, y1, x2, y2 = onp.round(rois[r, 1:] * scale)
        rw = max(x2 - x1 + 1, 1.0)
        rh = max(y2 - y1 + 1, 1.0)
        for ph in range(PH):
            for pw_ in range(PW):
                hs = int(onp.floor(ph * rh / PH) + y1)
                he = int(onp.ceil((ph + 1) * rh / PH) + y1)
                ws = int(onp.floor(pw_ * rw / PW) + x1)
                we = int(onp.ceil((pw_ + 1) * rw / PW) + x1)
                hs, he = max(hs, 0), min(he, H)
                ws, we = max(ws, 0), min(we, W)
                if hs >= he or ws >= we:
                    continue
                out[r, :, ph, pw_] = data[b, :, hs:he, ws:we].max(axis=(1, 2))
    return out


def test_roi_pooling_matches_naive():
    rs = onp.random.RandomState(2)
    data = rs.rand(2, 3, 12, 12).astype("float32")
    rois = onp.array([[0, 0, 0, 7, 7], [1, 2, 2, 9, 11], [0, 5, 3, 11, 11]],
                     "float32")
    got = mx.nd.ROIPooling(_arr(data), _arr(rois), pooled_size=(3, 3),
                           spatial_scale=1.0).asnumpy()
    want = _roi_pool_ref(data, rois, (3, 3), 1.0)
    assert onp.allclose(got, want, atol=1e-5), onp.abs(got - want).max()


def test_roi_pooling_spatial_scale():
    rs = onp.random.RandomState(3)
    data = rs.rand(1, 2, 8, 8).astype("float32")
    rois = onp.array([[0, 0, 0, 15, 15]], "float32")  # full image at 1/2
    got = mx.nd.ROIPooling(_arr(data), _arr(rois), pooled_size=(2, 2),
                           spatial_scale=0.5).asnumpy()
    want = _roi_pool_ref(data, rois, (2, 2), 0.5)
    assert onp.allclose(got, want, atol=1e-5)


def test_roi_align_matches_naive():
    rs = onp.random.RandomState(4)
    data = rs.rand(1, 2, 10, 10).astype("float32")
    rois = onp.array([[0, 1.0, 1.0, 8.0, 8.0]], "float32")
    PH = PW = sr = 2
    got = mx.nd._contrib_ROIAlign(_arr(data), _arr(rois),
                                  pooled_size=(PH, PW), spatial_scale=1.0,
                                  sample_ratio=sr).asnumpy()

    def bil(img, y, x):
        H, W = img.shape[1:]
        y0, x0 = int(onp.floor(y)), int(onp.floor(x))
        wy, wx = y - y0, x - x0
        val = 0
        for dy, fy in ((0, 1 - wy), (1, wy)):
            for dx, fx in ((0, 1 - wx), (1, wx)):
                yy, xx = y0 + dy, x0 + dx
                if 0 <= yy < H and 0 <= xx < W:
                    val += fy * fx * img[:, yy, xx]
        return val

    x1, y1, x2, y2 = rois[0, 1:]
    rw, rh = max(x2 - x1, 1), max(y2 - y1, 1)
    want = onp.zeros((1, 2, PH, PW), "float32")
    for ph in range(PH):
        for pw_ in range(PW):
            acc = 0
            for iy in range(sr):
                for ix in range(sr):
                    y = y1 + (ph * sr + iy + 0.5) * rh / (PH * sr)
                    x = x1 + (pw_ * sr + ix + 0.5) * rw / (PW * sr)
                    acc = acc + bil(data[0], y, x)
            want[0, :, ph, pw_] = acc / (sr * sr)
    assert onp.allclose(got, want, atol=1e-4), onp.abs(got - want).max()


# ---------------------------------------------------------------------------
# resize / adaptive pool (cross-checked against torch)
# ---------------------------------------------------------------------------

def test_bilinear_resize_2d_vs_torch():
    torch = pytest.importorskip("torch")
    import torch.nn.functional as F
    x = onp.random.RandomState(5).rand(2, 3, 7, 9).astype("float32")
    got = mx.nd._contrib_BilinearResize2D(_arr(x), height=14,
                                          width=5).asnumpy()
    want = F.interpolate(torch.from_numpy(x), size=(14, 5), mode="bilinear",
                         align_corners=True).numpy()
    assert onp.allclose(got, want, atol=1e-4)


def test_adaptive_avg_pooling_vs_torch():
    torch = pytest.importorskip("torch")
    import torch.nn.functional as F
    x = onp.random.RandomState(6).rand(2, 4, 11, 7).astype("float32")
    got = mx.nd._contrib_AdaptiveAvgPooling2D(
        _arr(x), output_size=(3, 4)).asnumpy()
    want = F.adaptive_avg_pool2d(torch.from_numpy(x), (3, 4)).numpy()
    assert onp.allclose(got, want, atol=1e-5)


# ---------------------------------------------------------------------------
# bounding-box ops
# ---------------------------------------------------------------------------

def test_box_iou():
    a = onp.array([[0, 0, 2, 2]], "float32")
    b = onp.array([[1, 1, 3, 3], [4, 4, 5, 5]], "float32")
    got = mx.nd._contrib_box_iou(_arr(a), _arr(b)).asnumpy()
    assert onp.allclose(got, [[1.0 / 7, 0.0]], atol=1e-5)


def test_box_iou_center_format():
    a = onp.array([[1, 1, 2, 2]], "float32")  # center -> [0,0,2,2]
    b = onp.array([[2, 2, 2, 2]], "float32")  # center -> [1,1,3,3]
    got = mx.nd._contrib_box_iou(_arr(a), _arr(b),
                                 format="center").asnumpy()
    assert onp.allclose(got, [[1.0 / 7]], atol=1e-5)


def test_box_nms_reference_example():
    """The documented example at reference bounding_box.cc:83."""
    x = onp.array([[0, 0.5, 0.1, 0.1, 0.2, 0.2],
                   [1, 0.4, 0.1, 0.1, 0.2, 0.2],
                   [0, 0.3, 0.1, 0.1, 0.14, 0.14],
                   [2, 0.6, 0.5, 0.5, 0.7, 0.8]], "float32")
    out = mx.nd._contrib_box_nms(_arr(x), overlap_thresh=0.1,
                                 coord_start=2, score_index=1, id_index=0,
                                 force_suppress=True).asnumpy()
    want = onp.array([[2, 0.6, 0.5, 0.5, 0.7, 0.8],
                      [0, 0.5, 0.1, 0.1, 0.2, 0.2],
                      [-1, -1, -1, -1, -1, -1],
                      [-1, -1, -1, -1, -1, -1]], "float32")
    assert onp.allclose(out, want, atol=1e-5), out


def test_box_nms_per_class():
    # without force_suppress, different ids don't suppress each other
    x = onp.array([[0, 0.5, 0.1, 0.1, 0.2, 0.2],
                   [1, 0.4, 0.1, 0.1, 0.2, 0.2]], "float32")
    out = mx.nd._contrib_box_nms(_arr(x), overlap_thresh=0.1,
                                 coord_start=2, score_index=1,
                                 id_index=0).asnumpy()
    assert (out[1] != -1).all()


def test_box_nms_valid_thresh_and_batch():
    x = onp.zeros((2, 3, 5), "float32")
    x[0, 0] = [0.9, 0, 0, 1, 1]
    x[0, 1] = [0.0, 0, 0, 1, 1]       # below valid_thresh
    x[0, 2] = [0.8, 2, 2, 3, 3]       # no overlap, kept
    x[1, 0] = [0.7, 0, 0, 1, 1]
    out = mx.nd._contrib_box_nms(_arr(x), overlap_thresh=0.5,
                                 valid_thresh=0.01, coord_start=1,
                                 score_index=0).asnumpy()
    assert onp.allclose(out[0, 0], [0.9, 0, 0, 1, 1])
    assert onp.allclose(out[0, 1], [0.8, 2, 2, 3, 3])
    assert (out[0, 2] == -1).all()
    assert onp.allclose(out[1, 0], [0.7, 0, 0, 1, 1])


def test_bipartite_matching():
    score = onp.array([[[0.9, 0.1], [0.8, 0.2]]], "float32")
    rows, cols = mx.nd._contrib_bipartite_matching(_arr(score),
                                                   threshold=0.05)
    rows, cols = rows.asnumpy(), cols.asnumpy()
    # greedy: (0,0)=0.9 first, then (1,1)=0.2
    assert rows[0].tolist() == [0.0, 1.0]
    assert cols[0].tolist() == [0.0, 1.0]


def test_multibox_prior():
    data = mx.nd.zeros((1, 3, 2, 2))
    anchors = mx.nd._contrib_MultiBoxPrior(
        data, sizes=(0.5, 0.25), ratios=(1.0, 2.0)).asnumpy()
    assert anchors.shape == (1, 2 * 2 * 3, 4)
    # first cell center is (0.25, 0.25); first anchor size 0.5
    assert onp.allclose(anchors[0, 0], [0.0, 0.0, 0.5, 0.5], atol=1e-5)
    # ratio-2 anchor: w = s0*sqrt(2), h = s0/sqrt(2)
    w = anchors[0, 2, 2] - anchors[0, 2, 0]
    h = anchors[0, 2, 3] - anchors[0, 2, 1]
    assert onp.allclose(w / h, 2.0, atol=1e-4)


# ---------------------------------------------------------------------------
# correlation
# ---------------------------------------------------------------------------

def _corr_ref(d1, d2, K, md, s1, s2, pad, mult):
    N, C, H, W = d1.shape
    kr = (K - 1) // 2
    border = md + kr
    Hp, Wp = H + 2 * pad, W + 2 * pad
    OH = -(-(Hp - 2 * border) // s1)
    OW = -(-(Wp - 2 * border) // s1)
    ngr = md // s2
    D = 2 * ngr + 1
    p1 = onp.zeros((N, C, Hp, Wp), "float32")
    p1[:, :, pad:pad + H, pad:pad + W] = d1
    p2 = onp.zeros((N, C, Hp, Wp), "float32")
    p2[:, :, pad:pad + H, pad:pad + W] = d2
    out = onp.zeros((N, D * D, OH, OW), "float32")
    for n in range(N):
        for i, dy in enumerate(range(-ngr, ngr + 1)):
            for j, dx in enumerate(range(-ngr, ngr + 1)):
                for oy in range(OH):
                    for ox in range(OW):
                        y1 = oy * s1 + border
                        x1 = ox * s1 + border
                        acc = 0.0
                        for ky in range(-kr, kr + 1):
                            for kx in range(-kr, kr + 1):
                                a = p1[n, :, y1 + ky, x1 + kx]
                                yy = y1 + ky + dy * s2
                                xx = x1 + kx + dx * s2
                                if 0 <= yy < Hp and 0 <= xx < Wp:
                                    b = p2[n, :, yy, xx]
                                else:
                                    b = 0.0
                                acc += (a * b).sum() if mult \
                                    else onp.abs(a - b).sum()
                        out[n, i * D + j, oy, ox] = acc / (K * K * C)
    return out


@pytest.mark.parametrize("mult", [True, False])
def test_correlation_matches_naive(mult):
    rs = onp.random.RandomState(7)
    d1 = rs.rand(1, 2, 6, 6).astype("float32")
    d2 = rs.rand(1, 2, 6, 6).astype("float32")
    got = mx.nd.Correlation(_arr(d1), _arr(d2), kernel_size=3,
                            max_displacement=1, stride1=1, stride2=1,
                            pad_size=2, is_multiply=mult).asnumpy()
    want = _corr_ref(d1, d2, 3, 1, 1, 1, 2, mult)
    assert got.shape == want.shape
    assert onp.allclose(got, want, atol=1e-4), onp.abs(got - want).max()


@pytest.mark.parametrize("K,md,s1,s2,pad", [
    (1, 3, 1, 2, 3),   # stride2 does NOT divide max_displacement
    (1, 2, 2, 1, 2),   # strided output
    (3, 2, 1, 2, 3),
])
def test_correlation_param_grid(K, md, s1, s2, pad):
    rs = onp.random.RandomState(11)
    d1 = rs.rand(1, 2, 8, 8).astype("float32")
    d2 = rs.rand(1, 2, 8, 8).astype("float32")
    got = mx.nd.Correlation(_arr(d1), _arr(d2), kernel_size=K,
                            max_displacement=md, stride1=s1, stride2=s2,
                            pad_size=pad, is_multiply=True).asnumpy()
    want = _corr_ref(d1, d2, K, md, s1, s2, pad, True)
    assert got.shape == want.shape
    assert onp.allclose(got, want, atol=1e-4), onp.abs(got - want).max()


# ---------------------------------------------------------------------------
# misc contrib ops
# ---------------------------------------------------------------------------

def test_div_sqrt_dim():
    x = onp.random.rand(2, 8).astype("float32")
    got = mx.nd._contrib_div_sqrt_dim(_arr(x)).asnumpy()
    assert onp.allclose(got, x / onp.sqrt(8), atol=1e-6)


def test_quadratic():
    x = onp.array([1.0, 2.0, 3.0], "float32")
    got = mx.nd._contrib_quadratic(_arr(x), a=2, b=3, c=4).asnumpy()
    assert onp.allclose(got, 2 * x * x + 3 * x + 4)


def test_quadratic_grad():
    x = _arr([1.0, 2.0])
    x.attach_grad()
    with mx.autograd.record():
        y = mx.nd._contrib_quadratic(x, a=1, b=2, c=0).sum()
    y.backward()
    assert onp.allclose(x.grad.asnumpy(), 2 * onp.array([1, 2]) + 2)


def test_index_array():
    x = mx.nd.zeros((2, 3))
    idx = mx.nd._contrib_index_array(x).asnumpy()
    assert idx.shape == (2, 3, 2)
    assert idx[1, 2].tolist() == [1, 2]
    idx = mx.nd._contrib_index_array(x, axes=(1,)).asnumpy()
    assert idx[1, 2].tolist() == [2]


def test_index_copy():
    old = mx.nd.zeros((5, 3))
    new = _arr(onp.ones((2, 3)))
    idx = mx.nd.array(onp.array([1, 3], "float32"))
    out = mx.nd._contrib_index_copy(old, idx, new).asnumpy()
    assert out[1].tolist() == [1, 1, 1]
    assert out[3].tolist() == [1, 1, 1]
    assert out[0].tolist() == [0, 0, 0]


def test_fft_ifft_roundtrip():
    x = onp.random.RandomState(8).rand(3, 8).astype("float32")
    f = mx.nd._contrib_fft(_arr(x))
    assert f.shape == (3, 16)
    # cuFFT-style unnormalized roundtrip: ifft(fft(x)) = x * d
    back = mx.nd._contrib_ifft(f).asnumpy()
    assert onp.allclose(back, x * 8, atol=1e-3)


def test_fft_values():
    x = onp.random.RandomState(9).rand(2, 4).astype("float32")
    got = mx.nd._contrib_fft(_arr(x)).asnumpy()
    ref = onp.fft.fft(x, axis=-1)
    inter = onp.stack([ref.real, ref.imag], -1).reshape(2, 8)
    assert onp.allclose(got, inter, atol=1e-4)


def test_count_sketch():
    x = onp.array([[1.0, 2.0, 3.0]], "float32")
    h = onp.array([0, 1, 0], "float32")
    s = onp.array([1, -1, 1], "float32")
    got = mx.nd._contrib_count_sketch(_arr(x), _arr(h), _arr(s),
                                      out_dim=2).asnumpy()
    assert onp.allclose(got, [[4.0, -2.0]])


def test_gradient_multiplier():
    x = _arr([1.0, 2.0])
    x.attach_grad()
    with mx.autograd.record():
        y = mx.nd._contrib_gradient_multiplier(x, scalar=-0.5).sum()
    y.backward()
    assert onp.allclose(x.grad.asnumpy(), [-0.5, -0.5])
    # forward is identity
    assert onp.allclose(
        mx.nd._contrib_gradient_multiplier(x, scalar=-0.5).asnumpy(),
        x.asnumpy())


def test_all_finite():
    ok = mx.nd.all_finite(_arr([1.0, 2.0])).asnumpy()
    assert ok.tolist() == [1.0]
    bad = mx.nd.all_finite(_arr([1.0, onp.inf])).asnumpy()
    assert bad.tolist() == [0.0]
    m = mx.nd.multi_all_finite(_arr([1.0]), _arr([onp.nan]),
                               num_arrays=2).asnumpy()
    assert m.tolist() == [0.0]


def test_adamw_decoupled_decay():
    """AdamW: wd is applied to the weight, not folded into the gradient."""
    opt = mx.optimizer.AdamW(learning_rate=0.1, wd=0.1, eta=1.0)
    w = _arr([1.0])
    g = _arr([0.0])  # zero gradient: only decay acts
    state = opt.create_state(0, w)
    opt.update(0, w, g, state)
    # m=v=0 with zero grad -> w' = w - eta*(wd*w) = 0.9
    assert onp.allclose(w.asnumpy(), [0.9], atol=1e-6)

    # nonzero grad matches the manual formula
    opt2 = mx.optimizer.AdamW(learning_rate=0.1, wd=0.0)
    w2 = _arr([1.0])
    g2 = _arr([0.5])
    st = opt2.create_state(0, w2)
    opt2.update(0, w2, g2, st)
    m = 0.1 * 0.5
    v = 0.001 * 0.25
    want = 1.0 - 0.1 * m / (onp.sqrt(v) + 1e-8)
    assert onp.allclose(w2.asnumpy(), [want], atol=1e-6)
