"""Continuous-batching inference server units (mxnet_tpu.serve):
bucket policy, AOT zero-recompile steady state, deadline propagation,
backpressure/shedding, state machine, drain, and the stablehlo bucketed
export path.  The injected-fault matrix lives in test_serve_chaos.py.
"""
import os
import sys
import time

import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import serve, telemetry
from mxnet_tpu.serve import (AotModel, InferenceServer, ServeConfig,
                             pad_batch, pick_bucket, plan_buckets)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

FEAT = (8,)
W = onp.arange(8 * 3, dtype="float32").reshape(8, 3) * 0.1


def _fn(x):
    import jax.numpy as jnp
    return x @ jnp.asarray(W)


def _cfg(**kw):
    base = dict(buckets=(1, 2, 4), max_queue=16, batch_wait_ms=2.0,
                default_deadline_ms=500.0, dispatch_timeout_ms=500.0,
                watchdog_interval_ms=15.0)
    base.update(kw)
    return ServeConfig(**base)


def _server(**kw):
    return InferenceServer(_fn, feature_shape=FEAT, config=_cfg(**kw))


def _rows(n):
    return [onp.full(FEAT, i, "float32") for i in range(n)]


# -- bucket policy ----------------------------------------------------------

def test_pick_bucket_smallest_covering():
    assert pick_bucket(1, (1, 2, 4)) == 1
    assert pick_bucket(3, (1, 2, 4)) == 4
    assert pick_bucket(4, (1, 2, 4)) == 4
    assert pick_bucket(5, (1, 2, 4)) is None
    assert pick_bucket(2, (1, 2, 4), quarantined=(2,)) == 4
    assert pick_bucket(4, (1, 2, 4), quarantined=(4,)) is None


def test_plan_buckets_healthy_and_degraded():
    assert plan_buckets(3, (1, 2, 4)) == [4]
    assert plan_buckets(6, (1, 2, 4)) == [4, 2]
    # quarantined big bucket: the batch degrades onto smaller buckets
    assert plan_buckets(4, (1, 2, 4), quarantined=(4,)) == [2, 2]
    assert plan_buckets(7, (1, 2, 4), quarantined=(4,)) == [2, 2, 2, 1]
    assert plan_buckets(2, (1, 2, 4), quarantined=(1, 2, 4)) is None
    assert plan_buckets(0, (1, 2)) == []


def test_pad_batch_pads_and_refuses_overflow():
    rows = _rows(2)
    out = pad_batch(rows, 4, FEAT, "float32")
    assert out.shape == (4, 8) and out.dtype == onp.float32
    onp.testing.assert_array_equal(out[1], rows[1])
    onp.testing.assert_array_equal(out[2:], 0)
    with pytest.raises(mx.MXNetError):
        pad_batch(_rows(3), 2, FEAT, "float32")


# -- serving happy path -----------------------------------------------------

def test_serves_correct_results_zero_steady_state_recompiles():
    srv = _server()
    srv.start()
    try:
        assert srv.state() == serve.READY
        rows = _rows(11)
        handles = [srv.submit(r) for r in rows]
        outs = [h.outcome(timeout=2.0) for h in handles]
        assert all(o is not None and o[0] == "result" for o in outs)
        for r, o in zip(rows, outs):
            onp.testing.assert_allclose(o[1], r @ W, rtol=1e-5)
        # the bucketed-AOT contract: every compile happened in start(),
        # the load phase added ZERO — the recompile-detector hard gate
        assert srv.steady_state_recompiles() == {}
        counts = telemetry.compile_counts()
        menu = {k: v for k, v in counts.items()
                if k.startswith("serve.%s." % srv.name)}
        assert len(menu) == 3 and set(menu.values()) == {1}
    finally:
        srv.close()


def test_latency_and_batching_census():
    srv = _server()
    srv.start()
    try:
        h = srv.submit(_rows(1)[0])
        assert h.outcome(timeout=2.0)[0] == "result"
        assert 0.0 < h.latency_ms() < 2000.0
    finally:
        srv.close()


def test_from_block_matches_net():
    from mxnet_tpu.gluon import nn
    net = nn.Dense(4)
    net.initialize(mx.init.Xavier())
    x = onp.random.RandomState(3).randn(1, 6).astype("float32")
    net(mx.nd.array(x))            # materialize params
    want = net(mx.nd.array(x)).asnumpy()
    srv = InferenceServer(net, feature_shape=(6,),
                          config=_cfg(buckets=(1, 2)), name="dense")
    srv.start()
    try:
        got = srv.submit(x[0]).result(timeout=2.0)
        onp.testing.assert_allclose(got, want[0], rtol=1e-5)
    finally:
        srv.close()


# -- deadlines --------------------------------------------------------------

def test_expired_request_dropped_before_dispatch():
    srv = _server()
    srv.start()
    try:
        d0 = telemetry.counter("serve.dispatches")
        drops0 = telemetry.counter("serve.deadline_drops")
        h = srv.submit(_rows(1)[0], deadline_ms=0.0)
        out = h.outcome(timeout=2.0)
        assert out is not None and out[0] == "timeout"
        # the expiry resolved BEFORE an executable dispatch was wasted
        assert telemetry.counter("serve.deadline_drops") == drops0 + 1
        assert telemetry.counter("serve.dispatches") == d0
    finally:
        srv.close()


def test_batch_never_waits_past_earliest_deadline():
    # batch_wait is huge; the single request's deadline must flush the
    # batch long before the wait window closes (the margin is the
    # dispatch-time headroom the flush leaves itself)
    srv = _server(batch_wait_ms=2000.0, deadline_margin_ms=40.0)
    srv.start()
    try:
        h = srv.submit(_rows(1)[0], deadline_ms=150.0)
        out = h.outcome(timeout=2.0)
        assert out is not None and out[0] == "result"
        assert h.latency_ms() < 1000.0
    finally:
        srv.close()


# -- admission control ------------------------------------------------------

def test_bad_shape_is_immediate_reject():
    srv = _server()
    srv.start()
    try:
        h = srv.submit(onp.zeros((3,), "float32"))
        kind, _, reason = h.outcome(timeout=1.0)
        assert kind == "reject" and "bad_shape" in reason
        with pytest.raises(serve.ServeRejected):
            h.result(timeout=0.1)
    finally:
        srv.close()


def test_submit_before_start_and_after_drain_rejects():
    srv = _server()
    h = srv.submit(_rows(1)[0])
    assert h.outcome(timeout=0.5) == ("reject", None, "not_ready")
    srv.start()
    srv.drain(timeout=5.0)
    assert srv.state() == serve.DRAINING
    h2 = srv.submit(_rows(1)[0])
    assert h2.outcome(timeout=0.5) == ("reject", None, "draining")
    srv.close()


def test_priority_shedding_under_overload_then_recovery():
    # shed watermark at depth 2 of a 4-slot queue; a huge batch_wait
    # keeps the batcher from draining while the burst lands
    srv = _server(max_queue=4, shed_fraction=0.5, resume_fraction=0.9,
                  batch_wait_ms=150.0, buckets=(1, 2, 4))
    srv.start()
    try:
        handles = [srv.submit(r, priority=1, deadline_ms=2000.0)
                   for r in _rows(10)]
        outs = [h.outcome(timeout=4.0) for h in handles]
        assert all(o is not None for o in outs)
        kinds = [o[0] for o in outs]
        sheds = sum(1 for o in outs
                    if o[0] == "reject" and o[2] in ("shed",))
        assert sheds >= 1, kinds
        # priority-0 requests are NOT shed at the same depth
        h0 = srv.submit(_rows(1)[0], priority=0, deadline_ms=2000.0)
        out0 = h0.outcome(timeout=4.0)
        assert out0 is not None and out0[2] != "shed"
        # once the queue subsides the watchdog recovers DEGRADED->READY
        deadline = time.monotonic() + 3.0
        while srv.state() != serve.READY and time.monotonic() < deadline:
            time.sleep(0.02)
        assert srv.state() == serve.READY
    finally:
        srv.close()


def test_queue_full_is_reject_not_block():
    # 2-slot queue + a batcher parked on a long wait: the 20-request
    # burst must come back queue_full immediately, never block submit
    srv = _server(max_queue=2, batch_wait_ms=200.0)
    srv.start()
    try:
        t0 = time.monotonic()
        handles = [srv.submit(r, deadline_ms=2000.0) for r in _rows(20)]
        submit_s = time.monotonic() - t0
        assert submit_s < 1.0          # no blocked producer
        outs = [h.outcome(timeout=4.0) for h in handles]
        assert all(o is not None for o in outs)
        assert any(o[0] == "reject" and o[2] == "queue_full"
                   for o in outs), [o[0:3:2] for o in outs]
    finally:
        srv.close()


# -- lifecycle --------------------------------------------------------------

def test_state_machine_and_clean_drain():
    srv = _server()
    assert srv.state() == serve.STARTING
    srv.start()
    assert srv.state() == serve.READY
    handles = [srv.submit(r) for r in _rows(6)]
    drained = srv.close(timeout=10.0)
    assert drained
    # accepted requests COMPLETED through the drain (not rejected)
    outs = [h.outcome(timeout=0.5) for h in handles]
    assert all(o is not None and o[0] == "result" for o in outs), \
        [o and o[0] for o in outs]
    assert srv.state() == serve.DRAINING
    # threads stopped and joined
    for t in (srv._batcher, srv._watchdog, srv._dispatcher):
        assert t is not None and not t.is_alive()
    # idempotent
    assert srv.close(timeout=1.0)


def test_close_without_start():
    srv = _server()
    srv.close(timeout=1.0)
    assert srv.state() == serve.DRAINING


# -- stablehlo bucketed export path ----------------------------------------

def test_export_bucketed_serves_from_disk(tmp_path):
    from mxnet_tpu.contrib import stablehlo
    from mxnet_tpu.gluon import nn
    net = nn.Dense(5)
    net.initialize(mx.init.Xavier())
    x = onp.random.RandomState(7).randn(2, 6).astype("float32")
    net(mx.nd.array(x))
    want = net(mx.nd.array(x)).asnumpy()

    prefix = str(tmp_path / "served")
    paths = stablehlo.export_bucketed(prefix, net, (1, 2), (6,))
    assert [p.rsplit("/", 1)[-1] for p in paths] == \
        ["served-b1-stablehlo.bin", "served-b2-stablehlo.bin"]
    arts = stablehlo.load_bucketed(prefix)
    assert sorted(arts) == [1, 2]

    srv = InferenceServer.from_exported(prefix, name="served")
    assert srv._cfg.buckets == (1, 2)
    srv.start()
    try:
        outs = [srv.submit(x[i]).result(timeout=2.0) for i in range(2)]
        onp.testing.assert_allclose(onp.stack(outs), want, rtol=1e-5)
        assert srv.steady_state_recompiles() == {}
    finally:
        srv.close()


def test_load_bucketed_missing_raises(tmp_path):
    from mxnet_tpu.contrib import stablehlo
    with pytest.raises(mx.MXNetError):
        stablehlo.load_bucketed(str(tmp_path / "nothing"))


# -- parse_log census -------------------------------------------------------

def test_parse_log_serve_census_roundtrip(tmp_path):
    from tools.parse_log import parse_jsonl, render_jsonl
    sink = tmp_path / "serve.jsonl"
    telemetry.set_jsonl_sink(str(sink))
    try:
        srv = _server()
        srv.start()
        for r in _rows(5):
            srv.submit(r)
        srv.submit(_rows(1)[0], deadline_ms=0.0)   # one timeout row
        srv.submit(onp.zeros((3,), "float32"))     # one reject row
        time.sleep(0.2)
        srv.close()
    finally:
        telemetry.set_jsonl_sink(None)
    agg = parse_jsonl(open(str(sink)))
    census = agg["serve"]
    assert census["batches"] >= 1
    assert census["events"].get("batch", 0) >= 1
    assert census["events"].get("timeout", 0) >= 1
    assert census["events"].get("reject", 0) >= 1
    assert any(s.startswith("STARTING->READY") for s in census["states"])
    text = render_jsonl(agg)
    assert "serve journal census" in text
    assert "serve/batch" in text and "serve/timeout" in text
    assert "mean-fill" in text
