"""Native C++ I/O layer: recordio reader, JPEG decode, ImageRecordIter.

Parity targets: dmlc recordio framing + the reference's C++
``ImageRecordIter`` (``src/io/iter_image_recordio_2.cc``)."""
import os

import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import native, recordio

pytestmark = pytest.mark.skipif(not native.available(),
                                reason="native library unavailable")


@pytest.fixture(scope="module")
def rec_file(tmp_path_factory):
    cv2 = pytest.importorskip("cv2")
    d = tmp_path_factory.mktemp("rec")
    rec_path = str(d / "data.rec")
    idx_path = str(d / "data.idx")
    rec = recordio.MXIndexedRecordIO(idx_path, rec_path, "w")
    rs = onp.random.RandomState(0)
    imgs = []
    for i in range(23):
        img = rs.randint(0, 255, (16, 16, 3), dtype=onp.uint8)
        imgs.append(img)
        hdr = recordio.IRHeader(0, float(i % 7), i, 0)
        rec.write_idx(i, recordio.pack_img(hdr, img, img_fmt=".png"))
    rec.close()
    # a JPEG-payload twin for the native pipeline (PNG exercises fallback)
    jrec_path = str(d / "jdata.rec")
    jidx_path = str(d / "jdata.idx")
    jrec = recordio.MXIndexedRecordIO(jidx_path, jrec_path, "w")
    for i in range(23):
        hdr = recordio.IRHeader(0, float(i % 7), i, 0)
        jrec.write_idx(i, recordio.pack_img(hdr, imgs[i], quality=100,
                                            img_fmt=".jpg"))
    jrec.close()
    return {"rec": rec_path, "idx": idx_path, "jrec": jrec_path,
            "jidx": jidx_path, "imgs": imgs}


def test_native_scan_matches_python_idx(rec_file):
    f = native.NativeRecordFile(rec_file["rec"])
    offs = f.scan()
    r = recordio.MXIndexedRecordIO(rec_file["idx"], rec_file["rec"], "r")
    assert list(offs) == [r.idx[k] for k in r.keys]
    assert f.read_at(int(offs[7])) == r.read_idx(7)
    f.close()
    r.close()


def test_native_jpeg_decode_parity(rec_file):
    import cv2
    r = recordio.MXIndexedRecordIO(rec_file["jidx"], rec_file["jrec"], "r")
    _, payload = recordio.unpack(r.read_idx(3))
    nat = native.jpeg_decode(payload)
    ref = cv2.cvtColor(
        cv2.imdecode(onp.frombuffer(payload, onp.uint8), 1),
        cv2.COLOR_BGR2RGB)
    assert nat.shape == ref.shape
    # same libjpeg under both; decode is bit-exact
    assert onp.array_equal(nat, ref)
    r.close()


def test_pipeline_epoch_coverage_and_reset(rec_file):
    f = native.NativeRecordFile(rec_file["jrec"])
    offs = f.scan()
    f.close()
    p = native.NativeImagePipeline(
        rec_file["jrec"], offs, batch_size=8, data_shape=(3, 16, 16),
        shuffle=True, seed=3, preprocess_threads=2)
    labels_seen = []
    tot = 0
    for _ in range(p.num_batches):
        data, labels, pad, errors = p.next()
        assert data.shape == (8, 3, 16, 16) and errors == 0
        n = 8 - pad
        labels_seen.extend(labels[:n, 0].tolist())
        tot += n
    assert p.next() is None
    assert tot == 23
    assert sorted(labels_seen) == sorted(float(i % 7) for i in range(23))
    p.reset()
    assert p.next() is not None
    p.close()


def test_image_record_iter_values(rec_file):
    """No resize/crop (images exactly data_shape): output must equal the
    exact decode normalized by mean/std, labels in file order."""
    import cv2
    it = mx.io.ImageRecordIter(
        path_imgrec=rec_file["jrec"], data_shape=(3, 16, 16), batch_size=4,
        mean_r=10.0, mean_g=20.0, mean_b=30.0, std_r=2.0, std_g=3.0,
        std_b=4.0, preprocess_threads=2)
    r = recordio.MXIndexedRecordIO(rec_file["jidx"], rec_file["jrec"], "r")
    batch = it.next()
    data = batch.data[0].asnumpy()
    label = batch.label[0].asnumpy()
    for i in range(4):
        _, payload = recordio.unpack(r.read_idx(i))
        rgb = cv2.cvtColor(
            cv2.imdecode(onp.frombuffer(payload, onp.uint8), 1),
            cv2.COLOR_BGR2RGB).astype(onp.float32)
        want = (rgb - onp.array([10., 20., 30.])) / onp.array([2., 3., 4.])
        got = data[i].transpose(1, 2, 0)
        assert onp.allclose(got, want, atol=1e-5)
        assert label[i] == float(i % 7)
    # full epoch then StopIteration, reset restarts
    n = 4
    for b in it:
        n += b.data[0].shape[0] - (b.pad or 0)
    assert n >= 23
    it.reset()
    assert it.next().data[0].shape == (4, 3, 16, 16)
    r.close()


def test_image_record_iter_png_fallback(rec_file):
    """PNG payloads can't use the native JPEG path — must fall back to the
    Python ImageIter and still deliver correct shapes."""
    it = mx.io.ImageRecordIter(
        path_imgrec=rec_file["rec"], data_shape=(3, 16, 16), batch_size=4)
    b = it.next()
    assert b.data[0].shape == (4, 3, 16, 16)


def test_pipeline_mid_epoch_reset_stress(rec_file):
    """Reset before the epoch is drained must not hang, leak slots, or
    deliver stale batches (create/reset race regression)."""
    f = native.NativeRecordFile(rec_file["jrec"])
    offs = f.scan()
    f.close()
    p = native.NativeImagePipeline(
        rec_file["jrec"], offs, batch_size=4, data_shape=(3, 16, 16),
        shuffle=True, seed=5, preprocess_threads=3, prefetch_buffer=2)
    for _ in range(10):
        out = p.next()          # consume one batch only
        assert out is not None
        p.reset()               # abandon the rest of the epoch
    # after all that, a full clean epoch must still deliver every record
    tot = 0
    for _ in range(p.num_batches):
        data, labels, pad, errors = p.next()
        tot += 4 - pad
    assert p.next() is None
    assert tot == 23
    p.close()


def test_pipeline_shuffle_deterministic(rec_file):
    f = native.NativeRecordFile(rec_file["jrec"])
    offs = f.scan()
    f.close()
    outs = []
    for _ in range(2):
        p = native.NativeImagePipeline(
            rec_file["jrec"], offs, batch_size=23, data_shape=(3, 16, 16),
            shuffle=True, seed=11, preprocess_threads=2,
            rand_crop=True, rand_mirror=True)
        data, labels, pad, errors = p.next()
        outs.append((data.copy(), labels.copy()))
        p.close()
    assert onp.array_equal(outs[0][0], outs[1][0])
    assert onp.array_equal(outs[0][1], outs[1][1])


@pytest.mark.slow
def test_pipeline_thread_count_invariant(rec_file):
    """Per-image work stealing must be schedule-independent: any thread
    count yields bit-identical batches (augment RNG is keyed on (seed,
    epoch, record position), not on worker assignment).  slow: a full
    worker-count sweep (3 epochs of the rec) — the single-config borrow/
    release and u8 parity tests below keep tier-1 coverage."""
    f = native.NativeRecordFile(rec_file["jrec"])
    offs = f.scan()
    f.close()
    outs = []
    for nthreads in (1, 4, 8):
        p = native.NativeImagePipeline(
            rec_file["jrec"], offs, batch_size=6, data_shape=(3, 16, 16),
            shuffle=True, seed=17, preprocess_threads=nthreads,
            rand_crop=True, rand_mirror=True, prefetch_buffer=3)
        epoch = []
        while True:
            out = p.next()
            if out is None:
                break
            epoch.append((out[0].copy(), out[1].copy(), out[2]))
        outs.append(epoch)
        p.close()
    for other in outs[1:]:
        assert len(other) == len(outs[0])
        for (d0, l0, p0), (d1, l1, p1) in zip(outs[0], other):
            assert onp.array_equal(d0, d1)
            assert onp.array_equal(l0, l1)
            assert p0 == p1


def test_pipeline_borrow_release_parity(rec_file):
    """The zero-copy borrow path must deliver the same batches, in the
    same order, as the copying path — including with several loans
    outstanding at once (the depth-K device feed's usage pattern)."""
    f = native.NativeRecordFile(rec_file["jrec"])
    offs = f.scan()
    f.close()
    kw = dict(batch_size=4, data_shape=(3, 16, 16), shuffle=True, seed=21,
              preprocess_threads=3, rand_crop=True, rand_mirror=True)
    pc = native.NativeImagePipeline(rec_file["jrec"], offs, **kw)
    pb = native.NativeImagePipeline(rec_file["jrec"], offs,
                                    prefetch_buffer=4, **kw)
    pending = []
    for _ in range(pc.num_batches):
        dc, lc, padc, ec = pc.next()
        out = pb.next_borrow()
        assert out is not None
        db, lb, padb, eb, token = out
        # the view aliases the slot; compare before release
        assert onp.array_equal(dc, db)
        assert onp.array_equal(lc, lb) and padc == padb and ec == eb
        pending.append(token)
        if len(pending) >= 3:       # hold 3 loans in flight
            pb.release(pending.pop(0))
    for t in pending:
        pb.release(t)
    assert pb.next_borrow() is None
    # a released ring still supports reset + a clean full epoch
    pb.reset()
    tot = 0
    for _ in range(pb.num_batches):
        out = pb.next_borrow()
        tot += 4 - out[2]
        pb.release(out[4])
    assert tot == 23
    pc.close()
    pb.close()


def test_image_record_iter_borrow_matches_host(rec_file):
    """ImageRecordIter.next_borrow: same stream as next_host, slot
    released via the returned callable."""
    kw = dict(path_imgrec=rec_file["jrec"], data_shape=(3, 16, 16),
              batch_size=4, preprocess_threads=2, u8_output=True,
              shuffle=True, seed=2)
    a = mx.io.ImageRecordIter(**kw)
    b = mx.io.ImageRecordIter(**kw)
    while True:
        try:
            dh, lh, padh = a.next_host()
        except StopIteration:
            with pytest.raises(StopIteration):
                b.next_borrow()
            break
        db, lb, padb, release = b.next_borrow()
        assert onp.array_equal(dh, db) and onp.array_equal(lh, lb)
        assert padh == padb
        release()
    a.close()
    b.close()


def test_pipeline_u8_output_parity(rec_file):
    """u8 mode returns the raw crop planes; normalizing them on the host
    must reproduce the f32 mode exactly (same RNG keying)."""
    f = native.NativeRecordFile(rec_file["jrec"])
    offs = f.scan()
    f.close()
    mean = [123.68, 116.78, 103.94]
    std = [58.4, 57.12, 57.38]
    kw = dict(batch_size=8, data_shape=(3, 16, 16), shuffle=True, seed=9,
              preprocess_threads=3, rand_crop=True, rand_mirror=True,
              mean=mean, std=std)
    pf = native.NativeImagePipeline(rec_file["jrec"], offs, **kw)
    pu = native.NativeImagePipeline(rec_file["jrec"], offs, u8_output=True,
                                    **kw)
    for _ in range(pf.num_batches):
        df, lf, padf, ef = pf.next()
        du, lu, padu, eu = pu.next()
        assert du.dtype == onp.uint8
        norm = (du.astype(onp.float32)
                - onp.asarray(mean, onp.float32).reshape(1, 3, 1, 1)) \
            / onp.asarray(std, onp.float32).reshape(1, 3, 1, 1)
        onp.testing.assert_allclose(norm, df, rtol=0, atol=1e-5)
        assert onp.array_equal(lf, lu) and padf == padu and ef == eu
    pf.close()
    pu.close()


def test_device_prefetch_over_native_u8_matches_f32_pipeline(rec_file):
    """Full path: native u8 decode -> borrowed slot -> DevicePrefetchIter
    (depth-K feeder, device_put, pre-jitted on-device normalize) must
    reproduce the host-normalized float32 pipeline bit-for-bit batch
    stream (order included), across a reset."""
    from mxnet_tpu.io import DevicePrefetchIter

    kw = dict(path_imgrec=rec_file["jrec"], data_shape=(3, 16, 16),
              batch_size=4, shuffle=True, seed=7, preprocess_threads=3,
              rand_crop=True, rand_mirror=True,
              mean_r=123.68, mean_g=116.78, mean_b=103.94,
              std_r=58.4, std_g=57.12, std_b=57.38)
    ref = mx.io.ImageRecordIter(u8_output=False, **kw)
    feed = DevicePrefetchIter(mx.io.ImageRecordIter(u8_output=True, **kw),
                              dtype="float32", depth=3)
    for _ in range(2):                       # second pass exercises reset
        n_batches = 0
        for rb, db in zip(ref, feed):
            onp.testing.assert_allclose(db.data[0].asnumpy(),
                                        rb.data[0].asnumpy(), atol=1e-4)
            onp.testing.assert_allclose(db.label[0].asnumpy(),
                                        rb.label[0].asnumpy())
            assert db.pad == rb.pad
            n_batches += 1
        assert n_batches == 6
        ref.reset()
        feed.reset()
    feed.close()
