"""gluon.rnn: fused layers, cells, consistency, gradients, convergence.

Reference: tests/python/unittest/test_gluon_rnn.py.
"""
import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import gluon
from mxnet_tpu.gluon import nn, rnn


@pytest.mark.parametrize("cls,nstate", [(rnn.LSTM, 2), (rnn.GRU, 1),
                                        (rnn.RNN, 1)])
def test_layer_shapes(cls, nstate):
    layer = cls(hidden_size=16, num_layers=2)
    layer.initialize()
    x = mx.nd.array(onp.random.rand(5, 3, 8).astype("float32"))  # TNC
    out = layer(x)
    assert out.shape == (5, 3, 16)
    states = layer.begin_state(3)
    out, new_states = layer(x, states)
    assert out.shape == (5, 3, 16)
    assert len(new_states) == nstate
    assert new_states[0].shape == (2, 3, 16)


def test_layer_ntc_layout():
    layer = rnn.LSTM(hidden_size=8, layout="NTC")
    layer.initialize()
    x = mx.nd.array(onp.random.rand(3, 5, 4).astype("float32"))
    out = layer(x)
    assert out.shape == (3, 5, 8)


def test_bidirectional_layer():
    layer = rnn.LSTM(hidden_size=8, num_layers=2, bidirectional=True)
    layer.initialize()
    x = mx.nd.array(onp.random.rand(5, 3, 4).astype("float32"))
    out = layer(x)
    assert out.shape == (5, 3, 16)  # 2 * hidden


@pytest.mark.parametrize("mode", ["lstm", "gru", "rnn_tanh"])
def test_fused_matches_cells(mode):
    """The fused scan layer must agree with the explicitly unrolled cell —
    weight-sharing through _unfuse (reference test_rnn_cells pattern)."""
    T, N, C, H = 4, 2, 3, 5
    layer = {"lstm": rnn.LSTM, "gru": rnn.GRU,
             "rnn_tanh": lambda *a, **kw: rnn.RNN(*a, activation="tanh",
                                                  **kw)}[mode](
        hidden_size=H, input_size=C)
    layer.initialize()
    x = mx.nd.array(onp.random.rand(T, N, C).astype("float32"))
    fused_out = layer(x).asnumpy()

    stack = layer._unfuse()
    outputs, _ = stack.unroll(T, [x[t] for t in range(T)],
                              merge_outputs=False)
    cell_out = onp.stack([o.asnumpy() for o in outputs], axis=0)
    onp.testing.assert_allclose(fused_out, cell_out, rtol=1e-5, atol=1e-6)


def test_lstm_layer_grad():
    layer = rnn.LSTM(hidden_size=8)
    layer.initialize()
    x = mx.nd.array(onp.random.rand(5, 3, 4).astype("float32"))
    with mx.autograd.record():
        out = layer(x)
        loss = (out * out).sum()
    loss.backward()
    for name, p in layer.collect_params().items():
        g = p.grad().asnumpy()
        assert onp.isfinite(g).all(), name
        assert onp.abs(g).sum() > 0, name


@pytest.mark.parametrize("cell_cls", [rnn.RNNCell, rnn.LSTMCell,
                                      rnn.GRUCell])
def test_cell_unroll(cell_cls):
    cell = cell_cls(10, input_size=6)
    cell.initialize()
    x = mx.nd.array(onp.random.rand(2, 3, 6).astype("float32"))  # NTC
    outputs, states = cell.unroll(3, x, layout="NTC", merge_outputs=True)
    assert outputs.shape == (2, 3, 10)


def test_residual_cell():
    cell = rnn.ResidualCell(rnn.GRUCell(4, input_size=4))
    cell.initialize()
    x = mx.nd.array(onp.random.rand(2, 3, 4).astype("float32"))
    outputs, _ = cell.unroll(3, x, layout="NTC", merge_outputs=True)
    assert outputs.shape == (2, 3, 4)


def test_sequential_cell_stack():
    stack = rnn.SequentialRNNCell()
    stack.add(rnn.LSTMCell(8, input_size=4))
    stack.add(rnn.LSTMCell(8, input_size=8))
    stack.initialize()
    x = mx.nd.array(onp.random.rand(2, 5, 4).astype("float32"))
    outputs, states = stack.unroll(5, x, layout="NTC", merge_outputs=True)
    assert outputs.shape == (2, 5, 8)
    assert len(states) == 4


def test_zoneout_cell_runs():
    cell = rnn.ZoneoutCell(rnn.RNNCell(4, input_size=4), 0.3, 0.3)
    cell.initialize()
    x = mx.nd.array(onp.random.rand(2, 3, 4).astype("float32"))
    with mx.autograd.train_mode():
        outputs, _ = cell.unroll(3, x, layout="NTC", merge_outputs=True)
    assert outputs.shape == (2, 3, 4)


def test_bidirectional_cell():
    cell = rnn.BidirectionalCell(rnn.LSTMCell(4, input_size=3),
                                 rnn.LSTMCell(4, input_size=3))
    cell.initialize()
    x = mx.nd.array(onp.random.rand(2, 5, 3).astype("float32"))
    outputs, states = cell.unroll(5, x, layout="NTC", merge_outputs=True)
    assert outputs.shape == (2, 5, 8)


def test_rnn_hybridize():
    """Fused layer under hybridize compiles to one program and matches."""
    layer = rnn.LSTM(hidden_size=8, input_size=4)
    layer.initialize()
    x = mx.nd.array(onp.random.rand(5, 3, 4).astype("float32"))
    ref = layer(x).asnumpy()
    layer.hybridize()
    got = layer(x).asnumpy()
    onp.testing.assert_allclose(ref, got, rtol=1e-5, atol=1e-6)


def test_word_lm_descends():
    """Tiny word-LM: embed → LSTM → dense; loss descends (BASELINE
    config 4 capability check)."""
    V, E, H, T, N = 20, 8, 16, 6, 4
    net = nn.HybridSequential()
    net.add(nn.Embedding(V, E))
    lstm = rnn.LSTM(hidden_size=H, layout="NTC", input_size=E)
    net.add(lstm)
    net.add(nn.Dense(V, flatten=False))
    net.initialize(mx.init.Xavier())
    rs = onp.random.RandomState(0)
    data = mx.nd.array(rs.randint(0, V, (N, T)).astype("float32"))
    target = mx.nd.array(rs.randint(0, V, (N, T)).astype("float32"))
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": 0.05})
    losses = []
    for _ in range(8):
        with mx.autograd.record():
            out = net(data)
            loss = loss_fn(out, target)
        loss.backward()
        trainer.step(N)
        losses.append(float(loss.mean().asscalar()))
    assert losses[-1] < losses[0] * 0.8, losses
