"""Regressions for the phase-5 errorflow burn-down: every durable
artifact writer that used to ``open(path, "w")`` in place now rides the
tmp + ``os.replace`` discipline (``fsutil.atomic_write_path`` /
``checkpoint.atomic_path``), and the shared commit window is
fault-injectable via the ``artifact_write_crash`` chaos mode.

The contract under test, for each converted writer: a crash inside the
commit window leaves the PREVIOUS file byte-identical and leaves no
``*.tmp.*`` litter — a reader can never observe a torn artifact.
"""
import glob
import json
import os

import pytest

from mxnet_tpu import fsutil, telemetry
from mxnet_tpu.parallel import chaos


@pytest.fixture(autouse=True)
def _clear_chaos():
    chaos.clear()
    yield
    chaos.clear()


def _no_tmp_litter(directory):
    return [p for p in glob.glob(os.path.join(directory, "*"))
            if ".tmp." in os.path.basename(p)]


def test_atomic_write_path_commits_and_cleans(tmp_path):
    target = tmp_path / "artifact.json"
    with fsutil.atomic_write_path(str(target)) as tmp:
        with open(tmp, "w") as f:
            f.write('{"ok": 1}')
        assert not target.exists()          # nothing until the commit
    assert json.loads(target.read_text()) == {"ok": 1}
    assert _no_tmp_litter(str(tmp_path)) == []


def test_atomic_write_path_crash_window_preserves_old_file(tmp_path):
    target = tmp_path / "artifact.json"
    target.write_text('{"version": 1}')
    chaos.install("artifact_write_crash", times=1)
    with pytest.raises(chaos.ChaosError):
        with fsutil.atomic_write_path(str(target)) as tmp:
            with open(tmp, "w") as f:
                f.write('{"version": 2}')
    assert json.loads(target.read_text()) == {"version": 1}
    assert _no_tmp_litter(str(tmp_path)) == []
    # the window is per-write: the retry commits
    with fsutil.atomic_write_path(str(target)) as tmp:
        with open(tmp, "w") as f:
            f.write('{"version": 2}')
    assert json.loads(target.read_text()) == {"version": 2}


def test_atomic_write_path_writer_error_keeps_old_file(tmp_path):
    target = tmp_path / "artifact.bin"
    target.write_bytes(b"old")
    with pytest.raises(RuntimeError):
        with fsutil.atomic_write_path(str(target)) as tmp:
            with open(tmp, "wb") as f:
                f.write(b"partial")
            raise RuntimeError("died mid-build")
    assert target.read_bytes() == b"old"
    assert _no_tmp_litter(str(tmp_path)) == []


def test_export_jsonl_atomic_under_crash(tmp_path):
    path = tmp_path / "rank0.jsonl"
    telemetry.event("unit", "before_crash")
    telemetry.export_jsonl(str(path))
    committed = path.read_text()
    assert committed                        # baseline export landed
    chaos.install("artifact_write_crash", times=1)
    telemetry.event("unit", "lost_by_crash")
    with pytest.raises(chaos.ChaosError):
        telemetry.export_jsonl(str(path))
    assert path.read_text() == committed    # old export intact, not torn
    assert _no_tmp_litter(str(tmp_path)) == []


def test_telemetry_collect_outputs_atomic_under_crash(tmp_path):
    from mxnet_tpu import telemetry_collect
    src = tmp_path / "rank0.jsonl"
    telemetry.event("unit", "collectme")
    telemetry.export_jsonl(str(src))
    out = tmp_path / "merged.trace.json"
    telemetry_collect.collect([str(src)], str(out))
    committed = out.read_text()
    json.loads(committed)                   # a complete JSON document
    chaos.install("artifact_write_crash", times=1)
    with pytest.raises(chaos.ChaosError):
        telemetry_collect.collect([str(src)], str(out))
    assert out.read_text() == committed
    assert _no_tmp_litter(str(tmp_path)) == []


def test_recordio_idx_sidecar_atomic_under_crash(tmp_path):
    from mxnet_tpu import recordio
    rec = str(tmp_path / "data.rec")
    idx = str(tmp_path / "data.idx")
    w = recordio.MXIndexedRecordIO(idx, rec, "w")
    w.write_idx(0, b"alpha")
    w.write_idx(1, b"beta")
    w.close()
    committed = open(idx).read()
    assert len(committed.splitlines()) == 2
    # rewrite with a crash inside the idx commit window: the .rec closes
    # but the OLD sidecar must survive un-torn
    w = recordio.MXIndexedRecordIO(idx, rec, "w")
    w.write_idx(0, b"gamma")
    chaos.install("artifact_write_crash", times=1)
    with pytest.raises(chaos.ChaosError):
        w.close()
    # the crash hit INSIDE the sidecar's commit window: the old sidecar
    # survives byte-identical (never torn mid-rewrite) and no tmp leaks
    assert open(idx).read() == committed
    assert _no_tmp_litter(str(tmp_path)) == []
    w.close()                               # retry: fault exhausted
    r = recordio.MXIndexedRecordIO(idx, rec, "r")
    assert r.read_idx(0) == b"gamma"
    r.close()


def test_save_optimizer_states_atomic(tmp_path):
    """Module.save_optimizer_states goes through atomic_path now — a
    checkpoint_write_crash in the commit window keeps the old .states
    file."""
    from mxnet_tpu.module import Module

    class FakeUpdater:
        blob = b"state-blob-v1"

        def get_states(self):
            return self.blob

    fname = str(tmp_path / "opt.states")
    mod = Module.__new__(Module)
    mod._update_on_kvstore = False
    mod._kvstore = None
    mod._updater = FakeUpdater()
    mod.optimizer_initialized = True
    mod.save_optimizer_states(fname)
    assert open(fname, "rb").read() == b"state-blob-v1"
    mod._updater.blob = b"state-blob-v2"
    chaos.install("checkpoint_write_crash", times=1)
    with pytest.raises(chaos.ChaosError):
        mod.save_optimizer_states(fname)
    assert open(fname, "rb").read() == b"state-blob-v1"
    assert _no_tmp_litter(str(tmp_path)) == []


def test_cost_table_write_rides_artifact_crash_window(tmp_path):
    from mxnet_tpu.tune.cost_table import CostTable
    path = str(tmp_path / "cost_table.jsonl")
    t = CostTable(path)
    t.record("layernorm", (64, 8), "float32", {"block_rows": 8},
             best_ms=1.0, platform="cpu-test")
    committed = open(path).read()
    chaos.install("artifact_write_crash", times=1)
    with pytest.raises(chaos.ChaosError):
        t.record("layernorm", (128, 8), "float32", {"block_rows": 16},
                 best_ms=2.0, platform="cpu-test")
    assert open(path).read() == committed
    assert _no_tmp_litter(str(tmp_path)) == []


def test_legacy_save_atomic_under_crash(tmp_path):
    import numpy as onp
    from mxnet_tpu.ndarray import legacy_io

    fname = str(tmp_path / "model.params")
    legacy_io.save_legacy(fname, {"w": onp.ones((2, 2), "float32")})
    committed = open(fname, "rb").read()
    assert legacy_io.is_legacy_file(fname)
    chaos.install("checkpoint_write_crash", times=1)
    with pytest.raises(chaos.ChaosError):
        legacy_io.save_legacy(fname, {"w": onp.zeros((2, 2), "float32")})
    assert open(fname, "rb").read() == committed
    assert _no_tmp_litter(str(tmp_path)) == []
