"""IO + gluon.data + recordio + image tests (reference test_io.py /
test_gluon_data.py / test_recordio.py / test_image.py strategies)."""
import os

import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import io, recordio
from mxnet_tpu.gluon import data as gdata


def test_ndarray_iter_basic():
    data = onp.arange(40, dtype="float32").reshape(10, 4)
    label = onp.arange(10, dtype="float32")
    it = io.NDArrayIter(data, label, batch_size=3, last_batch_handle="pad")
    batches = list(it)
    assert len(batches) == 4
    assert batches[0].data[0].shape == (3, 4)
    onp.testing.assert_allclose(batches[0].data[0].asnumpy(), data[:3])
    assert batches[-1].pad == 2
    # pad wraps around to the beginning
    onp.testing.assert_allclose(batches[-1].data[0].asnumpy()[1:],
                                data[[9, 0]][1:] if False else data[:2])

    it.reset()
    assert len(list(it)) == 4


def test_ndarray_iter_discard():
    data = onp.arange(40, dtype="float32").reshape(10, 4)
    it = io.NDArrayIter(data, None, batch_size=3, last_batch_handle="discard")
    assert len(list(it)) == 3


def test_ndarray_iter_shuffle():
    data = onp.arange(100, dtype="float32").reshape(100, 1)
    it = io.NDArrayIter(data, data[:, 0].copy(), batch_size=10, shuffle=True)
    batch = next(it)
    onp.testing.assert_allclose(batch.data[0].asnumpy()[:, 0],
                                batch.label[0].asnumpy())


def test_ndarray_iter_dict_input():
    it = io.NDArrayIter({"a": onp.zeros((6, 2)), "b": onp.ones((6, 3))},
                        onp.arange(6), batch_size=2)
    assert {d.name for d in it.provide_data} == {"a", "b"}
    b = next(it)
    assert len(b.data) == 2


def test_resize_iter():
    data = onp.zeros((10, 2), "float32")
    base = io.NDArrayIter(data, batch_size=5)
    it = io.ResizeIter(base, size=7)
    assert len(list(it)) == 7


def test_prefetching_iter():
    data = onp.arange(20, dtype="float32").reshape(10, 2)
    base = io.NDArrayIter(data, onp.arange(10, dtype="float32"), batch_size=5)
    it = io.PrefetchingIter(base)
    batches = list(it)
    assert len(batches) == 2
    it.reset()
    assert len(list(it)) == 2


def test_dataset_and_dataloader():
    x = onp.random.randn(20, 3).astype("float32")
    y = onp.arange(20, dtype="float32")
    ds = gdata.ArrayDataset(x, y)
    assert len(ds) == 20
    item = ds[3]
    onp.testing.assert_allclose(item[0], x[3])

    dl = gdata.DataLoader(ds, batch_size=6, last_batch="keep")
    batches = list(dl)
    assert len(batches) == 4
    assert batches[0][0].shape == (6, 3)
    assert batches[-1][0].shape == (2, 3)

    dl2 = gdata.DataLoader(ds, batch_size=6, shuffle=True, last_batch="discard",
                           num_workers=2)
    batches = list(dl2)
    assert len(batches) == 3


def test_dataset_transform_shard():
    ds = gdata.SimpleDataset(list(range(10)))
    t = ds.transform(lambda x: x * 2)
    assert t[3] == 6
    sh = ds.shard(3, 0)
    assert len(sh) == 4  # 10 = 4+3+3
    assert sh[0] == 0
    sh2 = ds.shard(3, 1)
    assert sh2[0] == 4


def test_batch_sampler_rollover():
    s = gdata.BatchSampler(gdata.SequentialSampler(10), 4, "rollover")
    first = list(s)
    assert len(first) == 2
    second = list(s)
    assert second[0][:2] == [8, 9]


def test_recordio_roundtrip(tmp_path):
    fname = str(tmp_path / "test.rec")
    w = recordio.MXRecordIO(fname, "w")
    for i in range(5):
        w.write(b"record%d" % i)
    w.close()
    r = recordio.MXRecordIO(fname, "r")
    for i in range(5):
        assert r.read() == b"record%d" % i
    assert r.read() is None
    r.close()


def test_indexed_recordio(tmp_path):
    fname = str(tmp_path / "test.rec")
    idxname = str(tmp_path / "test.idx")
    w = recordio.MXIndexedRecordIO(idxname, fname, "w")
    for i in range(5):
        w.write_idx(i, b"rec%d" % i)
    w.close()
    r = recordio.MXIndexedRecordIO(idxname, fname, "r")
    assert r.read_idx(3) == b"rec3"
    assert r.read_idx(0) == b"rec0"
    r.close()


def test_pack_unpack():
    hdr = recordio.IRHeader(0, 7.0, 42, 0)
    s = recordio.pack(hdr, b"payload")
    hdr2, payload = recordio.unpack(s)
    assert payload == b"payload"
    assert hdr2.label == 7.0 and hdr2.id == 42
    # multi-label
    hdr3 = recordio.IRHeader(0, onp.array([1.0, 2.0, 3.0], "float32"), 1, 0)
    s3 = recordio.pack(hdr3, b"x")
    hdr4, p4 = recordio.unpack(s3)
    onp.testing.assert_allclose(hdr4.label, [1, 2, 3])


def test_image_pack_img_and_dataset(tmp_path):
    cv2 = pytest.importorskip("cv2")
    from mxnet_tpu import image as mimg
    fname = str(tmp_path / "imgs.rec")
    idxname = str(tmp_path / "imgs.idx")
    w = recordio.MXIndexedRecordIO(idxname, fname, "w")
    rng = onp.random.RandomState(0)
    for i in range(4):
        img = rng.randint(0, 255, (32, 32, 3), dtype=onp.uint8)
        s = recordio.pack_img(recordio.IRHeader(0, float(i), i, 0), img,
                              quality=100, img_fmt=".png")
        w.write_idx(i, s)
    w.close()

    ds = gdata.vision.ImageRecordDataset(fname)
    assert len(ds) == 4
    img, label = ds[2]
    assert img.shape == (32, 32, 3)
    assert float(label) == 2.0

    it = mimg.ImageIter(batch_size=2, data_shape=(3, 28, 28),
                        path_imgrec=fname, rand_crop=True, rand_mirror=True)
    batch = it.next()
    assert batch.data[0].shape == (2, 3, 28, 28)


def test_transforms():
    from mxnet_tpu.gluon.data.vision import transforms as T
    img = mx.nd.array(onp.random.randint(0, 255, (32, 30, 3)), dtype="uint8")
    t = T.ToTensor()(img)
    assert t.shape == (3, 32, 30)
    assert float(t.max().asscalar()) <= 1.0
    n = T.Normalize(mean=(0.5, 0.5, 0.5), std=(0.2, 0.2, 0.2))(t)
    assert n.shape == (3, 32, 30)
    r = T.Resize((16, 16))(img)
    assert r.shape[:2] == (16, 16)
    c = T.CenterCrop(8)(img)
    assert c.shape[:2] == (8, 8)
    rc = T.RandomResizedCrop(12)(img)
    assert rc.shape[:2] == (12, 12)
    comp = T.Compose([T.Resize(20), T.ToTensor()])
    out = comp(img)
    assert out.shape[0] == 3


def test_mnist_iter_synthetic(tmp_path):
    """MNISTIter reads the idx-ubyte format (write a tiny synthetic file)."""
    import struct
    rng = onp.random.RandomState(0)
    images = rng.randint(0, 255, (10, 28, 28), dtype=onp.uint8)
    labels = rng.randint(0, 10, 10).astype(onp.uint8)
    img_f = str(tmp_path / "train-images-idx3-ubyte")
    lab_f = str(tmp_path / "train-labels-idx1-ubyte")
    with open(img_f, "wb") as f:
        f.write(struct.pack(">IIII", 2051, 10, 28, 28))
        f.write(images.tobytes())
    with open(lab_f, "wb") as f:
        f.write(struct.pack(">II", 2049, 10))
        f.write(labels.tobytes())
    it = io.MNISTIter(image=img_f, label=lab_f, batch_size=5, flat=False,
                      shuffle=False)
    b = next(it)
    assert b.data[0].shape == (5, 1, 28, 28)
    assert float(b.data[0].max().asscalar()) <= 1.0


def _double_batchify(samples):
    return onp.stack([onp.asarray(s[0]) * 2 for s in samples])


def test_dataloader_multiprocessing_shm():
    """Spawn-worker + shared-memory transport path (reference
    dataloader.py:66-120 multiprocessing + shm design): values must match
    the serial path exactly, across two epochs (pool reuse), including a
    custom batchify_fn executed worker-side."""
    x = onp.arange(36, dtype="float32").reshape(12, 3)
    y = onp.arange(12, dtype="float32")
    ds = gdata.ArrayDataset(x, y)
    dl = gdata.DataLoader(ds, batch_size=4, num_workers=2)
    for _ in range(2):  # two epochs through the same worker pool
        got_x, got_y = [], []
        for bx, by in dl:
            got_x.append(bx.asnumpy())
            got_y.append(by.asnumpy())
        onp.testing.assert_allclose(onp.concatenate(got_x), x)
        onp.testing.assert_allclose(onp.concatenate(got_y), y)
    dl.close()

    dl2 = gdata.DataLoader(ds, batch_size=6, num_workers=2,
                           batchify_fn=_double_batchify)
    out = onp.concatenate([b.asnumpy() for b in dl2])
    onp.testing.assert_allclose(out, x * 2)
    dl2.close()


def test_device_prefetch_iter_u8_normalize_and_order():
    """DevicePrefetchIter: u8 wire batches arrive device-resident,
    normalized (x-mean)/std in the target dtype, in order, pad preserved,
    and reset restarts the stream (reference PrefetchingIter contract,
    python/mxnet/io/io.py)."""
    import numpy as onp
    from mxnet_tpu.io import DataBatch, DataDesc, DataIter
    from mxnet_tpu.io import DevicePrefetchIter
    import mxnet_tpu as mx

    rs = onp.random.RandomState(0)
    batches = [rs.randint(0, 255, (4, 3, 8, 8), dtype=onp.uint8)
               for _ in range(5)]
    labels = [onp.arange(4, dtype="float32") + 10 * i for i in range(5)]
    mean = onp.array([100.0, 110.0, 120.0], "float32")
    std = onp.array([50.0, 55.0, 60.0], "float32")

    class U8Iter(DataIter):
        def __init__(self):
            super().__init__(4)
            self.i = 0
            self.mean = mean
            self.std = std

        @property
        def provide_data(self):
            return [DataDesc("data", (4, 3, 8, 8))]

        @property
        def provide_label(self):
            return [DataDesc("softmax_label", (4,))]

        def reset(self):
            self.i = 0

        def next(self):
            if self.i >= len(batches):
                raise StopIteration
            b = DataBatch([mx.nd.array(batches[self.i], dtype="uint8")],
                          [mx.nd.array(labels[self.i])],
                          pad=1 if self.i == len(batches) - 1 else 0)
            self.i += 1
            return b

    feed = DevicePrefetchIter(U8Iter(), dtype="float32")
    got = list(feed)
    assert len(got) == 5
    for i, b in enumerate(got):
        want = (batches[i].astype("float32")
                - mean.reshape(1, 3, 1, 1)) / std.reshape(1, 3, 1, 1)
        onp.testing.assert_allclose(b.data[0].asnumpy(), want, rtol=1e-6)
        onp.testing.assert_allclose(b.label[0].asnumpy(), labels[i])
        assert b.pad == (1 if i == 4 else 0)
    feed.reset()
    again = list(feed)
    assert len(again) == 5
    onp.testing.assert_allclose(again[2].data[0].asnumpy(),
                                got[2].data[0].asnumpy())


def _mk_u8_base(batches, labels, mean, std):
    """Tiny synthetic u8-wire DataIter for DevicePrefetchIter tests."""
    from mxnet_tpu.io import DataBatch, DataDesc, DataIter
    import mxnet_tpu as mx

    class U8Iter(DataIter):
        def __init__(self):
            super().__init__(batches[0].shape[0])
            self.i = 0
            self.mean = mean
            self.std = std

        @property
        def provide_data(self):
            return [DataDesc("data", batches[0].shape)]

        @property
        def provide_label(self):
            return [DataDesc("softmax_label", labels[0].shape)]

        def reset(self):
            self.i = 0

        def next(self):
            if self.i >= len(batches):
                raise StopIteration
            b = DataBatch([mx.nd.array(batches[self.i], dtype="uint8")],
                          [mx.nd.array(labels[self.i])],
                          pad=2 if self.i == len(batches) - 1 else 0)
            self.i += 1
            return b

    return U8Iter()


def test_device_prefetch_depth_k_order_and_reset():
    """depth >= 2 keeps several transfers in flight; delivery must stay
    in order with pads intact, reset mid-stream must restart cleanly,
    and an extra reset after exhaustion must replay the epoch."""
    import numpy as onp
    from mxnet_tpu.io import DevicePrefetchIter

    rs = onp.random.RandomState(1)
    batches = [rs.randint(0, 255, (4, 3, 8, 8), dtype=onp.uint8)
               for _ in range(7)]
    labels = [onp.arange(4, dtype="float32") + 10 * i for i in range(7)]
    mean = onp.array([100.0, 110.0, 120.0], "float32")
    std = onp.array([50.0, 55.0, 60.0], "float32")

    for depth in (2, 4):
        feed = DevicePrefetchIter(_mk_u8_base(batches, labels, mean, std),
                                  dtype="float32", depth=depth)
        first = feed.next()
        want0 = (batches[0].astype("float32")
                 - mean.reshape(1, 3, 1, 1)) / std.reshape(1, 3, 1, 1)
        onp.testing.assert_allclose(first.data[0].asnumpy(), want0,
                                    rtol=1e-6)
        feed.reset()                      # mid-stream (queue was primed)
        got = list(feed)
        assert len(got) == 7
        for i, b in enumerate(got):
            want = (batches[i].astype("float32")
                    - mean.reshape(1, 3, 1, 1)) / std.reshape(1, 3, 1, 1)
            onp.testing.assert_allclose(b.data[0].asnumpy(), want,
                                        rtol=1e-6)
            onp.testing.assert_allclose(b.label[0].asnumpy(), labels[i])
            assert b.pad == (2 if i == 6 else 0)
        feed.reset()                      # after exhaustion
        assert len(list(feed)) == 7
        feed.close()


def test_device_prefetch_clean_shutdown_and_gc():
    """close() must join the feeder thread, and a DROPPED iterator (GC,
    no close) must not leak its feeder: the weakref-based loop exits
    once the finalizer fires."""
    import gc
    import time
    import threading
    import numpy as onp
    from mxnet_tpu.io import DevicePrefetchIter

    def feeders():
        return [t for t in threading.enumerate()
                if t.name.startswith("DevicePrefetchIter")]

    rs = onp.random.RandomState(2)
    batches = [rs.randint(0, 255, (2, 3, 4, 4), dtype=onp.uint8)
               for _ in range(6)]
    labels = [onp.zeros(2, "float32") for _ in range(6)]
    base = feeders()

    feed = DevicePrefetchIter(_mk_u8_base(batches, labels, None, None),
                              dtype="float32", depth=1)
    feed.next()
    feed.close()
    assert feeders() == base

    feed2 = DevicePrefetchIter(_mk_u8_base(batches, labels, None, None),
                               dtype="float32", depth=1)
    feed2.next()                          # feeder alive, queue primed
    del feed2
    gc.collect()
    deadline = time.time() + 5.0
    while feeders() != base and time.time() < deadline:
        time.sleep(0.05)
    assert feeders() == base


def test_device_prefetch_error_passthrough():
    """An exception in the base iterator must surface on next(), not
    vanish in the feeder thread."""
    import pytest
    from mxnet_tpu.io import DataDesc, DataIter
    from mxnet_tpu.io import DevicePrefetchIter

    class Boom(DataIter):
        def __init__(self):
            super().__init__(2)

        @property
        def provide_data(self):
            return [DataDesc("data", (2, 3, 4, 4))]

        @property
        def provide_label(self):
            return [DataDesc("softmax_label", (2,))]

        def reset(self):
            pass

        def next(self):
            raise RuntimeError("decode exploded")

    feed = DevicePrefetchIter(Boom(), dtype="float32")
    with pytest.raises(RuntimeError, match="decode exploded"):
        feed.next()
    feed.close()
