"""graftlint tier-1 gate + checker unit tests.

The gate (`test_package_gate_zero_findings`) runs the full analyzer over
``mxnet_tpu/`` and fails on ANY new unsuppressed, un-baselined finding —
the static complement of the telemetry runtime detectors.  The fixture
tests assert exact rule IDs and line numbers against the seeded
violations in ``tests/lint_fixtures/`` (``# expect: <rule>`` markers).
"""
import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXDIR = os.path.join(REPO, "tests", "lint_fixtures")

sys.path.insert(0, REPO) if REPO not in sys.path else None

from tools.lint import run_lint, all_rules  # noqa: E402
from tools.lint.core import (Finding, diff_baseline, load_baseline,  # noqa: E402
                             parse_suppressions, write_baseline)


def _expected(path):
    """Parse `# expect: rule[, rule...]` markers -> {(rule, line), ...}."""
    out = set()
    with open(path) as f:
        for i, line in enumerate(f, start=1):
            if "# expect:" in line:
                tail = line.split("# expect:", 1)[1].strip()
                for rule in tail.split(","):
                    out.add((rule.strip(), i))
    return out


def _lint_fixture(name):
    path = os.path.join(FIXDIR, name)
    return path, run_lint([path], baseline_path=None)


@pytest.mark.parametrize("name", ["fx_trace.py", "fx_retrace.py",
                                  "fx_donation.py", "fx_pallas.py",
                                  "fx_sharding.py", "fx_concurrency.py",
                                  "fx_numerics.py", "fx_tune.py",
                                  "fx_errorflow.py"])
def test_fixture_rules_and_lines(name):
    path, result = _lint_fixture(name)
    got = {(f.rule, f.line) for f in result.new}
    want = _expected(path)
    assert got == want, (
        "finding mismatch for %s\n  missing: %s\n  extra: %s"
        % (name, sorted(want - got), sorted(got - want)))


def test_donation_flags_pr3_reconstruction():
    """Acceptance: the donation checker must flag the PR 3
    use-after-donate pattern (donated train-step carries read after the
    donating call) and stay quiet on the rebinding/mark_borrowed
    variants."""
    _, result = _lint_fixture("fx_donation.py")
    by_ctx = {}
    for f in result.new:
        by_ctx.setdefault(f.context, []).append(f.rule)
    assert by_ctx.get("pr3_use_after_donate") == ["donate-use-after-donate"]
    assert by_ctx.get("refeed_donated") == ["donate-use-after-donate"]
    assert by_ctx.get("helper_returned_donation") == \
        ["donate-use-after-donate"]
    for clean in ("train_loop", "borrowed_is_safe",
                  "metadata_reads_are_safe"):
        assert clean not in by_ctx, (clean, by_ctx.get(clean))


def test_suppressions_honored_and_reasons_mandatory():
    path, result = _lint_fixture("fx_suppress.py")
    got_new = {(f.rule, f.line) for f in result.new}
    assert got_new == _expected(path), got_new
    # the two properly-suppressed syncs land in .suppressed
    src = open(path).read().splitlines()
    line_a = next(i for i, l in enumerate(src, 1) if "a = float" in l)
    line_b = next(i for i, l in enumerate(src, 1) if "b = float" in l)
    suppressed = {(f.rule, f.line) for f in result.suppressed}
    assert ("trace-host-sync", line_a) in suppressed
    assert ("trace-host-sync", line_b) in suppressed


def test_suppression_parser_reason_forms():
    sups = parse_suppressions(
        "x = 1  # graftlint: disable=trace-host-sync -- inline reason\n"
        "# graftlint: disable-next=retrace-shape-branch --\n"
        "# reason on the continuation line\n"
        "y = 2\n"
        "z = 3  # graftlint: disable=trace-host-sync\n")
    assert sups[0].line == 1 and sups[0].reason == "inline reason"
    assert sups[1].line == 4
    assert sups[1].reason == "reason on the continuation line"
    assert sups[2].reason is None


def test_reasonless_suppression_cannot_steal_next_comment():
    """An inline suppression with no `--` must stay reasonless even when
    an unrelated comment follows — otherwise it silently activates and
    dodges lint-suppression-reason."""
    sups = parse_suppressions(
        "x = float(v)  # graftlint: disable=trace-host-sync\n"
        "# TODO: clean this up later\n")
    assert sups[0].reason is None
    # bare `--` without the -next form gets no continuation either
    sups = parse_suppressions(
        "x = float(v)  # graftlint: disable=trace-host-sync --\n"
        "# unrelated comment\n")
    assert sups[0].reason is None


def test_disable_next_covers_header_not_body(tmp_path):
    """disable-next above a compound statement covers only its header:
    a same-rule violation inside the body must still fire."""
    src = (
        "import jax\n"
        "\n"
        "\n"
        "@jax.jit\n"
        "def f(x):\n"
        "    # graftlint: disable-next=trace-tracer-branch -- header ok\n"
        "    if x.sum() > 0:\n"
        "        if x.max() > 1:\n"
        "            x = x + 1\n"
        "    return x\n")
    p = tmp_path / "mod.py"
    p.write_text(src)
    result = run_lint([str(p)], baseline_path=None)
    assert [(f.rule, f.line) for f in result.suppressed] == \
        [("trace-tracer-branch", 7)]
    assert [(f.rule, f.line) for f in result.new] == \
        [("trace-tracer-branch", 8)]


def test_parse_error_fails_the_gate(tmp_path):
    p = tmp_path / "broken.py"
    p.write_text("def f(:\n")
    result = run_lint([str(p)], baseline_path=None)
    assert [f.rule for f in result.new] == ["lint-parse-error"]


def test_baseline_diff_multiplicity(tmp_path):
    f = lambda line: Finding("trace-host-sync", "pkg/m.py", line, 0,
                             "sync", "fn")
    path = str(tmp_path / "baseline.json")
    write_baseline(path, [f(10)])
    table = load_baseline(path)
    # same (file, rule, context) at a DIFFERENT line stays baselined —
    # line drift must not churn the baseline
    new, old = diff_baseline([f(99)], table)
    assert not new and len(old) == 1
    # a second instance beyond the baselined count is NEW
    new, old = diff_baseline([f(10), f(20)], table)
    assert len(new) == 1 and len(old) == 1


# THE tier-1 full-package scan fixture (`package_scan`) is
# session-scoped in tests/conftest.py — shared by the gate,
# stale-suppression and changed-mode tests here so every rule family
# (numerics included) pays for ONE scan.


def test_package_gate_zero_findings(package_scan):
    """THE tier-1 gate: zero new findings over mxnet_tpu/ (stale
    suppressions included — the audit rides the gate scan), and the run
    is journaled into telemetry (lint.findings counter + lint event)."""
    from mxnet_tpu import telemetry
    result = package_scan
    assert result.files, "package scan found no files"
    msg = "\n".join(f.render() for f in result.new)
    assert not result.new, (
        "new graftlint findings (fix, or suppress with "
        "'# graftlint: disable=<rule> -- <reason>'):\n" + msg)
    # every inline suppression must carry a reason (checked by the
    # lint-suppression-reason meta rule, which lands in .new above);
    # the gate also emits its result into the telemetry journal
    assert telemetry.counter("lint.findings") == 0
    snap = telemetry.snapshot(events=4096)
    assert any(e.get("kind") == "lint" and e.get("name") == "gate"
               for e in snap["events"])


def test_detection_op_is_callback_free():
    """Satellite regression gate: the detection ops must stay pure
    jnp/lax — no host callbacks, no host syncs in jit-reachable code
    (this platform does not support callbacks; the *_host oracles are
    exempt because they are not jit-reachable)."""
    result = run_lint([os.path.join(REPO, "mxnet_tpu", "ops",
                                    "detection.py")],
                      baseline_path=None)
    trace = [f for f in result.new + result.suppressed
             if f.rule in ("trace-host-callback", "trace-host-sync")]
    assert not trace, "\n".join(f.render() for f in trace)


def test_cli_json_and_exit_codes(tmp_path):
    env = dict(os.environ, PYTHONPATH=REPO + os.pathsep
               + os.environ.get("PYTHONPATH", ""))
    # findings -> exit 1, valid JSON with exact rule/line payload
    res = subprocess.run(
        [sys.executable, "-m", "tools.lint",
         os.path.join(FIXDIR, "fx_retrace.py"), "--no-baseline",
         "--format", "json"],
        cwd=REPO, env=env, capture_output=True, text=True)
    assert res.returncode == 1, res.stderr
    data = json.loads(res.stdout)
    got = {(f["rule"], f["line"]) for f in data["findings"]}
    assert got == _expected(os.path.join(FIXDIR, "fx_retrace.py"))
    assert data["counts"]["new"] == len(got)
    # clean input -> exit 0 (the whole-package exit-0 path is covered
    # in-process by test_package_gate_zero_findings; a second full scan
    # in a subprocess would double the gate's tier-1 cost)
    res = subprocess.run(
        [sys.executable, "-m", "tools.lint",
         os.path.join(FIXDIR, "fx_donation.py"), "--no-baseline",
         "--rules", "trace-host-callback", "--format", "json"],
        cwd=REPO, env=env, capture_output=True, text=True)
    assert res.returncode == 0, res.stdout[-2000:] + res.stderr[-2000:]
    data = json.loads(res.stdout)
    assert data["counts"]["new"] == 0


def test_seeded_mesh_axis_bug_fails_the_gate(tmp_path):
    """Acceptance: renaming ONE mesh axis in a pristine parallel/ file
    must trip the sharding checker.  The unmodified copy stays clean —
    the finding comes from the seeded bug, not fixture noise."""
    src = open(os.path.join(REPO, "mxnet_tpu", "parallel",
                            "moe.py")).read()
    clean = tmp_path / "moe_clean.py"
    clean.write_text(src)
    result = run_lint([str(clean)], baseline_path=None)
    assert not result.new, "\n".join(f.render() for f in result.new)

    bugged = src.replace("recv = lax.all_to_all(send, axis,",
                         'recv = lax.all_to_all(send, "dp",')
    assert bugged != src, "seeding site moved — update the test"
    bad = tmp_path / "moe_bug.py"
    bad.write_text(bugged)
    result = run_lint([str(bad)], baseline_path=None)
    rules = {f.rule for f in result.new}
    assert "shard-axis-unknown" in rules, \
        "\n".join(f.render() for f in result.new)


# pristine two-lock module shared with the runtime half of the
# acceptance test (tests/test_runtime_lockorder.py reads the SAME
# fixture, so both detectors exercise byte-identical modules).  The
# seeded-bug test inverts ONE pair and the gate must trip.
LOCKPAIR_SRC = open(os.path.join(FIXDIR, "fx_lockpair.py")).read()
LOCKPAIR_INVERSION = (
    "def pop():\n    with _a:\n        with _b:",
    "def pop():\n    with _b:\n        with _a:")


def test_seeded_lock_inversion_fails_the_gate(tmp_path):
    """Acceptance: the pristine copy (consistent a->b order on every
    path) is clean; inverting ONE with-pair seeds the ABBA shape and
    must trip conc-lock-order."""
    clean = tmp_path / "lockpair_clean.py"
    clean.write_text(LOCKPAIR_SRC)
    result = run_lint([str(clean)], baseline_path=None)
    assert not result.new, "\n".join(f.render() for f in result.new)

    bugged = LOCKPAIR_SRC.replace(*LOCKPAIR_INVERSION)
    assert bugged != LOCKPAIR_SRC, "seeding site moved — update the test"
    bad = tmp_path / "lockpair_bug.py"
    bad.write_text(bugged)
    result = run_lint([str(bad)], baseline_path=None)
    rules = {f.rule for f in result.new}
    assert "conc-lock-order" in rules, \
        "\n".join(f.render() for f in result.new)


# pristine mini ZeRO update shared with the runtime half of the
# acceptance test (tests/test_runtime_numerics.py runs the SAME
# fixture on the mesh, so both detectors exercise byte-identical
# modules).  The seeded-bug test drops the fp32 upcast and the gate
# must trip.
ZERO_UPDATE_SRC = open(os.path.join(FIXDIR, "fx_zero_update.py")).read()
ZERO_UPDATE_SEED = ("g16.astype(jnp.float32)", "g16")


def test_seeded_lowprec_accum_fails_the_gate(tmp_path):
    """Acceptance: the pristine mini ZeRO update (explicit fp32 upcast
    before the reduce-scatter) is clean; dropping the upcast seeds the
    low-precision-accumulation bug and must trip num-lowprec-accum
    (the grad-norm now sums in float16) plus num-implicit-promotion
    (the master update now mixes f32 and f16)."""
    clean = tmp_path / "zero_clean.py"
    clean.write_text(ZERO_UPDATE_SRC)
    result = run_lint([str(clean)], baseline_path=None)
    assert not result.new, "\n".join(f.render() for f in result.new)

    bugged = ZERO_UPDATE_SRC.replace(*ZERO_UPDATE_SEED)
    assert bugged != ZERO_UPDATE_SRC, "seeding site moved — update the test"
    bad = tmp_path / "zero_bug.py"
    bad.write_text(bugged)
    result = run_lint([str(bad)], baseline_path=None)
    rules = {f.rule for f in result.new}
    assert "num-lowprec-accum" in rules, \
        "\n".join(f.render() for f in result.new)
    assert "num-implicit-promotion" in rules, \
        "\n".join(f.render() for f in result.new)


def _load_copy(path, name):
    """Import a seeded module copy under the package namespace so its
    relative imports resolve."""
    import importlib.util
    spec = importlib.util.spec_from_file_location(name, str(path))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_seeded_dropped_commit_fails_gate_and_tears_at_runtime(tmp_path):
    """Acceptance (errorflow): deleting the ``os.replace`` commit from a
    checkpoint.py copy's atomic_path must (a) trip res-nonatomic-write
    statically — the CM is blessed STRUCTURALLY, not by name — and
    (b) reproduce the hazard dynamically: writes through the de-fanged
    CM never reach the target.  The pristine copy is clean both ways."""
    src = open(os.path.join(REPO, "mxnet_tpu", "checkpoint.py")).read()
    clean = tmp_path / "ckpt_clean.py"
    clean.write_text(src)
    result = run_lint([str(clean)], baseline_path=None)
    assert not result.new, "\n".join(f.render() for f in result.new)

    bugged = src.replace("        os.replace(tmp, path)\n", "")
    assert bugged != src, "seeding site moved — update the test"
    bad = tmp_path / "ckpt_bug.py"
    bad.write_text(bugged)
    result = run_lint([str(bad)], baseline_path=None)
    rules = {f.rule for f in result.new}
    assert "res-nonatomic-write" in rules, \
        "\n".join(f.render() for f in result.new)

    # runtime half: the same seed, executed — the commit never lands
    good_mod = _load_copy(clean, "mxnet_tpu._seeded_ckpt_clean")
    target = tmp_path / "artifact.json"
    with good_mod.atomic_path(str(target)) as tmp:
        with open(tmp, "w") as f:
            f.write("{}")
    assert target.exists()                  # pristine copy commits
    target2 = tmp_path / "artifact2.json"
    bad_mod = _load_copy(bad, "mxnet_tpu._seeded_ckpt_bug")
    with bad_mod.atomic_path(str(target2)) as tmp:
        with open(tmp, "w") as f:
            f.write("{}")
    assert not target2.exists(), \
        "seeded copy still committed — the static finding lied"


def test_seeded_dropped_resolve_fails_gate_and_hangs_at_runtime(tmp_path):
    """Acceptance (errorflow): dropping the ``r._resolve("timeout")``
    from a serve/server.py copy's _drop_expired must (a) trip
    err-terminal-outcome statically — the var stays tracked through its
    ``done()`` guard — and (b) reproduce the hang dynamically: an
    expired request dropped by the seeded copy never gets an outcome.
    The pristine copy is clean and resolves."""
    import time
    src = open(os.path.join(REPO, "mxnet_tpu", "serve",
                            "server.py")).read()
    clean = tmp_path / "server_clean.py"
    clean.write_text(src)
    result = run_lint([str(clean)], baseline_path=None)
    assert not result.new, "\n".join(f.render() for f in result.new)

    seed_old = (
        'if r._resolve("timeout",\n'
        '                              reason="deadline expired in %s"'
        ' % stage):\n'
        '                    telemetry.inc("serve.timeouts")\n'
        '                    telemetry.inc("serve.deadline_drops")\n'
        '                    telemetry.event("serve", "timeout",'
        ' stage=stage)\n')
    seed_new = 'telemetry.inc("serve.deadline_drops")\n'
    bugged = src.replace(seed_old, seed_new)
    assert bugged != src, "seeding site moved — update the test"
    bad = tmp_path / "server_bug.py"
    bad.write_text(bugged)
    result = run_lint([str(bad)], baseline_path=None)
    findings = [f for f in result.new if f.rule == "err-terminal-outcome"]
    assert findings, "\n".join(f.render() for f in result.new)
    assert any(f.context.endswith("_drop_expired") for f in findings), \
        [f.context for f in findings]

    # runtime half: an expired request through each copy's batcher drop
    good_mod = _load_copy(clean, "mxnet_tpu.serve._seeded_server_clean")
    r = good_mod.PendingRequest(None, time.monotonic() - 1.0)
    live = good_mod.InferenceServer._drop_expired(None, [r], "queue")
    assert live == [] and r.outcome(0) is not None
    assert r.outcome(0)[0] == "timeout"     # pristine copy resolves

    bad_mod = _load_copy(bad, "mxnet_tpu.serve._seeded_server_bug")
    r = bad_mod.PendingRequest(None, time.monotonic() - 1.0)
    live = bad_mod.InferenceServer._drop_expired(None, [r], "queue")
    assert live == []
    assert r.outcome(0) is None, \
        "seeded copy still resolved — the static finding lied"


def test_changed_closure_covers_errorflow_rules(tmp_path):
    """Satellite: --changed's reverse-dependency closure must pull
    err-*/res-* findings in an IMPORTER of the changed file — the
    write-helper judgment lands at the call site, cross-module."""
    pkg = tmp_path / "pkg"
    pkg.mkdir()
    (pkg / "__init__.py").write_text("")
    (pkg / "helper.py").write_text(
        "class PendingRequest:\n"
        "    def _resolve(self, kind):\n"
        "        return True\n"
        "\n"
        "\n"
        "def dump(path, blob):\n"
        "    with open(path, 'w') as fh:\n"
        "        fh.write(blob)\n")
    (pkg / "worker.py").write_text(
        "from .helper import PendingRequest, dump\n"
        "\n"
        "\n"
        "def publish(blob):\n"
        "    dump('report.json', blob)\n"
        "\n"
        "\n"
        "def admit(q, blob):\n"
        "    req = PendingRequest(blob)\n"
        "    if q.full():\n"
        "        return None\n"
        "    q.put(req)\n"
        "    return req\n")
    relbase = os.path.relpath(str(pkg), REPO).replace(os.sep, "/")
    helper_rel = relbase + "/helper.py"
    worker_rel = relbase + "/worker.py"
    result = run_lint([str(tmp_path)], baseline_path=None,
                      changed_files=[helper_rel])
    assert worker_rel in result.files
    rules = {(f.path, f.rule) for f in result.new}
    assert (worker_rel, "res-nonatomic-write") in rules, sorted(rules)
    assert (worker_rel, "err-terminal-outcome") in rules, sorted(rules)


def test_changed_closure_covers_num_rules(tmp_path):
    """Satellite: --changed's reverse-dependency closure must pull a
    numerics finding in an IMPORTER of the changed file (the dtype-flow
    model resolves helpers cross-module)."""
    pkg = tmp_path / "pkg"
    pkg.mkdir()
    (pkg / "__init__.py").write_text("")
    (pkg / "helper.py").write_text("def scale():\n    return 2\n")
    (pkg / "worker.py").write_text(
        "import jax\n"
        "import jax.numpy as jnp\n"
        "from .helper import scale\n"
        "\n"
        "\n"
        "@jax.jit\n"
        "def reduce_loss(x):\n"
        "    h = x.astype(jnp.bfloat16)\n"
        "    return jnp.sum(h) * scale()\n")
    relbase = os.path.relpath(str(pkg), REPO).replace(os.sep, "/")
    helper_rel = relbase + "/helper.py"
    worker_rel = relbase + "/worker.py"
    result = run_lint([str(tmp_path)], baseline_path=None,
                      changed_files=[helper_rel])
    assert worker_rel in result.files
    rules = {(f.path, f.rule) for f in result.new}
    assert (worker_rel, "num-lowprec-accum") in rules, sorted(rules)


def test_changed_closure_covers_conc_rules(tmp_path):
    """Satellite: --changed's reverse-dependency closure must pull a
    concurrency finding in an IMPORTER of the changed file (the conc
    model is package-wide, not per-file)."""
    pkg = tmp_path / "pkg"
    pkg.mkdir()
    (pkg / "__init__.py").write_text("")
    (pkg / "helper.py").write_text("def payload():\n    return 1\n")
    (pkg / "worker.py").write_text(
        "import threading\n"
        "from .helper import payload\n"
        "\n"
        "_journal = []\n"
        "\n"
        "\n"
        "def _run():\n"
        "    _journal.append(payload())\n"
        "\n"
        "\n"
        "def spawn():\n"
        "    threading.Thread(target=_run, daemon=True).start()\n"
        "\n"
        "\n"
        "def read():\n"
        "    return list(_journal)\n")
    relbase = os.path.relpath(str(pkg), REPO).replace(os.sep, "/")
    helper_rel = relbase + "/helper.py"
    worker_rel = relbase + "/worker.py"
    result = run_lint([str(tmp_path)], baseline_path=None,
                      changed_files=[helper_rel])
    assert worker_rel in result.files
    rules = {(f.path, f.rule) for f in result.new}
    assert (worker_rel, "conc-unguarded-shared-write") in rules, \
        sorted(rules)
    assert (worker_rel, "conc-thread-lifecycle") in rules, \
        sorted(rules)


def test_changed_closure_covers_serve_stop_path(tmp_path):
    """CI/tooling satellite: a change to the serving bucket policy must
    pull the server module — the stop/drain path the conc-* rules gate
    — into the --changed reverse-dependency closure (server.py imports
    buckets.py), so an edit under serve/ can never dodge the
    thread-lifecycle analysis.  Scoped to the serve package: the
    closure property under test is intra-package (server.py imports
    buckets.py) and the full-package changed-run budget is already
    owned by test_changed_mode_matches_full_run."""
    target = "mxnet_tpu/serve/buckets.py"
    result = run_lint([os.path.join(REPO, "mxnet_tpu", "serve")],
                      baseline_path=None, changed_files=[target])
    assert target in result.files
    assert "mxnet_tpu/serve/server.py" in result.files
    assert "mxnet_tpu/serve/__init__.py" in result.files
    # and the closure run stays clean over serve/ like the full gate
    bad = [f for f in result.new
           if f.path.startswith("mxnet_tpu/serve/")]
    assert not bad, "\n".join(f.render() for f in bad)


def test_list_rules_groups_by_family():
    env = dict(os.environ, PYTHONPATH=REPO + os.pathsep
               + os.environ.get("PYTHONPATH", ""))
    res = subprocess.run(
        [sys.executable, "-m", "tools.lint", "--list-rules"],
        cwd=REPO, env=env, capture_output=True, text=True)
    assert res.returncode == 0, res.stderr
    lines = res.stdout.splitlines()
    assert "concurrency:" in lines
    fam_of = {}
    fam = None
    for line in lines:
        if line.endswith(":") and not line.startswith(" "):
            fam = line[:-1]
        elif line.strip():
            fam_of[line.split()[0]] = fam
    for rule in ("conc-lock-order", "conc-unguarded-shared-write",
                 "conc-blocking-under-lock", "conc-thread-lifecycle",
                 "conc-condition-wait-unlooped"):
        assert fam_of.get(rule) == "concurrency", (rule, fam_of.get(rule))
    assert fam_of.get("shard-axis-unknown") == "sharding"
    assert "numerics:" in lines
    for rule in ("num-implicit-promotion", "num-lowprec-accum",
                 "num-unstable-exp", "num-master-dtype",
                 "num-collective-dtype", "num-const-downcast"):
        assert fam_of.get(rule) == "numerics", (rule, fam_of.get(rule))
    assert "errorflow:" in lines
    for rule in ("err-swallowed-exception", "res-nonatomic-write",
                 "res-leaked-handle", "err-terminal-outcome",
                 "err-incident-trigger"):
        assert fam_of.get(rule) == "errorflow", (rule, fam_of.get(rule))


def test_stale_suppression_audit(tmp_path):
    """A suppression whose rule fires is kept quiet; one whose rule no
    longer fires on its line is flagged by --audit-suppressions (and
    stays invisible without the flag — the tier-1 gate is unchanged)."""
    src = (
        "import jax\n"
        "\n"
        "\n"
        "@jax.jit\n"
        "def f(x):\n"
        "    a = float(x)  # graftlint: disable=trace-host-sync -- used\n"
        "    b = x + 1  # graftlint: disable=trace-host-sync -- stale\n"
        "    c = float(x)  # graftlint: disable=trace-host-sync,"
        "retrace-jit-in-loop -- half\n"
        "    return a + b + c\n")
    p = tmp_path / "mod.py"
    p.write_text(src)
    quiet = run_lint([str(p)], baseline_path=None)
    assert not quiet.new, [f.render() for f in quiet.new]
    audited = run_lint([str(p)], baseline_path=None,
                       audit_suppressions=True)
    got = [(f.rule, f.line) for f in audited.new]
    # line 7: fully stale; line 8: multi-rule suppression whose
    # trace-host-sync half is live but whose retrace half is dead —
    # staleness is per RULE, not per comment
    assert got == [("lint-stale-suppression", 7),
                   ("lint-stale-suppression", 8)], got
    stale_msgs = [f.message for f in audited.new]
    assert any("retrace-jit-in-loop" in m and "trace-host-sync" not in m
               for m in stale_msgs), stale_msgs
    # a rules allowlist disables the audit (unrelated suppressions
    # would read as stale)
    filtered = run_lint([str(p)], baseline_path=None, rules=["pallas-"],
                        audit_suppressions=True)
    assert not filtered.new


def test_package_suppressions_not_stale(package_scan):
    """Satellite: every inline suppression in mxnet_tpu/ must still
    suppress a live finding — the audit re-validates what PR 4
    grandfathered by hand."""
    stale = [f for f in package_scan.new
             if f.rule == "lint-stale-suppression"]
    assert not stale, "\n".join(f.render() for f in stale)


def test_changed_mode_matches_full_run(package_scan):
    """Acceptance: a --changed run over one file reports exactly the
    findings a full-package run reports for that file (the index is
    still cross-file, only the checker pass narrows), inside the 10 s
    budget."""
    import time
    target = "mxnet_tpu/parallel/collectives.py"
    t0 = time.time()
    fast = run_lint([os.path.join(REPO, "mxnet_tpu")],
                    baseline_path=None, changed_files=[target],
                    audit_suppressions=True)
    elapsed = time.time() - t0
    assert target in fast.files
    full = package_scan

    def in_file(result):
        return sorted((f.rule, f.line) for f in
                      result.new + result.suppressed
                      if f.path == target)

    assert in_file(fast) == in_file(full)
    # the closure pulls in importers of collectives.py, but not the
    # whole package
    assert len(fast.files) < len(full.files)
    # budget 12 s (was 10): PR 11's checkpoint.py imports collectives
    # (padded_size), growing this file's reverse-dependency closure by
    # one threaded module the conc checkers walk
    assert elapsed < 12.0, "changed-mode run took %.1fs" % elapsed


def test_changed_closure_covers_telemetry_collect():
    """ISSUE 18 satellite: the cross-process collector and the flight
    recorder ride the changed-mode closure — an edit to telemetry.py
    (whose Histogram dict geometry both consume) must re-lint them —
    and a changed-run over the collector itself stays clean."""
    from tools.lint.core import collect_files, ModuleInfo
    from tools.lint.jitgraph import PackageIndex
    mods = []
    for p in collect_files([os.path.join(REPO, "mxnet_tpu")]):
        rel = os.path.relpath(p, REPO).replace(os.sep, "/")
        mods.append(ModuleInfo(p, rel, open(p).read()))
    idx = PackageIndex(mods)
    closure = idx.reverse_dependency_closure({"mxnet_tpu/telemetry.py"})
    assert "mxnet_tpu/telemetry_collect.py" in closure
    assert "mxnet_tpu/flight_recorder.py" in closure
    # and the collector passes the gate when IT is the changed file
    target = "mxnet_tpu/telemetry_collect.py"
    result = run_lint([os.path.join(REPO, "mxnet_tpu")],
                      baseline_path=None, changed_files=[target])
    assert target in result.files
    bad = [f for f in result.new if f.path == target]
    assert not bad, bad


def test_reverse_dependency_closure(tmp_path):
    pkg = tmp_path / "pkg"
    pkg.mkdir()
    (pkg / "__init__.py").write_text("from . import a\n")
    (pkg / "a.py").write_text("from .b import f\n")
    (pkg / "b.py").write_text("def f():\n    return 1\n")
    (pkg / "c.py").write_text("import os\n")
    from tools.lint.core import collect_files, ModuleInfo
    from tools.lint.jitgraph import PackageIndex
    mods = []
    for p in collect_files([str(tmp_path)]):
        rel = os.path.relpath(p, str(tmp_path))
        mods.append(ModuleInfo(p, rel, open(p).read()))
    idx = PackageIndex(mods)
    got = idx.reverse_dependency_closure({"pkg/b.py"})
    assert got == {"pkg/b.py", "pkg/a.py", "pkg/__init__.py"}, got
    assert idx.reverse_dependency_closure({"pkg/c.py"}) == {"pkg/c.py"}


def test_rule_catalog_documented():
    """Every rule id must appear in docs/LINTING.md."""
    doc = open(os.path.join(REPO, "docs", "LINTING.md")).read()
    for rule in all_rules():
        assert rule in doc, "rule %s missing from docs/LINTING.md" % rule
