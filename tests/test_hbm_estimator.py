"""Static per-chip HBM estimator (tools.lint.hbm) vs the runtime
telemetry gauges.

Acceptance (ISSUE 7): the static estimate for the PR-5 ZeRO bench
config (123 -> 2048 -> 1024 -> 10 fp32 MLP, Adam, 8-way dp mesh) must
agree with the runtime ``parallel.optimizer_state_bytes_per_chip``
gauge within 10% for BOTH the replicated and the dp-sharded layout.
The estimator is fed hand-written shapes (not runtime metadata), so the
two numbers are computed independently.
"""
import numpy as onp
import pytest

import jax

import mxnet_tpu as mx
from mxnet_tpu import gluon, parallel, telemetry
from mxnet_tpu.gluon import nn

from tools.lint import hbm


@pytest.fixture
def mesh8():
    assert len(jax.devices()) == 8, "conftest must force 8 CPU devices"
    m = parallel.device_mesh((8,), ("dp",))
    old = parallel.get_mesh()
    parallel.set_mesh(m)
    yield m
    parallel.set_mesh(old)


def test_padded_size_matches_collectives():
    """The estimator's padding arithmetic IS the ZeRO layout's — drift
    here silently skews every estimate."""
    from mxnet_tpu.parallel import collectives as coll
    for n in (1, 2, 3, 7, 100, 1000, 2048 * 123 + 5):
        for a in (1, 2, 4, 8, 16):
            assert hbm.padded_size(n, a) == coll.padded_size(n, a), (n, a)


def test_leaf_arithmetic():
    assert hbm.dtype_itemsize("float32") == 4
    assert hbm.dtype_itemsize("bfloat16") == 2
    # (1000,) over 8 chips: padded to 1000->1000? no: 125*8=1000 exact;
    # (1001,) pads to 1008
    assert hbm.leaf_bytes_per_chip((1000,), "float32",
                                   hbm.DP_SHARDED, 8) == 1000 * 4 // 8
    assert hbm.leaf_bytes_per_chip((7, 11, 13), "float32",
                                   hbm.DP_SHARDED, 8) == \
        hbm.padded_size(7 * 11 * 13, 8) * 4 // 8
    assert hbm.leaf_bytes_per_chip((1000,), "float32",
                                   hbm.REPLICATED, 8) == 4000
    # multi-precision: a bf16 weight carries an fp32 master as an extra
    # leaf and its state leaves are fp32
    est = hbm.estimate_step_hbm([((10,), "bfloat16")], axis_size=4,
                                state_leaves=2, shard_optimizer=True,
                                multi_precision=True)
    assert est["opt_state_bytes"] == 3 * hbm.padded_size(10, 4) * 4 // 4


def _bench_net(hidden=2048):
    """The PR-5 zero_sharded_update bench leg (bench.py): 123-feature
    input, Dense(hidden)->Dense(hidden//2)->Dense(10), fp32, Adam."""
    onp.random.seed(7)
    mx.random.seed(7)
    net = nn.HybridSequential()
    net.add(nn.Dense(hidden, activation="relu"),
            nn.Dense(hidden // 2, activation="relu"), nn.Dense(10))
    net.initialize(mx.init.Xavier())
    x = mx.nd.array(onp.random.rand(256, 123).astype("float32"))
    y = mx.nd.array(onp.random.randint(0, 10, (256,)).astype("float32"))
    net(x)
    return net, x, y


def _bench_param_spec(hidden=2048):
    """The same architecture written down statically — Dense weight is
    (units, in_units), bias (units,)."""
    dims = [(hidden, 123), (hidden // 2, hidden), (10, hidden // 2)]
    spec = []
    for units, in_units in dims:
        spec.append(((units, in_units), "float32"))
        spec.append(((units,), "float32"))
    return spec


@pytest.mark.parametrize("shard", [False, True])
def test_static_estimate_matches_runtime_gauge(mesh8, shard):
    telemetry.reset()
    net, x, y = _bench_net()
    L = gluon.loss.SoftmaxCrossEntropyLoss()
    step = parallel.DataParallelStep(
        net, lambda o, l: L(o, l), mx.optimizer.Adam(learning_rate=1e-3),
        mesh=mesh8, shard_optimizer=shard)
    gauge = telemetry.snapshot()["gauges"][
        "parallel.optimizer_state_bytes_per_chip"]
    assert gauge > 0
    est = hbm.estimate_step_hbm(_bench_param_spec(), axis_size=8,
                                state_leaves=2, shard_optimizer=shard)
    assert abs(est["opt_state_bytes"] - gauge) <= 0.10 * gauge, \
        (est["opt_state_bytes"], gauge)
    # the step's own journaling helper rides the same arithmetic
    m = step.hbm_estimate()
    assert m is not None
    assert m["opt_state_bytes_per_chip"] == est["opt_state_bytes"]
    assert m["n_shards"] == (8 if shard else 1)
    telemetry.reset()


def test_hbm_event_journaled_per_program(mesh8):
    """Every compiled signature journals ONE hbm/estimate event whose
    state bytes match the construction-time gauge; a cache hit journals
    nothing."""
    telemetry.reset()
    onp.random.seed(3)
    mx.random.seed(3)
    net = nn.HybridSequential()
    net.add(nn.Dense(7, activation="relu"), nn.Dense(4))
    net.initialize(mx.init.Xavier())
    x = mx.nd.array(onp.random.rand(16, 9).astype("float32"))
    y = mx.nd.array(onp.random.randint(0, 4, (16,)).astype("float32"))
    net(x)
    L = gluon.loss.SoftmaxCrossEntropyLoss()
    step = parallel.DataParallelStep(
        net, lambda o, l: L(o, l),
        mx.optimizer.SGD(learning_rate=0.1, momentum=0.9),
        mesh=mesh8, shard_optimizer=True)
    step(x, y).asnumpy()

    def hbm_events():
        snap = telemetry.snapshot(events=4096)
        return snap, [e for e in snap["events"]
                      if e["kind"] == "hbm" and e["name"] == "estimate"]

    snap, evs = hbm_events()
    assert len(evs) == 1
    ev = evs[0]
    assert ev["mode"] == "call"
    assert ev["program"].startswith("DataParallelStep[")
    assert ev["opt_state_bytes_per_chip"] == \
        snap["gauges"]["parallel.optimizer_state_bytes_per_chip"]
    assert ev["activation_bytes_per_chip"] > 0
    assert ev["total_bytes_per_chip"] >= ev["params_bytes_per_chip"]
    step(x, y).asnumpy()          # same signature: cached, no new event
    _, evs = hbm_events()
    assert len(evs) == 1
    telemetry.reset()
