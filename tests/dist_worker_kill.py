"""Worker body for the chaos / failure-detection matrix (reference
``include/mxnet/kvstore.h:353`` get_num_dead_node over ps-lite
heartbeats; here the jax coordination service's liveness view plus the
elastic runtime on top of it).

Modes, selected by ``MXTPU_KILL_MODE``:

* (default) ``liveness`` — 3 processes: rank 2 dies (os._exit, no
  cleanup — a crash, not a clean shutdown) right after joining; ranks
  0 and 1 must observe ``kv.num_dead_node()`` transition 0 -> 1.
* ``elastic`` — 3 processes TRAINING: the chaos ``kill_worker`` fault
  (armed via MXNET_TPU_CHAOS) preempts rank 2 mid-epoch; the
  survivors' ``ElasticContext`` detects the departure through the KV
  heartbeat liveness view, re-forms the mesh over their surviving
  devices, and training resumes mid-epoch with the loss still
  decreasing.  (Cross-process collectives are version-gated on this
  backend — each worker trains its replica on its local mesh; the
  cross-extent ZeRO re-shard math is covered in-process by
  tests/test_elastic.py.)
* ``ckpt_phase1`` — N processes train with an async CheckpointManager
  writing into MXTPU_CKPT_DIR, then die abruptly (os._exit, no
  shutdown barrier — a coordinator loss).
* ``ckpt_phase2`` — launched as a NEW, smaller job: restores from the
  manifest the dead job left behind, verifies the state bitwise
  against a deterministic recomputation, and keeps training.
"""
import os
import sys
import time

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["MXNET_TPU_RECOVERABLE"] = "1"      # survivors keep running
os.environ.setdefault("MXNET_TPU_HEARTBEAT_TIMEOUT", "10")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _exit_ordered(kv, rank, expect_done):
    """os._exit with leader-last ordering: rank 0's process hosts the
    coordination service, and on this jax its death fatally terminates
    (SIGABRT) any peer still running, regardless of recoverability —
    so non-leader ranks drop a done-key and exit first, and rank 0
    waits for ``expect_done`` of them (dead ranks never write one)
    before pulling the coordinator down."""
    from jax._src import distributed as _dist
    client = getattr(_dist.global_state, "client", None)
    if client is None or kv.num_workers <= 1:
        os._exit(0)
    if rank != 0:
        client.key_value_set("mxtpu/done/%d" % rank, "1")
        os._exit(0)
    deadline = time.time() + 30
    got = set()
    while len(got) < expect_done and time.time() < deadline:
        for r in range(1, kv.num_workers):
            if r in got:
                continue
            try:
                client.blocking_key_value_get("mxtpu/done/%d" % r, 100)
                got.add(r)
            except Exception:
                pass
    time.sleep(0.5)     # let the peers' os._exit land
    os._exit(0)


def _build_step(shard=True):
    import numpy as onp
    import jax
    import mxnet_tpu as mx
    from mxnet_tpu import gluon, parallel
    from mxnet_tpu.gluon import nn
    onp.random.seed(42)
    mx.random.seed(42)
    net = nn.HybridSequential()
    net.add(nn.Dense(7, activation="relu"), nn.Dense(4))
    net.initialize(mx.init.Xavier())
    X = onp.random.RandomState(0).randn(16, 9).astype("float32")
    Y = onp.random.RandomState(1).randint(0, 4, 16).astype("float32")
    net(mx.nd.array(X))
    L = gluon.loss.SoftmaxCrossEntropyLoss()
    # this worker's LOCAL devices only: cross-process computations are
    # version-gated on the CPU backend (see _cpu_multiprocess in
    # test_dist_multiprocess.py) — the elastic protocol under test is
    # process-level detection + re-formation, not DCN collectives
    mesh = parallel.device_mesh(devices=jax.local_devices())
    step = parallel.DataParallelStep(
        net, lambda o, l: L(o, l),
        mx.optimizer.SGD(learning_rate=0.1, momentum=0.9), mesh=mesh,
        shard_optimizer=shard)
    batch = (mx.nd.array(X), mx.nd.array(Y))
    return step, batch


def main_liveness():
    from mxnet_tpu import kvstore

    kv = kvstore.create("dist_sync")
    if kv.rank == 2:
        # crash without any coordination-service cleanup
        sys.stdout.flush()
        os._exit(0)

    # freshly joined: everyone alive (allow the service a beat to settle)
    assert kv.num_dead_node(timeout=5) in (0, 1)

    deadline = time.time() + 90
    seen_dead = 0
    while time.time() < deadline:
        seen_dead = kv.num_dead_node(timeout=5)
        if seen_dead >= 1:
            break
        time.sleep(1.0)
    assert seen_dead >= 1, "rank 2 died but num_dead_node stayed 0"
    print("KILL-WORKER %d OK (dead=%d)" % (kv.rank, seen_dead))
    sys.stdout.flush()
    # skip jax.distributed's atexit shutdown barrier: it needs EVERY
    # task to check in, and rank 2 is dead — exactly the condition this
    # test creates — so a clean interpreter exit would SIGABRT on the
    # unreachable barrier.  The assertion above is the test.
    os._exit(0)


def main_elastic():
    import jax
    from mxnet_tpu import flight_recorder, kvstore, telemetry
    from mxnet_tpu.parallel import chaos
    from mxnet_tpu.parallel.elastic import ElasticContext

    kv = kvstore.create("dist_sync")
    rank = kv.rank
    # align this rank's journal onto rank 0's wall clock so the parent
    # can merge every survivor's export into ONE de-skewed timeline
    from jax._src import distributed as _dist
    client = getattr(_dist.global_state, "client", None)
    if client is not None:
        telemetry.sync_clock(client, rank)
    chaos.install_from_env(rank=rank)
    step, batch = _build_step()
    ctx = ElasticContext(step, kvstore=kv,
                         liveness=lambda: kv.num_dead_node(timeout=1),
                         world_size=kv.num_workers)

    losses = []
    detected = None
    deadline = time.time() + 90
    i = 0
    while time.time() < deadline:
        chaos.maybe_kill(step=i, rank=rank)     # rank 2 dies mid-epoch
        losses.append(float(step(*batch).asscalar()))
        ev = ctx.maybe_recover(step=i)
        if ev is not None and ev["kind"] == "departed":
            detected = ev
            # resume mid-epoch on the re-formed mesh: a few more
            # steps, still converging
            for j in range(3):
                losses.append(float(step(*batch).asscalar()))
            break
        i += 1
        time.sleep(0.25)

    assert detected is not None, "survivor never detected the departure"
    assert detected["world_to"] == detected["world_from"] - 1
    assert losses[-1] < losses[0], "loss stopped decreasing: %r" % losses
    events = telemetry.snapshot(events=256)["events"]
    kinds = {(e["kind"], e["name"]) for e in events}
    assert ("elastic", "detect") in kinds
    assert ("elastic", "reshard") in kinds
    spans = {e["name"] for e in events if e["kind"] == "span"}
    assert {"elastic.detect", "elastic.reshard", "elastic.resume"} \
        <= spans, spans
    # the departure froze a flight-recorder bundle on this survivor
    inc_base = flight_recorder.incident_dir()
    bundles = [] if not os.path.isdir(inc_base) else \
        [d for d in os.listdir(inc_base)
         if d.startswith("incident-") and d.endswith("-elastic_departure")]
    assert bundles, "survivor dumped no elastic_departure bundle"
    # per-rank journal export for the parent's telemetry_collect merge
    out_dir = os.environ.get("MXTPU_TELEMETRY_DIR")
    if out_dir:
        telemetry.export_jsonl(
            os.path.join(out_dir, "telemetry-rank%d.jsonl" % rank))
    print("ELASTIC-WORKER %d OK (world %d->%d, loss %.4f->%.4f)"
          % (rank, detected["world_from"], detected["world_to"],
             losses[0], losses[-1]))
    sys.stdout.flush()
    # rank 2 is dead: skip the shutdown barrier; survivors leave
    # leader-last (only the live peers can write done-keys)
    _exit_ordered(kv, rank, expect_done=detected["world_to"] - 1)


def main_ckpt_phase1():
    from mxnet_tpu import checkpoint, kvstore

    kv = kvstore.create("dist_sync")
    ckpt_dir = os.environ["MXTPU_CKPT_DIR"]
    step, batch = _build_step()
    mgr = checkpoint.CheckpointManager(
        ckpt_dir, step, every_n_steps=2, rank=kv.rank,
        world_size=kv.num_workers)
    mgr.attach()
    for _ in range(6):
        step(*batch)
    assert mgr.flush(30.0), "checkpoint writer did not drain"
    if kv.rank == 0:
        man = checkpoint.read_manifest(ckpt_dir)
        assert man is not None and man["step"] == 6, man
    print("CKPT-PHASE1 %d OK" % kv.rank)
    sys.stdout.flush()
    # die abruptly — no manager close, no shutdown barrier: the
    # coordinator is "lost" and only the committed manifest survives
    # (leader-last, so peers are not SIGABRTed mid-flush)
    _exit_ordered(kv, kv.rank, expect_done=kv.num_workers - 1)


def main_ckpt_phase2():
    import numpy as onp
    from mxnet_tpu import checkpoint, kvstore

    kv = kvstore.create("dist_sync")   # the RESTARTED (smaller) job
    ckpt_dir = os.environ["MXTPU_CKPT_DIR"]
    step, batch = _build_step()
    restored = checkpoint.restore_latest(ckpt_dir, step)
    assert restored == 6, restored
    # phase 1 was deterministic (fixed seeds): recompute its 6 steps
    # fresh and the restored state must match BITWISE
    ref, _ = _build_step()
    for _ in range(6):
        ref(*batch)
    def canonical(st):
        # graph-order slots (name-sorted order flips across gluon's
        # auto-naming digit boundaries; see DataParallelStep._param_order)
        rank = {pi: k for k, pi in enumerate(st._param_order())}
        return sorted(range(len(st._opt_states)),
                      key=lambda s: rank[st._trainable[s]])

    for qa, qb in zip(canonical(ref), canonical(step)):
        for la, lb in zip(ref._materialize_slot(qa),
                          step._materialize_slot(qb)):
            onp.testing.assert_array_equal(la, lb)
    # and the restarted job keeps training
    l0 = float(step(*batch).asscalar())
    l1 = float(step(*batch).asscalar())
    assert l1 < l0
    print("CKPT-PHASE2 %d OK (restored step %d)" % (kv.rank, restored))
    sys.stdout.flush()
    os._exit(0)


def main():
    import jax
    jax.config.update("jax_platforms", "cpu")
    from mxnet_tpu import parallel

    parallel.initialize()
    mode = os.environ.get("MXTPU_KILL_MODE", "liveness")
    if mode == "elastic":
        main_elastic()
    elif mode == "ckpt_phase1":
        main_ckpt_phase1()
    elif mode == "ckpt_phase2":
        main_ckpt_phase2()
    else:
        assert jax.process_count() == 3
        main_liveness()


if __name__ == "__main__":
    main()
