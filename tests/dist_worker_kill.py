"""Worker body for the liveness / failure-detection test (reference
``include/mxnet/kvstore.h:353`` get_num_dead_node over ps-lite heartbeats;
here the jax coordination service's live-nodes view).

3 processes: rank 2 dies (os._exit, no cleanup — a crash, not a clean
shutdown) right after joining; ranks 0 and 1 must observe
``kv.num_dead_node()`` transition 0 -> 1 within the polling window.
"""
import os
import sys
import time

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["MXNET_TPU_RECOVERABLE"] = "1"      # survivors keep running
os.environ["MXNET_TPU_HEARTBEAT_TIMEOUT"] = "10"  # fast failure detection
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    import jax
    jax.config.update("jax_platforms", "cpu")
    from mxnet_tpu import kvstore, parallel

    parallel.initialize()
    assert jax.process_count() == 3
    kv = kvstore.create("dist_sync")

    if kv.rank == 2:
        # crash without any coordination-service cleanup
        sys.stdout.flush()
        os._exit(0)

    # freshly joined: everyone alive (allow the service a beat to settle)
    assert kv.num_dead_node(timeout=5) in (0, 1)

    deadline = time.time() + 90
    seen_dead = 0
    while time.time() < deadline:
        seen_dead = kv.num_dead_node(timeout=5)
        if seen_dead >= 1:
            break
        time.sleep(1.0)
    assert seen_dead >= 1, "rank 2 died but num_dead_node stayed 0"
    print("KILL-WORKER %d OK (dead=%d)" % (kv.rank, seen_dead))
    sys.stdout.flush()
    # skip jax.distributed's atexit shutdown barrier: it needs EVERY
    # task to check in, and rank 2 is dead — exactly the condition this
    # test creates — so a clean interpreter exit would SIGABRT on the
    # unreachable barrier.  The assertion above is the test.
    os._exit(0)


if __name__ == "__main__":
    main()
