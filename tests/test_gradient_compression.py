"""2-bit error-feedback gradient compression.

Parity target: reference ``src/kvstore/gradient_compression.h:38-132`` and
the dist-push wiring (``kvstore_dist.h:361``); bit-exact aggregation across
workers is what ``tests/nightly/dist_sync_kvstore.py:30-60`` checks there."""
import jax.numpy as jnp
import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu.base import MXNetError
from mxnet_tpu.gradient_compression import (
    GradientCompression, pack_2bit, quantize_2bit, unpack_2bit)


def test_shim_reexports_new_home():
    """mxnet_tpu.gradient_compression is a deprecation shim: the
    jnp-pure kernels live in mxnet_tpu.parallel.compression and both
    import paths hand back the SAME objects."""
    from mxnet_tpu.parallel import compression as C
    import mxnet_tpu.gradient_compression as shim
    assert shim.quantize_2bit is C.quantize_2bit
    assert shim.dequantize_2bit is C.dequantize_2bit
    assert shim.pack_2bit is C.pack_2bit
    assert shim.unpack_2bit is C.unpack_2bit
    # the legacy module no longer ships an ad-hoc __main__ self-test;
    # this file IS the test suite for the kernels
    import inspect
    src = inspect.getsource(shim)
    assert "_self_test" not in src and "__main__" not in src


def test_quantize_values_and_residual():
    g = jnp.asarray([0.7, -0.6, 0.2, -0.1, 0.0], jnp.float32)
    q, r = quantize_2bit(g, jnp.zeros_like(g), 0.5)
    assert onp.allclose(q, [0.5, -0.5, 0.0, 0.0, 0.0])
    assert onp.allclose(r, [0.2, -0.1, 0.2, -0.1, 0.0], atol=1e-6)


def test_pack_unpack_roundtrip():
    rs = onp.random.RandomState(0)
    g = jnp.asarray(rs.randn(101).astype("float32"))
    q, _ = quantize_2bit(g, jnp.zeros_like(g), 0.5)
    packed, n = pack_2bit(q, 0.5)
    assert packed.dtype == jnp.uint32
    assert packed.shape[0] == (101 + 15) // 16  # 16x wire reduction
    assert onp.array_equal(unpack_2bit(packed, n, 0.5), q)


def test_error_feedback_conserves_mean():
    """Constant gradient 0.1 with threshold 0.5: individual pushes send
    mostly zeros, but the residual carries the error so the transmitted
    mean over many steps equals the true gradient."""
    gc = GradientCompression({"type": "2bit", "threshold": 0.5})
    g = jnp.full((8,), 0.1, jnp.float32)
    total = jnp.zeros_like(g)
    for _ in range(50):
        total = total + gc.compress("k", g)
    assert onp.allclose(total / 50, g, atol=0.5 / 50 + 1e-6)


def test_bad_params_rejected():
    with pytest.raises(MXNetError):
        GradientCompression({"type": "1bit"})
    with pytest.raises(MXNetError):
        GradientCompression({"type": "2bit", "threshold": -1.0})
    with pytest.raises(MXNetError):
        GradientCompression({"type": "2bit", "bogus": 3})


def test_kvstore_local_rejects_compression():
    kv = mx.kv.create("local")
    with pytest.raises(MXNetError):
        kv.set_gradient_compression({"type": "2bit", "threshold": 0.5})


def test_kvstore_push_applies_compression():
    kv = mx.kv.create("device")
    kv.set_gradient_compression({"type": "2bit", "threshold": 0.5})
    w = mx.nd.zeros((4,))
    kv.init("w", w)
    kv.push("w", mx.nd.array(onp.array([0.7, 0.2, -0.8, 0.0], "float32")))
    out = mx.nd.zeros((4,))
    kv.pull("w", out=out)
    # no updater: store receives the quantized gradient
    assert onp.allclose(out.asnumpy(), [0.5, 0.0, -0.5, 0.0])
    # second push: residual [0.2, 0.2, -0.3, 0] + grad crosses threshold
    kv.push("w", mx.nd.array(onp.array([0.4, 0.2, -0.1, 0.0], "float32")))
    kv.pull("w", out=out)
    assert onp.allclose(out.asnumpy(), [0.5, 0.0, 0.0, 0.0])


def test_kvstore_tpu_compressed_training_descends():
    """Compression composes with the mesh all-reduce push and an updater;
    SGD on a quadratic still converges thanks to error feedback."""
    kv = mx.kv.create("tpu")
    kv.set_gradient_compression({"type": "2bit", "threshold": 0.05})
    opt = mx.optimizer.SGD(learning_rate=0.5)
    kv.set_optimizer(opt)
    target = onp.array([0.3, -0.4, 0.25, 0.0], "float32")
    w = mx.nd.zeros((4,))
    kv.init(0, w)
    cur = mx.nd.zeros((4,))
    for _ in range(60):
        kv.pull(0, out=cur)
        grad = mx.nd.array(cur.asnumpy() - target)  # dL/dw for 0.5||w-t||^2
        kv.push(0, grad)
    kv.pull(0, out=cur)
    assert onp.allclose(cur.asnumpy(), target, atol=0.06), cur.asnumpy()
