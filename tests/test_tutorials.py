"""Execute every python code block in docs/tutorials/*.md (reference
``tests/tutorials/test_tutorials.py`` runs its notebook corpus the same
way: docs that don't run are docs that rot).

Blocks within one tutorial share a namespace, in order — they are one
narrative program.  Assertions inside the blocks are the checks.
"""
import os
import re
import glob

import pytest

_DOCS = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "docs", "tutorials")
_TUTORIALS = sorted(glob.glob(os.path.join(_DOCS, "*.md")))

_BLOCK_RE = re.compile(r"```python\n(.*?)```", re.DOTALL)


def _blocks(path):
    with open(path) as f:
        return _BLOCK_RE.findall(f.read())


def test_tutorials_exist():
    assert len(_TUTORIALS) >= 7, _TUTORIALS


@pytest.mark.parametrize(
    "path",
    [pytest.param(p, marks=pytest.mark.slow)
     if os.path.basename(p).startswith("07_") else p
     for p in _TUTORIALS],   # 07_performance compiles bench-scale steps (~9 s); content is covered by bench protocol tests
    ids=[os.path.basename(p) for p in _TUTORIALS])
def test_tutorial_executes(path):
    blocks = _blocks(path)
    assert blocks, "tutorial %s has no python blocks" % path
    ns = {"__name__": "__tutorial__"}
    for i, src in enumerate(blocks):
        try:
            exec(compile(src, "%s[block %d]" % (os.path.basename(path), i),
                         "exec"), ns)
        except Exception as e:
            raise AssertionError(
                "%s block %d failed: %r\n---\n%s" % (
                    os.path.basename(path), i, e, src)) from e
