"""Elastic, preemption-tolerant training (parallel/elastic.py + chaos.py).

The in-process half of the chaos matrix, on the virtual 8-device CPU
mesh: a "lost chip" is simulated by re-forming the mesh over a device
subset, which exercises the REAL re-shard math — flat zero-padded ZeRO
state (fp32 master included) migrating between dp extents — the part a
multiprocess kill test cannot cover deterministically.  The
multiprocess protocol half (heartbeat detection across real OS
processes, manifest-based restart) lives in test_dist_multiprocess.py.
"""
import os
import time

import numpy as onp
import pytest

import jax

import mxnet_tpu as mx
from mxnet_tpu import gluon, parallel, telemetry
from mxnet_tpu.base import MXNetError
from mxnet_tpu.gluon import nn
from mxnet_tpu.gluon import loss as gloss
from mxnet_tpu.parallel import chaos
from mxnet_tpu.parallel.elastic import ElasticContext, kv_retry

# 9 in / 7 hidden: every leaf size is coprime with the dp extents used
# here, so 8->4->2 re-sharding always crosses different pad widths
_X = onp.random.RandomState(0).randn(16, 9).astype("float32")
_Y = onp.random.RandomState(1).randint(0, 4, 16).astype("float32")


@pytest.fixture
def mesh8():
    assert len(jax.devices()) == 8, "conftest must force 8 CPU devices"
    m = parallel.device_mesh((8,), ("dp",))
    old = parallel.get_mesh()
    parallel.set_mesh(m)
    yield m
    parallel.set_mesh(old)


@pytest.fixture(autouse=True)
def _clear_chaos():
    chaos.clear()
    yield
    chaos.clear()


def _build_step(mesh, shard=True, optimizer=None, bf16=False):
    onp.random.seed(42)
    mx.random.seed(42)
    net = nn.HybridSequential()
    net.add(nn.Dense(7, activation="relu"), nn.Dense(4))
    net.initialize(mx.init.Xavier())
    net(mx.nd.array(_X))
    if bf16:
        net.cast("bfloat16")
    L = gloss.SoftmaxCrossEntropyLoss()
    opt = optimizer() if optimizer else mx.optimizer.SGD(
        learning_rate=0.1, momentum=0.9)
    step = parallel.DataParallelStep(net, lambda o, l: L(o, l), opt,
                                     mesh=mesh, shard_optimizer=shard)
    return net, step


def _run(step, k):
    return [float(step(mx.nd.array(_X), mx.nd.array(_Y)).asscalar())
            for _ in range(k)]


# ---------------------------------------------------------------------------
# mesh re-formation + ZeRO re-shard
# ---------------------------------------------------------------------------

def test_reshard_8_to_4_loss_parity(mesh8):
    """Kill half the mesh mid-epoch: survivors re-form, ZeRO state
    re-shards 8->4, and the loss trajectory matches an uninterrupted
    run (the update math is dp-extent-invariant)."""
    net_a, st_a = _build_step(mesh8, True)
    losses_a = _run(st_a, 6)

    net_b, st_b = _build_step(mesh8, True)
    losses_b = _run(st_b, 3)
    ctx = ElasticContext(st_b, liveness=lambda: 0)
    mesh4 = ctx.reform(devices=jax.devices()[:4], step=3)
    assert dict(mesh4.shape) == {"dp": 4}
    losses_b += _run(st_b, 3)
    onp.testing.assert_allclose(losses_a, losses_b, rtol=1e-5, atol=1e-6)
    # state really lives at the new extent: flat, padded to 4, 1/4/chip
    assert all(st_b._shard_slots)
    leaf = st_b._opt_states[0][0]
    assert leaf.ndim == 1 and leaf.shape[0] % 4 == 0
    assert leaf.addressable_shards[0].data.shape[0] == leaf.shape[0] // 4
    # journal carries the transition
    ev = [e for e in telemetry.snapshot(events=256)["events"]
          if e["kind"] == "elastic" and e["name"] == "reshard"]
    assert ev and ev[-1]["world_from"] == 8 and ev[-1]["world_to"] == 4
    assert ev[-1]["bytes"] > 0 and ev[-1]["dur_ms"] >= 0


def test_reshard_preserves_fp32_master_bitwise(mesh8):
    """The fp32 master (state leaf 0 under multi_precision) must
    migrate bitwise through a reshard — and the next step must NOT
    resync it from the bf16 weight (which would round away exactly the
    precision the master keeps)."""
    mk = lambda: mx.optimizer.Adam(learning_rate=1e-3,  # noqa: E731
                                   multi_precision=True)
    net_b, st_b = _build_step(mesh8, True, optimizer=mk, bf16=True)
    _run(st_b, 3)
    masters = [st_b._materialize_slot(s)[0].copy()
               for s in range(len(st_b._opt_states))]
    ElasticContext(st_b, liveness=lambda: 0).reform(
        devices=jax.devices()[:4])
    for s, before in enumerate(masters):
        onp.testing.assert_array_equal(before,
                                       st_b._materialize_slot(s)[0])
    # the resync-suppression pin: the next dispatch rebuilds the master
    # from the half-width weight whenever _mp_written doesn't match the
    # (re-placed) weight object — reshard must re-pin it, or the fp32
    # truth silently degrades to a bf16 round-trip
    for slot, i in enumerate(st_b._trainable):
        assert st_b._mp_written[slot] is st_b._params[i]._data._data
    _run(st_b, 1)   # masters advance from their fp32 values, not bf16
    for s, before in enumerate(masters):
        after = st_b._materialize_slot(s)[0]
        assert after.dtype == onp.float32
        assert not onp.array_equal(before, after), "master never updated"


@pytest.mark.slow
def test_reshard_auto_knob_unsharded_and_back(mesh8):
    """shard_optimizer='auto': shrinking to a 1-device mesh drops to
    the natural replicated layout; re-growing re-shards — same trained
    parameters as an uninterrupted sharded run throughout."""
    net_a, st_a = _build_step(mesh8, True)
    net_b, st_b = _build_step(mesh8, "auto")
    _run(st_a, 2), _run(st_b, 2)
    ctx = ElasticContext(st_b, liveness=lambda: 0)
    ctx.reform(devices=jax.devices()[:1])
    assert st_b._shard_n == 0 and not any(st_b._shard_slots)
    _run(st_a, 2), _run(st_b, 2)
    ctx.reform(devices=jax.devices()[:4])
    assert st_b._shard_n == 4 and all(st_b._shard_slots)
    _run(st_a, 2), _run(st_b, 2)
    for (ka, pa), (kb, pb) in zip(
            sorted(net_a.collect_params().items()),
            sorted(net_b.collect_params().items())):
        onp.testing.assert_allclose(pa.data().asnumpy(),
                                    pb.data().asnumpy(),
                                    rtol=2e-5, atol=2e-6, err_msg=ka)


@pytest.mark.slow
def test_trainer_reshard_parity(mesh8):
    """Trainer path: the ZeRO mirror gathers back bitwise, weights
    re-place on the survivors' mesh, and the fused update re-engages at
    the new dp extent — parameters keep matching an uninterrupted
    trainer.  (slow: 4 fused-update compiles across two mesh extents;
    the DataParallelStep reshard path carries the tier-1 parity
    assertion.)"""
    def setup(mesh):
        onp.random.seed(3)
        mx.random.seed(3)
        net = nn.HybridSequential()
        net.add(nn.Dense(7, activation="relu"), nn.Dense(4))
        net.initialize(mx.init.Xavier())
        net(mx.nd.array(_X))
        for _, p in net.collect_params().items():
            p.set_data(parallel.replicate(p.data(), mesh))
        tr = gluon.Trainer(net.collect_params(), "sgd",
                           {"learning_rate": 0.05, "momentum": 0.9},
                           shard_optimizer=True)
        return net, tr

    L = gloss.SoftmaxCrossEntropyLoss()

    def epoch(net, tr, mesh, k):
        for _ in range(k):
            xb = parallel.shard_batch(mx.nd.array(_X), mesh)
            yb = parallel.shard_batch(mx.nd.array(_Y), mesh)
            with mx.autograd.record():
                loss = L(net(xb), yb).mean()
            loss.backward()
            tr.step(1)

    net_a, tr_a = setup(mesh8)
    net_b, tr_b = setup(mesh8)
    epoch(net_a, tr_a, mesh8, 4)
    epoch(net_b, tr_b, mesh8, 2)
    mesh4 = parallel.device_mesh((4,), ("dp",),
                                 devices=jax.devices()[:4])
    ElasticContext(tr_b, liveness=lambda: 0).reform(mesh=mesh4)
    epoch(net_b, tr_b, mesh4, 2)
    fused = tr_b._kv_fused or tr_b._local_fused
    assert fused is not None and fused._shard_n == 4
    parallel.set_mesh(mesh8)
    for (ka, pa), (kb, pb) in zip(
            sorted(net_a.collect_params().items()),
            sorted(net_b.collect_params().items())):
        onp.testing.assert_allclose(pa.data().asnumpy(),
                                    pb.data().asnumpy(),
                                    rtol=2e-5, atol=2e-6, err_msg=ka)


# ---------------------------------------------------------------------------
# detection + backoff
# ---------------------------------------------------------------------------

def test_elastic_context_detects_and_journals(mesh8):
    seq = iter([0, 0, 1, 1, 0])
    _, st = _build_step(mesh8, True)
    ctx = ElasticContext(st, liveness=lambda: next(seq))
    assert ctx.poll(step=0) is None
    assert ctx.poll(step=1) is None
    ev = ctx.poll(step=2)
    assert ev["kind"] == "departed"
    assert ev["world_from"] - ev["world_to"] == 1
    assert ctx.poll(step=3) is None        # unchanged world: no event
    ev = ctx.poll(step=4)
    assert ev["kind"] == "joined"
    kinds = [(e.get("change"), e.get("step")) for e in
             telemetry.snapshot(events=256)["events"]
             if e["kind"] == "elastic" and e["name"] == "detect"]
    assert ("departed", 2) in kinds and ("joined", 4) in kinds


def test_poll_interval_throttles_probes(mesh8):
    """poll_interval: the liveness probe is a coordinator RPC, so a
    per-step maybe_recover() must not pay one per step — throttled
    polls return None without probing."""
    calls = {"n": 0}

    def probe():
        calls["n"] += 1
        return 0

    _, st = _build_step(mesh8, True)
    ctx = ElasticContext(st, liveness=probe, poll_interval=60.0)
    assert ctx.poll(step=0) is None and calls["n"] == 1
    for i in range(5):
        assert ctx.poll(step=i + 1) is None
    assert calls["n"] == 1, "throttled polls still probed"


def test_maybe_recover_reforms_on_departure(mesh8):
    _, st = _build_step(mesh8, True)
    seq = iter([0, 1])
    ctx = ElasticContext(st, liveness=lambda: next(seq))
    assert ctx.maybe_recover(step=0) is None
    ev = ctx.maybe_recover(devices=jax.devices()[:4], step=1)
    assert ev["kind"] == "departed" and dict(ev["mesh"].shape) == {"dp": 4}
    assert st._shard_n == 4


def test_min_workers_floor_raises(mesh8):
    _, st = _build_step(mesh8, True)
    ctx = ElasticContext(st, liveness=lambda: 7, min_workers=2,
                         kvstore=None)
    ctx._world0 = 8
    with pytest.raises(MXNetError, match="min_workers"):
        ctx.poll()


def test_kv_retry_backoff_jitter_and_giveup():
    """Flaky op: retried under exponential backoff + jitter; a dead op
    re-raises after the bounded attempts (never a silent zero)."""
    delays = []
    calls = {"n": 0}

    def flaky():
        calls["n"] += 1
        if calls["n"] < 3:
            raise RuntimeError("flap %d" % calls["n"])
        return 41

    import random
    r0 = telemetry.counter("elastic.kv_retries")
    out = kv_retry(flaky, retries=5, base=0.05, cap=1.0, jitter=0.5,
                   rng=random.Random(7), sleep=delays.append)
    assert out == 41 and calls["n"] == 3
    assert len(delays) == 2
    # exponential base with bounded jitter: d0 in [.05,.075], d1 in [.1,.15]
    assert 0.05 <= delays[0] <= 0.075 and 0.1 <= delays[1] <= 0.15
    assert telemetry.counter("elastic.kv_retries") - r0 == 2

    with pytest.raises(RuntimeError, match="always"):
        kv_retry(lambda: (_ for _ in ()).throw(RuntimeError("always")),
                 retries=3, sleep=delays.append)


def test_coordinator_loss_is_reported_not_fatal(mesh8):
    """A coordinator unreachable past the retry budget classifies as
    coordinator_lost (restore from the manifest is the remedy) instead
    of raising out of the training loop."""
    def dead():
        raise RuntimeError("coordination service unreachable")

    _, st = _build_step(mesh8, True)
    ctx = ElasticContext(st, liveness=dead, retries=2, backoff_base=0.0,
                         jitter=0.0)
    ev = ctx.poll(step=5)
    assert ev["kind"] == "coordinator_lost"
    det = [e for e in telemetry.snapshot(events=256)["events"]
           if e["kind"] == "elastic" and e["name"] == "detect"
           and e.get("reason") == "coordinator_unreachable"]
    assert det and det[-1]["step"] == 5


# ---------------------------------------------------------------------------
# chaos harness determinism
# ---------------------------------------------------------------------------

def test_chaos_fault_triggers_are_deterministic():
    chaos.install("kill_worker", rank=2, at_step=3)
    # wrong rank: never fires
    assert not chaos.should_fire("kill_worker", step=3, rank=1)
    # right rank, wrong step: no fire
    assert not chaos.should_fire("kill_worker", step=2, rank=2)
    assert chaos.should_fire("kill_worker", step=3, rank=2)
    assert chaos.fired("kill_worker") == 1
    chaos.clear("kill_worker")
    assert not chaos.should_fire("kill_worker", step=3, rank=2)

    chaos.install("drop_heartbeat", times=2)
    assert chaos.should_fire("drop_heartbeat")
    assert chaos.should_fire("drop_heartbeat")
    assert not chaos.should_fire("drop_heartbeat")   # times exhausted

    chaos.install("kv_garble", after_calls=1, times=1)
    assert not chaos.should_fire("kv_garble")        # warm-up call
    assert chaos.should_fire("kv_garble")


def test_chaos_kv_proxy_garbles_reads():
    class C:
        def blocking_key_value_get(self, key, t):
            return "1234.5"

        def other(self):
            return "ok"

    proxy = chaos.wrap_kv_client(C())
    assert proxy.blocking_key_value_get("k", 50) == "1234.5"
    chaos.install("kv_garble", times=1)
    garbled = proxy.blocking_key_value_get("k", 50)
    assert garbled != "1234.5"
    with pytest.raises(ValueError):
        float(garbled)          # garbled payloads must not parse
    assert proxy.blocking_key_value_get("k", 50) == "1234.5"
    assert proxy.other() == "ok"


def test_chaos_kv_proxy_stalls_reads():
    """``kv_stall`` blocks proxied reads for its ``delay`` — the
    struggling-coordinator fault the kv_retry backoff path absorbs."""
    class C:
        def blocking_key_value_get(self, key, t):
            return "1234.5"

    proxy = chaos.wrap_kv_client(C())
    chaos.install("kv_stall", times=1, delay=0.05)
    t0 = time.monotonic()
    assert proxy.blocking_key_value_get("k", 50) == "1234.5"
    assert time.monotonic() - t0 >= 0.05      # stalled, payload intact
    t0 = time.monotonic()
    assert proxy.blocking_key_value_get("k", 50) == "1234.5"
    assert time.monotonic() - t0 < 0.05       # times=1: back to fast


def test_chaos_install_from_env(monkeypatch):
    monkeypatch.setenv(chaos.ENV_VAR,
                       "kill_worker:rank=2,at_step=3;drop_heartbeat:rank=1")
    assert chaos.install_from_env(rank=2) == ["kill_worker"]
    spec = chaos.active("kill_worker")
    assert spec["rank"] == 2 and spec["at_step"] == 3
    assert chaos.active("drop_heartbeat") is None    # other rank's fault


def test_garbled_liveness_rides_retry_to_recovery(mesh8):
    """End-to-end: a liveness probe whose first reads come back garbled
    (chaos kv_garble through the heartbeat parser) retries under
    backoff and lands on the true count."""
    import time
    good = iter([None, None, 1])

    def probe():
        nxt = next(good)
        if nxt is None:
            raise ValueError("garbled heartbeat payload")
        return nxt

    _, st = _build_step(mesh8, True)
    ctx = ElasticContext(st, liveness=probe, retries=4,
                         backoff_base=0.0, jitter=0.0)
    t0 = time.monotonic()
    ev = ctx.poll()
    assert time.monotonic() - t0 < 5.0
    assert ev["kind"] == "departed" and ev["n_dead"] == 1
