"""Operator forward-golden + numeric-gradient tests.

Reference: tests/python/unittest/test_operator.py (the largest suite there;
numeric-gradient checks for nearly every op — SURVEY.md §4 strategy (1)).
"""
import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd
from mxnet_tpu.test_utils import (
    assert_almost_equal, check_numeric_gradient, same,
)


def test_unary_golden():
    x = onp.random.uniform(0.5, 2.0, (3, 4)).astype(onp.float32)
    a = nd.array(x)
    cases = {
        "sqrt": onp.sqrt, "square": onp.square, "exp": onp.exp,
        "log": onp.log, "sin": onp.sin, "cos": onp.cos, "tanh": onp.tanh,
        "abs": onp.abs, "floor": onp.floor, "ceil": onp.ceil,
        "log1p": onp.log1p, "expm1": onp.expm1, "sign": onp.sign,
        "reciprocal": onp.reciprocal,
    }
    for name, ref in cases.items():
        got = getattr(nd, name)(a)
        assert_almost_equal(got, ref(x), rtol=1e-5, atol=1e-6, names=(name, "np"))
    assert_almost_equal(nd.sigmoid(a), 1 / (1 + onp.exp(-x)), rtol=1e-5, atol=1e-6)
    assert_almost_equal(nd.relu(nd.array(x - 1.0)), onp.maximum(x - 1.0, 0), rtol=1e-5, atol=1e-7)
    assert_almost_equal(nd.rsqrt(a), 1 / onp.sqrt(x), rtol=1e-5, atol=1e-6)


def test_binary_broadcast_golden():
    x = onp.random.normal(size=(2, 3, 1)).astype(onp.float32)
    y = onp.random.normal(size=(1, 3, 4)).astype(onp.float32)
    a, b = nd.array(x), nd.array(y)
    assert_almost_equal(nd.broadcast_add(a, b), x + y, rtol=1e-6, atol=1e-6)
    assert_almost_equal(nd.broadcast_mul(a, b), x * y, rtol=1e-6, atol=1e-6)
    assert_almost_equal(nd.broadcast_maximum(a, b), onp.maximum(x, y), rtol=1e-6, atol=1e-6)
    assert_almost_equal(nd.broadcast_sub(a, b), x - y, rtol=1e-6, atol=1e-6)


def test_reductions():
    x = onp.random.normal(size=(2, 3, 4)).astype(onp.float32)
    a = nd.array(x)
    assert_almost_equal(nd.sum(a), x.sum(), rtol=1e-5, atol=1e-5)
    assert_almost_equal(nd.sum(a, axis=1), x.sum(1), rtol=1e-5, atol=1e-5)
    assert_almost_equal(nd.sum(a, axis=(0, 2), keepdims=True), x.sum((0, 2), keepdims=True), rtol=1e-5, atol=1e-5)
    assert_almost_equal(nd.mean(a, axis=0), x.mean(0), rtol=1e-5, atol=1e-5)
    assert_almost_equal(nd.max(a, axis=2), x.max(2), rtol=1e-6, atol=1e-6)
    assert_almost_equal(nd.min(a), x.min(), rtol=1e-6, atol=1e-6)
    # exclude semantics: reduce over all axes EXCEPT the given ones
    assert_almost_equal(nd.sum(a, axis=1, exclude=True), x.sum((0, 2)), rtol=1e-5, atol=1e-5)
    assert same(nd.argmax(a, axis=1), x.argmax(1).astype(onp.float32))
    assert same(nd.argmin(a, axis=-1), x.argmin(-1).astype(onp.float32))
    assert_almost_equal(nd.norm(a), onp.sqrt((x ** 2).sum()), rtol=1e-5, atol=1e-5)


def test_dot():
    x = onp.random.normal(size=(3, 4)).astype(onp.float32)
    y = onp.random.normal(size=(4, 5)).astype(onp.float32)
    assert_almost_equal(nd.dot(nd.array(x), nd.array(y)), x @ y, rtol=1e-5, atol=1e-5)
    assert_almost_equal(nd.dot(nd.array(x), nd.array(y.T), transpose_b=True), x @ y, rtol=1e-5, atol=1e-5)
    assert_almost_equal(nd.dot(nd.array(x.T), nd.array(y), transpose_a=True), x @ y, rtol=1e-5, atol=1e-5)
    bx = onp.random.normal(size=(2, 3, 4)).astype(onp.float32)
    by = onp.random.normal(size=(2, 4, 5)).astype(onp.float32)
    assert_almost_equal(nd.batch_dot(nd.array(bx), nd.array(by)), bx @ by, rtol=1e-5, atol=1e-5)


def test_shape_manipulation():
    x = onp.arange(24).reshape(2, 3, 4).astype(onp.float32)
    a = nd.array(x)
    assert same(nd.transpose(a), x.T)
    assert same(nd.transpose(a, axes=(1, 0, 2)), x.transpose(1, 0, 2))
    assert same(nd.swapaxes(a, 0, 2), x.swapaxes(0, 2))
    assert same(nd.expand_dims(a, axis=1), x[:, None])
    assert same(nd.Flatten(a), x.reshape(2, 12))
    assert same(nd.slice_axis(a, axis=1, begin=1, end=3), x[:, 1:3])
    assert same(nd.slice(a, begin=(0, 1), end=(2, 3)), x[0:2, 1:3])
    assert same(nd.repeat(a, repeats=2, axis=0), x.repeat(2, 0))
    assert same(nd.tile(a, reps=(1, 2, 1)), onp.tile(x, (1, 2, 1)))
    assert same(nd.reverse(a, axis=0), x[::-1])
    assert same(nd.Cast(a, dtype="int32"), x.astype(onp.int32))
    assert same(a.squeeze(), x)  # no-op when no 1-dims
    assert same(nd.squeeze(nd.array(x[:1]), axis=0), x[0])


def test_take_pick_onehot_gather():
    x = onp.random.normal(size=(5, 3)).astype(onp.float32)
    a = nd.array(x)
    idx = nd.array([0, 4, 2], dtype=onp.int32)
    assert same(nd.take(a, idx), x[[0, 4, 2]])
    p = nd.pick(a, nd.array([0, 1, 2, 0, 1]), axis=1)
    assert same(p, x[onp.arange(5), [0, 1, 2, 0, 1]])
    oh = nd.one_hot(nd.array([1, 0, 2]), depth=3)
    assert same(oh, onp.eye(3, dtype=onp.float32)[[1, 0, 2]])
    g = nd.gather_nd(a, nd.array([[0, 2], [1, 0]], dtype=onp.int32))
    assert same(g, x[[0, 2], [1, 0]])


def test_ordering():
    x = onp.random.permutation(20).reshape(4, 5).astype(onp.float32)
    a = nd.array(x)
    assert same(nd.sort(a, axis=1), onp.sort(x, 1))
    assert same(nd.argsort(a, axis=1), onp.argsort(x, 1).astype(onp.float32))
    v = nd.topk(a, k=2, axis=1, ret_typ="value")
    ref = onp.sort(x, 1)[:, ::-1][:, :2]
    assert same(v, ref)


def test_softmax():
    x = onp.random.normal(size=(3, 5)).astype(onp.float32)
    a = nd.array(x)
    e = onp.exp(x - x.max(1, keepdims=True))
    ref = e / e.sum(1, keepdims=True)
    assert_almost_equal(nd.softmax(a), ref, rtol=1e-5, atol=1e-6)
    assert_almost_equal(nd.log_softmax(a), onp.log(ref), rtol=1e-4, atol=1e-5)
    assert_almost_equal(nd.softmax(a, axis=0),
                        onp.exp(x - x.max(0)) / onp.exp(x - x.max(0)).sum(0),
                        rtol=1e-5, atol=1e-6)


def test_elemwise_gradients():
    x = onp.random.uniform(0.5, 1.5, (3, 2)).astype(onp.float32)
    check_numeric_gradient(lambda a: a * a + 2 * a, [x])
    check_numeric_gradient(lambda a: nd.sqrt(a), [x])
    check_numeric_gradient(lambda a: nd.sigmoid(a), [x])
    check_numeric_gradient(lambda a: nd.tanh(a), [x])


def test_dot_gradient():
    x = onp.random.normal(size=(3, 4)).astype(onp.float32)
    y = onp.random.normal(size=(4, 2)).astype(onp.float32)
    check_numeric_gradient(lambda a, b: nd.dot(a, b), [x, y], rtol=2e-2, atol=1e-3)


def test_broadcast_gradient():
    x = onp.random.normal(size=(3, 1)).astype(onp.float32)
    y = onp.random.normal(size=(1, 4)).astype(onp.float32)
    check_numeric_gradient(lambda a, b: nd.broadcast_mul(a, b), [x, y])


def test_clip_where():
    x = onp.random.normal(size=(4, 4)).astype(onp.float32)
    a = nd.array(x)
    assert same(nd.clip(a, -0.5, 0.5), onp.clip(x, -0.5, 0.5))
    cond = nd.array((x > 0).astype(onp.float32))
    w = nd.where(cond, a, -a)
    assert same(w, onp.abs(x))


def test_random_ops():
    mx.random.seed(7)
    u = nd.random_uniform(low=2.0, high=5.0, shape=(1000,))
    un = u.asnumpy()
    assert un.min() >= 2.0 and un.max() <= 5.0 and abs(un.mean() - 3.5) < 0.2
    n = nd.random_normal(loc=1.0, scale=2.0, shape=(4000,))
    nn_ = n.asnumpy()
    assert abs(nn_.mean() - 1.0) < 0.2 and abs(nn_.std() - 2.0) < 0.2
    mx.random.seed(7)
    u2 = nd.random_uniform(low=2.0, high=5.0, shape=(1000,))
    assert same(u, u2)  # seeding reproduces streams
    r = nd.random_randint(low=0, high=10, shape=(100,))
    rn = r.asnumpy()
    assert rn.min() >= 0 and rn.max() < 10
    m = nd.sample_multinomial(nd.array([[0.0, 1.0, 0.0], [1.0, 0.0, 0.0]]))
    assert same(m, onp.array([1, 0], onp.int32))


def test_sequence_ops():
    x = onp.random.normal(size=(4, 3, 2)).astype(onp.float32)  # (T, N, C)
    a = nd.array(x)
    slen = nd.array([2.0, 4.0, 3.0])
    masked = nd.SequenceMask(a, sequence_length=slen, use_sequence_length=True, value=-1.0)
    mn = masked.asnumpy()
    assert (mn[2:, 0] == -1).all() and (mn[:2, 0] == x[:2, 0]).all()
    last = nd.SequenceLast(a, sequence_length=slen, use_sequence_length=True)
    assert_almost_equal(last[0], x[1, 0], rtol=1e-6, atol=1e-6)
    assert_almost_equal(last[1], x[3, 1], rtol=1e-6, atol=1e-6)


def test_linalg():
    x = onp.random.normal(size=(3, 3)).astype(onp.float32)
    spd = x @ x.T + 3 * onp.eye(3, dtype=onp.float32)
    a = nd.array(spd)
    L = nd.linalg_potrf(a)
    assert_almost_equal(nd.linalg_gemm2(L, L, transpose_b=True), spd, rtol=1e-4, atol=1e-4)
    assert_almost_equal(nd.linalg_inverse(a), onp.linalg.inv(spd), rtol=1e-3, atol=1e-4)


def test_l2_normalization_and_moments():
    x = onp.random.normal(size=(2, 3, 4)).astype(onp.float32)
    out = nd.L2Normalization(nd.array(x), mode="instance")
    ref = x / onp.sqrt((x.reshape(2, -1) ** 2).sum(1) + 1e-10).reshape(2, 1, 1)
    assert_almost_equal(out, ref, rtol=1e-5, atol=1e-6)
    mean, var = nd.moments(nd.array(x), axes=(0, 2))
    assert_almost_equal(mean, x.mean((0, 2)), rtol=1e-5, atol=1e-6)
    assert_almost_equal(var, x.var((0, 2)), rtol=1e-4, atol=1e-5)
