"""Search-based Pallas autotuner tests (CPU-safe).

Covers the PR contract end to end: cost-table round-trip (write →
reload → dispatch hit), corrupt/stale-schema tolerance (heuristic
fallback, never a crash), deterministic offline search under a fake
measurer, the strict dispatch-time trial budget, and — the regression
guard — that DEFAULT dispatch (no table, no ``MXNET_AUTOTUNE``) is
bit-identical to the pre-autotuner heuristics for attention and both
norm block pickers.
"""
import json
import os

import pytest

from mxnet_tpu import telemetry, tune
from mxnet_tpu.ops import pallas_attention as PA
from mxnet_tpu.ops import pallas_fused_norm as FN
from mxnet_tpu.ops import pallas_layernorm as LN
from mxnet_tpu.tune import search
from mxnet_tpu.tune.cost_table import CostTable, SCHEMA_VERSION


@pytest.fixture(autouse=True)
def _isolated_table(tmp_path, monkeypatch):
    """Every test gets its own table path and a reset singleton; the
    autotune env knobs start unset (default mode)."""
    monkeypatch.setenv("MXNET_AUTOTUNE_TABLE",
                       str(tmp_path / "cost_table.jsonl"))
    for var in ("MXNET_AUTOTUNE", "MXNET_AUTOTUNE_TRIALS",
                "MXNET_AUTOTUNE_CALLS", "MXNET_AUTOTUNE_INTERPRET"):
        monkeypatch.delenv(var, raising=False)
    tune._reset_for_tests()
    yield
    tune._reset_for_tests()


def _counter(name):
    return telemetry.counter(name)


# --- cost table ------------------------------------------------------------

def test_cost_table_roundtrip_dispatch_hit():
    """write → reload from disk → attention_dispatch serves the stored
    config with tuner_source=table (and counts the hit)."""
    t = tune.get_table()
    t.record("attention", (512, 512, 64), "bfloat16",
             {"block_q": 256, "block_k": 512}, best_ms=1.25,
             source="offline", trials=9)
    # fresh singleton: the entry must come back from DISK, not memory
    tune._reset_for_tests()
    hits = _counter("autotune.hit")
    plan = PA.attention_dispatch(512, 512, 64, "bfloat16", on_tpu=True)
    assert (plan["block_q"], plan["block_k"]) == (256, 512)
    assert plan["tuner_source"] == "table"
    assert plan["kernel"] == "short_seq"
    assert _counter("autotune.hit") == hits + 1
    # the stored record carries provenance for the census
    rec = tune.get_table().lookup("attention", (512, 512, 64), "bfloat16")
    assert rec["source"] == "offline" and rec["trials"] == 9
    assert rec["best_ms"] == pytest.approx(1.25)


def test_norm_pickers_consult_table():
    t = tune.get_table()
    # norm families key dtype-blind (fp32 VMEM working set): an entry
    # recorded from bf16 operands serves the picker's float32 lookup
    t.record("fused_norm", (4096, 512), "bfloat16",
             {"block_r": 64, "block_c": 256})
    t.record("layernorm", (4096, 1024), "float32", {"block_rows": 128})
    # ONE (rows, cols) entry serves BOTH the fwd (3-buf) and bwd
    # (5-buf) pickers — fwd and bwd must run the same measured blocks
    assert FN._pick_blocks(4096, 512, 3) == (64, 256)
    assert FN._pick_blocks(4096, 512, 5) == (64, 256)
    assert LN._pick_block_rows(1024, rows=4096) == 128
    # other shapes keep the heuristic
    assert FN._pick_blocks(4096, 768, 3) == \
        FN._pick_blocks_heuristic(4096, 768, 3)
    assert LN._pick_block_rows(768, rows=4096) == \
        LN._pick_block_rows_heuristic(768)


def test_flash_bwd_threads_tuned_blocks(monkeypatch):
    """The production VJP must run the backward with the SAME tuned
    blocks the forward dispatched — the A/B acceptance leg times tuned
    fwd+bwd together, so a heuristic bwd would bench a config that
    never runs."""
    import jax.numpy as jnp
    import numpy as onp
    tune.get_table().record("attention", (384, 384, 64), "bfloat16",
                            {"block_q": 128, "block_k": 384})
    captured = {}

    def fake_bwd(q, k, v, out, lse, g, **kw):
        captured.update(kw)
        return q, k, v
    monkeypatch.setattr(PA, "pallas_flash_attention_bwd", fake_bwd)
    monkeypatch.setattr(PA, "_use_pallas", lambda *a: True)
    x = jnp.asarray(onp.zeros((1, 1, 384, 64), "float32"), jnp.bfloat16)
    lse = jnp.zeros((1, 1, 384), jnp.float32)
    res = (x, x, x, x, lse, None, None, None)
    PA._flash_bwd(False, None, res, x)
    assert captured["block_q"] == 128 and captured["block_k"] == 384


def test_corrupt_and_stale_entries_fall_back(tmp_path):
    """Garbage lines, stale schema versions and field-less configs are
    skipped (counted), never raised; valid records still serve."""
    path = os.environ["MXNET_AUTOTUNE_TABLE"]
    good = {"schema": SCHEMA_VERSION, "family": "attention",
            "shape": [512, 512, 64], "dtype": "bfloat16",
            "platform": tune.platform_id(),
            "config": {"block_q": 256, "block_k": 512}}
    with open(path, "w") as fh:
        fh.write("{ not json at all\n")
        fh.write(json.dumps(dict(good, schema=SCHEMA_VERSION + 1,
                                 shape=[128, 128, 64])) + "\n")
        fh.write(json.dumps(dict(good, shape=[256, 256, 64],
                                 config={"block_q": "x"})) + "\n")
        # float shape dims (an external serializer / hand edit): must
        # be SKIPPED, not raise TypeError out of canon_shape
        fh.write(json.dumps(dict(good, shape=[640.0, 640, 64])) + "\n")
        fh.write(json.dumps(good) + "\n")
    before = _counter("autotune.corrupt_entry")
    # corrupt keys -> heuristic, silently
    p128 = PA.attention_dispatch(128, 128, 64, "bfloat16", on_tpu=True)
    assert p128["tuner_source"] == "heuristic"
    assert (p128["block_q"], p128["block_k"]) == \
        PA.tune_attention_blocks(128, 128, 64, "bfloat16")
    p256 = PA.attention_dispatch(256, 256, 64, "bfloat16", on_tpu=True)
    assert p256["tuner_source"] == "heuristic"
    # the valid record on the same file still serves
    p512 = PA.attention_dispatch(512, 512, 64, "bfloat16", on_tpu=True)
    assert p512["tuner_source"] == "table" and p512["block_q"] == 256
    assert _counter("autotune.corrupt_entry") == before + 4


def test_invalid_table_config_falls_back():
    """A stored config that no longer satisfies the kernels' own VMEM
    predicate (e.g. a table baked before a budget change) is refused —
    heuristic + autotune.fallback, not a compile attempt."""
    tune.get_table().record("attention", (2048, 2048, 64), "bfloat16",
                            {"block_q": 4096, "block_k": 4096})
    fallbacks = _counter("autotune.fallback")
    plan = PA.attention_dispatch(2048, 2048, 64, "bfloat16", on_tpu=True)
    assert plan["tuner_source"] == "heuristic"
    assert (plan["block_q"], plan["block_k"]) == \
        PA.tune_attention_blocks(2048, 2048, 64, "bfloat16")
    assert _counter("autotune.fallback") == fallbacks + 1


def test_stale_entry_retuned_under_autotune(monkeypatch):
    """With MXNET_AUTOTUNE=1 an invalid table entry must fall THROUGH
    to the on-miss search (which overwrites the stale record) — not pin
    the shape to the heuristic forever."""
    tune.get_table().record("attention", (512, 512, 64), "bfloat16",
                            {"block_q": 4096, "block_k": 4096})
    monkeypatch.setattr(search, "_measure_candidate",
                        lambda f, s, d, cfg, **kw: float(cfg["block_q"]))
    monkeypatch.setattr(tune, "_platform_is_tpu", lambda: True)
    monkeypatch.setenv("MXNET_AUTOTUNE", "1")
    plan = PA.attention_dispatch(512, 512, 64, "bfloat16", on_tpu=True)
    assert plan["tuner_source"] == "searched"
    rec = tune.get_table().lookup("attention", (512, 512, 64),
                                  "bfloat16")
    assert rec["config"]["block_q"] == plan["block_q"] != 4096


def test_invalid_entry_plus_failed_search_counts_one_fallback(monkeypatch):
    """One dispatch decision = one fallback event, even when an invalid
    entry's re-search then fails too."""
    tune.get_table().record("attention", (512, 512, 64), "bfloat16",
                            {"block_q": 4096, "block_k": 4096})

    def broken(*a, **kw):
        raise RuntimeError("no chip")
    monkeypatch.setattr(search, "_measure_candidate", broken)
    monkeypatch.setattr(tune, "_platform_is_tpu", lambda: True)
    monkeypatch.setenv("MXNET_AUTOTUNE", "1")
    before = _counter("autotune.fallback")
    plan = PA.attention_dispatch(512, 512, 64, "bfloat16", on_tpu=True)
    assert plan["tuner_source"] == "heuristic"
    assert _counter("autotune.fallback") == before + 1


def test_interpret_records_refused_on_real_chip(monkeypatch):
    """Interpret-mode (smoke) timings are stamped into the record and
    never served on a real chip — there they read as a miss, so
    MXNET_AUTOTUNE can re-tune with real measurements."""
    from mxnet_tpu.tune import cost_table as ct
    tune.get_table().record("attention", (512, 512, 64), "bfloat16",
                            {"block_q": 256, "block_k": 512},
                            interpret=True)
    rec = tune.get_table().lookup("attention", (512, 512, 64),
                                  "bfloat16")
    assert rec is not None and rec["interpret"] is True  # CPU: servable
    monkeypatch.setattr(ct, "_on_real_chip", lambda: True)
    assert tune.get_table().lookup("attention", (512, 512, 64),
                                   "bfloat16") is None


def test_platform_mismatch_is_a_miss():
    """A table baked on another chip generation must never serve."""
    tune.get_table().record("attention", (512, 512, 64), "bfloat16",
                            {"block_q": 256, "block_k": 512},
                            platform="tpu-v99")
    plan = PA.attention_dispatch(512, 512, 64, "bfloat16", on_tpu=True)
    assert plan["tuner_source"] == "heuristic"


# --- default mode: bit-identical to the pre-autotuner heuristics -----------

def test_default_dispatch_bit_identical_to_heuristic():
    """THE regression guard: with no table and MXNET_AUTOTUNE unset,
    every dispatch decision equals the pre-PR heuristic path exactly."""
    for s in (128, 384, 512, 1024, 2048, 4096, 8192):
        for d in (32, 64, 128):
            for dt in ("float32", "bfloat16"):
                plan = PA.attention_dispatch(s, s, d, dt, on_tpu=True)
                bq, bk = PA.tune_attention_blocks(s, s, d, dt)
                assert (plan["block_q"], plan["block_k"]) == (bq, bk), \
                    (s, d, dt, plan)
                assert plan["kernel"] == \
                    ("short_seq" if s <= bk else "streaming")
                assert plan["tuner_source"] == "heuristic"
    for rows, cols, n_bufs in ((512, 512, 3), (4096, 2048, 5),
                               (64, 128, 3), (10 ** 5, 4096, 5)):
        assert FN._pick_blocks(rows, cols, n_bufs) == \
            FN._pick_blocks_heuristic(rows, cols, n_bufs)
    for C in (128, 768, 1024, 10 ** 6):
        assert LN._pick_block_rows(C, rows=4096) == \
            LN._pick_block_rows_heuristic(C)


def test_default_mode_never_searches(monkeypatch):
    """Default mode must measure NOTHING at trace time: the measurer is
    unreachable without the MXNET_AUTOTUNE opt-in."""
    def boom(*a, **k):
        raise AssertionError("measured in default mode")
    monkeypatch.setattr(search, "_measure_candidate", boom)
    plan = PA.attention_dispatch(640, 640, 64, "bfloat16", on_tpu=True)
    assert plan["tuner_source"] == "heuristic"
    FN._pick_blocks(512, 512, 3)
    LN._pick_block_rows(768, rows=512)


# --- search driver ---------------------------------------------------------

def test_candidates_prune_through_vmem_predicate():
    """Every enumerated candidate honours the kernels' own clamp —
    the search can never time (or emit) an over-budget config."""
    import jax.numpy as jnp
    for shape, dt in (((8192, 8192, 256), "float32"),
                      ((2048, 2048, 64), "bfloat16")):
        cands = search.candidates("attention", shape, dt)
        assert cands, shape
        assert cands[0] == search.heuristic_config("attention", shape, dt)
        Dp = shape[2] + (-shape[2]) % 64
        for c in cands:
            assert PA._fwd_vmem_bytes(c["block_q"], c["block_k"], Dp,
                                      jnp.dtype(dt).itemsize) \
                <= PA._VMEM_CLAMP, c
    for c in search.candidates("fused_norm", (4096, 1024), "float32"):
        assert c["block_r"] * c["block_c"] * 4 * 5 <= FN._VMEM_BUDGET
    for c in search.candidates("layernorm", (4096, 1024), "float32"):
        assert 3 * 4 * c["block_rows"] * 1024 <= LN._VMEM_BUDGET


def test_offline_search_deterministic_with_fake_timer():
    """Given a deterministic measurer, the search result is a pure
    function of the instance: same candidates, same winner (the argmin,
    earliest on ties), twice in a row."""
    def fake_ms(cfg):
        # prefers an interior point, deterministic in the config alone
        return abs(cfg["block_q"] - 256) + abs(cfg["block_k"] - 512) + 1.0
    a = search.search_config("attention", (512, 512, 64), "bfloat16",
                             trials=32, measure=fake_ms)
    b = search.search_config("attention", (512, 512, 64), "bfloat16",
                             trials=32, measure=fake_ms)
    assert a == b
    assert a["config"] == {"block_q": 256, "block_k": 512}
    assert a["best_ms"] == pytest.approx(1.0)
    timed = [r["config"] for r in a["results"]]
    assert timed == search.candidates("attention", (512, 512, 64),
                                      "bfloat16")[:32]


def test_search_survives_failing_candidates():
    """A candidate that raises (compile failure on some chip) is
    recorded and skipped — the search still returns the best of the
    rest."""
    def flaky(cfg):
        if cfg["block_q"] == 256:
            raise RuntimeError("mosaic says no")
        return cfg["block_q"]
    res = search.search_config("attention", (512, 512, 64), "bfloat16",
                               trials=8, measure=flaky)
    assert res["config"]["block_q"] != 256
    assert any("error" in r for r in res["results"])


def test_dispatch_search_honors_trial_budget(monkeypatch):
    """MXNET_AUTOTUNE=1 on-miss search: at most MXNET_AUTOTUNE_TRIALS
    candidates are measured, the winner is persisted, and the next
    dispatch is a table hit with no further measurement."""
    calls = []

    def fake_measure(family, shape, dtype, cfg, **kw):
        calls.append(dict(cfg))
        return float(cfg["block_q"])          # smallest block_q wins
    monkeypatch.setattr(search, "_measure_candidate", fake_measure)
    monkeypatch.setattr(tune, "_platform_is_tpu", lambda: True)
    monkeypatch.setenv("MXNET_AUTOTUNE", "1")
    monkeypatch.setenv("MXNET_AUTOTUNE_TRIALS", "3")

    plan = PA.attention_dispatch(512, 512, 64, "bfloat16", on_tpu=True)
    assert plan["tuner_source"] == "searched"
    assert len(calls) == 3                     # the strict budget
    assert calls == search.candidates("attention", (512, 512, 64),
                                      "bfloat16")[:3]
    best_bq = min(c["block_q"] for c in calls)
    assert plan["block_q"] == best_bq
    # persisted: a fresh process (singleton reset) hits the table
    tune._reset_for_tests()
    plan2 = PA.attention_dispatch(512, 512, 64, "bfloat16", on_tpu=True)
    assert plan2["tuner_source"] == "table"
    assert plan2["block_q"] == best_bq
    assert len(calls) == 3                     # no re-measurement


def test_dispatch_search_needs_tpu_or_interpret_optin(monkeypatch):
    """MXNET_AUTOTUNE=1 on a CPU host must NOT try to time TPU kernels
    at dispatch (only the offline CLI's --interpret does that)."""
    def boom(*a, **k):
        raise AssertionError("searched on CPU without interpret opt-in")
    monkeypatch.setattr(search, "_measure_candidate", boom)
    monkeypatch.setenv("MXNET_AUTOTUNE", "1")
    plan = PA.attention_dispatch(512, 512, 64, "bfloat16", on_tpu=True)
    assert plan["tuner_source"] == "heuristic"


def test_table_blocks_default_and_field_order():
    assert tune.table_blocks("attention", (640, 640, 64), "bfloat16",
                             default=(1024, 2048)) == (1024, 2048)
    tune.get_table().record("attention", (640, 640, 64), "bfloat16",
                            {"block_q": 512, "block_k": 640})
    assert tune.table_blocks("attention", (640, 640, 64),
                             "bfloat16") == (512, 640)
    tune.get_table().record("layernorm", (0, 768), "float32",
                            {"block_rows": 64})
    # single-field family returns the bare int
    assert tune.table_blocks("layernorm", (0, 768), "float32") == 64


def test_norm_picker_census_is_once_per_decision():
    """One fused-epilogue routing decision censuses ONCE even though the
    fwd/bwd kernel entries re-read the blocks; same for layernorm
    fwd+bwd (quiet secondary lookups)."""
    before = _counter("autotune.miss")
    FN._pick_blocks(512, 512, 5)                       # the routing site
    FN._pick_blocks(512, 512, 3, quiet=True)           # fwd kernel entry
    FN._pick_blocks(512, 512, 5, quiet=True)           # bwd kernel entry
    LN._pick_block_rows(768, rows=512)                 # fwd
    LN._pick_block_rows(768, rows=512, quiet=True)     # bwd
    assert _counter("autotune.miss") == before + 2


def test_failed_dispatch_search_is_memoized(monkeypatch):
    """An on-miss search whose every candidate fails must not re-run at
    retraces / sibling call sites — the failure is memoized in-process
    (it cannot be cached on disk)."""
    calls = []

    def broken(family, shape, dtype, cfg, **kw):
        calls.append(1)
        raise RuntimeError("no chip")
    monkeypatch.setattr(search, "_measure_candidate", broken)
    monkeypatch.setattr(tune, "_platform_is_tpu", lambda: True)
    monkeypatch.setenv("MXNET_AUTOTUNE", "1")
    monkeypatch.setenv("MXNET_AUTOTUNE_TRIALS", "2")
    p1 = PA.attention_dispatch(512, 512, 64, "bfloat16", on_tpu=True)
    n = len(calls)
    assert p1["tuner_source"] == "heuristic" and n == 2
    p2 = PA.attention_dispatch(512, 512, 64, "bfloat16", on_tpu=True)
    assert p2["tuner_source"] == "heuristic"
    assert len(calls) == n                 # no second search


def test_record_merges_concurrent_writers(tmp_path):
    """Two CostTable instances on one file (two processes): the second
    writer's whole-file rewrite must keep the first writer's entries
    (merge-on-write, last writer wins per KEY not per file)."""
    path = str(tmp_path / "shared.jsonl")
    a = CostTable(path)
    b = CostTable(path)
    b.lookup("attention", (1, 1, 1), "bfloat16")   # b loads (empty file)
    a.record("attention", (512, 512, 64), "bfloat16",
             {"block_q": 256, "block_k": 512})
    b.record("attention", (2048, 2048, 64), "bfloat16",
             {"block_q": 512, "block_k": 1024})    # stale view of a's write
    fresh = CostTable(path)
    assert fresh.lookup("attention", (512, 512, 64),
                        "bfloat16") is not None, "first writer clobbered"
    assert fresh.lookup("attention", (2048, 2048, 64),
                        "bfloat16") is not None
    # disk wins for keys a process never wrote: b's stale startup view
    # of (512,...) must NOT revert a's re-tuned config when b records
    # an unrelated key
    a.record("attention", (512, 512, 64), "bfloat16",
             {"block_q": 512, "block_k": 512})       # a re-tunes X
    b.record("attention", (128, 128, 64), "bfloat16",
             {"block_q": 128, "block_k": 128})       # b writes Y
    final = CostTable(path)
    assert final.lookup("attention", (512, 512, 64),
                        "bfloat16")["config"]["block_q"] == 512, \
        "stale cache reverted a newer on-disk record"
    # an entry the operator DELETES from the file (the bench hard-fail
    # remedy) must not be resurrected by a process's stale cache
    kept = [ln for ln in open(path) if '"shape": [512, 512, 64]' not in ln]
    with open(path, "w") as fh:
        fh.writelines(kept)
    a.record("attention", (64, 64, 64), "bfloat16",
             {"block_q": 64, "block_k": 128})        # a's cache holds X
    assert CostTable(path).lookup("attention", (512, 512, 64),
                                  "bfloat16") is None, \
        "deleted entry resurrected by a stale cache"


def test_autotune_env_falsy_spellings(monkeypatch):
    for v in ("0", "false", "False", "OFF", "No", "", " off "):
        monkeypatch.setenv("MXNET_AUTOTUNE", v)
        assert not tune.autotune_enabled(), repr(v)
    for v in ("1", "true", "on"):
        monkeypatch.setenv("MXNET_AUTOTUNE", v)
        assert tune.autotune_enabled(), repr(v)


def test_oversize_epilogue_blocks_clamped_to_extents():
    """A stale/hand-edited table block larger than the instance must
    cost its own tile only — the epilogue pads to the CLAMPED block,
    mirroring the attention/LN kernels."""
    import jax.numpy as jnp
    import numpy as onp
    x = jnp.asarray(onp.random.RandomState(0).randn(16, 128), jnp.float32)
    s = jnp.ones((1, 128), jnp.float32)
    t = jnp.zeros((1, 128), jnp.float32)
    y = FN.pallas_epilogue_fwd(x, s, t, x, interpret=True,
                               block_r=512, block_c=1024)
    ref = FN._jnp_epilogue(x, s, t, x)
    assert y.shape == (16, 128)
    assert float(jnp.max(jnp.abs(y - ref))) < 1e-6


# --- offline CLI (interpret mode, tiny shape) ------------------------------

def test_offline_cli_searches_and_persists(capsys):
    """python -m mxnet_tpu.tune end to end on CPU via interpret mode:
    real Pallas measurements, winner persisted, --list round-trip."""
    from mxnet_tpu.tune.__main__ import main
    path = os.environ["MXNET_AUTOTUNE_TABLE"]
    rc = main(["--family", "layernorm", "--shape", "64:128",
               "--dtype", "float32", "--interpret", "--trials", "2",
               "--calls", "1"])
    assert rc == 0
    line = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert line["config"]["block_rows"] in (8, 16, 32, 64, 512)
    assert line["trials"] == 2 and line["best_ms"] > 0
    rc = main(["--list"])
    assert rc == 0
    rec = json.loads(capsys.readouterr().out.strip())
    assert rec["family"] == "layernorm" and rec["source"] == "offline"
    assert os.path.exists(path)
    # and the layernorm picker now serves it (same-process dispatch)
    tune._reset_for_tests()
    assert LN._pick_block_rows(128, rows=64) == \
        rec["config"]["block_rows"]


# --- telemetry census / parse_log round-trip -------------------------------

def test_parse_log_renders_autotune_census(tmp_path):
    import sys
    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))), "tools"))
    import parse_log

    tune.get_table().record("attention", (512, 512, 64), "bfloat16",
                            {"block_q": 256, "block_k": 512})
    PA.attention_dispatch(512, 512, 64, "bfloat16", on_tpu=True)   # hit
    PA.attention_dispatch(4096, 4096, 64, "bfloat16", on_tpu=True)  # miss
    path = str(tmp_path / "telemetry.jsonl")
    telemetry.export_jsonl(path)
    with open(path) as fh:
        agg = parse_log.parse_jsonl(fh)
    sources = [(e["family"], e["source"]) for e in agg["autotune"]]
    assert ("attention", "hit") in sources
    assert ("attention", "miss") in sources
    hit = next(e for e in agg["autotune"]
               if e["source"] == "hit" and e["shape"] == [512, 512, 64])
    assert hit["config"] == {"block_q": 256, "block_k": 512}
    text = parse_log.render_jsonl(agg)
    assert "autotune decisions" in text
    assert "512x512x64" in text and "block_q=256" in text
    assert "counter:autotune.hit" in text