"""Profiler facade (reference tests/python/unittest/test_profiler.py)."""
import os
import tempfile

import numpy as onp

import mxnet_tpu as mx
from mxnet_tpu import profiler


def test_profiler_trace_roundtrip():
    with tempfile.TemporaryDirectory() as d:
        profiler.set_config(profile_dir=d)
        profiler.set_state("run")
        x = mx.nd.array(onp.random.rand(32, 32).astype("float32"))
        x.attach_grad()
        with mx.autograd.record():
            y = (x * x).sum()
        y.backward()
        y.asnumpy()
        profiler.set_state("stop")
        out = profiler.dump()
        assert out == d
        # jax writes plugins/profile/<ts>/*; any artifact counts
        found = []
        for root, _, files in os.walk(d):
            found.extend(files)
        assert found, "no trace artifacts written"


def test_profiler_objects():
    from mxnet_tpu import telemetry
    telemetry.reset()
    dom = profiler.Domain("net")
    task = dom.new_task("fwd")
    counter = dom.new_counter("steps", 0)
    profiler.set_config(profile_dir=tempfile.mkdtemp())
    profiler.start()
    with task:
        counter += 1
    dom.new_marker("epoch").mark()
    profiler.stop()
    assert counter.get_value() == 1
    assert profiler.state() == "stop"
    # the objects are no longer inert: spans/counters/markers land in
    # the telemetry journal and snapshot
    snap = telemetry.snapshot()
    assert snap["spans"]["profiler.net::fwd"]["count"] == 1
    assert snap["gauges"]["profiler.net.steps"] == 1
    assert any(e["kind"] == "marker" and e["name"] == "net::epoch"
               for e in snap["events"])
    telemetry.reset()


def test_profiler_pause_resume_no_double_start():
    """pause keeps the logical 'run' state, and set_state('run') on a
    paused capture RESUMES it (same dir) instead of double-starting a
    fresh trace."""
    with tempfile.TemporaryDirectory() as d:
        profiler.set_config(profile_dir=d)
        profiler.set_state("run")
        assert profiler.state() == "run"
        profiler.pause()
        assert profiler.state() == "run"       # paused, still logically running
        assert profiler._STATE["paused"]
        profiler.set_state("run")              # must resume, not restart
        assert not profiler._STATE["paused"]
        assert profiler._STATE["dir"] == d
        profiler.pause()
        profiler.resume()
        assert not profiler._STATE["paused"]
        profiler.set_state("stop")
        assert profiler.state() == "stop"
        # stopping while paused must not call stop_trace twice
        profiler.set_state("run")
        profiler.pause()
        profiler.set_state("stop")
        assert profiler.state() == "stop" and not profiler._STATE["paused"]
