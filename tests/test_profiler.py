"""Profiler facade (reference tests/python/unittest/test_profiler.py)."""
import os
import tempfile

import numpy as onp

import mxnet_tpu as mx
from mxnet_tpu import profiler


def test_profiler_trace_roundtrip():
    with tempfile.TemporaryDirectory() as d:
        profiler.set_config(profile_dir=d)
        profiler.set_state("run")
        x = mx.nd.array(onp.random.rand(32, 32).astype("float32"))
        x.attach_grad()
        with mx.autograd.record():
            y = (x * x).sum()
        y.backward()
        y.asnumpy()
        profiler.set_state("stop")
        out = profiler.dump()
        assert out == d
        # jax writes plugins/profile/<ts>/*; any artifact counts
        found = []
        for root, _, files in os.walk(d):
            found.extend(files)
        assert found, "no trace artifacts written"


def test_profiler_objects():
    dom = profiler.Domain("net")
    task = dom.new_task("fwd")
    counter = dom.new_counter("steps", 0)
    profiler.set_config(profile_dir=tempfile.mkdtemp())
    profiler.start()
    with task:
        counter += 1
    dom.new_marker("epoch").mark()
    profiler.stop()
    assert counter.get_value() == 1
    assert profiler.state() == "stop"
