"""KV-heartbeat liveness fallback edges (PR 5's kill-test machinery).

``num_dead_node`` falls back to the ``mxtpu/hb/<rank>`` heartbeat
records when the jax coordination client has no ``get_live_nodes``.
These are the unit-level edge cases no multiprocess run covers: stale
and garbled timestamp payloads, peers that never wrote a record, and a
coordinator that flaps (raises) partway through the scan — none of
which may crash the query; they count the affected peer dead and move
on.
"""
import time

import jax
import pytest

import mxnet_tpu as mx
from mxnet_tpu import kvstore as kvs


class FakeClient:
    """Coordinator KV-store stand-in WITHOUT get_live_nodes (forces the
    heartbeat fallback path)."""

    def __init__(self, records=None, fail_on=()):
        self.records = dict(records or {})
        self.fail_on = set(fail_on)
        self.calls = 0

    def blocking_key_value_get(self, key, timeout_ms):
        self.calls += 1
        assert timeout_ms >= 50, "per-peer budget must stay readable"
        if key in self.fail_on:
            raise RuntimeError("coordination service flapped")
        if key not in self.records:
            raise KeyError(key)
        return self.records[key]


def test_fresh_heartbeats_count_alive():
    now = time.time()
    c = FakeClient({kvs._HB_KEY % 1: repr(now),
                    kvs._HB_KEY % 2: repr(now)})
    assert kvs._heartbeat_dead_count(c, [0, 1, 2], timeout=1) == 0


def test_stale_heartbeat_counts_dead():
    now = time.time()
    c = FakeClient({kvs._HB_KEY % 1: repr(now - 1e4),
                    kvs._HB_KEY % 2: repr(now)})
    assert kvs._heartbeat_dead_count(c, [0, 1, 2], timeout=1) == 1


@pytest.mark.parametrize("payload", ["definitely-not-a-timestamp", "",
                                     "1.2.3", b"\xff\xfe"])
def test_garbled_payload_counts_dead_without_crashing(payload):
    """A corrupt heartbeat record (torn write, wrong encoding) is a dead
    peer, not an exception out of num_dead_node."""
    now = time.time()
    c = FakeClient({kvs._HB_KEY % 1: payload,
                    kvs._HB_KEY % 2: repr(now)})
    assert kvs._heartbeat_dead_count(c, [0, 1, 2], timeout=1) == 1


def test_bytes_timestamp_payload_is_readable():
    # the coordination service may hand back bytes; a well-formed
    # timestamp still parses
    now = time.time()
    c = FakeClient({kvs._HB_KEY % 1: repr(now).encode()})
    assert kvs._heartbeat_dead_count(c, [0, 1], timeout=1) == 0


def test_missing_peer_counts_dead():
    c = FakeClient({})
    assert kvs._heartbeat_dead_count(c, [0, 1], timeout=1) == 1


def test_flapping_coordinator_mid_scan_does_not_crash():
    """Peer 1's record reads fine, peer 2's read blows up mid-scan
    (coordinator restart), peer 3's record is fine again — only the
    flapped read counts dead."""
    now = time.time()
    c = FakeClient({kvs._HB_KEY % 1: repr(now),
                    kvs._HB_KEY % 3: repr(now)},
                   fail_on={kvs._HB_KEY % 2})
    assert kvs._heartbeat_dead_count(c, [0, 1, 2, 3], timeout=1) == 1


def test_own_rank_never_polled():
    """The querying process must not read (or misjudge) its own record
    — jax.process_index() is excluded from the scan."""
    c = FakeClient({})     # nothing written, including rank 0 (me)
    assert kvs._heartbeat_dead_count(c, [0], timeout=1) == 0
    assert c.calls == 0


def test_num_dead_node_uses_heartbeat_fallback(monkeypatch):
    """End-to-end through KVStoreTPU.num_dead_node: a client without
    get_live_nodes routes into the heartbeat scan and survives a
    flapping coordinator."""
    from jax._src import distributed as _dist
    now = time.time()
    client = FakeClient({kvs._HB_KEY % 1: repr(now - 1e5)},
                        fail_on={kvs._HB_KEY % 2})
    monkeypatch.setattr(_dist.global_state, "client", client,
                        raising=False)
    monkeypatch.setattr(jax, "process_count", lambda: 3)
    kv = kvs.KVStoreTPU.__new__(kvs.KVStoreTPU)
    assert kv.num_dead_node(timeout=1) == 2     # stale + flapped
