"""KV-heartbeat liveness fallback edges (PR 5's kill-test machinery).

``num_dead_node`` falls back to the ``mxtpu/hb/<rank>`` heartbeat
records when the jax coordination client has no ``get_live_nodes``.
These are the unit-level edge cases no multiprocess run covers: stale
and garbled timestamp payloads, peers that never wrote a record, and a
coordinator that flaps (raises) partway through the scan — none of
which may crash the query; they count the affected peer dead and move
on.
"""
import time

import jax
import pytest

import mxnet_tpu as mx
from mxnet_tpu import kvstore as kvs


class FakeClient:
    """Coordinator KV-store stand-in WITHOUT get_live_nodes (forces the
    heartbeat fallback path)."""

    def __init__(self, records=None, fail_on=()):
        self.records = dict(records or {})
        self.fail_on = set(fail_on)
        self.calls = 0

    def blocking_key_value_get(self, key, timeout_ms):
        self.calls += 1
        assert timeout_ms >= 50, "per-peer budget must stay readable"
        if key in self.fail_on:
            raise RuntimeError("coordination service flapped")
        if key not in self.records:
            raise KeyError(key)
        return self.records[key]


def test_fresh_heartbeats_count_alive():
    now = time.time()
    c = FakeClient({kvs._HB_KEY % 1: repr(now),
                    kvs._HB_KEY % 2: repr(now)})
    assert kvs._heartbeat_dead_count(c, [0, 1, 2], timeout=1) == 0


def test_stale_heartbeat_counts_dead():
    now = time.time()
    c = FakeClient({kvs._HB_KEY % 1: repr(now - 1e4),
                    kvs._HB_KEY % 2: repr(now)})
    assert kvs._heartbeat_dead_count(c, [0, 1, 2], timeout=1) == 1


@pytest.mark.parametrize("payload", ["definitely-not-a-timestamp", "",
                                     "1.2.3", b"\xff\xfe"])
def test_garbled_payload_counts_dead_without_crashing(payload):
    """A corrupt heartbeat record (torn write, wrong encoding) is a dead
    peer, not an exception out of num_dead_node."""
    now = time.time()
    c = FakeClient({kvs._HB_KEY % 1: payload,
                    kvs._HB_KEY % 2: repr(now)})
    assert kvs._heartbeat_dead_count(c, [0, 1, 2], timeout=1) == 1


def test_bytes_timestamp_payload_is_readable():
    # the coordination service may hand back bytes; a well-formed
    # timestamp still parses
    now = time.time()
    c = FakeClient({kvs._HB_KEY % 1: repr(now).encode()})
    assert kvs._heartbeat_dead_count(c, [0, 1], timeout=1) == 0


def test_missing_peer_counts_dead():
    c = FakeClient({})
    assert kvs._heartbeat_dead_count(c, [0, 1], timeout=1) == 1


def test_flapping_coordinator_mid_scan_does_not_crash():
    """Peer 1's record reads fine, peer 2's read blows up mid-scan
    (coordinator restart), peer 3's record is fine again — only the
    flapped read counts dead."""
    now = time.time()
    c = FakeClient({kvs._HB_KEY % 1: repr(now),
                    kvs._HB_KEY % 3: repr(now)},
                   fail_on={kvs._HB_KEY % 2})
    assert kvs._heartbeat_dead_count(c, [0, 1, 2, 3], timeout=1) == 1


def test_own_rank_never_polled():
    """The querying process must not read (or misjudge) its own record
    — jax.process_index() is excluded from the scan."""
    c = FakeClient({})     # nothing written, including rank 0 (me)
    assert kvs._heartbeat_dead_count(c, [0], timeout=1) == 0
    assert c.calls == 0


class PublishClient(FakeClient):
    """FakeClient that also accepts the publisher's writes."""

    def __init__(self, *a, **kw):
        super().__init__(*a, **kw)
        self.sets = []
        self.fail_sets = False

    def key_value_set(self, key, value, allow_overwrite=None):
        if self.fail_sets:
            raise RuntimeError("coordination service flapped")
        self.sets.append((key, value))
        self.records[key] = value


@pytest.fixture
def publisher_env(monkeypatch):
    """Multi-process environment without get_live_nodes: the heartbeat
    publisher path, with a short window so beats come fast."""
    from jax._src import distributed as _dist
    client = PublishClient()
    monkeypatch.setattr(_dist.global_state, "client", client,
                        raising=False)
    monkeypatch.setattr(jax, "process_count", lambda: 2)
    monkeypatch.setattr(jax, "process_index", lambda: 0)
    monkeypatch.setenv("MXNET_TPU_HEARTBEAT_TIMEOUT", "2")
    kvs._stop_liveness_heartbeat()      # clean slate
    yield client
    kvs._stop_liveness_heartbeat()


def test_heartbeat_publisher_stop_signals_and_joins(publisher_env):
    """Regression (conc-thread-lifecycle): the publisher daemon now has
    a paired stop Event + join.  Stop must interrupt the inter-beat
    Event.wait instead of sleeping out the interval, and leave the
    module state restartable."""
    client = publisher_env
    kvs._start_liveness_heartbeat()
    t = kvs._hb_state["thread"]
    assert t is not None and t.is_alive()
    deadline = time.time() + 5
    while not client.sets and time.time() < deadline:
        time.sleep(0.01)
    assert client.sets and client.sets[0][0] == kvs._HB_KEY % 0

    t0 = time.time()
    kvs._stop_liveness_heartbeat()
    elapsed = time.time() - t0
    assert not t.is_alive()
    # interval is window/4 = 0.5 s; an un-signalled thread would hold
    # the join for a full sleep — the Event.wait returns immediately
    assert elapsed < 0.45, "stop did not interrupt the beat wait"
    assert kvs._hb_state["thread"] is None
    assert kvs._hb_state["stop"] is None
    # idempotent on an already-stopped publisher
    kvs._stop_liveness_heartbeat()

    # restartable: a later store may start a fresh publisher
    kvs._start_liveness_heartbeat()
    t2 = kvs._hb_state["thread"]
    assert t2 is not None and t2.is_alive() and t2 is not t


def test_kvstore_close_stops_publisher(publisher_env):
    """KVStoreTPU.close() is the user-facing shutdown path."""
    kv = kvs.KVStoreTPU("tpu")
    t = kvs._hb_state["thread"]
    assert t is not None and t.is_alive()
    kv.close()
    assert not t.is_alive()
    assert kvs._hb_state["thread"] is None
    kv.close()                          # idempotent


def test_publisher_survives_flap_until_stopped(publisher_env):
    """Transient coordinator failures must not kill the publisher
    (bounded backoff, MXNET_TPU_HEARTBEAT_RETRIES consecutive misses
    before give-up); recovery resumes publishing, and stop still joins
    cleanly mid-flap."""
    client = publisher_env
    client.fail_sets = True
    kvs._start_liveness_heartbeat()
    t = kvs._hb_state["thread"]
    time.sleep(0.1)
    assert t.is_alive()                 # one-ish miss is not fatal
    client.fail_sets = False
    deadline = time.time() + 5
    while not client.sets and time.time() < deadline:
        time.sleep(0.01)
    assert client.sets, "publisher did not recover from the flap"
    kvs._stop_liveness_heartbeat()
    assert not t.is_alive()


def test_publisher_backoff_giveup_journals_once(publisher_env,
                                                monkeypatch):
    """Satellite (elastic hardening): a coordinator that stays dead
    past the bounded retry budget makes the publisher exit — with
    every miss counted in ``elastic.heartbeat_misses`` and EXACTLY ONE
    ``elastic/publisher_giveup`` journal event — instead of the old
    hard 5-consecutive-miss silent exit.  The give-up also dumps an
    incident bundle (err-incident-trigger contract: a worker that goes
    dark to its peers must leave a postmortem)."""
    from mxnet_tpu import flight_recorder, telemetry
    client = publisher_env
    client.fail_sets = True
    monkeypatch.setenv("MXNET_TPU_HEARTBEAT_RETRIES", "2")
    m0 = telemetry.counter("elastic.heartbeat_misses")
    kvs._start_liveness_heartbeat()
    t = kvs._hb_state["thread"]
    deadline = time.time() + 10
    while t.is_alive() and time.time() < deadline:
        time.sleep(0.02)
    assert not t.is_alive(), "publisher did not give up after the budget"
    assert telemetry.counter("elastic.heartbeat_misses") - m0 == 2
    ev = [e for e in telemetry.snapshot(events=512)["events"]
          if e["kind"] == "elastic" and e["name"] == "publisher_giveup"]
    assert len(ev) == 1 and ev[0]["misses"] == 2 and ev[0]["rank"] == 0
    assert flight_recorder.bundles_dumped() == 1, \
        "publisher give-up must leave an incident bundle"


def test_publisher_backoff_spacing(publisher_env, monkeypatch):
    """Retries back off exponentially (with jitter) instead of
    hammering a struggling coordinator at the fixed beat interval:
    with retries=3 the give-up takes at least interval + 2*interval
    of backoff waits."""
    client = publisher_env
    client.fail_sets = True
    monkeypatch.setenv("MXNET_TPU_HEARTBEAT_RETRIES", "3")
    kvs._start_liveness_heartbeat()
    t = kvs._hb_state["thread"]
    t0 = time.time()
    deadline = t0 + 15
    while t.is_alive() and time.time() < deadline:
        time.sleep(0.02)
    assert not t.is_alive()
    # interval = window/4 = 0.5s: miss1 waits >=0.5, miss2 waits >=1.0,
    # miss3 gives up immediately -> at least ~1.5s total
    assert time.time() - t0 >= 1.4, "no backoff between retries"


def test_publisher_drop_heartbeat_chaos_fault(publisher_env):
    """chaos drop_heartbeat: the worker stays alive but publishes
    nothing (a partition, as peers see it); clearing the fault resumes
    beats — the seam the multiprocess chaos matrix drives."""
    from mxnet_tpu.parallel import chaos
    client = publisher_env
    chaos.install("drop_heartbeat", rank=0)
    try:
        kvs._start_liveness_heartbeat()
        t = kvs._hb_state["thread"]
        time.sleep(0.3)
        assert t.is_alive() and not client.sets, \
            "dropped beats must not reach the coordinator"
        chaos.clear("drop_heartbeat")
        deadline = time.time() + 5
        while not client.sets and time.time() < deadline:
            time.sleep(0.01)
        assert client.sets, "publisher did not resume after the fault"
    finally:
        chaos.clear()


def test_num_dead_node_uses_heartbeat_fallback(monkeypatch):
    """End-to-end through KVStoreTPU.num_dead_node: a client without
    get_live_nodes routes into the heartbeat scan and survives a
    flapping coordinator."""
    from jax._src import distributed as _dist
    now = time.time()
    client = FakeClient({kvs._HB_KEY % 1: repr(now - 1e5)},
                        fail_on={kvs._HB_KEY % 2})
    monkeypatch.setattr(_dist.global_state, "client", client,
                        raising=False)
    monkeypatch.setattr(jax, "process_count", lambda: 3)
    kv = kvs.KVStoreTPU.__new__(kvs.KVStoreTPU)
    assert kv.num_dead_node(timeout=1) == 2     # stale + flapped
