"""Worker body for the hybrid-topology distributed test: 2 processes x 4
virtual CPU devices each — the DCN (process boundary) x ICI (intra-process)
shape of a real multi-host pod, exercised exactly as ``tools/launch.py``
spawns real workers (reference fixture ``tools/launch.py:101-116`` local
mode; capability parity with the reference's multi-machine + multi-GPU
``dist_sync`` topology, ``docs/faq/distributed_training.md``).

Covers, on a global 2x4 ``(dp, tp)`` mesh:
  1. bit-exact hybrid aggregation — a jitted loss/grad step whose batch is
     sharded over BOTH axes; integer-valued data makes every summation
     order exact, so the asserted equality is bitwise;
  2. ring attention over a process-spanning ``sp`` axis (the ppermute ring
     crosses DCN twice per rotation);
  3. a GPipe pipeline whose ``pp`` axis is the process boundary (stage 0
     on host 0, stage 1 on host 1) with a 4-wide secondary axis.
"""
import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as onp  # noqa: E402


def main():
    import jax
    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from mxnet_tpu import parallel
    from mxnet_tpu.parallel.mesh import shard_map_compat

    parallel.initialize()
    assert jax.process_count() == 2, jax.process_count()
    devs = jax.devices()
    assert len(devs) == 8, len(devs)
    # rows = processes (DCN), columns = local devices (ICI)
    grid = onp.array(devs).reshape(2, 4)
    assert all(d.process_index == r for r in range(2) for d in grid[r]), \
        "device order does not group by process"
    mesh = Mesh(grid, ("dp", "tp"))

    def make_global(np_arr, spec):
        sh = NamedSharding(mesh, spec)
        return jax.make_array_from_callback(
            np_arr.shape, sh, lambda idx: np_arr[idx])

    # ---- 1) hybrid-sharded grad step, bitwise-exact ----------------------
    rs = onp.random.RandomState(0)
    X = rs.randint(-3, 4, (16, 8)).astype("float32")   # ints: exact sums
    Y = rs.randint(-3, 4, (16,)).astype("float32")
    W = rs.randint(-2, 3, (8,)).astype("float32")
    xg = make_global(X, P(("dp", "tp"), None))          # batch over BOTH axes
    yg = make_global(Y, P(("dp", "tp")))
    wg = make_global(W, P())                            # replicated params

    @jax.jit
    def grad_step(w, x, y):
        def loss(w):
            return jnp.sum((x @ w - y) ** 2)            # exact in f32 (ints)
        return jax.grad(loss)(w)

    g = grad_step(wg, xg, yg)
    g_local = onp.asarray(
        jax.device_get(g.addressable_shards[0].data))
    g_ref = 2.0 * X.T @ (X @ W - Y)
    onp.testing.assert_array_equal(g_local, g_ref)       # BITWISE
    for sh in g.addressable_shards:                      # replica agreement
        onp.testing.assert_array_equal(onp.asarray(jax.device_get(sh.data)),
                                       g_ref)

    # ---- 2) ring attention with sp spanning the process boundary --------
    mesh_sp = Mesh(onp.array(devs), ("sp",))
    B, H, T, D = 2, 2, 64, 16                           # 8 chunks of 8
    q = rs.uniform(-1, 1, (B, H, T, D)).astype("float32")
    k = rs.uniform(-1, 1, (B, H, T, D)).astype("float32")
    v = rs.uniform(-1, 1, (B, H, T, D)).astype("float32")
    spec = P(None, None, "sp", None)
    sh_sp = NamedSharding(mesh_sp, spec)
    qg = jax.make_array_from_callback(q.shape, sh_sp, lambda i: q[i])
    kg = jax.make_array_from_callback(k.shape, sh_sp, lambda i: k[i])
    vg = jax.make_array_from_callback(v.shape, sh_sp, lambda i: v[i])

    import functools
    from mxnet_tpu.parallel.ring_attention import ring_attention
    fn = jax.jit(shard_map_compat(
        functools.partial(ring_attention, axis_name="sp", causal=True),
        mesh=mesh_sp, in_specs=(spec, spec, spec), out_specs=spec))
    out = fn(qg, kg, vg)

    # dense causal reference, computed locally from the full arrays
    s = onp.einsum("bhqd,bhkd->bhqk", q, k) / onp.sqrt(D)
    mask = onp.tril(onp.ones((T, T), bool))
    s = onp.where(mask, s, -1e30)
    p = onp.exp(s - s.max(-1, keepdims=True))
    p /= p.sum(-1, keepdims=True)
    ref = onp.einsum("bhqk,bhkd->bhqd", p, v)
    for sh in out.addressable_shards:
        sl = sh.index[2]
        got = onp.asarray(jax.device_get(sh.data))
        onp.testing.assert_allclose(got, ref[:, :, sl, :], atol=2e-5,
                                    rtol=1e-4)

    # ---- 3) pipeline with pp across the DCN boundary --------------------
    from mxnet_tpu.parallel.pipeline import pipeline_train_step
    mesh_pp = Mesh(grid, ("pp", "mp"))                  # pp = processes
    n_micro, mb, dim = 4, 4, 8
    w0 = rs.uniform(-0.5, 0.5, (dim, dim)).astype("float32")
    w1 = rs.uniform(-0.5, 0.5, (dim, 1)).astype("float32")
    xs = rs.uniform(-1, 1, (n_micro, mb, dim)).astype("float32")
    ys = rs.uniform(-1, 1, (n_micro, mb, 1)).astype("float32")

    def stage0(p0, x):
        return jnp.tanh(x @ p0)

    def stage1(p1, act, y):
        return jnp.mean((act @ p1 - y) ** 2)

    def mk(npv, spec=P()):
        shd = NamedSharding(mesh_pp, spec)
        return jax.make_array_from_callback(npv.shape, shd,
                                            lambda i: npv[i])

    with mesh_pp:
        loss = pipeline_train_step(
            [stage0, stage1], (mk(w0), mk(w1)), mk(xs), mk(ys), mesh_pp)
    got = float(onp.asarray(jax.device_get(loss.addressable_shards[0].data)))
    act = onp.tanh(xs @ w0)
    want = float(onp.mean((act @ w1 - ys) ** 2))
    assert abs(got - want) < 1e-5, (got, want)

    print("HYBRID-WORKER %d/2 OK" % jax.process_index())


if __name__ == "__main__":
    main()
