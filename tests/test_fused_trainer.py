"""Fused Trainer update path: one jitted program vs eager per-param loop."""
import numpy as onp

import mxnet_tpu as mx
from mxnet_tpu import gluon
from mxnet_tpu.gluon import nn


def _make_net(seed=0):
    mx.random.seed(seed)
    net = nn.HybridSequential()
    net.add(nn.Dense(16, activation="relu"))
    net.add(nn.Dense(4))
    net.initialize(mx.init.Xavier())
    x = mx.nd.array(onp.random.RandomState(0).rand(8, 10).astype("float32"))
    net(x)  # materialize
    return net, x


def _train_steps(net, x, trainer, n=3):
    loss_fn = gluon.loss.L2Loss()
    y = mx.nd.array(onp.random.RandomState(1).rand(8, 4).astype("float32"))
    for _ in range(n):
        with mx.autograd.record():
            loss = loss_fn(net(x), y)
        loss.backward()
        trainer.step(8)
    # global name counters differ between nets; compare by insertion
    # position (sorting by name breaks when counters cross a digit
    # boundary, e.g. dense9 vs dense10)
    return [v.data().asnumpy() for v in net.collect_params().values()]


def test_fused_matches_eager_sgd():
    net1, x1 = _make_net()
    t1 = gluon.Trainer(net1.collect_params(), "sgd",
                       {"learning_rate": 0.1, "momentum": 0.9, "wd": 1e-4})
    out_fused = _train_steps(net1, x1, t1)
    assert t1._kv_fused is not None and not t1._kv_fused._unavailable

    net2, x2 = _make_net()
    t2 = gluon.Trainer(net2.collect_params(), "sgd",
                       {"learning_rate": 0.1, "momentum": 0.9, "wd": 1e-4})
    t2._fused_on_kvstore = lambda: False  # force eager push/pull path
    out_eager = _train_steps(net2, x2, t2)

    for a, b in zip(out_fused, out_eager):
        onp.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6)


def test_fused_matches_eager_adam():
    net1, x1 = _make_net()
    t1 = gluon.Trainer(net1.collect_params(), "adam",
                       {"learning_rate": 0.01})
    out_fused = _train_steps(net1, x1, t1)

    net2, x2 = _make_net()
    t2 = gluon.Trainer(net2.collect_params(), "adam",
                       {"learning_rate": 0.01})
    t2._fused_on_kvstore = lambda: False
    out_eager = _train_steps(net2, x2, t2)

    for a, b in zip(out_fused, out_eager):
        onp.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6)


def test_fused_lr_schedule_advances():
    """The scheduled lr must advance inside the fused (cached-jit) path."""
    net, x = _make_net()
    sched = mx.lr_scheduler.FactorScheduler(step=1, factor=0.5)
    t = gluon.Trainer(net.collect_params(), "sgd",
                      {"learning_rate": 1.0, "lr_scheduler": sched})
    loss_fn = gluon.loss.L2Loss()
    y = mx.nd.zeros((8, 4))
    lrs = []
    for _ in range(3):
        with mx.autograd.record():
            loss = loss_fn(net(x), y)
        loss.backward()
        t.step(8)
        lrs.append(t._optimizer.learning_rate)
    assert lrs[0] > lrs[1] > lrs[2], lrs


def test_fused_update_on_kvstore_false():
    """update_on_kvstore=False exercises the Trainer-level fused updater."""
    net, x = _make_net()
    t = gluon.Trainer(net.collect_params(), "sgd", {"learning_rate": 0.1},
                      update_on_kvstore=False)
    out = _train_steps(net, x, t)
    assert t._local_fused is not None and not t._local_fused._unavailable
    for v in out:
        assert onp.isfinite(v).all()


def test_fused_save_load_states_roundtrip():
    import tempfile, os
    net, x = _make_net()
    t = gluon.Trainer(net.collect_params(), "sgd",
                      {"learning_rate": 0.1, "momentum": 0.9})
    _train_steps(net, x, t, n=2)
    with tempfile.TemporaryDirectory() as d:
        fname = os.path.join(d, "trainer.states")
        t.save_states(fname)
        t.load_states(fname)
    _train_steps(net, x, t, n=1)


def test_reseed_restarts_step_rng_trajectory():
    """mx.random.seed() mid-run must restart the compiled step's
    on-device RNG carry: identical seeds => identical dropout/loss
    trajectories (regression: the carried key once ignored reseeds)."""
    import numpy as onp
    import mxnet_tpu as mx
    from mxnet_tpu import gluon
    from mxnet_tpu.gluon import nn

    def run():
        mx.random.seed(11)
        onp.random.seed(1)
        net = nn.HybridSequential()
        net.add(nn.Dense(16, activation="relu"), nn.Dropout(0.5),
                nn.Dense(4))
        net.initialize(mx.init.Xavier())
        x = mx.nd.array(onp.random.rand(8, 6).astype("float32"))
        y = mx.nd.array(onp.random.randint(0, 4, (8,)).astype("float32"))
        net(x)
        step = mx.parallel.DataParallelStep(
            net, gluon.loss.SoftmaxCrossEntropyLoss(),
            mx.optimizer.SGD(learning_rate=0.1), mesh=None)
        return [float(step(x, y).asnumpy()) for _ in range(3)]

    first = run()
    second = run()
    assert first == second, (first, second)
