"""Monitor / visualization / runtime features / engine knobs (reference
tests: test_monitor in test_operator.py, runtime feature tests)."""
import numpy as onp

import mxnet_tpu as mx
from mxnet_tpu import sym


def _mlp():
    data = sym.var("data")
    net = sym.FullyConnected(data, num_hidden=8, name="fc1")
    net = sym.Activation(net, act_type="relu", name="relu1")
    net = sym.FullyConnected(net, num_hidden=3, name="fc2")
    return sym.SoftmaxOutput(net, name="softmax")


def test_monitor_collects_stats():
    net = _mlp()
    mod = mx.mod.Module(net, context=mx.cpu())
    mod.bind(data_shapes=[("data", (4, 6))],
             label_shapes=[("softmax_label", (4,))])
    mod.init_params(mx.init.Xavier())
    mon = mx.monitor.Monitor(interval=1, pattern=".*weight.*")
    mod.install_monitor(mon)
    batch = mx.io.DataBatch(
        data=[mx.nd.array(onp.ones((4, 6), onp.float32))],
        label=[mx.nd.array(onp.zeros(4, onp.float32))])
    mon.tic()
    mod.forward(batch, is_train=True)
    mod.backward()
    res = mon.toc_print()
    names = {k for _, k, _ in res}
    assert "fc1_weight" in names and "fc2_weight" in names
    assert "fc1_weight_grad" in names
    assert all("bias" not in n for n in names)


def test_monitor_interval():
    mon = mx.monitor.Monitor(interval=2)
    mon.tic()
    assert mon.activated
    mon.toc()
    mon.tic()
    assert not mon.activated


def test_print_summary(capsys):
    net = _mlp()
    total = mx.viz.print_summary(net, shape={"data": (4, 6)})
    out = capsys.readouterr().out
    assert "fc1 (FullyConnected)" in out
    assert "softmax (SoftmaxOutput)" in out
    # fc1: 6*8+8=56, fc2: 8*3+3=27
    assert total == 83


def test_runtime_features():
    feats = mx.runtime.Features()
    assert feats.is_enabled("CPU")
    assert feats.is_enabled("BF16")
    assert not feats.is_enabled("CUDNN")
    assert any(f.name == "TPU" for f in mx.runtime.feature_list())
    try:
        feats.is_enabled("NOPE")
        raise AssertionError("should raise")
    except RuntimeError:
        pass


def test_engine_knobs():
    assert mx.engine.engine_type() == "ThreadedEnginePerDevice"
    with mx.engine.naive_engine():
        assert mx.engine.engine_type() == "NaiveEngine"
        # ops still work eagerly under disable_jit
        x = mx.nd.array(onp.ones(3, onp.float32))
        assert float((x + x).sum().asscalar()) == 6.0
    assert mx.engine.engine_type() == "ThreadedEnginePerDevice"
    prev = mx.engine.set_bulk_size(4)
    with mx.engine.bulk(32):
        pass
    mx.engine.set_bulk_size(prev)


def test_compilation_cache_purges_unsafe_entries(tmp_path):
    """enable_compilation_cache drops donated train-step executables
    (jit_step_fn/jit_scan_fn, and jit_fused since the ZeRO sharded
    update made the fused program relower after donation settles) from
    the cache dir: reloading a donation-settled pair of them is
    numerically wrong then fatal on jaxlib <= 0.4.36 (see
    engine._UNSAFE_CACHE_PREFIXES)."""
    import jax
    from mxnet_tpu import engine, telemetry
    d = tmp_path / "cache"
    d.mkdir()
    for name in ("jit_step_fn-abc123-cache", "jit_step_fn-abc123-atime",
                 "jit_scan_fn-def456-cache", "jit_fused-777-cache",
                 "jit_norm-888-cache"):
        (d / name).write_bytes(b"x")
    prev = jax.config.jax_compilation_cache_dir
    try:
        out = engine.enable_compilation_cache(str(d))
    finally:
        jax.config.update("jax_compilation_cache_dir", prev)
    assert out == str(d)
    left = sorted(p.name for p in d.iterdir())
    assert left == ["jit_norm-888-cache"]
    snap = telemetry.snapshot()
    ev = [e for e in snap["events"]
          if e["kind"] == "compilation_cache"]
    assert ev and ev[-1]["count"] == 4


def test_namespace_submodules_forward():
    """mx.nd.random / mx.nd.linalg / mx.sym.random / mx.sym.linalg mirror
    the upstream module layout (reference python/mxnet/ndarray/{random,
    linalg}.py and symbol twins)."""
    import numpy as onp
    import mxnet_tpu as mx
    from mxnet_tpu import symbol as sym

    mx.random.seed(0)
    assert mx.nd.random.normal(0, 1, (2, 3)).shape == (2, 3)
    assert mx.nd.random.randn(4, 2).shape == (4, 2)
    assert mx.random.uniform(0, 1, (3,)).shape == (3,)

    a = mx.nd.array(onp.eye(3, dtype="float32") * 4)
    onp.testing.assert_allclose(mx.nd.linalg.potrf(a).asnumpy(),
                                onp.eye(3) * 2, rtol=1e-5)
    x = sym.var("x")
    det = sym.linalg.det(x)
    got = det.eval_imperative({"x": a})
    assert abs(float(got.asnumpy()) - 64.0) < 1e-3
    assert sym.random.uniform(0, 1, shape=(2, 2)).eval_imperative(
        {}).shape == (2, 2)
