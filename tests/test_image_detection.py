"""Detection augmenter + ImageDetIter tests (reference
``tests/python/unittest/test_image.py`` detection sections)."""
import os

import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import image


def _img(h=40, w=60):
    rs = onp.random.RandomState(0)
    return mx.nd.array(rs.randint(0, 255, (h, w, 3)).astype("uint8"))


def _label():
    # rows: [cls, x1, y1, x2, y2]
    return onp.array([[0, 0.2, 0.2, 0.6, 0.7],
                      [1, 0.5, 0.1, 0.9, 0.4]], "float32")


def test_det_borrow_aug():
    aug = image.DetBorrowAug(image.CastAug())
    src, label = aug(_img(), _label())
    assert src.dtype == onp.float32
    assert onp.allclose(label, _label())


def test_det_horizontal_flip():
    import random
    random.seed(0)
    aug = image.DetHorizontalFlipAug(1.0)  # always flip
    x = _img()
    src, label = aug(x, _label())
    # image flipped
    assert onp.allclose(src.asnumpy(), x.asnumpy()[:, ::-1])
    # x coords mirrored: new_x1 = 1 - old_x2, new_x2 = 1 - old_x1
    want = _label()
    want[:, (1, 3)] = 1.0 - want[:, (3, 1)]
    assert onp.allclose(label, want, atol=1e-6)
    # y coords untouched
    assert onp.allclose(label[:, (2, 4)], _label()[:, (2, 4)])


def test_det_random_crop_labels_consistent():
    import random
    random.seed(42)
    aug = image.DetRandomCropAug(min_object_covered=0.1,
                                 area_range=(0.1, 1.0), max_attempts=50)
    for _ in range(10):
        src, label = aug(_img(), _label())
        # all surviving labels stay normalized and well-formed
        assert (label[:, 1:5] >= 0).all() and (label[:, 1:5] <= 1).all()
        assert (label[:, 3] > label[:, 1]).all()
        assert (label[:, 4] > label[:, 2]).all()
        assert label.shape[0] >= 1


def test_det_random_pad_labels_consistent():
    import random
    random.seed(1)
    aug = image.DetRandomPadAug(area_range=(1.5, 3.0))
    x = _img()
    src, label = aug(x, _label())
    h, w = src.shape[:2]
    assert h >= 40 and w >= 60 and (h > 40 or w > 60)
    # boxes shrink: areas in the padded frame must be <= original
    assert ((label[:, 3] - label[:, 1])
            <= (_label()[:, 3] - _label()[:, 1]) + 1e-6).all()


def test_det_random_pad_min_area():
    import random
    random.seed(2)
    aug = image.DetRandomPadAug(area_range=(2.0, 3.0),
                                aspect_ratio_range=(1.0, 1.0))
    for _ in range(10):
        src, _ = aug(_img(), _label())
        h, w = src.shape[:2]
        # canvas must honor the minimum area expansion
        assert h * w >= 2.0 * 40 * 60 * 0.9, (h, w)


def test_det_random_select_skip():
    aug = image.DetRandomSelectAug(
        [image.DetHorizontalFlipAug(1.0)], skip_prob=1.0)
    x = _img()
    src, label = aug(x, _label())
    assert onp.allclose(src.asnumpy(), x.asnumpy())


def test_create_det_augmenter():
    augs = image.CreateDetAugmenter((3, 30, 30), rand_crop=0.5,
                                    rand_pad=0.5, rand_mirror=True,
                                    mean=True, std=True, brightness=0.1,
                                    hue=0.1, rand_gray=0.1)
    assert len(augs) > 4
    src, label = _img(), _label()
    for aug in augs:
        src, label = aug(src, label)
    assert src.shape[:2] == (30, 30)
    assert label.shape[1] == 5


def test_multi_rand_crop_augmenter_aligns_params():
    aug = image.CreateMultiRandCropAugmenter(
        min_object_covered=[0.1, 0.5], area_range=(0.1, 1.0))
    assert len(aug.aug_list) == 2
    assert aug.aug_list[1].min_object_covered == 0.5


def _write_det_dataset(tmpdir, n=6):
    cv2 = pytest.importorskip("cv2")
    imglist = []
    rs = onp.random.RandomState(3)
    for i in range(n):
        fname = "img%d.png" % i
        cv2.imwrite(os.path.join(str(tmpdir), fname),
                    rs.randint(0, 255, (32 + i, 48, 3)).astype("uint8"))
        # header: [header_width=2, obj_width=5], then i%2+1 objects
        objs = []
        for j in range(i % 2 + 1):
            objs += [float(j), 0.1, 0.1, 0.6, 0.7]
        imglist.append([[2.0, 5.0] + objs, fname])
    return imglist


def test_image_det_iter(tmp_path):
    imglist = _write_det_dataset(tmp_path)
    it = image.ImageDetIter(batch_size=2, data_shape=(3, 24, 24),
                            imglist=imglist, path_root=str(tmp_path),
                            aug_list=image.CreateDetAugmenter((3, 24, 24)))
    # label shape estimated from the dataset: max 2 objects, width 5
    assert it.label_shape == (2, 5)
    assert it.provide_label[0].shape == (2, 2, 5)
    batches = list(it)
    assert len(batches) == 3
    b = batches[0]
    assert b.data[0].shape == (2, 3, 24, 24)
    assert b.label[0].shape == (2, 2, 5)
    lab = b.label[0].asnumpy()
    # single-object samples padded with -1 rows
    assert (lab[0, 1] == -1).all()
    # iterate again after reset
    it.reset()
    assert len(list(it)) == 3


def test_image_det_iter_reshape(tmp_path):
    imglist = _write_det_dataset(tmp_path)
    it = image.ImageDetIter(batch_size=2, data_shape=(3, 24, 24),
                            imglist=imglist, path_root=str(tmp_path),
                            aug_list=image.CreateDetAugmenter((3, 24, 24)))
    it.reshape(label_shape=(5, 5))
    assert it.provide_label[0].shape == (2, 5, 5)
    b = next(it)
    assert b.label[0].shape == (2, 5, 5)
    with pytest.raises(ValueError):
        it.check_label_shape((1, 5))
    with pytest.raises(ValueError):
        it.check_label_shape((5, 6))


def test_hue_and_gray_augs():
    import random
    random.seed(0)
    x = _img()
    out = image.HueJitterAug(0.5)(x)
    assert out.shape == x.shape
    gray = image.RandomGrayAug(1.0)(x)
    g = gray.asnumpy()
    assert onp.allclose(g[..., 0], g[..., 1], atol=1e-4)
    assert onp.allclose(g[..., 1], g[..., 2], atol=1e-4)


def test_copy_make_border():
    x = _img(4, 5)
    out = image.copyMakeBorder(x, 1, 2, 3, 4, values=(7, 8, 9))
    assert out.shape == (7, 12, 3)
    o = out.asnumpy()
    assert (o[0, 0] == [7, 8, 9]).all()
    assert onp.allclose(o[1:5, 3:8], x.asnumpy())
