"""The always-on telemetry layer (ISSUE 3): spans/counters/gauges,
recompile detection with cache-key diffs, prefetch/memory gauges in a
real Trainer run, step-hook-driven Monitor/Speedometer, exporters."""
import json
import logging

import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon, telemetry
from mxnet_tpu.gluon import nn
from mxnet_tpu.gluon import loss as gloss


@pytest.fixture(autouse=True)
def _clean_telemetry():
    """Telemetry state is process-global: every test starts from a
    clean slate and leaves no step hooks behind."""
    telemetry.reset()
    telemetry.enable()
    yield
    with telemetry._lock:
        telemetry._step_hooks.clear()
    telemetry.set_jsonl_sink(None)
    telemetry.reset()


def _make_net(in_dim=6, classes=4):
    net = nn.HybridSequential()
    net.add(nn.Dense(8, activation="relu"), nn.Dense(classes))
    net.initialize()
    net(mx.nd.array(onp.zeros((2, in_dim), "float32")))
    return net


def _float_feed(n_batches=4, bs=4, dim=6):
    """DevicePrefetchIter over a tiny synthetic float32 DataIter."""
    from mxnet_tpu.io import DataBatch, DataDesc, DataIter
    from mxnet_tpu.io import DevicePrefetchIter

    rs = onp.random.RandomState(0)
    batches = [rs.randn(bs, dim).astype("float32")
               for _ in range(n_batches)]
    labels = [rs.randint(0, 4, bs).astype("float32")
              for _ in range(n_batches)]

    class F32Iter(DataIter):
        def __init__(self):
            super().__init__(bs)
            self.i = 0

        @property
        def provide_data(self):
            return [DataDesc("data", (bs, dim))]

        @property
        def provide_label(self):
            return [DataDesc("softmax_label", (bs,))]

        def reset(self):
            self.i = 0

        def next(self):
            if self.i >= len(batches):
                raise StopIteration
            b = DataBatch([mx.nd.array(batches[self.i])],
                          [mx.nd.array(labels[self.i])])
            self.i += 1
            return b

    return DevicePrefetchIter(F32Iter(), dtype="float32", depth=2)


# ---------------------------------------------------------------------------
# primitives
# ---------------------------------------------------------------------------

def test_span_counter_gauge_event_snapshot():
    with telemetry.span("unit.work"):
        pass
    with telemetry.span("unit.work"):
        pass
    telemetry.inc("unit.count", 3)
    telemetry.inc("unit.count")
    telemetry.gauge("unit.g", 0.5)
    telemetry.event("phase", "warmup_done", detail=1)
    snap = telemetry.snapshot()
    agg = snap["spans"]["unit.work"]
    assert agg["count"] == 2
    assert agg["total_ms"] >= agg["max_ms"] >= agg["min_ms"] >= 0
    assert snap["counters"]["unit.count"] == 4
    assert snap["gauges"]["unit.g"] == 0.5
    kinds = [(e["kind"], e["name"]) for e in snap["events"]]
    assert ("span", "unit.work") in kinds
    assert ("phase", "warmup_done") in kinds
    telemetry.reset()
    snap = telemetry.snapshot()
    assert not snap["spans"] and not snap["counters"] and not snap["events"]


def test_disabled_is_noop():
    with telemetry.disabled():
        assert not telemetry.enabled()
        with telemetry.span("off.work"):
            pass
        telemetry.inc("off.c")
        telemetry.gauge("off.g", 1)
        telemetry.event("off", "e")
        telemetry.record_compile("off.fn", {"shape": [1]})
    assert telemetry.enabled()
    snap = telemetry.snapshot()
    assert "off.work" not in snap["spans"]
    assert "off.c" not in snap["counters"]
    assert not snap["events"] and not snap["compiles"]


def test_journal_is_bounded():
    for i in range(telemetry.JOURNAL_MAXLEN + 50):
        telemetry.event("tick", "t%d" % i)
    snap = telemetry.snapshot(events=0)
    with telemetry._lock:
        assert len(telemetry._journal) == telemetry.JOURNAL_MAXLEN


# ---------------------------------------------------------------------------
# recompile detector
# ---------------------------------------------------------------------------

def test_forced_retrace_names_changed_axis():
    """The acceptance shape: the SAME jitted step called with a changed
    batch axis must journal a recompile event naming that axis."""
    net = _make_net()
    step = mx.parallel.DataParallelStep(
        net, gloss.SoftmaxCrossEntropyLoss(),
        mx.optimizer.SGD(learning_rate=0.1), mesh=None)
    rs = onp.random.RandomState(0)
    x = mx.nd.array(rs.randn(4, 6).astype("float32"))
    y = mx.nd.array(rs.randint(0, 4, 4).astype("float32"))
    step(x, y)
    # same step, changed leading (batch) axis -> forced retrace
    x2 = mx.nd.array(rs.randn(8, 6).astype("float32"))
    y2 = mx.nd.array(rs.randint(0, 4, 8).astype("float32"))
    step(x2, y2)
    snap = telemetry.snapshot()
    # detector keys are per-instance (DataParallelStep[<id>]) so
    # unrelated steps' first compiles never read as retraces
    counts = [v for k, v in snap["compiles"].items()
              if k.startswith("DataParallelStep[")]
    assert counts == [2], snap["compiles"]
    rec = [e for e in snap["events"] if e["kind"] == "recompile"
           and e["name"].startswith("DataParallelStep[")]
    assert len(rec) == 1
    changed = rec[0]["changed"]
    assert any("data.shape[0]: 4 -> 8" in c for c in changed), changed
    # per-step spans recorded for both calls
    assert snap["spans"]["parallel.step"]["count"] == 2


def test_retrace_warning_fires(caplog):
    telemetry.record_compile("fn", {"shape": [2, 2]})
    telemetry.record_compile("fn", {"shape": [2, 3]})
    with caplog.at_level(logging.WARNING):
        changed = telemetry.record_compile("fn", {"shape": [2, 4]})
    assert changed == ["shape[1]: 3 -> 4"]
    assert any("compiled 3 times" in r.message and "shape[1]" in r.message
               for r in caplog.records)


def test_diff_keys_dtype_and_static_args():
    old = {"data": {"shape": [4, 6], "dtype": "float32"}, "mode": "call"}
    new = {"data": {"shape": [4, 6], "dtype": "bfloat16"}, "mode": "scan"}
    d = telemetry._diff_keys(old, new)
    assert "data.dtype: 'float32' -> 'bfloat16'" in d
    assert "mode: 'call' -> 'scan'" in d


# ---------------------------------------------------------------------------
# the acceptance run: 3-step Trainer over a prefetched feed
# ---------------------------------------------------------------------------

def test_trainer_run_snapshot_has_spans_ring_and_memory():
    net = _make_net()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.1})
    L = gloss.SoftmaxCrossEntropyLoss()
    feed = _float_feed(n_batches=3)
    steps = 0
    for batch in feed:
        with autograd.record():
            loss = L(net(batch.data[0]), batch.label[0])
        loss.backward()
        trainer.step(batch.data[0].shape[0])
        steps += 1
    feed.close()
    assert steps == 3
    snap = telemetry.snapshot()
    # step spans
    assert snap["spans"]["trainer.step"]["count"] == 3
    assert snap["spans"]["trainer.step"]["mean_ms"] > 0
    # prefetch ring gauges + stage timings
    assert "prefetch.ring_occupancy" in snap["gauges"]
    assert snap["gauges"]["prefetch.ring_depth"] == 2
    assert snap["counters"]["prefetch.batches"] == 3
    assert snap["spans"]["prefetch.host"]["count"] == 3
    assert snap["spans"]["prefetch.ship"]["count"] == 3
    # memory gauge sampled at the trainer.step span boundary
    assert snap["gauges"]["mem.host_rss_bytes"] > 0
    # the fused update compiled exactly once (no retrace storm)
    assert [v for k, v in snap["compiles"].items()
            if k.startswith("FusedUpdate[")] == [1]


# ---------------------------------------------------------------------------
# step hooks: Monitor / Speedometer without loop plumbing
# ---------------------------------------------------------------------------

def _run_steps(net, trainer, n=2, bs=4):
    L = gloss.SoftmaxCrossEntropyLoss()
    rs = onp.random.RandomState(0)
    x = mx.nd.array(rs.randn(bs, 6).astype("float32"))
    y = mx.nd.array(rs.randint(0, 4, bs).astype("float32"))
    for _ in range(n):
        with autograd.record():
            loss = L(net(x), y)
        loss.backward()
        trainer.step(bs)


def test_monitor_attach_pattern_filtering(caplog):
    net = _make_net()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.1})
    mon = mx.monitor.Monitor(interval=1, pattern=".*weight.*",
                             monitor_all=True).attach(trainer)
    try:
        with caplog.at_level(logging.INFO):
            _run_steps(net, trainer, n=2)
    finally:
        mon.detach()
    logged = [r.message for r in caplog.records if "Batch:" in r.message]
    assert logged, "attached monitor never fired"
    assert any("weight" in m and "_grad" in m for m in logged)
    assert any("weight" in m and "_grad" not in m for m in logged)
    assert not any("bias" in m for m in logged)


def test_monitor_attach_monitor_all_false(caplog):
    net = _make_net()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.1})
    mon = mx.monitor.Monitor(interval=1, pattern=".*",
                             monitor_all=False).attach(trainer)
    try:
        with caplog.at_level(logging.INFO):
            _run_steps(net, trainer, n=1)
    finally:
        mon.detach()
    logged = [r.message for r in caplog.records if "Batch:" in r.message]
    assert logged
    assert not any("_grad" in m for m in logged)
    assert any("bias" in m for m in logged)   # pattern .* includes biases


def test_monitor_attach_interval():
    net = _make_net()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.1})
    seen = []
    mon = mx.monitor.Monitor(interval=2, pattern=".*weight.*")
    orig = mon._collect_trainer
    mon._collect_trainer = lambda t, i: seen.append(i) or orig(t, i)
    mon.attach(trainer)
    try:
        _run_steps(net, trainer, n=4)
    finally:
        mon.detach()
    assert seen == [0, 2]   # interval=2: steps 0 and 2 are due


def test_speedometer_attach_emits_telemetry_line(caplog):
    net = _make_net()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.1})
    spd = mx.callback.Speedometer(batch_size=4, frequent=2).attach()
    spd.set_epoch(3)     # trainer steps carry no epoch; the loop sets it
    try:
        with caplog.at_level(logging.INFO):
            _run_steps(net, trainer, n=5)
    finally:
        spd.detach()
    lines = [r.getMessage() for r in caplog.records
             if "samples/sec" in r.getMessage()]
    assert lines, "speedometer never logged"
    # telemetry-enriched format: step span time rides on the line
    assert any("step-ms=" in ln for ln in lines)
    assert all(ln.startswith("Epoch[3]") for ln in lines)


def test_step_hook_failure_does_not_break_training(caplog):
    net = _make_net()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.1})

    def bad_hook(rec):
        raise RuntimeError("observer bug")
    telemetry.add_step_hook(bad_hook)
    try:
        with caplog.at_level(logging.ERROR):
            _run_steps(net, trainer, n=1)    # must not raise
    finally:
        telemetry.remove_step_hook(bad_hook)
    assert any("step hook" in r.message for r in caplog.records)


# ---------------------------------------------------------------------------
# exporters
# ---------------------------------------------------------------------------

def test_export_jsonl_and_streaming_sink(tmp_path):
    stream = tmp_path / "stream.jsonl"
    telemetry.set_jsonl_sink(str(stream))
    with telemetry.span("exp.step"):
        pass
    telemetry.inc("exp.count", 2)
    telemetry.record_compile("exp.fn", {"shape": [4]})
    telemetry.record_compile("exp.fn", {"shape": [8]})
    telemetry.set_jsonl_sink(None)
    streamed = [json.loads(ln) for ln in
                stream.read_text().strip().splitlines()]
    assert any(r["kind"] == "span" and r["name"] == "exp.step"
               for r in streamed)
    assert any(r["kind"] == "recompile" for r in streamed)

    dump = tmp_path / "dump.jsonl"
    telemetry.export_jsonl(str(dump))
    recs = [json.loads(ln) for ln in
            dump.read_text().strip().splitlines()]
    snap_rec = [r for r in recs if r["kind"] == "snapshot"]
    assert len(snap_rec) == 1
    assert snap_rec[0]["counters"]["exp.count"] == 2
    assert snap_rec[0]["spans"]["exp.step"]["count"] == 1


def test_export_chrome_trace(tmp_path):
    with telemetry.span("ct.step"):
        pass
    telemetry.inc("ct.count")
    telemetry.event("marker", "ct.mark")
    path = tmp_path / "telemetry.trace.json"
    telemetry.export_chrome_trace(str(path))
    trace = json.loads(path.read_text())
    evs = trace["traceEvents"]
    xs = [e for e in evs if e["ph"] == "X"]
    assert any(e["name"] == "ct.step" for e in xs)
    assert all("ts" in e and "dur" in e for e in xs)
    assert any(e["ph"] == "C" and e["name"] == "ct.count" for e in evs)
    assert any(e["ph"] == "i" for e in evs)


# ---------------------------------------------------------------------------
# trace contexts (ISSUE 18): trace ids, sid/parent chains, rank stamps
# ---------------------------------------------------------------------------

def test_trace_ids_unique_and_pid_qualified():
    import os
    ids = {telemetry.new_trace_id() for _ in range(64)}
    assert len(ids) == 64
    assert all("%x" % (os.getpid() & 0xffffff) in i.split("-")[1]
               for i in ids)


def test_trace_context_stamps_events_and_spans():
    with telemetry.trace() as tr:
        assert telemetry.current_trace() == tr.trace_id
        telemetry.event("unit", "inside")
        with telemetry.span("unit.outer"):
            with telemetry.span("unit.inner"):
                pass
    assert telemetry.current_trace() is None
    telemetry.event("unit", "outside")
    recs = telemetry.snapshot()["events"]
    inside = [r for r in recs if r.get("name") == "inside"]
    outside = [r for r in recs if r.get("name") == "outside"]
    assert inside[0]["trace"] == tr.trace_id
    assert "trace" not in outside[0]
    spans = {r["name"]: r for r in recs if r["kind"] == "span"}
    assert spans["unit.outer"]["trace"] == tr.trace_id
    assert spans["unit.inner"]["trace"] == tr.trace_id
    # the sid/parent chain links inner -> outer causally
    assert spans["unit.inner"]["parent"] == spans["unit.outer"]["sid"]
    assert spans["unit.outer"].get("parent") is None


def test_trace_join_if_active_vs_explicit_reenter():
    with telemetry.trace() as outer:
        # no id + active trace: JOIN (same id, and exit must not
        # tear down the outer context)
        with telemetry.trace() as joined:
            assert joined.trace_id == outer.trace_id
        assert telemetry.current_trace() == outer.trace_id
    # explicit id always activates (the serve worker-thread re-enter)
    with telemetry.trace("req-42") as tr:
        assert tr.trace_id == "req-42"
        telemetry.event("unit", "reentered")
    recs = telemetry.snapshot()["events"]
    assert any(r.get("trace") == "req-42" for r in recs
               if r.get("name") == "reentered")


def test_rank_stamped_on_every_record():
    telemetry.set_rank(3)
    try:
        telemetry.event("unit", "ranked")
        with telemetry.span("unit.r"):
            pass
    finally:
        telemetry.set_rank(None)
    recs = telemetry.snapshot()["events"]
    assert all(r.get("rank") == 3 for r in recs
               if r.get("name") in ("ranked", "unit.r"))


def test_span_event_carries_explicit_trace_and_histogram():
    telemetry.span_event("unit.cross", 0.005, trace="t-1",
                         parent=7, hist=True, bucket=4)
    recs = telemetry.snapshot()["events"]
    rec = [r for r in recs if r.get("name") == "unit.cross"][0]
    assert rec["trace"] == "t-1" and rec["parent"] == 7
    assert rec["bucket"] == 4
    assert telemetry.snapshot()["spans"]["unit.cross"]["count"] == 1
    assert telemetry.histogram("unit.cross").count == 1


# ---------------------------------------------------------------------------
# online histograms: log-bucketed, fixed memory, mergeable
# ---------------------------------------------------------------------------

def test_histogram_quantiles_track_exact_within_bucket_error():
    import math
    rs = onp.random.RandomState(7)
    samples = onp.exp(rs.randn(5000) * 1.5 + 1.0)   # lognormal ms
    h = telemetry.Histogram()
    for v in samples:
        h.add(float(v))
    s = onp.sort(samples)
    for q in (0.5, 0.9, 0.99):
        exact = float(s[int(q * len(s)) - 1])
        est = h.quantile(q)
        # bucket ratio is 10**(1/10) ~ 1.26; allow 2 bucket widths
        assert abs(math.log10(est) - math.log10(exact)) < 0.2, \
            (q, est, exact)
    assert h.min == float(samples.min())
    assert h.max == float(samples.max())


def test_histogram_memory_is_fixed():
    h = telemetry.Histogram()
    h.add(1.0)
    n_after_10 = len(h.buckets)
    for v in range(10000):
        h.add(float(v) + 0.5)
    assert len(h.buckets) == n_after_10 == telemetry.Histogram.NBUCKETS
    assert h.count == 10001


def test_histogram_merge_and_roundtrip():
    a, b = telemetry.Histogram(), telemetry.Histogram()
    for v in (1.0, 2.0, 3.0):
        a.add(v)
    for v in (100.0, 200.0):
        b.add(v)
    merged = telemetry.Histogram.from_dict(a.to_dict()).merge(b)
    assert merged.count == 5
    assert merged.min == 1.0 and merged.max == 200.0
    assert merged.quantile(0.5) < 100.0 <= merged.quantile(0.95)
    # geometry mismatch is a loud error, not silent bucket garbage
    bad = a.to_dict()
    bad["bpd"] = 5
    with pytest.raises(ValueError):
        telemetry.Histogram.from_dict(bad)


def test_histogram_since_carves_a_leg():
    h = telemetry.Histogram()
    for v in (1.0, 2.0, 4.0):
        h.add(v)
    base = h.to_dict()
    for v in (50.0, 60.0, 70.0, 80.0):
        h.add(v)
    leg = h.since(base)
    assert leg.count == 4
    assert 40.0 < leg.quantile(0.5) < 100.0


def test_span_hist_feeds_named_histogram():
    with telemetry.span("unit.h", hist=True):
        pass
    with telemetry.span("unit.h", hist=True):
        pass
    h = telemetry.histogram("unit.h")
    assert h is not None and h.count == 2
    snap = telemetry.snapshot()
    assert snap["histograms"]["unit.h"]["count"] == 2


def test_export_jsonl_snapshot_carries_histograms(tmp_path):
    telemetry.hist_observe("exp.h", 5.0)
    dump = tmp_path / "dump.jsonl"
    telemetry.export_jsonl(str(dump))
    recs = [json.loads(ln) for ln in
            dump.read_text().strip().splitlines()]
    snap_rec = [r for r in recs if r["kind"] == "snapshot"][0]
    assert snap_rec["histograms"]["exp.h"]["count"] == 1
    # full mergeable form, not just the summary
    assert "buckets" in snap_rec["histograms"]["exp.h"]


# ---------------------------------------------------------------------------
# retrace-warning dedupe: one warning per (instance, changed-key family)
# ---------------------------------------------------------------------------

def test_retrace_warning_dedupes_per_key_family(caplog):
    with caplog.at_level(logging.WARNING):
        for n in (2, 4, 8, 16):
            telemetry.record_compile("fam.fn", {"shape": [2, n]})
    warns = [r for r in caplog.records if "retrace" in r.message
             and "fam.fn" in r.message]
    assert len(warns) == 1, [r.message for r in warns]
    # a DIFFERENT changed-key family on the same instance warns again
    with caplog.at_level(logging.WARNING):
        telemetry.record_compile("fam.fn", {"shape": [2, 16],
                                            "dtype": "bf16"})
    warns = [r for r in caplog.records if "retrace" in r.message
             and "fam.fn" in r.message]
    assert len(warns) == 2, [r.message for r in warns]
    # every retrace still journals an event (dedupe is log-side only)
    evs = [e for e in telemetry.snapshot()["events"]
           if e["kind"] == "recompile"]
    assert len(evs) == 4


def test_sync_clock_journals_reference_pair():
    class FakeKV:
        def __init__(self):
            self.kv = {}

        def key_value_set(self, k, v):
            self.kv[k] = v

        def blocking_key_value_get(self, k, timeout_ms):
            return self.kv[k]

    kv = FakeKV()
    ref0 = telemetry.sync_clock(kv, 0, key="t/clock")
    ref1 = telemetry.sync_clock(kv, 1, key="t/clock")
    assert ref0 is not None and abs(ref1 - ref0) < 1e-6
    clocks = [e for e in telemetry.snapshot()["events"]
              if e["kind"] == "clock"]
    assert len(clocks) == 2
    for e in clocks:
        assert e["local_wall"] is not None
        assert e["ref_wall"] is not None


# ---------------------------------------------------------------------------
# attention dispatch census
# ---------------------------------------------------------------------------

def test_attention_dispatch_counted():
    from mxnet_tpu.ops.pallas_attention import attention_dispatch
    plan = attention_dispatch(8, 8, 64, "float32", on_tpu=False)
    assert plan["kernel"] == "dense_fallback"
    assert telemetry.counter("attention.kernel.dense_fallback") == 1
    plan = attention_dispatch(2048, 2048, 64, "bfloat16", on_tpu=True)
    assert telemetry.counter("attention.kernel.%s" % plan["kernel"]) == 1
    snap = telemetry.snapshot()
    evs = [e for e in snap["events"] if e["kind"] == "attention_dispatch"]
    assert evs and evs[-1]["seq_q"] == 2048
