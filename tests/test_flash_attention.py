"""Flash-attention op + Pallas kernel tests (CPU: interpret mode / jnp
fallback; the same kernels run compiled on a real TPU — see bench.py's
attention microbench for the on-chip numbers).

Reference capability: ``src/operator/contrib/transformer.cc``
(interleaved matmul self-attention pipeline).
"""
import numpy as onp
import jax
import jax.numpy as jnp
import pytest

import mxnet_tpu as mx
from mxnet_tpu.ops import pallas_attention as P


def _dense(q, k, v, causal=False, scale=None):
    d = q.shape[-1]
    scale = scale if scale is not None else d ** -0.5
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k).astype(jnp.float32) * scale
    if causal:
        tq, tk = s.shape[-2:]
        mask = onp.tril(onp.ones((tq, tk), bool), tk - tq)
        s = jnp.where(jnp.asarray(mask), s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p.astype(q.dtype), v)


def _rand(shape, seed):
    return jnp.asarray(
        onp.random.RandomState(seed).uniform(-1, 1, shape).astype("float32"))


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("shape", [(2, 3, 256, 64), (1, 1, 200, 48)])
def test_pallas_fwd_kernel_matches_dense(causal, shape):
    q, k, v = (_rand(shape, i) for i in range(3))
    out, lse = P.pallas_flash_attention(
        q, k, v, causal=causal, interpret=True, return_lse=True,
        block_q=128, block_k=128)
    ref = _dense(q, k, v, causal)
    assert float(jnp.max(jnp.abs(out - ref))) < 2e-5
    # lse really is the softmax log-normalizer
    want_lse = jax.nn.logsumexp(
        (jnp.einsum("bhqd,bhkd->bhqk", q, k) * shape[-1] ** -0.5
         ).astype(jnp.float32), axis=-1)
    if not causal:
        assert float(jnp.max(jnp.abs(lse - want_lse))) < 2e-4


@pytest.mark.parametrize("causal", [False, True])
def test_pallas_bwd_kernels_match_dense_vjp(causal):
    shape = (2, 2, 256, 64)
    q, k, v = (_rand(shape, 10 + i) for i in range(3))
    g = _rand(shape, 20)
    out, lse = P.pallas_flash_attention(
        q, k, v, causal=causal, interpret=True, return_lse=True,
        block_q=128, block_k=128)
    dq, dk, dv = P.pallas_flash_attention_bwd(
        q, k, v, out, lse, g, causal=causal, interpret=True,
        block_q=128, block_k=128)
    _, vjp = jax.vjp(lambda a, b, c: _dense(a, b, c, causal), q, k, v)
    rq, rk, rv = vjp(g)
    for got, want in ((dq, rq), (dk, rk), (dv, rv)):
        assert float(jnp.max(jnp.abs(got - want))) < 5e-5


def _dense_masked(q, k, v, kv_lens=None, q_seg=None, kv_seg=None,
                  causal=False):
    d = q.shape[-1]
    tq, tk = q.shape[2], k.shape[2]
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k).astype(jnp.float32) * d ** -0.5
    mask = jnp.ones((q.shape[0], 1, tq, tk), bool)
    if kv_lens is not None:
        mask = mask & (jnp.arange(tk)[None, None, None, :]
                       < kv_lens[:, None, None, None])
    if q_seg is not None:
        mask = mask & (q_seg[:, None, :, None] == kv_seg[:, None, None, :])
    if causal:
        mask = mask & (jnp.arange(tq)[:, None] >= jnp.arange(tk)[None, :])
    s = jnp.where(mask, s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    p = jnp.where(jnp.any(mask, axis=-1, keepdims=True), p, 0.0)
    return jnp.einsum("bhqk,bhkd->bhqd", p.astype(q.dtype), v)


@pytest.mark.parametrize("lens", [[256, 131], [1, 256]])
def test_pallas_kv_lens_matches_dense(lens):
    shape = (2, 2, 256, 64)
    q, k, v = (_rand(shape, 40 + i) for i in range(3))
    kv_lens = jnp.asarray(lens, jnp.int32)
    out = P.pallas_flash_attention(q, k, v, interpret=True, block_q=128,
                                   block_k=128, kv_lens=kv_lens)
    ref = _dense_masked(q, k, v, kv_lens=kv_lens)
    assert float(jnp.max(jnp.abs(out - ref))) < 2e-5


def test_pallas_kv_lens_beyond_tk_clamps_to_seq_len():
    # out-of-range kv_lens (> Tk) must behave exactly like lens == Tk:
    # the length mask replaces the padded-tail mask, so without clamping
    # the zero-padded key rows would enter the online softmax
    shape = (2, 2, 200, 64)       # Tk=200 pads to 256 inside the kernel
    q, k, v = (_rand(shape, 45 + i) for i in range(3))
    out = P.pallas_flash_attention(
        q, k, v, interpret=True, block_q=128, block_k=128,
        kv_lens=jnp.asarray([500, 200], jnp.int32))
    ref = _dense_masked(q, k, v, kv_lens=jnp.asarray([200, 200], jnp.int32))
    assert float(jnp.max(jnp.abs(out - ref))) < 2e-5


def test_pallas_kv_lens_bwd_matches_dense_vjp():
    shape = (2, 2, 256, 64)
    q, k, v = (_rand(shape, 50 + i) for i in range(3))
    g = _rand(shape, 53)
    kv_lens = jnp.asarray([200, 77], jnp.int32)
    out, lse = P.pallas_flash_attention(
        q, k, v, interpret=True, return_lse=True, block_q=128, block_k=128,
        kv_lens=kv_lens)
    dq, dk, dv = P.pallas_flash_attention_bwd(
        q, k, v, out, lse, g, interpret=True, block_q=128, block_k=128,
        kv_lens=kv_lens)
    _, vjp = jax.vjp(lambda a, b, c: _dense_masked(a, b, c, kv_lens),
                     q, k, v)
    rq, rk, rv = vjp(g)
    for got, want in ((dq, rq), (dk, rk), (dv, rv)):
        assert float(jnp.max(jnp.abs(got - want))) < 5e-5
    # masked-out keys get exactly zero dk/dv (their blocks are skipped)
    assert float(jnp.max(jnp.abs(dk[1, :, 77:]))) == 0.0
    assert float(jnp.max(jnp.abs(dv[1, :, 77:]))) == 0.0


@pytest.mark.parametrize("causal", [False, True])
def test_pallas_segment_ids_match_dense(causal):
    shape = (2, 2, 256, 32)
    q, k, v = (_rand(shape, 60 + i) for i in range(3))
    # packed sequences: two segments per row, split at different points
    seg = onp.zeros((2, 256), onp.int32)
    seg[0, 100:] = 1
    seg[1, 180:] = 1
    seg = jnp.asarray(seg)
    out = P.pallas_flash_attention(
        q, k, v, causal=causal, interpret=True, block_q=128, block_k=128,
        q_segments=seg, kv_segments=seg)
    ref = _dense_masked(q, k, v, q_seg=seg, kv_seg=seg, causal=causal)
    assert float(jnp.max(jnp.abs(out - ref))) < 2e-5


def test_pallas_segment_ids_bwd_matches_dense_vjp():
    shape = (1, 2, 256, 32)
    q, k, v = (_rand(shape, 70 + i) for i in range(3))
    g = _rand(shape, 73)
    seg = jnp.asarray(onp.repeat([[0, 1]], 128, axis=1).reshape(1, 256))
    out, lse = P.pallas_flash_attention(
        q, k, v, interpret=True, return_lse=True, block_q=128, block_k=128,
        q_segments=seg, kv_segments=seg)
    dq, dk, dv = P.pallas_flash_attention_bwd(
        q, k, v, out, lse, g, interpret=True, block_q=128, block_k=128,
        q_segments=seg, kv_segments=seg)
    _, vjp = jax.vjp(
        lambda a, b, c: _dense_masked(a, b, c, q_seg=seg, kv_seg=seg),
        q, k, v)
    rq, rk, rv = vjp(g)
    for got, want in ((dq, rq), (dk, rk), (dv, rv)):
        assert float(jnp.max(jnp.abs(got - want))) < 5e-5


def test_fully_masked_rows_emit_zero_and_zero_grads():
    """A q row whose segment matches no key must return exactly 0 with
    zero dq, and contribute nothing to dk/dv (regression: the online
    softmax saw exp(s - m_new) == 1 when the whole row was -inf)."""
    shape = (1, 2, 128, 32)
    q, k, v = (_rand(shape, 90 + i) for i in range(3))
    g = _rand(shape, 93)
    q_seg = jnp.asarray(onp.where(onp.arange(128) < 64, 0, 7)[None, :])
    kv_seg = jnp.zeros((1, 128), jnp.int32)       # id 7 matches nothing
    out, lse = P.pallas_flash_attention(
        q, k, v, interpret=True, return_lse=True, block_q=64, block_k=64,
        q_segments=q_seg, kv_segments=kv_seg)
    assert float(jnp.max(jnp.abs(out[:, :, 64:]))) == 0.0
    assert float(jnp.max(jnp.abs(lse[:, :, 64:]))) == 0.0
    dq, dk, dv = P.pallas_flash_attention_bwd(
        q, k, v, out, lse, g, interpret=True, block_q=64, block_k=64,
        q_segments=q_seg, kv_segments=kv_seg)
    assert float(jnp.max(jnp.abs(dq[:, :, 64:]))) == 0.0
    _, vjp = jax.vjp(
        lambda a, b, c: _dense_masked(a, b, c, q_seg=q_seg, kv_seg=kv_seg),
        q, k, v)
    rq, rk, rv = vjp(g)
    for got, want in ((dq, rq), (dk, rk), (dv, rv)):
        assert float(jnp.max(jnp.abs(got - want))) < 5e-5


def test_mha_mask_plus_valid_length_combines():
    """Dense path: an explicit additive mask AND valid_length together —
    padded keys must still be excluded."""
    from mxnet_tpu.gluon.contrib.nn import MultiHeadAttention
    mx.random.seed(0)
    attn = MultiHeadAttention(units=32, num_heads=2)
    attn.initialize()
    x = mx.nd.array(onp.random.RandomState(7).uniform(
        -1, 1, (2, 48, 32)).astype("float32"))
    attn(x)
    zero_mask = mx.nd.zeros((2, 1, 1, 48))
    vl = mx.nd.array(onp.array([48, 20]), dtype="int32")
    got = attn(x, zero_mask, vl).asnumpy()
    # reference: additive mask that encodes the same padding
    add = onp.zeros((2, 1, 1, 48), "float32")
    add[1, :, :, 20:] = -1e30
    want = attn(x, mx.nd.array(add)).asnumpy()
    assert onp.abs(got[0] - want[0]).max() < 2e-5
    assert onp.abs(got[1, :20] - want[1, :20]).max() < 2e-5


def test_flash_attention_custom_vjp_masked_fallback():
    """The public custom-vjp op with kv_lens via the CPU fallback path."""
    shape = (2, 2, 128, 32)
    q, k, v = (_rand(shape, 80 + i) for i in range(3))
    kv_lens = jnp.asarray([128, 57], jnp.int32)

    def loss(q, k, v):
        return jnp.sum(P.flash_attention(q, k, v, False, None,
                                         kv_lens) ** 2)

    def dense_loss(q, k, v):
        return jnp.sum(_dense_masked(q, k, v, kv_lens=kv_lens) ** 2)

    assert float(jnp.abs(loss(q, k, v) - dense_loss(q, k, v))) < 1e-3
    got = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
    want = jax.grad(dense_loss, argnums=(0, 1, 2))(q, k, v)
    for g1, g2 in zip(got, want):
        assert float(jnp.max(jnp.abs(g1 - g2))) < 5e-5


def test_transformer_valid_length_routes_flash():
    """MultiHeadAttention(valid_length=...) == explicit additive mask."""
    from mxnet_tpu.gluon.contrib.nn import MultiHeadAttention
    mx.random.seed(0)
    attn = MultiHeadAttention(units=64, num_heads=4)
    attn.initialize()
    x = mx.nd.array(onp.random.RandomState(5).uniform(
        -1, 1, (2, 96, 64)).astype("float32"))
    attn(x)  # materialize
    vl = mx.nd.array(onp.array([96, 40]), dtype="int32")
    out_flash = attn(x, None, vl)
    # dense path: additive -inf on padded keys
    add = onp.zeros((2, 1, 1, 96), "float32")
    add[1, :, :, 40:] = -1e30
    out_dense = attn(x, mx.nd.array(add))
    got = out_flash.asnumpy()
    want = out_dense.asnumpy()
    # padded q rows differ (garbage either way); compare valid rows
    assert onp.abs(got[0] - want[0]).max() < 2e-5
    assert onp.abs(got[1, :40] - want[1, :40]).max() < 2e-5


def test_flash_attention_op_and_grad_fallback():
    """The registered op (jnp fallback off-TPU) forward + custom-vjp grad."""
    shape = (1, 2, 128, 32)
    q, k, v = (_rand(shape, 30 + i) for i in range(3))
    out = mx.nd.flash_attention(mx.nd.from_jax(q), mx.nd.from_jax(k),
                                mx.nd.from_jax(v))
    ref = _dense(q, k, v)
    assert onp.abs(out.asnumpy() - onp.asarray(ref)).max() < 2e-5

    def loss(q, k, v):
        return jnp.sum(P.flash_attention(q, k, v) ** 2)

    def dense_loss(q, k, v):
        return jnp.sum(_dense(q, k, v) ** 2)

    got = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
    want = jax.grad(dense_loss, argnums=(0, 1, 2))(q, k, v)
    for g1, g2 in zip(got, want):
        assert float(jnp.max(jnp.abs(g1 - g2))) < 5e-5


# --- BSHD (transpose-free) layout ------------------------------------------

def _bshd(x):
    return jnp.swapaxes(x, 1, 2)


@pytest.mark.parametrize("lens", [None, (100, 128)])
@pytest.mark.parametrize("causal", [False, True])
def test_bshd_kernels_match_bhtd(causal, lens):
    """The (B,T,H,D)-layout kernels compute exactly what the flat-grid
    BHTD kernels do, fwd and bwd (no transposes on either side)."""
    B, H, T, D = 2, 3, 128, 64
    q, k, v = (_rand((B, H, T, D), i) for i in range(3))
    kv = jnp.asarray(lens, jnp.int32) if lens else None
    o1, l1 = P.pallas_flash_attention(
        q, k, v, causal=causal, return_lse=True, interpret=True,
        block_q=64, block_k=64, kv_lens=kv)
    o2, l2 = P.pallas_flash_attention_bshd(
        _bshd(q), _bshd(k), _bshd(v), causal=causal, return_lse=True,
        interpret=True, block_q=64, block_k=64, kv_lens=kv)
    assert float(jnp.max(jnp.abs(_bshd(o2) - o1))) < 1e-6
    assert float(jnp.max(jnp.abs(l2 - l1))) < 1e-6
    do = _rand((B, H, T, D), 7)
    g1 = P.pallas_flash_attention_bwd(q, k, v, o1, l1, do, causal=causal,
                                      interpret=True, block_q=64,
                                      block_k=64, kv_lens=kv)
    g2 = P.pallas_flash_attention_bwd_bshd(
        _bshd(q), _bshd(k), _bshd(v), o2, l2, _bshd(do), causal=causal,
        interpret=True, block_q=64, block_k=64, kv_lens=kv)
    for a, b in zip(g1, g2):
        assert float(jnp.max(jnp.abs(_bshd(b) - a))) < 5e-6


def test_flash_attention_bshd_fallback_grads_match_dense():
    """Off-TPU the BSHD public op runs the jnp path; grads match the
    dense oracle on transposed operands."""
    B, H, T, D = 2, 2, 64, 32
    q, k, v = (_rand((B, H, T, D), i) for i in range(3))

    def f(a, b, c):
        return jnp.sum(P.flash_attention_bshd(_bshd(a), _bshd(b),
                                              _bshd(c)).astype(jnp.float32))

    def ref(a, b, c):
        return jnp.sum(_dense(a, b, c).astype(jnp.float32))

    g1 = jax.grad(f, argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g1, g2):
        assert float(jnp.max(jnp.abs(a - b))) < 2e-4


@pytest.mark.parametrize("config", [
    {}, {"causal": True}, {"lens": (100, 128)},
    {"segs": True}, {"causal": True, "lens": (100, 128)},
])
def test_fused_single_kblock_bwd_matches_split(config):
    """When the whole K axis fits one block the backward runs the fused
    dqkv kernel (5 dots, shared score/dp recompute); it must match the
    split dq+dkv kernels bit-for-fp32-bit across every mask config."""
    B, H, T, D = 2, 3, 128, 64
    q, k, v, do = (_rand((B, H, T, D), i) for i in range(4))
    causal = config.get("causal", False)
    kl = jnp.asarray(config["lens"], jnp.int32) if "lens" in config \
        else None
    segs = jnp.asarray(
        onp.repeat(onp.arange(4), 32)[None].repeat(B, 0), jnp.int32) \
        if config.get("segs") else None
    kw = dict(causal=causal, kv_lens=kl, q_segments=segs,
              kv_segments=segs, interpret=True, block_q=64)
    o1, l1 = P.pallas_flash_attention(q, k, v, return_lse=True,
                                      block_k=128, **kw)
    g_fused = P.pallas_flash_attention_bwd(q, k, v, o1, l1, do,
                                           block_k=128, **kw)   # n_k=1
    o2, l2 = P.pallas_flash_attention(q, k, v, return_lse=True,
                                      block_k=64, **kw)
    g_split = P.pallas_flash_attention_bwd(q, k, v, o2, l2, do,
                                           block_k=64, **kw)    # n_k=2
    for a, b in zip(g_fused, g_split):
        assert float(jnp.max(jnp.abs(a - b))) < 2e-5
