"""Flash-attention op + Pallas kernel tests (CPU: interpret mode / jnp
fallback; the same kernels run compiled on a real TPU — see bench.py's
attention microbench for the on-chip numbers).

Reference capability: ``src/operator/contrib/transformer.cc``
(interleaved matmul self-attention pipeline).
"""
import numpy as onp
import jax
import jax.numpy as jnp
import pytest

import mxnet_tpu as mx
from mxnet_tpu.ops import pallas_attention as P


def _dense(q, k, v, causal=False, scale=None):
    d = q.shape[-1]
    scale = scale if scale is not None else d ** -0.5
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k).astype(jnp.float32) * scale
    if causal:
        tq, tk = s.shape[-2:]
        mask = onp.tril(onp.ones((tq, tk), bool), tk - tq)
        s = jnp.where(jnp.asarray(mask), s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p.astype(q.dtype), v)


def _rand(shape, seed):
    return jnp.asarray(
        onp.random.RandomState(seed).uniform(-1, 1, shape).astype("float32"))


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("shape", [(2, 3, 256, 64), (1, 1, 200, 48)])
def test_pallas_fwd_kernel_matches_dense(causal, shape):
    q, k, v = (_rand(shape, i) for i in range(3))
    out, lse = P.pallas_flash_attention(
        q, k, v, causal=causal, interpret=True, return_lse=True,
        block_q=128, block_k=128)
    ref = _dense(q, k, v, causal)
    assert float(jnp.max(jnp.abs(out - ref))) < 2e-5
    # lse really is the softmax log-normalizer
    want_lse = jax.nn.logsumexp(
        (jnp.einsum("bhqd,bhkd->bhqk", q, k) * shape[-1] ** -0.5
         ).astype(jnp.float32), axis=-1)
    if not causal:
        assert float(jnp.max(jnp.abs(lse - want_lse))) < 2e-4


@pytest.mark.parametrize("causal", [False, True])
def test_pallas_bwd_kernels_match_dense_vjp(causal):
    shape = (2, 2, 256, 64)
    q, k, v = (_rand(shape, 10 + i) for i in range(3))
    g = _rand(shape, 20)
    out, lse = P.pallas_flash_attention(
        q, k, v, causal=causal, interpret=True, return_lse=True,
        block_q=128, block_k=128)
    dq, dk, dv = P.pallas_flash_attention_bwd(
        q, k, v, out, lse, g, causal=causal, interpret=True,
        block_q=128, block_k=128)
    _, vjp = jax.vjp(lambda a, b, c: _dense(a, b, c, causal), q, k, v)
    rq, rk, rv = vjp(g)
    for got, want in ((dq, rq), (dk, rk), (dv, rv)):
        assert float(jnp.max(jnp.abs(got - want))) < 5e-5


def test_flash_attention_op_and_grad_fallback():
    """The registered op (jnp fallback off-TPU) forward + custom-vjp grad."""
    shape = (1, 2, 128, 32)
    q, k, v = (_rand(shape, 30 + i) for i in range(3))
    out = mx.nd.flash_attention(mx.nd.from_jax(q), mx.nd.from_jax(k),
                                mx.nd.from_jax(v))
    ref = _dense(q, k, v)
    assert onp.abs(out.asnumpy() - onp.asarray(ref)).max() < 2e-5

    def loss(q, k, v):
        return jnp.sum(P.flash_attention(q, k, v) ** 2)

    def dense_loss(q, k, v):
        return jnp.sum(_dense(q, k, v) ** 2)

    got = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
    want = jax.grad(dense_loss, argnums=(0, 1, 2))(q, k, v)
    for g1, g2 in zip(got, want):
        assert float(jnp.max(jnp.abs(g1 - g2))) < 5e-5
