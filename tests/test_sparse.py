"""Sparse NDArray facade tests (reference
tests/python/unittest/test_sparse_ndarray.py, simplified to the emulated
TPU semantics)."""
import numpy as onp

import mxnet_tpu as mx
from mxnet_tpu.ndarray import sparse


def test_csr_roundtrip():
    data = onp.array([1., 2., 3., 4., 5.], "f")
    indices = onp.array([0, 2, 2, 0, 1], "f")
    indptr = onp.array([0, 2, 3, 5], "f")
    a = sparse.csr_matrix((data, indices, indptr), shape=(3, 3))
    assert a.stype == "csr"
    expect = onp.array([[1, 0, 2], [0, 0, 3], [4, 5, 0]], "f")
    onp.testing.assert_allclose(a.asnumpy(), expect)
    d, i, p = (a.data.asnumpy(), a.indices.asnumpy(), a.indptr.asnumpy())
    onp.testing.assert_allclose(d, data)
    onp.testing.assert_allclose(i, [0, 2, 2, 0, 1])
    onp.testing.assert_allclose(p, [0, 2, 3, 5])


def test_row_sparse_roundtrip():
    data = onp.array([[1., 2.], [3., 4.]], "f")
    indices = onp.array([1, 3], "f")
    a = sparse.row_sparse_array((data, indices), shape=(4, 2))
    assert a.stype == "row_sparse"
    expect = onp.zeros((4, 2), "f")
    expect[[1, 3]] = data
    onp.testing.assert_allclose(a.asnumpy(), expect)
    onp.testing.assert_allclose(a.indices.asnumpy(), [1, 3])
    onp.testing.assert_allclose(a.data.asnumpy(), data)


def test_tostype_and_cast_storage():
    x = mx.nd.array(onp.array([[1., 0.], [0., 0.], [2., 3.]], "f"))
    rs = x.tostype("row_sparse")
    assert rs.stype == "row_sparse"
    onp.testing.assert_allclose(rs.indices.asnumpy(), [0, 2])
    back = rs.tostype("default")
    assert back.stype == "default"
    onp.testing.assert_allclose(back.asnumpy(), x.asnumpy())
    csr = x.tostype("csr")
    assert csr.stype == "csr"
    onp.testing.assert_allclose(csr.asnumpy(), x.asnumpy())


def test_retain():
    x = mx.nd.array(onp.arange(12, dtype="f").reshape(4, 3) + 1)
    rs = x.tostype("row_sparse")
    kept = rs.retain(mx.nd.array(onp.array([0, 2], "f")))
    out = kept.asnumpy()
    assert (out[[0, 2]] != 0).all()
    assert (out[[1, 3]] == 0).all()
    onp.testing.assert_allclose(kept.indices.asnumpy(), [0, 2])


def test_sparse_zeros_and_dot():
    z = sparse.zeros("row_sparse", (3, 4))
    assert z.stype == "row_sparse" and z.asnumpy().sum() == 0
    a = sparse.csr_matrix(onp.array([[1., 0.], [0., 2.]], "f"))
    b = mx.nd.array(onp.array([[1., 1.], [1., 1.]], "f"))
    out = sparse.dot(a, b)
    onp.testing.assert_allclose(out.asnumpy(), [[1., 1.], [2., 2.]])


def test_sparse_ops_work_dense():
    """Sparse facades participate in normal dense math (the emulation
    contract)."""
    a = sparse.row_sparse_array(
        (onp.ones((1, 2), "f"), onp.array([1, ], "f")), shape=(3, 2))
    out = (a * 2 + 1).asnumpy()
    onp.testing.assert_allclose(out[1], [3., 3.])
    onp.testing.assert_allclose(out[0], [1., 1.])


def test_kvstore_row_sparse_pull():
    kv = mx.kv.create("local")
    w = mx.nd.array(onp.arange(8, dtype="f").reshape(4, 2) + 1)
    kv.init("w", w)
    out = mx.nd.zeros((4, 2))
    kv.row_sparse_pull("w", out=out, row_ids=mx.nd.array([0., 3.]))
    got = out.asnumpy()
    onp.testing.assert_allclose(got[[0, 3]], w.asnumpy()[[0, 3]])
    onp.testing.assert_allclose(got[[1, 2]], onp.zeros((2, 2)))
