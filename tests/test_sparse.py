"""Sparse NDArray facade tests (reference
tests/python/unittest/test_sparse_ndarray.py, simplified to the emulated
TPU semantics)."""
import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu.ndarray import sparse


def test_csr_roundtrip():
    data = onp.array([1., 2., 3., 4., 5.], "f")
    indices = onp.array([0, 2, 2, 0, 1], "f")
    indptr = onp.array([0, 2, 3, 5], "f")
    a = sparse.csr_matrix((data, indices, indptr), shape=(3, 3))
    assert a.stype == "csr"
    expect = onp.array([[1, 0, 2], [0, 0, 3], [4, 5, 0]], "f")
    onp.testing.assert_allclose(a.asnumpy(), expect)
    d, i, p = (a.data.asnumpy(), a.indices.asnumpy(), a.indptr.asnumpy())
    onp.testing.assert_allclose(d, data)
    onp.testing.assert_allclose(i, [0, 2, 2, 0, 1])
    onp.testing.assert_allclose(p, [0, 2, 3, 5])


def test_row_sparse_roundtrip():
    data = onp.array([[1., 2.], [3., 4.]], "f")
    indices = onp.array([1, 3], "f")
    a = sparse.row_sparse_array((data, indices), shape=(4, 2))
    assert a.stype == "row_sparse"
    expect = onp.zeros((4, 2), "f")
    expect[[1, 3]] = data
    onp.testing.assert_allclose(a.asnumpy(), expect)
    onp.testing.assert_allclose(a.indices.asnumpy(), [1, 3])
    onp.testing.assert_allclose(a.data.asnumpy(), data)


def test_tostype_and_cast_storage():
    x = mx.nd.array(onp.array([[1., 0.], [0., 0.], [2., 3.]], "f"))
    rs = x.tostype("row_sparse")
    assert rs.stype == "row_sparse"
    onp.testing.assert_allclose(rs.indices.asnumpy(), [0, 2])
    back = rs.tostype("default")
    assert back.stype == "default"
    onp.testing.assert_allclose(back.asnumpy(), x.asnumpy())
    csr = x.tostype("csr")
    assert csr.stype == "csr"
    onp.testing.assert_allclose(csr.asnumpy(), x.asnumpy())


def test_retain():
    x = mx.nd.array(onp.arange(12, dtype="f").reshape(4, 3) + 1)
    rs = x.tostype("row_sparse")
    kept = rs.retain(mx.nd.array(onp.array([0, 2], "f")))
    out = kept.asnumpy()
    assert (out[[0, 2]] != 0).all()
    assert (out[[1, 3]] == 0).all()
    onp.testing.assert_allclose(kept.indices.asnumpy(), [0, 2])


def test_sparse_zeros_and_dot():
    z = sparse.zeros("row_sparse", (3, 4))
    assert z.stype == "row_sparse" and z.asnumpy().sum() == 0
    a = sparse.csr_matrix(onp.array([[1., 0.], [0., 2.]], "f"))
    b = mx.nd.array(onp.array([[1., 1.], [1., 1.]], "f"))
    out = sparse.dot(a, b)
    onp.testing.assert_allclose(out.asnumpy(), [[1., 1.], [2., 2.]])


def test_sparse_ops_work_dense():
    """Sparse facades participate in normal dense math (the emulation
    contract)."""
    a = sparse.row_sparse_array(
        (onp.ones((1, 2), "f"), onp.array([1, ], "f")), shape=(3, 2))
    out = (a * 2 + 1).asnumpy()
    onp.testing.assert_allclose(out[1], [3., 3.])
    onp.testing.assert_allclose(out[0], [1., 1.])


def test_kvstore_row_sparse_pull():
    kv = mx.kv.create("local")
    w = mx.nd.array(onp.arange(8, dtype="f").reshape(4, 2) + 1)
    kv.init("w", w)
    out = mx.nd.zeros((4, 2))
    kv.row_sparse_pull("w", out=out, row_ids=mx.nd.array([0., 3.]))
    got = out.asnumpy()
    onp.testing.assert_allclose(got[[0, 3]], w.asnumpy()[[0, 3]])
    onp.testing.assert_allclose(got[[1, 2]], onp.zeros((2, 2)))


# ---------------------------------------------------------------------------
# round-3: REAL row-sparse path (parts-backed container, sparse embedding
# gradients, lazy sparse optimizer updates, gathering row_sparse_pull) —
# reference: Embedding(sparse_grad) + FComputeEx optimizer kernels
# (src/operator/optimizer_op.cc) + kvstore.py:270 row_sparse_pull
# ---------------------------------------------------------------------------

def test_row_sparse_parts_backed_no_densify():
    vals = onp.arange(6, dtype="float32").reshape(2, 3)
    idx = onp.array([1, 4], "int64")
    rs = sparse.row_sparse_array((vals, idx), shape=(6, 3))
    assert rs.has_parts
    assert rs.__dict__["_dense_cache"] is None   # nothing densified
    onp.testing.assert_array_equal(rs.indices.asnumpy(), idx)
    onp.testing.assert_array_equal(rs.data.asnumpy(), vals)
    assert rs.shape == (6, 3)
    # dense view on demand
    dense = rs.asnumpy()
    assert dense.shape == (6, 3)
    onp.testing.assert_array_equal(dense[1], vals[0])
    onp.testing.assert_array_equal(dense[0], onp.zeros(3))


def test_row_sparse_retain_stays_parts():
    vals = onp.ones((3, 2), "float32") * onp.arange(1, 4)[:, None]
    rs = sparse.row_sparse_array((vals, [0, 2, 5]), shape=(8, 2))
    kept = rs.retain([2, 5, 7])
    assert kept.has_parts
    onp.testing.assert_array_equal(kept.indices.asnumpy(), [2, 5])
    onp.testing.assert_array_equal(kept.data.asnumpy(), vals[1:])


def test_embedding_sparse_grad_is_row_sparse():
    from mxnet_tpu import autograd
    from mxnet_tpu.gluon import nn
    vocab, dim = 50, 4
    emb = nn.Embedding(vocab, dim, sparse_grad=True)
    emb.initialize()
    ids = mx.nd.array(onp.array([[3, 7, 3], [7, 9, 1]], "float32"))
    _ = emb(ids)
    trainer_params = emb.collect_params()
    import mxnet_tpu.gluon as gluon
    trainer = gluon.Trainer(trainer_params, "sgd", {"learning_rate": 0.0})
    with autograd.record():
        out = emb(ids)
        loss = (out * out).sum()
    loss.backward()
    w = emb.weight
    g = w.grad()
    assert isinstance(g, sparse.RowSparseNDArray) and g.has_parts
    onp.testing.assert_array_equal(g.indices.asnumpy(), [1, 3, 7, 9])
    # values match the dense-path gradient on those rows
    emb2 = nn.Embedding(vocab, dim, sparse_grad=False)
    emb2.initialize()
    emb2.weight.set_data(w.data())
    t2 = gluon.Trainer(emb2.collect_params(), "sgd", {"learning_rate": 0.0})
    with autograd.record():
        loss2 = (emb2(ids) * emb2(ids)).sum()
    loss2.backward()
    dense_g = emb2.weight.grad().asnumpy()
    onp.testing.assert_allclose(g.data.asnumpy(), dense_g[[1, 3, 7, 9]],
                                rtol=1e-5)
    onp.testing.assert_allclose(onp.abs(dense_g).sum(),
                                onp.abs(g.data.asnumpy()).sum(), rtol=1e-5)


@pytest.mark.parametrize("optname", ["sgd", "adam"])
def test_sparse_update_matches_dense_on_touched_rows(optname):
    import mxnet_tpu.optimizer as opt
    vocab, dim = 30, 5
    rs_w = onp.random.RandomState(0).randn(vocab, dim).astype("float32")
    idx = onp.array([2, 11, 29])
    vals = onp.random.RandomState(1).randn(3, dim).astype("float32")

    mk = (lambda: opt.SGD(learning_rate=0.1, momentum=0.9)) \
        if optname == "sgd" else (lambda: opt.Adam(learning_rate=0.1))
    # sparse path
    o1 = mk()
    w1 = mx.nd.array(rs_w.copy())
    st1 = o1.create_state(0, w1)
    g_sp = sparse.row_sparse_array((vals, idx), shape=(vocab, dim))
    o1.update(0, w1, g_sp, st1)
    # dense path: same grad with zeros elsewhere
    o2 = mk()
    w2 = mx.nd.array(rs_w.copy())
    st2 = o2.create_state(0, w2)
    dense_g = onp.zeros((vocab, dim), "float32")
    dense_g[idx] = vals
    o2.update(0, w2, mx.nd.array(dense_g), st2)
    # touched rows must match the dense update exactly
    onp.testing.assert_allclose(w1.asnumpy()[idx], w2.asnumpy()[idx],
                                rtol=1e-5, atol=1e-6)
    # untouched rows unchanged under the lazy (sparse) policy
    mask = onp.ones(vocab, bool)
    mask[idx] = False
    onp.testing.assert_array_equal(w1.asnumpy()[mask], rs_w[mask])


def test_row_sparse_pull_gathers_parts():
    kv = mx.kv.create("local")
    table = onp.random.RandomState(2).randn(20, 3).astype("float32")
    kv.init("emb", mx.nd.array(table))
    out = mx.nd.zeros((20, 3))
    kv.row_sparse_pull("emb", out=out, row_ids=mx.nd.array([4.0, 9.0, 4.0]))
    assert isinstance(out, sparse.RowSparseNDArray) and out.has_parts
    onp.testing.assert_array_equal(out.indices.asnumpy(), [4, 9])
    onp.testing.assert_allclose(out.data.asnumpy(), table[[4, 9]], rtol=1e-6)
    # dense view still correct (zeros elsewhere)
    dense = out.asnumpy()
    assert onp.abs(dense[0]).sum() == 0


def test_large_vocab_sparse_embedding_trains():
    """The point of row_sparse: a large-vocab embedding trains with grads
    and updates proportional to the batch, and the grad buffer holds only
    the live rows."""
    from mxnet_tpu import autograd
    from mxnet_tpu.gluon import nn
    import mxnet_tpu.gluon as gluon
    vocab, dim, batch = 100_000, 16, 32
    emb = nn.Embedding(vocab, dim, sparse_grad=True)
    emb.initialize()
    rs = onp.random.RandomState(3)
    ids = mx.nd.array(rs.randint(0, vocab, (batch,)).astype("float32"))
    _ = emb(ids)
    # the MSE mean divides grads by batch*dim; scale lr so few steps move
    trainer = gluon.Trainer(emb.collect_params(), "sgd",
                            {"learning_rate": 120.0})
    target = mx.nd.array(rs.randn(batch, dim).astype("float32"))
    losses = []
    for _ in range(8):
        with autograd.record():
            diff = emb(ids) - target
            loss = (diff * diff).mean()
        loss.backward()
        g = emb.weight.grad()
        assert g.has_parts
        assert g.data.shape[0] == len(onp.unique(ids.asnumpy()))
        trainer.step(1)
        losses.append(float(loss.asnumpy()))
    assert losses[-1] < losses[0] * 0.5, losses


def test_sparse_grad_through_non_leaf_weight_densifies():
    """When the embedding weight is itself a recorded computation (tied /
    scaled weights), the sparse cotangent must densify to flow through the
    upstream node's vjp instead of crashing."""
    from mxnet_tpu import autograd
    w = mx.nd.array(onp.random.RandomState(0).randn(10, 4).astype("float32"))
    w.attach_grad()
    ids = mx.nd.array(onp.array([1.0, 3.0, 1.0]))
    with autograd.record():
        w2 = w * 2.0
        out = mx.nd.Embedding(ids, w2, input_dim=10, output_dim=4,
                              sparse_grad=True)
        loss = (out * out).sum()
    loss.backward()
    g = w.grad.asnumpy()
    # chain rule through the scale: dL/dw = 2 * dL/dw2
    # per occurrence: dL/dout = 2*out = 4w; dL/dw2 = 4w; dL/dw = 2*4w = 8w
    want = onp.zeros((10, 4), "float32")
    wv = w.asnumpy()
    for i in [1, 3, 1]:
        want[i] += 8 * wv[i]
    onp.testing.assert_allclose(g, want, rtol=1e-5)


def test_row_sparse_pull_out_of_range_ids_dropped():
    kv = mx.kv.create("local")
    kv.init("t", mx.nd.array(onp.arange(8, dtype="f").reshape(4, 2)))
    out = mx.nd.zeros((4, 2))
    kv.row_sparse_pull("t", out=out, row_ids=mx.nd.array([1.0, 9.0]))
    onp.testing.assert_array_equal(out.indices.asnumpy(), [1])
    dense = out.asnumpy()
    onp.testing.assert_array_equal(dense[1], [2.0, 3.0])
    # absent / out-of-range rows are zero, never clamped gathers
    assert onp.abs(dense[[0, 2, 3]]).sum() == 0
