"""Concurrency stress: the threaded host layer under the runtime
lock-order sanitizer.

Drives the real hazards this PR's concurrency rules model: N consumer
threads against one ``DevicePrefetchIter`` with a racing ``close()``
(the shape the prefetcher lifecycle-lock + END-sentinel fix hardens),
and the KV heartbeat publisher flapping through a failing coordinator.
Every scenario runs inside ``LockOrderSanitizer`` and must satisfy the
static-vs-runtime contract: the observed acquisition-order graph is a
subgraph of ``tools.lint.concurrency.static_lock_graph(mxnet_tpu/)``
and contains no cycle.

The tier-1 variant uses 2 consumers and a deterministic close point;
the ``slow``-marked variant randomizes depth, consumer count and close
timing across rounds.
"""
import os
import sys
import threading
import time

import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu.io.device_prefetch import DevicePrefetchIter
from mxnet_tpu.io.io import DataDesc, DataIter

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO) if REPO not in sys.path else None

from tools.lint.runtime_lockorder import LockOrderSanitizer  # noqa: E402

# package_lock_graph: session-scoped fixture from tests/conftest.py


class HostIter(DataIter):
    """Minimal host-side base: ``next_host`` batches with an optional
    per-batch delay so consumers can be forced to block on the ring."""

    def __init__(self, n=16, delay=0.0, batch=4):
        super().__init__(batch)
        self.n, self.delay, self.i = n, delay, 0
        self._batch = batch

    @property
    def provide_data(self):
        return [DataDesc("data", (self._batch, 3, 2, 2))]

    @property
    def provide_label(self):
        return [DataDesc("softmax_label", (self._batch,))]

    def reset(self):
        self.i = 0

    def next_host(self):
        if self.delay:
            time.sleep(self.delay)
        if self.i >= self.n:
            raise StopIteration
        self.i += 1
        data = onp.full((self._batch, 3, 2, 2), self.i, "float32")
        label = onp.zeros((self._batch,), "float32")
        return data, label, 0


def _consume(it, got, errs):
    try:
        while True:
            got.append(it.next())
    except StopIteration:
        pass
    except Exception as e:        # noqa: BLE001 - the assertion payload
        errs.append(e)


def _run_consumers(it, n_threads, close_after_s, join_timeout=20.0):
    got, errs = [], []
    threads = [threading.Thread(target=_consume, args=(it, got, errs),
                                name="consumer-%d" % i)
               for i in range(n_threads)]
    for t in threads:
        t.start()
    time.sleep(close_after_s)
    it.close()
    for t in threads:
        t.join(timeout=join_timeout)
    hung = [t.name for t in threads if t.is_alive()]
    return got, errs, hung


def test_prefetch_concurrent_consume_close_deterministic(
        package_lock_graph):
    """tier-1: 2 consumers, one mid-stream close.  No consumer may
    hang (the END sentinel chains through all blocked waiters), no
    consumer may crash (the queue snapshot in next() beats the
    lifecycle transition), and the observed lock graph must honor the
    static contract."""
    with LockOrderSanitizer() as san:
        it = DevicePrefetchIter(HostIter(n=64, delay=0.01),
                                dtype="float32", depth=2)
        got, errs, hung = _run_consumers(it, n_threads=2,
                                         close_after_s=0.08)
        # a second close is idempotent
        it.close()
    assert not hung, "consumers hung across close(): %s" % hung
    assert not errs, errs
    assert got, "consumers never saw a batch before close"
    san.assert_no_cycles()
    san.assert_subgraph_of(package_lock_graph)


def test_close_wakes_consumer_blocked_on_empty_ring():
    """Regression for the close()-vs-blocked-next() race: with a slow
    feeder the consumer blocks inside q.get(); close() must wake it
    with StopIteration instead of leaving it parked on a dead queue."""
    it = DevicePrefetchIter(HostIter(n=1000, delay=0.15),
                            dtype="float32", depth=1)
    done = threading.Event()
    errs = []

    def consume():
        try:
            while True:
                it.next()
        except StopIteration:
            pass
        except Exception as e:    # noqa: BLE001
            errs.append(e)
        finally:
            done.set()

    t = threading.Thread(target=consume)
    t.start()
    time.sleep(0.4)               # consumer is now blocked on the ring
    it.close()
    assert done.wait(timeout=10), "consumer hung in next() across close()"
    t.join(timeout=5)
    assert not t.is_alive()
    assert not errs, errs


def test_exhaustion_sentinel_chains_to_all_waiters():
    """Natural end-of-epoch with multiple blocked consumers: the
    feeder puts ONE _END; consumers must chain it so every waiter
    unblocks."""
    it = DevicePrefetchIter(HostIter(n=3, delay=0.05), dtype="float32",
                            depth=1)
    got, errs, hung = _run_consumers(it, n_threads=3, close_after_s=0.5)
    assert not hung, hung
    assert not errs, errs
    assert len(got) == 3


def test_feeder_error_unblocks_all_consumers():
    """A feeder error puts ONE (_ERR, e); exactly one consumer must
    surface the exception and every other blocked consumer must wake
    with a clean StopIteration (the _ERR branch chains the sentinel
    like the _END branch does)."""

    class Boom(HostIter):
        def next_host(self):
            if self.i >= 1:
                time.sleep(0.05)
                raise RuntimeError("decode boom")
            return super().next_host()

    it = DevicePrefetchIter(Boom(n=5), dtype="float32", depth=1)
    errs, got = [], []
    threads = [threading.Thread(target=_consume, args=(it, got, errs))
               for _ in range(3)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=10)
    assert not any(t.is_alive() for t in threads), \
        "a consumer stayed blocked after the feeder error"
    assert len(errs) == 1 and "decode boom" in str(errs[0]), errs
    it.close()


def test_reset_epoch_not_poisoned_by_stale_sentinel():
    """A consumer that loses the race against reset() may dequeue the
    OLD queue's shutdown sentinel after the fresh epoch started; that
    stale sentinel must not mark the new epoch exhausted."""
    it = DevicePrefetchIter(HostIter(n=4, delay=0.08), dtype="float32",
                            depth=1)

    def consume_one():
        try:
            it.next()
        except StopIteration:
            pass

    t = threading.Thread(target=consume_one)
    q = it._q
    t.start()
    # wait until the consumer is REALLY parked in q.get() (the queue's
    # not_empty waiter list is the observable), not a fixed sleep —
    # under CI load the thread may take arbitrarily long to get there
    deadline = time.time() + 10
    while time.time() < deadline and not q.not_empty._waiters:
        time.sleep(0.005)
    assert q.not_empty._waiters, "consumer never blocked on the ring"
    it.reset()                    # swaps the queue under the consumer
    t.join(timeout=10)
    assert not t.is_alive()
    fresh = list(it)              # the NEW epoch must deliver in full
    assert len(fresh) == 4, "stale sentinel poisoned the reset epoch"
    it.close()


def test_heartbeat_flap_under_sanitizer(monkeypatch,
                                        package_lock_graph):
    """The mxtpu-heartbeat publisher driven through a flapping
    coordinator (the tests/test_heartbeat.py fake), started and torn
    down inside the sanitizer: stop must join promptly and the lock
    contract must hold."""
    import jax
    from jax._src import distributed as _dist
    from mxnet_tpu import kvstore as kvs

    class FlappingClient:
        def __init__(self):
            self.sets = []
            self.calls = 0

        def key_value_set(self, key, value, allow_overwrite=None):
            self.calls += 1
            if self.calls % 2 == 0:
                raise RuntimeError("coordination service flapped")
            self.sets.append((key, value))

    client = FlappingClient()
    monkeypatch.setattr(_dist.global_state, "client", client,
                        raising=False)
    monkeypatch.setattr(jax, "process_count", lambda: 2)
    monkeypatch.setattr(jax, "process_index", lambda: 0)
    monkeypatch.setenv("MXNET_TPU_HEARTBEAT_TIMEOUT", "2")
    kvs._stop_liveness_heartbeat()
    with LockOrderSanitizer() as san:
        kvs._start_liveness_heartbeat()
        t = kvs._hb_state["thread"]
        assert t is not None and t.is_alive()
        deadline = time.time() + 5
        while len(client.sets) < 1 and time.time() < deadline:
            time.sleep(0.01)
        kvs._stop_liveness_heartbeat()
        assert not t.is_alive()
    assert client.sets and client.sets[0][0] == kvs._HB_KEY % 0
    san.assert_no_cycles()
    san.assert_subgraph_of(package_lock_graph)


@pytest.mark.slow
def test_prefetch_stress_randomized(package_lock_graph):
    """slow sweep: rounds of N consumers x randomized depth and close
    timing, all inside ONE sanitizer scope so the observed graph
    accumulates across schedules."""
    import random
    rng = random.Random(20260804)
    with LockOrderSanitizer() as san:
        for _ in range(10):
            depth = rng.choice([1, 2, 4])
            n_threads = rng.choice([2, 3, 4, 6])
            it = DevicePrefetchIter(
                HostIter(n=48, delay=rng.choice([0.0, 0.002, 0.01])),
                dtype="float32", depth=depth)
            got, errs, hung = _run_consumers(
                it, n_threads=n_threads,
                close_after_s=rng.uniform(0.0, 0.12))
            assert not hung, hung
            assert not errs, errs
    san.assert_no_cycles()
    san.assert_subgraph_of(package_lock_graph)
