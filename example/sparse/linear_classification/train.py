#!/usr/bin/env python
"""Sparse linear (logistic) classification over libsvm data (reference
``example/sparse/linear_classification/train.py``).

The reference trains w·x logistic regression where x is a CSR batch and
the weight is a ``row_sparse`` array updated lazily — only rows touched
by a batch move.  The TPU-native equivalent keeps the same sparsity
contract through the path that is REAL in this build: features arrive as
(index, value) pairs, the weight lives in an ``Embedding(sparse_grad=
True)`` whose backward emits a parts-backed ``RowSparseNDArray``, and the
SGD update is lazy (rows outside the batch are untouched — see
``optimizer/optimizer.py`` lazy_update).  Data is read with
``mx.io.LibSVMIter`` (reference ``src/io/iter_libsvm.cc``).

    python example/sparse/linear_classification/train.py            # synthetic
    python example/sparse/linear_classification/train.py --data a.svm
"""
import argparse
import logging
import os
import sys
import tempfile

import numpy as onp

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", "..", ".."))
import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu import autograd, gluon  # noqa: E402
from mxnet_tpu.gluon import nn  # noqa: E402


def synthetic_libsvm(path, rs, n_rows, n_feat, nnz=8):
    """Binary-label rows: label = sign of a fixed sparse hyperplane."""
    w_true = rs.randn(n_feat)
    with open(path, "w") as f:
        for _ in range(n_rows):
            idx = rs.choice(n_feat, size=nnz, replace=False)
            val = rs.rand(nnz) + 0.1
            y = 1 if float((w_true[idx] * val).sum()) > 0 else 0
            feats = " ".join("%d:%.4f" % (i, v)
                             for i, v in sorted(zip(idx, val)))
            f.write("%d %s\n" % (y, feats))


def batch_to_pairs(x, max_nnz):
    """Dense batch → padded (indices, values, mask) triplet.

    LibSVMIter delivers the documented dense emulation; the nonzero
    structure is recovered here so the model's gather path (the real
    sparse kernel on TPU) sees indices, not a dense matrix."""
    x = x.asnumpy()
    bs = x.shape[0]
    idx = onp.zeros((bs, max_nnz), "int32")
    val = onp.zeros((bs, max_nnz), "float32")
    for r in range(bs):
        nz = onp.nonzero(x[r])[0][:max_nnz]
        idx[r, :len(nz)] = nz
        val[r, :len(nz)] = x[r, nz]
    return mx.nd.array(idx, dtype="int32"), mx.nd.array(val)


class SparseLinear(gluon.Block):
    """w·x + b with the weight behind a sparse-grad gather."""

    def __init__(self, num_features, **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.w = nn.Embedding(num_features, 1, sparse_grad=True,
                                  prefix="w_")
            self.b = self.params.get("bias", shape=(1,), init="zeros")

    def forward(self, idx, val):
        contrib = self.w(idx)[:, :, 0] * val        # (bs, nnz)
        return contrib.sum(axis=1) + self.b.data()


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--data", default=None, help="libsvm file (default: "
                    "generate a synthetic one)")
    ap.add_argument("--num-features", type=int, default=1000)
    ap.add_argument("--batch-size", type=int, default=32)
    ap.add_argument("--epochs", type=int, default=5)
    ap.add_argument("--lr", type=float, default=0.5)
    ap.add_argument("--max-nnz", type=int, default=16)
    ap.add_argument("--seed", type=int, default=0)
    args = ap.parse_args()
    logging.basicConfig(level=logging.INFO)
    rs = onp.random.RandomState(args.seed)
    mx.random.seed(args.seed)

    path = args.data
    tmp = None
    if path is None:
        tmp = tempfile.NamedTemporaryFile(suffix=".svm", delete=False)
        tmp.close()
        path = tmp.name
        synthetic_libsvm(path, rs, 512, args.num_features)

    it = mx.io.LibSVMIter(data_libsvm=path,
                          data_shape=(args.num_features,),
                          batch_size=args.batch_size, round_batch=False)

    net = SparseLinear(args.num_features)
    net.initialize(mx.init.Zero())
    loss_fn = gluon.loss.SigmoidBinaryCrossEntropyLoss()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": args.lr}, kvstore="local")

    first_loss = None
    for epoch in range(args.epochs):
        it.reset()
        total, n = 0.0, 0
        for batch in it:
            idx, val = batch_to_pairs(batch.data[0], args.max_nnz)
            y = batch.label[0]
            with autograd.record():
                logit = net(idx, val)
                loss = loss_fn(logit, y)
            loss.backward()
            # the embedding's gradient really is row-sparse: only rows a
            # batch touched carry parts (lazy SGD skips the rest)
            g = net.w.weight.grad()
            assert getattr(g, "stype", "default") == "row_sparse", g
            trainer.step(idx.shape[0])
            total += float(loss.mean().asscalar()) * idx.shape[0]
            n += idx.shape[0]
        avg = total / max(n, 1)
        if first_loss is None:
            first_loss = avg
        logging.info("epoch %d loss %.4f", epoch, avg)

    # accuracy against the labels it trained on (capability smoke)
    it.reset()
    correct, n = 0, 0
    for batch in it:
        idx, val = batch_to_pairs(batch.data[0], args.max_nnz)
        pred = (net(idx, val).asnumpy() > 0).astype("float32")
        correct += int((pred == batch.label[0].asnumpy()).sum())
        n += idx.shape[0]
    logging.info("final train accuracy: %.3f (loss %.4f -> %.4f)",
                 correct / max(n, 1), first_loss, avg)
    if tmp is not None:
        os.unlink(path)


if __name__ == "__main__":
    main()
