#!/usr/bin/env python
"""Matrix factorization with sparse-gradient embeddings (reference
``example/sparse/matrix_factorization/train.py``).

Classic MovieLens-style MF: rating(u, i) ≈ <p_u, q_i> + b_u + b_i.  Both
factor tables are ``Embedding(sparse_grad=True)`` so a batch's backward
produces parts-backed row-sparse gradients and Adam updates only the
touched rows lazily (the reference's FComputeEx sparse adam kernel,
``src/operator/optimizer_op.cc``).  On TPU the gather/scatter pair rides
XLA's native dynamic-gather; the dense factor matmul is MXU work.

    python example/sparse/matrix_factorization/train.py
"""
import argparse
import logging
import os
import sys

import numpy as onp

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", "..", ".."))
import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu import autograd, gluon  # noqa: E402
from mxnet_tpu.gluon import nn  # noqa: E402


class MFBlock(gluon.Block):
    def __init__(self, n_users, n_items, dim, **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.p = nn.Embedding(n_users, dim, sparse_grad=True,
                                  prefix="user_")
            self.q = nn.Embedding(n_items, dim, sparse_grad=True,
                                  prefix="item_")
            self.bu = nn.Embedding(n_users, 1, sparse_grad=True,
                                   prefix="user_bias_")
            self.bi = nn.Embedding(n_items, 1, sparse_grad=True,
                                   prefix="item_bias_")

    def forward(self, users, items):
        dot = (self.p(users) * self.q(items)).sum(axis=1)
        return dot + self.bu(users)[:, 0] + self.bi(items)[:, 0]


def synthetic_ratings(rs, n_users, n_items, n_obs, dim=4):
    """Low-rank ground truth + noise, centred near 3 stars."""
    P = rs.randn(n_users, dim) * 0.5
    Q = rs.randn(n_items, dim) * 0.5
    u = rs.randint(0, n_users, n_obs)
    i = rs.randint(0, n_items, n_obs)
    r = (P[u] * Q[i]).sum(1) + 3.0 + rs.randn(n_obs) * 0.1
    return (u.astype("int32"), i.astype("int32"),
            onp.clip(r, 1.0, 5.0).astype("float32"))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--num-users", type=int, default=400)
    ap.add_argument("--num-items", type=int, default=300)
    ap.add_argument("--num-obs", type=int, default=4096)
    ap.add_argument("--dim", type=int, default=16)
    ap.add_argument("--batch-size", type=int, default=256)
    ap.add_argument("--epochs", type=int, default=8)
    ap.add_argument("--lr", type=float, default=0.02)
    ap.add_argument("--seed", type=int, default=0)
    args = ap.parse_args()
    logging.basicConfig(level=logging.INFO)
    rs = onp.random.RandomState(args.seed)
    mx.random.seed(args.seed)

    users, items, ratings = synthetic_ratings(
        rs, args.num_users, args.num_items, args.num_obs)
    it = mx.io.NDArrayIter({"user": users, "item": items}, ratings,
                           batch_size=args.batch_size, shuffle=True,
                           last_batch_handle="discard")

    net = MFBlock(args.num_users, args.num_items, args.dim)
    net.initialize(mx.init.Normal(0.05))
    loss_fn = gluon.loss.L2Loss()
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": args.lr}, kvstore="local")

    first = last = None
    for epoch in range(args.epochs):
        it.reset()
        total, n = 0.0, 0
        for batch in it:
            u, i = batch.data
            r = batch.label[0]
            with autograd.record():
                pred = net(u, i)
                loss = loss_fn(pred, r)
            loss.backward()
            trainer.step(u.shape[0])
            total += float(loss.mean().asscalar()) * u.shape[0]
            n += u.shape[0]
        last = total / max(n, 1)
        if first is None:
            first = last
        logging.info("epoch %d mse/2 %.4f", epoch, last)
    logging.info("final rmse: %.4f (loss %.4f -> %.4f)",
                 (2 * last) ** 0.5, first, last)


if __name__ == "__main__":
    main()
