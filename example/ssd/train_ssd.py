#!/usr/bin/env python
"""SSD training example (reference ``example/ssd/train.py`` capability,
unlocked by the contrib detection ops: MultiBoxPrior/Target/Detection).

Trains a compact SSD — multi-scale conv feature maps, per-scale anchor
heads — on synthetic detection data.  By default the FULL step (forward +
MultiBoxTarget assignment + SSD loss + backward + update) compiles into
one jitted XLA program via ``DataParallelStep`` — the target op is pure
jnp/lax, so no host callbacks are involved and the step runs on-chip
(reference runs the same kernels on the accelerator, multibox_target.cu).
``--eager`` keeps the per-op imperative path.

    python example/ssd/train_ssd.py --epochs 2
"""
import argparse
import logging
import os
import sys
import time

import numpy as onp

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))
import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu import autograd, gluon  # noqa: E402
from mxnet_tpu.gluon import nn  # noqa: E402


class SSDNet(gluon.HybridBlock):
    """Small SSD: conv body + 2 downsample stages; cls+loc head per scale."""

    def __init__(self, num_classes, anchors_per_cell, **kwargs):
        super().__init__(**kwargs)
        self.num_classes = num_classes
        a = anchors_per_cell
        with self.name_scope():
            self.body = nn.HybridSequential(prefix="body_")
            with self.body.name_scope():
                for i, ch in enumerate((32, 64)):
                    self.body.add(nn.Conv2D(ch, 3, padding=1,
                                            activation="relu"),
                                  nn.BatchNorm(),
                                  nn.MaxPool2D(2))
            self.down = nn.HybridSequential(prefix="down_")
            self.cls_heads = []
            self.loc_heads = []
            for s in range(3):
                blk = nn.HybridSequential(prefix="down%d_" % s)
                if s > 0:
                    blk.add(nn.Conv2D(64, 3, padding=1, activation="relu"),
                            nn.MaxPool2D(2))
                self.down.add(blk)
                cls = nn.Conv2D((num_classes + 1) * a, 3, padding=1,
                                prefix="cls%d_" % s)
                loc = nn.Conv2D(4 * a, 3, padding=1, prefix="loc%d_" % s)
                self.register_child(cls)
                self.register_child(loc)
                self.cls_heads.append(cls)
                self.loc_heads.append(loc)

    def hybrid_forward(self, F, x):
        f = self.body(x)
        cls_outs, loc_outs = [], []
        for s in range(3):
            f = self.down[s](f)
            b = f.shape[0]
            # (B, A*(C+1), H, W) -> (B, H*W*A, C+1)
            cls_outs.append(self.cls_heads[s](f).transpose(
                axes=(0, 2, 3, 1)).reshape(b, -1, self.num_classes + 1))
            loc_outs.append(self.loc_heads[s](f).transpose(
                axes=(0, 2, 3, 1)).reshape(b, -1))
        return F.concat(*cls_outs, dim=1), F.concat(*loc_outs, dim=1)


def build_anchors(image_size, sizes_per_scale, ratios):
    """MultiBoxPrior per feature scale, concatenated (reference
    symbol_builder multi_layer_feature + anchors)."""
    anchors = []
    # matches SSDNet: body pools /4, then each down stage halves again
    dims = [image_size // 4, image_size // 8, image_size // 16]
    for s, sizes in enumerate(sizes_per_scale):
        fm = mx.nd.zeros((1, 1, dims[s], dims[s]))
        anchors.append(mx.nd.contrib.MultiBoxPrior(
            fm, sizes=sizes, ratios=ratios))
    return mx.nd.concat(*anchors, dim=1)


def synthetic_batch(rs, batch_size, image_size, num_classes):
    """One synthetic image batch: a colored box on noise + its gt."""
    x = rs.rand(batch_size, 3, image_size, image_size).astype("float32")
    labels = onp.full((batch_size, 1, 5), -1.0, "float32")
    for b in range(batch_size):
        cls = rs.randint(0, num_classes)
        x1, y1 = rs.uniform(0.05, 0.4, 2)
        x2, y2 = x1 + rs.uniform(0.2, 0.5), y1 + rs.uniform(0.2, 0.5)
        x2, y2 = min(x2, 0.95), min(y2, 0.95)
        xi = slice(int(x1 * image_size), int(x2 * image_size))
        yi = slice(int(y1 * image_size), int(y2 * image_size))
        x[b, cls % 3, yi, xi] = 1.0
        labels[b, 0] = [cls, x1, y1, x2, y2]
    return mx.nd.array(x), mx.nd.array(labels)


class SSDLoss(gluon.loss.Loss):
    """MultiBoxTarget assignment + class CE + location Huber, all inside
    the traced step (the target op is jnp/lax, so this jits on TPU)."""

    def __init__(self, anchors, num_classes):
        super().__init__(weight=None, batch_axis=0)
        self._anchors = anchors
        self._nc = num_classes
        self._ce = gluon.loss.SoftmaxCrossEntropyLoss()
        self._huber = gluon.loss.HuberLoss()

    def hybrid_forward(self, F, outputs, labels):
        cls_pred, loc_pred = outputs
        loc_t, loc_m, cls_t = F.contrib.MultiBoxTarget(
            self._anchors, labels, cls_pred.transpose(axes=(0, 2, 1)),
            negative_mining_ratio=3.0)
        # targets are labels, not activations: no gradient flows back
        # through the assignment (reference: target op has no backward)
        loc_t, loc_m, cls_t = (F.BlockGrad(t) for t in (loc_t, loc_m,
                                                        cls_t))
        # anchors dropped by negative mining carry cls_target=-1 and must
        # be EXCLUDED: mask them out (a -1 label would wrap to the last
        # class in take_along_axis)
        cls_mask = (cls_t >= 0).reshape(-1, 1)
        cls_loss = self._ce(cls_pred.reshape(-1, self._nc + 1),
                            F.maximum(cls_t, 0).reshape(-1), cls_mask)
        loc_loss = self._huber(loc_pred * loc_m, loc_t * loc_m)
        return cls_loss.mean() + loc_loss.mean()


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch-size", type=int, default=16)
    ap.add_argument("--epochs", type=int, default=3)
    ap.add_argument("--batches-per-epoch", type=int, default=8)
    ap.add_argument("--image-size", type=int, default=64)
    ap.add_argument("--num-classes", type=int, default=3)
    ap.add_argument("--lr", type=float, default=0.05)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--eager", action="store_true",
                    help="per-op imperative step instead of the jitted one")
    args = ap.parse_args()
    logging.basicConfig(level=logging.INFO)

    ratios = (1.0, 2.0, 0.5)
    sizes_per_scale = ((0.2, 0.27), (0.37, 0.45), (0.54, 0.62))
    a = len(sizes_per_scale[0]) + len(ratios) - 1
    net = SSDNet(args.num_classes, a)
    net.initialize(mx.init.Xavier(), ctx=mx.tpu())
    anchors = build_anchors(args.image_size, sizes_per_scale, ratios)
    logging.info("anchors: %s", anchors.shape)

    loss_fn = SSDLoss(anchors.as_in_context(mx.tpu()), args.num_classes)
    rs = onp.random.RandomState(args.seed)

    if not args.eager:
        # warm-up eager forward materializes deferred shapes, then the
        # whole train step (incl. MultiBoxTarget) compiles as ONE program
        x0, _ = synthetic_batch(rs, args.batch_size, args.image_size,
                                args.num_classes)
        net(x0.as_in_context(mx.tpu()))
        step = mx.parallel.DataParallelStep(
            net, loss_fn,
            mx.optimizer.SGD(learning_rate=args.lr, momentum=0.9, wd=5e-4),
            mesh=None)
    else:
        trainer = gluon.Trainer(net.collect_params(), "sgd",
                                {"learning_rate": args.lr, "momentum": 0.9,
                                 "wd": 5e-4})

    for epoch in range(args.epochs):
        tic = time.time()
        epoch_loss = 0.0
        for _ in range(args.batches_per_epoch):
            x, labels = synthetic_batch(rs, args.batch_size,
                                        args.image_size, args.num_classes)
            x = x.as_in_context(mx.tpu())
            if not args.eager:
                loss = step(x, labels.as_in_context(mx.tpu()))
            else:
                with autograd.record():
                    outputs = net(x)
                    loss = loss_fn(outputs, labels)
                loss.backward()
                trainer.step(1)
            epoch_loss += float(loss.asnumpy())
        logging.info("epoch %d: loss %.4f (%.1fs)", epoch,
                     epoch_loss / args.batches_per_epoch,
                     time.time() - tic)

    # decode detections for one batch (inference path)
    cls_pred, loc_pred = net(x)
    probs = mx.nd.softmax(cls_pred.transpose(axes=(0, 2, 1)), axis=1)
    det = mx.nd.contrib.MultiBoxDetection(probs, loc_pred, anchors,
                                          nms_threshold=0.45)
    kept = (det.asnumpy()[:, :, 0] >= 0).sum(axis=1)
    logging.info("detections kept per image: %s", kept[:8].tolist())
    print("FINAL_LOSS %.4f" % (epoch_loss / args.batches_per_epoch))


if __name__ == "__main__":
    main()
