#!/usr/bin/env python
"""DCGAN on synthetic images (reference ``example/gan`` capability:
adversarial training with a transposed-convolution generator).

Generator: latent → Conv2DTranspose stack → 16x16 image.
Discriminator: conv stack → real/fake logit.  Both train imperatively
with alternating updates — the define-by-run pattern GANs need — and each
sub-network hybridizes to a compiled program.

    python example/gan/dcgan.py --epochs 2
"""
import argparse
import logging
import os
import sys
import time

import numpy as onp

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))
import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu import autograd, gluon  # noqa: E402
from mxnet_tpu.gluon import nn  # noqa: E402


def build_generator(latent):
    g = nn.HybridSequential(prefix="gen_")
    with g.name_scope():
        # latent (B, L, 1, 1) -> (B, 32, 4, 4) -> (B, 16, 8, 8) -> (B,1,16,16)
        g.add(nn.Conv2DTranspose(32, 4, strides=1, padding=0,
                                 use_bias=False),
              nn.BatchNorm(), nn.Activation("relu"),
              nn.Conv2DTranspose(16, 4, strides=2, padding=1,
                                 use_bias=False),
              nn.BatchNorm(), nn.Activation("relu"),
              nn.Conv2DTranspose(1, 4, strides=2, padding=1,
                                 use_bias=False),
              nn.Activation("tanh"))
    return g


def build_discriminator():
    d = nn.HybridSequential(prefix="disc_")
    with d.name_scope():
        # no BatchNorm in D: per-pass batch statistics let D separate the
        # real and fake passes trivially (both losses collapse) — the
        # standard DCGAN-on-small-data fix
        d.add(nn.Conv2D(16, 4, strides=2, padding=1),
              nn.LeakyReLU(0.2),
              nn.Conv2D(32, 4, strides=2, padding=1),
              nn.LeakyReLU(0.2),
              nn.Conv2D(1, 4, strides=1, padding=0))
    return d


def real_batch(rs, n):
    """'Real' data: smooth circular blobs — an easy mode to learn."""
    xs = onp.zeros((n, 1, 16, 16), "float32")
    yy, xx = onp.mgrid[0:16, 0:16]
    for i in range(n):
        cx, cy = rs.uniform(5, 11, 2)
        r2 = (xx - cx) ** 2 + (yy - cy) ** 2
        xs[i, 0] = onp.exp(-r2 / rs.uniform(4, 9)) * 2 - 1
    return mx.nd.array(xs)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=3)
    ap.add_argument("--batches-per-epoch", type=int, default=10)
    ap.add_argument("--batch-size", type=int, default=32)
    ap.add_argument("--latent", type=int, default=16)
    ap.add_argument("--lr", type=float, default=2e-3)
    ap.add_argument("--seed", type=int, default=0)
    args = ap.parse_args()
    logging.basicConfig(level=logging.INFO)
    mx.random.seed(args.seed)
    rs = onp.random.RandomState(args.seed)

    G = build_generator(args.latent)
    D = build_discriminator()
    G.initialize(mx.init.Normal(0.02), ctx=mx.tpu())
    D.initialize(mx.init.Normal(0.02), ctx=mx.tpu())

    def noise():
        return mx.nd.array(rs.randn(args.batch_size, args.latent, 1, 1)
                           .astype("float32")).as_in_context(mx.tpu())

    G(noise())                 # materialize
    D(real_batch(rs, 2).as_in_context(mx.tpu()))
    G.hybridize()
    D.hybridize()

    gt = gluon.Trainer(G.collect_params(), "adam",
                       {"learning_rate": args.lr, "beta1": 0.5})
    dt = gluon.Trainer(D.collect_params(), "adam",
                       {"learning_rate": args.lr, "beta1": 0.5})
    bce = gluon.loss.SigmoidBinaryCrossEntropyLoss()
    ones = mx.nd.ones((args.batch_size,), ctx=mx.tpu())
    zeros = mx.nd.zeros((args.batch_size,), ctx=mx.tpu())

    d_loss = g_loss = None
    for epoch in range(args.epochs):
        tic = time.time()
        dsum = gsum = 0.0
        for _ in range(args.batches_per_epoch):
            real = real_batch(rs, args.batch_size).as_in_context(mx.tpu())
            fake = G(noise())
            # D step: real -> 1, fake (detached) -> 0
            with autograd.record():
                d_loss = (bce(D(real).reshape(-1), ones)
                          + bce(D(fake.detach()).reshape(-1), zeros)).mean()
            d_loss.backward()
            dt.step(args.batch_size)
            # G step: fool D
            with autograd.record():
                g_loss = bce(D(G(noise())).reshape(-1), ones).mean()
            g_loss.backward()
            gt.step(args.batch_size)
            dsum += float(d_loss.asnumpy())
            gsum += float(g_loss.asnumpy())
        n = args.batches_per_epoch
        logging.info("epoch %d: D %.4f G %.4f (%.1fs)", epoch, dsum / n,
                     gsum / n, time.time() - tic)

    sample = G(noise())
    spread = float(sample.asnumpy().std())
    logging.info("sample pixel std: %.3f", spread)
    print("FINAL_D %.4f FINAL_G %.4f STD %.3f"
          % (float(d_loss.asnumpy()), float(g_loss.asnumpy()), spread))


if __name__ == "__main__":
    main()
