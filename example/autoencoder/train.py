#!/usr/bin/env python
"""Stacked dense autoencoder (reference ``example/autoencoder/`` —
the AutoEncoderModel pretrain+finetune recipe, condensed to the
end-to-end finetune phase).

Encoder 64→32→8, decoder mirrors it; L2 reconstruction loss; optional
``--denoise`` adds input noise like the reference's corruption stage.
Reconstruction MSE on held-out data must drop well below the variance
of the inputs (the trivial predict-the-mean baseline).

    python example/autoencoder/train.py
    python example/autoencoder/train.py --denoise 0.2
"""
import argparse
import logging
import os
import sys

import numpy as onp

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))
import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu import autograd, gluon  # noqa: E402
from mxnet_tpu.gluon import nn  # noqa: E402


def build(dims=(32, 8)):
    enc = nn.HybridSequential(prefix="enc_")
    with enc.name_scope():
        for d in dims[:-1]:
            enc.add(nn.Dense(d, activation="relu"))
        enc.add(nn.Dense(dims[-1]))
    dec = nn.HybridSequential(prefix="dec_")
    with dec.name_scope():
        for d in reversed(dims[:-1]):
            dec.add(nn.Dense(d, activation="relu"))
        dec.add(nn.Dense(64))
    net = nn.HybridSequential()
    net.add(enc, dec)
    return net


def low_rank_data(rs, n, U):
    """Samples on a fixed rank-r manifold in 64-d: compressible to 8
    codes (train and test share the SAME subspace U)."""
    Z = rs.randn(n, U.shape[0]).astype("float32")
    return Z @ U + 0.05 * rs.randn(n, 64).astype("float32")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=15)
    ap.add_argument("--batch-size", type=int, default=64)
    ap.add_argument("--lr", type=float, default=1e-2)
    ap.add_argument("--denoise", type=float, default=0.0)
    ap.add_argument("--seed", type=int, default=0)
    args = ap.parse_args()
    logging.basicConfig(level=logging.INFO)
    rs = onp.random.RandomState(args.seed)
    mx.random.seed(args.seed)

    U = rs.randn(6, 64).astype("float32")
    Xtr = low_rank_data(rs, 2048, U)
    Xte = low_rank_data(onp.random.RandomState(args.seed + 1), 256, U)
    it = mx.io.NDArrayIter(Xtr, batch_size=args.batch_size, shuffle=True,
                           last_batch_handle="discard")

    net = build()
    net.initialize(mx.init.Xavier())
    net.hybridize()
    loss_fn = gluon.loss.L2Loss()
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": args.lr})

    for epoch in range(args.epochs):
        it.reset()
        total, n = 0.0, 0
        for batch in it:
            x = batch.data[0]
            inp = x
            if args.denoise:
                noise = mx.nd.array(
                    rs.randn(*x.shape).astype("float32") * args.denoise)
                inp = x + noise
            with autograd.record():
                loss = loss_fn(net(inp), x)
            loss.backward()
            trainer.step(x.shape[0])
            total += float(loss.mean().asscalar()) * x.shape[0]
            n += x.shape[0]
        logging.info("epoch %d recon l2 %.4f", epoch, total / n)

    xte = mx.nd.array(Xte)
    mse = float(((net(xte) - xte) ** 2).mean().asscalar())
    baseline = float(Xte.var())
    logging.info("test recon mse %.4f vs input variance %.4f", mse,
                 baseline)
    assert mse < 0.5 * baseline, (mse, baseline)
    print("RECON_MSE %.4f baseline %.4f" % (mse, baseline))


if __name__ == "__main__":
    main()
