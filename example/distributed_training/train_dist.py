#!/usr/bin/env python
"""Multi-process data-parallel training (reference
``example/distributed_training/`` + ``tools/launch.py`` workflow).

Each OS process is one worker: it bootstraps ``jax.distributed`` from the
launcher's env contract, builds the same model, trains on its own shard
of the data, and synchronizes gradients through ``kvstore('dist_sync')``
— whose cross-process aggregation is one jitted collective over the
process-spanning mesh (optionally 2-bit wire-compressed).

Launch locally (N workers on this host):

    python tools/launch.py -n 2 --launcher local \
        python example/distributed_training/train_dist.py

Every worker prints its rank's view of the final loss; all ranks see
bit-identical parameters.
"""
import argparse
import logging
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as onp  # noqa: E402


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=20)
    ap.add_argument("--batch-size", type=int, default=32)
    ap.add_argument("--lr", type=float, default=0.35)
    ap.add_argument("--compress", action="store_true",
                    help="2-bit wire compression on gradient pushes")
    args = ap.parse_args()
    logging.basicConfig(level=logging.INFO)

    import jax
    jax.config.update("jax_platforms", "cpu")
    import mxnet_tpu as mx
    from mxnet_tpu import autograd, gluon
    from mxnet_tpu.gluon import nn

    kv = mx.kv.create("dist_sync")
    rank, nworker = kv.rank, kv.num_workers
    if args.compress:
        kv.set_gradient_compression({"type": "2bit", "threshold": 0.02})
    logging.info("worker %d/%d up", rank, nworker)

    # every worker sees a DIFFERENT shard (seeded by rank), same model
    mx.random.seed(7)
    rs = onp.random.RandomState(100 + rank)
    X = mx.nd.array(rs.rand(256, 16).astype("float32"))
    W_true = onp.linspace(-1, 1, 16).astype("float32")
    Y = mx.nd.array(X.asnumpy() @ W_true)

    net = nn.Dense(1, use_bias=False)
    net.initialize(mx.init.Zero())
    net(X[:1])
    params = list(net.collect_params().values())
    for i, p in enumerate(params):
        kv.init(i, p.data())

    loss_fn = gluon.loss.L2Loss()
    for epoch in range(args.epochs):
        total = 0.0
        for s in range(0, 256, args.batch_size):
            xb, yb = X[s:s + args.batch_size], Y[s:s + args.batch_size]
            with autograd.record():
                loss = loss_fn(net(xb).reshape(-1), yb).mean()
            loss.backward()
            for i, p in enumerate(params):
                # push local grad; pull back the cross-worker aggregate
                kv.push(i, p.grad() / nworker)
                agg = mx.nd.zeros(p.shape)
                kv.pull(i, out=agg)
                p.set_data(p.data() - args.lr * agg)
            total += float(loss.asnumpy())
        logging.info("rank %d epoch %d loss %.5f", rank, epoch, total)
    w = net.weight.data().asnumpy().ravel()
    err = float(onp.abs(w - W_true).max())
    print("RANK %d FINAL_ERR %.4f" % (rank, err))


if __name__ == "__main__":
    main()
