#!/usr/bin/env python
"""Fast Gradient Sign Method adversarial examples (reference
``example/adversary/adversary_generation.ipynb``).

Trains a small classifier on synthetic blob digits, then perturbs test
inputs along sign(∂loss/∂x) — the gradient w.r.t. the INPUT, taken by
attaching a grad to the data array (``x.attach_grad()`` +
``autograd.record``), the same imperative input-gradient path the
reference notebook uses.  Accuracy on the perturbed batch should
collapse while clean accuracy stays high.

    python example/adversarial/fgsm.py
    python example/adversarial/fgsm.py --epsilon 0.3
"""
import argparse
import logging
import os
import sys

import numpy as onp

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))
import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu import autograd, gluon  # noqa: E402
from mxnet_tpu.gluon import nn  # noqa: E402


def synthetic_digits(rs, n, num_classes):
    X = rs.rand(n, 64).astype("float32") * 0.3
    Y = rs.randint(0, num_classes, n)
    for i, k in enumerate(Y):
        X[i, int(k) * 6:int(k) * 6 + 6] += 1.0
    return X, Y.astype("float32")


def accuracy(net, X, Y):
    pred = net(X).asnumpy().argmax(axis=1)
    return float((pred == Y.asnumpy()).mean())


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--num-classes", type=int, default=10)
    ap.add_argument("--epochs", type=int, default=10)
    ap.add_argument("--batch-size", type=int, default=64)
    ap.add_argument("--epsilon", type=float, default=0.5)
    ap.add_argument("--seed", type=int, default=0)
    args = ap.parse_args()
    logging.basicConfig(level=logging.INFO)
    rs = onp.random.RandomState(args.seed)
    mx.random.seed(args.seed)

    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Dense(64, activation="relu"),
                nn.Dense(args.num_classes))
    net.initialize(mx.init.Xavier())
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.2})

    Xtr, Ytr = synthetic_digits(rs, 1024, args.num_classes)
    for epoch in range(args.epochs):
        perm = rs.permutation(len(Xtr))
        total = 0.0
        for s in range(0, len(Xtr), args.batch_size):
            idx = perm[s:s + args.batch_size]
            x = mx.nd.array(Xtr[idx])
            y = mx.nd.array(Ytr[idx])
            with autograd.record():
                loss = loss_fn(net(x), y)
            loss.backward()
            trainer.step(len(idx))
            total += float(loss.mean().asscalar())
        logging.info("epoch %d loss %.4f", epoch,
                     total / (len(Xtr) // args.batch_size))

    Xt, Yt = synthetic_digits(onp.random.RandomState(args.seed + 1), 256,
                              args.num_classes)
    x = mx.nd.array(Xt)
    y = mx.nd.array(Yt)
    clean_acc = accuracy(net, x, y)

    # FGSM: one gradient step on the INPUT
    x.attach_grad()
    with autograd.record():
        loss = loss_fn(net(x), y)
    loss.backward()
    x_adv = x + args.epsilon * x.grad.sign()
    adv_acc = accuracy(net, x_adv, y)

    logging.info("clean accuracy: %.3f", clean_acc)
    logging.info("adversarial accuracy (eps=%.2f): %.3f", args.epsilon,
                 adv_acc)
    assert clean_acc > 0.9, clean_acc
    assert adv_acc < clean_acc - 0.2, (clean_acc, adv_acc)
    print("FGSM_DROP %.3f -> %.3f" % (clean_acc, adv_acc))


if __name__ == "__main__":
    main()
