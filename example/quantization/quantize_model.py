#!/usr/bin/env python
"""Post-training int8 quantization (reference ``example/quantization/``:
imagenet_gen_qsym + imagenet_inference, condensed).

Flow: train a small float conv net → calibrate activation ranges on a
few batches (entropy/KL mode, like the reference calibrator) →
``quantize_model`` rewrites the graph to int8 ops (MXU-native int8
matmuls on TPU) → compare accuracy and argmax agreement against fp32.

    python example/quantization/quantize_model.py
"""
import argparse
import logging
import os
import sys

import numpy as onp

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))
import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu import symbol as sym  # noqa: E402
from mxnet_tpu.contrib.quantization import quantize_model  # noqa: E402


def build_sym(num_classes):
    d = sym.var("data")
    x = sym.Convolution(data=d, num_filter=16, kernel=(3, 3), pad=(1, 1),
                        name="conv1")
    x = sym.Activation(data=x, act_type="relu", name="relu1")
    x = sym.Pooling(data=x, kernel=(2, 2), stride=(2, 2), pool_type="max",
                    name="pool1")
    x = sym.Flatten(data=x, name="flat")
    x = sym.FullyConnected(data=x, num_hidden=32, name="fc1")
    x = sym.Activation(data=x, act_type="relu", name="relu2")
    x = sym.FullyConnected(data=x, num_hidden=num_classes, name="fc2")
    return sym.SoftmaxOutput(data=x, name="softmax")


def synthetic_data(rs, n, num_classes):
    """Blob-per-class images: class k lights up a kxk-ish quadrant."""
    X = rs.rand(n, 1, 8, 8).astype("float32") * 0.2
    Y = rs.randint(0, num_classes, n)
    for i, k in enumerate(Y):
        r, c = divmod(int(k), 2)
        X[i, 0, r * 4:r * 4 + 4, c * 4:c * 4 + 4] += 1.0
    return X, Y.astype("float32")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--num-classes", type=int, default=4)
    ap.add_argument("--epochs", type=int, default=8)
    ap.add_argument("--calib-mode", default="entropy",
                    choices=["none", "naive", "entropy"])
    ap.add_argument("--seed", type=int, default=0)
    args = ap.parse_args()
    logging.basicConfig(level=logging.INFO)
    rs = onp.random.RandomState(args.seed)

    X, Y = synthetic_data(rs, 256, args.num_classes)
    it = mx.io.NDArrayIter(X, Y, batch_size=32, shuffle=True,
                           label_name="softmax_label")

    net = build_sym(args.num_classes)
    mod = mx.mod.Module(net, context=mx.cpu(),
                        label_names=["softmax_label"])
    mod.fit(it, num_epoch=args.epochs, optimizer="sgd",
            optimizer_params={"learning_rate": 0.3,
                              "rescale_grad": 1.0 / 32},
            initializer=mx.init.Xavier())
    fp32_acc = mod.score(it, "acc")[0][1]
    logging.info("fp32 accuracy: %.3f", fp32_acc)

    arg_params, aux_params = mod.get_params()
    calib = mx.io.NDArrayIter(X[:96], Y[:96], batch_size=32,
                              label_name="softmax_label")
    qsym, qargs, qaux = quantize_model(
        net, arg_params, aux_params, calib_mode=args.calib_mode,
        calib_data=calib, num_calib_examples=96,
        excluded_sym_names=["fc2"])      # keep the tiny head in float
    logging.info("quantized graph ops: %d",
                 qsym.tojson().count('"op"'))

    # evaluate the int8 graph imperatively (quantized param shapes are
    # carried by the arrays themselves, reference imagenet_inference.py
    # feeds them the same way)
    feed = {**qargs, **qaux}
    preds = qsym.eval_imperative(
        {**feed, "data": mx.nd.array(X),
         "softmax_label": mx.nd.array(Y)}).asnumpy()
    int8_acc = float((preds.argmax(axis=1) == Y).mean())
    logging.info("int8 accuracy: %.3f (fp32 %.3f)", int8_acc, fp32_acc)
    print("FP32_ACC %.4f" % fp32_acc)
    print("INT8_ACC %.4f" % int8_acc)


if __name__ == "__main__":
    main()
