#!/usr/bin/env python
"""Train an MLP on MNIST through the Module path (reference
``example/image-classification/train_mnist.py``).

Uses the gluon vision MNIST dataset when its files are available, else a
synthetic separable task (so the script always runs offline).
"""
import argparse
import logging
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as onp  # noqa: E402

import mxnet_tpu as mx  # noqa: E402
from symbols import get_mlp  # noqa: E402


def get_mnist_iters(batch_size):
    try:
        from mxnet_tpu.gluon.data.vision import MNIST
        train = MNIST(train=True)
        # read the dataset's whole-array storage once instead of 60k
        # per-item __getitem__ device round-trips
        X = onp.asarray(train._data.asnumpy(), "float32")
        X = X.reshape(len(X), -1) / 255
        Y = onp.asarray(train._label, "float32").reshape(-1)
    except Exception:
        logging.warning("MNIST files unavailable; using synthetic data")
        rs = onp.random.RandomState(0)
        X = rs.uniform(0, 1, (4096, 784)).astype("float32")
        W = rs.normal(0, 1, (784, 10)).astype("float32")
        Y = (X @ W).argmax(axis=1).astype("float32")
    n = int(len(X) * 0.9)
    train_iter = mx.io.NDArrayIter(X[:n], Y[:n], batch_size, shuffle=True,
                                   label_name="softmax_label")
    val_iter = mx.io.NDArrayIter(X[n:], Y[n:], batch_size,
                                 label_name="softmax_label")
    return train_iter, val_iter


def main():
    parser = argparse.ArgumentParser(description="train mnist")
    parser.add_argument("--batch-size", type=int, default=64)
    parser.add_argument("--num-epochs", type=int, default=5)
    parser.add_argument("--lr", type=float, default=0.1)
    parser.add_argument("--kv-store", default="local")
    parser.add_argument("--model-prefix", default=None)
    args = parser.parse_args()
    logging.basicConfig(level=logging.INFO)

    train, val = get_mnist_iters(args.batch_size)
    devs = mx.tpu() if mx.num_tpus() else mx.cpu()
    mod = mx.mod.Module(get_mlp(10), context=devs)
    epoch_cb = mx.callback.do_checkpoint(args.model_prefix) \
        if args.model_prefix else None
    mod.fit(train, eval_data=val, num_epoch=args.num_epochs,
            kvstore=args.kv_store, optimizer="sgd",
            optimizer_params={"learning_rate": args.lr, "momentum": 0.9,
                              "rescale_grad": 1.0 / args.batch_size},
            initializer=mx.init.Xavier(),
            batch_end_callback=mx.callback.Speedometer(args.batch_size,
                                                       100),
            epoch_end_callback=epoch_cb)
    score = mod.score(val, "acc")
    logging.info("final validation accuracy: %s", score)


if __name__ == "__main__":
    main()
