#!/usr/bin/env python
"""Transfer learning / finetune from upstream ``.params`` (reference
docs/faq/finetune.md; example/image-classification/fine-tune.py).

Flow:
  1. a "pretrained" ResNet-18 checkpoint is written in the upstream
     binary ``.params`` format (the same dmlc NDArray container real
     MXNet ships — mxnet_tpu reads/writes it bit-compatibly);
  2. a fresh zoo net with a DIFFERENT number of classes loads the
     feature weights from that checkpoint (head skipped);
  3. only the new head trains at full lr (features frozen via
     grad_req='null'), on a synthetic 3-class color task;
  4. prints FINAL_ACC for the smoke test.
"""
import argparse
import os
import sys

import numpy as onp

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu import gluon  # noqa: E402
from mxnet_tpu.gluon.model_zoo import vision  # noqa: E402
from mxnet_tpu.gluon.utils import materialize_params  # noqa: E402


def synthetic_batches(n_batches, batch_size, size, rs):
    """3-class task: class = brightest channel."""
    for _ in range(n_batches):
        y = rs.randint(0, 3, batch_size)
        x = rs.uniform(0, 0.3, (batch_size, 3, size, size)).astype("float32")
        for i, c in enumerate(y):
            x[i, c] += 0.6
        yield mx.nd.array(x), mx.nd.array(y.astype("float32"))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--image-size", type=int, default=32)
    ap.add_argument("--batch-size", type=int, default=16)
    ap.add_argument("--epochs", type=int, default=3)
    ap.add_argument("--batches-per-epoch", type=int, default=8)
    ap.add_argument("--lr", type=float, default=0.05)
    ap.add_argument("--params", default="")
    args = ap.parse_args()
    rs = onp.random.RandomState(0)

    params_file = args.params
    if not params_file:
        # 1) fabricate the "upstream checkpoint": a 1000-class ResNet-18
        src = vision.resnet18_v1(classes=1000)
        src.initialize(mx.init.Xavier())
        materialize_params(src, mx.nd.zeros(
            (1, 3, args.image_size, args.image_size)))
        params_file = "/tmp/finetune_src.params"
        # upstream BINARY .params container (dmlc NDArray list format),
        # exactly what a real-MXNet deployment ships
        from mxnet_tpu.ndarray.legacy_io import is_legacy_file, save_legacy
        sp = src._collect_params_with_prefix()
        save_legacy(params_file,
                    {k: v.data().asnumpy() for k, v in sp.items()
                     if v._data is not None})
        assert is_legacy_file(params_file), \
            "checkpoint must be upstream binary format"

    # 2) fresh net, NEW head (3 classes); load feature weights only
    net = vision.resnet18_v1(classes=3)
    net.initialize(mx.init.Xavier())
    materialize_params(net, mx.nd.zeros(
        (1, 3, args.image_size, args.image_size)))
    # the classic finetune surgery (reference fine-tune.py get_fine_tune_
    # model): take every feature tensor from the checkpoint, drop the old
    # 1000-way head
    loaded = mx.nd.load(params_file)
    fp = net.features._collect_params_with_prefix()
    n_loaded = 0
    for k, p in fp.items():
        src_k = "features." + k
        if src_k in loaded:
            p.set_data(loaded[src_k].astype(p.dtype))
            n_loaded += 1
    assert n_loaded == len(fp), (n_loaded, len(fp))
    assert not any(k.startswith("output.") and "3" in str(loaded[k].shape)
                   for k in loaded), "old head is 1000-way"
    after = {k: v.data().asnumpy() for k, v
             in net.features.collect_params().items()}
    print("loaded %d feature tensors from %s" % (n_loaded, params_file))

    # 3) freeze features, train only the head
    for _, p in net.features.collect_params().items():
        p.grad_req = "null"
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": args.lr, "momentum": 0.9})

    acc = mx.metric.Accuracy()
    for epoch in range(args.epochs):
        acc.reset()
        for x, y in synthetic_batches(args.batches_per_epoch,
                                      args.batch_size, args.image_size, rs):
            with mx.autograd.record():
                out = net(x)
                loss = loss_fn(out, y).mean()
            loss.backward()
            trainer.step(1)
            acc.update([y], [out])
        print("epoch %d acc %.3f" % (epoch, acc.get()[1]))

    # frozen feature WEIGHTS must be untouched by training (BN moving
    # stats still track batch statistics in train mode — the reference's
    # frozen-backbone finetune behaves the same)
    final = {k: v.data().asnumpy() for k, v
             in net.features.collect_params().items()}
    for k in after:
        if "moving_" in k or "running_" in k:
            continue
        onp.testing.assert_array_equal(after[k], final[k])
    print("FINAL_ACC %.3f" % acc.get()[1])


if __name__ == "__main__":
    main()
