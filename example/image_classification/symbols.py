"""Symbolic image-classification networks (reference
``example/image-classification/symbols/{resnet,mlp}.py``).

Built on the ``mx.sym`` API so the Module/`train_imagenet.py` path runs
the same way reference scripts do; the graphs compile to single XLA
programs via the Executor.
"""
from mxnet_tpu import symbol as sym


def get_mlp(num_classes=10):
    data = sym.var("data")
    net = sym.Flatten(data)
    net = sym.FullyConnected(net, num_hidden=128, name="fc1")
    net = sym.Activation(net, act_type="relu")
    net = sym.FullyConnected(net, num_hidden=64, name="fc2")
    net = sym.Activation(net, act_type="relu")
    net = sym.FullyConnected(net, num_hidden=num_classes, name="fc3")
    return sym.SoftmaxOutput(net, name="softmax")


def _conv_bn_relu(data, num_filter, kernel, stride, pad, name,
                  relu=True):
    net = sym.Convolution(data, kernel=kernel, num_filter=num_filter,
                          stride=stride, pad=pad, no_bias=True,
                          name=name + "_conv")
    net = sym.BatchNorm(net, fix_gamma=False, eps=2e-5, momentum=0.9,
                        name=name + "_bn")
    if relu:
        net = sym.Activation(net, act_type="relu", name=name + "_relu")
    return net


def _residual_unit(data, num_filter, stride, dim_match, name,
                   bottle_neck=True):
    """One ResNet v1 unit (reference symbols/resnet.py residual_unit)."""
    if bottle_neck:
        body = _conv_bn_relu(data, num_filter // 4, (1, 1), (1, 1), (0, 0),
                             name + "_c1")
        body = _conv_bn_relu(body, num_filter // 4, (3, 3), stride, (1, 1),
                             name + "_c2")
        body = _conv_bn_relu(body, num_filter, (1, 1), (1, 1), (0, 0),
                             name + "_c3", relu=False)
    else:
        body = _conv_bn_relu(data, num_filter, (3, 3), stride, (1, 1),
                             name + "_c1")
        body = _conv_bn_relu(body, num_filter, (3, 3), (1, 1), (1, 1),
                             name + "_c2", relu=False)
    if dim_match:
        shortcut = data
    else:
        shortcut = _conv_bn_relu(data, num_filter, (1, 1), stride, (0, 0),
                                 name + "_sc", relu=False)
    return sym.Activation(body + shortcut, act_type="relu",
                          name=name + "_out")


_RESNET_CFG = {  # depth -> (bottleneck, units, filters)
    18: (False, [2, 2, 2, 2], [64, 64, 128, 256, 512]),
    34: (False, [3, 4, 6, 3], [64, 64, 128, 256, 512]),
    50: (True, [3, 4, 6, 3], [64, 256, 512, 1024, 2048]),
    101: (True, [3, 4, 23, 3], [64, 256, 512, 1024, 2048]),
    152: (True, [3, 8, 36, 3], [64, 256, 512, 1024, 2048]),
}


def get_resnet(depth=50, num_classes=1000, image_shape=(3, 224, 224)):
    """ResNet v1 symbol (reference symbols/resnet.py resnet()).

    Small inputs (height <= 32, e.g. CIFAR) get the 3x3/s1 stem without the
    stem max-pool, like the reference's small-image branch, so the last
    stages don't collapse to 1x1 feature maps.
    """
    bottle_neck, units, filters = _RESNET_CFG[depth]
    data = sym.var("data")
    small_image = image_shape[-2] <= 32
    if small_image:
        body = _conv_bn_relu(data, filters[0], (3, 3), (1, 1), (1, 1),
                             "stem")
    else:
        body = _conv_bn_relu(data, filters[0], (7, 7), (2, 2), (3, 3),
                             "stem")
        body = sym.Pooling(body, kernel=(3, 3), stride=(2, 2), pad=(1, 1),
                           pool_type="max", name="stem_pool")
    for stage, n_units in enumerate(units):
        for unit in range(n_units):
            stride = (1, 1) if stage == 0 or unit > 0 else (2, 2)
            # identity shortcut when channels and stride match; stage 1 of
            # basic-block resnets (18/34) keeps 64 channels at stride 1
            dim_match = unit > 0 or (
                stage == 0 and filters[0] == filters[1])
            body = _residual_unit(
                body, filters[stage + 1], stride, dim_match=dim_match,
                name="stage%d_unit%d" % (stage + 1, unit + 1),
                bottle_neck=bottle_neck)
    body = sym.Pooling(body, global_pool=True, pool_type="avg",
                       kernel=(7, 7), name="global_pool")
    body = sym.Flatten(body)
    body = sym.FullyConnected(body, num_hidden=num_classes, name="fc1")
    return sym.SoftmaxOutput(body, name="softmax")


def get_symbol(network, num_classes, **kwargs):
    if network == "mlp":
        return get_mlp(num_classes)
    if network.startswith("resnet"):
        return get_resnet(int(network[len("resnet"):]), num_classes,
                          **kwargs)
    raise ValueError("unknown network %r" % network)
