#!/usr/bin/env python
"""Train an image-classification network through the Module path
(reference ``example/image-classification/train_imagenet.py`` +
``common/fit.py``).

The north-star invocation shapes work unchanged:

    python train_imagenet.py --network resnet50 --kv-store tpu \
        --batch-size 64 --benchmark 1

``--benchmark 1`` feeds synthetic data (reference fit.py --benchmark),
which is also what the published perf numbers used
(docs/faq/perf.md:239-241).  With --data-train pointing at a .rec file,
ImageRecordIter-equivalent input (mx.image.ImageIter) is used.
"""
import argparse
import logging
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as onp  # noqa: E402

import mxnet_tpu as mx  # noqa: E402
from symbols import get_symbol  # noqa: E402


class SyntheticDataIter(mx.io.DataIter):
    """Reference common/fit.py SyntheticDataIter: on-host random batch
    served repeatedly (input pipeline excluded from the benchmark)."""

    def __init__(self, num_classes, data_shape, max_iter, dtype="float32"):
        super().__init__(batch_size=data_shape[0])
        self.cur_iter = 0
        self.max_iter = max_iter
        rs = onp.random.RandomState(99)
        label = rs.randint(0, num_classes, (data_shape[0],))
        self.data = mx.nd.array(
            rs.uniform(-1, 1, data_shape).astype(dtype))
        self.label = mx.nd.array(label.astype("float32"))
        self._provide_data = [mx.io.DataDesc("data", data_shape)]
        self._provide_label = [mx.io.DataDesc("softmax_label",
                                              (data_shape[0],))]

    @property
    def provide_data(self):
        return self._provide_data

    @property
    def provide_label(self):
        return self._provide_label

    def next(self):
        self.cur_iter += 1
        if self.cur_iter > self.max_iter:
            raise StopIteration
        return mx.io.DataBatch(data=[self.data], label=[self.label],
                               pad=0, index=None,
                               provide_data=self._provide_data,
                               provide_label=self._provide_label)

    def reset(self):
        self.cur_iter = 0


def get_data(args):
    image_shape = tuple(int(v) for v in args.image_shape.split(","))
    if args.benchmark:
        shape = (args.batch_size,) + image_shape
        train = SyntheticDataIter(args.num_classes, shape,
                                  args.num_batches, args.dtype)
        return train, None
    train = mx.image.ImageIter(
        batch_size=args.batch_size, data_shape=image_shape,
        path_imgrec=args.data_train, shuffle=True,
        label_name="softmax_label")
    val = None
    if args.data_val:
        val = mx.image.ImageIter(
            batch_size=args.batch_size, data_shape=image_shape,
            path_imgrec=args.data_val, label_name="softmax_label")
    return train, val


def main():
    parser = argparse.ArgumentParser(description="train imagenet",
                                     formatter_class=argparse.ArgumentDefaultsHelpFormatter)
    parser.add_argument("--network", default="resnet50")
    parser.add_argument("--num-classes", type=int, default=1000)
    parser.add_argument("--num-epochs", type=int, default=1)
    parser.add_argument("--batch-size", type=int, default=64)
    parser.add_argument("--image-shape", default="3,224,224")
    parser.add_argument("--kv-store", default="local")
    parser.add_argument("--lr", type=float, default=0.1)
    parser.add_argument("--mom", type=float, default=0.9)
    parser.add_argument("--wd", type=float, default=1e-4)
    parser.add_argument("--optimizer", default="sgd")
    parser.add_argument("--dtype", default="float32")
    parser.add_argument("--benchmark", type=int, default=0)
    parser.add_argument("--num-batches", type=int, default=40,
                        help="batches per epoch in benchmark mode")
    parser.add_argument("--data-train", default=None)
    parser.add_argument("--data-val", default=None)
    parser.add_argument("--disp-batches", type=int, default=10)
    parser.add_argument("--model-prefix", default=None)
    parser.add_argument("--amp", type=int, default=0,
                        help="1 = bf16 mixed precision via contrib.amp")
    args = parser.parse_args()

    logging.basicConfig(level=logging.INFO)
    logging.info("args: %s", args)
    # (under tools/launch.py, importing mxnet_tpu already joined the
    # coordination service from the env contract)

    if args.amp:
        from mxnet_tpu.contrib import amp
        amp.init(target_dtype="bfloat16")

    image_shape = tuple(int(v) for v in args.image_shape.split(","))
    net = get_symbol(args.network, args.num_classes,
                     image_shape=image_shape)
    devs = mx.tpu() if mx.num_tpus() else mx.cpu()
    mod = mx.mod.Module(net, context=devs)
    train, val = get_data(args)

    optimizer_params = {
        "learning_rate": args.lr,
        "wd": args.wd,
        "rescale_grad": 1.0 / args.batch_size,
    }
    if args.optimizer in ("sgd", "nag"):
        optimizer_params["momentum"] = args.mom

    callbacks = [mx.callback.Speedometer(args.batch_size,
                                         args.disp_batches)]
    epoch_cb = None
    if args.model_prefix:
        epoch_cb = mx.callback.do_checkpoint(args.model_prefix)

    tic = time.time()
    mod.fit(train, eval_data=val, num_epoch=args.num_epochs,
            kvstore=args.kv_store, optimizer=args.optimizer,
            optimizer_params=optimizer_params,
            initializer=mx.init.Xavier(rnd_type="gaussian",
                                       factor_type="in", magnitude=2),
            batch_end_callback=callbacks, epoch_end_callback=epoch_cb,
            eval_metric=["acc"])
    total = args.num_batches * args.num_epochs * args.batch_size
    dt = time.time() - tic
    if args.benchmark:
        logging.info("benchmark: %.2f img/s overall (incl. compile)",
                     total / dt)


if __name__ == "__main__":
    main()
