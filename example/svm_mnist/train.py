#!/usr/bin/env python
"""SVM digit classification with ``SVMOutput`` (reference
``example/svm_mnist/svm_mnist.py``).

The reference swaps a softmax head for ``SVMOutput`` — forward is
identity, backward injects the multiclass hinge-loss gradient (L2-SVM by
default, ``use_linear`` for L1) — and trains a small MLP on MNIST.  This
build registers the same op (``ops/nn.py`` SVMOutput, ref
``src/operator/svm_output.cc``); here it trains on synthetic blob digits
so it runs with zero egress, via the Module API end to end.

    python example/svm_mnist/train.py
    python example/svm_mnist/train.py --l1  # linear hinge
"""
import argparse
import logging
import os
import sys

import numpy as onp

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))
import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu import symbol as sym  # noqa: E402


def build_sym(num_classes, use_linear):
    d = sym.var("data")
    x = sym.FullyConnected(data=d, num_hidden=64, name="fc1")
    x = sym.Activation(data=x, act_type="relu", name="relu1")
    x = sym.FullyConnected(data=x, num_hidden=num_classes, name="fc2")
    return sym.SVMOutput(data=x, name="svm", margin=1.0,
                         regularization_coefficient=1.0,
                         use_linear=use_linear)


def synthetic_digits(rs, n, num_classes):
    """Blob-per-class 8x8 images (stands in for MNIST: zero egress)."""
    X = rs.rand(n, 64).astype("float32") * 0.3
    Y = rs.randint(0, num_classes, n)
    for i, k in enumerate(Y):
        X[i, int(k) * 6:int(k) * 6 + 6] += 1.0
    return X, Y.astype("float32")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--num-classes", type=int, default=10)
    ap.add_argument("--batch-size", type=int, default=64)
    ap.add_argument("--epochs", type=int, default=10)
    ap.add_argument("--lr", type=float, default=0.1)
    ap.add_argument("--l1", action="store_true", help="linear (L1) hinge")
    ap.add_argument("--seed", type=int, default=0)
    args = ap.parse_args()
    logging.basicConfig(level=logging.INFO)
    rs = onp.random.RandomState(args.seed)

    X, Y = synthetic_digits(rs, 1024, args.num_classes)
    Xv, Yv = synthetic_digits(onp.random.RandomState(args.seed + 1), 256,
                              args.num_classes)
    train = mx.io.NDArrayIter(X, Y, batch_size=args.batch_size, shuffle=True,
                              label_name="svm_label")
    val = mx.io.NDArrayIter(Xv, Yv, batch_size=args.batch_size,
                            label_name="svm_label")

    mod = mx.mod.Module(build_sym(args.num_classes, args.l1),
                        context=mx.cpu(), label_names=["svm_label"])
    mod.fit(train, eval_data=val, num_epoch=args.epochs, optimizer="sgd",
            optimizer_params={"learning_rate": args.lr,
                              "rescale_grad": 1.0 / args.batch_size},
            initializer=mx.init.Xavier(),
            eval_metric=mx.metric.Accuracy())
    acc = mod.score(val, mx.metric.Accuracy())[0][1]
    logging.info("final validation accuracy: %.3f", acc)


if __name__ == "__main__":
    main()
