#!/usr/bin/env python
"""BERT masked-LM pretraining (BASELINE config 5: "BERT-base pretraining,
mixed-precision" — the reference ecosystem's GluonNLP bert pretraining
script, built on src/operator/contrib/transformer.cc attention ops).

TPU-native: the encoder's attention runs in the Pallas flash kernel WITH
the per-row padding mask applied inside the online softmax
(``valid_length``), the net trains in bf16 (MXU-native), and the whole
step — forward, masked-position cross-entropy, backward, Adam — is ONE
donated-buffer XLA program via ``DataParallelStep``.

    python example/bert/pretrain.py --arch small --epochs 2      # smoke
    python example/bert/pretrain.py --arch base --seq-len 512

Synthetic corpus: Markov token streams (maskable positions are
predictable from context, so the MLM loss genuinely descends); point
--data at a token-id .npy of shape (N, seq_len) for real input.  NSP is
not included (the RoBERTa-style MLM-only recipe).
"""
import argparse
import logging
import os
import sys
import time

import numpy as onp

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))
import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu import gluon  # noqa: E402
from mxnet_tpu.gluon.model_zoo import bert_base, bert_small  # noqa: E402

MASK_RATE = 0.15


def synthetic_mlm_batch(rs, batch_size, seq_len, vocab, mask_id):
    """Markov token rows + random valid lengths; 15% of valid positions
    masked.  Returns (tokens, valid_length, positions, labels): the
    GluonNLP pretraining shape — ``positions`` (B, P) are the masked
    slots the model decodes (P = 15% of seq_len; short rows pad with
    position 0 / label -1, which the loss masks out)."""
    n_pred = max(1, int(seq_len * MASK_RATE))
    toks = onp.zeros((batch_size, seq_len), onp.int64)
    state = rs.randint(5, vocab, batch_size)
    for t in range(seq_len):
        state = (state * 13 + rs.randint(0, 5, batch_size)) % (vocab - 5) + 5
        toks[:, t] = state
    vl = rs.randint(seq_len // 2, seq_len + 1, batch_size)
    positions = onp.zeros((batch_size, n_pred), onp.int64)
    labels = onp.full((batch_size, n_pred), -1.0, onp.float32)
    inp = toks.copy()
    for b in range(batch_size):
        n_mask = min(n_pred, max(1, int(vl[b] * MASK_RATE)))
        pos = onp.sort(rs.choice(vl[b], n_mask, replace=False))
        positions[b, :n_mask] = pos
        labels[b, :n_mask] = toks[b, pos]
        inp[b, pos] = mask_id
        inp[b, vl[b]:] = 0
    return (mx.nd.array(inp.astype("float32")),
            mx.nd.array(vl.astype("int32"), dtype="int32"),
            mx.nd.array(positions.astype("int32"), dtype="int32"),
            mx.nd.array(labels))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--arch", default="small", choices=["small", "base"])
    ap.add_argument("--vocab", type=int, default=1000)
    ap.add_argument("--seq-len", type=int, default=128)
    ap.add_argument("--batch-size", type=int, default=16)
    ap.add_argument("--epochs", type=int, default=3)
    ap.add_argument("--batches-per-epoch", type=int, default=16)
    ap.add_argument("--lr", type=float, default=5e-4)
    ap.add_argument("--dtype", default="bfloat16",
                    choices=["float32", "bfloat16"])
    ap.add_argument("--data", default=None,
                    help=".npy of token ids (N, seq_len); synthetic if "
                    "unset")
    ap.add_argument("--seed", type=int, default=0)
    args = ap.parse_args()
    logging.basicConfig(level=logging.INFO)
    mx.random.seed(args.seed)
    rs = onp.random.RandomState(args.seed)
    mask_id = 1                             # [MASK]

    ctor = bert_base if args.arch == "base" else bert_small
    net = ctor(vocab_size=args.vocab, max_length=args.seq_len,
               dropout=0.1, use_pooler=False, use_decoder=True)
    net.initialize(mx.init.Xavier())
    tokens, vl, positions, labels = synthetic_mlm_batch(
        rs, args.batch_size, args.seq_len, args.vocab, mask_id)
    net(tokens, None, None, vl, positions)  # materialize deferred shapes
    if args.dtype != "float32":
        net.cast(args.dtype)                # bf16: the AMP-equivalent tier
    net.collect_params().reset_ctx(mx.tpu())

    corpus = None
    if args.data:
        corpus = onp.load(args.data)
        logging.info("corpus: %s", corpus.shape)

    vocab = args.vocab

    class MLMLoss(gluon.loss.Loss):
        """CE over the gathered masked positions (labels -1 = pad).

        The model decodes ONLY ``masked_positions`` (B, P) — the vocab
        projection never touches the other 85% of slots, exactly like
        the GluonNLP pretraining pipeline."""

        def __init__(self):
            super().__init__(weight=None, batch_axis=0)
            self._ce = gluon.loss.SoftmaxCrossEntropyLoss()

        def hybrid_forward(self, F, outputs, lab):
            _, logits = outputs                       # (B, P, vocab)
            flat = lab.reshape(-1)
            mask = (flat >= 0).reshape(-1, 1)
            ce = self._ce(logits.reshape(-1, vocab),
                          F.maximum(flat, 0), mask)
            return ce.sum() / F.maximum(mask.sum(), 1.0)

    step = mx.parallel.DataParallelStep(
        net, MLMLoss(), mx.optimizer.Adam(learning_rate=args.lr),
        mesh=None)

    final = None
    for epoch in range(args.epochs):
        tic = time.time()
        total = 0.0
        for b in range(args.batches_per_epoch):
            if corpus is not None:
                n_pred = max(1, int(args.seq_len * MASK_RATE))
                rows = rs.randint(0, corpus.shape[0], args.batch_size)
                toks = corpus[rows]
                vl_np = onp.full(args.batch_size, args.seq_len)
                pos_np = onp.zeros((args.batch_size, n_pred), onp.int64)
                labels_np = onp.full((args.batch_size, n_pred), -1.0,
                                     onp.float32)
                inp = toks.copy()
                for i in range(args.batch_size):
                    pos = onp.sort(rs.choice(args.seq_len, n_pred,
                                             replace=False))
                    pos_np[i] = pos
                    labels_np[i] = toks[i, pos]
                    inp[i, pos] = mask_id
                tokens = mx.nd.array(inp.astype("float32"))
                vl = mx.nd.array(vl_np.astype("int32"), dtype="int32")
                positions = mx.nd.array(pos_np.astype("int32"),
                                        dtype="int32")
                labels = mx.nd.array(labels_np)
            else:
                tokens, vl, positions, labels = synthetic_mlm_batch(
                    rs, args.batch_size, args.seq_len, args.vocab, mask_id)
            loss = step((tokens.as_in_context(mx.tpu()), None, None,
                         vl.as_in_context(mx.tpu()),
                         positions.as_in_context(mx.tpu())),
                        labels.as_in_context(mx.tpu()))
            total += float(loss.asnumpy())
        n = args.batches_per_epoch
        toks_s = n * args.batch_size * args.seq_len / (time.time() - tic)
        logging.info("epoch %d: mlm loss %.4f (%.0f tok/s)", epoch,
                     total / n, toks_s)
        final = total / n
    print("FINAL_LOSS %.4f" % final)


if __name__ == "__main__":
    main()
