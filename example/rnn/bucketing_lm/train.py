#!/usr/bin/env python
"""Bucketing LSTM language model (reference example/rnn/bucketing/
lstm_bucketing.py + bucket_io.py; docs/faq/bucketing.md).

Variable-length sentences are grouped into length buckets; ONE set of
parameters is shared across buckets while each bucket length gets its own
compiled program — `BucketingModule`'s per-bucket jit cache, the XLA
answer to the reference's per-bucket shared-memory executors.

Synthetic corpus: order-2 patterned sequences so the LM has real structure
to learn; prints FINAL_PPL for the smoke test.
"""
import argparse
import os
import sys

import numpy as onp

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", "..", ".."))

import mxnet_tpu as mx  # noqa: E402


class BucketSentenceIter(mx.io.DataIter):
    """Batches same-length sentences per bucket (reference bucket_io.py
    BucketSentenceIter): each batch carries its bucket length as
    ``bucket_key`` so BucketingModule can switch programs."""

    def __init__(self, sentences, buckets, batch_size, vocab,
                 data_name="data", label_name="softmax_label", seed=0):
        super().__init__(batch_size)
        self.buckets = sorted(buckets)
        self.data_name = data_name
        self.label_name = label_name
        self.vocab = vocab
        self._rs = onp.random.RandomState(seed)
        self._by_bucket = {b: [] for b in self.buckets}
        for s in sentences:
            for b in self.buckets:
                if len(s) <= b:
                    pad = onp.zeros(b, "float32")
                    pad[:len(s)] = s
                    self._by_bucket[b].append(pad)
                    break
        self._plan = []
        for b, rows in self._by_bucket.items():
            for i in range(0, len(rows) - batch_size + 1, batch_size):
                self._plan.append((b, i))
        self.default_bucket_key = max(self.buckets)
        self.reset()

    @property
    def provide_data(self):
        return [mx.io.DataDesc(self.data_name,
                               (self.batch_size, self.default_bucket_key))]

    @property
    def provide_label(self):
        return [mx.io.DataDesc(self.label_name,
                               (self.batch_size, self.default_bucket_key))]

    def reset(self):
        self._rs.shuffle(self._plan)
        self._i = 0

    def next(self):
        if self._i >= len(self._plan):
            raise StopIteration
        b, start = self._plan[self._i]
        self._i += 1
        rows = onp.stack(self._by_bucket[b][start:start + self.batch_size])
        # next-token LM: input is the full row; label is the row shifted
        # left with a trailing pad 0 (Perplexity ignores label 0)
        label = onp.concatenate([rows[:, 1:], onp.zeros((rows.shape[0], 1),
                                                        "float32")], axis=1)
        batch = mx.io.DataBatch([mx.nd.array(rows)], [mx.nd.array(label)])
        batch.bucket_key = b
        batch.provide_data = [mx.io.DataDesc(self.data_name,
                                             (self.batch_size, b))]
        batch.provide_label = [mx.io.DataDesc(self.label_name,
                                              (self.batch_size, b))]
        return batch


def sym_gen_factory(vocab, embed, hidden, batch_size):
    # flat fused-RNN parameter vector (reference rnn.cc packed layout):
    # 1 layer, unidirectional LSTM = 4h*(in+h) weights + 8h biases
    n_par = 4 * hidden * (embed + hidden) + 8 * hidden

    def sym_gen(seq_len):
        data = mx.sym.Variable("data")
        label = mx.sym.Variable("softmax_label")
        emb = mx.sym.Embedding(data, input_dim=vocab, output_dim=embed,
                               name="embed")
        # fused scan-LSTM (reference RNN op, TNC layout): ONE shared flat
        # parameter vector feeds every bucket's program
        par = mx.sym.Variable("lstm_parameters", shape=(n_par,),
                      init=mx.init.Uniform(0.1))
        h0 = mx.sym.Variable("lstm_init_h", shape=(1, batch_size, hidden),
                             lr_mult=0.0, init=mx.init.Zero())
        c0 = mx.sym.Variable("lstm_init_c", shape=(1, batch_size, hidden),
                             lr_mult=0.0, init=mx.init.Zero())
        tnc = mx.sym.SwapAxis(emb, dim1=0, dim2=1)
        rnn = mx.sym.RNN(tnc, par, h0, state_cell=c0, state_size=hidden, num_layers=1,
                         mode="lstm", name="lstm")
        ntc = mx.sym.SwapAxis(rnn, dim1=0, dim2=1)
        pred = mx.sym.Reshape(ntc, shape=(-1, hidden))
        fc = mx.sym.FullyConnected(pred, num_hidden=vocab, name="fc")
        sm = mx.sym.SoftmaxOutput(fc, mx.sym.Reshape(label, shape=(-1,)),
                                  name="softmax")
        return sm, ("data",), ("softmax_label",)
    return sym_gen


def synthetic_sentences(n, vocab, rs):
    """Order-2 structured sequences: next token = (a + b) % vocab."""
    outs = []
    for _ in range(n):
        ln = int(rs.choice([8, 12, 16, 24]))
        s = [int(rs.randint(1, vocab)), int(rs.randint(1, vocab))]
        while len(s) < ln:
            s.append((s[-1] + s[-2]) % (vocab - 1) + 1)
        outs.append(onp.asarray(s, "float32"))
    return outs


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=5)
    ap.add_argument("--batch-size", type=int, default=16)
    ap.add_argument("--vocab", type=int, default=32)
    ap.add_argument("--embed", type=int, default=32)
    ap.add_argument("--hidden", type=int, default=64)
    ap.add_argument("--sentences", type=int, default=400)
    ap.add_argument("--lr", type=float, default=0.5)
    args = ap.parse_args()

    rs = onp.random.RandomState(0)
    buckets = [8, 12, 16, 24]
    it = BucketSentenceIter(synthetic_sentences(args.sentences, args.vocab,
                                                rs),
                            buckets, args.batch_size, args.vocab)

    mod = mx.mod.BucketingModule(
        sym_gen_factory(args.vocab, args.embed, args.hidden,
                        args.batch_size),
        default_bucket_key=it.default_bucket_key)
    mod.bind(data_shapes=it.provide_data, label_shapes=it.provide_label)
    mod.init_params(mx.init.Xavier())
    mod.init_optimizer(optimizer="sgd",
                       optimizer_params={"learning_rate": args.lr,
                                         "momentum": 0.9,
                                         # SoftmaxOutput grads sum over batch*seq rows
                                         "rescale_grad": 1.0 / (args.batch_size * 4)})
    metric = mx.metric.Perplexity(ignore_label=0)

    final_ppl = None
    for epoch in range(args.epochs):
        it.reset()
        metric.reset()
        for batch in it:
            mod.forward(batch, is_train=True)
            mod.update_metric(metric, batch.label)
            mod.backward()
            mod.update()
        final_ppl = metric.get()[1]
        print("epoch %d ppl %.2f (buckets compiled: %d)"
              % (epoch, final_ppl, len(mod._buckets)))
    assert len(mod._buckets) == len(buckets), \
        "expected one compiled program per bucket"
    print("FINAL_PPL %.3f" % final_ppl)


if __name__ == "__main__":
    main()
