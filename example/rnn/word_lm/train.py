#!/usr/bin/env python
"""Word-level LSTM language model (reference ``example/rnn/word_lm/``).

Trains embedding → multi-layer scan-fused LSTM → (optionally weight-tied)
softmax head on a WikiText-style token file, with truncated BPTT batching.
The whole step — forward, cross-entropy over every position, backward,
clipped SGD — compiles into ONE jitted XLA program (``DataParallelStep``);
the LSTM recurrence is a ``lax.scan`` so XLA pipelines the timesteps
instead of dispatching per-step kernels (reference: the cuDNN fused RNN
path, src/operator/rnn-inl.h).

    python example/rnn/word_lm/train.py --data ./wiki.train.tokens
    python example/rnn/word_lm/train.py --synthetic --epochs 2   # smoke

bf16: --dtype bfloat16 runs the LSTM/matmul stack at MXU-native width.
"""
import argparse
import logging
import math
import os
import sys
import tempfile
import time

import numpy as onp

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", "..", ".."))
import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu import gluon  # noqa: E402
from mxnet_tpu.gluon import nn, rnn  # noqa: E402
from mxnet_tpu.gluon.contrib.data.text import LanguageModelDataset  # noqa


class RNNModel(gluon.HybridBlock):
    """Embedding → LSTM stack → vocab head (reference word_lm/model.py)."""

    def __init__(self, vocab_size, embed_size, hidden_size, num_layers,
                 dropout=0.2, tied=False, **kwargs):
        super().__init__(**kwargs)
        self._tied = tied
        with self.name_scope():
            self.drop = nn.Dropout(dropout) if dropout else None
            self.embed = nn.Embedding(vocab_size, embed_size,
                                      prefix="embed_")
            self.lstm = rnn.LSTM(hidden_size, num_layers=num_layers,
                                 layout="NTC", dropout=dropout,
                                 prefix="lstm_")
            if tied:
                if embed_size != hidden_size:
                    raise ValueError("weight tying needs "
                                     "embed_size == hidden_size")
                self.head = nn.Dense(vocab_size, flatten=False,
                                     params=self.embed.params,
                                     prefix="embed_")
            else:
                self.head = nn.Dense(vocab_size, flatten=False,
                                     prefix="head_")

    def hybrid_forward(self, F, x):
        e = self.embed(x)
        if self.drop is not None:
            e = self.drop(e)
        h = self.lstm(e)
        if self.drop is not None:
            h = self.drop(h)
        return self.head(h)


def _synthetic_corpus(path, n_tokens=30000, vocab=200, seed=0):
    """A Zipf-ish random corpus with local structure (so the model can
    actually learn and the smoke test can assert descending ppl)."""
    rs = onp.random.RandomState(seed)
    words = ["w%d" % i for i in range(vocab)]
    toks, state = [], 0
    for _ in range(n_tokens):
        state = (state * 31 + rs.randint(0, 7)) % vocab
        toks.append(words[state])
        if rs.rand() < 0.05:
            toks.append(".")
    with open(path, "w") as f:
        f.write(" ".join(toks))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--data", default=None,
                    help="token file (wiki.train.tokens style)")
    ap.add_argument("--synthetic", action="store_true")
    ap.add_argument("--batch-size", type=int, default=32)
    ap.add_argument("--bptt", type=int, default=35)
    ap.add_argument("--embed-size", type=int, default=200)
    ap.add_argument("--hidden-size", type=int, default=200)
    ap.add_argument("--num-layers", type=int, default=2)
    ap.add_argument("--dropout", type=float, default=0.2)
    ap.add_argument("--tied", action="store_true")
    ap.add_argument("--optimizer", default="sgd", choices=["sgd", "adam"])
    ap.add_argument("--lr", type=float, default=20.0,
                    help="reference word_lm default for sgd; use ~3e-3 "
                    "with adam")
    ap.add_argument("--clip", type=float, default=0.25)
    ap.add_argument("--epochs", type=int, default=3)
    ap.add_argument("--dtype", default="float32",
                    choices=["float32", "bfloat16"])
    ap.add_argument("--max-batches", type=int, default=0,
                    help="cap batches/epoch (0 = full epoch)")
    ap.add_argument("--seed", type=int, default=0)
    args = ap.parse_args()
    logging.basicConfig(level=logging.INFO)
    mx.random.seed(args.seed)

    if args.synthetic or args.data is None:
        tmp = os.path.join(tempfile.mkdtemp(prefix="wordlm"), "corpus.txt")
        _synthetic_corpus(tmp, seed=args.seed)
        args.data = tmp
        logging.info("synthetic corpus at %s", args.data)
    dataset = LanguageModelDataset(args.data, seq_len=args.bptt)
    vocab_size = len(dataset.vocabulary)
    loader = gluon.data.DataLoader(dataset, batch_size=args.batch_size,
                                   shuffle=True, last_batch="discard")
    logging.info("corpus: %d samples of bptt=%d, vocab=%d",
                 len(dataset), args.bptt, vocab_size)

    net = RNNModel(vocab_size, args.embed_size, args.hidden_size,
                   args.num_layers, dropout=args.dropout, tied=args.tied)
    net.initialize(mx.init.Xavier())
    warm = mx.nd.zeros((args.batch_size, args.bptt))
    net(warm)                         # materialize deferred shapes
    if args.dtype != "float32":
        net.cast(args.dtype)
    net.collect_params().reset_ctx(mx.tpu())

    class SeqCELoss(gluon.loss.Loss):
        def __init__(self):
            super().__init__(weight=None, batch_axis=0)
            self._ce = gluon.loss.SoftmaxCrossEntropyLoss()

        def hybrid_forward(self, F, logits, lab):
            return self._ce(logits.reshape(-1, vocab_size),
                            lab.reshape(-1))

    # the step's loss is already the mean over batch*time, so no
    # rescale_grad (the reference divides a summed loss instead)
    if args.optimizer == "adam":
        lr = args.lr if args.lr < 1.0 else 3e-3
        opt = mx.optimizer.Adam(learning_rate=lr,
                                clip_gradient=args.clip)
    else:
        opt = mx.optimizer.SGD(learning_rate=args.lr,
                               clip_gradient=args.clip)
    step = mx.parallel.DataParallelStep(net, SeqCELoss(), opt, mesh=None)

    final_ppl = None
    for epoch in range(args.epochs):
        tic = time.time()
        total, nb = 0.0, 0
        for data, label in loader:
            data = data.as_in_context(mx.tpu())
            label = label.as_in_context(mx.tpu())
            loss = step(data, label)
            total += float(loss.asnumpy())
            nb += 1
            if args.max_batches and nb >= args.max_batches:
                break
        ppl = math.exp(min(total / max(nb, 1), 20.0))
        toks = nb * args.batch_size * args.bptt
        logging.info("epoch %d: ppl %.2f (%.0f tok/s)", epoch, ppl,
                     toks / (time.time() - tic))
        final_ppl = ppl
    print("FINAL_PPL %.3f" % final_ppl)


if __name__ == "__main__":
    main()
