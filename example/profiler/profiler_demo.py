#!/usr/bin/env python
"""Profiling a training step (reference ``example/profiler/profiler_ndarray.py``
family).

The reference's profiler records per-op engine events into a chrome
trace; here ``mx.profiler`` wraps ``jax.profiler`` and captures an XLA
xplane trace (viewable in Perfetto / TensorBoard) of whatever the chip
actually ran — fused kernels, DMA, host callbacks.  The flow is the
reference's verbatim: ``set_config → set_state('run') → work →
set_state('stop') → dump()``.

    python example/profiler/profiler_demo.py --trace-dir /tmp/mxtpu_trace
"""
import argparse
import glob
import logging
import os
import sys
import tempfile

import numpy as onp

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))
import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu import autograd, gluon  # noqa: E402
from mxnet_tpu.gluon import nn  # noqa: E402


def build_net():
    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Dense(256, activation="relu"),
                nn.Dense(256, activation="relu"),
                nn.Dense(10))
    return net


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--trace-dir", default=None)
    ap.add_argument("--batch-size", type=int, default=128)
    ap.add_argument("--steps", type=int, default=20)
    ap.add_argument("--seed", type=int, default=0)
    args = ap.parse_args()
    logging.basicConfig(level=logging.INFO)
    trace_dir = args.trace_dir or tempfile.mkdtemp(prefix="mxtpu_trace_")
    rs = onp.random.RandomState(args.seed)
    mx.random.seed(args.seed)

    net = build_net()
    net.initialize(mx.init.Xavier())
    net.hybridize()
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.1})

    X = mx.nd.array(rs.rand(args.batch_size, 784).astype("float32"))
    Y = mx.nd.array(rs.randint(0, 10, args.batch_size).astype("float32"))

    def step():
        with autograd.record():
            loss = loss_fn(net(X), Y)
        loss.backward()
        trainer.step(args.batch_size)
        return loss

    step()  # warm up: compile outside the capture window
    mx.nd.waitall()

    mx.profiler.set_config(profile_all=True, profile_dir=trace_dir)
    mx.profiler.set_state("run")
    for _ in range(args.steps):
        loss = step()
    mx.nd.waitall()
    mx.profiler.set_state("stop")
    out = mx.profiler.dump()

    artifacts = glob.glob(os.path.join(out, "**", "*.xplane.pb"),
                          recursive=True) + \
        glob.glob(os.path.join(out, "**", "*.json.gz"), recursive=True)
    logging.info("final loss %.4f", float(loss.mean().asscalar()))
    logging.info("trace written to %s (%d artifact files)", out,
                 len(artifacts))
    assert artifacts, "profiler produced no trace artifacts in %s" % out


if __name__ == "__main__":
    main()
