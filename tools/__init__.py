"""Developer tooling for mxnet-tpu.

A real package (not a loose script directory) so the static analyzer is
invocable as ``python -m tools.lint``; the standalone scripts
(``im2rec.py``, ``parse_log.py``, …) still run directly.
"""
