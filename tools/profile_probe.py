#!/usr/bin/env python
"""Per-op device-time profile of a bench step (scratch tool for the
roofline notes; findings land in bench.py / kernel defaults).

Captures a jax.profiler trace around k executions of a bench step and
aggregates the device-lane op durations from the perfetto trace.json.gz,
printing the top-N ops by total device time.

    python tools/profile_probe.py --what bert
    python tools/profile_probe.py --what train --top 30
"""
import argparse
import glob
import gzip
import json
import os
import sys
import tempfile
from collections import defaultdict

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def collect(trace_dir):
    paths = glob.glob(os.path.join(trace_dir, "**", "*.trace.json.gz"),
                      recursive=True)
    if not paths:
        raise RuntimeError("no trace.json.gz under %s" % trace_dir)
    with gzip.open(sorted(paths)[-1], "rt") as f:
        trace = json.load(f)
    events = trace.get("traceEvents", [])
    # device lanes: pid whose process_name mentions TPU/device; fall back
    # to lanes that carry XLA op events (they have 'run_id'/'long_name'
    # args or hlo-ish names)
    names = {}
    for e in events:
        if e.get("ph") == "M" and e.get("name") == "process_name":
            names[e["pid"]] = e["args"].get("name", "")
    device_pids = {p for p, n in names.items()
                   if "TPU" in n or "/device" in n.lower()}
    agg = defaultdict(float)
    cnt = defaultdict(int)
    fam = defaultdict(float)
    for e in events:
        if e.get("ph") != "X":
            continue
        if device_pids and e.get("pid") not in device_pids:
            continue
        dur = e.get("dur", 0)
        n = e["name"]
        # drop module/program spans (parents that double-count their
        # children): jit_* wrappers and bare numeric step markers
        if not dur or n.startswith("jit_") or n.isdigit():
            continue
        agg[n] += dur
        cnt[n] += 1
        fam[n.split(".")[0]] += dur
    return agg, cnt, fam, names


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--what", default="bert",
                    choices=["bert", "train", "attention", "lstm"])
    ap.add_argument("--steps", type=int, default=3)
    ap.add_argument("--top", type=int, default=20)
    ap.add_argument("--batch-size", type=int, default=0)
    args = ap.parse_args()

    import jax
    import bench  # repo-root bench.py via the path insert above

    d = tempfile.mkdtemp(prefix="profprobe_")

    if args.what == "bert":
        # lean: ONE step function, compiled once, traced per-call (the
        # full bench_bert would recompile its whole matrix twice)
        import numpy as onp
        import mxnet_tpu as mx
        from mxnet_tpu import gluon
        from mxnet_tpu.gluon.model_zoo import bert_base
        from mxnet_tpu.parallel import DataParallelStep
        vocab = 30522
        batch_size, seq_len = 24, 512
        net = bert_base(vocab_size=vocab, max_length=seq_len, dropout=0.0,
                        use_pooler=False, use_decoder=True)
        net.initialize(mx.init.Xavier())
        rs = onp.random.RandomState(0)
        host_tokens = mx.nd.array(rs.randint(0, vocab, (batch_size,
                                                        seq_len))
                                  .astype("float32"))
        lens = rs.randint(seq_len // 3, seq_len + 1, (batch_size,))
        lens[: max(1, batch_size // 4)] = seq_len
        host_vl = mx.nd.array(lens.astype("int32"), dtype="int32")
        n_pred = max(1, int(seq_len * 0.15))
        host_pos = mx.nd.array(
            onp.sort(onp.stack([rs.choice(int(lens.min()), n_pred,
                                          replace=False)
                                for _ in range(batch_size)]), 1)
            .astype("int32"), dtype="int32")
        net(host_tokens, None, None, host_vl, host_pos)
        net.cast("bfloat16")
        net.collect_params().reset_ctx(mx.tpu())
        tokens = mx.nd.array(host_tokens.asnumpy(), ctx=mx.tpu())
        labels = mx.nd.array(rs.randint(0, vocab, (batch_size, n_pred))
                             .astype("float32"), ctx=mx.tpu())
        vl = mx.nd.array(host_vl.asnumpy(), ctx=mx.tpu(), dtype="int32")
        pos = mx.nd.array(host_pos.asnumpy(), ctx=mx.tpu(), dtype="int32")

        class MLMLoss(gluon.loss.Loss):
            def __init__(self):
                super().__init__(weight=None, batch_axis=0)
                self._ce = gluon.loss.SoftmaxCrossEntropyLoss()

            def hybrid_forward(self, F, outputs, lab):
                _, logits = outputs
                return self._ce(logits.reshape(-1, vocab),
                                lab.reshape(-1))

        step = DataParallelStep(net, MLMLoss(),
                                mx.optimizer.Adam(learning_rate=1e-4),
                                mesh=None)
        run = lambda: step((tokens, None, None, vl, pos), labels)
        runner = lambda: [bench._sync(run()) for _ in range(args.steps)]
    elif args.what == "train":
        bs = args.batch_size or 128
        step, data, label = bench._build_train_step(
            "resnet50_v1", bs, "bfloat16")
        runner = lambda: [bench._sync(step(data, label))
                          for _ in range(args.steps)]
    elif args.what == "lstm":
        runner = lambda: bench.bench_lstm(iters=args.steps)
    else:
        runner = lambda: bench.bench_attention(iters=args.steps)

    for _ in range(2):
        runner()  # warm: compile + settle donation layouts pre-capture
    jax.profiler.start_trace(d)
    out = runner()
    jax.profiler.stop_trace()
    print("# steps traced:", args.steps, flush=True)

    agg, cnt, fam, names = collect(d)
    total = sum(agg.values())
    print("# device lanes: %s" % sorted(set(names.values()))[:8])
    print("# total device-op us (HLO level): %.0f" % total)
    print("# --- op families ---")
    for name, us in sorted(fam.items(), key=lambda kv: -kv[1])[:args.top]:
        print(json.dumps({"family": name[:80], "us": round(us, 0),
                          "pct": round(100 * us / total, 1)}))
    print("# --- top individual ops ---")
    for name, us in sorted(agg.items(), key=lambda kv: -kv[1])[:args.top]:
        print(json.dumps({"op": name[:110], "us": round(us, 0),
                          "pct": round(100 * us / total, 1),
                          "n": cnt[name]}))


if __name__ == "__main__":
    main()
