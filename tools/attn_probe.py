#!/usr/bin/env python
"""Flash-attention block-size sweep on the real chip (scratch tool for
kernel tuning; winners land in ops/pallas_attention.py defaults).

Times FULL fwd+bwd (grads w.r.t. q,k,v) with ``inner`` chained
iterations inside one jit, the same protocol as bench.py's
bench_attention, across (block_q, block_k) combos.  Optionally times
jax's own shipped TPU flash kernel as an expert-tuned upper bound.

    python tools/attn_probe.py --seqlen 2048
    python tools/attn_probe.py --seqlen 512 --blocks 512:512,256:512
    python tools/attn_probe.py --jax-reference
"""
import argparse
import functools
import json
import os
import sys
import time

import numpy as onp

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def time_loop(loop, q, k, v, sync, iters=3):
    sync(loop(q, k, v))
    t0 = time.perf_counter()
    for _ in range(iters):
        out = loop(q, k, v)
    sync(out)
    return (time.perf_counter() - t0) / iters


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--heads", type=int, default=16)
    ap.add_argument("--seqlen", type=int, default=2048)
    ap.add_argument("--head-dim", type=int, default=64)
    ap.add_argument("--inner", type=int, default=10)
    ap.add_argument("--iters", type=int, default=3)
    ap.add_argument("--causal", action="store_true")
    ap.add_argument("--blocks", default="1024:2048,512:2048,2048:1024,"
                    "512:1024,1024:1024,256:2048,2048:512")
    ap.add_argument("--jax-reference", action="store_true",
                    help="also time jax.experimental.pallas.ops.tpu "
                    "flash_attention")
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp

    rs = onp.random.RandomState(0)
    shape = (args.batch, args.heads, args.seqlen, args.head_dim)
    q, k, v = (jnp.asarray(rs.uniform(-1, 1, shape).astype("float32"),
                           jnp.bfloat16) for _ in range(3))
    S, D = args.seqlen, args.head_dim
    # fwd 4*S^2*D per head, bwd ~2.5x (flash recompute), per bench.py
    flops = args.inner * 3.5 * 4 * S * S * D * args.batch * args.heads
    if args.causal:
        flops /= 2

    def sync(t):
        onp.asarray(jax.tree_util.tree_leaves(t)[0].ravel()[:1])

    def mk_loop(fn):
        grad = jax.grad(lambda q, k, v:
                        jnp.sum(fn(q, k, v).astype(jnp.float32)),
                        argnums=(0, 1, 2))

        @jax.jit
        def loop(q, k, v):
            def body(_, qkv):
                q, k, v = qkv
                dq, dk, dv = grad(q, k, v)
                return (q + 0 * dq, k + 0 * dk, v + 0 * dv)
            return jax.lax.fori_loop(0, args.inner, body, (q, k, v))
        return loop

    for spec in args.blocks.split(","):
        bq, bk = (int(x) for x in spec.split(":"))
        from mxnet_tpu.ops import pallas_attention as pa

        def fn(q, k, v, bq=bq, bk=bk):
            out, _ = pa.pallas_flash_attention(
                q, k, v, causal=args.causal, return_lse=True, block_q=bq,
                block_k=bk)
            return out

        def full(q, k, v, bq=bq, bk=bk):
            # custom fwd+bwd with explicit blocks (bypasses the default-
            # block custom_vjp wrapper)
            out, lse = pa.pallas_flash_attention(
                q, k, v, causal=args.causal, return_lse=True,
                block_q=bq, block_k=bk)
            return out, lse

        @functools.partial(jax.custom_vjp)
        def att(q, k, v):
            return full(q, k, v)[0]

        def att_fwd(q, k, v):
            out, lse = full(q, k, v)
            return out, (q, k, v, out, lse)

        def att_bwd(res, g):
            q, k, v, out, lse = res
            return pa.pallas_flash_attention_bwd(
                q, k, v, out, lse, g, causal=args.causal,
                block_q=bq, block_k=bk)

        att.defvjp(att_fwd, att_bwd)
        try:
            s = time_loop(mk_loop(att), q, k, v, sync, iters=args.iters)
            print(json.dumps({"block_q": bq, "block_k": bk,
                              "ms": round(s * 1000 / args.inner, 3),
                              "tflops": round(flops / s / 1e12 / 1, 1)}),
                  flush=True)
        except Exception as e:
            print(json.dumps({"block_q": bq, "block_k": bk,
                              "error": repr(e)[:200]}), flush=True)

    if args.jax_reference:
        try:
            from jax.experimental.pallas.ops.tpu.flash_attention import (
                flash_attention as jf)

            def jfn(q, k, v):
                return jf(q, k, v, causal=args.causal, sm_scale=D ** -0.5)
            s = time_loop(mk_loop(jfn), q, k, v, sync, iters=args.iters)
            print(json.dumps({"impl": "jax_reference",
                              "ms": round(s * 1000 / args.inner, 3),
                              "tflops": round(flops / s / 1e12, 1)}),
                  flush=True)
        except Exception as e:
            print(json.dumps({"impl": "jax_reference",
                              "error": repr(e)[:300]}), flush=True)


if __name__ == "__main__":
    main()
