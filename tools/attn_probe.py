#!/usr/bin/env python
"""Flash-attention block-size sweep on the real chip — a thin wrapper
over the autotune search driver (``mxnet_tpu.tune.search``), which owns
the ONE timing harness (jitted chained fwd+bwd loop, min-of-K calls
bounded by block_until_ready).  Winners belong in the persistent cost
table (``python -m mxnet_tpu.tune``), not in code edits; this probe
remains for quick manual sweeps and for timing jax's own shipped TPU
flash kernel as an expert-tuned upper bound.

    python tools/attn_probe.py --seqlen 2048
    python tools/attn_probe.py --seqlen 512 --blocks 512:512,256:512
    python tools/attn_probe.py --jax-reference
"""
import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--heads", type=int, default=16)
    ap.add_argument("--seqlen", type=int, default=2048)
    ap.add_argument("--head-dim", type=int, default=64)
    ap.add_argument("--inner", type=int, default=10)
    ap.add_argument("--iters", type=int, default=3,
                    help="timed calls per config (min-of-K)")
    ap.add_argument("--causal", action="store_true")
    ap.add_argument("--blocks", default="1024:2048,512:2048,2048:1024,"
                    "512:1024,1024:1024,256:2048,2048:512")
    ap.add_argument("--jax-reference", action="store_true",
                    help="also time jax.experimental.pallas.ops.tpu "
                    "flash_attention")
    args = ap.parse_args()

    from mxnet_tpu.tune import search

    S, D = args.seqlen, args.head_dim
    # fwd 4*S^2*D per head, bwd ~2.5x (flash recompute), per bench.py
    flops = 3.5 * 4 * S * S * D * args.batch * args.heads
    if args.causal:
        flops /= 2

    for spec in args.blocks.split(","):
        bq, bk = (int(x) for x in spec.split(":"))
        try:
            s = search.measure_attention_config(
                args.batch, args.heads, S, S, D, "bfloat16",
                {"block_q": bq, "block_k": bk}, causal=args.causal,
                inner=args.inner, calls=args.iters)
            print(json.dumps({"block_q": bq, "block_k": bk,
                              "ms": round(s * 1000, 3),
                              "tflops": round(flops / s / 1e12, 1)}),
                  flush=True)
        except Exception as e:
            print(json.dumps({"block_q": bq, "block_k": bk,
                              "error": repr(e)[:200]}), flush=True)

    if args.jax_reference:
        try:
            from jax.experimental.pallas.ops.tpu.flash_attention import (
                flash_attention as jf)

            def jfn(q, k, v):
                return jf(q, k, v, causal=args.causal, sm_scale=D ** -0.5)
            loop = search.fwd_bwd_loop(jfn, args.inner)
            q, k, v = search._rand_operands(
                ((args.batch, args.heads, S, D),) * 3, "bfloat16")
            s = search.min_time(lambda: loop(q, k, v),
                                calls=args.iters) / args.inner
            print(json.dumps({"impl": "jax_reference",
                              "ms": round(s * 1000, 3),
                              "tflops": round(flops / s / 1e12, 1)}),
                  flush=True)
        except Exception as e:
            print(json.dumps({"impl": "jax_reference",
                              "error": repr(e)[:300]}), flush=True)


if __name__ == "__main__":
    main()
