#!/usr/bin/env python
"""Pack an image directory into RecordIO (reference ``tools/im2rec.py``).

Two phases, same CLI shape as the reference:
  --list: walk an image root, write a ``.lst`` file
          (index \\t label \\t relpath per line, label = folder index).
  (default): read a ``.lst`` file, encode each image and append it to
          ``prefix.rec`` + ``prefix.idx`` via MXIndexedRecordIO.
"""
import argparse
import os
import random
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from mxnet_tpu import fsutil, recordio  # noqa: E402

EXTS = (".jpg", ".jpeg", ".png", ".bmp")


def make_list(args):
    image_list = []
    label = 0
    labels = {}
    for root, dirs, files in os.walk(args.root, followlinks=True):
        dirs.sort()
        files.sort()
        for f in files:
            if os.path.splitext(f)[1].lower() in EXTS:
                folder = os.path.relpath(root, args.root)
                if folder not in labels:
                    labels[folder] = label
                    label += 1
                image_list.append(
                    (os.path.relpath(os.path.join(root, f), args.root),
                     labels[folder]))
    if args.shuffle:
        random.seed(100)
        random.shuffle(image_list)
    fname = args.prefix + ".lst"
    with fsutil.atomic_write_path(fname) as tmp_lst:
        with open(tmp_lst, "w") as f:
            for i, (path, lab) in enumerate(image_list):
                f.write("%d\t%f\t%s\n" % (i, lab, path))
    print("wrote %s (%d images, %d classes)" % (fname, len(image_list),
                                                label))


def read_list(path):
    with open(path) as f:
        for line in f:
            parts = line.strip().split("\t")
            if len(parts) < 3:
                continue
            yield int(parts[0]), [float(v) for v in parts[1:-1]], parts[-1]


def pack_records(args):
    import cv2
    rec = recordio.MXIndexedRecordIO(args.prefix + ".idx",
                                     args.prefix + ".rec", "w")
    count = 0
    for idx, labels, rel in read_list(args.prefix + ".lst"):
        path = os.path.join(args.root, rel)
        img = cv2.imread(path, args.color)
        if img is None:
            print("skip unreadable %s" % path)
            continue
        if args.resize:
            h, w = img.shape[:2]
            scale = args.resize / min(h, w)
            img = cv2.resize(img, (int(w * scale + 0.5),
                                   int(h * scale + 0.5)))
        if args.center_crop:
            h, w = img.shape[:2]
            s = min(h, w)
            y0, x0 = (h - s) // 2, (w - s) // 2
            img = img[y0:y0 + s, x0:x0 + s]
        label = labels[0] if len(labels) == 1 else labels
        header = recordio.IRHeader(0, label, idx, 0)
        packed = recordio.pack_img(header, img, quality=args.quality,
                                   img_fmt=args.encoding)
        rec.write_idx(idx, packed)
        count += 1
    rec.close()
    print("packed %d records into %s.rec" % (count, args.prefix))


def main():
    parser = argparse.ArgumentParser(
        description="Create an image list / RecordIO pack (reference "
                    "tools/im2rec.py)")
    parser.add_argument("prefix", help="prefix of .lst/.rec/.idx")
    parser.add_argument("root", help="image root dir")
    parser.add_argument("--list", action="store_true",
                        help="generate the .lst instead of packing")
    parser.add_argument("--shuffle", type=int, default=1)
    parser.add_argument("--resize", type=int, default=0)
    parser.add_argument("--center-crop", action="store_true")
    parser.add_argument("--quality", type=int, default=95)
    parser.add_argument("--encoding", default=".jpg",
                        choices=[".jpg", ".png"])
    parser.add_argument("--color", type=int, default=1,
                        choices=[-1, 0, 1])
    args = parser.parse_args()
    if args.list:
        make_list(args)
    else:
        pack_records(args)


if __name__ == "__main__":
    main()
