#!/usr/bin/env python
"""Run a test many times to estimate flakiness (reference
``tools/flakiness_checker.py``): same CLI shape —
``python tools/flakiness_checker.py test_module.test_name [-n trials]``.

Each trial runs under a fresh random seed (MXNET_TEST_SEED, honored by
the suite's seeded fixtures) in a fresh interpreter, so state cannot
leak between trials.  Exits nonzero if any trial fails.
"""
import argparse
import os
import random
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def spec_to_pytest(spec):
    """'test_module.test_name' or 'path/to/test.py::name' -> pytest id."""
    if "::" in spec or spec.endswith(".py"):
        return spec
    if "." in spec:
        mod, name = spec.rsplit(".", 1)
        return os.path.join("tests", mod.replace(".", os.sep) + ".py") \
            + "::" + name
    return os.path.join("tests", spec + ".py")   # bare module name


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("test", help="test spec: test_module.test_name or a "
                                 "pytest id (file.py::name)")
    ap.add_argument("-n", "--num-trials", type=int, default=10)
    ap.add_argument("-s", "--seed", type=int, default=None,
                    help="fixed base seed (default: random per trial)")
    ap.add_argument("-v", "--verbose", action="store_true")
    args = ap.parse_args(argv)

    target = spec_to_pytest(args.test)
    failures = 0
    for trial in range(args.num_trials):
        seed = args.seed if args.seed is not None \
            else random.randint(0, 2 ** 31 - 1)
        env = dict(os.environ, MXNET_TEST_SEED=str(seed),
                   PYTHONPATH=REPO + os.pathsep
                   + os.environ.get("PYTHONPATH", ""))
        res = subprocess.run(
            [sys.executable, "-m", "pytest", target, "-x", "-q"],
            cwd=REPO, env=env, capture_output=not args.verbose)
        ok = res.returncode == 0
        failures += 0 if ok else 1
        print("trial %d/%d seed=%d: %s"
              % (trial + 1, args.num_trials, seed,
                 "PASS" if ok else "FAIL"), flush=True)
        if not ok and not args.verbose and res.stdout:
            sys.stdout.write(res.stdout.decode()[-1500:])
    print("flakiness: %d/%d trials failed" % (failures, args.num_trials))
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
