#!/usr/bin/env python
"""Measure per-test flake rates over repeated runs (reference
``tools/flakiness_checker.py``, rebuilt around the tier-1 gate).

Runs the tier-1 selection (``tests/ -m 'not slow'``) — or a single test
spec — N times, each trial in a fresh interpreter under a fresh random
seed (``MXNET_TEST_SEED``, honored by the suite's seeded fixtures), and
aggregates per-test outcomes from the per-trial junit XML into a JSON
report::

    python tools/flakiness_checker.py -n 5 --json flakes.json
    python tools/flakiness_checker.py test_module.test_name -n 20

Report shape::

    {"trials": N, "marker": "not slow", "seeds": [...],
     "tests": {nodeid: {"runs": n, "failures": k, "errors": e,
                        "skips": s, "flake_rate": k/n}},
     "flaky": [nodeid...],        # 0 < failures < runs
     "always_fail": [nodeid...],  # failures == runs
     "summary": {"tests": T, "flaky": F, "always_fail": A}}

Exit status: 0 = stable, 1 = flaky tests found, 2 = every trial was
unrunnable.  ``always_fail`` tests are reported but do NOT flip the
exit code — a deterministic failure is the tier-1 gate's job; this tool
measures *stability* (the "no worse than seed" claim needs flake rates,
not pass/fail).
"""
import argparse
import json
import os
import random
import subprocess
import sys
import tempfile
import xml.etree.ElementTree as ET

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def spec_to_pytest(spec):
    """'test_module.test_name' or 'path/to/test.py::name' -> pytest id."""
    if "::" in spec or spec.endswith(".py") or os.path.sep in spec:
        return spec
    if "." in spec:
        mod, name = spec.rsplit(".", 1)
        return os.path.join("tests", mod.replace(".", os.sep) + ".py") \
            + "::" + name
    return os.path.join("tests", spec + ".py")   # bare module name


def parse_junit(path):
    """junit XML -> {nodeid: "pass"|"fail"|"error"|"skip"}.

    pytest's junit classname is the dotted module path plus (for
    class-based tests) the test class: ``tests.test_mod.TestFoo``.  The
    class segment must become a ``::`` component, not part of the file
    path, so the reported nodeid can be fed straight back to pytest."""
    out = {}
    root = ET.parse(path).getroot()
    for case in root.iter("testcase"):
        cls = case.get("classname", "")
        name = case.get("name", "")
        if cls:
            parts = cls.split(".")
            if parts[-1][:1].isupper():         # PEP8 test class
                modpath, klass = parts[:-1], parts[-1]
            else:
                modpath, klass = parts, None
            nodeid = "/".join(modpath) + ".py" \
                + ("::" + klass if klass else "") + "::" + name
        else:
            nodeid = name
        status = "pass"
        for child in case:
            if child.tag == "failure":
                status = "fail"
            elif child.tag == "error":
                status = "error"
            elif child.tag == "skipped":
                status = "skip"
        out[nodeid] = status
    return out


def run_trial(target, seed, marker, verbose, extra_env=None):
    """One fresh-interpreter pytest run; returns (rc, {nodeid: status})."""
    env = dict(os.environ, MXNET_TEST_SEED=str(seed),
               PYTHONPATH=REPO + os.pathsep
               + os.environ.get("PYTHONPATH", ""))
    env.update(extra_env or {})
    with tempfile.NamedTemporaryFile(suffix=".xml", delete=False) as f:
        xml_path = f.name
    try:
        cmd = [sys.executable, "-m", "pytest", target, "-q",
               "--continue-on-collection-errors",
               "-p", "no:cacheprovider", "-p", "no:randomly",
               "--junitxml=" + xml_path]
        if marker:
            cmd += ["-m", marker]
        res = subprocess.run(cmd, cwd=REPO, env=env,
                             capture_output=not verbose)
        try:
            return res.returncode, parse_junit(xml_path)
        except ET.ParseError:
            return res.returncode, {}
    finally:
        try:
            os.unlink(xml_path)
        except OSError:
            pass


def aggregate(trial_results):
    tests = {}
    for statuses in trial_results:
        for nodeid, status in statuses.items():
            t = tests.setdefault(nodeid, {"runs": 0, "failures": 0,
                                          "errors": 0, "skips": 0})
            t["runs"] += 1
            if status == "fail":
                t["failures"] += 1
            elif status == "error":
                t["errors"] += 1
            elif status == "skip":
                t["skips"] += 1
    for t in tests.values():
        bad = t["failures"] + t["errors"]
        t["flake_rate"] = round(bad / t["runs"], 4) if t["runs"] else 0.0
    flaky = sorted(n for n, t in tests.items()
                   if 0 < t["failures"] + t["errors"] < t["runs"])
    always = sorted(n for n, t in tests.items()
                    if t["runs"] and t["failures"] + t["errors"]
                    == t["runs"])
    return tests, flaky, always


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="per-test flake rates over N fresh-seed runs of the "
                    "tier-1 selection (or one test spec)")
    ap.add_argument("test", nargs="?", default=None,
                    help="test spec (test_module.test_name / pytest id); "
                         "default: the whole tier-1 selection (tests/)")
    ap.add_argument("-n", "--num-trials", type=int, default=5)
    ap.add_argument("-s", "--seed", type=int, default=None,
                    help="fixed base seed (trial i uses seed+i); "
                         "default: random per trial")
    ap.add_argument("-m", "--marker", default=None,
                    help="pytest -m expression (default: 'not slow' in "
                         "suite mode, none for a single spec)")
    ap.add_argument("--json", dest="json_out", default=None,
                    help="write the JSON report here (default: stdout "
                         "alongside the progress lines)")
    ap.add_argument("-v", "--verbose", action="store_true")
    args = ap.parse_args(argv)

    suite_mode = args.test is None
    target = "tests" if suite_mode else spec_to_pytest(args.test)
    marker = args.marker if args.marker is not None \
        else ("not slow" if suite_mode else None)

    seeds = []
    trial_results = []
    unrunnable = 0
    for trial in range(args.num_trials):
        seed = (args.seed + trial) if args.seed is not None \
            else random.randint(0, 2 ** 31 - 1)
        seeds.append(seed)
        rc, statuses = run_trial(target, seed, marker, args.verbose)
        if not statuses:
            unrunnable += 1
        trial_results.append(statuses)
        bad = sum(1 for s in statuses.values() if s in ("fail", "error"))
        print("trial %d/%d seed=%d: %d tests, %d failing (pytest rc=%d)"
              % (trial + 1, args.num_trials, seed, len(statuses), bad,
                 rc), flush=True)

    tests, flaky, always = aggregate(trial_results)
    report = {
        "trials": args.num_trials,
        "target": target,
        "marker": marker,
        "seeds": seeds,
        "tests": tests,
        "flaky": flaky,
        "always_fail": always,
        "summary": {"tests": len(tests), "flaky": len(flaky),
                    "always_fail": len(always)},
    }
    text = json.dumps(report, indent=1, sort_keys=True)
    if args.json_out:
        # atomic report: CI consumers may read while a retry rewrites
        tmp_report = args.json_out + ".tmp.%d" % os.getpid()
        with open(tmp_report, "w") as f:
            f.write(text + "\n")
        os.replace(tmp_report, args.json_out)
        print("wrote %s" % args.json_out)
    else:
        print(text)
    for n in flaky:
        print("FLAKY %s: %d/%d failed" % (
            n, tests[n]["failures"] + tests[n]["errors"],
            tests[n]["runs"]))
    if unrunnable == args.num_trials:
        print("error: no trial produced test results", file=sys.stderr)
        return 2
    failed_trials = sum(
        1 for statuses in trial_results
        if any(s in ("fail", "error") for s in statuses.values()))
    print("%d/%d trials failed; %d flaky test(s)"
          % (failed_trials, args.num_trials, len(flaky)), flush=True)
    return 1 if flaky else 0


if __name__ == "__main__":
    sys.exit(main())
