#!/usr/bin/env python
"""One-off perf probe: ResNet-50 train step across mirror modes / batch
sizes on the real chip.  Not part of the bench contract — a scratch tool
for the roofline investigation (results land in bench.py defaults)."""
import json
import sys
import time

sys.path.insert(0, ".")
import bench


def main():
    import argparse
    ap = argparse.ArgumentParser()
    ap.add_argument("--configs", default="128:bfloat16:none,128:bfloat16:mirror,"
                    "128:bfloat16:full,256:bfloat16:mirror,64:bfloat16:mirror")
    ap.add_argument("--iters", type=int, default=12)
    args = ap.parse_args()
    for cfg in args.configs.split(","):
        bs, dt, mode = cfg.split(":")
        mode = None if mode == "none" else mode
        t0 = time.time()
        try:
            step, data, label = bench._build_train_step(
                "resnet50_v1", int(bs), dt, mirror=mode)
            step_s, loss, _ = bench._time_calls(lambda: step(data, label),
                                                bench._sync,
                                                iters=args.iters)
            out = {"bs": int(bs), "dtype": dt, "mirror": mode,
                   "step_ms": round(step_s * 1000, 2),
                   "img_s": round(int(bs) / step_s, 1),
                   "loss": round(bench._sync(loss), 3),
                   "build_s": round(time.time() - t0, 1)}
        except Exception as e:
            out = {"bs": int(bs), "dtype": dt, "mirror": mode,
                   "error": repr(e)[:300]}
        print(json.dumps(out), flush=True)


if __name__ == "__main__":
    main()
