#!/usr/bin/env python
"""Cost-analysis probe: XLA bytes/flops of the ResNet-50 train step with
and without backward-mirror remat (scratch tool for the roofline note).
Thin wrapper: the lower->compile->cost_analysis plumbing lives in
``mxnet_tpu.tune.search.compiled_cost`` (via ``bench._step_cost_analysis``)
— the same driver the autotuner uses, so there is ONE measurement/cost
harness."""
import json
import sys

sys.path.insert(0, ".")
import bench


def analyze(bs, dtype, mode):
    step, data, label = bench._build_train_step("resnet50_v1", bs, dtype,
                                                mirror=mode)
    out = {"bs": bs, "dtype": dtype, "mirror": mode}
    out.update(bench._step_cost_analysis(step, data, label))
    return out


def main():
    for bs, dt, mode in ((128, "bfloat16", None), (128, "bfloat16", "mirror"),
                         (256, "bfloat16", "mirror")):
        try:
            print(json.dumps(analyze(bs, dt, mode)), flush=True)
        except Exception as e:
            print(json.dumps({"bs": bs, "mirror": mode,
                              "error": repr(e)[:300]}), flush=True)


if __name__ == "__main__":
    main()
