#!/usr/bin/env python
"""Cost-analysis probe: XLA bytes/flops of the ResNet-50 train step with
and without backward-mirror remat (scratch tool for the roofline note)."""
import json
import sys

sys.path.insert(0, ".")
import bench


def analyze(bs, dtype, mode):
    import jax
    import mxnet_tpu as mx
    step, data, label = bench._build_train_step("resnet50_v1", bs, dtype,
                                                mirror=mode)
    # reach the inner jitted fn the way __call__ does, then lower it
    import jax.numpy as jnp
    from mxnet_tpu import random as _random
    dval, lval = data._data, label._data
    jfn = step._build()          # the jax.jit-wrapped step
    lrs = jnp.zeros((len(step._trainable),), jnp.float32)
    pvals = [p._data._data for p in step._params]
    lowered = jfn.lower(pvals, step._opt_states, jnp.asarray(1, jnp.int32),
                        lrs, _random.next_key(), dval, lval)
    cost = lowered.compile().cost_analysis()
    if isinstance(cost, list):
        cost = cost[0]
    out = {"bs": bs, "dtype": dtype, "mirror": mode,
           "gbytes": round(cost.get("bytes accessed", 0.0) / 1e9, 2),
           "tflops": round(cost.get("flops", 0.0) / 1e12, 3)}
    for k, v in sorted(cost.items()):
        if k.startswith("bytes accessed") and "operand" not in k:
            out.setdefault("detail", {})[k] = round(v / 1e9, 2)
    return out


def main():
    for bs, dt, mode in ((128, "bfloat16", None), (128, "bfloat16", "mirror"),
                         (256, "bfloat16", "mirror")):
        try:
            print(json.dumps(analyze(bs, dt, mode)), flush=True)
        except Exception as e:
            print(json.dumps({"bs": bs, "mirror": mode,
                              "error": repr(e)[:300]}), flush=True)


if __name__ == "__main__":
    main()
