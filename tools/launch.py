#!/usr/bin/env python
"""Distributed job launcher (reference ``tools/launch.py:57-116``).

The reference forks a ps-lite scheduler + servers + workers with
``DMLC_ROLE`` env vars; on TPU there is no parameter server — SPMD workers
coordinate through the jax coordination service — so the launcher only has
to (1) pick a coordinator address, (2) spawn N copies of the command with
per-process rank env, (3) propagate failures.  The training script should
call ``mx.parallel.initialize()`` before its first jax computation;
``kvstore.create('dist_sync')`` also attempts it from the same env as a
best-effort fallback (too late if jax backends already initialized).

Launchers:
  local — N processes on this host (the reference's ``--launcher local``
          test fixture, SURVEY.md §4 "distributed tests without a real
          cluster").
  ssh   — one process per host from --hostfile.

Env contract (set for each spawned process):
  MXNET_TPU_COORDINATOR_ADDRESS  host:port of process 0
  MXNET_TPU_NUM_PROCESSES        N
  MXNET_TPU_PROCESS_ID           rank
(DMLC_NUM_WORKER / DMLC_WORKER_ID are also set for reference scripts.)
"""
import argparse
import os
import socket
import subprocess
import sys

DEFAULT_PORT = 9462


def _free_port():
    s = socket.socket()
    s.bind(("", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _worker_env(base, coordinator, n, rank):
    env = dict(base)
    env.update({
        "MXNET_TPU_COORDINATOR_ADDRESS": coordinator,
        "MXNET_TPU_NUM_PROCESSES": str(n),
        "MXNET_TPU_PROCESS_ID": str(rank),
        "DMLC_NUM_WORKER": str(n),
        "DMLC_WORKER_ID": str(rank),
        "DMLC_ROLE": "worker",
    })
    return env


def launch_local(n, command, env=None):
    """Spawn n local workers; returns the list of exit codes."""
    coordinator = "127.0.0.1:%d" % _free_port()
    procs = []
    for rank in range(n):
        procs.append(subprocess.Popen(
            command, shell=isinstance(command, str),
            env=_worker_env(env or os.environ, coordinator, n, rank)))
    codes = [p.wait() for p in procs]
    return codes


def launch_ssh(hosts, command, env_keys=("PYTHONPATH",), port=DEFAULT_PORT):
    import shlex
    coordinator = "%s:%d" % (hosts[0], port)
    procs = []
    for rank, host in enumerate(hosts):
        env = _worker_env({}, coordinator, len(hosts), rank)
        for k in env_keys:
            if k in os.environ:
                env[k] = os.environ[k]
        exports = " ".join("%s=%s" % (k, shlex.quote(v))
                           for k, v in env.items())
        remote_cmd = command if isinstance(command, str) \
            else " ".join(shlex.quote(c) for c in command)
        cmd = ["ssh", "-o", "StrictHostKeyChecking=no", host,
               "cd %s; env %s %s" % (shlex.quote(os.getcwd()), exports,
                                     remote_cmd)]
        procs.append(subprocess.Popen(cmd))
    return [p.wait() for p in procs]


def main():
    parser = argparse.ArgumentParser(
        description="Launch a distributed training job (reference "
                    "tools/launch.py)")
    parser.add_argument("-n", "--num-workers", type=int, required=True)
    parser.add_argument("--launcher", choices=["local", "ssh"],
                        default="local")
    parser.add_argument("-H", "--hostfile", default=None,
                        help="one host per line (ssh launcher)")
    parser.add_argument("-p", "--port", type=int, default=DEFAULT_PORT,
                        help="coordination-service port on host 0 (ssh "
                             "launcher); change when two jobs share a "
                             "coordinator host")
    parser.add_argument("command", nargs=argparse.REMAINDER)
    args = parser.parse_args()
    if args.command and args.command[0] == "--":
        args.command = args.command[1:]
    if not args.command:
        parser.error("no command given")
    if args.launcher == "local":
        codes = launch_local(args.num_workers, args.command)
    else:
        with open(args.hostfile) as f:
            hosts = [h.strip() for h in f if h.strip()]
        assert len(hosts) >= args.num_workers, "not enough hosts"
        codes = launch_ssh(hosts[:args.num_workers], args.command,
                           port=args.port)
    bad = [c for c in codes if c != 0]
    if bad:
        sys.exit(bad[0])


if __name__ == "__main__":
    main()
