#!/usr/bin/env python
"""Rebuild the ``.idx`` file for a ``.rec`` (reference ``tools/rec2idx.py``
IndexCreator): one pass with the canonical ``MXRecordIO`` reader —
``tell()`` before each ``read()`` is the record's byte offset, and the
payload's IRHeader carries its id; ``id \\t byte-offset`` lines out.
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                ".."))

from mxnet_tpu import fsutil, recordio  # noqa: E402


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("record", help="path to the .rec file")
    ap.add_argument("index", nargs="?", default=None,
                    help="output .idx path (default: alongside the .rec)")
    ap.add_argument("--sequential", action="store_true",
                    help="number records 0..n-1 instead of reading the "
                         "packed IRHeader id")
    args = ap.parse_args(argv)
    idx_path = args.index or os.path.splitext(args.record)[0] + ".idx"

    # ONE pass with the canonical reader — tell() before each read() is
    # the record's byte offset, and the payload carries its IRHeader id
    # (so framing knowledge stays in recordio.py alone)
    reader = recordio.MXRecordIO(args.record, "r")
    n = 0
    try:
        # atomic sidecar: a crash mid-scan must not leave a truncated
        # .idx shadowing a complete .rec
        with fsutil.atomic_write_path(idx_path) as tmp_idx:
            with open(tmp_idx, "w") as out:
                while True:
                    off = reader.tell()
                    payload = reader.read()
                    if payload is None:
                        break
                    if args.sequential:
                        key = n
                    else:
                        header, _ = recordio.unpack(payload)
                        key = int(header.id)
                    out.write("%d\t%d\n" % (key, off))
                    n += 1
    finally:
        reader.close()
    print("wrote %d entries to %s" % (n, idx_path))
    return idx_path


if __name__ == "__main__":
    main()
