#!/usr/bin/env python
"""Parse training logs into a table (reference ``tools/parse_log.py``).

Consumes the log lines the Module/callback stack emits::

    INFO:root:Epoch[3] Train-accuracy=0.96
    INFO:root:Epoch[3] Time cost=2.3
    INFO:root:Epoch[3] Validation-accuracy=0.94

the telemetry-enriched Speedometer line::

    INFO:root:Epoch[3] Batch [50-100]\tSpeed: 1234.56 samples/sec\t\
step-ms=12.345\tring=3/4\taccuracy=0.912000

and (``--jsonl``) the telemetry JSONL metrics sink
(``mxnet_tpu.telemetry.export_jsonl`` / ``set_jsonl_sink``), and prints
markdown (or tsv) with one row per epoch.
"""
import argparse
import json
import re
import sys

TRAIN_RE = re.compile(r"Epoch\[(\d+)\] Train-([\w-]+)=([\d.eE+-]+)")
VAL_RE = re.compile(r"Epoch\[(\d+)\] Validation-([\w-]+)=([\d.eE+-]+)")
TIME_RE = re.compile(r"Epoch\[(\d+)\] Time cost=([\d.eE+-]+)")
SPEED_RE = re.compile(r"Epoch\[(\d+)\].*Speed: ([\d.eE+-]+) samples/sec")
STEPMS_RE = re.compile(r"Epoch\[(\d+)\].*\bstep-ms=([\d.eE+-]+)")
RING_RE = re.compile(r"Epoch\[(\d+)\].*\bring=(\d+)/(\d+)")


def parse(lines):
    """rows[epoch] = {"train": {metric: v}, "val": {metric: v},
    "time": float|None, "speed": [..], "step_ms": [..], "ring": [..]} —
    every metric name kept (fit can emit several eval metrics per
    epoch); step_ms/ring come from the telemetry-enriched Speedometer
    line."""
    rows = {}

    def row(e):
        return rows.setdefault(int(e), {"train": {}, "val": {},
                                        "time": None, "speed": [],
                                        "step_ms": [], "ring": []})
    for line in lines:
        m = TRAIN_RE.search(line)
        if m:
            row(m.group(1))["train"][m.group(2)] = float(m.group(3))
        m = VAL_RE.search(line)
        if m:
            row(m.group(1))["val"][m.group(2)] = float(m.group(3))
        m = TIME_RE.search(line)
        if m:
            row(m.group(1))["time"] = float(m.group(2))
        m = SPEED_RE.search(line)
        if m:
            row(m.group(1))["speed"].append(float(m.group(2)))
        m = STEPMS_RE.search(line)
        if m:
            row(m.group(1))["step_ms"].append(float(m.group(2)))
        m = RING_RE.search(line)
        if m:
            row(m.group(1))["ring"].append(
                int(m.group(2)) / max(1, int(m.group(3))))
    return rows


def parse_jsonl(lines):
    """Parse a telemetry JSONL sink (one JSON object per line) into
    ``{"spans": {name: {count, mean_ms, total_ms}}, "counters": {...},
    "gauges": {...}, "recompiles": [...], "steps": int}``.

    Span stats are aggregated from the per-event ``dur_ms`` stream; a
    trailing ``kind="snapshot"`` record (written by ``export_jsonl``)
    overrides counters/gauges with the authoritative final values."""
    spans = {}
    counters = {}
    gauges = {}
    recompiles = []
    steps = 0
    for line in lines:
        line = line.strip()
        if not line:
            continue
        try:
            rec = json.loads(line)
        except ValueError:
            continue
        kind = rec.get("kind")
        if kind == "span":
            s = spans.setdefault(rec["name"], {"count": 0, "total_ms": 0.0})
            s["count"] += 1
            s["total_ms"] += float(rec.get("dur_ms", 0.0))
        elif kind == "step":
            steps += 1
        elif kind == "recompile":
            recompiles.append({"name": rec.get("name"),
                               "n": rec.get("n"),
                               "changed": rec.get("changed", [])})
        elif kind == "snapshot":
            counters.update(rec.get("counters", {}))
            gauges.update(rec.get("gauges", {}))
            for name, agg in rec.get("spans", {}).items():
                spans[name] = {"count": agg["count"],
                               "total_ms": agg["total_ms"]}
    for s in spans.values():
        s["mean_ms"] = round(s["total_ms"] / s["count"], 4) \
            if s["count"] else None
        s["total_ms"] = round(s["total_ms"], 4)
    return {"spans": spans, "counters": counters, "gauges": gauges,
            "recompiles": recompiles, "steps": steps}


def render_jsonl(agg, fmt="markdown"):
    """One row per span name, then counters — the epoch-table analogue
    for the metrics sink."""
    header = ["span", "count", "mean-ms", "total-ms"]
    out = []
    if fmt == "markdown":
        out.append("| " + " | ".join(header) + " |")
        out.append("| " + " | ".join("---" for _ in header) + " |")
    for name in sorted(agg["spans"]):
        s = agg["spans"][name]
        vals = [name, str(s["count"]), "%.6g" % (s["mean_ms"] or 0),
                "%.6g" % s["total_ms"]]
        out.append("| " + " | ".join(vals) + " |" if fmt == "markdown"
                   else "\t".join(vals))
    for name in sorted(agg["counters"]):
        vals = ["counter:" + name, "%.6g" % agg["counters"][name], "-", "-"]
        out.append("| " + " | ".join(vals) + " |" if fmt == "markdown"
                   else "\t".join(vals))
    if agg["recompiles"]:
        out.append("")
        out.append("recompiles:")
        for r in agg["recompiles"]:
            out.append("  %s (#%s): %s" % (r["name"], r["n"],
                                           "; ".join(r["changed"])))
    return "\n".join(out)


def render(rows, fmt="markdown"):
    train_metrics = sorted({k for r in rows.values() for k in r["train"]})
    val_metrics = sorted({k for r in rows.values() for k in r["val"]})
    has_step = any(r["step_ms"] for r in rows.values())
    has_ring = any(r["ring"] for r in rows.values())
    header = (["epoch"] + ["train-%s" % m for m in train_metrics]
              + ["val-%s" % m for m in val_metrics] + ["time", "speed"]
              + (["step-ms"] if has_step else [])
              + (["ring"] if has_ring else []))
    out = []
    if fmt == "markdown":
        out.append("| " + " | ".join(header) + " |")
        out.append("| " + " | ".join("---" for _ in header) + " |")

    def mean(xs):
        return (sum(xs) / len(xs)) if xs else None
    for e in sorted(rows):
        r = rows[e]
        cells = ([r["train"].get(m) for m in train_metrics]
                 + [r["val"].get(m) for m in val_metrics]
                 + [r["time"], mean(r["speed"])]
                 + ([mean(r["step_ms"])] if has_step else [])
                 + ([mean(r["ring"])] if has_ring else []))
        vals = [str(e)] + ["%.6g" % v if v is not None else "-"
                           for v in cells]
        if fmt == "markdown":
            out.append("| " + " | ".join(vals) + " |")
        else:
            out.append("\t".join(vals))
    return "\n".join(out)


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("logfile", nargs="?", default="-")
    parser.add_argument("--format", choices=["markdown", "tsv"],
                        default="markdown")
    parser.add_argument("--jsonl", action="store_true",
                        help="input is a telemetry JSONL metrics sink, "
                             "not a text training log")
    args = parser.parse_args()
    lines = sys.stdin if args.logfile == "-" else open(args.logfile)
    if args.jsonl:
        print(render_jsonl(parse_jsonl(lines), args.format))
    else:
        print(render(parse(lines), args.format))


if __name__ == "__main__":
    main()
