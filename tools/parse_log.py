#!/usr/bin/env python
"""Parse training logs into a table (reference ``tools/parse_log.py``).

Consumes the log lines the Module/callback stack emits::

    INFO:root:Epoch[3] Train-accuracy=0.96
    INFO:root:Epoch[3] Time cost=2.3
    INFO:root:Epoch[3] Validation-accuracy=0.94

the telemetry-enriched Speedometer line::

    INFO:root:Epoch[3] Batch [50-100]\tSpeed: 1234.56 samples/sec\t\
step-ms=12.345\tring=3/4\taccuracy=0.912000

and (``--jsonl``) the telemetry JSONL metrics sink
(``mxnet_tpu.telemetry.export_jsonl`` / ``set_jsonl_sink``), and prints
markdown (or tsv) with one row per epoch.

``--jsonl --trace <id>`` renders ONE trace as a waterfall table: every
span/event carrying that trace id, ordered by timestamp, nested by the
``sid``/``parent`` chain — one serve request or one training step end
to end, across ranks when the input is a collector-merged export.

``--incident <dir>`` summarises a flight-recorder bundle
(``mxnet_tpu.flight_recorder.dump_incident``): the trigger, the
journal-tail census, histogram quantiles and counters at the moment of
death.

``--lint`` renders a graftlint JSON findings report
(``python -m tools.lint --format json``) as a per-rule/per-file table
plus the individual new findings — the human-readable face of the lint
gate's machine output.
"""
import argparse
import json
import math
import os
import re
import sys

TRAIN_RE = re.compile(r"Epoch\[(\d+)\] Train-([\w-]+)=([\d.eE+-]+)")
VAL_RE = re.compile(r"Epoch\[(\d+)\] Validation-([\w-]+)=([\d.eE+-]+)")
TIME_RE = re.compile(r"Epoch\[(\d+)\] Time cost=([\d.eE+-]+)")
SPEED_RE = re.compile(r"Epoch\[(\d+)\].*Speed: ([\d.eE+-]+) samples/sec")
STEPMS_RE = re.compile(r"Epoch\[(\d+)\].*\bstep-ms=([\d.eE+-]+)")
RING_RE = re.compile(r"Epoch\[(\d+)\].*\bring=(\d+)/(\d+)")


def parse(lines):
    """rows[epoch] = {"train": {metric: v}, "val": {metric: v},
    "time": float|None, "speed": [..], "step_ms": [..], "ring": [..]} —
    every metric name kept (fit can emit several eval metrics per
    epoch); step_ms/ring come from the telemetry-enriched Speedometer
    line."""
    rows = {}

    def row(e):
        return rows.setdefault(int(e), {"train": {}, "val": {},
                                        "time": None, "speed": [],
                                        "step_ms": [], "ring": []})
    for line in lines:
        m = TRAIN_RE.search(line)
        if m:
            row(m.group(1))["train"][m.group(2)] = float(m.group(3))
        m = VAL_RE.search(line)
        if m:
            row(m.group(1))["val"][m.group(2)] = float(m.group(3))
        m = TIME_RE.search(line)
        if m:
            row(m.group(1))["time"] = float(m.group(2))
        m = SPEED_RE.search(line)
        if m:
            row(m.group(1))["speed"].append(float(m.group(2)))
        m = STEPMS_RE.search(line)
        if m:
            row(m.group(1))["step_ms"].append(float(m.group(2)))
        m = RING_RE.search(line)
        if m:
            row(m.group(1))["ring"].append(
                int(m.group(2)) / max(1, int(m.group(3))))
    return rows


def _hist_merge(into, d):
    """Merge one ``Histogram.to_dict`` snapshot into ``into`` (same
    sparse-bucket form) — how multi-rank snapshot records in one
    collector-merged file combine.  Pure dict math: this script stays
    import-free of mxnet_tpu, and the geometry (``lo``/``bpd``) rides
    in the snapshot itself."""
    if into is None:
        return dict(d, buckets=dict(d.get("buckets") or {}))
    into["count"] = into.get("count", 0) + d.get("count", 0)
    into["sum"] = into.get("sum", 0.0) + d.get("sum", 0.0)
    for k in ("min", "max"):
        pick = min if k == "min" else max
        vs = [v for v in (into.get(k), d.get(k)) if v is not None]
        into[k] = pick(vs) if vs else None
    b = into.setdefault("buckets", {})
    for i, c in (d.get("buckets") or {}).items():
        b[i] = b.get(i, 0) + c
    return into


def _hist_quantile(d, q):
    """Quantile from a ``Histogram.to_dict`` snapshot: geometric
    midpoint of the bucket holding the q-th observation, clamped by the
    exact min/max (mirrors mxnet_tpu.telemetry.Histogram.quantile)."""
    count = d.get("count", 0)
    if not count:
        return None
    lo = float(d.get("lo", 1e-3))
    bpd = float(d.get("bpd", 10))

    def edge(j):
        return lo * 10.0 ** (j / bpd)

    target = q * count
    seen = 0
    for i, c in sorted((int(k), v)
                       for k, v in (d.get("buckets") or {}).items()):
        seen += c
        if seen >= target and c:
            b_lo = 0.0 if i == 0 else edge(i - 1)
            b_hi = edge(i)
            mid = math.sqrt(b_lo * b_hi) if b_lo > 0 else b_hi / 2.0
            if d.get("min") is not None:
                mid = max(d["min"], mid)
            if d.get("max") is not None:
                mid = min(d["max"], mid)
            return mid
    return d.get("max")


def parse_jsonl(lines):
    """Parse a telemetry JSONL sink (one JSON object per line) into
    ``{"spans": {name: {count, mean_ms, total_ms}}, "counters": {...},
    "gauges": {...}, "recompiles": [...], "steps": int}`` plus the
    observability streams: ``histograms`` (name -> merged
    ``Histogram.to_dict``), ``traces`` (trace id -> its records, in
    file order) and ``incidents`` (flight-recorder dump journal).

    Span stats are aggregated from the per-event ``dur_ms`` stream; a
    trailing ``kind="snapshot"`` record (written by ``export_jsonl``)
    overrides counters/gauges with the authoritative final values —
    histogram snapshots from SEVERAL ranks' records merge by adding
    counts."""
    spans = {}
    counters = {}
    gauges = {}
    recompiles = []
    hbm = {}
    lockorder = []
    numerics = {}
    autotune = []
    histograms = {}
    traces = {}
    incidents = []
    model = {"errors": [], "fallbacks": {}, "picks": 0}
    program = []
    elastic = []
    compress = []
    serve = {"events": {}, "batches": 0, "fill_pct_sum": 0.0,
             "queue_depth_sum": 0, "wait_ms_sum": 0.0, "states": []}
    lint_gate = None
    chaos_audit = None
    steps = 0
    for line in lines:
        line = line.strip()
        if not line:
            continue
        try:
            rec = json.loads(line)
        except ValueError:
            continue
        kind = rec.get("kind")
        if rec.get("trace") is not None:
            traces.setdefault(str(rec["trace"]), []).append(rec)
        if kind == "span":
            s = spans.setdefault(rec["name"], {"count": 0, "total_ms": 0.0})
            s["count"] += 1
            s["total_ms"] += float(rec.get("dur_ms", 0.0))
        elif kind == "incident":
            # flight-recorder dump journal: one row per committed /
            # capped / failed bundle (mxnet_tpu.flight_recorder)
            incidents.append({"event": rec.get("name"),
                              "reason": rec.get("reason"),
                              "path": rec.get("path"),
                              "error": rec.get("error")})
        elif kind == "step":
            steps += 1
        elif kind == "recompile":
            recompiles.append({"name": rec.get("name"),
                               "n": rec.get("n"),
                               "changed": rec.get("changed", [])})
        elif kind == "hbm":
            # static per-chip HBM estimate, one per compiled program
            # (mxnet_tpu.parallel journals these at jit-cache misses);
            # keyed (program, mode) so the scan and per-call variants of
            # one step each keep their row
            key = "%s/%s" % (rec.get("program", "?"),
                             rec.get("mode", "?"))
            hbm[key] = rec
        elif kind == "lockorder":
            # runtime lock-order sanitizer observations (one event per
            # newly observed acquisition edge — tools.lint.runtime_lockorder)
            lockorder.append({"src": rec.get("src"),
                              "dst": rec.get("dst")})
        elif kind == "numerics":
            # runtime numerics sanitizer observations (one event per
            # leaf first-sighting / dtype change / non-finite count —
            # tools.lint.runtime_numerics, Monitor nan_guard)
            leaf = rec.get("leaf", "?")
            n = numerics.setdefault(leaf, {"dtypes": [], "nonfinite": 0,
                                           "size": rec.get("size"),
                                           "first_bad_step": None})
            dt = rec.get("dtype")
            if dt and dt not in n["dtypes"]:
                n["dtypes"].append(dt)
            bad = int(rec.get("nonfinite") or 0)
            n["nonfinite"] += bad
            if bad and n["first_bad_step"] is None:
                n["first_bad_step"] = rec.get("step")
        elif kind == "autotune":
            # one event per dispatch decision (mxnet_tpu.tune): name is
            # the source (hit|miss|search|fallback), payload the
            # instance key + chosen config — the per-shape census.
            # v2 adds the learned-cost-model stream on the same kind:
            # "model" (one per ranked search, predicted-vs-measured
            # error stats), "model_fallback" (model unusable, reason)
            # and "model_pick" (dispatch served a model-predicted
            # config without timing)
            name = rec.get("name")
            if name == "model":
                model["errors"].append(
                    {k: rec.get(k) for k in
                     ("family", "shape", "dtype", "n", "mean_err_pct",
                      "max_err_pct", "cv_error", "n_samples")})
            elif name == "model_fallback":
                r = str(rec.get("reason"))
                model["fallbacks"][r] = model["fallbacks"].get(r, 0) + 1
            elif name == "model_pick":
                model["picks"] += 1
            else:
                autotune.append({"source": name,
                                 "family": rec.get("family"),
                                 "shape": rec.get("shape"),
                                 "dtype": rec.get("dtype"),
                                 "config": rec.get("config"),
                                 "reason": rec.get("reason")})
        elif kind == "autotune_program":
            # whole-program schedule lookups (mxnet_tpu.tune.program):
            # one event per consumer decision (prefetch depth, scan
            # window, ZeRO on/off, bucket menu) stamped with where the
            # knob came from
            program.append({"event": "program/%s" % rec.get("name"),
                            "family": rec.get("family"),
                            "shape": rec.get("shape"),
                            "source": rec.get("tuner_source"),
                            "config": rec.get("config"),
                            "detail": rec.get("strategy")
                            or rec.get("reason")})
        elif kind == "zero" and rec.get("name") in (
                "auto_decision", "trainer_auto_decision"):
            # shard_optimizer="auto" resolutions (DataParallelStep /
            # Trainer): measured table decision or heuristic fallback
            program.append({"event": "zero/%s" % rec.get("name"),
                            "family": "prog_zero",
                            "shape": [rec.get("params"), rec.get("dp")],
                            "source": rec.get("tuner_source"),
                            "config": {"shard": rec.get("shard")},
                            "detail": rec.get("path")})
        elif kind == "compress":
            # compressed-collective decisions (parallel/compression.py
            # wire, journaled by DataParallelStep / Trainer at each
            # grad_compression resolution): one row per decision with
            # the schedule-arithmetic wire bytes vs the f32 baseline
            if rec.get("name") == "decision":
                compress.append(
                    {k: rec.get(k) for k in
                     ("mode", "requested", "path", "tuner_source", "dp",
                      "params", "dtype", "wire_bytes", "scale_bytes",
                      "f32_bytes", "ratio")})
        elif kind in ("elastic", "ckpt"):
            # elastic-transition / checkpoint journal events (one per
            # detect/reshard/write/restore — mxnet_tpu.parallel.elastic
            # + mxnet_tpu.checkpoint): the recovery-protocol census
            w_from, w_to = rec.get("world_from"), rec.get("world_to")
            if w_from is not None and w_to is not None \
                    and w_from != w_to:
                world = "%s->%s" % (w_from, w_to)
            elif w_to is not None or w_from is not None:
                world = str(w_to if w_to is not None else w_from)
            else:
                world = rec.get("world")
                world = str(world) if world is not None else None
            elastic.append({"event": "%s/%s" % (kind, rec.get("name")),
                            "step": rec.get("step"),
                            "world": world,
                            "bytes": rec.get("bytes"),
                            "dur_ms": rec.get("dur_ms"),
                            "detail": rec.get("change") or rec.get("reason")
                            or rec.get("error")})
        elif kind == "serve":
            # serving-stack journal events (mxnet_tpu.serve.server):
            # per-batch fill/queue-depth stream plus one row per
            # shed/timeout/reject/watchdog/quarantine/state transition
            name = rec.get("name", "?")
            serve["events"][name] = serve["events"].get(name, 0) + 1
            if name == "batch":
                serve["batches"] += 1
                serve["fill_pct_sum"] += float(rec.get("fill_pct") or 0.0)
                serve["queue_depth_sum"] += int(
                    rec.get("queue_depth") or 0)
                serve["wait_ms_sum"] += float(rec.get("wait_ms") or 0.0)
            elif name == "state":
                serve["states"].append(
                    "%s->%s" % (rec.get("state_from"),
                                rec.get("state_to")))
            elif name == "bucket_menu":
                # buckets="auto" resolution — also a program-schedule
                # decision (the menu came from the prog_buckets table
                # or its heuristic)
                program.append({"event": "serve/bucket_menu",
                                "family": "prog_buckets",
                                "shape": None,
                                "source": rec.get("tuner_source"),
                                "config": {"buckets":
                                           rec.get("buckets")},
                                "detail": rec.get("model")})
        elif kind == "lint" and rec.get("name") == "gate":
            lint_gate = rec
        elif kind == "lint" and rec.get("name") == "chaos_audit":
            # fault-injection coverage matrix (tools.lint --audit-chaos
            # --telemetry): one row per fault point
            chaos_audit = rec
        elif kind == "snapshot":
            counters.update(rec.get("counters", {}))
            gauges.update(rec.get("gauges", {}))
            for name, agg in rec.get("spans", {}).items():
                spans[name] = {"count": agg["count"],
                               "total_ms": agg["total_ms"]}
            for name, h in (rec.get("histograms") or {}).items():
                histograms[name] = _hist_merge(histograms.get(name), h)
    for s in spans.values():
        s["mean_ms"] = round(s["total_ms"] / s["count"], 4) \
            if s["count"] else None
        s["total_ms"] = round(s["total_ms"], 4)
    return {"spans": spans, "counters": counters, "gauges": gauges,
            "recompiles": recompiles, "steps": steps, "hbm": hbm,
            "lockorder": lockorder, "numerics": numerics,
            "autotune": autotune, "model": model, "program": program,
            "elastic": elastic, "compress": compress, "serve": serve,
            "lint_gate": lint_gate,
            "chaos_audit": chaos_audit, "histograms": histograms,
            "traces": traces, "incidents": incidents}


def _render_hbm(hbm, fmt="markdown"):
    """Bytes-per-chip table, one row per compiled program, from the
    hbm/estimate journal events."""
    if not hbm:
        return []
    header = ["program", "mode", "params-MiB", "state-MiB", "act-MiB",
              "total-MiB", "shards"]
    out = ["", "static HBM estimate (bytes/chip per compiled program):"]
    if fmt == "markdown":
        out.append("| " + " | ".join(header) + " |")
        out.append("| " + " | ".join("---" for _ in header) + " |")

    def mib(rec, key):
        v = rec.get(key)
        return "%.4g" % (float(v) / 1048576.0) if v is not None else "-"

    for key in sorted(hbm):
        r = hbm[key]
        vals = [str(r.get("program", "?")), str(r.get("mode", "?")),
                mib(r, "params_bytes_per_chip"),
                mib(r, "opt_state_bytes_per_chip"),
                mib(r, "activation_bytes_per_chip"),
                mib(r, "total_bytes_per_chip"),
                str(r.get("n_shards", "-"))]
        out.append("| " + " | ".join(vals) + " |" if fmt == "markdown"
                   else "\t".join(vals))
    return out


def render_jsonl(agg, fmt="markdown"):
    """One row per span name, then counters — the epoch-table analogue
    for the metrics sink."""
    header = ["span", "count", "mean-ms", "total-ms"]
    out = []
    if fmt == "markdown":
        out.append("| " + " | ".join(header) + " |")
        out.append("| " + " | ".join("---" for _ in header) + " |")
    for name in sorted(agg["spans"]):
        s = agg["spans"][name]
        vals = [name, str(s["count"]), "%.6g" % (s["mean_ms"] or 0),
                "%.6g" % s["total_ms"]]
        out.append("| " + " | ".join(vals) + " |" if fmt == "markdown"
                   else "\t".join(vals))
    for name in sorted(agg["counters"]):
        vals = ["counter:" + name, "%.6g" % agg["counters"][name], "-", "-"]
        out.append("| " + " | ".join(vals) + " |" if fmt == "markdown"
                   else "\t".join(vals))
    if agg["recompiles"]:
        out.append("")
        out.append("recompiles:")
        for r in agg["recompiles"]:
            out.append("  %s (#%s): %s" % (r["name"], r["n"],
                                           "; ".join(r["changed"])))
    if agg.get("lockorder"):
        out.append("")
        out.append("lockorder/observed acquisition edges "
                   "(runtime sanitizer):")
        for e in agg["lockorder"]:
            out.append("  %s -> %s" % (e["src"], e["dst"]))
    out.extend(_render_numerics(agg.get("numerics") or {}, fmt))
    out.extend(_render_autotune(agg.get("autotune") or [],
                                agg.get("counters") or {}, fmt))
    out.extend(_render_model(agg.get("model") or {},
                             agg.get("counters") or {}, fmt))
    out.extend(_render_program(agg.get("program") or [], fmt))
    out.extend(_render_compress(agg.get("compress") or [],
                                agg.get("gauges") or {}, fmt))
    out.extend(_render_elastic(agg.get("elastic") or [], fmt))
    out.extend(_render_serve(agg.get("serve") or {},
                             agg.get("counters") or {}, fmt))
    out.extend(_render_histograms(agg.get("histograms") or {}, fmt))
    out.extend(_render_traces(agg.get("traces") or {}))
    out.extend(_render_incidents(agg.get("incidents") or [], fmt))
    out.extend(_render_chaos_audit(agg.get("chaos_audit"), fmt))
    out.extend(_render_hbm(agg.get("hbm") or {}, fmt))
    return "\n".join(out)


def _render_chaos_audit(rec, fmt="markdown"):
    """Fault-injection coverage matrix from the lint/chaos_audit
    telemetry event: fault point | injection | covering test."""
    if not rec:
        return []
    out = ["", "chaos coverage (%s): %d mode(s), %d fault point(s), "
           "%d problem(s)"
           % ("OK" if rec.get("ok") else "FAILING",
              rec.get("modes", 0), rec.get("points", 0),
              rec.get("problems", 0))]
    matrix = rec.get("matrix") or []
    if not matrix:
        return out
    header = ["fault point", "site", "injection", "covering test"]
    if fmt == "markdown":
        out.append("| " + " | ".join(header) + " |")
        out.append("| " + " | ".join("---" for _ in header) + " |")
    for row in matrix:
        kind, site, modes, tests = (list(row) + ["", "", "", ""])[:4]
        vals = [str(kind), str(site), str(modes) or "-",
                str(tests) or "-"]
        out.append("| " + " | ".join(vals) + " |" if fmt == "markdown"
                   else "\t".join(vals))
    return out


def _render_histograms(histograms, fmt="markdown"):
    """Quantile digest table from the snapshot records' mergeable
    histogram dicts — one row per metric (serve latency, queue wait,
    step time, prefetch stages), quantiles computed bucket-side."""
    if not histograms:
        return []
    header = ["histogram", "count", "mean-ms", "p50-ms", "p90-ms",
              "p99-ms", "max-ms"]
    out = ["", "histograms (log-bucketed, merged across snapshots):"]
    if fmt == "markdown":
        out.append("| " + " | ".join(header) + " |")
        out.append("| " + " | ".join("---" for _ in header) + " |")

    def g(v):
        return "%.6g" % v if v is not None else "-"

    for name in sorted(histograms):
        h = histograms[name]
        n = h.get("count", 0)
        vals = [name, str(n),
                g(h.get("sum", 0.0) / n if n else None),
                g(_hist_quantile(h, 0.50)), g(_hist_quantile(h, 0.90)),
                g(_hist_quantile(h, 0.99)), g(h.get("max"))]
        out.append("| " + " | ".join(vals) + " |" if fmt == "markdown"
                   else "\t".join(vals))
    return out


def _render_traces(traces):
    """One summary line: how many distinct traces the journal carries
    (render any single one with ``--trace <id>``)."""
    if not traces:
        return []
    ids = sorted(traces, key=lambda t: traces[t][0].get("ts") or 0)
    shown = ", ".join(ids[:4]) + (", ..." if len(ids) > 4 else "")
    return ["", "traces: %d distinct (%s) — render one with "
            "--trace <id>" % (len(ids), shown)]


def _render_incidents(incidents, fmt="markdown"):
    """Flight-recorder dump journal: one row per committed, capped or
    failed bundle."""
    if not incidents:
        return []
    header = ["incident", "reason", "path/error"]
    out = ["", "flight-recorder incidents:"]
    if fmt == "markdown":
        out.append("| " + " | ".join(header) + " |")
        out.append("| " + " | ".join("---" for _ in header) + " |")
    for e in incidents:
        vals = [str(e.get("event", "?")), str(e.get("reason", "?")),
                str(e.get("path") or e.get("error") or "-")]
        out.append("| " + " | ".join(vals) + " |" if fmt == "markdown"
                   else "\t".join(vals))
    return out


def render_trace(agg, trace_id, fmt="markdown"):
    """Waterfall for ONE trace: its records ordered by timestamp,
    span names indented by the ``sid``/``parent`` nesting, offsets
    relative to the trace's first record — readable straight off a
    per-rank export or a collector-merged multi-rank file."""
    recs = (agg.get("traces") or {}).get(str(trace_id))
    if not recs:
        return "trace %s: not found (%d traces in input)" \
            % (trace_id, len(agg.get("traces") or {}))
    recs = sorted(recs, key=lambda r: r.get("ts") or 0)
    t0 = recs[0].get("ts") or 0
    depth = {}
    for r in recs:
        sid = r.get("sid")
        if sid is not None:
            depth[sid] = depth.get(r.get("parent"), 0) + 1
    header = ["offset-ms", "dur-ms", "rank", "kind", "name", "detail"]
    out = ["trace %s (%d records):" % (trace_id, len(recs))]
    if fmt == "markdown":
        out.append("| " + " | ".join(header) + " |")
        out.append("| " + " | ".join("---" for _ in header) + " |")
    skip = ("ts", "kind", "name", "trace", "sid", "parent", "tid",
            "dur_ms", "rank")
    for r in recs:
        ind = "  " * (depth.get(r.get("sid"),
                                depth.get(r.get("parent"), 0)))
        detail = " ".join(
            "%s=%s" % (k, r[k]) for k in sorted(r)
            if k not in skip and r[k] is not None)
        vals = ["%.3f" % (((r.get("ts") or 0) - t0) * 1e3),
                "%.3f" % r["dur_ms"] if r.get("dur_ms") is not None
                else "-",
                "-" if r.get("rank") is None else str(r["rank"]),
                str(r.get("kind", "?")),
                ind + str(r.get("name", "?")), detail or "-"]
        out.append("| " + " | ".join(vals) + " |" if fmt == "markdown"
                   else "\t".join(vals))
    return "\n".join(out)


def parse_incident(path):
    """Load a flight-recorder bundle directory
    (``incident-<ts>-<reason>/``) into one dict: config + snapshot +
    histogram dicts + the parsed journal tail."""
    def load(name, default):
        p = os.path.join(path, name)
        if not os.path.exists(p):
            return default
        try:
            with open(p) as f:
                return json.load(f)
        except ValueError:
            return default

    journal_path = os.path.join(path, "journal.jsonl")
    journal_agg = None
    n_journal = 0
    if os.path.exists(journal_path):
        with open(journal_path) as f:
            lines = f.readlines()
        n_journal = len(lines)
        journal_agg = parse_jsonl(lines)
    return {"path": path, "config": load("config.json", {}),
            "snapshot": load("snapshot.json", {}),
            "histograms": load("histograms.json", {}),
            "lockgraph": load("lockgraph.json", []),
            "hbm": load("hbm.json", []),
            "journal": journal_agg, "journal_records": n_journal}


def render_incident(inc, fmt="markdown"):
    """Bundle summary: the trigger line (reason/detail/rank/pid), the
    journal-tail census (event kinds, serve outcomes, traces),
    histogram quantiles and final counters."""
    cfg = inc.get("config") or {}
    out = ["incident bundle %s" % inc.get("path"),
           "  reason: %s" % cfg.get("reason"),
           "  detail: %s" % cfg.get("detail"),
           "  rank=%s pid=%s ts=%s" % (cfg.get("rank"), cfg.get("pid"),
                                       cfg.get("ts"))]
    if cfg.get("extra"):
        out.append("  extra: %s" % json.dumps(cfg["extra"],
                                              default=str,
                                              sort_keys=True))
    snap = inc.get("snapshot") or {}
    counters = snap.get("counters") or {}
    if counters:
        out.append("  counters: %s"
                   % " ".join("%s=%s" % (k, counters[k])
                              for k in sorted(counters)))
    out.extend(_render_histograms(inc.get("histograms") or {}, fmt))
    j = inc.get("journal")
    if j is not None:
        out.append("")
        out.append("journal tail (%d records):"
                   % inc.get("journal_records", 0))
        out.append("  traces: %d distinct"
                   % len(j.get("traces") or {}))
        out.extend(_render_serve(j.get("serve") or {},
                                 j.get("counters") or {}, fmt))
        out.extend(_render_elastic(j.get("elastic") or [], fmt))
        out.extend(_render_incidents(j.get("incidents") or [], fmt))
    if inc.get("lockgraph"):
        out.append("")
        out.append("lock-order edges at dump: %d" % len(inc["lockgraph"]))
    return "\n".join(out)


def _render_serve(serve, counters, fmt="markdown"):
    """Serving journal census: dispatched-batch aggregates (count, mean
    fill %, mean queue depth, mean batch wait) plus one row per event
    kind (sheds, timeouts, rejects, watchdog fires, quarantines, state
    transitions) — the client-visible failure envelope at a glance."""
    events = (serve or {}).get("events") or {}
    if not events and not any(k.startswith("serve.") for k in counters):
        return []
    out = ["", "serve journal census:"]
    n = serve.get("batches", 0)
    if n:
        out.append(
            "  batches=%d mean-fill=%.1f%% mean-queue-depth=%.2f "
            "mean-wait-ms=%.3f"
            % (n, serve["fill_pct_sum"] / n,
               serve["queue_depth_sum"] / n, serve["wait_ms_sum"] / n))
    header = ["event", "count"]
    if fmt == "markdown":
        out.append("| " + " | ".join(header) + " |")
        out.append("| " + " | ".join("---" for _ in header) + " |")
    for name in sorted(events):
        vals = ["serve/%s" % name, str(events[name])]
        out.append("| " + " | ".join(vals) + " |" if fmt == "markdown"
                   else "\t".join(vals))
    counts = " ".join("%s=%s" % (k.split(".", 1)[1], counters[k])
                      for k in sorted(counters)
                      if k.startswith("serve."))
    if counts:
        out.append("  counters: %s" % counts)
    if serve.get("states"):
        out.append("  state transitions: %s"
                   % " ".join(serve["states"]))
    return out


def _render_elastic(elastic, fmt="markdown"):
    """Elastic/checkpoint journal census: one row per recovery-protocol
    transition (elastic/detect, elastic/reshard, ckpt/write,
    ckpt/restore, ...) with the step, world-size transition, bytes
    moved and duration."""
    if not elastic:
        return []
    header = ["event", "step", "world", "bytes", "ms", "detail"]
    out = ["", "elastic/checkpoint journal census:"]
    if fmt == "markdown":
        out.append("| " + " | ".join(header) + " |")
        out.append("| " + " | ".join("---" for _ in header) + " |")

    def cell(v):
        return "-" if v is None else str(v)

    for e in elastic:
        vals = [e["event"], cell(e.get("step")), cell(e.get("world")),
                cell(e.get("bytes")), cell(e.get("dur_ms")),
                cell(e.get("detail"))]
        out.append("| " + " | ".join(vals) + " |" if fmt == "markdown"
                   else "\t".join(vals))
    return out


def _render_compress(compress, gauges, fmt="markdown"):
    """Gradient-compression census from the compress/decision journal:
    one row per knob resolution (mode, who decided, dp extent, and the
    schedule-arithmetic bytes on the wire vs the f32 baseline), headed
    by the final wire-savings gauges."""
    if not compress and not any(k.startswith("compression.")
                                for k in gauges):
        return []
    out = ["", "gradient compression census:"]
    saved = gauges.get("compression.bytes_saved")
    scale = gauges.get("compression.scale_bytes")
    if saved is not None or scale is not None:
        out.append("  wire bytes saved/step: %s (scale side tensor: %s)"
                   % ("%.6g" % saved if saved is not None else "-",
                      "%.6g" % scale if scale is not None else "-"))
    if not compress:
        return out
    header = ["mode", "requested", "path", "source", "dp", "params",
              "dtype", "wire-B", "scale-B", "f32-B", "ratio"]
    if fmt == "markdown":
        out.append("| " + " | ".join(header) + " |")
        out.append("| " + " | ".join("---" for _ in header) + " |")

    def cell(v):
        return "-" if v is None else str(v)

    for d in compress:
        vals = [cell(d.get("mode")), cell(d.get("requested")),
                cell(d.get("path")), cell(d.get("tuner_source")),
                cell(d.get("dp")), cell(d.get("params")),
                cell(d.get("dtype")), cell(d.get("wire_bytes")),
                cell(d.get("scale_bytes")), cell(d.get("f32_bytes")),
                cell(d.get("ratio"))]
        out.append("| " + " | ".join(vals) + " |" if fmt == "markdown"
                   else "\t".join(vals))
    return out


def _render_autotune(autotune, counters, fmt="markdown"):
    """Per-shape chosen-config table from the autotune journal events
    (one per dispatch decision) headed by the hit/miss/search/fallback
    counter line — where every shape's kernel config came from."""
    if not autotune and not any(k.startswith("autotune.")
                                for k in counters):
        return []
    counts = " ".join("%s=%d" % (k.split(".", 1)[1], counters[k])
                      for k in sorted(counters)
                      if k.startswith("autotune."))
    out = ["", "autotune decisions (cost-table census%s):"
           % (": " + counts if counts else "")]
    if not autotune:
        return out
    header = ["family", "shape", "dtype", "source", "config"]
    if fmt == "markdown":
        out.append("| " + " | ".join(header) + " |")
        out.append("| " + " | ".join("---" for _ in header) + " |")
    for e in autotune:
        cfg = e.get("config")
        cfg_s = " ".join("%s=%s" % (k, cfg[k]) for k in sorted(cfg)) \
            if isinstance(cfg, dict) else (e.get("reason") or "-")
        vals = [str(e.get("family", "?")),
                "x".join(str(d) for d in (e.get("shape") or [])) or "-",
                str(e.get("dtype", "?")), str(e.get("source", "?")),
                cfg_s]
        out.append("| " + " | ".join(vals) + " |" if fmt == "markdown"
                   else "\t".join(vals))
    return out


def _render_model(model, counters, fmt="markdown"):
    """Learned-cost-model census: the rank/hit/fallback counter line,
    the per-search predicted-vs-measured error table (one row per
    model-ranked search — how well the model ordered the candidates it
    was trusted to prune) and the fallback-reason tally."""
    errors = (model or {}).get("errors") or []
    fallbacks = (model or {}).get("fallbacks") or {}
    have_counts = any(k.startswith("autotune.model")
                      for k in counters)
    if not errors and not fallbacks and not have_counts:
        return []
    counts = " ".join("%s=%d" % (k.split(".", 1)[1], counters[k])
                      for k in sorted(counters)
                      if k.startswith("autotune.model"))
    out = ["", "autotune cost model (predicted vs measured%s):"
           % (": " + counts if counts else "")]
    if errors:
        header = ["family", "shape", "dtype", "timed", "mean-err%",
                  "max-err%", "cv-err", "samples"]
        if fmt == "markdown":
            out.append("| " + " | ".join(header) + " |")
            out.append("| " + " | ".join("---" for _ in header) + " |")
        def pct(v):
            return "%.4g" % float(v) if v is not None else "-"

        for e in errors:
            vals = [str(e.get("family", "?")),
                    "x".join(str(d) for d in (e.get("shape") or []))
                    or "-",
                    str(e.get("dtype", "?")), str(e.get("n", "-")),
                    pct(e.get("mean_err_pct")),
                    pct(e.get("max_err_pct")), pct(e.get("cv_error")),
                    str(e.get("n_samples", "-"))]
            out.append("| " + " | ".join(vals) + " |"
                       if fmt == "markdown" else "\t".join(vals))
    for reason in sorted(fallbacks):
        out.append("  fallback[%s]=%d" % (reason, fallbacks[reason]))
    return out


def _render_program(program, fmt="markdown"):
    """Whole-program schedule decision census: one row per consumer
    lookup (prefetch depth, scan window, ZeRO auto resolution, serving
    bucket menu) with the knob's provenance
    (table|model|searched|heuristic)."""
    if not program:
        return []
    header = ["event", "family", "shape", "source", "config", "detail"]
    out = ["", "program schedule decisions:"]
    if fmt == "markdown":
        out.append("| " + " | ".join(header) + " |")
        out.append("| " + " | ".join("---" for _ in header) + " |")
    for e in program:
        cfg = e.get("config")
        if isinstance(cfg, dict):
            cfg_s = " ".join("%s=%s" % (k, cfg[k]) for k in sorted(cfg))
        else:
            cfg_s = "-" if cfg is None else str(cfg)
        vals = [str(e.get("event", "?")), str(e.get("family", "?")),
                "x".join(str(d) for d in (e.get("shape") or [])) or "-",
                str(e.get("source", "?")), cfg_s,
                "-" if e.get("detail") is None else str(e["detail"])]
        out.append("| " + " | ".join(vals) + " |" if fmt == "markdown"
                   else "\t".join(vals))
    return out


def _render_numerics(numerics, fmt="markdown"):
    """Per-leaf observed-dtype + finite-gauge table from the
    numerics/observed journal events (runtime numerics sanitizer /
    Monitor nan_guard)."""
    if not numerics:
        return []
    header = ["leaf", "observed-dtypes", "nonfinite", "size",
              "first-bad-step"]
    out = ["", "numerics/observed leaves (runtime sanitizer):"]
    if fmt == "markdown":
        out.append("| " + " | ".join(header) + " |")
        out.append("| " + " | ".join("---" for _ in header) + " |")
    for leaf in sorted(numerics):
        n = numerics[leaf]
        vals = [leaf, " -> ".join(n["dtypes"]) or "-",
                str(n["nonfinite"]),
                "-" if n.get("size") is None else str(n["size"]),
                "-" if n.get("first_bad_step") is None
                else str(n["first_bad_step"])]
        out.append("| " + " | ".join(vals) + " |" if fmt == "markdown"
                   else "\t".join(vals))
    return out


# rule-id prefix -> checker family (docs/LINTING.md catalog sections;
# mirrors tools.lint.rule_family — this script stays import-free)
_RULE_FAMILIES = {"trace": "trace-safety", "retrace": "retrace",
                  "donate": "donation", "pallas": "pallas",
                  "shard": "sharding", "conc": "concurrency",
                  "num": "numerics", "err": "errorflow",
                  "res": "errorflow", "lint": "meta"}


def _rule_family(rule):
    return _RULE_FAMILIES.get(rule.split("-", 1)[0], "other")


def parse_lint(text):
    """Parse a graftlint ``--format json`` report into
    ``{"counts": {...}, "by_rule": {rule: n}, "by_file": {path: n},
    "findings": [...], "hbm": {...}}`` (new findings only;
    baselined/suppressed are reflected in counts).

    Also accepts a telemetry JSONL sink instead of a report: the
    ``lint/gate`` event supplies the counts and the ``hbm/estimate``
    events the bytes-per-chip table (one file carries both when the
    tier-1 gate and a training run share a journal)."""
    data = None
    try:
        data = json.loads(text)
    except ValueError:
        pass
    if not isinstance(data, dict):
        agg = parse_jsonl(text.splitlines())
        gate = agg.get("lint_gate") or {}
        counts = {k: gate.get(k, 0)
                  for k in ("new", "baselined", "suppressed")}
        counts["total"] = sum(counts.values())
        return {"counts": counts, "by_rule": {}, "by_file": {},
                "findings": [], "hbm": agg.get("hbm") or {}}
    by_rule = {}
    by_file = {}
    for f in data.get("findings", []):
        by_rule[f["rule"]] = by_rule.get(f["rule"], 0) + 1
        by_file[f["path"]] = by_file.get(f["path"], 0) + 1
    return {"counts": data.get("counts", {}), "by_rule": by_rule,
            "by_file": by_file, "findings": data.get("findings", []),
            "hbm": data.get("hbm_estimates", {})}


def render_lint(agg, fmt="markdown"):
    """Summary table (new/baselined/suppressed + per-family/rule
    counts), one line per new finding, and the static-HBM table when
    the input journal carried hbm/estimate events."""
    c = agg["counts"]
    header = ["family", "rule", "new"]
    out = []
    if fmt == "markdown":
        out.append("lint: %d new, %d baselined, %d suppressed (%d total)"
                   % (c.get("new", 0), c.get("baselined", 0),
                      c.get("suppressed", 0), c.get("total", 0)))
        out.append("")
        out.append("| " + " | ".join(header) + " |")
        out.append("| " + " | ".join("---" for _ in header) + " |")
    else:
        out.append("new\t%d" % c.get("new", 0))
        out.append("baselined\t%d" % c.get("baselined", 0))
        out.append("suppressed\t%d" % c.get("suppressed", 0))
    for rule in sorted(agg["by_rule"],
                       key=lambda r: (_rule_family(r), r)):
        vals = [_rule_family(rule), rule, str(agg["by_rule"][rule])]
        out.append("| " + " | ".join(vals) + " |" if fmt == "markdown"
                   else "\t".join(vals))
    if agg["findings"]:
        out.append("")
        for f in agg["findings"]:
            out.append("%s:%d: %s [%s] (in %s)"
                       % (f["path"], f["line"], f["message"], f["rule"],
                          f.get("context", "?")))
    out.extend(_render_hbm(agg.get("hbm") or {}, fmt))
    return "\n".join(out)


def render(rows, fmt="markdown"):
    train_metrics = sorted({k for r in rows.values() for k in r["train"]})
    val_metrics = sorted({k for r in rows.values() for k in r["val"]})
    has_step = any(r["step_ms"] for r in rows.values())
    has_ring = any(r["ring"] for r in rows.values())
    header = (["epoch"] + ["train-%s" % m for m in train_metrics]
              + ["val-%s" % m for m in val_metrics] + ["time", "speed"]
              + (["step-ms"] if has_step else [])
              + (["ring"] if has_ring else []))
    out = []
    if fmt == "markdown":
        out.append("| " + " | ".join(header) + " |")
        out.append("| " + " | ".join("---" for _ in header) + " |")

    def mean(xs):
        return (sum(xs) / len(xs)) if xs else None
    for e in sorted(rows):
        r = rows[e]
        cells = ([r["train"].get(m) for m in train_metrics]
                 + [r["val"].get(m) for m in val_metrics]
                 + [r["time"], mean(r["speed"])]
                 + ([mean(r["step_ms"])] if has_step else [])
                 + ([mean(r["ring"])] if has_ring else []))
        vals = [str(e)] + ["%.6g" % v if v is not None else "-"
                           for v in cells]
        if fmt == "markdown":
            out.append("| " + " | ".join(vals) + " |")
        else:
            out.append("\t".join(vals))
    return "\n".join(out)


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("logfile", nargs="?", default="-")
    parser.add_argument("--format", choices=["markdown", "tsv"],
                        default="markdown")
    parser.add_argument("--jsonl", action="store_true",
                        help="input is a telemetry JSONL metrics sink, "
                             "not a text training log")
    parser.add_argument("--lint", action="store_true",
                        help="input is a graftlint --format json report "
                             "(python -m tools.lint --format json)")
    parser.add_argument("--trace", metavar="ID",
                        help="with --jsonl: render ONE trace as a "
                             "waterfall table instead of the summary")
    parser.add_argument("--incident", metavar="DIR",
                        help="summarise a flight-recorder bundle "
                             "directory (incident-<ts>-<reason>/); "
                             "no logfile needed")
    args = parser.parse_args()
    if args.incident:
        print(render_incident(parse_incident(args.incident),
                              args.format))
        return
    lines = sys.stdin if args.logfile == "-" else open(args.logfile)
    if args.lint:
        print(render_lint(parse_lint(lines.read()), args.format))
    elif args.trace:
        print(render_trace(parse_jsonl(lines), args.trace, args.format))
    elif args.jsonl:
        print(render_jsonl(parse_jsonl(lines), args.format))
    else:
        print(render(parse(lines), args.format))


if __name__ == "__main__":
    main()
