#!/usr/bin/env python
"""Parse training logs into a table (reference ``tools/parse_log.py``).

Consumes the log lines the Module/callback stack emits::

    INFO:root:Epoch[3] Train-accuracy=0.96
    INFO:root:Epoch[3] Time cost=2.3
    INFO:root:Epoch[3] Validation-accuracy=0.94

and prints markdown (or tsv) with one row per epoch.
"""
import argparse
import re
import sys

TRAIN_RE = re.compile(r"Epoch\[(\d+)\] Train-([\w-]+)=([\d.eE+-]+)")
VAL_RE = re.compile(r"Epoch\[(\d+)\] Validation-([\w-]+)=([\d.eE+-]+)")
TIME_RE = re.compile(r"Epoch\[(\d+)\] Time cost=([\d.eE+-]+)")
SPEED_RE = re.compile(r"Epoch\[(\d+)\].*Speed: ([\d.eE+-]+) samples/sec")


def parse(lines):
    """rows[epoch] = {"train": {metric: v}, "val": {metric: v},
    "time": float|None, "speed": [..]} — every metric name kept (fit can
    emit several eval metrics per epoch)."""
    rows = {}

    def row(e):
        return rows.setdefault(int(e), {"train": {}, "val": {},
                                        "time": None, "speed": []})
    for line in lines:
        m = TRAIN_RE.search(line)
        if m:
            row(m.group(1))["train"][m.group(2)] = float(m.group(3))
        m = VAL_RE.search(line)
        if m:
            row(m.group(1))["val"][m.group(2)] = float(m.group(3))
        m = TIME_RE.search(line)
        if m:
            row(m.group(1))["time"] = float(m.group(2))
        m = SPEED_RE.search(line)
        if m:
            row(m.group(1))["speed"].append(float(m.group(2)))
    return rows


def render(rows, fmt="markdown"):
    train_metrics = sorted({k for r in rows.values() for k in r["train"]})
    val_metrics = sorted({k for r in rows.values() for k in r["val"]})
    header = (["epoch"] + ["train-%s" % m for m in train_metrics]
              + ["val-%s" % m for m in val_metrics] + ["time", "speed"])
    out = []
    if fmt == "markdown":
        out.append("| " + " | ".join(header) + " |")
        out.append("| " + " | ".join("---" for _ in header) + " |")
    for e in sorted(rows):
        r = rows[e]
        speed = (sum(r["speed"]) / len(r["speed"])) if r["speed"] else None
        cells = ([r["train"].get(m) for m in train_metrics]
                 + [r["val"].get(m) for m in val_metrics]
                 + [r["time"], speed])
        vals = [str(e)] + ["%.6g" % v if v is not None else "-"
                           for v in cells]
        if fmt == "markdown":
            out.append("| " + " | ".join(vals) + " |")
        else:
            out.append("\t".join(vals))
    return "\n".join(out)


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("logfile", nargs="?", default="-")
    parser.add_argument("--format", choices=["markdown", "tsv"],
                        default="markdown")
    args = parser.parse_args()
    lines = sys.stdin if args.logfile == "-" else open(args.logfile)
    print(render(parse(lines), args.format))


if __name__ == "__main__":
    main()
