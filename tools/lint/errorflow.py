"""errorflow — exception-flow & resource-lifecycle analysis (phase 5).

PRs 11-13 grew a failure-handling surface (atomic tmp+``os.replace``
artifact writes, terminal-outcome request lifecycles, incident bundles)
whose disciplines nothing *proved*; one new ``open(path, "w")`` or a
swallowed exception in a thread loop silently reopens the torn-file and
hung-request bug classes.  Five rules make those contracts
machine-checked over the shared :class:`jitgraph.PackageIndex`:

* ``err-swallowed-exception`` — a bare/broad ``except`` whose handler
  neither re-raises, journals/logs, resolves a terminal outcome, nor
  returns a fallback — scoped to where a silent swallow actually
  deadlocks or corrupts: thread-reachable code (the PR-7 model) and
  shutdown/cleanup paths (``close``/``stop``/...), plus every *bare*
  ``except:``.  Allowlisted idioms: journal-and-continue in a daemon
  loop (the handler calls ``telemetry``/``logging``), the
  single-statement best-effort probe (``try: <one call> except: pass``)
  and ``__del__`` finalizers (which must never raise).
* ``res-nonatomic-write`` — a durable artifact written in place:
  ``open(path, "w"/"wb")`` (or a direct ``np.savez``) on a non-tmp path
  instead of the ``atomic_path``/``atomic_write_path`` tmp +
  ``os.replace`` discipline.  Interprocedural: a helper that *returns*
  a writable handle taints its call sites, a helper that *receives* the
  target path is judged at each resolved call site, and the blessing of
  a locally-defined atomic contextmanager is structural (it must
  actually contain the ``os.replace`` commit — a copy with the commit
  deleted is caught).  A tmp-named write with no reachable commit, and
  a ``@contextmanager`` yielding a tmp path without ``os.replace``,
  fire too.  Streaming writers (``self.fh = open(...)``, append mode)
  are the allowlisted incremental-format idiom.
* ``res-leaked-handle`` — a file/socket/temp-dir acquired into a local
  without a ``with`` block or a ``finally``-reachable release: an
  exception between acquire and the straight-line ``close()`` leaks
  the handle.  Handles that escape (returned, stored on ``self``,
  passed to another call) are the caller's to manage and clean.
* ``err-terminal-outcome`` — dataflow over the first-write-wins
  ``PendingRequest`` API: a request-carrying path that can exit its
  scope with the request neither resolved (``_resolve``/reject/timeout/
  error) nor handed off (passed on, stored, returned, appended).  Fires
  only when *some* sibling path does resolve — the partial-resolution
  signal behind every hung-request bug.
* ``err-incident-trigger`` — a codepath journaling a terminal failure
  event (``*_failed``/``*giveup``/``*quarantine``) without
  ``flight_recorder.dump_incident`` reachable from the same function —
  drift from the documented trigger matrix (docs/OBSERVABILITY.md).

The runtime counterpart is ``tools.lint.chaos_coverage``: the same
index enumerates the fault points these disciplines protect and audits
that each one has a chaos injection and a covering test.
"""
from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Set

from .concurrency import _SHUTDOWN_NAMES
from .core import Finding, ModuleInfo
from .jitgraph import PackageIndex, call_target_name, call_target_parts

RULES = {
    "err-swallowed-exception":
        "bare/broad except that neither re-raises, journals, nor "
        "resolves an outcome (thread loops & cleanup paths)",
    "res-nonatomic-write":
        "durable artifact written in place instead of the "
        "atomic_path/tmp+os.replace discipline",
    "res-leaked-handle":
        "file/socket/temp-dir acquired without a with block or "
        "finally-reachable release on exception edges",
    "err-terminal-outcome":
        "a PendingRequest-carrying path can exit without reaching a "
        "terminal outcome (resolve/reject/timeout/error)",
    "err-incident-trigger":
        "journals a *_failed/giveup/quarantine event but never calls "
        "flight_recorder.dump_incident",
}

_INTERESTING_TOKENS = ("except", "open(", "savez", "os.replace",
                      "PendingRequest", "_resolve", "dump_incident",
                      "mkdtemp", "socket(", "atomic")


def _is_interesting(module: ModuleInfo) -> bool:
    src = module.source
    return any(tok in src for tok in _INTERESTING_TOKENS)


def _parents(tree) -> Dict[int, ast.AST]:
    out: Dict[int, ast.AST] = {}
    for node in ast.walk(tree):
        for child in ast.iter_child_nodes(node):
            out[id(child)] = node
    return out


def _enclosing_function(index: PackageIndex, parents, node):
    cur = parents.get(id(node))
    while cur is not None:
        if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef,
                            ast.Lambda)):
            return index.function_at(cur)
        cur = parents.get(id(cur))
    return None


# -- err-swallowed-exception -------------------------------------------------

_BROAD_TYPES = {"Exception", "BaseException"}
# calls that make a handler "observed": journaling, logging, incident
# dumps, terminal request outcomes, process exit
_HANDLED_ROOTS = {"logging", "warnings", "telemetry", "_telemetry",
                  "flight_recorder", "log", "logger", "_log", "LOG"}
_HANDLED_ATTRS = {"exception", "warning", "warn", "error", "debug",
                  "info", "critical", "log", "event", "inc", "journal",
                  "dump_incident", "_exit", "print"}
_TERMINAL_ATTRS = {"_resolve", "resolve", "reject", "set_result",
                   "set_exception", "cancel"}
# cleanup-path scope: the concurrency shutdown-name set minus __del__
# (a finalizer that swallows is the CORRECT idiom — exceptions in
# __del__ print interpreter noise and can fire mid-teardown)
_CLEANUP_NAMES = frozenset(_SHUTDOWN_NAMES) - {"__del__"}
_SIMPLE_STMTS = (ast.Expr, ast.Assign, ast.AugAssign, ast.Delete,
                 ast.Import, ast.ImportFrom)


def _is_broad(handler: ast.ExceptHandler) -> bool:
    t = handler.type
    if t is None:
        return True
    if isinstance(t, ast.Name):
        return t.id in _BROAD_TYPES
    if isinstance(t, ast.Tuple):
        return any(isinstance(e, ast.Name) and e.id in _BROAD_TYPES
                   for e in t.elts)
    return False


def _handler_observed(handler: ast.ExceptHandler) -> bool:
    for node in ast.walk(handler):
        if isinstance(node, (ast.Raise, ast.Return)):
            return True
        if isinstance(node, ast.Call):
            parts = call_target_parts(node)
            if not parts:
                continue
            if parts[0] in _HANDLED_ROOTS \
                    or parts[-1] in _HANDLED_ATTRS \
                    or parts[-1] in _TERMINAL_ATTRS:
                return True
    return False


def _swallowed_findings(index: PackageIndex, module: ModuleInfo,
                        parents) -> List[Finding]:
    out: List[Finding] = []
    reach = index.thread_reachable()
    for node in ast.walk(module.tree):
        if not isinstance(node, ast.ExceptHandler):
            continue
        if not _is_broad(node) or _handler_observed(node):
            continue
        try_node = parents.get(id(node))
        if isinstance(try_node, ast.Try) and len(try_node.body) == 1 \
                and isinstance(try_node.body[0], _SIMPLE_STMTS):
            # best-effort probe: try body is ONE simple statement whose
            # failure the code explicitly rides out
            continue
        fi = _enclosing_function(index, parents, node)
        if fi is not None and fi.name == "__del__":
            continue
        bare = node.type is None
        in_thread = fi is not None and id(fi.node) in reach
        in_cleanup = fi is not None and fi.name in _CLEANUP_NAMES
        if not (bare or in_thread or in_cleanup):
            continue
        where = "thread loop" if in_thread else \
            ("cleanup path" if in_cleanup else "handler")
        out.append(Finding(
            rule="err-swallowed-exception", path=module.relpath,
            line=node.lineno, col=node.col_offset,
            message="broad except in %s swallows the exception "
                    "silently — re-raise, journal (telemetry.event/"
                    "logging), or resolve an outcome" % where,
            context=fi.qualname if fi else "<module>"))
    return out


# -- res-nonatomic-write -----------------------------------------------------

_ATOMIC_CM_NAMES = {"atomic_path", "atomic_write_path"}
_TEMPFILE_CTORS = {"mkdtemp", "mkstemp", "NamedTemporaryFile",
                   "TemporaryDirectory", "TemporaryFile", "gettempdir",
                   "mktemp"}


def _has_os_replace(fn_node) -> bool:
    for node in ast.walk(fn_node):
        if isinstance(node, ast.Call) \
                and call_target_parts(node)[-2:] == ("os", "replace"):
            return True
    return False


def _is_contextmanager(fn_node) -> bool:
    for dec in getattr(fn_node, "decorator_list", ()):
        name = dec.attr if isinstance(dec, ast.Attribute) else \
            (dec.id if isinstance(dec, ast.Name) else None)
        if name in ("contextmanager", "asynccontextmanager"):
            return True
    return False


class _WriteModel:
    """Per-module bookkeeping for the atomic-write analysis."""

    def __init__(self, index: PackageIndex, module: ModuleInfo, parents):
        self.index = index
        self.module = module
        self.parents = parents
        self.call_by_node = {id(cs.node): cs
                             for cs in index.calls_in(module)}
        # per-function: with-item bindings (name -> context call) and
        # local assignments (name -> last value expr)
        self.withmap: Dict[int, Dict[str, ast.Call]] = {}
        self.assigns: Dict[int, Dict[str, ast.expr]] = {}
        for fi in index.functions_in(module):
            wm: Dict[str, ast.Call] = {}
            am: Dict[str, ast.expr] = {}
            for node in ast.walk(fi.node):
                if isinstance(node, ast.With):
                    for item in node.items:
                        if isinstance(item.optional_vars, ast.Name) \
                                and isinstance(item.context_expr,
                                               ast.Call):
                            wm[item.optional_vars.id] = \
                                item.context_expr
                elif isinstance(node, ast.Assign) \
                        and len(node.targets) == 1 \
                        and isinstance(node.targets[0], ast.Name):
                    am[node.targets[0].id] = node.value
            self.withmap[id(fi.node)] = wm
            self.assigns[id(fi.node)] = am

    def blessed_cm(self, call: Optional[ast.Call]) -> bool:
        """Is ``call`` an atomic-write contextmanager?  Resolved
        helpers are checked STRUCTURALLY (the body must contain the
        ``os.replace`` commit) so a copy with the commit deleted is not
        blessed by its name; unresolved (imported) helpers are blessed
        by name."""
        if call is None:
            return False
        name = call_target_name(call)
        if name not in _ATOMIC_CM_NAMES:
            return False
        cs = self.call_by_node.get(id(call))
        callee = cs.callee if cs is not None else None
        if callee is None:
            return True
        return _has_os_replace(callee.node)

    def _names_in(self, expr) -> Set[str]:
        return {n.id for n in ast.walk(expr) if isinstance(n, ast.Name)}

    def _strs_in(self, expr) -> List[str]:
        return [n.value for n in ast.walk(expr)
                if isinstance(n, ast.Constant)
                and isinstance(n.value, str)]

    def target_kind(self, expr, fi) -> str:
        """Classify an open/savez target in function ``fi``:
        ``blessed`` (bound from an atomic CM), ``tempfile`` (a true
        temp path needing no commit), ``tmp`` (tmp-named: needs an
        os.replace commit in scope), ``param`` (judged at call sites)
        or ``plain``."""
        wm = self.withmap.get(id(fi.node), {}) if fi else {}
        am = self.assigns.get(id(fi.node), {}) if fi else {}
        names = self._names_in(expr)
        for n in names:
            if self.blessed_cm(wm.get(n)):
                return "blessed"
        # one chase through local bindings: `target = d + "/x"` where
        # `d = tempfile.mkdtemp()` is still a temp path
        extended = set(names)
        for n in names:
            bound = am.get(n)
            if bound is not None:
                extended |= self._names_in(bound)
        for n in extended:
            bound = am.get(n)
            if bound is not None and isinstance(bound, ast.Call):
                parts = call_target_parts(bound)
                if parts and (parts[0] == "tempfile"
                              or parts[-1] in _TEMPFILE_CTORS):
                    return "tempfile"
        tmpish = any("tmp" in n.lower() for n in names) \
            or any("tmp" in s for s in self._strs_in(expr))
        if not tmpish and fi is not None:
            # one chase through a local binding: tmp = "%s.tmp" % path
            for n in names:
                bound = am.get(n)
                if bound is not None and any(
                        "tmp" in s for s in self._strs_in(bound)):
                    tmpish = True
        if tmpish:
            return "tmp"
        if fi is not None and isinstance(expr, ast.Name) \
                and expr.id in fi.param_names():
            return "param"
        return "plain"

    def callsite_tmpish(self, expr, scope) -> bool:
        names = self._names_in(expr)
        if any("tmp" in n.lower() for n in names) \
                or any("tmp" in s for s in self._strs_in(expr)):
            return True
        for n in names:
            if scope is not None and self.blessed_cm(
                    self.withmap.get(id(scope.node), {}).get(n)):
                return True
        return False


def _open_mode(call: ast.Call) -> Optional[str]:
    mode = None
    if len(call.args) > 1:
        mode = call.args[1]
    for kw in call.keywords:
        if kw.arg == "mode":
            mode = kw.value
    if mode is None:
        return "r"
    if isinstance(mode, ast.Constant) and isinstance(mode.value, str):
        return mode.value
    return None


def _param_arg(call: ast.Call, callee, pname: str) -> Optional[ast.expr]:
    names = callee.param_names()
    if pname not in names:
        return None
    pos = names.index(pname)
    if callee.is_method and names and names[0] in ("self", "cls"):
        pos -= 1          # bound-method call sites omit self
    if 0 <= pos < len(call.args):
        return call.args[pos]
    for kw in call.keywords:
        if kw.arg == pname:
            return kw.value
    return None


def _nonatomic_findings(index: PackageIndex, module: ModuleInfo,
                        parents) -> List[Finding]:
    out: List[Finding] = []
    model = _WriteModel(index, module, parents)
    # helpers that RETURN writable handles taint their call sites
    returns_handle: Set[int] = set()
    for fi in index.functions_in(module):
        for node in ast.walk(fi.node):
            if isinstance(node, ast.Return) \
                    and isinstance(node.value, ast.Call) \
                    and call_target_name(node.value) == "open":
                m = _open_mode(node.value)
                if m and m[0] in "wx":
                    returns_handle.add(id(fi.node))

    def report(node, fi, msg):
        out.append(Finding(
            rule="res-nonatomic-write", path=module.relpath,
            line=node.lineno, col=node.col_offset, message=msg,
            context=fi.qualname if fi else "<module>"))

    def judge_write(call, target, fi):
        """One write of ``target`` inside ``fi`` — the shared decision
        for direct opens, savez calls and handle-returning helpers."""
        kind = model.target_kind(target, fi)
        if kind in ("blessed", "tempfile"):
            return
        if kind == "tmp":
            if fi is not None and _has_os_replace(fi.node):
                return
            report(call, fi,
                   "tmp path written but never committed — no "
                   "os.replace reachable in %s"
                   % (fi.qualname if fi else "<module>"))
            return
        if kind == "param" and fi is not None:
            sites = index._calls_by_callee.get(id(fi.node), ())
            if sites:
                for cs in sites:
                    arg = _param_arg(cs.node, fi, target.id)
                    if arg is None:
                        continue
                    if not model.callsite_tmpish(arg, cs.scope):
                        out.append(Finding(
                            rule="res-nonatomic-write",
                            path=cs.module.relpath,
                            line=cs.node.lineno,
                            col=cs.node.col_offset,
                            message="helper '%s' writes its argument "
                                    "in place — pass a tmp path from "
                                    "atomic_path/atomic_write_path"
                                    % fi.name,
                            context=(cs.scope.qualname if cs.scope
                                     else "<module>")))
                return
        report(call, fi,
               "durable artifact written in place — use "
               "checkpoint.atomic_path / fsutil.atomic_write_path "
               "(tmp + os.replace)")

    for node in ast.walk(module.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                and _is_contextmanager(node) \
                and not _has_os_replace(node):
            # an atomic-write CM that never commits: yields a tmp path
            # the callers will write and nobody will publish
            fi = index.function_at(node)
            for sub in ast.walk(node):
                if isinstance(sub, ast.Yield) and sub.value is not None:
                    kindfi = fi if fi is not None else None
                    if kindfi is not None and model.target_kind(
                            sub.value, kindfi) == "tmp":
                        report(sub, fi,
                               "contextmanager yields a tmp path but "
                               "contains no os.replace commit")
        if not isinstance(node, ast.Call):
            continue
        name = call_target_name(node)
        parts = call_target_parts(node)
        fi = _enclosing_function(index, parents, node)
        if name == "open":
            mode = _open_mode(node)
            if mode is None or not mode or mode[0] not in "wx":
                continue
            if not node.args:
                continue
            parent = parents.get(id(node))
            if isinstance(parent, ast.Assign) and all(
                    isinstance(t, (ast.Attribute, ast.Subscript))
                    for t in parent.targets):
                # streaming-writer idiom: the handle lives on the
                # object and the format is incremental by design
                continue
            if isinstance(parent, ast.Return):
                # handle-returning helper: judged at its call sites
                # through the returns_handle tracking below
                continue
            judge_write(node, node.args[0], fi)
        elif parts and parts[-1] in ("savez", "savez_compressed") \
                and node.args:
            target = node.args[0]
            if isinstance(target, ast.Name) and fi is not None:
                bound = model.assigns.get(id(fi.node), {}).get(target.id)
                wm = model.withmap.get(id(fi.node), {})
                if (isinstance(bound, ast.Call)
                        and call_target_name(bound) == "open") \
                        or target.id in wm:
                    continue        # the open site governs the handle
            judge_write(node, target, fi)
        elif fi is not None:
            cs = model.call_by_node.get(id(node))
            if cs is not None and cs.callee is not None \
                    and id(cs.callee.node) in returns_handle \
                    and node.args:
                judge_write(node, node.args[0], fi)
    return out


# -- res-leaked-handle -------------------------------------------------------

_ACQUIRE_SOCKET = {"socket"}
_RELEASE_ATTRS = {"close", "cleanup", "shutdown", "terminate",
                  "unlink", "release"}


def _acquisition_kind(call: ast.Call) -> Optional[str]:
    name = call_target_name(call)
    parts = call_target_parts(call)
    if name == "open":
        return "file handle"
    if parts[-2:] == ("socket", "socket"):
        return "socket"
    if parts and parts[-1] in ("mkdtemp", "mkstemp") \
            and (len(parts) == 1 or parts[0] == "tempfile"):
        return "temp dir/file"
    return None


def _released_in_finally(fn_node, var: str) -> bool:
    for node in ast.walk(fn_node):
        if not isinstance(node, ast.Try) or not node.finalbody:
            continue
        for sub in node.finalbody:
            for n in ast.walk(sub):
                if isinstance(n, ast.Call):
                    f = n.func
                    if isinstance(f, ast.Attribute) \
                            and isinstance(f.value, ast.Name) \
                            and f.value.id == var \
                            and f.attr in _RELEASE_ATTRS:
                        return True
                    # shutil.rmtree(var) / os.rmdir(var) style
                    if any(isinstance(a, ast.Name) and a.id == var
                           for a in n.args):
                        return True
    return False


def _escapes(fn_node, var: str, acquire: ast.Call) -> bool:
    for node in ast.walk(fn_node):
        if isinstance(node, ast.Return) and node.value is not None:
            for n in ast.walk(node.value):
                if isinstance(n, ast.Name) and n.id == var:
                    return True
        if isinstance(node, ast.With):
            for item in node.items:
                ctx = item.context_expr
                if isinstance(ctx, ast.Name) and ctx.id == var:
                    return True
                if isinstance(ctx, ast.Call) and any(
                        isinstance(a, ast.Name) and a.id == var
                        for a in ctx.args):
                    return True      # with closing(x) / with wrap(x)
        if isinstance(node, ast.Call) and node is not acquire:
            f = node.func
            is_release = isinstance(f, ast.Attribute) \
                and isinstance(f.value, ast.Name) and f.value.id == var
            if not is_release and any(
                    isinstance(a, ast.Name) and a.id == var
                    for a in list(node.args)
                    + [kw.value for kw in node.keywords]):
                return True          # handed to another owner
        if isinstance(node, ast.Assign) \
                and isinstance(node.value, ast.Name) \
                and node.value.id == var and all(
                    isinstance(t, (ast.Attribute, ast.Subscript))
                    for t in node.targets):
            return True              # stored on an object / registry
    return False


def _leak_findings(index: PackageIndex, module: ModuleInfo,
                   parents) -> List[Finding]:
    out: List[Finding] = []
    for node in ast.walk(module.tree):
        if not isinstance(node, ast.Call):
            continue
        kind = _acquisition_kind(node)
        if kind is None:
            continue
        parent = parents.get(id(node))
        if isinstance(parent, ast.withitem):
            continue
        if not isinstance(parent, ast.Assign):
            continue                 # expression use: escapes or dies
        if not (len(parent.targets) == 1
                and isinstance(parent.targets[0], ast.Name)):
            continue                 # attribute store / unpack: escapes
        var = parent.targets[0].id
        fi = _enclosing_function(index, parents, node)
        if fi is None:
            continue
        if _released_in_finally(fi.node, var) \
                or _escapes(fi.node, var, node):
            continue
        out.append(Finding(
            rule="res-leaked-handle", path=module.relpath,
            line=node.lineno, col=node.col_offset,
            message="%s '%s' has no with block or finally-reachable "
                    "release — an exception before close() leaks it"
                    % (kind, var),
            context=fi.qualname))
    return out


# -- err-terminal-outcome ----------------------------------------------------

_REQ_TERMINAL = {"_resolve", "resolve", "reject", "set_result",
                 "set_exception", "cancel", "fail"}


class _OutcomeFlow:
    """All-paths coverage of one request variable ``v`` over a
    statement list: every path must perform a terminal/handoff action
    on ``v`` or end in raise/continue/break.  ``if v.done()`` guards
    and ``v is None`` null-guards exempt the corresponding branch.

    States are path-sensitive: ``U`` (not yet assigned — a path may
    exit freely), ``L`` (live and unresolved — exiting here is the
    hung-request bug), ``C`` (covered by a terminal outcome or a
    handoff)."""

    def __init__(self, var: str):
        self.var = var
        self.any_action = False
        self.endings: List[int] = []

    # -- action predicates ------------------------------------------
    def _action_in(self, node) -> bool:
        found = False
        for n in ast.walk(node):
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.Lambda)):
                continue
            if isinstance(n, ast.Call):
                f = n.func
                if isinstance(f, ast.Attribute) \
                        and isinstance(f.value, ast.Name) \
                        and f.value.id == self.var \
                        and f.attr in _REQ_TERMINAL:
                    found = True
                for a in list(n.args) + [kw.value for kw in n.keywords]:
                    tgt = a.value if isinstance(a, ast.Starred) else a
                    if isinstance(tgt, ast.Name) and tgt.id == self.var:
                        found = True
            if isinstance(n, ast.Assign) \
                    and isinstance(n.value, ast.Name) \
                    and n.value.id == self.var and all(
                        isinstance(t, (ast.Attribute, ast.Subscript))
                        for t in n.targets):
                found = True
        if found:
            self.any_action = True
        return found

    def _test_guard(self, test) -> Optional[str]:
        """'done' for a v.done() test, 'isnone'/'notnone' for null
        guards, else None."""
        node = test
        neg = False
        while isinstance(node, ast.UnaryOp) \
                and isinstance(node.op, ast.Not):
            neg = not neg
            node = node.operand
        if isinstance(node, ast.Call) \
                and isinstance(node.func, ast.Attribute) \
                and isinstance(node.func.value, ast.Name) \
                and node.func.value.id == self.var \
                and node.func.attr == "done":
            return "done"
        if isinstance(node, ast.Compare) and len(node.ops) == 1 \
                and isinstance(node.left, ast.Name) \
                and node.left.id == self.var \
                and isinstance(node.comparators[0], ast.Constant) \
                and node.comparators[0].value is None:
            isnone = isinstance(node.ops[0], ast.Is)
            if neg:
                isnone = not isnone
            return "isnone" if isnone else "notnone"
        return None

    def _is_birth(self, stmt) -> bool:
        """``v = PendingRequest(...)`` — the point an unassigned var
        becomes live."""
        if not (isinstance(stmt, ast.Assign) and len(stmt.targets) == 1
                and isinstance(stmt.targets[0], ast.Name)
                and stmt.targets[0].id == self.var
                and isinstance(stmt.value, ast.Call)):
            return False
        parts = call_target_parts(stmt.value)
        return bool(parts) and parts[-1] == "PendingRequest"

    # -- CFG walk -----------------------------------------------------
    def flow(self, stmts, states: Set[str]) -> Set[str]:
        """Returns the states reaching the end of ``stmts`` (empty set:
        no fall-through).  ``L``-state path ends are recorded in
        ``self.endings``."""
        for stmt in stmts:
            if not states:
                return states
            if isinstance(stmt, (ast.Return,)):
                covered = "L" not in states
                if self._action_in(stmt):
                    covered = True
                if not covered and isinstance(stmt.value, ast.Name) \
                        and stmt.value.id == self.var:
                    covered = True   # hand the request back to caller
                if not covered:
                    self.endings.append(stmt.lineno)
                return set()
            if isinstance(stmt, (ast.Raise, ast.Continue, ast.Break)):
                return set()
            if self._is_birth(stmt):
                states = {"L"}
                continue
            if isinstance(stmt, ast.If):
                guard = self._test_guard(stmt.test)
                if guard == "done":
                    # whichever branch corresponds to done=True needs
                    # nothing more; treat the whole If as satisfied —
                    # but still record actions inside it (any_action
                    # must see a sibling path that resolves)
                    self._action_in(stmt)
                    states = {"C"}
                    continue
                if self._action_in(stmt.test):
                    states = {"C"}
                then_in = states
                else_in = states
                if guard == "isnone":
                    then_in = {"C"}      # v is None: nothing to resolve
                elif guard == "notnone":
                    else_in = {"C"}
                t_out = self.flow(stmt.body, set(then_in))
                e_out = self.flow(stmt.orelse, set(else_in)) \
                    if stmt.orelse else set(else_in)
                states = t_out | e_out
                continue
            if isinstance(stmt, (ast.For, ast.AsyncFor)):
                if self._action_in(stmt.iter):
                    states = {"C"}
                body_out = self.flow(stmt.body, set(states))
                if stmt.orelse:
                    body_out |= self.flow(stmt.orelse, set(states))
                states = states | body_out
                continue
            if isinstance(stmt, ast.While):
                if self._action_in(stmt.test):
                    states = {"C"}
                body_out = self.flow(stmt.body, set(states))
                states = states | body_out
                continue
            if isinstance(stmt, ast.Try):
                b_out = self.flow(stmt.body, set(states))
                h_out: Set[str] = set()
                for h in stmt.handlers:
                    if self._action_in(h):
                        h_out |= {"C"}
                        continue
                    h_out |= self.flow(h.body, set(states))
                o_out = self.flow(stmt.orelse, set(b_out)) \
                    if stmt.orelse else b_out
                merged = o_out | h_out
                if stmt.finalbody:
                    merged = self.flow(stmt.finalbody, set(merged))
                states = merged
                continue
            if isinstance(stmt, (ast.With, ast.AsyncWith)):
                for item in stmt.items:
                    if self._action_in(item.context_expr):
                        states = {"C"}
                states = self.flow(stmt.body, set(states))
                continue
            if self._action_in(stmt):
                states = {"C"}
        return states


def _request_vars(fi) -> Dict[str, object]:
    """{var: scope} — scope is the For node for loop vars, else the
    function itself.  A var is request-bearing when a terminal-outcome
    method OR the first-write-wins ``done()`` guard is called on it, or
    it is assigned from a PendingRequest constructor.  Tracking via
    ``done()`` matters for the seeded-bug class: a copy with the
    resolve call DELETED still guards on ``done()`` and must be
    caught."""
    vars_: Dict[str, object] = {}
    for node in ast.walk(fi.node):
        if isinstance(node, ast.Call) \
                and isinstance(node.func, ast.Attribute) \
                and isinstance(node.func.value, ast.Name) \
                and (node.func.attr in _REQ_TERMINAL
                     or node.func.attr == "done"):
            vars_.setdefault(node.func.value.id, None)
        if isinstance(node, ast.Assign) \
                and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name) \
                and isinstance(node.value, ast.Call):
            parts = call_target_parts(node.value)
            if parts and parts[-1] == "PendingRequest":
                vars_.setdefault(node.targets[0].id, None)
    # bind loop vars to their loops (innermost scope wins)
    for node in ast.walk(fi.node):
        if isinstance(node, (ast.For, ast.AsyncFor)):
            targets = [node.target] if isinstance(node.target, ast.Name) \
                else [e for e in ast.walk(node.target)
                      if isinstance(e, ast.Name)]
            for t in targets:
                if t.id in vars_:
                    vars_[t.id] = node
    return vars_


def _terminal_findings(index: PackageIndex,
                       module: ModuleInfo) -> List[Finding]:
    if "PendingRequest" not in module.source:
        return []
    out: List[Finding] = []
    for fi in index.functions_in(module):
        if isinstance(fi.node, ast.Lambda):
            continue
        for var, scope in _request_vars(fi).items():
            flow = _OutcomeFlow(var)
            if scope is not None:                    # loop var
                body, anchor = scope.body, scope.lineno
                ends = flow.flow(body, {"L"})
            else:
                # ctor-assigned locals start unassigned ("U"): a path
                # that exits before the request exists owes nothing;
                # params are live from entry
                body, anchor = fi.node.body, fi.node.lineno
                init = "L" if var in fi.param_names() else "U"
                ends = flow.flow(body, {init})
            if "L" in ends:
                flow.endings.append(body[-1].lineno if body else anchor)
            if flow.any_action and flow.endings:
                out.append(Finding(
                    rule="err-terminal-outcome", path=module.relpath,
                    line=anchor, col=0,
                    message="request '%s' can exit without a terminal "
                            "outcome (resolve/reject/timeout/error) on "
                            "a path ending near line %d"
                            % (var, min(flow.endings)),
                    context=fi.qualname))
    return out


# -- err-incident-trigger ----------------------------------------------------

_FAILURE_EVENT = re.compile(r"(_failed|failed|giveup|give_up|"
                            r"quarantine)$")


def _dumps_incident(index: PackageIndex, fi, depth: int = 3) -> bool:
    seen: Set[int] = set()
    frontier = [fi]
    for _ in range(depth):
        nxt = []
        for f in frontier:
            if f is None or id(f.node) in seen:
                continue
            seen.add(id(f.node))
            for node in ast.walk(f.node):
                if isinstance(node, ast.Call) and \
                        call_target_parts(node)[-1:] == \
                        ("dump_incident",):
                    return True
            for cs in index.calls_in_scope(f):
                if cs.callee is not None:
                    nxt.append(cs.callee)
        frontier = nxt
        if not frontier:
            break
    return False


def _incident_findings(index: PackageIndex,
                       module: ModuleInfo) -> List[Finding]:
    # the recorder itself journals its own dump_failed and must not
    # recurse into another dump
    for node in module.tree.body:
        if isinstance(node, ast.FunctionDef) \
                and node.name == "dump_incident":
            return []
    out: List[Finding] = []
    for cs in index.calls_in(module):
        parts = call_target_parts(cs.node)
        if not parts or parts[-1] != "event":
            continue
        if len(cs.node.args) < 2:
            continue
        name = cs.node.args[1]
        if not (isinstance(name, ast.Constant)
                and isinstance(name.value, str)
                and _FAILURE_EVENT.search(name.value)):
            continue
        if cs.scope is not None and _dumps_incident(index, cs.scope):
            continue
        out.append(Finding(
            rule="err-incident-trigger", path=module.relpath,
            line=cs.node.lineno, col=cs.node.col_offset,
            message="journals terminal failure event '%s' but "
                    "flight_recorder.dump_incident is unreachable — "
                    "the incident-trigger matrix "
                    "(docs/OBSERVABILITY.md) loses this postmortem"
                    % name.value,
            context=cs.scope.qualname if cs.scope else "<module>"))
    return out


# -- entry -------------------------------------------------------------------

def check(module: ModuleInfo, index: PackageIndex) -> List[Finding]:
    cached = getattr(index, "_errorflow_findings", None)
    if cached is None:
        cached = {}
        for m in index.modules:
            if not _is_interesting(m):
                continue
            parents = _parents(m.tree)
            fs = (_swallowed_findings(index, m, parents)
                  + _nonatomic_findings(index, m, parents)
                  + _leak_findings(index, m, parents)
                  + _terminal_findings(index, m)
                  + _incident_findings(index, m))
            for f in fs:
                cached.setdefault(f.path, []).append(f)
        index._errorflow_findings = cached
    return list(cached.get(module.relpath, ()))
