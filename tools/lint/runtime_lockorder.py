"""Runtime lock-order sanitizer: the dynamic half of ``conc-lock-order``.

``LockOrderSanitizer`` is a context manager that replaces
``threading.Lock``/``threading.RLock`` with recording wrappers for the
duration of a designated stress test.  Every lock **created inside the
scope** is attributed to its creation site (the first stack frame under
the repo root), and every acquisition taken while the same thread
already holds other sanitized locks records an observed
acquisition-order edge ``held-site -> acquired-site``.

The contract mirrors PR 6's static-vs-runtime HBM cross-check:

* the **observed** graph restricted to statically-known lock sites must
  be a *subgraph* of the static graph
  (:func:`tools.lint.concurrency.static_lock_graph`) — if the runtime
  ever witnesses a nesting the analyzer did not derive, either the code
  grew an unmodeled acquisition path or the analyzer regressed;
* a **cycle** in the observed graph is a hard failure regardless of
  what the static side knows — two threads really did acquire the same
  locks in opposite orders.

Locks created before entering the scope (module-level locks like
``telemetry._lock``) are not wrapped — the sanitizer sees the locks the
scenario under test creates (prefetcher/queue/event internals, fixture
locks), which is exactly the surface a stress test exercises.  Each
newly observed edge is journaled as a ``lockorder/observed`` telemetry
event (rendered by ``tools/parse_log.py --jsonl``).

Usage::

    from tools.lint.runtime_lockorder import LockOrderSanitizer
    from tools.lint.concurrency import static_lock_graph

    with LockOrderSanitizer() as san:
        ...drive the threaded scenario...
    san.assert_no_cycles()
    san.assert_subgraph_of(static_lock_graph(["mxnet_tpu"]))
"""
from __future__ import annotations

import os
import sys
import threading
from typing import Dict, List, Optional, Set, Tuple

from .core import _repo_root


class _SanitizedLock:
    """Transparent wrapper over a real lock that reports acquisitions
    to its sanitizer.  Compatible with ``threading.Condition``'s duck
    typing (``acquire``/``release``/``__enter__``/``__exit__``; RLock
    extras delegate through ``__getattr__``)."""

    def __init__(self, inner, san: "LockOrderSanitizer",
                 site: Optional[str], reentrant: bool):
        self._inner = inner
        self._san = san
        self._site = site
        self._reentrant = reentrant

    def acquire(self, blocking=True, timeout=-1):
        got = self._inner.acquire(blocking, timeout)
        if got:
            self._san._acquired(self)
        return got

    def release(self):
        self._inner.release()
        self._san._released(self)

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def locked(self):
        return self._inner.locked()

    def _at_fork_reinit(self):
        self._inner._at_fork_reinit()

    def __getattr__(self, name):
        # RLock internals Condition probes for (_release_save,
        # _acquire_restore, _is_owned) resolve here iff the inner lock
        # has them — hasattr() keeps working for plain Locks
        return getattr(self._inner, name)

    def __repr__(self):
        return "<SanitizedLock %s %r>" % (self._site or "<anon>",
                                          self._inner)


class LockOrderSanitizer:
    """Record the lock-acquisition-order graph of a threaded scenario.

    ``repo_root``: creation frames under this directory become lock
    sites (``relpath:line``); everything else (stdlib ``queue``
    internals, test harness frames outside the repo) stays anonymous —
    anonymous locks participate in cycle detection but are excluded
    from the static-subgraph comparison.
    """

    def __init__(self, repo_root: Optional[str] = None,
                 telemetry_events: bool = True):
        self.repo_root = os.path.abspath(repo_root or _repo_root())
        self.telemetry_events = telemetry_events
        # (src_site, dst_site) -> acquisition count; sites are
        # "relpath:line" or "<anon:N>" for out-of-repo creations
        self.edges: Dict[Tuple[str, str], int] = {}
        self.lock_sites: Dict[str, int] = {}     # site -> locks created
        self._held = threading.local()
        self._orig: Optional[tuple] = None
        self._reclock = threading.Lock()         # created UNWRAPPED
        self._anon = 0
        self._active = False

    # -- patching -------------------------------------------------------
    def __enter__(self):
        if self._active:
            raise RuntimeError("LockOrderSanitizer is not reentrant")
        self._orig = (threading.Lock, threading.RLock)

        def make_lock():
            return self._wrap(self._orig[0](), reentrant=False)

        def make_rlock():
            return self._wrap(self._orig[1](), reentrant=True)

        threading.Lock = make_lock
        threading.RLock = make_rlock
        self._active = True
        return self

    def __exit__(self, *exc):
        threading.Lock, threading.RLock = self._orig
        self._active = False
        return False

    # -- recording ------------------------------------------------------
    def _creation_site(self) -> Optional[str]:
        f = sys._getframe(2)
        skip = (os.path.abspath(__file__),)
        while f is not None:
            fn = f.f_code.co_filename
            if not fn.startswith("<") and os.path.abspath(fn) not in skip \
                    and not fn.endswith(("threading.py", "queue.py")):
                path = os.path.abspath(fn)
                if path.startswith(self.repo_root + os.sep):
                    rel = os.path.relpath(path, self.repo_root)
                    return "%s:%d" % (rel.replace(os.sep, "/"), f.f_lineno)
                return None
            f = f.f_back
        return None

    def _wrap(self, inner, reentrant: bool) -> _SanitizedLock:
        site = self._creation_site()
        if site is None:
            with self._reclock:
                self._anon += 1
                site = "<anon:%d>" % self._anon
        else:
            with self._reclock:
                self.lock_sites[site] = self.lock_sites.get(site, 0) + 1
        return _SanitizedLock(inner, self, site, reentrant)

    def _stack(self) -> List[_SanitizedLock]:
        st = getattr(self._held, "stack", None)
        if st is None:
            st = self._held.stack = []
        return st

    def _acquired(self, lock: _SanitizedLock):
        st = self._stack()
        new = []
        for held in st:
            if held is lock:        # RLock re-entry: no self-edge
                continue
            if held._site != lock._site:
                new.append((held._site, lock._site))
        st.append(lock)
        if new:
            with self._reclock:
                fresh = [e for e in new if e not in self.edges]
                for e in new:
                    self.edges[e] = self.edges.get(e, 0) + 1
            if fresh and self.telemetry_events:
                try:
                    from mxnet_tpu import telemetry
                    for src, dst in fresh:
                        telemetry.event("lockorder", "observed",
                                        src=src, dst=dst)
                except Exception:
                    pass

    def _released(self, lock: _SanitizedLock):
        st = self._stack()
        for i in range(len(st) - 1, -1, -1):
            if st[i] is lock:
                del st[i]
                break

    # -- queries / assertions -------------------------------------------
    def observed_edges(self, repo_only: bool = False
                       ) -> Set[Tuple[str, str]]:
        with self._reclock:
            edges = set(self.edges)
        if repo_only:
            edges = {(a, b) for a, b in edges
                     if not a.startswith("<anon") and
                     not b.startswith("<anon")}
        return edges

    def cycles(self) -> List[List[str]]:
        """Cycles in the observed graph (each as a site list with the
        start repeated at the end)."""
        succ: Dict[str, Set[str]] = {}
        for a, b in self.observed_edges():
            succ.setdefault(a, set()).add(b)
        out, state = [], {}

        def dfs(node, path):
            state[node] = 1
            path.append(node)
            for nxt in sorted(succ.get(node, ())):
                if state.get(nxt) == 1:
                    out.append(path[path.index(nxt):] + [nxt])
                elif state.get(nxt) is None:
                    dfs(nxt, path)
            path.pop()
            state[node] = 2

        for node in sorted(succ):
            if state.get(node) is None:
                dfs(node, [])
        return out

    def assert_no_cycles(self):
        cyc = self.cycles()
        assert not cyc, (
            "runtime lock-order cycle observed (threads acquired the "
            "same locks in opposite orders):\n  "
            + "\n  ".join(" -> ".join(c) for c in cyc))

    def assert_subgraph_of(self, static_graph: dict):
        """Every observed edge between two statically-known lock sites
        must exist in ``static_graph`` (the
        :func:`tools.lint.concurrency.static_lock_graph` result) — the
        runtime graph is a subgraph of the derived one."""
        known = set(static_graph.get("locks", ()))
        static_edges = set(static_graph.get("edges", ()))
        missing = [(a, b) for a, b in self.observed_edges(repo_only=True)
                   if a in known and b in known
                   and (a, b) not in static_edges]
        assert not missing, (
            "runtime observed lock-order edges the static analyzer did "
            "not derive (analyzer gap or unmodeled acquisition path):\n  "
            + "\n  ".join("%s -> %s" % e for e in sorted(missing)))
