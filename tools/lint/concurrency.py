"""concurrency checker: host-thread race & deadlock analysis.

The host side of this stack is genuinely threaded — the depth-K
prefetch feeder (``io/device_prefetch.py``), the per-child producer
threads of ``PrefetchingIter``, the ``mxtpu-heartbeat`` liveness
publisher (``kvstore.py``) and the telemetry journal they all write
into — and its failure modes (a torn shared write, a lock-order
inversion, a daemon thread that outlives its owner) are invisible to
the jit-centric rule families.  This checker partitions every scanned
function into *thread context* (reachable from a
``threading.Thread(target=...)`` entry — see
``PackageIndex.thread_entries``) vs *main context* and checks:

* ``conc-unguarded-shared-write`` — an attribute/module-global written
  from thread context and read or written from main context with no
  common ``Lock``/``RLock``/``Condition`` guard on both sides.
  Allowlisted by design: synchronization objects themselves
  (``Event``/``Queue``/``Semaphore``/``deque(maxlen=...)`` — their
  methods are atomic), and immutable-constant rebinds (a
  ``self._done = True`` stop flag is GIL-atomic);
* ``conc-lock-order`` — the static lock-acquisition graph (``with
  lock:`` nesting, interprocedural through the call tables via a
  may-held-at-entry pass) contains a cycle: two call paths acquire the
  same locks in opposite orders — the classic ABBA deadlock.  The same
  graph is exported by :func:`static_lock_graph` and cross-checked at
  runtime by ``tools.lint.runtime_lockorder``;
* ``conc-blocking-under-lock`` — a blocking call (``queue.get/put``,
  ``Event.wait``, ``Thread.join``, ``time.sleep``,
  ``block_until_ready``) reachable while a lock is held (must-held,
  lexically or at every call site) — it turns the lock into a
  convoy/deadlock seed;
* ``conc-thread-lifecycle`` — a started thread with no paired
  stop-signal (an ``Event.set()``) + ``join`` reachable from any
  shutdown path (``close``/``stop``/``reset``/``__del__``/... or an
  ``atexit.register``/``weakref.finalize`` callee) — the thread
  outlives its owner or the join hangs forever;
* ``conc-condition-wait-unlooped`` — ``Condition.wait()`` outside a
  ``while`` recheck loop (spurious wakeups make a plain ``if``/linear
  wait incorrect; ``wait_for`` loops internally and is exempt).
"""
from __future__ import annotations

import ast
from typing import Dict, List, Optional, Sequence, Set, Tuple

from .core import Finding, ModuleInfo
from .jitgraph import (PackageIndex, FunctionInfo, call_target_name,
                       call_target_parts)

RULES = {
    "conc-unguarded-shared-write":
        "attribute/global written from a thread-entry context and "
        "accessed from main context with no common lock guard on both "
        "sides",
    "conc-lock-order":
        "cycle in the static lock-acquisition graph (with-lock nesting, "
        "interprocedural) — ABBA deadlock shape",
    "conc-blocking-under-lock":
        "blocking call (queue.get/put, Event.wait, Thread.join, "
        "time.sleep, block_until_ready) reachable while a lock is held",
    "conc-thread-lifecycle":
        "started thread with no paired stop-signal + join on any "
        "close/shutdown/__del__ path",
    "conc-condition-wait-unlooped":
        "Condition.wait() outside a while recheck loop (spurious "
        "wakeups break if/linear waits)",
}

# constructor name -> type tag (threading.X / queue.X / collections)
_LOCK_CTORS = {"Lock": "lock", "RLock": "rlock", "Condition": "condition"}
_SYNC_CTORS = {"Event": "event", "Semaphore": "sync",
               "BoundedSemaphore": "sync", "Barrier": "sync",
               "Queue": "queue", "LifoQueue": "queue",
               "PriorityQueue": "queue", "SimpleQueue": "queue",
               "local": "sync", "Thread": "thread"}

# mutation methods that count as a WRITE to the receiver object
_MUTATORS = {"append", "appendleft", "extend", "insert", "pop", "popleft",
             "remove", "clear", "update", "add", "discard", "setdefault",
             "popitem"}

# functions whose bodies count as shutdown paths for the lifecycle rule
_SHUTDOWN_NAMES = {"close", "stop", "shutdown", "stop_and_join",
                   "terminate", "reset", "detach", "join", "__del__",
                   "__exit__", "finalize"}

_CONST_UNARY = (ast.USub, ast.UAdd, ast.Not)


def _is_const_expr(node: ast.expr) -> bool:
    if isinstance(node, ast.Constant):
        return True
    if isinstance(node, ast.UnaryOp) and isinstance(node.op, _CONST_UNARY):
        return _is_const_expr(node.operand)
    return False


def _ctor_tag(node: ast.expr) -> Optional[str]:
    """Type tag when ``node`` constructs a threading/queue sync object
    (``threading.Lock()``, ``queue.Queue()``, ``deque(maxlen=...)``)."""
    if not isinstance(node, ast.Call):
        return None
    name = call_target_name(node)
    if name in _LOCK_CTORS:
        return _LOCK_CTORS[name]
    if name in _SYNC_CTORS:
        return _SYNC_CTORS[name]
    if name == "deque" and any(k.arg == "maxlen" and
                               not (isinstance(k.value, ast.Constant)
                                    and k.value.value is None)
                               for k in node.keywords):
        return "deque_maxlen"
    return None


def _enclosing_class(fi: FunctionInfo) -> Optional[str]:
    s = fi
    while s is not None:
        if s.cls is not None:
            return s.cls
        s = s.parent
    return None


# a module with none of these tokens cannot create locks/threads/queues;
# its functions are skipped by the (expensive) lexical walk.  Shared-var
# keys are module-local (self.X attrs, module globals), so rule coverage
# is unaffected; the one approximation is a helper in a non-threading
# module blocking under a lock held by its cross-module caller.
_INTERESTING_TOKENS = ("threading", "Thread", "Queue", "deque",
                       "Semaphore", "Condition")


def _is_interesting(module) -> bool:
    return any(tok in module.source for tok in _INTERESTING_TOKENS)


class _Conc:
    """Whole-package concurrency model, built once per PackageIndex."""

    def __init__(self, index: PackageIndex):
        self.index = index
        self.thread_fns = index.thread_reachable()
        self.interesting = {m.relpath for m in index.modules
                            if _is_interesting(m)}
        # var key -> set of ctor tags / creation sites / rebind flags
        self.var_tags: Dict[tuple, Set[str]] = {}
        self.var_sites: Dict[tuple, List[Tuple[str, int]]] = {}
        self.var_rebound: Set[tuple] = set()
        # per-module global names (module-level single-name assigns)
        self.module_globals: Dict[str, Set[str]] = {}
        for m in index.modules:
            names: Set[str] = set()
            for stmt in m.tree.body:
                for t in getattr(stmt, "targets", []) or \
                        ([stmt.target] if isinstance(
                            stmt, (ast.AnnAssign, ast.AugAssign)) else []):
                    if isinstance(t, ast.Name):
                        names.add(t.id)
            self.module_globals[m.relpath] = names
        # walk products
        self.accesses: List[dict] = []       # shared-var accesses
        self.acquisitions: List[dict] = []   # with-lock acquisitions
        self.blocking: List[dict] = []       # blocking calls + held set
        self.cond_waits: List[dict] = []     # Condition.wait sites
        self.callsite_held: Dict[int, frozenset] = {}
        self.fn_locals: Dict[int, Set[str]] = {}
        self.fn_globals_decl: Dict[int, Set[str]] = {}
        self._collect_var_types()
        for fi in index.functions:
            if fi.module.relpath in self.interesting:
                self._prepare_fn(fi)
        for fi in index.functions:
            if not isinstance(fi.node, ast.Lambda) and \
                    fi.module.relpath in self.interesting:
                self._walk_fn(fi)
        self._compute_entry_held()
        self._build_edges()

    # -- var typing -----------------------------------------------------
    def _iter_assigns(self):
        """(fi_or_None, target, value, module) over every assignment in
        an interesting module."""
        for m in self.index.modules:
            if m.relpath not in self.interesting:
                continue
            for stmt in m.tree.body:
                if isinstance(stmt, ast.Assign):
                    for t in stmt.targets:
                        yield None, t, stmt.value, m
                elif isinstance(stmt, ast.AnnAssign) and stmt.value:
                    yield None, stmt.target, stmt.value, m
        for fi in self.index.functions:
            if isinstance(fi.node, ast.Lambda) or \
                    fi.module.relpath not in self.interesting:
                continue
            for stmt in self.index.shallow_nodes(fi):
                if isinstance(stmt, ast.Assign):
                    for t in stmt.targets:
                        yield fi, t, stmt.value, fi.module
                elif isinstance(stmt, ast.AnnAssign) and stmt.value:
                    yield fi, stmt.target, stmt.value, fi.module

    def _target_key(self, fi: Optional[FunctionInfo], t: ast.expr,
                    module) -> Optional[tuple]:
        if isinstance(t, ast.Attribute) and \
                isinstance(t.value, ast.Name) and \
                t.value.id in ("self", "cls") and fi is not None:
            cls = _enclosing_class(fi)
            if cls is not None:
                return ("attr", module.relpath, cls, t.attr)
            return None
        if isinstance(t, ast.Name):
            if fi is None:
                return ("global", module.relpath, t.id)
            if t.id in self.fn_globals_decl.get(id(fi.node), ()):
                return ("global", module.relpath, t.id)
            return ("local", id(fi.node), t.id)
        return None

    def _collect_var_types(self):
        # global declarations must be known before classifying targets
        for fi in self.index.functions:
            if isinstance(fi.node, ast.Lambda) or \
                    fi.module.relpath not in self.interesting:
                continue
            decl: Set[str] = set()
            for n in self.index.shallow_nodes(fi):
                if isinstance(n, ast.Global):
                    decl.update(n.names)
            self.fn_globals_decl[id(fi.node)] = decl
        for fi, t, value, module in self._iter_assigns():
            key = self._target_key(fi, t, module)
            if key is None:
                continue
            tag = _ctor_tag(value)
            if tag is not None:
                self.var_tags.setdefault(key, set()).add(tag)
                self.var_sites.setdefault(key, []).append(
                    (module.relpath, value.lineno))
            elif fi is not None and fi.name != "__init__":
                self.var_rebound.add(key)

    def is_sync_object(self, key: tuple) -> bool:
        """Allowlist for the shared-write rule: the var IS a
        synchronization / thread-safe container, consistently."""
        tags = self.var_tags.get(key)
        return bool(tags) and key not in self.var_rebound

    def resolve_var(self, fi: Optional[FunctionInfo], node: ast.expr
                    ) -> Optional[tuple]:
        """Var key for an expression used as a receiver/lock."""
        if isinstance(node, ast.Attribute) and \
                isinstance(node.value, ast.Name) and \
                node.value.id in ("self", "cls") and fi is not None:
            cls = _enclosing_class(fi)
            if cls is not None:
                return ("attr", fi.module.relpath, cls, node.attr)
            return None
        if isinstance(node, ast.Name) and fi is not None:
            s = fi
            while s is not None:
                k = ("local", id(s.node), node.id)
                if k in self.var_tags:
                    return k
                if node.id in self.fn_locals.get(id(s.node), ()) and \
                        node.id not in self.fn_globals_decl.get(
                            id(s.node), ()):
                    return ("local", id(s.node), node.id)
                s = s.parent
            if node.id in self.module_globals.get(fi.module.relpath, ()):
                return ("global", fi.module.relpath, node.id)
        return None

    def var_tag(self, fi, node) -> Optional[str]:
        key = self.resolve_var(fi, node)
        tags = self.var_tags.get(key, ()) if key is not None else ()
        if not tags and isinstance(node, ast.Name) and fi is not None:
            # untyped local: chase its binding (`t, q = self._thread,
            # self._q` — the local carries the attr's type)
            chased = _chase_local(self.index, fi, node.id)
            if chased is not None and not isinstance(chased, ast.Name):
                key = self.resolve_var(fi, chased)
                if key is not None:
                    tags = self.var_tags.get(key, ())
        return next(iter(tags)) if len(tags) == 1 else None

    def resolve_lock(self, fi, node) -> Optional[tuple]:
        key = self.resolve_var(fi, node)
        if key is not None and \
                self.var_tags.get(key, set()) & {"lock", "rlock",
                                                 "condition"}:
            return key
        return None

    # -- per-function lexical walk --------------------------------------
    def _prepare_fn(self, fi: FunctionInfo):
        if isinstance(fi.node, ast.Lambda):
            self.fn_locals[id(fi.node)] = set()
            return
        bound: Set[str] = set(fi.param_names()) | set(fi.kwonly_names())
        a = fi.node.args
        if a.vararg:
            bound.add(a.vararg.arg)
        if a.kwarg:
            bound.add(a.kwarg.arg)
        for n in self.index.shallow_nodes(fi):
            if isinstance(n, ast.Name) and \
                    isinstance(n.ctx, (ast.Store, ast.Del)):
                bound.add(n.id)
            elif isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.ClassDef)):
                bound.add(n.name)
        self.fn_locals[id(fi.node)] = bound

    def _walk_fn(self, fi: FunctionInfo):
        body = fi.node.body if not isinstance(fi.node, ast.Lambda) else []
        for stmt in body:
            self._walk(fi, stmt, frozenset(), False)

    def _walk(self, fi, node, held, in_while):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda, ast.ClassDef)):
            return
        if isinstance(node, (ast.With, ast.AsyncWith)):
            new_held = held
            for item in node.items:
                self._walk(fi, item.context_expr, new_held, in_while)
                lid = self.resolve_lock(fi, item.context_expr)
                if lid is not None:
                    self.acquisitions.append({
                        "lock": lid, "fi": fi,
                        "line": item.context_expr.lineno,
                        "col": node.col_offset, "held": new_held})
                    new_held = new_held | {lid}
            for stmt in node.body:
                self._walk(fi, stmt, new_held, in_while)
            return
        if isinstance(node, (ast.While,)):
            self._walk(fi, node.test, held, in_while)
            for stmt in node.body + node.orelse:
                self._walk(fi, stmt, held, True)
            return
        # statement-level write detection; an AugAssign is a
        # read-modify-write, never an atomic constant rebind
        if isinstance(node, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
            targets = node.targets if isinstance(node, ast.Assign) \
                else [node.target]
            value = node.value if not isinstance(node, ast.AugAssign) \
                else None
            if value is not None or isinstance(node, ast.AugAssign):
                for t in targets:
                    self._record_write(fi, t, value, held)
        if isinstance(node, ast.Call):
            self._record_call(fi, node, held, in_while)
            if isinstance(node.func, ast.expr):
                self.callsite_held[id(node)] = held
        if isinstance(node, (ast.Name, ast.Attribute)) and \
                isinstance(getattr(node, "ctx", None), ast.Load):
            key = self._access_key(fi, node)
            if key is not None:
                self.accesses.append({
                    "key": key, "kind": "read", "fi": fi,
                    "line": node.lineno, "col": node.col_offset,
                    "held": held, "const": False})
        for child in ast.iter_child_nodes(node):
            self._walk(fi, child, held, in_while)

    def _access_key(self, fi, node) -> Optional[tuple]:
        """Shared-var key for a load/store expression: self.X attrs and
        module globals only (locals are thread-private)."""
        if isinstance(node, ast.Attribute) and \
                isinstance(node.value, ast.Name) and \
                node.value.id in ("self", "cls"):
            cls = _enclosing_class(fi)
            if cls is not None:
                return ("attr", fi.module.relpath, cls, node.attr)
            return None
        if isinstance(node, ast.Name):
            key = self.resolve_var(fi, node)
            if key is not None and key[0] == "global":
                return key
        return None

    def _record_write(self, fi, target, value, held):
        if isinstance(target, (ast.Tuple, ast.List)):
            for e in target.elts:
                self._record_write(fi, e, value, held)
            return
        node = target
        if isinstance(target, ast.Subscript):
            node = target.value
            value = None        # container mutation, never a pure rebind
        key = self._access_key(fi, node)
        if key is None:
            return
        self.accesses.append({
            "key": key, "kind": "write", "fi": fi,
            "line": target.lineno, "col": target.col_offset,
            "held": held,
            "const": value is not None and _is_const_expr(value)})

    def _record_call(self, fi, node: ast.Call, held, in_while):
        name = call_target_name(node)
        parts = call_target_parts(node)
        recv = node.func.value if isinstance(node.func, ast.Attribute) \
            else None
        recv_tag = self.var_tag(fi, recv) if recv is not None else None
        # mutation methods on shared containers count as writes
        if name in _MUTATORS and recv is not None:
            key = self._access_key(fi, recv)
            if key is not None:
                self.accesses.append({
                    "key": key, "kind": "write", "fi": fi,
                    "line": node.lineno, "col": node.col_offset,
                    "held": held, "const": False})
        # blocking calls.  Condition.wait releases the condition's OWN
        # lock while waiting — only OTHER held locks make it a hazard
        # (the unlooped-wait rule owns the wait itself).
        blocked = None
        held_for_block = held
        if name == "sleep" and (len(parts) == 1
                                or parts[0] in ("time", "_time")):
            blocked = "time.sleep"
        elif name == "wait" and recv_tag == "event":
            blocked = "event.wait"
        elif name in ("wait", "wait_for") and recv_tag == "condition":
            own = self.resolve_var(fi, recv)
            held_for_block = frozenset(held) - {own}
            if held_for_block:
                blocked = "condition.wait"
        elif name == "join" and recv_tag == "thread":
            blocked = "Thread.join"
        elif name in ("get", "put") and recv_tag == "queue":
            if not any(k.arg == "block" and
                       isinstance(k.value, ast.Constant) and
                       k.value.value is False for k in node.keywords):
                blocked = "queue.%s" % name
        elif name == "block_until_ready":
            blocked = "block_until_ready"
        elif name == "acquire" and recv is not None and \
                self.resolve_lock(fi, recv) is not None:
            blocked = "Lock.acquire"
        if blocked is not None:
            self.blocking.append({
                "what": blocked, "fi": fi, "line": node.lineno,
                "col": node.col_offset, "held": held_for_block})
        if name == "wait" and recv_tag == "condition":
            self.cond_waits.append({
                "fi": fi, "line": node.lineno, "col": node.col_offset,
                "in_while": in_while})

    # -- interprocedural held sets --------------------------------------
    def _entry_pass(self, combine, init):
        out: Dict[int, frozenset] = {}
        # run to convergence (the loop breaks as soon as a sweep is
        # quiet); the bound only guards against oscillation and must
        # exceed the deepest call chain a held set can propagate down
        for _ in range(len(self.index.functions) + 2):
            changed = False
            for fi in self.index.functions:
                sites = self.index._calls_by_callee.get(id(fi.node), ())
                vals = []
                for cs in sites:
                    h = self.callsite_held.get(id(cs.node))
                    if h is None:
                        vals.append(frozenset())
                        continue
                    caller = out.get(id(cs.scope.node), init) \
                        if cs.scope is not None else frozenset()
                    vals.append(h | caller)
                new = combine(vals) if vals else frozenset()
                if out.get(id(fi.node), init) != new:
                    out[id(fi.node)] = new
                    changed = True
            if not changed:
                break
        return out

    def _compute_entry_held(self):
        # must-held: a lock credited as a guard must be held on EVERY
        # path into the function; may-held over-approximates for the
        # lock-order graph
        self.must_entry = self._entry_pass(
            lambda vs: frozenset.intersection(*vs), frozenset())
        self.may_entry = self._entry_pass(
            lambda vs: frozenset.union(*vs), frozenset())

    def effective_held(self, rec, must=True) -> frozenset:
        table = self.must_entry if must else self.may_entry
        return rec["held"] | table.get(id(rec["fi"].node), frozenset())

    # -- lock-order graph ------------------------------------------------
    def _build_edges(self):
        self.edges: Dict[Tuple[tuple, tuple], List[dict]] = {}
        for acq in self.acquisitions:
            held = acq["held"] | self.may_entry.get(
                id(acq["fi"].node), frozenset())
            for h in held:
                if h == acq["lock"]:
                    continue
                self.edges.setdefault((h, acq["lock"]), []).append(acq)
        # self-nesting of a plain (non-reentrant) Lock is an immediate
        # deadlock — record it as a self-edge
        for acq in self.acquisitions:
            if acq["lock"] in acq["held"] and \
                    self.var_tags.get(acq["lock"]) == {"lock"}:
                self.edges.setdefault((acq["lock"], acq["lock"]),
                                      []).append(acq)

    def cyclic_edge_sites(self) -> List[Tuple[Tuple[tuple, tuple], dict]]:
        succ: Dict[tuple, Set[tuple]] = {}
        for (a, b) in self.edges:
            succ.setdefault(a, set()).add(b)

        def reaches(src, dst):
            seen, todo = set(), [src]
            while todo:
                cur = todo.pop()
                if cur == dst:
                    return True
                if cur in seen:
                    continue
                seen.add(cur)
                todo.extend(succ.get(cur, ()))
            return False

        out = []
        for (a, b), sites in self.edges.items():
            if a == b or reaches(b, a):
                for s in sites:
                    out.append(((a, b), s))
        return out


def _conc(index: PackageIndex) -> _Conc:
    model = getattr(index, "_conc_model", None)
    if model is None:
        model = _Conc(index)
        index._conc_model = model
    return model


def _var_label(key: tuple) -> str:
    if key[0] == "attr":
        return "%s.%s" % (key[2], key[3])
    if key[0] == "global":
        return key[2]
    return key[2]


# ---------------------------------------------------------------------------
# rule passes (run ONCE over the whole package, bucketed per module —
# re-deriving them per scanned file would be O(files x accesses))
# ---------------------------------------------------------------------------

def _shared_write_findings(model: _Conc) -> List[Finding]:
    by_key: Dict[tuple, dict] = {}
    for a in model.accesses:
        fi = a["fi"]
        if fi.name == "__init__":
            continue            # construction happens-before publication
        key = a["key"]
        ent = by_key.setdefault(key, {"thread_w": [], "main": []})
        if id(fi.node) in model.thread_fns:
            if a["kind"] == "write" and not a["const"]:
                ent["thread_w"].append(a)
        else:
            ent["main"].append(a)
    out = []
    for key, ent in by_key.items():
        if not ent["thread_w"] or not ent["main"]:
            continue
        if model.is_sync_object(key):
            continue
        hit = None
        for w in sorted(ent["thread_w"], key=lambda r: (r["line"],
                                                        r["col"])):
            wg = model.effective_held(w)
            for a in sorted(ent["main"], key=lambda r: (r["line"],
                                                        r["col"])):
                if not (wg & model.effective_held(a)):
                    hit = (w, a)
                    break
            if hit:
                break
        if hit is None:
            continue
        w, a = hit
        out.append(Finding(
            "conc-unguarded-shared-write", key[1], w["line"],
            w["col"],
            "%r is written on the %s thread here but accessed from "
            "main-context %s (%s:%d) with no common lock held on both "
            "sides" % (_var_label(key), w["fi"].name, a["fi"].qualname,
                       a["fi"].module.relpath, a["line"]),
            w["fi"].qualname))
    return out


def _lock_order_findings(model: _Conc) -> List[Finding]:
    out = []
    seen = set()
    for (a, b), acq in model.cyclic_edge_sites():
        rel = acq["fi"].module.relpath
        dedup = (rel, acq["line"], a, b)
        if dedup in seen:
            continue
        seen.add(dedup)
        if a == b:
            msg = "non-reentrant lock %r re-acquired while already " \
                  "held — immediate self-deadlock" % (_var_label(a),)
        else:
            msg = "lock %r acquired while holding %r, but another " \
                  "path acquires them in the opposite order — ABBA " \
                  "deadlock" % (_var_label(b), _var_label(a))
        out.append(Finding("conc-lock-order", rel,
                           acq["line"], acq["col"], msg,
                           acq["fi"].qualname))
    return out


def _blocking_findings(model: _Conc) -> List[Finding]:
    out = []
    for rec in model.blocking:
        held = model.effective_held(rec, must=True)
        if not held:
            continue
        lock = sorted(_var_label(h) for h in held)[0]
        out.append(Finding(
            "conc-blocking-under-lock", rec["fi"].module.relpath,
            rec["line"], rec["col"],
            "%s called while lock %r is held — blocks every other "
            "thread contending for it (convoy/deadlock seed)"
            % (rec["what"], lock), rec["fi"].qualname))
    return out


def _cond_wait_findings(model: _Conc) -> List[Finding]:
    out = []
    for rec in model.cond_waits:
        if rec["in_while"]:
            continue
        out.append(Finding(
            "conc-condition-wait-unlooped", rec["fi"].module.relpath,
            rec["line"], rec["col"],
            "Condition.wait() outside a while recheck loop — spurious "
            "wakeups make the predicate unreliable; use `while not "
            "pred: cond.wait()` or wait_for()", rec["fi"].qualname))
    return out


# -- thread lifecycle --------------------------------------------------------

def _is_thread_ctor(node: ast.Call) -> bool:
    if call_target_name(node) != "Thread":
        return False
    parts = call_target_parts(node)
    return len(parts) <= 1 or parts[-2] == "threading"


def _chase_local(index, fi, name: str) -> Optional[ast.expr]:
    s = fi
    while s is not None:
        for stmt in index.shallow_nodes(s):
            if not isinstance(stmt, ast.Assign) or \
                    len(stmt.targets) != 1:
                continue
            t = stmt.targets[0]
            if isinstance(t, ast.Name) and t.id == name:
                return stmt.value
            # pairwise tuple unpack: `t, q = self._thread, self._q`
            if isinstance(t, ast.Tuple) and \
                    isinstance(stmt.value, ast.Tuple) and \
                    len(t.elts) == len(stmt.value.elts):
                for te, ve in zip(t.elts, stmt.value.elts):
                    if isinstance(te, ast.Name) and te.id == name:
                        return ve
        s = s.parent
    return None


def _handle_descriptor(model: _Conc, fi, expr) -> Optional[tuple]:
    """Normalize a thread-handle expression: ``self.X`` attrs,
    module-global names, and holder-container reads
    (``_state["thread"]`` / ``holder.get("thread")``)."""
    if isinstance(expr, ast.Attribute) and \
            isinstance(expr.value, ast.Name) and \
            expr.value.id in ("self", "cls"):
        cls = _enclosing_class(fi)
        return ("attr", fi.module.relpath, cls, expr.attr) if cls else None
    if isinstance(expr, ast.Subscript):
        base = expr.value
        if isinstance(base, ast.Name) and base.id in \
                model.module_globals.get(fi.module.relpath, ()):
            return ("holder", fi.module.relpath, base.id)
        return None
    if isinstance(expr, ast.Call) and \
            call_target_name(expr) == "get" and \
            isinstance(expr.func, ast.Attribute) and \
            isinstance(expr.func.value, ast.Name):
        base = expr.func.value
        if base.id in model.module_globals.get(fi.module.relpath, ()):
            return ("holder", fi.module.relpath, base.id)
        return None
    if isinstance(expr, ast.Name):
        key = model.resolve_var(fi, expr)
        if key is not None and key[0] == "global":
            return key
        chased = _chase_local(model.index, fi, expr.id)
        if chased is not None and not isinstance(chased, ast.Name):
            return _handle_descriptor(model, fi, chased)
    return None


def _shutdown_reachable(index: PackageIndex) -> Set[int]:
    """Function-node-ids reachable from a shutdown-path entry: a
    function named like a teardown hook, or one registered with
    ``atexit.register`` / ``weakref.finalize``."""
    roots: Set[int] = set()
    for fi in index.functions:
        if not isinstance(fi.node, ast.Lambda) and \
                fi.name in _SHUTDOWN_NAMES:
            roots.add(id(fi.node))
    for cs in index.call_sites:
        name = call_target_name(cs.node)
        cand = None
        if name == "register" and call_target_parts(cs.node)[:1] == \
                ("atexit",) and cs.node.args:
            cand = cs.node.args[0]
        elif name == "finalize" and len(cs.node.args) >= 2:
            cand = cs.node.args[1]
        if cand is not None:
            fi = index._resolve_thread_target(cs, cand)
            if fi is not None:
                roots.add(id(fi.node))
    reach = set(roots)
    changed = True
    while changed:
        changed = False
        for cs in index.call_sites:
            if cs.scope is None or id(cs.scope.node) not in reach:
                continue
            if cs.callee is not None and id(cs.callee.node) not in reach:
                reach.add(id(cs.callee.node))
                changed = True
    return reach


def _lifecycle_findings(index: PackageIndex,
                        model: _Conc) -> List[Finding]:
    shutdown = _shutdown_reachable(index)

    # joins + Event.set()s on shutdown paths, package-wide
    joins: Set[tuple] = set()
    stop_sets: Set[tuple] = set()
    for cs in index.call_sites:
        if cs.scope is None or not isinstance(cs.node.func, ast.Attribute):
            continue
        if id(cs.scope.node) not in shutdown:
            continue
        name = cs.node.func.attr
        if name == "join":
            d = _handle_descriptor(model, cs.scope, cs.node.func.value)
            if d is not None:
                joins.add(d)
        elif name == "set":
            key = model.resolve_var(cs.scope, cs.node.func.value)
            if key is not None and \
                    "event" in model.var_tags.get(key, ()):
                stop_sets.add(key)
            else:
                d = _handle_descriptor(model, cs.scope,
                                       cs.node.func.value)
                if d is not None:
                    stop_sets.add(d)

    out = []
    for fi in index.functions:
        if isinstance(fi.node, ast.Lambda) or \
                fi.module.relpath not in model.interesting:
            continue
        rel = fi.module.relpath
        for node in index.shallow_nodes(fi):
            if not (isinstance(node, ast.Call)
                    and _is_thread_ctor(node)):
                continue
            handle, started = _handle_and_started(model, fi, node)
            if not started:
                continue
            cls = _enclosing_class(fi)
            joined = handle is not None and handle in joins
            # a class-held thread needs a stop signal scoped to ITS
            # class; module-level threads accept any same-module
            # global/holder (or class) signal
            if handle is not None and handle[0] == "attr":
                stopped = any(k[0] == "attr" and k[1] == rel
                              and k[2] == handle[2] for k in stop_sets)
            else:
                stopped = any(
                    (k[0] == "attr" and cls is not None
                     and k[1] == rel and k[2] == cls)
                    or (k[0] in ("global", "holder") and k[1] == rel)
                    for k in stop_sets)
            if joined and stopped:
                continue
            if not joined:
                what = "no join() of this thread is reachable " \
                       "from any close/stop/__del__/atexit path"
            else:
                what = "no stop-signal (Event.set()) is reachable " \
                       "from any shutdown path — the join can " \
                       "hang forever"
            out.append(Finding(
                "conc-thread-lifecycle", rel,
                node.lineno, node.col_offset,
                "thread started here outlives its owner: %s"
                % what, fi.qualname))
    return out


def _handle_and_started(model: _Conc, fi,
                        ctor: ast.Call) -> Tuple[Optional[tuple], bool]:
    """(handle descriptor, started?) for a Thread construction site,
    resolved within the constructing function."""
    index = model.index
    handle = None
    local_name = None
    for stmt in index.shallow_nodes(fi):
        if isinstance(stmt, ast.Assign) and stmt.value is ctor and \
                len(stmt.targets) == 1:
            t = stmt.targets[0]
            if isinstance(t, ast.Name):
                local_name = t.id
                key = model.resolve_var(fi, t)
                if key is not None and key[0] == "global":
                    handle = key
            else:
                handle = _handle_descriptor(model, fi, t) or \
                    (model._target_key(fi, t, fi.module)
                     if isinstance(t, ast.Attribute) else None)
    started = False
    for stmt in index.shallow_nodes(fi):
        if not isinstance(stmt, ast.Call) or \
                not isinstance(stmt.func, ast.Attribute) or \
                stmt.func.attr != "start":
            continue
        recv = stmt.func.value
        if recv is ctor:
            started = True
        elif isinstance(recv, ast.Name) and recv.id == local_name:
            started = True
        elif handle is not None and \
                _handle_descriptor(model, fi, recv) == handle:
            started = True
    if local_name is not None:
        # promotion of the local into an attr/global/holder
        for stmt in index.shallow_nodes(fi):
            if isinstance(stmt, ast.Assign) and \
                    isinstance(stmt.value, ast.Name) and \
                    stmt.value.id == local_name and \
                    len(stmt.targets) == 1:
                d = _handle_descriptor(model, fi, stmt.targets[0])
                if d is None and isinstance(stmt.targets[0],
                                            ast.Attribute):
                    d = model._target_key(fi, stmt.targets[0],
                                          fi.module)
                if d is None and isinstance(stmt.targets[0],
                                            ast.Subscript):
                    d = _handle_descriptor(model, fi, stmt.targets[0])
                if d is not None:
                    handle = d
    return handle, started


# ---------------------------------------------------------------------------
# checker entry + static graph export
# ---------------------------------------------------------------------------

def check(module: ModuleInfo, index: PackageIndex) -> List[Finding]:
    model = _conc(index)
    cached = getattr(index, "_conc_findings", None)
    if cached is None:
        cached = {}
        all_findings = (_shared_write_findings(model)
                        + _lock_order_findings(model)
                        + _blocking_findings(model)
                        + _cond_wait_findings(model)
                        + _lifecycle_findings(index, model))
        for f in all_findings:
            cached.setdefault(f.path, []).append(f)
        index._conc_findings = cached
    return list(cached.get(module.relpath, ()))


def static_lock_graph(paths: Sequence[str],
                      root: Optional[str] = None) -> dict:
    """Build the static lock-acquisition graph over ``paths`` for the
    runtime sanitizer cross-check (``tools.lint.runtime_lockorder``).

    Returns ``{"locks": {"relpath:line": name}, "edges":
    {("relpath:line", "relpath:line"), ...}}`` — nodes are lock
    CREATION sites (the ``threading.Lock()`` call), matching how the
    runtime wrapper attributes the locks it observes.  ``root``
    defaults to the repo root; pass the sanitizer's ``repo_root`` when
    checking code outside the repo (test fixtures)."""
    import os
    from .core import collect_files, ModuleInfo as MI, _repo_root

    root = os.path.abspath(root) if root else _repo_root()
    modules = []
    for path in collect_files(paths):
        rel = os.path.relpath(os.path.abspath(path), root)
        try:
            with open(path, encoding="utf-8") as f:
                src = f.read()
        except OSError:
            continue
        try:
            modules.append(MI(path, rel, src))
        except SyntaxError:
            continue
    index = PackageIndex(modules)
    model = _conc(index)
    locks = {}
    site_of: Dict[tuple, List[str]] = {}
    for key, sites in model.var_sites.items():
        if not (model.var_tags.get(key, set()) & {"lock", "rlock",
                                                  "condition"}):
            continue
        for rel, line in sites:
            site = "%s:%d" % (rel, line)
            locks[site] = _var_label(key)
            site_of.setdefault(key, []).append(site)
    edges = set()
    for (a, b) in model.edges:
        for sa in site_of.get(a, ()):
            for sb in site_of.get(b, ()):
                edges.add((sa, sb))
    return {"locks": locks, "edges": edges}
