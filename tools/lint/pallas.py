"""Pallas kernel checker: BlockSpec/grid/index-map consistency and a
static VMEM-footprint estimate.

* ``pallas-index-map-arity`` — a BlockSpec index_map whose lambda cannot
  accept the grid's rank (Mosaic fails at lowering, i.e. on-device);
* ``pallas-block-rank`` — index_map returns a different number of block
  coordinates than the block shape has dims (or out_specs/out_shape
  length mismatch);
* ``pallas-dim-semantics`` — ``dimension_semantics`` length differs from
  the grid rank;
* ``pallas-vmem-budget`` — the per-grid-step working set (in/out blocks
  + scratch + one fp32 score tile for attention-shaped kernels),
  evaluated at the tuned default blocks from ``tune_attention_blocks``
  via constant folding of the enclosing function (including ``min``-
  clamp chains), exceeds the module's explicit ``_VMEM_CLAMP`` budget.

The folder follows the codebase's own sizing arithmetic: e.g. the fused
dqkv backward's ``max_bq = max(8, (10 MiB)//(3*4*block_k))`` /
``pow2 = 1 << (max_bq.bit_length()-1)`` clamp folds to block_q=256 at
the default block_k=2048, and the footprint is checked *after* it.
"""
from __future__ import annotations

import ast
from typing import Dict, List, Optional, Tuple

from .core import Finding, ModuleInfo
from .jitgraph import (PackageIndex, call_target_name, call_target_parts,
                       fold_or_none, shallow_walk)

RULES = {
    "pallas-index-map-arity":
        "BlockSpec index_map arity incompatible with the grid rank",
    "pallas-block-rank":
        "BlockSpec block shape rank differs from the index_map's "
        "coordinate count (or out_specs/out_shape mismatch)",
    "pallas-dim-semantics":
        "compiler_params dimension_semantics length differs from the "
        "grid rank",
    "pallas-vmem-budget":
        "estimated per-grid-step VMEM working set exceeds the module's "
        "_VMEM_CLAMP budget at the tuned default block sizes",
}

_DEFAULT_CLAMP = 12 * 1024 * 1024
_DEFAULT_DIM = 128          # substituted for unfoldable block dims
_F32 = {"float32", "f32", "int32", "uint32"}


def _module_env(module: ModuleInfo) -> Dict[str, object]:
    env: Dict[str, object] = {}
    for stmt in module.tree.body:
        if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 and \
                isinstance(stmt.targets[0], ast.Name):
            v = fold_or_none(stmt.value, env)
            if v is not None:
                env[stmt.targets[0].id] = v
    return env


def _tuned_defaults(index: PackageIndex) -> Tuple[int, int]:
    """Streaming-path default (block_q, block_k) parsed out of
    tune_attention_blocks (`block_q, block_k = 1024, 2048`)."""
    for fi in index.functions:
        if fi.name != "tune_attention_blocks":
            continue
        for stmt in shallow_walk(fi.node):
            if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 \
                    and isinstance(stmt.targets[0], ast.Tuple):
                names = [t.id for t in stmt.targets[0].elts
                         if isinstance(t, ast.Name)]
                if names == ["block_q", "block_k"]:
                    v = fold_or_none(stmt.value)
                    if isinstance(v, tuple) and len(v) == 2:
                        return int(v[0]), int(v[1])
    return 1024, 2048


def _global_clamp(index: PackageIndex) -> int:
    for m in index.modules:
        env = _module_env(m)
        if isinstance(env.get("_VMEM_CLAMP"), int):
            return env["_VMEM_CLAMP"]
    return _DEFAULT_CLAMP


# the tune-package lookup spellings whose ``default=`` literal is the
# config a caller is sized at on a miss: the plain table lookup, the
# v2 model-ranked lookup (same tuple contract, learned-model fallback),
# and the program-knob lookup (whole-program schedule knobs — folded so
# a knob that feeds kernel sizing still resolves)
_TUNE_LOOKUPS = ("table_blocks", "model_blocks", "program_knobs")


def _fold_tune_lookup(expr: ast.expr, env) -> Optional[object]:
    """Blocks that arrive via an autotune cost-table lookup instead of a
    literal clamp chain: ``table_blocks(family, shape, dtype,
    default=(bq, bk))`` (mxnet_tpu.tune) — or its v2 siblings
    ``model_blocks`` / ``program_knobs`` — folds to its ``default=``
    fallback config — the config the caller is sized at on a table
    miss, and the declared anchor the measured search prunes around
    with the same VMEM predicate this rule checks statically.  (The
    model/table legs only ever serve configs from the statically-pruned
    candidate grid, so the ``default=`` literal is the one config the
    lookup can return that the search machinery never validated.)"""
    if not isinstance(expr, ast.Call) or \
            call_target_name(expr) not in _TUNE_LOOKUPS:
        return None
    for kw in expr.keywords:
        if kw.arg == "default":
            return fold_or_none(kw.value, env)
    return None


def _local_env(module, fi, call_line, base: Dict[str, object]
               ) -> Dict[str, object]:
    """Fold the enclosing function's assignments (source order, up to the
    call) over ``base``.  On fold failure the existing binding is KEPT —
    the clamp chains this codebase writes only shrink blocks via min(),
    so a stale binding is the conservative upper bound."""
    env = dict(base)
    if fi is None:
        return env
    stmts = [s for s in shallow_walk(fi.node)
             if isinstance(s, ast.Assign) and s.lineno < call_line]
    for stmt in sorted(stmts, key=lambda s: s.lineno):
        if len(stmt.targets) != 1:
            continue
        t = stmt.targets[0]
        if isinstance(t, ast.Name):
            v = fold_or_none(stmt.value, env)
            if v is None:
                v = _fold_tune_lookup(stmt.value, env)
            if v is not None:
                env[t.id] = v
        elif isinstance(t, ast.Tuple) and \
                all(isinstance(e, ast.Name) for e in t.elts):
            v = fold_or_none(stmt.value, env)
            if v is None:
                v = _fold_tune_lookup(stmt.value, env)
            if isinstance(v, tuple) and len(v) == len(t.elts):
                for e, x in zip(t.elts, v):
                    env[e.id] = x
    return env


def _spec_elements(expr: Optional[ast.expr]
                   ) -> Tuple[List[ast.Call], bool]:
    """BlockSpec Call nodes out of an in_specs/out_specs expression;
    second value = True when the list is complete (no `+ extra` tail)."""
    if expr is None:
        return [], False
    complete = True
    lists: List[ast.List] = []
    if isinstance(expr, (ast.List, ast.Tuple)):
        lists.append(expr)
    elif isinstance(expr, ast.BinOp) and isinstance(expr.op, ast.Add):
        complete = False
        for side in (expr.left, expr.right):
            if isinstance(side, (ast.List, ast.Tuple)):
                lists.append(side)
    else:
        return [], False
    out: List[ast.Call] = []
    for li in lists:
        for e in li.elts:
            if isinstance(e, ast.Call) and \
                    call_target_name(e) == "BlockSpec":
                out.append(e)
    return out, complete


def _lambda_arity(lam: ast.Lambda) -> Tuple[int, int]:
    a = lam.args
    total = len(a.posonlyargs) + len(a.args)
    return total - len(a.defaults), total


def _index_map_coords(lam: ast.Lambda) -> Optional[int]:
    body = lam.body
    if isinstance(body, ast.Tuple):
        return len(body.elts)
    return 1


def _block_dims(spec: ast.Call) -> Optional[ast.expr]:
    if spec.args:
        return spec.args[0]
    for kw in spec.keywords:
        if kw.arg == "block_shape":
            return kw.value
    return None


def _spec_index_map(spec: ast.Call) -> Optional[ast.Lambda]:
    cand = None
    if len(spec.args) >= 2:
        cand = spec.args[1]
    else:
        for kw in spec.keywords:
            if kw.arg == "index_map":
                cand = kw.value
    return cand if isinstance(cand, ast.Lambda) else None


def _is_smem(spec: ast.Call) -> bool:
    for kw in spec.keywords:
        if kw.arg == "memory_space":
            return "SMEM" in ast.dump(kw.value)
    return False


def _fold_dims(expr: Optional[ast.expr], env) -> Optional[List[int]]:
    if expr is None:
        return None
    if not isinstance(expr, (ast.Tuple, ast.List)):
        return None
    dims = []
    for e in expr.elts:
        v = fold_or_none(e, env)
        if isinstance(v, (int, float)):
            dims.append(int(v))
        else:
            dims.append(_DEFAULT_DIM)
    return dims


def _dtype_size(expr: Optional[ast.expr]) -> int:
    """Itemsize of a dtype expression; unknown -> 2 (the tuned kernels'
    bf16 operand dtype — tune_attention_blocks halves blocks for wider
    dtypes before the kernels ever see them)."""
    if expr is None:
        return 2
    text = ast.dump(expr)
    if any(t in text for t in ("float64", "int64")):
        return 8
    if any(t in text for t in _F32):
        return 4
    if any(t in text for t in ("bfloat16", "float16", "int16")):
        return 2
    if any(t in text for t in ("int8", "uint8")):
        return 1
    return 2


def _kw(call: ast.Call, name: str) -> Optional[ast.expr]:
    for kw in call.keywords:
        if kw.arg == name:
            return kw.value
    return None


def check(module: ModuleInfo, index: PackageIndex) -> List[Finding]:
    findings: List[Finding] = []
    calls = [cs for cs in index.calls_in(module)
             if call_target_name(cs.node) == "pallas_call"]
    if not calls:
        return findings

    bq, bk = _tuned_defaults(index)
    clamp = _global_clamp(index)
    base = _module_env(module)
    base.setdefault("block_q", bq)
    base.setdefault("block_k", bk)
    base.setdefault("Dp", _DEFAULT_DIM)

    for cs in calls:
        node = cs.node
        ctx = cs.scope.qualname if cs.scope else "<module>"
        env = _local_env(module, cs.scope, node.lineno, base)
        grid_expr = _kw(node, "grid")
        grid = fold_or_none(grid_expr, env) if grid_expr is not None \
            else None
        if isinstance(grid, (int, float)):
            grid = (int(grid),)
        grid_rank = len(grid) if isinstance(grid, tuple) else None

        in_specs, _ = _spec_elements(_kw(node, "in_specs"))
        out_specs, out_complete = _spec_elements(_kw(node, "out_specs"))
        out_shape_expr = _kw(node, "out_shape")
        out_shapes: List[ast.Call] = []
        if isinstance(out_shape_expr, (ast.List, ast.Tuple)):
            out_shapes = [e for e in out_shape_expr.elts
                          if isinstance(e, ast.Call)]

        if out_complete and out_shapes and \
                len(out_specs) != len(out_shapes):
            findings.append(Finding(
                "pallas-block-rank", module.relpath,
                node.lineno, node.col_offset,
                "pallas_call has %d out_specs but %d out_shape entries"
                % (len(out_specs), len(out_shapes)), ctx))

        total_bytes = 0
        est_ok = True
        for i, spec in enumerate(in_specs + out_specs):
            is_out = i >= len(in_specs)
            lam = _spec_index_map(spec)
            if lam is not None and grid_rank is not None:
                lo, hi = _lambda_arity(lam)
                if not (lo <= grid_rank <= hi):
                    findings.append(Finding(
                        "pallas-index-map-arity", module.relpath,
                        spec.lineno, spec.col_offset,
                        "index_map takes %s args but the grid has rank "
                        "%d" % ("%d-%d" % (lo, hi) if lo != hi else lo,
                                grid_rank), ctx))
            dims_expr = _block_dims(spec)
            if lam is not None and \
                    isinstance(dims_expr, (ast.Tuple, ast.List)):
                coords = _index_map_coords(lam)
                if coords is not None and \
                        coords != len(dims_expr.elts):
                    findings.append(Finding(
                        "pallas-block-rank", module.relpath,
                        spec.lineno, spec.col_offset,
                        "block shape has %d dims but index_map returns "
                        "%d coordinates"
                        % (len(dims_expr.elts), coords), ctx))
            if _is_smem(spec):
                continue
            dims = _fold_dims(dims_expr, env)
            if dims is None:
                est_ok = False
                continue
            size = 1
            for d in dims:
                size *= max(int(d), 1)
            if is_out:
                oi = i - len(in_specs)
                dt = None
                if oi < len(out_shapes) and \
                        len(out_shapes[oi].args) >= 2:
                    dt = out_shapes[oi].args[1]
                total_bytes += size * _dtype_size(dt)
            else:
                total_bytes += size * 2

        scratch_expr = _kw(node, "scratch_shapes")
        if isinstance(scratch_expr, (ast.List, ast.Tuple)):
            for e in scratch_expr.elts:
                if not (isinstance(e, ast.Call) and e.args):
                    continue
                dims = _fold_dims(e.args[0], env)
                if dims is None:
                    est_ok = False
                    continue
                size = 1
                for d in dims:
                    size *= max(int(d), 1)
                dt = e.args[1] if len(e.args) >= 2 else None
                # scratch is VMEM((dims), dtype) — fp32 when unspecified
                total_bytes += size * (_dtype_size(dt)
                                       if dt is not None else 4)

        # attention-shaped kernels materialize one fp32 score tile
        # (block_q, block_k) that no spec describes
        names_used = {n.id for spec in in_specs + out_specs
                      for n in ast.walk(spec)
                      if isinstance(n, ast.Name)}
        if "block_q" in names_used and "block_k" in names_used and \
                isinstance(env.get("block_q"), int) and \
                isinstance(env.get("block_k"), int):
            total_bytes += env["block_q"] * env["block_k"] * 4

        if est_ok and total_bytes and in_specs and \
                total_bytes > clamp:
            findings.append(Finding(
                "pallas-vmem-budget", module.relpath,
                node.lineno, node.col_offset,
                "estimated per-step VMEM working set %.1f MiB exceeds "
                "the %.1f MiB _VMEM_CLAMP budget at default blocks "
                "(block_q=%s, block_k=%s)" % (
                    total_bytes / 1048576.0, clamp / 1048576.0,
                    env.get("block_q"), env.get("block_k")), ctx))

        sem = None
        for sub in ast.walk(node):
            if isinstance(sub, ast.keyword) and \
                    sub.arg == "dimension_semantics":
                sem = sub.value
        if sem is not None and grid_rank is not None and \
                isinstance(sem, (ast.Tuple, ast.List)) and \
                len(sem.elts) != grid_rank:
            findings.append(Finding(
                "pallas-dim-semantics", module.relpath,
                sem.lineno, sem.col_offset,
                "dimension_semantics has %d entries but the grid has "
                "rank %d" % (len(sem.elts), grid_rank), ctx))
    return findings
