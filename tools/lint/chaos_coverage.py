"""chaos_coverage — fault-injection coverage auditor (phase 5 runtime
cross-check).

``errorflow`` proves the error-handling *disciplines* hold statically;
this module audits that the *failure modes* those disciplines exist for
are actually injectable and injected.  It statically enumerates the
package's fault points —

* every ``os.replace`` commit window (the crash instant atomicity
  exists to survive),
* every host-thread entry from the PR-7 concurrency model (a thread
  that dies or stalls silently is a hang),
* every KV coordinator op behind ``kv_retry`` (the seam a struggling
  coordinator perturbs),

— and maps them against the chaos-mode registry (``MODES`` in
``mxnet_tpu/parallel/chaos.py``, parsed as a literal so the audit
imports nothing from the package) and against the tests that install
each mode.  The audit FAILS when:

* a fault point has no reachable chaos consultation and no waiver,
* a registered mode is never consulted by any seam,
* a consulted mode is missing from the registry,
* a registered mode has no test installing it.

Explicit waivers (below) document the fault points that are
legitimately outside the switchboard — e.g. the native-extension build
cache, whose failure path is "fall back to eager", exercised without
injection.  A waiver names its site; when the site disappears the
waiver goes stale and the audit fails, so waivers cannot rot.

This is the same static-vs-runtime closure the LockOrderSanitizer and
NumericsSanitizer established: the static model enumerates, the runtime
harness must cover, and the gate holds the two together.
"""
from __future__ import annotations

import ast
import os
import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Set, Tuple

from .core import ModuleInfo, _repo_root, collect_files
from .jitgraph import PackageIndex, call_target_name, call_target_parts

# mode-name-bearing consultation entry points in parallel/chaos.py
_CONSULT_FNS = {"should_fire", "maybe_stall", "active"}

# (relpath suffix, context qualname, reason) — fault points the chaos
# switchboard intentionally does not reach.  Keep reasons load-bearing:
# they are printed in the audit matrix.
WAIVERS: Tuple[Tuple[str, str, str], ...] = (
    ("native/__init__.py", "_build",
     "one-shot import-time build cache: a torn .so is rebuilt on next "
     "import and every failure path falls back to the eager kernels"),
    ("io/device_prefetch.py", "DevicePrefetchIter._feed",
     "feeder faults are driven through the upstream iterator "
     "(StopIteration / raising source), not the chaos switchboard"),
    ("io/io.py", "_Producer._run",
     "single-epoch producer: its only fault path is the child "
     "iterator raising/exhausting, exercised by the io restart tests"),
)


@dataclass
class FaultPoint:
    kind: str            # commit-window | thread-entry | kv-op
    path: str
    line: int
    context: str
    modes: Tuple[str, ...] = ()
    status: str = "uncovered"     # covered | waived | uncovered
    note: str = ""

    def to_dict(self) -> dict:
        return {"kind": self.kind, "path": self.path, "line": self.line,
                "context": self.context, "modes": list(self.modes),
                "status": self.status, "note": self.note}


@dataclass
class ChaosAudit:
    registry: Dict[str, str] = field(default_factory=dict)
    points: List[FaultPoint] = field(default_factory=list)
    consultations: Dict[str, List[str]] = field(default_factory=dict)
    tests: Dict[str, List[str]] = field(default_factory=dict)
    problems: List[str] = field(default_factory=list)

    @property
    def ok(self) -> bool:
        return not self.problems

    def to_dict(self) -> dict:
        return {
            "ok": self.ok,
            "modes": {
                m: {"seam": self.registry[m],
                    "consultations": self.consultations.get(m, []),
                    "tests": self.tests.get(m, [])}
                for m in sorted(self.registry)},
            "fault_points": [p.to_dict() for p in self.points],
            "problems": list(self.problems),
        }

    def render_text(self) -> str:
        out = ["chaos coverage: %d mode(s), %d fault point(s)"
               % (len(self.registry), len(self.points))]
        out.append("%-24s %-38s %s" % ("mode", "consulted at",
                                       "installed by"))
        for m in sorted(self.registry):
            cons = self.consultations.get(m, [])
            tst = self.tests.get(m, [])
            out.append("%-24s %-38s %s" % (
                m, cons[0] if cons else "<never>",
                ", ".join(tst) if tst else "<no test>"))
        out.append("")
        out.append("%-14s %-42s %-9s %s" % ("fault point", "site",
                                            "status", "injection"))
        for p in self.points:
            out.append("%-14s %-42s %-9s %s" % (
                p.kind, "%s:%d (%s)" % (p.path, p.line, p.context),
                p.status,
                ", ".join(p.modes) if p.modes else (p.note or "-")))
        for prob in self.problems:
            out.append("PROBLEM: " + prob)
        out.append("chaos coverage: %s"
                   % ("OK" if self.ok else
                      "%d problem(s)" % len(self.problems)))
        return "\n".join(out)


def _load_registry(modules: Sequence[ModuleInfo]) -> Dict[str, str]:
    for m in modules:
        if not m.relpath.endswith("parallel/chaos.py"):
            continue
        for node in m.tree.body:
            if isinstance(node, ast.Assign) \
                    and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name) \
                    and node.targets[0].id == "MODES":
                try:
                    reg = ast.literal_eval(node.value)
                except (ValueError, SyntaxError):
                    return {}
                if isinstance(reg, dict):
                    return {str(k): str(v) for k, v in reg.items()}
    return {}


def _consultations(index: PackageIndex) -> Dict[str, List[Tuple]]:
    """mode -> [(relpath, line, scope-FunctionInfo)] for every
    mode-naming chaos consultation in the package."""
    out: Dict[str, List[Tuple]] = {}
    for cs in index.call_sites:
        name = call_target_parts(cs.node)[-1:]
        name = name[0] if name else None
        mode = None
        if name in _CONSULT_FNS and cs.node.args:
            a0 = cs.node.args[0]
            if isinstance(a0, ast.Constant) and isinstance(a0.value, str):
                mode = a0.value
        elif name == "maybe_kill":
            mode = "kill_worker"
        if mode is None:
            continue
        out.setdefault(mode, []).append(
            (cs.module.relpath, cs.node.lineno, cs.scope))
    return out


def _fn_consults(index: PackageIndex, fi) -> bool:
    for cs in index.calls_in_scope(fi):
        parts = call_target_parts(cs.node)
        name = parts[-1] if parts else None
        if name == "maybe_kill":
            return True
        if name in _CONSULT_FNS and cs.node.args \
                and isinstance(cs.node.args[0], ast.Constant) \
                and isinstance(cs.node.args[0].value, str):
            return True
    return False


def _reachable(index: PackageIndex, entry_fi) -> List:
    """Functions reachable from ``entry_fi`` through resolved call
    sites, with the same receiver-blind same-class step the thread
    model uses."""
    seen: Set[int] = {id(entry_fi.node)}
    order = [entry_fi]
    todo = [entry_fi]
    while todo:
        fi = todo.pop()
        for cs in index.calls_in_scope(fi):
            callee = cs.callee
            if callee is None and isinstance(cs.node.func, ast.Attribute):
                s, cls = fi, None
                while s is not None and cls is None:
                    cls = s.cls
                    s = s.parent
                if cls is not None:
                    callee = index.methods.get(
                        (cs.module.relpath, cls, cs.node.func.attr))
            if callee is not None and id(callee.node) not in seen:
                seen.add(id(callee.node))
                order.append(callee)
                todo.append(callee)
    return order


_KV_OPS = re.compile(r"^(blocking_)?key_value_|^kv_retry$")


def _waiver_for(path: str, context: str) -> Optional[str]:
    for suffix, ctx, reason in WAIVERS:
        if path.endswith(suffix) and (context == ctx
                                      or context.endswith("." + ctx)
                                      or context.startswith(ctx)):
            return reason
    return None


def _scan_tests(registry: Dict[str, str],
                tests_dir: str) -> Dict[str, List[str]]:
    """mode -> test files mentioning it as an installed fault: either
    ``install("mode", ...)`` / ``wrap_kv_client`` fixtures or an
    ``MXNET_TPU_CHAOS``-style env spec ``"mode:rank=..."``."""
    out: Dict[str, List[str]] = {m: [] for m in registry}
    if not os.path.isdir(tests_dir):
        return out
    pats = {m: re.compile(r"""['"]%s[:'"]""" % re.escape(m))
            for m in registry}
    for name in sorted(os.listdir(tests_dir)):
        if not name.endswith(".py"):
            continue
        try:
            with open(os.path.join(tests_dir, name),
                      encoding="utf-8") as f:
                src = f.read()
        except OSError:
            continue
        for m, pat in pats.items():
            if pat.search(src):
                out[m].append("tests/" + name)
    return out


def audit(paths: Optional[Sequence[str]] = None,
          root: Optional[str] = None,
          tests_dir: Optional[str] = None) -> ChaosAudit:
    root = root or _repo_root()
    if paths is None:
        paths = [os.path.join(root, "mxnet_tpu")]
    if tests_dir is None:
        tests_dir = os.path.join(root, "tests")
    modules: List[ModuleInfo] = []
    for path in collect_files(paths):
        rel = os.path.relpath(os.path.abspath(path), root)
        try:
            with open(path, encoding="utf-8") as f:
                modules.append(ModuleInfo(path, rel, f.read()))
        except (OSError, SyntaxError):
            continue
    index = PackageIndex(modules)
    res = ChaosAudit()
    res.registry = _load_registry(modules)
    if not res.registry:
        res.problems.append(
            "no MODES registry found in parallel/chaos.py — the audit "
            "has nothing to map fault points against")
        return res

    cons = _consultations(index)
    res.consultations = {m: ["%s:%d" % (p, ln) for p, ln, _ in sites]
                         for m, sites in sorted(cons.items())}
    res.tests = _scan_tests(res.registry, tests_dir)

    # -- registry <-> consultation <-> test closure ---------------------
    for m in sorted(res.registry):
        if m not in cons:
            res.problems.append(
                "mode '%s' is registered but no seam consults it "
                "(should_fire/maybe_stall/active/maybe_kill)" % m)
        if not res.tests.get(m):
            res.problems.append(
                "mode '%s' has no installing test under tests/" % m)
    for m in sorted(cons):
        if m not in res.registry:
            res.problems.append(
                "mode '%s' is consulted at %s but missing from the "
                "MODES registry" % (m, res.consultations[m][0]))

    # -- fault points ----------------------------------------------------
    # 1. commit windows: every os.replace call — the crash instant the
    #    atomic-write discipline exists for
    for cs in index.call_sites:
        if call_target_parts(cs.node)[-2:] != ("os", "replace"):
            continue
        ctx = cs.scope.qualname if cs.scope else "<module>"
        fp = FaultPoint("commit-window", cs.module.relpath,
                        cs.node.lineno, ctx)
        if cs.scope is not None and _fn_consults(index, cs.scope):
            fp.status = "covered"
            fp.modes = tuple(sorted(
                m for m, sites in cons.items()
                if any(s is cs.scope for _, _, s in sites)))
        else:
            reason = _waiver_for(fp.path, ctx)
            if reason:
                fp.status, fp.note = "waived", reason
        res.points.append(fp)

    # 2. thread entries: each function a threading.Thread targets
    entries = index.thread_entries()
    entry_fis = [(nid, desc, index.by_node.get(nid))
                 for nid, desc in sorted(entries.items(),
                                         key=lambda kv: kv[1])]
    covered_groups: Set[Tuple[str, Optional[str]]] = set()
    pending = []
    for nid, desc, fi in entry_fis:
        if fi is None:
            continue
        path, _, line = desc.rpartition(":")
        fp = FaultPoint("thread-entry", path, int(line), fi.qualname)
        modes: Set[str] = set()
        kv_seam = False
        for rfi in _reachable(index, fi):
            if _fn_consults(index, rfi):
                for m, sites in cons.items():
                    if any(s is rfi for _, _, s in sites):
                        modes.add(m)
            for cs in index.calls_in_scope(rfi):
                parts = call_target_parts(cs.node)
                if parts and _KV_OPS.search(parts[-1]):
                    kv_seam = True
        if kv_seam:
            modes.update(m for m in ("kv_garble", "kv_stall")
                         if m in res.registry)
        if modes:
            fp.status = "covered"
            fp.modes = tuple(sorted(modes))
            covered_groups.add((fi.module.relpath, fi.cls))
        else:
            reason = _waiver_for(fi.module.relpath, fi.qualname)
            if reason:
                fp.status, fp.note = "waived", reason
        pending.append((fp, fi))
    for fp, fi in pending:
        if fp.status == "uncovered" \
                and (fi.module.relpath, fi.cls) in covered_groups \
                and fi.cls is not None:
            # group rule: a sibling thread of the same object IS
            # covered, and the chaos matrix perturbs the shared queues
            # this thread drains (the serve batcher/watchdog case)
            fp.status = "covered"
            fp.note = "via sibling thread of %s" % fi.cls
        res.points.append(fp)

    # 3. KV coordinator ops behind kv_retry
    for cs in index.call_sites:
        name = call_target_name(cs.node)
        if name != "kv_retry":
            continue
        if cs.module.relpath.endswith("parallel/elastic.py") \
                and cs.scope is not None and cs.scope.name == "kv_retry":
            continue
        ctx = cs.scope.qualname if cs.scope else "<module>"
        fp = FaultPoint("kv-op", cs.module.relpath, cs.node.lineno, ctx)
        kv_modes = tuple(m for m in ("kv_garble", "kv_stall")
                         if m in res.registry and m in cons)
        if len(kv_modes) == 2:
            fp.status = "covered"
            fp.modes = kv_modes
            fp.note = "via wrap_kv_client read proxy"
        res.points.append(fp)

    res.points.sort(key=lambda p: (p.path, p.line))
    for p in res.points:
        if p.status == "uncovered":
            res.problems.append(
                "%s at %s:%d (%s) has no chaos injection and no "
                "waiver" % (p.kind, p.path, p.line, p.context))

    # stale waivers must not rot: a waiver whose FILE is in the audited
    # set must still match a fault point.  (A waiver for a file outside
    # the audit — or deleted along with its fault point — is vacuous,
    # not stale: the hazard it documented is gone with the site.)
    matched = {p.note for p in res.points if p.status == "waived"}
    present = {m.relpath for m in modules}
    for suffix, ctx, reason in WAIVERS:
        if not any(r.endswith(suffix) for r in present):
            continue
        if reason not in matched:
            res.problems.append(
                "stale waiver: no fault point matches %s (%s) — "
                "delete the waiver" % (suffix, ctx))
    return res


def emit_telemetry(res: ChaosAudit) -> None:
    try:
        from mxnet_tpu import telemetry
        telemetry.event(
            "lint", "chaos_audit", ok=res.ok,
            modes=len(res.registry), points=len(res.points),
            problems=len(res.problems),
            matrix=[[p.kind, "%s:%d" % (p.path, p.line),
                     ",".join(p.modes) or p.status,
                     ";".join(sorted(set(
                         t for m in p.modes
                         for t in res.tests.get(m, []))))]
                    for p in res.points])
    except Exception:
        pass
