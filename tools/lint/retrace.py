"""retrace-hazard checker: the static complement of telemetry's
``record_compile`` detector.

* ``retrace-unhashable-static`` — ``static_argnums``/``static_argnames``
  naming a parameter whose default is a list/dict/set: every call raises
  (unhashable) or retraces;
* ``retrace-closure-array`` — a function handed directly to ``jax.jit``
  that closes over an array built in the enclosing scope (or a mutable
  list/dict): the value is baked in as a constant, so every rebuild of
  the closure is a full retrace and the constant bloats the executable;
* ``retrace-shape-branch`` — Python branching on ``.shape``/``len()`` of
  a traced value inside jit-reachable code: legal, but every distinct
  shape compiles a new executable (the telemetry recompile detector sees
  these at runtime; this flags them at review time);
* ``retrace-jit-in-loop`` — ``jax.jit``/``pjit`` called inside a Python
  loop: each iteration builds a fresh callable with an empty compile
  cache.
"""
from __future__ import annotations

import ast
from typing import List, Optional, Set

from .core import Finding, ModuleInfo
from .jitgraph import (PackageIndex, call_target_name, call_target_parts,
                       is_tracing_wrapper_call, shallow_walk)
from .trace_safety import _span_text

RULES = {
    "retrace-unhashable-static":
        "static_argnums/static_argnames naming a parameter with an "
        "unhashable (list/dict/set) default",
    "retrace-closure-array":
        "jitted function closes over an enclosing-scope array or mutable "
        "container (baked-in constant; rebuild = retrace)",
    "retrace-shape-branch":
        "Python branch on .shape/len() of a traced value in "
        "jit-reachable code (one compile per distinct shape)",
    "retrace-jit-in-loop":
        "jax.jit/pjit constructed inside a Python loop (fresh compile "
        "cache every iteration)",
}

_UNHASHABLE = (ast.List, ast.Dict, ast.Set, ast.ListComp, ast.DictComp,
               ast.SetComp)

_ARRAY_ROOTS = {"np", "onp", "numpy", "jnp", "jax"}


def _jit_call_name(node: ast.Call) -> Optional[str]:
    name = call_target_name(node)
    return name if name in ("jit", "pjit") else None


def _check_unhashable_static(module, index, findings):
    for cs in index.calls_in(module):
        if _jit_call_name(cs.node) is None or not cs.node.args:
            continue
        # resolve the WRAPPED function (args[0]), not the jit callee
        fi = index.resolve_call(cs.module, cs.scope, cs.node.args[0])
        if fi is None:
            continue
        params = fi.params()
        defaults = {}
        a = fi.node.args
        if a.defaults:
            tail = params[len(params) - len(a.defaults):]
            defaults = {p.arg: d for p, d in zip(tail, a.defaults)}
        for kw, d in zip(a.kwonlyargs, a.kw_defaults):
            if d is not None:
                defaults[kw.arg] = d
        for name in fi.static_params:
            d = defaults.get(name)
            if d is not None and isinstance(d, _UNHASHABLE):
                findings.append(Finding(
                    "retrace-unhashable-static", module.relpath,
                    cs.node.lineno, cs.node.col_offset,
                    "static arg %r of %s defaults to an unhashable %s — "
                    "jit static args must be hashable" % (
                        name, fi.name, type(d).__name__.lower()),
                    cs.scope.qualname if cs.scope else "<module>"))


def _enclosing_bindings(fi) -> dict:
    """Assignments in the ENCLOSING function scope: name -> value node."""
    out = {}
    p = fi.parent
    while p is not None:
        for stmt in shallow_walk(p.node):
            if isinstance(stmt, ast.Assign):
                for t in stmt.targets:
                    if isinstance(t, ast.Name) and t.id not in out:
                        out[t.id] = stmt.value
        p = p.parent
    return out


def _is_array_construction(node: ast.expr) -> bool:
    if isinstance(node, ast.Call):
        parts = call_target_parts(node)
        return bool(parts) and parts[0] in _ARRAY_ROOTS
    return isinstance(node, (ast.List, ast.Dict, ast.ListComp,
                             ast.DictComp))


def _local_names(fi) -> Set[str]:
    names: Set[str] = set(fi.param_names() + fi.kwonly_names())
    a = fi.node.args
    if a.vararg:
        names.add(a.vararg.arg)
    if a.kwarg:
        names.add(a.kwarg.arg)
    for n in shallow_walk(fi.node):
        if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Store):
            names.add(n.id)
        elif isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
            names.add(n.name)
        elif isinstance(n, (ast.ListComp, ast.SetComp, ast.GeneratorExp,
                            ast.DictComp)):
            for g in n.generators:
                for t in ast.walk(g.target):
                    if isinstance(t, ast.Name):
                        names.add(t.id)
    return names


def _check_closure_capture(module, index, findings):
    for fi in index.functions_in(module):
        if fi.parent is None or isinstance(fi.node, ast.Lambda):
            continue
        reason = fi.entry_reason or ""
        if not (reason.startswith("wrapped:jit")
                or reason.startswith("wrapped:pjit")
                or reason.startswith("decorator:jit")):
            continue
        enclosing = _enclosing_bindings(fi)
        local = _local_names(fi)
        seen: Set[str] = set()
        for n in shallow_walk(fi.node):
            if not (isinstance(n, ast.Name)
                    and isinstance(n.ctx, ast.Load)):
                continue
            if n.id in local or n.id in seen or n.id not in enclosing:
                continue
            seen.add(n.id)
            bound = enclosing[n.id]
            if _is_array_construction(bound):
                kind = ("array" if isinstance(bound, ast.Call)
                        else "mutable container")
                findings.append(Finding(
                    "retrace-closure-array", module.relpath, n.lineno,
                    n.col_offset,
                    "jitted %s closes over enclosing-scope %s %r (built "
                    "at line %d) — baked in as a constant; pass it as an "
                    "argument instead" % (fi.name, kind, n.id,
                                          bound.lineno), fi.qualname))


def _shape_read_of_tracer(node: ast.expr, taint) -> Optional[str]:
    """A `.shape`/`.ndim`/`.size`/len() read of a traced value inside
    ``node`` — returns a description or None."""
    for sub in ast.walk(node):
        if isinstance(sub, ast.Attribute) and \
                sub.attr in ("shape", "ndim", "size") and \
                taint.expr(sub.value):
            return "%s.%s" % (_name_of(sub.value), sub.attr)
        if isinstance(sub, ast.Call) and \
                isinstance(sub.func, ast.Name) and \
                sub.func.id == "len" and sub.args and \
                taint.expr(sub.args[0]):
            return "len(%s)" % _name_of(sub.args[0])
    return None


def _name_of(node) -> str:
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        return "%s.%s" % (_name_of(node.value), node.attr)
    return "<expr>"


def _check_shape_branch(module, index, findings):
    for fi in index.functions_in(module):
        if not fi.reachable or isinstance(fi.node, ast.Lambda):
            continue
        taint = index.taint(fi)
        for node in index.shallow_nodes(fi):
            if not isinstance(node, (ast.If, ast.While, ast.IfExp)):
                continue
            desc = _shape_read_of_tracer(node.test, taint)
            if desc is not None:
                findings.append(Finding(
                    "retrace-shape-branch", module.relpath,
                    node.lineno, node.col_offset,
                    "branch on %s in jit-reachable code: each distinct "
                    "shape triggers a retrace (intended specialization "
                    "should be suppressed with a reason)" % desc,
                    fi.qualname))


def _check_jit_in_loop(module, index, findings):
    flagged: Set[int] = set()

    def scan_loop_body(loop, ctx):
        for node in ast.walk(loop):
            if isinstance(node, ast.Call) and id(node) not in flagged \
                    and _jit_call_name(node) is not None \
                    and is_tracing_wrapper_call(node):
                flagged.add(id(node))
                findings.append(Finding(
                    "retrace-jit-in-loop", module.relpath, node.lineno,
                    node.col_offset,
                    "jax.%s constructed inside a loop: the compiled-"
                    "function cache is per-callable, so every iteration "
                    "recompiles — hoist the jit out of the loop"
                    % call_target_name(node), ctx))

    def visit(node, ctx):
        for child in ast.iter_child_nodes(node):
            nctx = ctx
            fi = index.function_at(child)
            if fi is not None:
                nctx = fi.qualname
            if isinstance(child, (ast.For, ast.While)):
                scan_loop_body(child, nctx)
            visit(child, nctx)

    visit(module.tree, "<module>")


def check(module: ModuleInfo, index: PackageIndex) -> List[Finding]:
    findings: List[Finding] = []
    _check_unhashable_static(module, index, findings)
    _check_closure_capture(module, index, findings)
    _check_shape_branch(module, index, findings)
    _check_jit_in_loop(module, index, findings)
    return findings
