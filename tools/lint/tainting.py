"""Per-function tracer-taint analysis (shared by the trace-safety and
retrace checkers, and by the PackageIndex config-param fixpoint).

Flow-insensitive and monotone: values derived from tracer params are
tainted; shape/dtype/len reads, ``is None`` checks, numpy results and
host-sync results are not.  ``for`` targets bind *pairwise* through
``zip``/``enumerate`` so a static index iterated next to a traced value
stays static.
"""
from __future__ import annotations

import ast
from typing import Optional, Set

from .jitgraph import call_target_name, call_target_parts, shallow_walk

# attributes whose value is trace-time Python data even on a tracer
STATIC_ATTRS = {"shape", "ndim", "dtype", "size", "itemsize",
                "sharding", "device", "devices", "aval", "weak_type",
                "committed", "grad_req", "name", "stype", "context"}

# builtins whose result is host/static data regardless of args
STATIC_FUNCS = {"len", "isinstance", "issubclass", "type", "hasattr",
                "getattr", "callable", "id", "repr", "str", "format",
                "range", "print", "sorted_keys"}

SYNC_BUILTINS = {"float", "int", "bool", "complex"}
SYNC_METHODS = {"item", "tolist", "block_until_ready",
                "copy_to_host_async", "asnumpy"}
NUMPY_ROOTS = {"np", "onp", "numpy"}
ARRAY_ROOTS = {"jnp", "lax", "jax", "pl", "pltpu", "nd", "npx"}

# iteration adapters: Python-level iteration over containers, never a
# direct tracer concretization
_ITER_ADAPTERS = {"zip", "enumerate", "reversed", "sorted", "list",
                  "tuple", "items", "keys", "values"}


class Taint:
    """Taint over one function; closure variables inherit the enclosing
    reachable functions' tracer params."""

    def __init__(self, index, fi):
        self.index = index
        self.fi = fi
        self.tainted: Set[str] = set(index.tracer_params(fi))
        p = fi.parent
        depth = 0
        while p is not None and depth < 4:
            if p.reachable:
                self.tainted |= set(index.tracer_params(p))
            p = p.parent
            depth += 1
        self._fixpoint()

    def _fixpoint(self):
        nodes = self.index.shallow_nodes(self.fi)
        for _ in range(4):
            before = len(self.tainted)
            for stmt in nodes:
                self._visit_binding(stmt)
            if len(self.tainted) == before:
                break

    def _visit_binding(self, node):
        if isinstance(node, ast.Assign):
            if len(node.targets) == 1 and \
                    isinstance(node.targets[0], ast.Tuple) and \
                    isinstance(node.value, ast.Call) and \
                    self._bind_call_return(node.targets[0], node.value):
                return
            if self.expr(node.value):
                for t in node.targets:
                    self._taint_target(t)
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            if self.expr(node.value):
                self._taint_target(node.target)
        elif isinstance(node, ast.AugAssign):
            if self.expr(node.value) or self.expr(node.target):
                self._taint_target(node.target)
        elif isinstance(node, ast.NamedExpr):
            if self.expr(node.value):
                self._taint_target(node.target)
        elif isinstance(node, ast.For):
            self.bind_loop_target(node.target, node.iter)
        elif isinstance(node, ast.withitem):
            if node.optional_vars is not None and \
                    self.expr(node.context_expr):
                self._taint_target(node.optional_vars)
        elif isinstance(node, (ast.ListComp, ast.SetComp,
                               ast.GeneratorExp, ast.DictComp)):
            for gen in node.generators:
                self.bind_loop_target(gen.target, gen.iter)

    def _bind_call_return(self, target: ast.Tuple, call: ast.Call) -> bool:
        """Per-element taint for `a, b, n = local_helper(...)` when the
        helper's return tuple is statically visible: a helper returning
        (padded_array, ..., new_len) must not taint the shape ints.
        Returns True when handled."""
        callee = self.index.resolve_call(self.fi.module, self.fi,
                                         call.func)
        if callee is None or isinstance(callee.node, ast.Lambda):
            return False
        ct = self.index.taint(callee)
        if ct is None:           # recursion guard hit — stay conservative
            return False
        rets = [r.value for r in self.index.shallow_nodes(callee)
                if isinstance(r, ast.Return) and r.value is not None]
        if len(rets) != 1 or not isinstance(rets[0], ast.Tuple) or \
                len(rets[0].elts) != len(target.elts):
            return False
        for t, e in zip(target.elts, rets[0].elts):
            if ct.expr(e):
                self._taint_target(t)
        return True

    def bind_loop_target(self, target, it):
        """Pairwise binding through zip/enumerate so static loop indices
        next to traced values stay static."""
        if isinstance(it, ast.Call):
            name = call_target_name(it)
            if name == "zip" and isinstance(target, ast.Tuple) and \
                    len(target.elts) == len(it.args):
                for t, a in zip(target.elts, it.args):
                    self.bind_loop_target(t, a)
                return
            if name == "enumerate" and isinstance(target, ast.Tuple) \
                    and len(target.elts) == 2 and it.args:
                # the counter is always a Python int
                self.bind_loop_target(target.elts[1], it.args[0])
                return
            if name in ("reversed", "sorted", "list", "tuple") and \
                    it.args:
                self.bind_loop_target(target, it.args[0])
                return
            if name == "range":
                if any(self.expr(a) for a in it.args):
                    self._taint_target(target)
                return
        if self.expr(it):
            self._taint_target(target)

    def _taint_target(self, t):
        if isinstance(t, ast.Name):
            self.tainted.add(t.id)
        elif isinstance(t, (ast.Tuple, ast.List)):
            for e in t.elts:
                self._taint_target(e)
        elif isinstance(t, ast.Starred):
            self._taint_target(t.value)

    # -- expression taint ----------------------------------------------
    def expr(self, node: Optional[ast.expr]) -> bool:
        if node is None:
            return False
        if isinstance(node, ast.Constant):
            return False
        if isinstance(node, ast.Name):
            return node.id in self.tainted
        if isinstance(node, ast.Attribute):
            if node.attr in STATIC_ATTRS:
                return False
            return self.expr(node.value)
        if isinstance(node, ast.Subscript):
            return self.expr(node.value) or self.expr(node.slice)
        if isinstance(node, ast.Compare):
            # `x is None` is an identity check on the Python object
            if all(isinstance(op, (ast.Is, ast.IsNot))
                   for op in node.ops):
                return False
            return self.expr(node.left) or \
                any(self.expr(c) for c in node.comparators)
        if isinstance(node, ast.BoolOp):
            return any(self.expr(v) for v in node.values)
        if isinstance(node, ast.BinOp):
            return self.expr(node.left) or self.expr(node.right)
        if isinstance(node, ast.UnaryOp):
            return self.expr(node.operand)
        if isinstance(node, ast.IfExp):
            return self.expr(node.body) or self.expr(node.orelse)
        if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
            return any(self.expr(e) for e in node.elts)
        if isinstance(node, ast.Dict):
            return any(self.expr(v) for v in node.values) or \
                any(self.expr(k) for k in node.keys if k is not None)
        if isinstance(node, ast.Starred):
            return self.expr(node.value)
        if isinstance(node, (ast.ListComp, ast.SetComp,
                             ast.GeneratorExp)):
            # targets were pairwise-bound in _visit_binding; the
            # comprehension's value is its element expression
            return self.expr(node.elt)
        if isinstance(node, ast.DictComp):
            return self.expr(node.key) or self.expr(node.value)
        if isinstance(node, ast.Slice):
            return any(self.expr(e) for e in
                       (node.lower, node.upper, node.step))
        if isinstance(node, ast.Call):
            return self.call_taint(node)
        if isinstance(node, (ast.JoinedStr, ast.Lambda)):
            return False
        return any(self.expr(v) for v in ast.iter_child_nodes(node)
                   if isinstance(v, ast.expr))

    def call_taint(self, node: ast.Call) -> bool:
        name = call_target_name(node)
        parts = call_target_parts(node)
        if name in STATIC_FUNCS or name in SYNC_BUILTINS or \
                name in SYNC_METHODS:
            # syncs are flagged elsewhere; their RESULT is host data
            return False
        if name in ("issubdtype", "result_type", "promote_types",
                    "can_cast", "iinfo", "finfo"):
            return False          # dtype algebra is trace-time Python
        if parts and parts[0] in NUMPY_ROOTS:
            return False          # numpy result is host data
        if parts and parts[0] in ARRAY_ROOTS:
            return True           # jnp./lax./jax. produce traced values
        if isinstance(node.func, ast.Attribute):
            # method on a traced object (x.astype, x.sum, x.at[..].set)
            if self.expr(node.func.value):
                return True
        return any(self.expr(a) for a in node.args) or \
            any(self.expr(k.value) for k in node.keywords)


# calls whose result differs per mesh member / host process — the seed
# of the sharding checker's divergent-control-flow analysis
DIVERGENT_CALLS = {"axis_index", "process_index"}
DIVERGENT_ATTRS = {"rank", "process_index"}


class Divergence:
    """Names in one function holding per-shard/per-host varying values
    (derived from ``lax.axis_index``/``jax.process_index``/``.rank``).

    A Python branch over such a value inside a shard_map body executes a
    DIFFERENT trace per member — collectives under it are issued by some
    members and not others, the classic multi-host deadlock.  Same
    flow-insensitive fixpoint shape as :class:`Taint`, but the property
    tracked is member-divergence, not tracedness: shapes and dtypes of
    divergent values are NOT divergent, arithmetic over them is.
    """

    def __init__(self, index, fi):
        self.index = index
        self.fi = fi
        self.divergent: Set[str] = set()
        nodes = index.shallow_nodes(fi)
        for _ in range(4):
            before = len(self.divergent)
            for node in nodes:
                self._visit(node)
            if len(self.divergent) == before:
                break

    def _visit(self, node):
        if isinstance(node, (ast.Assign, ast.AnnAssign, ast.AugAssign,
                             ast.NamedExpr)):
            value = getattr(node, "value", None)
            if value is not None and self.expr(value):
                targets = node.targets if isinstance(node, ast.Assign) \
                    else [node.target]
                for t in targets:
                    for n in ast.walk(t):
                        if isinstance(n, ast.Name):
                            self.divergent.add(n.id)

    def expr(self, node: Optional[ast.expr]) -> bool:
        if node is None or isinstance(node, (ast.Constant, ast.Lambda)):
            return False
        if isinstance(node, ast.Name):
            return node.id in self.divergent
        if isinstance(node, ast.Attribute):
            if node.attr in DIVERGENT_ATTRS:
                return True
            if node.attr in STATIC_ATTRS:
                return False
            return self.expr(node.value)
        if isinstance(node, ast.Call):
            if call_target_name(node) in DIVERGENT_CALLS:
                return True
            return any(self.expr(a) for a in node.args) or \
                any(self.expr(k.value) for k in node.keywords) or \
                self.expr(node.func)
        return any(self.expr(v) for v in ast.iter_child_nodes(node)
                   if isinstance(v, ast.expr))


def is_iter_adapter(it: ast.expr) -> bool:
    """True when a for-loop's iterable is Python-level container
    iteration (zip/enumerate/.items()/list literals/comprehensions) —
    unrolled at trace time, not a tracer concretization."""
    if isinstance(it, (ast.List, ast.Tuple, ast.ListComp,
                       ast.GeneratorExp, ast.Dict, ast.Set)):
        return True
    if isinstance(it, ast.Call):
        return call_target_name(it) in _ITER_ADAPTERS
    return False
