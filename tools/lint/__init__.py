"""graftlint — framework-aware static analysis for the mxnet-tpu JAX
training stack.

Four checkers (see docs/LINTING.md for the rule catalog):

* trace-safety  — host-sync escapes inside jit-reachable code
* retrace       — static recompile hazards (the compile-time complement
                  of telemetry's record_compile detector)
* donation      — use-after-donate dataflow over donate_argnums users
* pallas        — BlockSpec/grid/index-map consistency + static VMEM
                  footprint vs. the tune_attention_blocks clamp budget

Run ``python -m tools.lint mxnet_tpu/`` (text or ``--format json``).
Findings are suppressed inline with a mandatory reason::

    x = float(v)  # graftlint: disable=trace-host-sync -- epoch boundary

or grandfathered in ``tools/lint/baseline.json``; the tier-1 gate
(``tests/test_lint.py``) fails on any new unsuppressed finding.
"""
from __future__ import annotations

from . import donation, pallas, retrace, trace_safety
from .core import (Finding, LintResult, ModuleInfo, default_baseline_path,
                   diff_baseline, load_baseline, run_lint, write_baseline)

__all__ = ["CHECKERS", "all_rules", "run_lint", "Finding", "LintResult",
           "ModuleInfo", "load_baseline", "write_baseline",
           "diff_baseline", "default_baseline_path"]

CHECKERS = (trace_safety, retrace, donation, pallas)

# rules owned by the runner itself (suppression hygiene)
_META_RULES = {
    "lint-suppression-reason":
        "graftlint suppression without a '-- <reason>' clause",
    "lint-unknown-rule": "suppression names an unknown rule id",
    "lint-parse-error": "file could not be parsed/read",
}


def all_rules() -> dict:
    rules = dict(_META_RULES)
    for c in CHECKERS:
        rules.update(c.RULES)
    return rules
