"""graftlint — framework-aware static analysis for the mxnet-tpu JAX
training stack.

Eight checkers (see docs/LINTING.md for the rule catalog):

* trace-safety  — host-sync escapes inside jit-reachable code
* retrace       — static recompile hazards (the compile-time complement
                  of telemetry's record_compile detector)
* donation      — use-after-donate dataflow over donate_argnums users
* pallas        — BlockSpec/grid/index-map consistency + static VMEM
                  footprint vs. the tune_attention_blocks clamp budget
* sharding      — mesh-axis consistency, collective pairing/order
                  (deadlock shapes) and scan-carry sharding stability
                  over the ``parallel/`` layer; its companion static
                  per-chip HBM estimator lives in ``tools.lint.hbm``
* concurrency   — host-thread races & deadlocks: thread-entry
                  discovery, unguarded shared writes, lock-order
                  cycles, blocking-under-lock, thread lifecycle; its
                  runtime counterpart is the lock-order sanitizer in
                  ``tools.lint.runtime_lockorder``
* numerics      — dtype-flow analysis: implicit promotions,
                  low-precision accumulation, unstable transcendentals,
                  fp32-master and collective working-dtype contracts,
                  float64-under-disabled-x64 surprises; its runtime
                  counterpart is the numerics sanitizer in
                  ``tools.lint.runtime_numerics``
* errorflow     — exception-flow & resource lifecycle: swallowed
                  exceptions in thread/cleanup paths, non-atomic
                  durable-artifact writes, leaked handles on exception
                  edges, PendingRequest terminal-outcome dataflow,
                  incident-trigger drift; its runtime counterpart is
                  the fault-injection coverage auditor in
                  ``tools.lint.chaos_coverage`` (``--audit-chaos``)

Run ``python -m tools.lint mxnet_tpu/`` (text or ``--format json``);
``--changed`` lints only files touched vs ``git merge-base HEAD main``
plus their reverse-dependency closure.  Findings are suppressed inline
with a mandatory reason::

    x = float(v)  # graftlint: disable=trace-host-sync -- epoch boundary

or grandfathered in ``tools/lint/baseline.json``; the tier-1 gate
(``tests/test_lint.py``) fails on any new unsuppressed finding, and
``--audit-suppressions`` flags suppressions whose rule no longer fires.
"""
from __future__ import annotations

from . import concurrency, donation, errorflow, numerics, pallas, \
    retrace, sharding, trace_safety
from .core import (Finding, LintResult, ModuleInfo, default_baseline_path,
                   diff_baseline, load_baseline, run_lint, write_baseline)

__all__ = ["CHECKERS", "all_rules", "rule_family", "run_lint", "Finding",
           "LintResult", "ModuleInfo", "load_baseline", "write_baseline",
           "diff_baseline", "default_baseline_path"]

CHECKERS = (trace_safety, retrace, donation, pallas, sharding,
            concurrency, numerics, errorflow)

# rules owned by the runner itself (suppression hygiene)
_META_RULES = {
    "lint-suppression-reason":
        "graftlint suppression without a '-- <reason>' clause",
    "lint-unknown-rule": "suppression names an unknown rule id",
    "lint-parse-error": "file could not be parsed/read",
    "lint-stale-suppression":
        "suppression whose rule no longer fires on its line "
        "(--audit-suppressions / --write-baseline)",
}


def all_rules() -> dict:
    rules = dict(_META_RULES)
    for c in CHECKERS:
        rules.update(c.RULES)
    return rules


# rule-id prefix -> family name (docs/LINTING.md catalog sections;
# mirrored by tools/parse_log.py which must stay import-free)
_RULE_FAMILIES = {"trace": "trace-safety", "retrace": "retrace",
                  "donate": "donation", "pallas": "pallas",
                  "shard": "sharding", "conc": "concurrency",
                  "num": "numerics", "err": "errorflow",
                  "res": "errorflow", "lint": "meta"}


def rule_family(rule: str) -> str:
    return _RULE_FAMILIES.get(rule.split("-", 1)[0], "other")
