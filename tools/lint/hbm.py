"""Static per-chip HBM footprint estimator.

Pure shape arithmetic (stdlib only — no jax, no numpy): given the leaf
specs of a jitted train step — parameter shapes/dtypes, optimizer
state-leaf multiplicity, the dp-axis size and the layout each leaf
lives in (replicated vs the ZeRO flat zero-padded dp-sharded layout of
``parallel/collectives.py``) — compute the bytes ONE chip holds.  The
padding math mirrors ``collectives.padded_size`` exactly, so the
estimate agrees with the runtime ``optimizer_state_bytes_per_chip``
gauges (cross-checked in ``tests/test_hbm_estimator.py``).

Consumers:

* ``DataParallelStep.hbm_estimate()`` journals a ``hbm/estimate``
  telemetry event per jitted program (rendered by
  ``tools/parse_log.py``);
* the Pallas autotuner (ROADMAP item 4) and the 3D-parallelism
  composition (item 5) use it as the validity predicate for candidate
  layouts before anything is compiled.
"""
from __future__ import annotations

from typing import Dict, Iterable, Optional, Sequence, Tuple

REPLICATED = "replicated"
DP_SHARDED = "dp_sharded"      # flat zero-padded, sharded over the dp axis

_ITEMSIZE = {
    "float64": 8, "int64": 8, "uint64": 8, "complex64": 8,
    "float32": 4, "int32": 4, "uint32": 4,
    "bfloat16": 2, "float16": 2, "int16": 2, "uint16": 2,
    "int8": 1, "uint8": 1, "bool": 1, "bool_": 1,
}


def dtype_itemsize(dtype) -> int:
    """Itemsize of a dtype given by name ('float32', 'bf16'-style names
    fall back to trailing-bit-count parsing); unknown names raise."""
    name = str(dtype)
    if name in _ITEMSIZE:
        return _ITEMSIZE[name]
    digits = ""
    for ch in reversed(name):
        if ch.isdigit():
            digits = ch + digits
        else:
            break
    if digits and int(digits) % 8 == 0:
        return int(digits) // 8
    raise ValueError("unknown dtype %r" % (dtype,))


def padded_size(n: int, axis_size: int) -> int:
    """Smallest multiple of ``axis_size`` >= n (and >= axis_size) — the
    flat zero-padded ZeRO leaf length.  Must stay identical to
    ``mxnet_tpu.parallel.collectives.padded_size``."""
    return max(1, -(-int(n) // int(axis_size))) * int(axis_size)


def _numel(shape: Sequence[int]) -> int:
    n = 1
    for d in shape:
        n *= int(d)
    return n


def leaf_bytes_per_chip(shape: Sequence[int], dtype, layout: str,
                        axis_size: int = 1) -> int:
    """Bytes ONE chip holds for a leaf of ``shape``/``dtype``.

    ``replicated`` leaves cost their full natural size everywhere;
    ``dp_sharded`` leaves live flat zero-padded and each chip holds
    ``padded_size(numel, axis_size) / axis_size`` elements."""
    isz = dtype_itemsize(dtype)
    if layout == REPLICATED or axis_size <= 1:
        return _numel(shape) * isz
    if layout != DP_SHARDED:
        raise ValueError("unknown layout %r" % (layout,))
    return padded_size(_numel(shape), axis_size) * isz // int(axis_size)


def estimate_step_hbm(params: Iterable, *, axis_size: int = 1,
                      state_leaves: int = 0,
                      shard_optimizer: bool = False,
                      multi_precision: bool = False,
                      activations: Iterable = ()) -> Dict[str, int]:
    """Per-chip HBM estimate for one fused train step.

    ``params``: iterable of ``(shape, dtype)`` or ``(shape, dtype,
    trainable)`` tuples (trainable defaults True).  Parameters are
    replicated (the dp layout this codebase trains in).

    ``state_leaves``: elementwise optimizer state leaves per trainable
    param (SGD+momentum: 1, Adam: 2).  Under ``multi_precision``,
    half-width (itemsize < 4) weights carry an fp32 master as an extra
    leaf and their state leaves are fp32 — mirroring
    ``DataParallelStep``.  ``shard_optimizer`` puts every state leaf in
    the flat padded dp-sharded layout (structured/non-elementwise state
    that falls back replicated at runtime is not modeled — pass
    per-leaf calls to :func:`leaf_bytes_per_chip` for exotic slots).

    ``activations``: ``(shape, dtype)`` batch leaves, sharded over dp on
    their leading axis.

    Returns ``{"params_bytes", "opt_state_bytes", "activation_bytes",
    "total_bytes"}`` — all per chip.
    """
    layout = DP_SHARDED if shard_optimizer else REPLICATED
    p_bytes = 0
    s_bytes = 0
    for entry in params:
        shape, dtype = entry[0], entry[1]
        trainable = entry[2] if len(entry) > 2 else True
        p_bytes += leaf_bytes_per_chip(shape, dtype, REPLICATED, axis_size)
        if not trainable:
            continue
        mp_active = multi_precision and dtype_itemsize(dtype) < 4
        state_dtype = "float32" if mp_active else dtype
        n_leaves = state_leaves + (1 if mp_active else 0)
        s_bytes += n_leaves * leaf_bytes_per_chip(shape, state_dtype,
                                                  layout, axis_size)
    a_bytes = 0
    for shape, dtype in activations:
        full = _numel(shape) * dtype_itemsize(dtype)
        a_bytes += full // max(1, int(axis_size))
    return {"params_bytes": p_bytes, "opt_state_bytes": s_bytes,
            "activation_bytes": a_bytes,
            "total_bytes": p_bytes + s_bytes + a_bytes}
