"""Shared framework-aware AST analysis for graftlint.

Builds, over the whole scanned file set:

* a function table (module-level defs, methods, nested defs, lambdas)
  and a class table with package-internal inheritance, so gluon
  ``forward``/``hybrid_forward`` methods of Block-like classes are
  recognized as trace entry points;
* a call-site table with lexical scopes, feeding three analyses:
* **jit-reachability**: a function is jit-reachable when it is
  (a) decorated with / passed to a JAX tracing wrapper (``jax.jit``,
  ``vmap``, ``grad``, ``lax.scan``, ``pl.pallas_call``, ``defvjp``, …),
  (b) registered as a graph op via ``@register`` (ops run under the
  executor's jit), (c) a ``forward``/``hybrid_forward`` method of a
  Block-like class, or (d) called (directly, via ``self.``, or through a
  jit-forwarding helper parameter like ``_mirror_wrap``) from a
  jit-reachable function;
* **config params**: an interprocedural fixpoint marking parameters that
  only ever receive trace-time Python configuration (scalar defaults,
  keyword-only params, ``static_argnums``/``static_argnames``
  declarations, or call sites that always pass literals / other config
  params) — everything else positional is a *tracer param*;
* a small constant folder (ints/tuples, ``min``/``max``/shifts/
  ``bit_length``) used to evaluate ``donate_argnums`` and Pallas block
  shapes statically.
"""
from __future__ import annotations

import ast
from collections import deque
from typing import Dict, List, Optional, Sequence, Set, Tuple

# tracing wrappers: any function-valued argument of a call to one of
# these is traced (and therefore jit-reachable).  Matched on the LAST
# attribute segment so jax.jit / pl.pallas_call / lax.scan all resolve
# without import tracking.
TRACING_WRAPPERS = {
    "jit", "pjit", "pmap", "vmap", "grad", "value_and_grad", "vjp",
    "jvp", "linearize", "checkpoint", "remat", "custom_vjp",
    "custom_jvp", "pallas_call", "scan", "fori_loop", "while_loop",
    "cond", "switch", "associative_scan", "defvjp", "defjvp",
    "named_call", "shard_map", "xmap",
}
# "map" only counts when spelled lax.map / jax.lax.map (bare map() is
# the builtin)
_QUALIFIED_ONLY = {"map": ("lax", "jax")}

# keyword arguments of wrapper calls that are never traced functions
_NON_FN_KWARGS = {"static_argnums", "static_argnames", "donate_argnums",
                  "donate_argnames", "policy", "in_axes", "out_axes",
                  "axis_name", "grid", "in_specs", "out_specs",
                  "out_shape", "scratch_shapes", "compiler_params",
                  "interpret", "length", "reverse", "unroll",
                  "has_aux", "prevent_cse", "dimension_semantics"}

# decorators that make a function a trace entry on their own
ENTRY_DECORATORS = {"register", "custom_vjp", "custom_jvp"}

# gluon Block-like root classes: forward/hybrid_forward methods of their
# (transitive, package-internal) subclasses run under the fused train
# step's jit
BLOCK_ROOTS = {"Block", "HybridBlock", "SymbolBlock", "Loss"}
BLOCK_ENTRY_METHODS = {"forward", "hybrid_forward"}

_SCALAR_CONST = (int, float, bool, str, bytes)


def shallow_walk(node):
    """ast.walk that does NOT descend into nested function/class bodies:
    the caller analyzes exactly one function's own statements (a nested
    def has its own reachability and its own tracer params)."""
    todo = deque(ast.iter_child_nodes(node))
    while todo:
        n = todo.popleft()
        yield n
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.Lambda, ast.ClassDef)):
            continue
        todo.extend(ast.iter_child_nodes(n))


def call_target_name(node: ast.Call) -> Optional[str]:
    """Last dotted segment of the callee ('jax.jit' -> 'jit')."""
    f = node.func
    if isinstance(f, ast.Name):
        return f.id
    if isinstance(f, ast.Attribute):
        return f.attr
    return None


def call_target_parts(node: ast.Call) -> Tuple[str, ...]:
    parts: List[str] = []
    f = node.func
    while isinstance(f, ast.Attribute):
        parts.append(f.attr)
        f = f.value
    if isinstance(f, ast.Name):
        parts.append(f.id)
    return tuple(reversed(parts))


def is_tracing_wrapper_call(node: ast.Call) -> bool:
    name = call_target_name(node)
    if name is None:
        return False
    if name in _QUALIFIED_ONLY:
        parts = call_target_parts(node)
        return len(parts) >= 2 and parts[-2] in _QUALIFIED_ONLY[name]
    return name in TRACING_WRAPPERS


def _is_scalar_config(node: ast.expr) -> bool:
    if isinstance(node, ast.Constant):
        return node.value is None or isinstance(node.value, _SCALAR_CONST)
    if isinstance(node, ast.UnaryOp) and \
            isinstance(node.op, (ast.USub, ast.UAdd)):
        return _is_scalar_config(node.operand)
    if isinstance(node, (ast.Tuple, ast.List)):
        return all(_is_scalar_config(e) for e in node.elts)
    return False


def _has_scalar_default(fi: "FunctionInfo", name: str) -> bool:
    ps = fi.params()
    a = fi.node.args
    if a.defaults:
        for p, d in zip(ps[len(ps) - len(a.defaults):], a.defaults):
            if p.arg == name:
                # None defaults stay traced: optional array operands
                # (kv_lens=None) are the dominant pattern
                return not (isinstance(d, ast.Constant)
                            and d.value is None) and _is_scalar_config(d)
    return False


class FunctionInfo:
    """One function/method/lambda definition."""

    def __init__(self, module, node, qualname: str,
                 parent: Optional["FunctionInfo"], cls: Optional[str]):
        self.module = module                  # core.ModuleInfo
        self.node = node
        self.qualname = qualname
        self.parent = parent
        self.cls = cls                        # enclosing class name or None
        self.reachable = False
        self.entry_reason: Optional[str] = None
        # static params declared at jit sites wrapping this function
        self.static_params: Set[str] = set()
        self.is_method = cls is not None

    @property
    def name(self) -> str:
        if isinstance(self.node, ast.Lambda):
            return "<lambda>"
        return self.node.name

    def params(self) -> List[ast.arg]:
        a = self.node.args
        return list(a.posonlyargs) + list(a.args)

    def param_names(self) -> List[str]:
        return [p.arg for p in self.params()]

    def kwonly_names(self) -> List[str]:
        return [p.arg for p in self.node.args.kwonlyargs]

    def default_expr(self, name: str) -> Optional[ast.expr]:
        """The default-value AST node of parameter ``name`` (positional
        or keyword-only), or None."""
        a = self.node.args
        ps = self.params()
        if a.defaults:
            for p, d in zip(ps[len(ps) - len(a.defaults):], a.defaults):
                if p.arg == name:
                    return d
        for p, d in zip(a.kwonlyargs, a.kw_defaults):
            if p.arg == name and d is not None:
                return d
        return None


class CallSite:
    __slots__ = ("module", "scope", "node", "callee")

    def __init__(self, module, scope, node, callee):
        self.module = module
        self.scope = scope        # FunctionInfo containing the call (or None)
        self.node = node
        self.callee = callee      # resolved FunctionInfo or None


class _ClassInfo:
    def __init__(self, module, node: ast.ClassDef):
        self.module = module
        self.node = node
        self.name = node.name
        self.base_names = []
        for b in node.bases:
            if isinstance(b, ast.Name):
                self.base_names.append(b.id)
            elif isinstance(b, ast.Attribute):
                self.base_names.append(b.attr)


class PackageIndex:
    """Cross-file function/class index + jit-reachability fixpoint."""

    def __init__(self, modules: Sequence):
        self.modules = list(modules)
        self.functions: List[FunctionInfo] = []
        self.by_node: Dict[int, FunctionInfo] = {}
        self.toplevel: Dict[Tuple[str, str], FunctionInfo] = {}
        self.methods: Dict[Tuple[str, str, str], FunctionInfo] = {}
        self.classes: Dict[Tuple[str, str], _ClassInfo] = {}
        self.imports: Dict[str, Dict[str, str]] = {}
        # per-module absolute dotted import candidates (module-dep graph
        # feeding the --changed reverse-dependency closure)
        self._import_targets: Dict[str, Set[str]] = {}
        # direct named children per function node (nested-def lookup)
        self._children: Dict[int, Dict[str, FunctionInfo]] = {}
        for m in modules:
            self._collect(m)
        self._toplevel_by_name: Dict[str, List[FunctionInfo]] = {}
        for (rel, nm), fi in self.toplevel.items():
            self._toplevel_by_name.setdefault(nm, []).append(fi)
        self.call_sites: List[CallSite] = []
        self._calls_by_scope: Dict[int, List[CallSite]] = {}
        self._calls_by_callee: Dict[int, List[CallSite]] = {}
        for m in modules:
            self._collect_calls(m)
        self._blocklike = self._compute_blocklike()
        self._jit_forwarding = self._compute_jit_forwarding_params()
        self._mark_entries()
        self._propagate()
        self._config = self._compute_config_params()
        self._taint_cache: Dict[int, object] = {}
        self._taint_in_progress: Set[int] = set()
        self._shallow_cache: Dict[int, List] = {}
        self._refine_config()

    # -- collection -----------------------------------------------------
    def _collect(self, module):
        imports: Dict[str, str] = {}
        targets: Set[str] = set()
        pkg = module.relpath.rsplit("/", 1)[0].split("/") \
            if "/" in module.relpath else []

        def walk(node, parent_fn, cls_name, prefix):
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.Import, ast.ImportFrom)):
                    if isinstance(child, ast.Import):
                        for alias in child.names:
                            targets.add(alias.name)
                    else:
                        # resolve relative levels against this module's
                        # package: level=1 -> same package, level=2 ->
                        # parent, ...; each imported name may itself be
                        # a submodule (`from . import telemetry`)
                        base = pkg[:len(pkg) - (child.level - 1)] \
                            if child.level else []
                        parts = base + (child.module.split(".")
                                        if child.module else [])
                        mod = ".".join(parts)
                        if mod:
                            targets.add(mod)
                        for alias in child.names:
                            if mod and alias.name != "*":
                                targets.add(mod + "." + alias.name)
                    for alias in child.names:
                        local = alias.asname or alias.name.split(".")[0]
                        imports[local] = alias.name
                    continue
                if isinstance(child, ast.ClassDef):
                    self.classes[(module.relpath, child.name)] = \
                        _ClassInfo(module, child)
                    walk(child, None, child.name,
                         prefix + child.name + ".")
                    continue
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef)):
                    fi = FunctionInfo(module, child, prefix + child.name,
                                      parent_fn, cls_name)
                    self._register_fn(fi)
                    walk(child, fi, cls_name if parent_fn is None
                         else None, prefix + child.name + ".")
                    continue
                if isinstance(child, ast.Lambda):
                    fi = FunctionInfo(
                        module, child,
                        prefix + "<lambda@%d>" % child.lineno,
                        parent_fn, cls_name)
                    self._register_fn(fi)
                    walk(child, fi, None, fi.qualname + ".")
                    continue
                walk(child, parent_fn, cls_name, prefix)

        walk(module.tree, None, None, "")
        self.imports[module.relpath] = imports
        self._import_targets[module.relpath] = targets

    def _register_fn(self, fi: FunctionInfo):
        self.functions.append(fi)
        self.by_node[id(fi.node)] = fi
        if fi.parent is not None and \
                not isinstance(fi.node, ast.Lambda):
            self._children.setdefault(id(fi.parent.node), {}) \
                .setdefault(fi.name, fi)
        if fi.parent is None and fi.cls is None and \
                not isinstance(fi.node, ast.Lambda):
            self.toplevel.setdefault((fi.module.relpath, fi.name), fi)
        if fi.parent is None and fi.cls is not None and \
                not isinstance(fi.node, ast.Lambda):
            self.methods[(fi.module.relpath, fi.cls, fi.name)] = fi

    def _collect_calls(self, module):
        def walk(node, scope):
            for child in ast.iter_child_nodes(node):
                inner = self.by_node.get(id(child))
                nscope = inner if inner is not None else scope
                if isinstance(child, ast.Call):
                    callee = self.resolve_call(module, nscope, child.func)
                    cs = CallSite(module, nscope, child, callee)
                    self.call_sites.append(cs)
                    if nscope is not None:
                        self._calls_by_scope.setdefault(
                            id(nscope.node), []).append(cs)
                    if callee is not None:
                        self._calls_by_callee.setdefault(
                            id(callee.node), []).append(cs)
                walk(child, nscope)

        walk(module.tree, None)

    # -- class hierarchy ------------------------------------------------
    def _compute_blocklike(self) -> Set[Tuple[str, str]]:
        blocklike: Set[Tuple[str, str]] = set()
        names_block: Set[str] = set(BLOCK_ROOTS)
        changed = True
        while changed:
            changed = False
            for key, ci in self.classes.items():
                if key in blocklike:
                    continue
                if any(b in names_block for b in ci.base_names):
                    blocklike.add(key)
                    names_block.add(ci.name)
                    changed = True
        return blocklike

    # -- resolution -----------------------------------------------------
    def resolve_call(self, module, scope: Optional[FunctionInfo],
                     node: ast.expr) -> Optional[FunctionInfo]:
        """Resolve a callee/argument expression to a FunctionInfo."""
        if isinstance(node, ast.Lambda):
            return self.by_node.get(id(node))
        if isinstance(node, ast.Call):
            # functools.partial(f, ...) — analysis follows f
            if call_target_name(node) == "partial" and node.args:
                return self.resolve_call(module, scope, node.args[0])
            return None
        if isinstance(node, ast.Name):
            s = scope
            while s is not None:
                hit = self._nested_def(s, node.id)
                if hit is not None:
                    return hit
                s = s.parent
            hit = self.toplevel.get((module.relpath, node.id))
            if hit is not None:
                return hit
            target = self.imports.get(module.relpath, {}).get(node.id)
            lookup = target.split(".")[-1] if target else node.id
            cands = self._toplevel_by_name.get(lookup, ())
            if len(cands) == 1:
                return cands[0]
            return None
        if isinstance(node, ast.Attribute):
            if isinstance(node.value, ast.Name) and \
                    node.value.id == "self" and scope is not None:
                s, cls = scope, None
                while s is not None and cls is None:
                    cls = s.cls
                    s = s.parent
                if cls is not None:
                    return self.methods.get(
                        (module.relpath, cls, node.attr))
            cands = self._toplevel_by_name.get(node.attr, ())
            if len(cands) == 1:
                return cands[0]
        return None

    def _nested_def(self, scope: FunctionInfo, name: str
                    ) -> Optional[FunctionInfo]:
        return self._children.get(id(scope.node), {}).get(name)

    # -- jit-forwarding helper params -----------------------------------
    def _compute_jit_forwarding_params(self) -> Dict[int, Set[int]]:
        """For helpers like ``_mirror_wrap(fn, mode)`` that pass a
        parameter into a tracing wrapper (``jax.checkpoint(fn)``): the
        parameter indices that forward their argument into a trace."""
        out: Dict[int, Set[int]] = {}
        for fi in self.functions:
            if isinstance(fi.node, ast.Lambda):
                continue
            names = fi.param_names()
            fwd: Set[int] = set()
            for sub in ast.walk(fi.node):
                if isinstance(sub, ast.Call) and \
                        is_tracing_wrapper_call(sub):
                    for a in sub.args:
                        if isinstance(a, ast.Name) and a.id in names:
                            fwd.add(names.index(a.id))
            if fwd:
                out[id(fi.node)] = fwd
        return out

    # -- entry marking --------------------------------------------------
    def _static_decls(self, call: ast.Call, target: FunctionInfo):
        names = target.param_names()
        for kw in call.keywords:
            if kw.arg == "static_argnames":
                for v in _iter_str_constants(kw.value):
                    target.static_params.add(v)
            elif kw.arg == "static_argnums":
                for v in _iter_int_constants(kw.value):
                    if 0 <= v < len(names):
                        target.static_params.add(names[v])

    def _mark_entry(self, fi: FunctionInfo, reason: str):
        if not fi.reachable:
            fi.reachable = True
            fi.entry_reason = reason

    def _custom_vjp_links(self):
        """custom_vjp nondiff awareness: ``@partial(jax.custom_vjp,
        nondiff_argnums=(i,...))`` marks those params static on the
        primal; ``primal.defvjp(fwd, bwd)`` mirrors them onto the fwd
        (same positions) and the bwd (its LEADING len(nondiff) params —
        jax passes nondiff args first to the bwd)."""
        nondiff: Dict[int, Tuple[int, ...]] = {}
        for fi in self.functions:
            if isinstance(fi.node, ast.Lambda):
                continue
            for dec in fi.node.decorator_list:
                if not isinstance(dec, ast.Call):
                    continue
                if call_target_name(dec) != "partial" or not dec.args:
                    continue
                wrapped = dec.args[0]
                wname = wrapped.attr if isinstance(wrapped, ast.Attribute) \
                    else (wrapped.id if isinstance(wrapped, ast.Name)
                          else None)
                if wname != "custom_vjp":
                    continue
                inner = dec
                idxs = []
                for kw in inner.keywords:
                    if kw.arg == "nondiff_argnums":
                        idxs = list(_iter_int_constants(kw.value))
                names = fi.param_names()
                for i in idxs:
                    if 0 <= i < len(names):
                        fi.static_params.add(names[i])
                if idxs:
                    nondiff[id(fi.node)] = tuple(sorted(idxs))
        for cs in self.call_sites:
            node = cs.node
            if not (isinstance(node.func, ast.Attribute)
                    and node.func.attr == "defvjp" and len(node.args) >= 2):
                continue
            primal = self.resolve_call(cs.module, cs.scope,
                                       node.func.value)
            if primal is None:
                continue
            idxs = nondiff.get(id(primal.node), ())
            if not idxs:
                continue
            fwd = self.resolve_call(cs.module, cs.scope, node.args[0])
            bwd = self.resolve_call(cs.module, cs.scope, node.args[1])
            if fwd is not None:
                names = fwd.param_names()
                for i in idxs:
                    if 0 <= i < len(names):
                        fwd.static_params.add(names[i])
            if bwd is not None:
                names = bwd.param_names()
                for n in names[:len(idxs)]:
                    bwd.static_params.add(n)

    def _mark_entries(self):
        self._custom_vjp_links()
        for fi in self.functions:
            node = fi.node
            if not isinstance(node, ast.Lambda):
                for dec in node.decorator_list:
                    dname = None
                    if isinstance(dec, ast.Call):
                        dname = call_target_name(dec)
                    elif isinstance(dec, ast.Name):
                        dname = dec.id
                    elif isinstance(dec, ast.Attribute):
                        dname = dec.attr
                    if dname in ENTRY_DECORATORS or \
                            dname in TRACING_WRAPPERS:
                        self._mark_entry(fi, "decorator:%s" % dname)
                        if isinstance(dec, ast.Call):
                            self._static_decls(dec, fi)
            if fi.is_method and fi.parent is None and \
                    fi.name in BLOCK_ENTRY_METHODS and \
                    (fi.module.relpath, fi.cls) in self._blocklike and \
                    "gluon/data/" not in fi.module.relpath:
                # gluon.data transforms are Blocks by API but execute
                # host-side in DataLoader workers — not trace entries
                self._mark_entry(fi, "block-forward")
        for cs in self.call_sites:
            if not is_tracing_wrapper_call(cs.node):
                continue
            for a in list(cs.node.args) + \
                    [k.value for k in cs.node.keywords
                     if k.arg not in _NON_FN_KWARGS]:
                fi = self.resolve_call(cs.module, cs.scope, a)
                if fi is not None:
                    self._mark_entry(fi, "wrapped:%s"
                                     % call_target_name(cs.node))
                    if call_target_name(cs.node) in ("jit", "pjit"):
                        self._static_decls(cs.node, fi)

    # -- propagation ----------------------------------------------------
    def _propagate(self):
        changed = True
        while changed:
            changed = False
            for cs in self.call_sites:
                if cs.scope is None or not cs.scope.reachable:
                    continue
                if cs.callee is not None and not cs.callee.reachable:
                    cs.callee.reachable = True
                    cs.callee.entry_reason = \
                        "called-from:%s" % cs.scope.qualname
                    changed = True
                if cs.callee is not None:
                    fwd = self._jit_forwarding.get(id(cs.callee.node), ())
                    for idx in fwd:
                        if idx < len(cs.node.args):
                            g = self.resolve_call(cs.module, cs.scope,
                                                  cs.node.args[idx])
                            if g is not None and not g.reachable:
                                g.reachable = True
                                g.entry_reason = "forwarded-via:%s" % \
                                    cs.callee.qualname
                                changed = True

    # -- config params --------------------------------------------------
    def _bind_args(self, cs: CallSite) -> Optional[Dict[str, ast.expr]]:
        """Map call arguments onto the callee's parameter names; None if
        the call uses */** unpacking (binding unknown)."""
        fi = cs.callee
        if any(isinstance(a, ast.Starred) for a in cs.node.args) or \
                any(k.arg is None for k in cs.node.keywords):
            return None
        names = fi.param_names()
        if names and names[0] in ("self", "cls") and fi.is_method and \
                isinstance(cs.node.func, ast.Attribute):
            names = names[1:]
        bound: Dict[str, ast.expr] = {}
        for i, a in enumerate(cs.node.args):
            if i < len(names):
                bound[names[i]] = a
        for k in cs.node.keywords:
            bound[k.arg] = k.value
        return bound

    def _compute_config_params(self) -> Set[Tuple[int, str]]:
        """Fixpoint of (function-node-id, param) pairs that are
        trace-time Python config rather than traced arrays."""
        config: Set[Tuple[int, str]] = set()
        for fi in self.functions:
            # mxnet op convention: a @register-ed op's params WITH
            # defaults (None included) are op ATTRIBUTES — Python config
            # baked into the graph — only default-less positionals are
            # tensor inputs
            is_op = not isinstance(fi.node, ast.Lambda) and any(
                (isinstance(d, ast.Call)
                 and call_target_name(d) == "register")
                or (isinstance(d, ast.Name) and d.id == "register")
                for d in fi.node.decorator_list)
            defaulted: Set[str] = set()
            ps = fi.params()
            nd = len(fi.node.args.defaults)
            if nd:
                defaulted = {p.arg for p in ps[len(ps) - nd:]}
            for n in fi.param_names():
                if n in ("self", "cls") or n in fi.static_params or \
                        _has_scalar_default(fi, n) or \
                        (is_op and n in defaulted):
                    config.add((id(fi.node), n))
            for n in fi.kwonly_names():
                config.add((id(fi.node), n))

        def arg_is_config(cs: CallSite, expr: ast.expr) -> bool:
            if _is_scalar_config(expr):
                return True
            if isinstance(expr, ast.Name) and cs.scope is not None:
                return (id(cs.scope.node), expr.id) in config
            return False

        changed = True
        while changed:
            changed = False
            for fi in self.functions:
                sites = self._calls_by_callee.get(id(fi.node), ())
                if not sites:
                    continue
                bindings = [self._bind_args(cs) for cs in sites]
                if any(b is None for b in bindings):
                    continue
                for n in fi.param_names():
                    if (id(fi.node), n) in config or n in ("self", "cls"):
                        continue
                    exprs = [(cs, b[n]) for cs, b in zip(sites, bindings)
                             if n in b]
                    if exprs and all(arg_is_config(cs, e)
                                     for cs, e in exprs):
                        config.add((id(fi.node), n))
                        changed = True
        return config

    def _refine_config(self):
        """Second config fixpoint using caller taint: a parameter whose
        every observed argument is UNTAINTED in its caller (a loop index,
        a shape read, a folded constant) is trace-time config, not a
        tracer.  Monotone — config only grows, taint only shrinks.
        Runs to convergence (bound = #functions, the longest possible
        caller->helper chain): two sweeps covered the pre-autotune
        package, but config-hood must reach the bottom of deep
        trace-time helper chains like dispatch -> cost-table lookup ->
        search -> candidate enumeration."""
        for _ in range(max(2, len(self.functions))):
            self._taint_cache = {}
            changed = False
            for fi in self.functions:
                sites = self._calls_by_callee.get(id(fi.node), ())
                if not sites:
                    continue
                bindings = [self._bind_args(cs) for cs in sites]
                if any(b is None for b in bindings):
                    continue
                ps = fi.params()
                nd = len(fi.node.args.defaults)
                defaulted = {p.arg for p in ps[len(ps) - nd:]} if nd \
                    else set()
                for n in fi.param_names():
                    if (id(fi.node), n) in self._config or \
                            n in ("self", "cls"):
                        continue
                    exprs = [(cs, b[n]) for cs, b in zip(sites, bindings)
                             if n in b]
                    if exprs:
                        ok = all(self._arg_untainted(cs, e)
                                 for cs, e in exprs)
                    else:
                        # bound at NO observed site: the param always
                        # takes its (scalar) default
                        ok = n in defaulted
                    if ok:
                        self._config.add((id(fi.node), n))
                        changed = True
            if not changed:
                break
        self._taint_cache = {}

    def _arg_untainted(self, cs: CallSite, expr: ast.expr) -> bool:
        if cs.scope is None:
            return _is_scalar_config(expr)
        if not cs.scope.reachable:
            # a caller that is not jit-reachable executes host-side
            # only — its arguments are plain Python values by
            # construction and cannot carry tracers into the callee.
            # Without this, host-only entry points (the tune CLI, the
            # v2 model/program lookup APIs) poison config-hood of the
            # shared dispatch -> cost-table -> search chain.
            return True
        t = self.taint(cs.scope)
        return t is not None and not t.expr(expr)

    def shallow_nodes(self, fi: FunctionInfo):
        """Cached list(shallow_walk(fi.node)) — taint fixpoints and the
        per-function checkers traverse each function many times."""
        nodes = self._shallow_cache.get(id(fi.node))
        if nodes is None:
            nodes = list(shallow_walk(fi.node))
            self._shallow_cache[id(fi.node)] = nodes
        return nodes

    def taint(self, fi: FunctionInfo):
        """Cached per-function Taint analysis.  Returns None when ``fi``
        is already being analyzed (recursive helper chains) — callers
        fall back to conservative whole-value taint."""
        key = id(fi.node)
        t = self._taint_cache.get(key)
        if t is not None:
            return t
        if key in self._taint_in_progress:
            return None
        self._taint_in_progress.add(key)
        try:
            from .tainting import Taint
            t = Taint(self, fi)
        finally:
            self._taint_in_progress.discard(key)
        self._taint_cache[key] = t
        return t

    # -- module-dependency graph (--changed closure) --------------------
    @staticmethod
    def module_dotted(relpath: str) -> str:
        """Dotted module name of a repo-relative path
        ('mxnet_tpu/parallel/mesh.py' -> 'mxnet_tpu.parallel.mesh';
        a package __init__ maps to the package name)."""
        p = relpath[:-3] if relpath.endswith(".py") else relpath
        parts = p.split("/")
        if parts and parts[-1] == "__init__":
            parts = parts[:-1]
        return ".".join(parts)

    def module_deps(self) -> Dict[str, Set[str]]:
        """relpath -> set of relpaths (within the scanned set) it
        imports, resolved through relative levels and
        `from pkg import submodule` forms."""
        by_name = {self.module_dotted(m.relpath): m.relpath
                   for m in self.modules}
        deps: Dict[str, Set[str]] = {}
        for m in self.modules:
            out: Set[str] = set()
            for cand in self._import_targets.get(m.relpath, ()):
                hit = by_name.get(cand)
                if hit is not None and hit != m.relpath:
                    out.add(hit)
            deps[m.relpath] = out
        return deps

    def reverse_dependency_closure(self, changed) -> Set[str]:
        """relpaths that transitively import any of ``changed``
        (changed files themselves included) — the set whose findings can
        move when ``changed`` moves."""
        deps = self.module_deps()
        rev: Dict[str, Set[str]] = {}
        for src, outs in deps.items():
            for dst in outs:
                rev.setdefault(dst, set()).add(src)
        known = {m.relpath for m in self.modules}
        todo = deque(c for c in changed if c in known)
        seen: Set[str] = set(todo)
        while todo:
            cur = todo.popleft()
            for imp in rev.get(cur, ()):
                if imp not in seen:
                    seen.add(imp)
                    todo.append(imp)
        return seen

    # -- host-thread entries (concurrency checker) ----------------------
    def _resolve_thread_target(self, cs: CallSite, node: ast.expr
                               ) -> Optional[FunctionInfo]:
        """Resolve a ``threading.Thread(target=...)`` expression: plain
        names and ``partial`` ride :meth:`resolve_call`; ``Cls.method``
        spellings (the prefetcher's ``DevicePrefetchIter._feed``) and
        ``self.method`` resolve through the method table."""
        if isinstance(node, ast.Call) and \
                call_target_name(node) == "partial" and node.args:
            return self._resolve_thread_target(cs, node.args[0])
        if isinstance(node, ast.Attribute) and \
                isinstance(node.value, ast.Name):
            hit = self.methods.get(
                (cs.module.relpath, node.value.id, node.attr))
            if hit is not None:
                return hit
        return self.resolve_call(cs.module, cs.scope, node)

    def thread_entries(self) -> Dict[int, str]:
        """{function-node-id: entry description} for every function a
        ``threading.Thread(target=...)`` call site names (the host-side
        analogue of :meth:`_mark_entries`' tracing wrappers)."""
        cached = getattr(self, "_thread_entries", None)
        if cached is not None:
            return cached
        entries: Dict[int, str] = {}
        for cs in self.call_sites:
            if call_target_name(cs.node) != "Thread":
                continue
            parts = call_target_parts(cs.node)
            if len(parts) > 1 and parts[-2] != "threading":
                continue
            target = None
            for kw in cs.node.keywords:
                if kw.arg == "target":
                    target = kw.value
            if target is None and len(cs.node.args) > 1:
                # threading.Thread(group, target, ...) positional form
                target = cs.node.args[1]
            if target is None:
                continue
            fi = self._resolve_thread_target(cs, target)
            if fi is not None:
                entries.setdefault(
                    id(fi.node), "%s:%d" % (cs.module.relpath,
                                            cs.node.lineno))
        self._thread_entries = entries
        return entries

    def thread_reachable(self) -> Set[int]:
        """Function-node-ids reachable from a thread entry — the code
        that runs OFF the main thread.  Propagation follows resolved
        call sites plus one receiver-blind step: inside a
        thread-reachable function of class ``C``, an unresolved
        ``<expr>.m(...)`` call resolves to ``C.m`` when it exists (the
        weakref-deref idiom ``it = wref(); it._ship(...)``)."""
        cached = getattr(self, "_thread_reachable", None)
        if cached is not None:
            return cached
        reach: Set[int] = set(self.thread_entries())
        changed = True
        while changed:
            changed = False
            for cs in self.call_sites:
                if cs.scope is None or id(cs.scope.node) not in reach:
                    continue
                callee = cs.callee
                if callee is None and \
                        isinstance(cs.node.func, ast.Attribute):
                    s, cls = cs.scope, None
                    while s is not None and cls is None:
                        cls = s.cls
                        s = s.parent
                    if cls is not None:
                        callee = self.methods.get(
                            (cs.module.relpath, cls, cs.node.func.attr))
                if callee is not None and id(callee.node) not in reach:
                    reach.add(id(callee.node))
                    changed = True
        self._thread_reachable = reach
        return reach

    # -- queries --------------------------------------------------------
    def function_at(self, node) -> Optional[FunctionInfo]:
        return self.by_node.get(id(node))

    def functions_in(self, module) -> List[FunctionInfo]:
        # cached: every checker iterates per module, and a linear scan
        # of the whole function table per (checker, module) pair is the
        # dominant cost of a full-package run
        cache = getattr(self, "_fns_by_module", None)
        if cache is None:
            cache = {}
            for fi in self.functions:
                cache.setdefault(id(fi.module), []).append(fi)
            self._fns_by_module = cache
        return cache.get(id(module), [])

    def calls_in(self, module) -> List[CallSite]:
        """All call sites lexically in ``module`` (cached, source
        order)."""
        cache = getattr(self, "_calls_by_module", None)
        if cache is None:
            cache = {}
            for cs in self.call_sites:
                cache.setdefault(id(cs.module), []).append(cs)
            self._calls_by_module = cache
        return cache.get(id(module), [])

    def calls_in_scope(self, fi: FunctionInfo) -> List[CallSite]:
        return self._calls_by_scope.get(id(fi.node), [])

    def is_config_param(self, fi: FunctionInfo, name: str) -> bool:
        return (id(fi.node), name) in self._config

    def tracer_params(self, fi: FunctionInfo) -> Set[str]:
        """Positional parameters treated as traced array values."""
        out: Set[str] = set()
        for n in fi.param_names():
            if n in ("self", "cls"):
                continue
            if (id(fi.node), n) in self._config:
                continue
            out.add(n)
        return out


def _iter_str_constants(node):
    for sub in ast.walk(node):
        if isinstance(sub, ast.Constant) and isinstance(sub.value, str):
            yield sub.value


def _iter_int_constants(node):
    for sub in ast.walk(node):
        if isinstance(sub, ast.Constant) and isinstance(sub.value, int) \
                and not isinstance(sub.value, bool):
            yield sub.value


# ---------------------------------------------------------------------------
# constant folding
# ---------------------------------------------------------------------------

class NotConst(Exception):
    pass


_BINOPS = {
    ast.Add: lambda a, b: a + b,
    ast.Sub: lambda a, b: a - b,
    ast.Mult: lambda a, b: a * b,
    ast.FloorDiv: lambda a, b: a // b,
    ast.Div: lambda a, b: a / b,
    ast.Mod: lambda a, b: a % b,
    ast.Pow: lambda a, b: a ** b,
    ast.LShift: lambda a, b: a << b,
    ast.RShift: lambda a, b: a >> b,
    ast.BitOr: lambda a, b: a | b,
    ast.BitAnd: lambda a, b: a & b,
}

_CMPOPS = {
    ast.Lt: lambda a, b: a < b, ast.LtE: lambda a, b: a <= b,
    ast.Gt: lambda a, b: a > b, ast.GtE: lambda a, b: a >= b,
    ast.Eq: lambda a, b: a == b, ast.NotEq: lambda a, b: a != b,
}


def fold(node: ast.expr, env: Optional[Dict[str, object]] = None):
    """Evaluate an int/tuple expression statically; raises NotConst.

    Supports the arithmetic this codebase uses for block sizing:
    literals, names from ``env``, +,-,*,//,/,%,**,<<,>>, unary -,
    min/max/abs/int/round, ``x.bit_length()``, tuples, subscripts, and
    conditional expressions with foldable tests."""
    env = env or {}
    if isinstance(node, ast.Constant):
        if node.value is None or isinstance(node.value, _SCALAR_CONST):
            return node.value
        raise NotConst()
    if isinstance(node, ast.Name):
        if node.id in env and env[node.id] is not None:
            return env[node.id]
        raise NotConst()
    if isinstance(node, ast.BinOp):
        op = _BINOPS.get(type(node.op))
        if op is None:
            raise NotConst()
        return op(fold(node.left, env), fold(node.right, env))
    if isinstance(node, ast.UnaryOp):
        v = fold(node.operand, env)
        if isinstance(node.op, ast.USub):
            return -v
        if isinstance(node.op, ast.UAdd):
            return +v
        if isinstance(node.op, ast.Not):
            return not v
        raise NotConst()
    if isinstance(node, (ast.Tuple, ast.List)):
        return tuple(fold(e, env) for e in node.elts)
    if isinstance(node, ast.Call):
        name = call_target_name(node)
        if name in ("min", "max", "abs", "int", "float", "round") \
                and node.args and not node.keywords:
            args = [fold(a, env) for a in node.args]
            return {"min": min, "max": max, "abs": abs, "int": int,
                    "float": float, "round": round}[name](*args)
        if name == "bit_length" and isinstance(node.func, ast.Attribute):
            return fold(node.func.value, env).bit_length()
        raise NotConst()
    if isinstance(node, ast.IfExp):
        return fold(node.body, env) if fold(node.test, env) \
            else fold(node.orelse, env)
    if isinstance(node, ast.Compare) and len(node.ops) == 1:
        f = _CMPOPS.get(type(node.ops[0]))
        if f is None:
            raise NotConst()
        return f(fold(node.left, env), fold(node.comparators[0], env))
    if isinstance(node, ast.Subscript):
        v = fold(node.value, env)
        i = fold(node.slice, env)
        return v[i]
    raise NotConst()


def fold_or_none(node, env=None):
    try:
        return fold(node, env)
    except Exception:
        return None
