"""sharding checker: mesh-axis, collective and scan-carry semantics.

The ``parallel/`` layer's failure modes are silent: a mistyped mesh
axis or a mismatched ``PartitionSpec`` rank produces wrong numerics or
a trace error only on a real multi-chip mesh; a collective issued by
some mesh members and not others (divergent control flow inside a
``shard_map`` body) is a cross-host hang no CPU test can reproduce; an
unbalanced ``reduce_scatter_padded``/``all_gather_unpad`` pair corrupts
the ZeRO flat layout; a scan carry whose sharding constraint differs
between iteration entry and exit resharded every step (a silent
recompile/collective per iteration).  GSPMD (arxiv 2105.04663) shows
sharding programs have a checkable propagation semantics — these rules
are the reviewable subset of it:

* ``shard-axis-unknown`` — an ``axis_name=``/``PartitionSpec`` axis
  that does not resolve to an axis declared by the enclosing
  ``shard_map``'s mesh/specs (or, when those stay symbolic, by any mesh
  declaration in the scanned package);
* ``shard-spec-rank`` — a ``PartitionSpec`` with more entries than the
  statically-known rank of the constrained array;
* ``shard-collective-pairing`` — a ``reduce_scatter_padded`` whose
  paired ``all_gather_unpad`` reconstructs a different flat padded size
  (or runs over a different axis), evaluated with the same constant
  folder the ``padded_size``/``flatten_pad`` arithmetic uses;
* ``shard-collective-order`` — collectives issued under control flow
  that diverges across mesh members (a branch over ``lax.axis_index``/
  ``process_index``, differing per-branch collective sequences, or
  ``lax.cond``/``switch`` branches with asymmetric collectives) inside
  a ``shard_map`` body — the classic multi-host deadlock shape;
* ``shard-carry-reshard`` — a ``lax.scan`` carry element constrained to
  two different shardings between iteration entry and exit.

Static HBM estimation (the companion facility this family gates for —
see docs/PERF.md) lives in :mod:`tools.lint.hbm`.
"""
from __future__ import annotations

import ast
from typing import Dict, List, Optional, Sequence, Set, Tuple

from .core import Finding, ModuleInfo
from .hbm import padded_size
from .jitgraph import (PackageIndex, FunctionInfo, call_target_name,
                       fold_or_none)
from .tainting import Divergence

RULES = {
    "shard-axis-unknown":
        "axis_name/PartitionSpec axis does not resolve to an axis "
        "declared by the enclosing mesh/specs (or any mesh in the "
        "package)",
    "shard-spec-rank":
        "PartitionSpec has more entries than the statically-known rank "
        "of the constrained array",
    "shard-collective-pairing":
        "reduce_scatter_padded/all_gather_unpad pair with mismatched "
        "flat padded sizes or axes (corrupts the ZeRO flat layout)",
    "shard-collective-order":
        "collective issued under mesh-member-divergent control flow or "
        "with per-branch order divergence inside a shard_map body "
        "(multi-host deadlock shape)",
    "shard-carry-reshard":
        "lax.scan carry constrained to different shardings at iteration "
        "entry vs exit (per-step reshard/recompile hazard)",
}

SHARD_MAP_NAMES = {"shard_map", "shard_map_compat", "_shard_map", "xmap"}
_SPEC_NAMES = {"P", "PartitionSpec"}
_MESH_CTORS = {"Mesh", "device_mesh", "make_mesh"}

# collective -> positional index of its axis operand (an axis_name=
# keyword always wins); pvary/pcast take a TUPLE of axes
COLLECTIVES = {
    "psum": 1, "pmean": 1, "pmax": 1, "pmin": 1, "all_gather": 1,
    "psum_scatter": 1, "reduce_scatter": 1, "ppermute": 1,
    "all_to_all": 1, "axis_index": 0, "pbroadcast": 1, "pshuffle": 1,
    "reduce_scatter_padded": 1, "all_gather_unpad": 2, "pvary": 1,
    "pcast": 1,
}
# the subset that moves data: order across members matters (axis_index
# and the vma casts are local and cannot hang)
_ORDERED = set(COLLECTIVES) - {"axis_index", "pvary", "pcast"}


# ---------------------------------------------------------------------------
# shared resolution helpers
# ---------------------------------------------------------------------------

def _chase_name(index: PackageIndex, module: ModuleInfo,
                scope: Optional[FunctionInfo], name: str,
                depth: int = 0) -> Optional[ast.expr]:
    """The value expression last bound to ``name``: scope chain first
    (single-target assignments only), then module level."""
    if depth > 4:
        return None
    s = scope
    while s is not None:
        for stmt in index.shallow_nodes(s):
            if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 \
                    and isinstance(stmt.targets[0], ast.Name) \
                    and stmt.targets[0].id == name:
                return stmt.value
        s = s.parent
    for stmt in module.tree.body:
        if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 \
                and isinstance(stmt.targets[0], ast.Name) \
                and stmt.targets[0].id == name:
            return stmt.value
    return None


def _resolve_symbol(index, module, scope, name) -> Optional[str]:
    """Resolve a Name used as an axis to a string: a parameter's string
    default along the scope chain, or a local/module assignment that
    folds to a string."""
    s = scope
    while s is not None:
        if not isinstance(s.node, ast.Lambda) and \
                (name in s.param_names() or name in s.kwonly_names()):
            d = s.default_expr(name)
            v = fold_or_none(d) if d is not None else None
            return v if isinstance(v, str) else None
        s = s.parent
    bound = _chase_name(index, module, scope, name)
    if bound is not None:
        v = fold_or_none(bound)
        if isinstance(v, str):
            return v
    return None


def _axis_name_tuple(call: ast.Call) -> Optional[ast.expr]:
    """The axis-names operand of a Mesh/device_mesh constructor call."""
    cand = call.args[1] if len(call.args) > 1 else None
    for kw in call.keywords:
        if kw.arg == "axis_names":
            cand = kw.value
    return cand


def _fold_axis_names(expr: Optional[ast.expr]) -> Optional[Tuple[str, ...]]:
    v = fold_or_none(expr) if expr is not None else None
    if isinstance(v, str):
        return (v,)
    if isinstance(v, tuple) and v and all(isinstance(x, str) for x in v):
        return v
    return None


def _mesh_axes(index, module, scope, expr, depth=0
               ) -> Optional[Tuple[str, ...]]:
    """Statically-known axis names of a mesh expression, or None."""
    if expr is None or depth > 3:
        return None
    if isinstance(expr, ast.Call) and \
            call_target_name(expr) in _MESH_CTORS:
        return _fold_axis_names(_axis_name_tuple(expr))
    if isinstance(expr, ast.Name):
        bound = _chase_name(index, module, scope, expr.id)
        if bound is not None and bound is not expr:
            return _mesh_axes(index, module, scope, bound, depth + 1)
    return None


def _axis_universe(index: PackageIndex) -> Set[str]:
    """Every mesh axis the scanned package declares: Mesh/device_mesh
    axis_names literals, ``axis_names`` membership checks,
    ``mesh.shape["..."]`` subscripts, and the string defaults of
    ``axis``/``axis_name``/``axis_names`` parameters (each parallel
    component's canonical axis)."""
    cached = getattr(index, "_shard_axis_universe", None)
    if cached is not None:
        return cached
    uni: Set[str] = set()

    def scan(node):
        if isinstance(node, ast.Compare):
            ops = [node.left] + list(node.comparators)
            if any(isinstance(o, ast.Attribute)
                   and o.attr == "axis_names" for o in ops):
                for o in ops:
                    if isinstance(o, ast.Constant) and \
                            isinstance(o.value, str):
                        uni.add(o.value)
        elif isinstance(node, ast.Subscript):
            if isinstance(node.value, ast.Attribute) and \
                    node.value.attr == "shape" and \
                    isinstance(node.slice, ast.Constant) and \
                    isinstance(node.slice.value, str):
                uni.add(node.slice.value)

    # mesh constructors ride the call-site table; membership checks and
    # shape subscripts ride the cached per-function node lists plus the
    # module-level statements — no fresh full-tree walk
    for cs in index.call_sites:
        if call_target_name(cs.node) in _MESH_CTORS:
            axes = _fold_axis_names(_axis_name_tuple(cs.node))
            if axes:
                uni.update(axes)
    for fi in index.functions:
        if isinstance(fi.node, ast.Lambda):
            continue
        for node in index.shallow_nodes(fi):
            scan(node)
        for n in fi.param_names() + fi.kwonly_names():
            if n in ("axis", "axis_name", "axis_names"):
                axes = _fold_axis_names(fi.default_expr(n))
                if axes:
                    uni.update(axes)
    for m in index.modules:
        # module- and class-level statements, SKIPPING function bodies
        # (those ride the cached shallow_nodes loop above) but not their
        # siblings — a declaration after a def must still count
        todo = list(ast.iter_child_nodes(m.tree))
        while todo:
            node = todo.pop()
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)):
                continue
            scan(node)
            todo.extend(ast.iter_child_nodes(node))
    index._shard_axis_universe = uni
    return uni


def _axis_refs(expr: Optional[ast.expr]
               ) -> List[Tuple[ast.expr, Optional[str], Optional[str]]]:
    """(node, literal, symbol) triples for every axis mentioned in an
    axis operand (string, name, or tuple/list of either)."""
    out: List[Tuple[ast.expr, Optional[str], Optional[str]]] = []

    def one(e):
        if isinstance(e, ast.Constant) and isinstance(e.value, str):
            out.append((e, e.value, None))
        elif isinstance(e, ast.Name):
            out.append((e, None, e.id))
        elif isinstance(e, (ast.Tuple, ast.List)):
            for x in e.elts:
                one(x)

    if expr is not None:
        one(expr)
    return out


def _axis_operand(call: ast.Call) -> Optional[ast.expr]:
    for kw in call.keywords:
        if kw.arg == "axis_name":
            return kw.value
    idx = COLLECTIVES[call_target_name(call)]
    if idx < len(call.args):
        return call.args[idx]
    return None


# ---------------------------------------------------------------------------
# shard_map sites
# ---------------------------------------------------------------------------

class _Site:
    __slots__ = ("call", "scope", "body_fns", "vocab_vals", "vocab_syms",
                 "mesh_axes", "spec_calls")

    def __init__(self):
        self.body_fns: List[FunctionInfo] = []
        self.vocab_vals: Set[str] = set()
        self.vocab_syms: Set[str] = set()
        self.mesh_axes: Optional[Tuple[str, ...]] = None
        self.spec_calls: List[ast.Call] = []


def _nested_fns(index: PackageIndex, root: FunctionInfo
                ) -> List[FunctionInfo]:
    out = []
    for fi in index.functions:
        p = fi
        while p is not None:
            if p is root:
                out.append(fi)
                break
            p = p.parent
    return out


def _spec_calls_in(index, module, scope, expr, depth=0) -> List[ast.Call]:
    """PartitionSpec/P Call nodes inside a spec container expression,
    chasing Names bound to local containers (``in_specs = (...)``)."""
    if expr is None or depth > 3:
        return []
    if isinstance(expr, ast.Name):
        bound = _chase_name(index, module, scope, expr.id)
        if bound is None or bound is expr:
            return []
        return _spec_calls_in(index, module, scope, bound, depth + 1)
    out = []
    for node in ast.walk(expr):
        if isinstance(node, ast.Call) and \
                call_target_name(node) in _SPEC_NAMES:
            out.append(node)
        elif isinstance(node, ast.Name) and node is not expr and \
                depth < 2:
            bound = _chase_name(index, module, scope, node.id)
            if isinstance(bound, ast.Call) and \
                    call_target_name(bound) in _SPEC_NAMES:
                out.append(bound)
    return out


def _implicit_decls(index, module, scope, site: _Site):
    """Axis names the enclosing function already validates against the
    mesh (``mesh.shape[axis]`` subscripts, ``axis in mesh.axis_names``
    membership checks) — runtime-checked declarations."""
    s = scope
    while s is not None:
        for node in index.shallow_nodes(s):
            if isinstance(node, ast.Subscript) and \
                    isinstance(node.value, ast.Attribute) and \
                    node.value.attr == "shape":
                if isinstance(node.slice, ast.Constant) and \
                        isinstance(node.slice.value, str):
                    site.vocab_vals.add(node.slice.value)
                elif isinstance(node.slice, ast.Name):
                    site.vocab_syms.add(node.slice.id)
            elif isinstance(node, ast.Compare):
                ops = [node.left] + list(node.comparators)
                if any(isinstance(o, ast.Attribute)
                       and o.attr == "axis_names" for o in ops):
                    for o in ops:
                        if isinstance(o, ast.Constant) and \
                                isinstance(o.value, str):
                            site.vocab_vals.add(o.value)
                        elif isinstance(o, ast.Name):
                            site.vocab_syms.add(o.id)
        s = s.parent


def _shard_map_sites(module: ModuleInfo, index: PackageIndex
                     ) -> List[_Site]:
    sites = []
    for cs in index.calls_in(module):
        if call_target_name(cs.node) not in SHARD_MAP_NAMES or \
                not cs.node.args:
            continue
        site = _Site()
        site.call = cs.node
        site.scope = cs.scope
        body = index.resolve_call(cs.module, cs.scope, cs.node.args[0])
        if body is not None:
            site.body_fns = _nested_fns(index, body)
        mesh_expr = cs.node.args[1] if len(cs.node.args) > 1 else None
        spec_exprs = list(cs.node.args[2:])
        for kw in cs.node.keywords:
            if kw.arg == "mesh":
                mesh_expr = kw.value
            else:
                spec_exprs.append(kw.value)
        site.mesh_axes = _mesh_axes(index, module, cs.scope, mesh_expr)
        if site.mesh_axes:
            site.vocab_vals.update(site.mesh_axes)
        for expr in spec_exprs:
            site.spec_calls.extend(
                _spec_calls_in(index, module, cs.scope, expr))
        for spec in site.spec_calls:
            for _, lit, sym in _axis_refs(ast.Tuple(elts=list(spec.args))):
                if lit is not None:
                    site.vocab_vals.add(lit)
                elif sym is not None:
                    site.vocab_syms.add(sym)
                    val = _resolve_symbol(index, module, cs.scope, sym)
                    if val is not None:
                        site.vocab_vals.add(val)
        _implicit_decls(index, module, cs.scope, site)
        for sym in list(site.vocab_syms):
            val = _resolve_symbol(index, module, cs.scope, sym)
            if val is not None:
                site.vocab_vals.add(val)
        sites.append(site)
    return sites


# ---------------------------------------------------------------------------
# rule: shard-axis-unknown
# ---------------------------------------------------------------------------

def _check_axis_ref(module, index, scope, site, universe, node, lit, sym,
                    where, findings, reported):
    if lit is not None:
        val = lit
    else:
        if site is not None and sym in site.vocab_syms:
            return
        val = _resolve_symbol(index, module, scope, sym)
        if val is None:
            return            # symbolic and untrackable: stay quiet
    ok = False
    if site is not None and site.mesh_axes:
        ok = val in site.mesh_axes
    elif site is not None and val in site.vocab_vals:
        ok = True
    elif universe and val in universe:
        ok = True
    elif not universe:
        ok = True             # nothing declared anywhere: no basis
    if ok or (id(node), val) in reported:
        return
    reported.add((id(node), val))
    declared = site.mesh_axes if (site is not None and site.mesh_axes) \
        else tuple(sorted((site.vocab_vals if site is not None
                           and site.vocab_vals else universe)))
    findings.append(Finding(
        "shard-axis-unknown", module.relpath, node.lineno,
        node.col_offset,
        "%s references mesh axis %r, not among the declared axes %r"
        % (where, val, tuple(declared)),
        scope.qualname if scope else "<module>"))


def _check_axes(module, index, sites, universe, findings):
    reported: Set[Tuple[int, str]] = set()
    in_site_specs: Set[int] = set()
    in_site_bodies: Set[int] = set()
    for site in sites:
        for spec in site.spec_calls:
            in_site_specs.add(id(spec))
            for n, lit, _ in _axis_refs(ast.Tuple(elts=list(spec.args))):
                if lit is not None and site.mesh_axes and \
                        lit not in site.mesh_axes:
                    _check_axis_ref(module, index, site.scope, site,
                                    universe, n, lit, None,
                                    "PartitionSpec", findings, reported)
        for fi in site.body_fns:
            in_site_bodies.add(id(fi.node))
            for node in index.shallow_nodes(fi):
                if isinstance(node, ast.Call) and \
                        call_target_name(node) in COLLECTIVES:
                    for n, lit, sym in _axis_refs(_axis_operand(node)):
                        _check_axis_ref(
                            module, index, fi, site, universe, n, lit,
                            sym, "%s()" % call_target_name(node),
                            findings, reported)
    # outside any shard_map site: literal axis names in collectives and
    # PartitionSpecs still have to exist SOMEWHERE in the package
    for fi in index.functions_in(module):
        if isinstance(fi.node, ast.Lambda) or id(fi.node) in in_site_bodies:
            continue
        for node in index.shallow_nodes(fi):
            if not isinstance(node, ast.Call):
                continue
            name = call_target_name(node)
            if name in COLLECTIVES and fi.reachable:
                for n, lit, _ in _axis_refs(_axis_operand(node)):
                    if lit is not None:
                        _check_axis_ref(module, index, fi, None,
                                        universe, n, lit, None,
                                        "%s()" % name, findings,
                                        reported)
            elif name in _SPEC_NAMES and id(node) not in in_site_specs:
                for n, lit, _ in _axis_refs(
                        ast.Tuple(elts=list(node.args))):
                    if lit is not None:
                        _check_axis_ref(module, index, fi, None,
                                        universe, n, lit, None,
                                        "PartitionSpec", findings,
                                        reported)
    return findings


# ---------------------------------------------------------------------------
# rule: shard-spec-rank
# ---------------------------------------------------------------------------

_RANK1_CALLS = {"flatten_pad", "arange", "linspace", "ravel", "flatten"}
_SHAPED_CTORS = {"zeros", "ones", "full", "empty"}


def _expr_rank(index, module, fi, expr, env: Dict[str, int], depth=0
               ) -> Optional[int]:
    """Statically-known rank of an array expression (conservative:
    None when unknown)."""
    if depth > 3 or expr is None:
        return None
    if isinstance(expr, ast.Name):
        return env.get(expr.id)
    if not isinstance(expr, ast.Call):
        return None
    name = call_target_name(expr)
    if name in _RANK1_CALLS:
        return 1
    if name == "reshape" and expr.args:
        if len(expr.args) == 1 and isinstance(expr.args[0],
                                              (ast.Tuple, ast.List)):
            return len(expr.args[0].elts)
        return len(expr.args)
    if name in _SHAPED_CTORS and expr.args:
        a = expr.args[0]
        if isinstance(a, (ast.Tuple, ast.List)):
            return len(a.elts)
        if isinstance(a, ast.Constant) and isinstance(a.value, int):
            return 1
    return None


def _local_ranks(index, module, fi) -> Dict[str, int]:
    env: Dict[str, int] = {}
    for _ in range(2):
        for stmt in index.shallow_nodes(fi):
            if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 \
                    and isinstance(stmt.targets[0], ast.Name):
                r = _expr_rank(index, module, fi, stmt.value, env)
                if r is not None:
                    env[stmt.targets[0].id] = r
    return env


def _spec_call_of(index, module, scope, expr, depth=0
                  ) -> Optional[ast.Call]:
    """The P/PartitionSpec Call a sharding expression boils down to:
    direct, inside NamedSharding(mesh, spec), or via a Name binding."""
    if expr is None or depth > 3:
        return None
    if isinstance(expr, ast.Call):
        name = call_target_name(expr)
        if name in _SPEC_NAMES:
            return expr
        if name == "NamedSharding" and len(expr.args) >= 2:
            return _spec_call_of(index, module, scope, expr.args[1],
                                 depth + 1)
    if isinstance(expr, ast.Name):
        bound = _chase_name(index, module, scope, expr.id)
        if bound is not None and bound is not expr:
            return _spec_call_of(index, module, scope, bound, depth + 1)
    return None


def _check_spec_rank(module, index, findings):
    for fi in index.functions_in(module):
        if isinstance(fi.node, ast.Lambda):
            continue
        env = None
        for node in index.shallow_nodes(fi):
            if not isinstance(node, ast.Call):
                continue
            name = call_target_name(node)
            if name == "with_sharding_constraint" and len(node.args) >= 2:
                target, sh = node.args[0], node.args[1]
            elif name == "device_put" and len(node.args) >= 2:
                target, sh = node.args[0], node.args[1]
            else:
                continue
            spec = _spec_call_of(index, module, fi, sh)
            if spec is None or not spec.args:
                continue
            if env is None:
                env = _local_ranks(index, module, fi)
            rank = _expr_rank(index, module, fi, target, env)
            if rank is not None and len(spec.args) > rank:
                findings.append(Finding(
                    "shard-spec-rank", module.relpath, node.lineno,
                    node.col_offset,
                    "PartitionSpec has %d entries but the constrained "
                    "array has rank %d" % (len(spec.args), rank),
                    fi.qualname))


# ---------------------------------------------------------------------------
# rule: shard-collective-pairing
# ---------------------------------------------------------------------------

def _local_shapes(index, fi) -> Dict[str, Tuple[int, ...]]:
    """name -> statically-folded shape for literal array constructors."""
    env: Dict[str, Tuple[int, ...]] = {}
    for stmt in index.shallow_nodes(fi):
        if not (isinstance(stmt, ast.Assign) and len(stmt.targets) == 1
                and isinstance(stmt.targets[0], ast.Name)
                and isinstance(stmt.value, ast.Call)):
            continue
        if call_target_name(stmt.value) in _SHAPED_CTORS and \
                stmt.value.args:
            v = fold_or_none(stmt.value.args[0])
            if isinstance(v, int):
                v = (v,)
            if isinstance(v, tuple) and all(isinstance(x, int)
                                            for x in v):
                env[stmt.targets[0].id] = v
    return env


def _numel(shape) -> int:
    n = 1
    for d in shape:
        n *= int(d)
    return n


def _fold_env(index, fi) -> Dict[str, object]:
    env: Dict[str, object] = {}
    for stmt in index.shallow_nodes(fi):
        if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 and \
                isinstance(stmt.targets[0], ast.Name):
            v = fold_or_none(stmt.value, env)
            if v is not None:
                env[stmt.targets[0].id] = v
    return env


def _rs_axis_size(call: ast.Call, env) -> Optional[int]:
    cand = call.args[2] if len(call.args) > 2 else None
    for kw in call.keywords:
        if kw.arg == "axis_size":
            cand = kw.value
    v = fold_or_none(cand, env) if cand is not None else None
    return int(v) if isinstance(v, int) and v > 0 else None


def _axis_key(index, module, scope, call) -> Optional[str]:
    refs = _axis_refs(_axis_operand(call))
    if len(refs) != 1:
        return None
    _, lit, sym = refs[0]
    if lit is not None:
        return lit
    return _resolve_symbol(index, module, scope, sym) or ("~" + sym)


def _check_pairing(module, index, findings):
    for fi in index.functions_in(module):
        if isinstance(fi.node, ast.Lambda):
            continue
        rs_by_name: Dict[str, ast.Call] = {}
        pairs: List[Tuple[ast.Call, ast.Call]] = []
        for node in index.shallow_nodes(fi):
            if isinstance(node, ast.Assign) and \
                    isinstance(node.value, ast.Call) and \
                    call_target_name(node.value) == \
                    "reduce_scatter_padded":
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        rs_by_name[t.id] = node.value
            if isinstance(node, ast.Call) and \
                    call_target_name(node) == "all_gather_unpad" and \
                    node.args:
                src = node.args[0]
                if isinstance(src, ast.Call) and \
                        call_target_name(src) == "reduce_scatter_padded":
                    pairs.append((src, node))
                elif isinstance(src, ast.Name) and src.id in rs_by_name:
                    pairs.append((rs_by_name[src.id], node))
        if not pairs:
            continue
        env = _fold_env(index, fi)
        shapes = _local_shapes(index, fi)
        for rs, ag in pairs:
            rs_axis = _axis_key(index, module, fi, rs)
            ag_axis = _axis_key(index, module, fi, ag)
            if rs_axis and ag_axis and rs_axis != ag_axis and \
                    not (rs_axis.startswith("~") or
                         ag_axis.startswith("~")):
                findings.append(Finding(
                    "shard-collective-pairing", module.relpath,
                    ag.lineno, ag.col_offset,
                    "all_gather_unpad over axis %r paired with a "
                    "reduce_scatter_padded over axis %r" % (ag_axis,
                                                            rs_axis),
                    fi.qualname))
                continue
            n = _rs_axis_size(rs, env)
            if n is None:
                continue
            in_shape = None
            if rs.args:
                a = rs.args[0]
                if isinstance(a, ast.Name):
                    in_shape = shapes.get(a.id)
                else:
                    v = fold_or_none(a, env)
                    if isinstance(v, tuple):
                        in_shape = v
            out_shape = fold_or_none(ag.args[1], env) \
                if len(ag.args) > 1 else None
            if isinstance(out_shape, int):
                out_shape = (out_shape,)
            if in_shape is None or not isinstance(out_shape, tuple):
                continue
            pad_in = padded_size(_numel(in_shape), n)
            pad_out = padded_size(_numel(out_shape), n)
            if pad_in != pad_out:
                findings.append(Finding(
                    "shard-collective-pairing", module.relpath,
                    ag.lineno, ag.col_offset,
                    "flat padded size mismatch: reduce_scatter_padded "
                    "moves %d elements but all_gather_unpad "
                    "reconstructs %d (shape %r, axis_size %d)"
                    % (pad_in, pad_out, tuple(out_shape), n),
                    fi.qualname))


# ---------------------------------------------------------------------------
# rule: shard-collective-order
# ---------------------------------------------------------------------------

def _branch_seq(stmts: Sequence[ast.stmt]) -> List[Tuple[str, str]]:
    """Ordered (collective, raw-axis-text) sequence in a branch, not
    descending into nested function definitions."""
    out: List[Tuple[str, str]] = []
    todo = list(stmts)
    while todo:
        node = todo.pop(0)
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda, ast.ClassDef)):
            continue
        if isinstance(node, ast.Call):
            name = call_target_name(node)
            if name in _ORDERED:
                op = _axis_operand(node)
                key = ast.dump(op) if op is not None else ""
                out.append((name, key))
        todo[:0] = list(ast.iter_child_nodes(node))
    return out


def _fn_seq(index, fi) -> List[Tuple[str, str]]:
    if fi is None or isinstance(fi.node, ast.Lambda):
        body = [fi.node.body] if fi is not None else []
        return _branch_seq(body)
    return _branch_seq(fi.node.body)


def _check_order(module, index, sites, findings):
    seen: Set[int] = set()
    for site in sites:
        for fi in site.body_fns:
            if id(fi.node) in seen:
                continue
            seen.add(id(fi.node))
            div = Divergence(index, fi)
            for node in index.shallow_nodes(fi):
                if isinstance(node, ast.If):
                    sb = _branch_seq(node.body)
                    se = _branch_seq(node.orelse)
                    if not (sb or se):
                        continue
                    if div.expr(node.test):
                        findings.append(Finding(
                            "shard-collective-order", module.relpath,
                            node.lineno, node.col_offset,
                            "collective under a branch that diverges "
                            "across mesh members (axis_index/"
                            "process_index) — members disagree on "
                            "whether to issue it: multi-host deadlock",
                            fi.qualname))
                    elif sb and se and sb != se:
                        findings.append(Finding(
                            "shard-collective-order", module.relpath,
                            node.lineno, node.col_offset,
                            "the two branches issue different "
                            "collective sequences (%s vs %s) — "
                            "divergent issue order deadlocks the mesh"
                            % ([c for c, _ in sb], [c for c, _ in se]),
                            fi.qualname))
                elif isinstance(node, ast.Call) and \
                        call_target_name(node) in ("cond", "switch"):
                    branches: List[FunctionInfo] = []
                    cand_args = list(node.args)
                    if call_target_name(node) == "switch" and \
                            len(node.args) >= 2 and \
                            isinstance(node.args[1],
                                       (ast.List, ast.Tuple)):
                        cand_args = list(node.args[1].elts)
                    for a in cand_args:
                        b = index.resolve_call(module, fi, a)
                        if b is not None:
                            branches.append(b)
                    if len(branches) < 2:
                        continue
                    seqs = [_fn_seq(index, b) for b in branches]
                    if any(s != seqs[0] for s in seqs[1:]):
                        findings.append(Finding(
                            "shard-collective-order", module.relpath,
                            node.lineno, node.col_offset,
                            "lax.%s branches issue different "
                            "collective sequences — collectives must "
                            "be unconditional across mesh members"
                            % call_target_name(node), fi.qualname))


# ---------------------------------------------------------------------------
# rule: shard-carry-reshard
# ---------------------------------------------------------------------------

def _spec_key(index, module, scope, expr) -> Optional[Tuple]:
    spec = _spec_call_of(index, module, scope, expr)
    if spec is None:
        return None
    vals = []
    for a in spec.args:
        v = fold_or_none(a)
        if v is None and not (isinstance(a, ast.Constant)
                              and a.value is None):
            return None
        vals.append(v)
    return tuple(vals)


def _wsc_parts(node: ast.Call):
    if call_target_name(node) == "with_sharding_constraint" and \
            len(node.args) >= 2:
        return node.args[0], node.args[1]
    return None, None


def _check_carry(module, index, findings):
    for cs in index.calls_in(module):
        if call_target_name(cs.node) != "scan" or not cs.node.args:
            continue
        body = index.resolve_call(cs.module, cs.scope, cs.node.args[0])
        if body is None or isinstance(body.node, ast.Lambda):
            continue
        params = body.param_names()
        if not params:
            continue
        carry_param = params[0]
        # entry names: the carry tuple destructure (`a, b = carry`)
        entry_names: List[str] = [carry_param]
        for stmt in index.shallow_nodes(body):
            if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 \
                    and isinstance(stmt.targets[0], ast.Tuple) and \
                    isinstance(stmt.value, ast.Name) and \
                    stmt.value.id == carry_param:
                entry_names = [t.id for t in stmt.targets[0].elts
                               if isinstance(t, ast.Name)]
        # specs: direct applications to a name, and name -> spec of the
        # wsc call whose result it is bound to
        applied: Dict[str, List[Tuple[Tuple, ast.Call]]] = {}
        spec_of: Dict[str, Tuple] = {}
        derived_from: Dict[str, str] = {}
        for stmt in index.shallow_nodes(body):
            if not (isinstance(stmt, ast.Assign)
                    and len(stmt.targets) == 1
                    and isinstance(stmt.targets[0], ast.Name)
                    and isinstance(stmt.value, ast.Call)):
                continue
            src, sh = _wsc_parts(stmt.value)
            if src is None:
                continue
            key = _spec_key(index, module, body, sh)
            if key is None:
                continue
            tgt = stmt.targets[0].id
            spec_of[tgt] = key
            if isinstance(src, ast.Name):
                applied.setdefault(src.id, []).append((key, stmt.value))
                derived_from[tgt] = src.id
        if not spec_of:
            continue
        # exit specs per carry position from `return (c0, c1, ...), y`
        exit_specs: Dict[int, Tuple] = {}
        for stmt in index.shallow_nodes(body):
            if not (isinstance(stmt, ast.Return)
                    and isinstance(stmt.value, ast.Tuple)
                    and stmt.value.elts):
                continue
            carry_out = stmt.value.elts[0]
            elts = carry_out.elts if isinstance(carry_out, ast.Tuple) \
                else [carry_out]
            for i, el in enumerate(elts):
                if isinstance(el, ast.Name) and el.id in spec_of:
                    exit_specs[i] = spec_of[el.id]
                elif isinstance(el, ast.Call):
                    _, sh = _wsc_parts(el)
                    if sh is not None:
                        key = _spec_key(index, module, body, sh)
                        if key is not None:
                            exit_specs[i] = key
        for i, name in enumerate(entry_names):
            # entry-to-exit spec chain of carry position i: constraints
            # applied directly to the entry name (in source order), the
            # constraints of names derived FROM it via wsc, and the
            # returned position's spec
            specs: List[Tuple] = [k for k, _ in applied.get(name, [])]
            anchors: List[ast.Call] = [c for _, c in applied.get(name, [])]
            for tgt, src in derived_from.items():
                if src == name and tgt in spec_of:
                    specs.append(spec_of[tgt])
            if i in exit_specs:
                specs.append(exit_specs[i])
            distinct: List[Tuple] = []
            for s in specs:
                if s not in distinct:
                    distinct.append(s)
            if len(distinct) < 2:
                continue
            loc = anchors[-1] if anchors else body.node
            findings.append(Finding(
                "shard-carry-reshard", module.relpath, loc.lineno,
                getattr(loc, "col_offset", 0),
                "scan carry %r is constrained to %r at entry but %r "
                "at exit — every iteration reshards (hidden collective "
                "+ recompile pressure)"
                % (name, distinct[0], distinct[-1]), body.qualname))


# ---------------------------------------------------------------------------

def check(module: ModuleInfo, index: PackageIndex) -> List[Finding]:
    findings: List[Finding] = []
    sites = _shard_map_sites(module, index)
    universe = _axis_universe(index)
    _check_axes(module, index, sites, universe, findings)
    _check_spec_rank(module, index, findings)
    _check_pairing(module, index, findings)
    _check_order(module, index, sites, findings)
    _check_carry(module, index, findings)
    return findings
