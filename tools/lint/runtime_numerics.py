"""Runtime numerics sanitizer: the dynamic half of the ``num-*`` rules.

``NumericsSanitizer`` records, for tagged values ("sites"), the
**observed dtype** and a sampled **finite-ness gauge**
(``jnp.isfinite`` reduction) every ``interval``-th check.  The contract
mirrors the PR-6 HBM and PR-7 lock-order cross-checks:

* ``assert_all_finite()`` — no tagged value ever held a NaN/inf
  (``first_nonfinite`` names the first offending (step, site));
* ``assert_no_dtype_drift()`` — every site kept ONE dtype across the
  run.  A drift is a live implicit promotion: exactly the class
  ``pin_update_dtypes`` exists to prevent (a bf16 carry silently
  rewritten f32 doubles HBM traffic from that step on);
* ``assert_master_fp32()`` — sites tagged ``role="master"`` observed
  ``float32``, the multi_precision contract ``num-master-dtype``
  checks statically;
* ``assert_consistent_with(flow)`` — observed dtypes match the static
  dtype-flow table (:func:`tools.lint.numerics.static_dtype_flow`):
  a site named ``"<relpath>:<qualname>:<var>"`` whose static entry is
  concrete must observe exactly that dtype.  If the runtime ever
  witnesses a dtype the analyzer derived differently, either the code
  grew an unmodeled conversion or the analyzer regressed.

Each site's first observation — and any later dtype change or
non-finite count — is journaled as a ``numerics/observed`` telemetry
event (per-leaf finite counts + observed dtype, rendered by
``tools/parse_log.py --jsonl``).  ``attach(trainer)`` installs a
telemetry step hook that sweeps the trainer's params, grads and (under
``multi_precision``) fp32 master leaves — including the live ZeRO
sharded mirror — every ``interval`` steps.

Usage::

    from tools.lint.runtime_numerics import NumericsSanitizer
    from tools.lint.numerics import static_dtype_flow

    san = NumericsSanitizer(interval=2).attach(trainer)
    ...train...
    san.detach()
    san.assert_all_finite()
    san.assert_no_dtype_drift()
    san.assert_master_fp32()
"""
from __future__ import annotations

from typing import Dict, List, Optional, Tuple

__all__ = ["NumericsSanitizer"]


def _is_inexact(dtype) -> bool:
    # NOT dtype.kind: ml_dtypes registers bfloat16 with kind 'V'
    import jax.numpy as jnp
    try:
        return bool(jnp.issubdtype(dtype, jnp.inexact))
    except TypeError:
        return False


def _unwrap(value):
    data = getattr(value, "_data", None)
    return data if data is not None else value


class NumericsSanitizer:
    """Observed-dtype journal + sampled finite-ness gauges for tagged
    param/grad/state leaves (see module docstring for the contract)."""

    def __init__(self, interval: int = 1, telemetry_events: bool = True):
        self.interval = max(1, int(interval))
        self.telemetry_events = telemetry_events
        # site -> {"dtypes": [..in observation order..], "checks": int,
        #          "nonfinite": int, "role": str|None}
        self.observed: Dict[str, dict] = {}
        self.first_nonfinite: Optional[Tuple[Optional[int], str]] = None
        self._hook = None
        self._attached: List[object] = []
        self._steps = 0

    # -- recording ------------------------------------------------------
    def observe(self, site: str, value, role: Optional[str] = None,
                step: Optional[int] = None):
        """Record one observation of ``value`` at ``site``.  Floating
        leaves get a finite-ness reduction (one device sync); integer
        leaves record dtype only."""
        import jax.numpy as jnp
        arr = _unwrap(value)
        dt = str(arr.dtype)
        bad = 0
        if _is_inexact(arr.dtype):
            bad = int(arr.size - int(jnp.isfinite(arr).sum()))
        rec = self.observed.get(site)
        fresh = rec is None
        if fresh:
            rec = self.observed[site] = {"dtypes": [], "checks": 0,
                                         "nonfinite": 0, "role": role}
        drift = bool(rec["dtypes"]) and dt not in rec["dtypes"]
        if fresh or drift:
            rec["dtypes"].append(dt)
        rec["checks"] += 1
        rec["nonfinite"] += bad
        if bad and self.first_nonfinite is None:
            self.first_nonfinite = (step, site)
        if (fresh or drift or bad) and self.telemetry_events:
            try:
                from mxnet_tpu import telemetry
                telemetry.event("numerics", "observed", leaf=site,
                                dtype=dt, nonfinite=bad,
                                size=int(arr.size), step=step,
                                role=role,
                                drift=rec["dtypes"] if drift else None)
            except Exception:
                pass
        return rec

    # -- trainer sweep --------------------------------------------------
    def _sweep_trainer(self, trainer, step):
        optimizer = getattr(trainer, "_optimizer", None)
        mp = bool(getattr(optimizer, "multi_precision", False))
        # the live ZeRO sharded mirror shadows the updater's
        # natural-shape states; its leaf 0 IS the master under mp
        mirror = {}
        for f in (getattr(trainer, "_kv_fused", None),
                  getattr(trainer, "_local_fused", None)):
            if f is not None:
                mirror.update(getattr(f, "_sharded", {}))
        updater = None
        if getattr(trainer, "_update_on_kvstore", False):
            updater = getattr(getattr(trainer, "_kvstore", None),
                              "_updater", None)
        if updater is None:
            updater = getattr(trainer, "_updaters", None)
        if isinstance(updater, (list, tuple)):
            updater = updater[0] if updater else None
        states = getattr(updater, "states", {}) if updater is not None \
            else {}
        import numpy as onp
        for i, p in enumerate(getattr(trainer, "_params", [])):
            if p._data is None:
                continue
            self.observe("param:%s" % p.name, p.data(), role="param",
                         step=step)
            if p.grad_req != "null" and p._grad is not None:
                self.observe("grad:%s" % p.name, p.grad(), role="grad",
                             step=step)
            if mp and onp.dtype(p.dtype).itemsize < 4:
                master = None
                if i in mirror and mirror[i]:
                    master = mirror[i][0]
                else:
                    st = states.get(i)
                    if isinstance(st, (tuple, list)) and st:
                        master = st[0]
                if master is not None:
                    self.observe("master:%s" % p.name, master,
                                 role="master", step=step)

    def attach(self, trainer):
        """Sweep ``trainer``'s params/grads/masters from the telemetry
        step hook every ``interval``-th step (the Monitor.attach
        pattern — no training-loop plumbing).  Returns ``self``."""
        from mxnet_tpu import telemetry
        if trainer not in self._attached:
            self._attached.append(trainer)
        if self._hook is None:
            def _hook(rec):
                if rec.get("source") != "trainer" or \
                        rec.get("owner") not in self._attached:
                    return
                self._steps += 1
                if (self._steps - 1) % self.interval:
                    return
                self._sweep_trainer(rec["owner"], rec.get("index"))
            self._hook = telemetry.add_step_hook(_hook)
        return self

    def detach(self):
        if self._hook is not None:
            from mxnet_tpu import telemetry
            telemetry.remove_step_hook(self._hook)
            self._hook = None
        self._attached = []

    # -- queries / assertions -------------------------------------------
    def dtypes(self) -> Dict[str, str]:
        """site -> first observed dtype."""
        return {s: r["dtypes"][0] for s, r in self.observed.items()
                if r["dtypes"]}

    @staticmethod
    def _contract_failed(contract: str, msg: str):
        """A violated runtime contract is an incident: freeze the
        flight-recorder bundle (journal tail holds the
        ``numerics/observed`` events that narrate the drift) BEFORE
        raising, so the postmortem survives the test/process dying on
        the AssertionError."""
        try:
            from mxnet_tpu import flight_recorder
            flight_recorder.dump_incident("numerics_%s" % contract,
                                          detail=msg)
        except Exception:       # recorder trouble must not mask the bug
            pass
        raise AssertionError(msg)

    def assert_all_finite(self):
        bad = {s: r["nonfinite"] for s, r in self.observed.items()
               if r["nonfinite"]}
        if bad:
            self._contract_failed("nonfinite", (
                "runtime numerics: non-finite values observed (first at "
                "step %s in %r):\n  "
                % (self.first_nonfinite or (None, "?"))
                + "\n  ".join("%s: %d non-finite" % kv
                              for kv in sorted(bad.items()))))

    def assert_no_dtype_drift(self):
        drifted = {s: r["dtypes"] for s, r in self.observed.items()
                   if len(r["dtypes"]) > 1}
        if drifted:
            self._contract_failed("dtype_drift", (
                "runtime numerics: observed dtype drift (a live "
                "implicit promotion — the static complement is "
                "num-implicit-promotion):\n  "
                + "\n  ".join("%s: %s" % (s, " -> ".join(d))
                              for s, d in sorted(drifted.items()))))

    def assert_master_fp32(self):
        bad = {s: r["dtypes"] for s, r in self.observed.items()
               if r.get("role") == "master"
               and r["dtypes"] != ["float32"]}
        if bad:
            self._contract_failed("master_dtype", (
                "runtime numerics: fp32 master leaves observed "
                "off-float32 (num-master-dtype contract):\n  "
                + "\n  ".join("%s: %s" % (s, d)
                              for s, d in sorted(bad.items()))))

    def assert_consistent_with(self, flow: dict):
        """Every observed site named ``"<relpath>:<qualname>:<var>"``
        whose variable has a concrete entry in ``flow`` (a
        :func:`tools.lint.numerics.static_dtype_flow` table) must have
        observed exactly that dtype."""
        mismatches = []
        for site, rec in sorted(self.observed.items()):
            fn_key, _, var = site.rpartition(":")
            expect = flow.get(fn_key, {}).get(var)
            if expect is None:
                continue
            if rec["dtypes"] != [expect]:
                mismatches.append((site, expect, rec["dtypes"]))
        if mismatches:
            self._contract_failed("flow_mismatch", (
                "runtime numerics: observed dtypes diverge from the "
                "static dtype-flow table (unmodeled conversion or "
                "analyzer regression):\n  "
                + "\n  ".join("%s: static %s, observed %s" % m
                              for m in mismatches)))
