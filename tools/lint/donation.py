"""donation checker: use-after-donate dataflow.

``donate-use-after-donate`` — within one function, a buffer passed to a
donating call (``jax.jit(..., donate_argnums=...)`` directly, a local
bound to one, or a helper/method that *returns* one, like
``DataParallelStep._build``) is read again afterwards without an
intervening ``mark_borrowed()`` or rebinding.  On TPU the donated buffer
is freed device-side — a later read returns garbage or segfaults (the
PR 3 jaxlib<=0.4.36 persistent-cache crash was exactly this class).

The pass is linear in source order (lint granularity): a donation at
line D taints every ``Load`` of the donated name/attribute at lines
> D, killed by a ``Store`` to it or by ``x.mark_borrowed()`` anywhere
before the read.  Identity bookkeeping over donated *shells* (ring
guards) is legitimate and should be suppressed with a reason.
"""
from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from .core import Finding, ModuleInfo
from .jitgraph import (PackageIndex, FunctionInfo, call_target_name,
                       fold_or_none, shallow_walk)

RULES = {
    "donate-use-after-donate":
        "buffer read after being passed to a donating call without an "
        "intervening mark_borrowed()/rebinding",
}

# ALL = donated positions unknown -> treat every positional arg as donated
ALL = object()


def _expr_key(node: ast.expr) -> Optional[str]:
    """Dotted key for Name/attribute/subscript chains: 'x',
    'self._opt_states', 'self._sharded[i]', 'states[0]'.

    Subscripts cover the ZeRO sharded-update layout, where the donated
    carries are CONTAINER ENTRIES (per-slot lists of dp-sharded state
    leaves indexed by weight slot) rather than whole locals — a donation
    of ``self._sharded[i]`` must taint later reads of that entry, and a
    rebinding store ``self._sharded[i] = new`` must kill the taint.
    Only constant and simple-name indices are keyed; anything fancier
    stays untracked (conservative: no false positives from aliasing)."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        base = _expr_key(node.value)
        return None if base is None else base + "." + node.attr
    if isinstance(node, ast.Subscript):
        base = _expr_key(node.value)
        if base is None:
            return None
        sl = node.slice
        if isinstance(sl, ast.Constant):
            return "%s[%r]" % (base, sl.value)
        if isinstance(sl, ast.Name):
            return "%s[%s]" % (base, sl.id)
        return None
    return None


def _jit_donation(node: ast.expr) -> Optional[object]:
    """If ``node`` is ``jax.jit(f, donate_argnums=...)`` return the
    donated positions (tuple of ints, or ALL when unfoldable); None if
    not a donating jit."""
    if not isinstance(node, ast.Call):
        return None
    if call_target_name(node) not in ("jit", "pjit"):
        return None
    for kw in node.keywords:
        if kw.arg in ("donate_argnums", "donate_argnames"):
            v = fold_or_none(kw.value)
            if isinstance(v, int):
                return (v,)
            if isinstance(v, tuple) and \
                    all(isinstance(x, int) for x in v):
                return v if v else None
            return ALL
    return None


def _returns_donating(fi: FunctionInfo) -> Optional[object]:
    """Donated positions if ``fi`` returns a donating jit callable."""
    for stmt in shallow_walk(fi.node):
        if isinstance(stmt, ast.Return) and stmt.value is not None:
            d = _jit_donation(stmt.value)
            if d is not None:
                return d
    return None


class _Event:
    __slots__ = ("key", "line", "end_line", "node")

    def __init__(self, key, line, end_line, node):
        self.key = key
        self.line = line
        self.end_line = end_line
        self.node = node


def _donated_keys(call: ast.Call, positions) -> List[str]:
    keys: List[str] = []
    args = call.args
    if positions is ALL:
        idxs = range(len(args))
    else:
        idxs = [p for p in positions if p < len(args)]
    for i in idxs:
        a = args[i]
        if isinstance(a, (ast.Tuple, ast.List)):
            for e in a.elts:
                k = _expr_key(e)
                if k is not None:
                    keys.append(k)
        else:
            k = _expr_key(a)
            if k is not None:
                keys.append(k)
    return keys


def _analyze_function(module, index, fi, findings):
    # 1) donating callables visible in this function
    donating: Dict[str, object] = {}        # local name -> positions
    for stmt in index.shallow_nodes(fi):
        if not isinstance(stmt, ast.Assign):
            continue
        d = _jit_donation(stmt.value)
        if d is None and isinstance(stmt.value, ast.Call):
            callee = index.resolve_call(module, fi, stmt.value.func)
            if callee is not None:
                d = _returns_donating(callee)
        if d is not None:
            for t in stmt.targets:
                k = _expr_key(t)
                if k is not None:
                    donating[k] = d

    # 2) donation events + kills + reads, in source order
    donations: List[_Event] = []
    stores: List[Tuple[str, int]] = []
    borrows: List[Tuple[str, int]] = []
    reads: List[_Event] = []
    call_spans: List[Tuple[int, int]] = []

    # reads that only touch Python metadata of the handle — len()/
    # isinstance()/type()/id() args and `is`/`is not` operands — never
    # dereference the device buffer
    exempt: Set[int] = set()
    for node in index.shallow_nodes(fi):
        if isinstance(node, ast.Call) and \
                isinstance(node.func, ast.Name) and \
                node.func.id in ("len", "isinstance", "type", "id"):
            for a in node.args:
                exempt.add(id(a))
        if isinstance(node, ast.Compare) and \
                all(isinstance(op, (ast.Is, ast.IsNot))
                    for op in node.ops):
            exempt.add(id(node.left))
            for c in node.comparators:
                exempt.add(id(c))

    for node in index.shallow_nodes(fi):
        if isinstance(node, ast.Call):
            positions = None
            # direct: jax.jit(f, donate_argnums=...)(x, y)
            inner = _jit_donation(node.func) \
                if isinstance(node.func, ast.Call) else None
            if inner is not None:
                positions = inner
            else:
                k = _expr_key(node.func)
                if k is not None and k in donating:
                    positions = donating[k]
            if positions is not None:
                end = getattr(node, "end_lineno", node.lineno)
                call_spans.append((node.lineno, end))
                for key in _donated_keys(node, positions):
                    donations.append(_Event(key, node.lineno, end, node))
            if isinstance(node.func, ast.Attribute) and \
                    node.func.attr == "mark_borrowed":
                k = _expr_key(node.func.value)
                if k is not None:
                    borrows.append((k, node.lineno))
        if isinstance(node, (ast.Name, ast.Attribute, ast.Subscript)):
            k = _expr_key(node)
            if k is None:
                continue
            ctx = getattr(node, "ctx", None)
            if isinstance(ctx, ast.Store):
                stores.append((k, node.lineno))
            elif isinstance(ctx, ast.Load) and id(node) not in exempt:
                reads.append(_Event(k, node.lineno,
                                    getattr(node, "end_lineno",
                                            node.lineno), node))

    if not donations:
        return

    reported: Set[Tuple[str, int]] = set()
    for r in reads:
        for d in donations:
            if r.key != d.key and not r.key.startswith(d.key + "."):
                continue
            if r.line <= d.end_line:
                continue
            # inside a LATER donating call re-passing the same buffer is
            # still a read (that is the PR 3 re-feed bug) — only the
            # originating call span is exempt
            if any(s <= r.line <= e for s, e in call_spans
                   if (s, e) == (d.line, d.end_line)):
                continue
            killed = any(k == d.key and d.line <= ln <= r.line
                         for k, ln in stores) or \
                any(k == d.key and ln <= r.line for k, ln in borrows)
            if killed:
                continue
            if (r.key, r.line) in reported:
                continue
            reported.add((r.key, r.line))
            findings.append(Finding(
                "donate-use-after-donate", module.relpath, r.line,
                r.node.col_offset,
                "%r is read after being donated at line %d — the buffer "
                "may already be freed; copy it, mark_borrowed() it, or "
                "rebind before reuse" % (r.key, d.line), fi.qualname))
            break


def check(module: ModuleInfo, index: PackageIndex) -> List[Finding]:
    findings: List[Finding] = []
    for fi in index.functions_in(module):
        if isinstance(fi.node, ast.Lambda):
            continue
        _analyze_function(module, index, fi, findings)
    return findings
