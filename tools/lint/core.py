"""graftlint core: findings, inline suppressions, baseline, runner.

The analyzer is framework-aware (it understands this repo's JAX idioms —
jit-reachability, donation, Pallas grids) but the machinery here is
generic: checkers produce :class:`Finding`s, the runner filters them
through inline suppressions (``# graftlint: disable=<rule> -- reason``)
and the checked-in baseline (grandfathered findings, matched by
(file, rule, context) so line drift never churns it), and whatever
survives is "new" — the tier-1 gate fails on any of it.
"""
from __future__ import annotations

import ast
import json
import os
import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

BASELINE_VERSION = 1

# inline suppression grammar (reason is MANDATORY):
#   x = float(v)  # graftlint: disable=trace-host-sync -- epoch boundary sync
#   # graftlint: disable-next=donate-use-after-donate -- identity check only
_SUPPRESS_RE = re.compile(
    r"#\s*graftlint:\s*disable(?P<next>-next)?="
    r"(?P<rules>[A-Za-z0-9_,*-]+)"
    r"(?P<dash>\s*--(?:\s*(?P<reason>\S.*))?)?")


@dataclass
class Finding:
    """One diagnostic: ``rule`` identifies the check, ``context`` the
    enclosing function qualname (baseline identity is line-free)."""
    rule: str
    path: str            # repo-relative, '/'-separated
    line: int
    col: int
    message: str
    context: str = "<module>"

    def baseline_key(self) -> Tuple[str, str, str]:
        return (self.path, self.rule, self.context)

    def sort_key(self):
        return (self.path, self.line, self.col, self.rule)

    def to_dict(self) -> dict:
        return {"rule": self.rule, "path": self.path, "line": self.line,
                "col": self.col, "message": self.message,
                "context": self.context}

    def render(self) -> str:
        return "%s:%d:%d: %s [%s] (in %s)" % (
            self.path, self.line, self.col, self.message, self.rule,
            self.context)


@dataclass
class Suppression:
    line: int            # line the suppression APPLIES to
    rules: Tuple[str, ...]
    reason: Optional[str]
    comment_line: int    # line the comment itself is on
    used: bool = False
    # rules that actually matched a finding — the audit flags per RULE,
    # so one dead rule in a multi-rule suppression is still caught
    used_rules: set = field(default_factory=set)


class ModuleInfo:
    """Parsed view of one source file shared by all checkers."""

    def __init__(self, path: str, relpath: str, source: str):
        self.path = path
        self.relpath = relpath.replace(os.sep, "/")
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=path)
        self.suppressions = parse_suppressions(source)

    def line_text(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1]
        return ""


def parse_suppressions(source: str) -> List[Suppression]:
    lines = source.splitlines()
    out = []
    for i, text in enumerate(lines, start=1):
        m = _SUPPRESS_RE.search(text)
        if not m:
            continue
        rules = tuple(r.strip() for r in m.group("rules").split(",")
                      if r.strip())
        reason = m.group("reason")
        if reason is None and m.group("dash") and m.group("next") \
                and i < len(lines):
            # ONLY the disable-next form with an explicit trailing `--`
            # may continue its reason on the next comment line (79-col
            # style); a bare reasonless suppression must NOT steal an
            # unrelated comment as its reason
            nxt = lines[i].strip()
            if nxt.startswith("#") and not _SUPPRESS_RE.search(nxt):
                cand = nxt.lstrip("#").strip()
                if cand:
                    reason = cand
        if m.group("next"):
            # skip trailing comment/blank lines so a reason may wrap
            # onto continuation comment lines (79-col style)
            target = i + 1
            while target <= len(lines) and (
                    not lines[target - 1].strip()
                    or lines[target - 1].lstrip().startswith("#")):
                target += 1
        else:
            target = i
        out.append(Suppression(line=target, rules=rules,
                               reason=reason, comment_line=i))
    return out


# ---------------------------------------------------------------------------
# baseline
# ---------------------------------------------------------------------------

def load_baseline(path: str) -> Dict[Tuple[str, str, str], int]:
    """Baseline file -> {(file, rule, context): count}."""
    with open(path) as f:
        data = json.load(f)
    if data.get("version") != BASELINE_VERSION:
        raise ValueError("unsupported baseline version %r"
                         % (data.get("version"),))
    table: Dict[Tuple[str, str, str], int] = {}
    for e in data.get("entries", []):
        key = (e["file"], e["rule"], e.get("context", "<module>"))
        table[key] = table.get(key, 0) + int(e.get("count", 1))
    return table


def write_baseline(path: str, findings: Sequence[Finding]) -> dict:
    counts: Dict[Tuple[str, str, str], int] = {}
    for f in sorted(findings, key=Finding.sort_key):
        counts[f.baseline_key()] = counts.get(f.baseline_key(), 0) + 1
    entries = [{"file": k[0], "rule": k[1], "context": k[2], "count": n}
               for k, n in sorted(counts.items())]
    data = {"version": BASELINE_VERSION, "entries": entries}
    # tmp + os.replace, hand-rolled: tools.lint must not import
    # mxnet_tpu (fsutil.atomic_write_path), and the baseline is read by
    # every gate run — it must never be observable half-written
    tmp = "%s.tmp.%d" % (path, os.getpid())
    try:
        with open(tmp, "w") as f:
            json.dump(data, f, indent=1, sort_keys=True)
            f.write("\n")
        os.replace(tmp, path)
    finally:
        if os.path.exists(tmp):
            try:
                os.remove(tmp)
            except OSError:
                pass
    return data


def diff_baseline(findings: Sequence[Finding],
                  baseline: Dict[Tuple[str, str, str], int]
                  ) -> Tuple[List[Finding], List[Finding]]:
    """Split findings into (new, baselined).  Matching consumes baseline
    multiplicity so a file that GAINS a second instance of a
    grandfathered finding still reports the extra one as new."""
    budget = dict(baseline)
    new: List[Finding] = []
    old: List[Finding] = []
    for f in sorted(findings, key=Finding.sort_key):
        k = f.baseline_key()
        if budget.get(k, 0) > 0:
            budget[k] -= 1
            old.append(f)
        else:
            new.append(f)
    return new, old


# ---------------------------------------------------------------------------
# runner
# ---------------------------------------------------------------------------

@dataclass
class LintResult:
    files: List[str] = field(default_factory=list)
    new: List[Finding] = field(default_factory=list)
    baselined: List[Finding] = field(default_factory=list)
    suppressed: List[Finding] = field(default_factory=list)

    @property
    def total(self) -> int:
        return len(self.new) + len(self.baselined) + len(self.suppressed)

    def to_dict(self) -> dict:
        return {
            "files_scanned": len(self.files),
            "counts": {"new": len(self.new),
                       "baselined": len(self.baselined),
                       "suppressed": len(self.suppressed),
                       "total": self.total},
            "findings": [f.to_dict() for f in
                         sorted(self.new, key=Finding.sort_key)],
            "baselined": [f.to_dict() for f in
                          sorted(self.baselined, key=Finding.sort_key)],
            "suppressed": [f.to_dict() for f in
                           sorted(self.suppressed, key=Finding.sort_key)],
        }


def collect_files(paths: Sequence[str]) -> List[str]:
    out = []
    for p in paths:
        if os.path.isfile(p):
            out.append(p)
            continue
        for root, dirs, names in os.walk(p):
            dirs[:] = sorted(d for d in dirs
                             if d != "__pycache__"
                             and not d.startswith("."))
            for n in sorted(names):
                if n.endswith(".py"):
                    out.append(os.path.join(root, n))
    return out


def _repo_root() -> str:
    return os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))


def default_baseline_path() -> str:
    return os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "baseline.json")


def _apply_suppressions(module: ModuleInfo, findings: List[Finding],
                        known_rules: Dict[str, str]
                        ) -> Tuple[List[Finding], List[Finding],
                                   List[Finding]]:
    """-> (kept, suppressed, meta) where meta are findings about the
    suppression comments themselves (missing reason / unknown rule).

    A suppression targeting the first line of a multi-line statement
    covers findings on the statement's continuation lines too.  For
    compound statements (if/for/while/with/def) the covered span is the
    HEADER only — a suppression above an `if` must not blanket every
    same-rule finding inside its body."""
    spans: Dict[int, int] = {}
    for node in ast.walk(module.tree):
        if isinstance(node, ast.stmt):
            end = getattr(node, "end_lineno", node.lineno)
            body = getattr(node, "body", None)
            if isinstance(body, list) and body and \
                    isinstance(body[0], ast.stmt):
                end = min(end, body[0].lineno - 1)
            spans[node.lineno] = max(spans.get(node.lineno, 0), end)
    by_line: Dict[int, List[Suppression]] = {}
    meta: List[Finding] = []
    for s in module.suppressions:
        for ln in range(s.line, spans.get(s.line, s.line) + 1):
            by_line.setdefault(ln, []).append(s)
        if not s.reason:
            meta.append(Finding(
                rule="lint-suppression-reason", path=module.relpath,
                line=s.comment_line, col=0,
                message="graftlint suppression must carry a reason: "
                        "'# graftlint: disable=<rule> -- <why>'"))
        for r in s.rules:
            if r != "*" and r not in known_rules:
                meta.append(Finding(
                    rule="lint-unknown-rule", path=module.relpath,
                    line=s.comment_line, col=0,
                    message="suppression names unknown rule %r" % (r,)))
    kept, suppressed = [], []
    for f in findings:
        hit = None
        for s in by_line.get(f.line, ()):
            if s.reason and ("*" in s.rules or f.rule in s.rules):
                hit = s
                break
        if hit is not None:
            hit.used = True
            hit.used_rules.add(f.rule)
            suppressed.append(f)
        else:
            kept.append(f)
    return kept, suppressed, meta


def run_lint(paths: Sequence[str], baseline_path: Optional[str] = None,
             rules: Optional[Sequence[str]] = None,
             emit_telemetry: bool = False,
             changed_files: Optional[Sequence[str]] = None,
             audit_suppressions: bool = False) -> LintResult:
    """Run every checker over ``paths``.

    ``baseline_path``: JSON baseline consumed by :func:`diff_baseline`
    (None disables baselining — everything unsuppressed is "new").
    ``rules``: optional rule-id allowlist.  ``emit_telemetry``: bump the
    ``lint.findings`` counter + journal an event via mxnet_tpu.telemetry
    (best-effort import; used by the tier-1 gate).

    ``changed_files``: repo-relative paths — the cross-file index is
    still built over ALL of ``paths`` (jit-reachability and config
    inference need every caller), but checkers only run on the changed
    files plus their reverse-dependency closure, so findings in the
    reported files match a full run exactly.

    ``audit_suppressions``: report every ``# graftlint: disable``
    comment whose rule no longer fires on its line as a
    ``lint-stale-suppression`` meta finding (skipped when a ``rules``
    allowlist is active — unrelated suppressions would read as stale).
    """
    from . import CHECKERS, all_rules
    from .jitgraph import PackageIndex

    known = all_rules()
    files = collect_files(paths)
    root = _repo_root()
    modules: List[ModuleInfo] = []
    result = LintResult()
    parse_errors: List[Finding] = []
    for path in files:
        rel = os.path.relpath(os.path.abspath(path), root)
        try:
            with open(path, encoding="utf-8") as f:
                src = f.read()
            modules.append(ModuleInfo(path, rel, src))
        except (OSError, SyntaxError) as e:
            parse_errors.append(Finding(
                rule="lint-parse-error", path=rel.replace(os.sep, "/"),
                line=getattr(e, "lineno", 0) or 0, col=0,
                message="cannot analyze file: %s" % (e,)))

    index = PackageIndex(modules)
    report_set = None
    if changed_files is not None:
        rel_changed = {c.replace(os.sep, "/") for c in changed_files}
        report_set = index.reverse_dependency_closure(rel_changed)
    targets = [m for m in modules
               if report_set is None or m.relpath in report_set]
    result.files = [m.relpath for m in targets]
    if report_set is not None:
        # a changed file that fails to parse is not in the module index
        # (so not in the closure) but must still fail the gate
        parse_errors = [f for f in parse_errors
                        if f.path in report_set or f.path in rel_changed]

    # parse errors ride the normal new/baseline pipeline — an
    # unanalyzable file must FAIL the gate, not scan as clean
    raw: List[Finding] = list(parse_errors)
    audit = audit_suppressions and not rules
    for module in targets:
        per_file: List[Finding] = []
        for checker in CHECKERS:
            per_file.extend(checker.check(module, index))
        if rules:
            per_file = [f for f in per_file if f.rule in rules]
        kept, suppressed, meta = _apply_suppressions(module, per_file,
                                                     known)
        if audit:
            for s in module.suppressions:
                if not s.reason:
                    # reasonless comments already fire
                    # lint-suppression-reason; don't double-report
                    continue
                if "*" in s.rules:
                    # wildcard: live as long as ANYTHING matched
                    stale = () if s.used else ("*",)
                else:
                    # per RULE: one dead rule in a multi-rule
                    # suppression is still dead weight (unknown rule
                    # ids are lint-unknown-rule's job)
                    stale = tuple(r for r in s.rules
                                  if r in known and
                                  r not in s.used_rules)
                if not stale:
                    continue
                meta.append(Finding(
                    rule="lint-stale-suppression", path=module.relpath,
                    line=s.comment_line, col=0,
                    message="suppression of %s no longer matches any "
                            "finding on line %d — the rule was fixed "
                            "or the engine got more precise; delete "
                            "it" % (",".join(stale), s.line)))
        raw.extend(kept)
        raw.extend(meta)          # meta findings are never suppressible
        result.suppressed.extend(suppressed)

    baseline = {}
    if baseline_path:
        baseline = load_baseline(baseline_path)
    result.new, result.baselined = diff_baseline(raw, baseline)
    result.new.sort(key=Finding.sort_key)
    result.baselined.sort(key=Finding.sort_key)
    result.suppressed.sort(key=Finding.sort_key)

    if emit_telemetry:
        try:
            from mxnet_tpu import telemetry
            telemetry.inc("lint.findings", len(result.new))
            telemetry.inc("lint.baselined", len(result.baselined))
            telemetry.inc("lint.suppressed", len(result.suppressed))
            telemetry.event("lint", "gate", new=len(result.new),
                            baselined=len(result.baselined),
                            suppressed=len(result.suppressed),
                            files=len(result.files))
        except Exception:
            pass
    return result
