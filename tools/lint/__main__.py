"""CLI: ``python -m tools.lint [paths] [--format json] [...]``.

Exit codes: 0 = clean (no new findings), 1 = new findings, 2 = usage.
"""
from __future__ import annotations

import argparse
import json
import os
import sys

from . import all_rules
from .core import default_baseline_path, run_lint, write_baseline


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tools.lint",
        description="graftlint: framework-aware static analysis "
                    "(trace-safety, retrace, donation, Pallas)")
    ap.add_argument("paths", nargs="*", default=None,
                    help="files/directories to lint (default: mxnet_tpu/)")
    ap.add_argument("--format", choices=("text", "json"), default="text")
    ap.add_argument("--baseline", default=None,
                    help="baseline JSON (default: tools/lint/baseline.json)")
    ap.add_argument("--no-baseline", action="store_true",
                    help="report grandfathered findings as new")
    ap.add_argument("--write-baseline", action="store_true",
                    help="regenerate the baseline from current findings "
                         "and exit 0")
    ap.add_argument("--rules", default=None,
                    help="comma-separated rule-id allowlist")
    ap.add_argument("--list-rules", action="store_true")
    ap.add_argument("--show-baselined", action="store_true",
                    help="also print grandfathered findings")
    ap.add_argument("--telemetry", action="store_true",
                    help="emit lint.findings into the telemetry journal")
    args = ap.parse_args(argv)

    if args.list_rules:
        for rid, desc in sorted(all_rules().items()):
            print("%-28s %s" % (rid, desc))
        return 0

    paths = args.paths or ["mxnet_tpu"]
    for p in paths:
        if not os.path.exists(p):
            print("error: no such path: %s" % p, file=sys.stderr)
            return 2

    baseline = None if (args.no_baseline or args.write_baseline) \
        else (args.baseline or default_baseline_path())
    if baseline is not None and not os.path.exists(baseline):
        baseline = None
    rules = [r.strip() for r in args.rules.split(",")] if args.rules \
        else None

    result = run_lint(paths, baseline_path=baseline, rules=rules,
                      emit_telemetry=args.telemetry)

    if args.write_baseline:
        path = args.baseline or default_baseline_path()
        data = write_baseline(path, result.new + result.baselined)
        print("wrote %d baseline entries (%d findings) to %s"
              % (len(data["entries"]),
                 len(result.new) + len(result.baselined), path))
        return 0

    if args.format == "json":
        json.dump(result.to_dict(), sys.stdout, indent=1)
        sys.stdout.write("\n")
    else:
        for f in result.new:
            print(f.render())
        if args.show_baselined:
            for f in result.baselined:
                print("[baselined] " + f.render())
        print("graftlint: %d file(s): %d new, %d baselined, "
              "%d suppressed"
              % (len(result.files), len(result.new),
                 len(result.baselined), len(result.suppressed)))
    return 1 if result.new else 0


if __name__ == "__main__":
    sys.exit(main())
