"""CLI: ``python -m tools.lint [paths] [--format json] [...]``.

Exit codes: 0 = clean (no new findings), 1 = new findings, 2 = usage.

``--changed`` is the pre-commit fast mode: lint only files touched vs
``git merge-base HEAD main`` (plus untracked files) and their
reverse-dependency closure.  The cross-file index is still built over
the full path set, so the findings in the reported files are identical
to a full run's.
"""
from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys

from . import all_rules
from .core import (_repo_root, default_baseline_path, run_lint,
                   write_baseline)


def _git_changed_files(root: str):
    """Repo-relative .py files changed vs merge-base(HEAD, main), plus
    untracked ones.  Returns None when git is unavailable (caller falls
    back to a full run)."""
    def git(*args):
        return subprocess.run(("git",) + args, cwd=root,
                              capture_output=True, text=True)
    mb = git("merge-base", "HEAD", "main")
    base = mb.stdout.strip() if mb.returncode == 0 and mb.stdout.strip() \
        else "HEAD"
    diff = git("diff", "--name-only", "-z", base, "--")
    if diff.returncode != 0:
        return None
    names = [n for n in diff.stdout.split("\0") if n]
    untracked = git("ls-files", "--others", "--exclude-standard", "-z")
    if untracked.returncode == 0:
        names += [n for n in untracked.stdout.split("\0") if n]
    return sorted({n for n in names if n.endswith(".py")})


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tools.lint",
        description="graftlint: framework-aware static analysis "
                    "(trace-safety, retrace, donation, Pallas, "
                    "sharding, concurrency, numerics)")
    ap.add_argument("paths", nargs="*", default=None,
                    help="files/directories to lint (default: mxnet_tpu/)")
    ap.add_argument("--format", choices=("text", "json"), default="text")
    ap.add_argument("--baseline", default=None,
                    help="baseline JSON (default: tools/lint/baseline.json)")
    ap.add_argument("--no-baseline", action="store_true",
                    help="report grandfathered findings as new")
    ap.add_argument("--write-baseline", action="store_true",
                    help="regenerate the baseline from current findings "
                         "and exit 0")
    ap.add_argument("--rules", default=None,
                    help="comma-separated rule-id allowlist")
    ap.add_argument("--list-rules", action="store_true")
    ap.add_argument("--show-baselined", action="store_true",
                    help="also print grandfathered findings")
    ap.add_argument("--telemetry", action="store_true",
                    help="emit lint.findings into the telemetry journal")
    ap.add_argument("--changed", action="store_true",
                    help="lint only files changed vs merge-base(HEAD, "
                         "main) plus their reverse-dependency closure "
                         "(pre-commit fast mode)")
    ap.add_argument("--audit-suppressions", action="store_true",
                    help="flag inline suppressions whose rule no longer "
                         "fires on their line (always on under "
                         "--write-baseline)")
    ap.add_argument("--audit-chaos", action="store_true",
                    help="audit fault-injection coverage: every "
                         "statically-enumerated fault point must map to "
                         "a chaos mode and an installing test "
                         "(tools.lint.chaos_coverage)")
    args = ap.parse_args(argv)

    if args.list_rules:
        from . import rule_family
        fams = {}
        for rid, desc in all_rules().items():
            fams.setdefault(rule_family(rid), []).append((rid, desc))
        for fam in sorted(fams):
            print("%s:" % fam)
            for rid, desc in sorted(fams[fam]):
                print("  %-30s %s" % (rid, desc))
        return 0

    paths = args.paths or ["mxnet_tpu"]
    for p in paths:
        if not os.path.exists(p):
            print("error: no such path: %s" % p, file=sys.stderr)
            return 2

    if args.audit_chaos:
        from . import chaos_coverage
        res = chaos_coverage.audit(
            None if args.paths == [] or not args.paths else paths)
        if args.telemetry:
            chaos_coverage.emit_telemetry(res)
        if args.format == "json":
            json.dump(res.to_dict(), sys.stdout, indent=1)
            sys.stdout.write("\n")
        else:
            print(res.render_text())
        return 0 if res.ok else 1

    baseline = None if (args.no_baseline or args.write_baseline) \
        else (args.baseline or default_baseline_path())
    if baseline is not None and not os.path.exists(baseline):
        baseline = None
    rules = [r.strip() for r in args.rules.split(",")] if args.rules \
        else None

    if args.changed and args.write_baseline:
        # a narrowed scan would rewrite the baseline WITHOUT the
        # grandfathered entries of every out-of-closure file
        print("error: --changed cannot be combined with "
              "--write-baseline (the baseline must come from a full "
              "scan)", file=sys.stderr)
        return 2

    changed = None
    if args.changed:
        changed = _git_changed_files(_repo_root())
        if changed is None:
            print("warning: git unavailable, falling back to a full "
                  "run", file=sys.stderr)
        elif not changed:
            print("graftlint: no .py files changed vs merge-base — "
                  "nothing to lint")
            return 0

    result = run_lint(paths, baseline_path=baseline, rules=rules,
                      emit_telemetry=args.telemetry,
                      changed_files=changed,
                      audit_suppressions=(args.audit_suppressions
                                          or args.write_baseline))

    if args.write_baseline:
        # stale-suppression findings are REPORTED, never grandfathered:
        # baselining them would defeat the audit
        stale = [f for f in result.new
                 if f.rule == "lint-stale-suppression"]
        keep = [f for f in result.new + result.baselined
                if f.rule != "lint-stale-suppression"]
        path = args.baseline or default_baseline_path()
        data = write_baseline(path, keep)
        for f in stale:
            print(f.render())
        print("wrote %d baseline entries (%d findings) to %s"
              % (len(data["entries"]), len(keep), path))
        return 1 if stale else 0

    if args.format == "json":
        json.dump(result.to_dict(), sys.stdout, indent=1)
        sys.stdout.write("\n")
    else:
        for f in result.new:
            print(f.render())
        if args.show_baselined:
            for f in result.baselined:
                print("[baselined] " + f.render())
        print("graftlint: %d file(s): %d new, %d baselined, "
              "%d suppressed"
              % (len(result.files), len(result.new),
                 len(result.baselined), len(result.suppressed)))
    return 1 if result.new else 0


if __name__ == "__main__":
    sys.exit(main())
