"""numerics checker: dtype-flow analysis over jit-reachable code.

Every remaining reduced-precision leg of this stack — bf16 training,
the int8 serving path, the ZeRO fp32-master / working-dtype update
contract (arxiv 2004.13336) — fails *silently* when a dtype goes wrong:
an implicit bf16→f32 promotion doubles HBM traffic, a bf16 accumulation
swallows gradient mass, an unshifted ``exp`` overflows half floats, a
collective pair that changes dtype mid-flight corrupts the flat ZeRO
layout.  The TPU serving comparison (arxiv 2605.25645) shows the
bf16/int8 precision choice dominates both throughput and quality, so a
wrong dtype is simultaneously a performance and a correctness bug.

The checker propagates a small dtype lattice through each jit-reachable
function (over the same :class:`~tools.lint.jitgraph.PackageIndex`
closure the trace/retrace rules use): concrete dtypes (``float32``,
``bfloat16``, ...), weak-typed Python literals (``weak_float`` /
``weak_int`` — they do NOT promote, mirroring JAX's weak-type rules),
and unknown (⊤, on which every rule stays silent).  Transfer functions
cover ``astype`` / ``asarray`` / constructors / ``zeros_like`` /
``preferred_element_type`` / ``promote_types`` / reductions /
elementwise passthrough, plus one level of local-helper return-dtype
resolution through :meth:`PackageIndex.resolve_call`.

Rules (each with its runtime counterpart in
``tools.lint.runtime_numerics`` — see docs/LINTING.md):

* ``num-implicit-promotion`` — a binary op mixing a 16-bit float with a
  wider float, relying on silent promotion;
* ``num-lowprec-accum`` — sum/mean/matmul/einsum reducing 16-bit floats
  without fp32 accumulation (``preferred_element_type=`` / ``dtype=`` /
  an explicit upcast);
* ``num-unstable-exp`` — exp/log/softmax/logsumexp over 16-bit floats
  with no max-shift / eps-guard / upcast;
* ``num-master-dtype`` — the multi_precision fp32 master leaf assigned
  a half-width value, an update applied to the master with a half-width
  operand, or an ``astype`` round-trip through a half dtype;
* ``num-collective-dtype`` — a reduce-scatter/all-gather pair over one
  axis whose dtypes differ with no explicit conversion (the ZeRO
  working-dtype contract, composing with ``shard-collective-pairing``);
* ``num-const-downcast`` — float64 requested (or numpy's float64
  default relied on) under disabled x64, and weak literals beyond the
  float16 range.
"""
from __future__ import annotations

import ast
from typing import Dict, List, Optional, Sequence, Set, Tuple

from .core import Finding, ModuleInfo
from .jitgraph import (PackageIndex, FunctionInfo, call_target_name,
                       call_target_parts, fold_or_none)
from .sharding import _chase_name
from .tainting import NUMPY_ROOTS

RULES = {
    "num-implicit-promotion":
        "binary op mixes a 16-bit float with a wider float — silent "
        "promotion; make it explicit with astype or align dtypes",
    "num-lowprec-accum":
        "sum/mean/matmul/einsum reduces 16-bit floats without fp32 "
        "accumulation (preferred_element_type/dtype=/explicit upcast)",
    "num-unstable-exp":
        "exp/log/softmax/logsumexp over 16-bit floats without "
        "max-shift, eps-guard or upcast",
    "num-master-dtype":
        "fp32 master leaf leaves float32 (half-width assignment, "
        "half-width update operand, or astype round-trip)",
    "num-collective-dtype":
        "reduce-scatter/all-gather pair over one axis with asymmetric "
        "dtypes and no explicit conversion (ZeRO working-dtype "
        "contract)",
    "num-const-downcast":
        "float64 constant/dtype under disabled x64 (silent downcast), "
        "or a weak literal outside the float16 range",
}

# -- the lattice -------------------------------------------------------------

WEAK_FLOAT = "weak_float"
WEAK_INT = "weak_int"

HALF_FLOATS = {"float16", "bfloat16"}
WIDE_FLOATS = {"float32", "float64"}
CONCRETE_FLOATS = HALF_FLOATS | WIDE_FLOATS
INTS = {"int8", "uint8", "int16", "uint16", "int32", "uint32", "int64",
        "uint64"}

# attribute / string spellings -> canonical dtype
_DTYPE_NAMES = {
    "float16": "float16", "half": "float16", "bfloat16": "bfloat16",
    "float32": "float32", "single": "float32", "float64": "float64",
    "double": "float64", "float_": "float64", "int8": "int8",
    "uint8": "uint8", "int16": "int16", "uint16": "uint16",
    "int32": "int32", "uint32": "uint32", "int64": "int64",
    "uint64": "uint64", "bool_": "bool",
}

_F_ORDER = {"float16": 1, "bfloat16": 1, "float32": 2, "float64": 3}

# float16 finite range — a weak literal beyond it overflows f16 operands
_F16_MAX = 65504.0


def promote(a: Optional[str], b: Optional[str]) -> Optional[str]:
    """JAX's promote_types restricted to this lattice (x64 disabled:
    weak Python literals never widen a concrete operand)."""
    if a is None or b is None:
        return None
    if a == b:
        return a
    for x, y in ((a, b), (b, a)):
        if x == WEAK_INT:
            return y
        if x == WEAK_FLOAT:
            if y in CONCRETE_FLOATS or y == WEAK_FLOAT:
                return y
            if y in INTS or y == "bool":
                return "float32"
            return None
    if a in CONCRETE_FLOATS and b in CONCRETE_FLOATS:
        if a in HALF_FLOATS and b in HALF_FLOATS:
            return "float32"        # f16 + bf16 promotes to f32
        return a if _F_ORDER[a] >= _F_ORDER[b] else b
    if a in CONCRETE_FLOATS:
        return a
    if b in CONCRETE_FLOATS:
        return b
    return None                     # int/int and exotica: not rule-relevant


# -- call vocabularies -------------------------------------------------------

# first-operand passthrough: result dtype == dtype of the FIRST array
# operand (later args are config — axes, shapes, pad widths, indices)
_PASSTHROUGH_FIRST = {
    "exp", "expm1", "exp2", "log", "log1p", "log2", "log10", "sqrt",
    "rsqrt", "abs", "absolute", "negative", "square", "tanh", "sigmoid",
    "relu", "gelu", "erf", "sin", "cos", "sign", "floor", "ceil",
    "round", "rint", "clip",
    "reshape", "ravel", "flatten", "transpose", "swapaxes", "squeeze",
    "expand_dims", "broadcast_to", "pad", "roll", "flip", "take",
    "take_along_axis", "gather", "dynamic_slice", "tile", "repeat",
    "stop_gradient", "with_sharding_constraint", "device_put",
    "max", "min", "amax", "amin", "softmax", "log_softmax",
    "logsumexp", "flatten_pad", "unflatten", "psum", "pmean",
    "all_gather", "psum_scatter", "ppermute", "all_to_all",
    "reduce_scatter", "reduce_scatter_padded", "all_gather_unpad",
}
# join passthrough: result dtype == promote over every array operand
_PASSTHROUGH_JOIN = {"add", "subtract", "multiply", "divide",
                     "true_divide", "power", "logaddexp", "maximum",
                     "minimum", "where", "hypot", "concatenate",
                     "stack"}
# of these, the genuinely binary ones participate in the
# implicit-promotion rule alongside ast.BinOp
_BINARY_CALLS = {"add", "subtract", "multiply", "divide", "true_divide",
                 "power", "logaddexp", "maximum", "minimum", "where"}

_REDUCE_CALLS = {"sum", "mean", "prod", "cumsum", "var", "std",
                 "nansum", "average"}
_MATMUL_CALLS = {"matmul", "dot", "einsum", "tensordot", "dot_general",
                 "conv_general_dilated", "conv", "vdot"}
_CTOR_CALLS = {"zeros", "ones", "full", "empty", "arange", "linspace",
               "eye", "identity"}
_LIKE_CALLS = {"zeros_like", "ones_like", "full_like", "empty_like"}

_EXP_CALLS = {"exp", "expm1", "exp2"}
_LOG_CALLS = {"log", "log2", "log10"}
_SOFTMAX_CALLS = {"softmax", "log_softmax", "logsumexp"}

_RS_CALLS = {"reduce_scatter", "reduce_scatter_padded", "psum_scatter"}
_AG_CALLS = {"all_gather", "all_gather_unpad"}

# roots that make `root.fn(x)` a module call, not a method on an array
_MODULE_ROOTS = {"jnp", "np", "onp", "numpy", "jax", "lax", "nn", "pl",
                 "pltpu", "scipy", "special", "linalg", "random",
                 "collectives", "mx", "npx"}


def _receiver(call: ast.Call) -> Optional[ast.expr]:
    """The array receiver of a method call (``x.sum()`` -> ``x``), or
    None when the callee is a module function (``jnp.sum(x)``)."""
    if not isinstance(call.func, ast.Attribute):
        return None
    parts = call_target_parts(call)
    if parts and parts[0] in _MODULE_ROOTS:
        return None
    return call.func.value

_MASTER_RE_PARTS = ("master",)


def _is_master_name(name: str) -> bool:
    low = name.lower()
    return any(p in low for p in _MASTER_RE_PARTS)


# ---------------------------------------------------------------------------
# dtype environment (one per function, cached on the index)
# ---------------------------------------------------------------------------

class DtypeEnv:
    """Flow-insensitive dtype lattice over one function's locals.

    Optimistic fixpoint in the :class:`~tools.lint.tainting.Taint`
    style: bindings whose value dtype resolves join into ``types``;
    a name bound to two *different* concrete dtypes becomes a conflict
    (permanently unknown) so every rule stays silent on it.  Parameters
    start unknown — in-package evidence (``astype``, constructors,
    ``preferred_element_type``) is what seeds the lattice, which is
    exactly the precision/recall trade the zero-findings gate needs.
    """

    def __init__(self, index: PackageIndex, fi: FunctionInfo):
        self.index = index
        self.fi = fi
        self.module = fi.module
        self.types: Dict[str, str] = {}
        self.conflict: Set[str] = set()
        self.bindings = self._collect_bindings()
        for _ in range(3):
            changed = False
            for name, expr in self.bindings:
                dt = self.of(expr)
                if dt is None or name in self.conflict:
                    continue
                cur = self.types.get(name)
                if cur is None:
                    self.types[name] = dt
                    changed = True
                elif cur != dt:
                    self.conflict.add(name)
                    del self.types[name]
                    changed = True
            if not changed:
                break

    def _collect_bindings(self) -> List[Tuple[str, ast.expr]]:
        out: List[Tuple[str, ast.expr]] = []
        for node in self.index.shallow_nodes(self.fi):
            if isinstance(node, ast.Assign) and node.targets:
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        out.append((t.id, node.value))
                    elif isinstance(t, (ast.Tuple, ast.List)) and \
                            isinstance(node.value, (ast.Tuple, ast.List)) \
                            and len(t.elts) == len(node.value.elts):
                        for te, ve in zip(t.elts, node.value.elts):
                            if isinstance(te, ast.Name):
                                out.append((te.id, ve))
            elif isinstance(node, ast.AnnAssign) and \
                    node.value is not None and \
                    isinstance(node.target, ast.Name):
                out.append((node.target.id, node.value))
            elif isinstance(node, ast.AugAssign) and \
                    isinstance(node.target, ast.Name):
                # x += v : promote(x, v) via a synthetic BinOp
                out.append((node.target.id,
                            ast.BinOp(left=ast.Name(id=node.target.id,
                                                    ctx=ast.Load()),
                                      op=node.op, right=node.value)))
            elif isinstance(node, ast.NamedExpr) and \
                    isinstance(node.target, ast.Name):
                out.append((node.target.id, node.value))
        return out

    # -- dtype-valued expressions (jnp.float32, "bfloat16", x.dtype) ----
    def dtype_const(self, node: Optional[ast.expr], depth: int = 0
                    ) -> Optional[str]:
        if node is None or depth > 4:
            return None
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            return _DTYPE_NAMES.get(node.value)
        if isinstance(node, ast.Attribute):
            if node.attr == "dtype":
                return self.of(node.value, depth + 1)
            if node.attr in _DTYPE_NAMES and \
                    isinstance(node.value, ast.Name):
                return _DTYPE_NAMES[node.attr]
            return None
        if isinstance(node, ast.Name):
            if node.id in _DTYPE_NAMES:
                return _DTYPE_NAMES[node.id]
            # a parameter whose default is a dtype, or a local binding
            s = self.fi
            while s is not None:
                if not isinstance(s.node, ast.Lambda) and \
                        (node.id in s.param_names()
                         or node.id in s.kwonly_names()):
                    return self.dtype_const(s.default_expr(node.id),
                                            depth + 1)
                s = s.parent
            bound = _chase_name(self.index, self.module, self.fi, node.id)
            if bound is not None and bound is not node:
                return self.dtype_const(bound, depth + 1)
            return None
        if isinstance(node, ast.Call):
            name = call_target_name(node)
            if name == "dtype" and node.args:
                return self.dtype_const(node.args[0], depth + 1)
            if name == "promote_types" and len(node.args) == 2:
                return promote(self.dtype_const(node.args[0], depth + 1),
                               self.dtype_const(node.args[1], depth + 1))
            if name == "result_type" and node.args:
                out = None
                for a in node.args:
                    d = self.dtype_const(a, depth + 1) or \
                        self.of(a, depth + 1)
                    if d is None:
                        return None
                    out = d if out is None else promote(out, d)
                return out
        return None

    # -- array-expression dtype -----------------------------------------
    def of(self, node: Optional[ast.expr], depth: int = 0
           ) -> Optional[str]:
        if node is None or depth > 6:
            return None
        if isinstance(node, ast.Constant):
            if isinstance(node.value, bool):
                return "bool"
            if isinstance(node.value, float):
                return WEAK_FLOAT
            if isinstance(node.value, int):
                return WEAK_INT
            return None
        if isinstance(node, ast.Name):
            dt = self.types.get(node.id)
            if dt is not None or node.id in self.conflict:
                return dt
            # module-level / default-value Python constants are
            # weak-typed scalars (N_SHARDS, EPS, ...)
            bound = _chase_name(self.index, self.module, self.fi,
                                node.id)
            v = fold_or_none(bound) if bound is not None else None
            if isinstance(v, bool) or v is None:
                return None
            if isinstance(v, int):
                return WEAK_INT
            if isinstance(v, float):
                return WEAK_FLOAT
            return None
        if isinstance(node, ast.Attribute):
            if node.attr in ("T", "real", "mT"):
                return self.of(node.value, depth + 1)
            return None
        if isinstance(node, ast.Subscript):
            return self.of(node.value, depth + 1)
        if isinstance(node, ast.UnaryOp):
            return self.of(node.operand, depth + 1)
        if isinstance(node, ast.BinOp):
            return promote(self.of(node.left, depth + 1),
                           self.of(node.right, depth + 1))
        if isinstance(node, ast.Compare):
            return "bool"
        if isinstance(node, ast.IfExp):
            a = self.of(node.body, depth + 1)
            b = self.of(node.orelse, depth + 1)
            return a if a == b else None
        if isinstance(node, ast.Call):
            return self._call_dtype(node, depth + 1)
        return None

    def _kw(self, call: ast.Call, name: str) -> Optional[ast.expr]:
        for kw in call.keywords:
            if kw.arg == name:
                return kw.value
        return None

    def _call_dtype(self, call: ast.Call, depth: int) -> Optional[str]:
        name = call_target_name(call)
        parts = call_target_parts(call)
        root = parts[0] if parts else None
        is_np = root in NUMPY_ROOTS
        recv = _receiver(call)

        if name == "astype" and call.args:
            return self.dtype_const(call.args[0], depth)
        if name == "convert_element_type" and len(call.args) >= 2:
            return self.dtype_const(call.args[1], depth)
        if name in ("asarray", "array"):
            d = self._kw(call, "dtype")
            if d is None and len(call.args) >= 2:
                d = call.args[1]
            if d is not None:
                return self.dtype_const(d, depth)
            src = self.of(call.args[0], depth) if call.args else None
            if src in (WEAK_FLOAT, WEAK_INT) or src is None:
                if is_np and call.args and _has_float_literal(call.args[0]):
                    return "float64"      # numpy's default float
                return src
            return src
        if name in _CTOR_CALLS:
            d = self._kw(call, "dtype")
            if d is None:
                idx = {"full": 2}.get(name, 1)
                if name in ("zeros", "ones", "empty", "full") and \
                        len(call.args) > idx:
                    d = call.args[idx]
            if d is not None:
                return self.dtype_const(d, depth)
            if name in ("arange",):
                return None               # int or float, per args
            return "float64" if is_np else "float32"
        if name in _LIKE_CALLS:
            d = self._kw(call, "dtype")
            if d is not None:
                return self.dtype_const(d, depth)
            return self.of(call.args[0], depth) if call.args else None
        if name in _MATMUL_CALLS:
            pet = self._kw(call, "preferred_element_type")
            if pet is not None:
                return self.dtype_const(pet, depth)
            out = None
            operands = list(call.args)
            if recv is not None:
                operands.insert(0, recv)
            for a in operands:
                if isinstance(a, ast.Constant):
                    continue              # einsum spec string
                d = self.of(a, depth)
                if d is None:
                    return None
                out = d if out is None else promote(out, d)
            return out
        if name in _REDUCE_CALLS:
            d = self._kw(call, "dtype")
            if d is not None:
                return self.dtype_const(d, depth)
            op = recv if recv is not None else \
                (call.args[0] if call.args else None)
            return self.of(op, depth)
        if name in ("float",):
            return WEAK_FLOAT
        if name in ("int",):
            return WEAK_INT
        if name in _PASSTHROUGH_FIRST:
            op = recv if recv is not None else \
                (call.args[0] if call.args else None)
            return self.of(op, depth)
        if name in _PASSTHROUGH_JOIN:
            if name == "where" and len(call.args) >= 3:
                operands = list(call.args[1:3])
            elif name in ("concatenate", "stack") and call.args and \
                    isinstance(call.args[0], (ast.List, ast.Tuple)):
                operands = list(call.args[0].elts)
            else:
                operands = ([recv] if recv is not None else []) + \
                    [a for a in call.args
                     if not isinstance(a, ast.Constant)]
            out = None
            for a in operands:
                d = self.of(a, depth)
                if d is None:
                    return None
                out = d if out is None else promote(out, d)
            return out
        if recv is not None and name in ("copy", "conj"):
            return self.of(recv, depth)
        # one level of local-helper return-dtype resolution
        callee = self.index.resolve_call(self.module, self.fi, call.func)
        if callee is not None and depth <= 3:
            return _return_dtype(self.index, callee)
        return None


def _has_float_literal(node: ast.expr) -> bool:
    for sub in ast.walk(node):
        if isinstance(sub, ast.Constant) and \
                isinstance(sub.value, float):
            return True
    return False


def _env_for(index: PackageIndex, fi: FunctionInfo) -> Optional[DtypeEnv]:
    """Cached per-function DtypeEnv (None while under construction —
    recursive helper chains stay conservatively unknown)."""
    cache = getattr(index, "_numerics_envs", None)
    if cache is None:
        cache = index._numerics_envs = {}
    prog = getattr(index, "_numerics_in_progress", None)
    if prog is None:
        prog = index._numerics_in_progress = set()
    key = id(fi.node)
    if key in cache:
        return cache[key]
    if key in prog:
        return None
    prog.add(key)
    try:
        env = DtypeEnv(index, fi)
    finally:
        prog.discard(key)
    cache[key] = env
    return env


def _return_dtype(index: PackageIndex, fi: FunctionInfo) -> Optional[str]:
    """Dtype of a helper's single visible return expression."""
    if isinstance(fi.node, ast.Lambda):
        env = _env_for(index, fi)
        return env.of(fi.node.body) if env is not None else None
    rets = [r.value for r in index.shallow_nodes(fi)
            if isinstance(r, ast.Return) and r.value is not None]
    if len(rets) != 1:
        return None
    env = _env_for(index, fi)
    return env.of(rets[0]) if env is not None else None


# ---------------------------------------------------------------------------
# guard detection (max-shift, eps, upcast)
# ---------------------------------------------------------------------------

def _contains_call(node: ast.expr, names: Set[str]) -> bool:
    for sub in ast.walk(node):
        if isinstance(sub, ast.Call) and \
                call_target_name(sub) in names:
            return True
    return False


def _resolve_arg(env: DtypeEnv, node: ast.expr) -> ast.expr:
    """Chase a Name one step to the expression it was bound to, so a
    guard applied on the binding line still counts."""
    if isinstance(node, ast.Name):
        bound = _chase_name(env.index, env.module, env.fi, node.id)
        if bound is not None and bound is not node:
            return bound
    return node


def _is_max_shifted(env: DtypeEnv, arg: ast.expr) -> bool:
    """``x - max(x)`` (directly or through one binding) — the online /
    guarded-softmax shift that makes half-precision exp safe."""
    arg = _resolve_arg(env, arg)
    if isinstance(arg, ast.BinOp) and isinstance(arg.op, ast.Sub):
        rhs = arg.right
        if _contains_call(rhs, {"max", "amax", "stop_gradient"}):
            return True
        if isinstance(rhs, ast.Name):
            bound = _chase_name(env.index, env.module, env.fi, rhs.id)
            if bound is not None and \
                    _contains_call(bound, {"max", "amax"}):
                return True
    # exp(-|x|): bounded above by 1, cannot overflow
    if isinstance(arg, ast.UnaryOp) and isinstance(arg.op, ast.USub) and \
            _contains_call(arg.operand, {"abs", "absolute"}):
        return True
    return _contains_call(arg, {"clip", "minimum"})


def _is_eps_guarded(env: DtypeEnv, arg: ast.expr) -> bool:
    """``log(x + eps)`` / ``log(maximum(x, eps))`` style guards."""
    arg = _resolve_arg(env, arg)
    if isinstance(arg, ast.BinOp) and isinstance(arg.op, (ast.Add,
                                                          ast.Sub)):
        return True
    return _contains_call(arg, {"maximum", "clip", "where"})


def _is_explicit_cast(node: ast.expr) -> bool:
    return isinstance(node, ast.Call) and \
        call_target_name(node) in ("astype", "asarray",
                                   "convert_element_type")


# ---------------------------------------------------------------------------
# per-function rule pass
# ---------------------------------------------------------------------------

def _check_function(module: ModuleInfo, index: PackageIndex,
                    fi: FunctionInfo, findings: List[Finding]):
    env = _env_for(index, fi)
    if env is None:
        return
    ctx = fi.qualname
    rs_seen: List[Tuple[str, str, ast.Call]] = []   # (axis, dtype, call)
    ag_seen: List[Tuple[str, str, ast.Call, bool]] = []

    def emit(rule, node, msg):
        findings.append(Finding(rule, module.relpath, node.lineno,
                                node.col_offset, msg, ctx))

    for node in index.shallow_nodes(fi):
        # num-implicit-promotion / num-const-downcast on binary ops
        if isinstance(node, ast.BinOp) and not isinstance(
                node.op, (ast.BitOr, ast.BitAnd, ast.BitXor, ast.LShift,
                          ast.RShift)):
            a, b = env.of(node.left), env.of(node.right)
            if a in CONCRETE_FLOATS and b in CONCRETE_FLOATS and \
                    a != b and (a in HALF_FLOATS or b in HALF_FLOATS):
                emit("num-implicit-promotion", node,
                     "binary op mixes %s and %s — relies on silent "
                     "promotion to %s; cast explicitly (astype) or "
                     "align the dtypes" % (a, b, promote(a, b)))
            for side, other in ((node.left, b), (node.right, a)):
                if other != "float16":
                    continue
                v = fold_or_none(side)
                if isinstance(v, float) and abs(v) > _F16_MAX:
                    emit("num-const-downcast", node,
                         "weak literal %g exceeds the float16 finite "
                         "range (max %g) — the op computes in float16 "
                         "and overflows to inf" % (v, _F16_MAX))
            continue
        if not isinstance(node, ast.Call):
            continue
        name = call_target_name(node)
        parts = call_target_parts(node)
        recv = _receiver(node)

        # num-const-downcast: explicit float64, or numpy's f64 default
        dkw = env._kw(node, "dtype")
        if dkw is not None and env.dtype_const(dkw) == "float64":
            emit("num-const-downcast", node,
                 "dtype=float64 under disabled x64 — jax silently "
                 "downcasts to float32; request float32 (or enable "
                 "x64) explicitly")
        elif name == "astype" and node.args and \
                env.dtype_const(node.args[0]) == "float64":
            emit("num-const-downcast", node,
                 "astype(float64) under disabled x64 — jax silently "
                 "downcasts to float32")
        elif parts and parts[0] in NUMPY_ROOTS and dkw is None and (
                (name in ("array", "asarray") and node.args
                 and _has_float_literal(node.args[0]))
                or name == "linspace"):
            emit("num-const-downcast", node,
                 "numpy %s() defaults to float64 — under disabled x64 "
                 "the constant is silently downcast when it meets a "
                 "traced value; pass dtype= explicitly" % name)

        # num-implicit-promotion via jnp binary calls
        if name in _BINARY_CALLS:
            operands = node.args[1:3] if name == "where" \
                else node.args[:2]
            if len(operands) == 2:
                a, b = env.of(operands[0]), env.of(operands[1])
                if a in CONCRETE_FLOATS and b in CONCRETE_FLOATS and \
                        a != b and (a in HALF_FLOATS or
                                    b in HALF_FLOATS):
                    emit("num-implicit-promotion", node,
                         "%s() mixes %s and %s — relies on silent "
                         "promotion to %s; cast explicitly"
                         % (name, a, b, promote(a, b)))

        # num-lowprec-accum: reductions
        if name in _REDUCE_CALLS:
            dt = None
            if dkw is not None:
                dt = env.dtype_const(dkw)
            else:
                op = recv if recv is not None else \
                    (node.args[0] if node.args else None)
                dt = env.of(op)
            if dt in HALF_FLOATS:
                emit("num-lowprec-accum", node,
                     "%s() accumulates in %s — pass dtype=jnp.float32 "
                     "or upcast the operand first" % (name, dt))
        # num-lowprec-accum: contractions
        if name in _MATMUL_CALLS and \
                env._kw(node, "preferred_element_type") is None:
            operands = ([recv] if recv is not None else []) + \
                [a for a in node.args
                 if not isinstance(a, ast.Constant)]
            dts = [env.of(a) for a in operands]
            if any(d in HALF_FLOATS for d in dts):
                emit("num-lowprec-accum", node,
                     "%s() over %s inputs without "
                     "preferred_element_type — the MXU accumulator "
                     "stays low-precision; pass preferred_element_type"
                     "=jnp.float32" % (name, next(d for d in dts
                                                  if d in HALF_FLOATS)))

        # num-unstable-exp
        if name in _EXP_CALLS and node.args:
            dt = env.of(node.args[0])
            if dt in HALF_FLOATS and \
                    not _is_max_shifted(env, node.args[0]):
                emit("num-unstable-exp", node,
                     "%s() over %s without a max-shift — half floats "
                     "overflow/underflow fast; subtract the row max "
                     "or upcast to float32" % (name, dt))
        elif name in _LOG_CALLS and node.args:
            dt = env.of(node.args[0])
            if dt in HALF_FLOATS and \
                    not _is_eps_guarded(env, node.args[0]):
                emit("num-unstable-exp", node,
                     "%s() over %s without an eps-guard or upcast"
                     % (name, dt))
        elif name in _SOFTMAX_CALLS and node.args:
            dt = env.of(node.args[0])
            if dt in HALF_FLOATS:
                emit("num-unstable-exp", node,
                     "%s() over %s — the normalizer accumulates in "
                     "%s; upcast to float32 (re-quantize after)"
                     % (name, dt, dt))

        # num-master-dtype (c): update applied with a half operand
        if len(node.args) >= 2 and any(
                isinstance(a, ast.Name) and _is_master_name(a.id)
                for a in node.args):
            for a in node.args:
                if isinstance(a, ast.Name) and _is_master_name(a.id):
                    continue
                if env.of(a) in HALF_FLOATS:
                    emit("num-master-dtype", node,
                         "update applied to the fp32 master with a %s "
                         "operand — upcast it to float32 first"
                         % env.of(a))
                    break

        # num-master-dtype (a): astype round-trip through a half dtype.
        # DIRECT syntactic chains only: `m.astype(bf16).astype(f32)` is
        # an unambiguous precision drop, while upcasting a half value
        # held in a NAME is the legitimate compute-in-f32 idiom (the fix
        # the accumulation rule prescribes) and must stay clean.
        if name == "astype" and node.args and recv is not None:
            outer = env.dtype_const(node.args[0])
            inner_call = recv
            if outer in WIDE_FLOATS and \
                    isinstance(inner_call, ast.Call) and \
                    call_target_name(inner_call) == "astype" and \
                    inner_call.args and \
                    env.dtype_const(inner_call.args[0]) in HALF_FLOATS:
                emit("num-master-dtype", node,
                     "astype round-trip through %s back to %s — the "
                     "mantissa is already gone; keep the fp32 value "
                     "live instead" % (
                         env.dtype_const(inner_call.args[0]), outer))

        # num-collective-dtype bookkeeping
        if name in _RS_CALLS and node.args:
            axis = _collective_axis(env, node)
            dt = env.of(node.args[0])
            if axis is not None and dt is not None:
                rs_seen.append((axis, dt, node))
        elif name in _AG_CALLS and node.args:
            axis = _collective_axis(env, node)
            dt = env.of(node.args[0])
            if axis is not None and dt is not None:
                ag_seen.append((axis, dt, node,
                                _is_explicit_cast(node.args[0])))

    # num-master-dtype (b): master-named binding to a half value
    for bname, bexpr in env.bindings:
        if _is_master_name(bname) and env.of(bexpr) in HALF_FLOATS:
            findings.append(Finding(
                "num-master-dtype", module.relpath, bexpr.lineno,
                bexpr.col_offset,
                "fp32 master leaf %r assigned a %s value — the master "
                "must stay float32 end-to-end (multi_precision "
                "contract)" % (bname, env.of(bexpr)), ctx))

    # num-collective-dtype: asymmetric pairs over the same axis
    for ag_axis, ag_dt, ag_node, explicit in ag_seen:
        if explicit:
            continue          # intentional conversion (bf16 all-gather)
        for rs_axis, rs_dt, _rs in rs_seen:
            if rs_axis == ag_axis and rs_dt != ag_dt:
                findings.append(Finding(
                    "num-collective-dtype", module.relpath,
                    ag_node.lineno, ag_node.col_offset,
                    "reduce-scatter over axis %r runs in %s but the "
                    "paired all-gather moves %s — dtype-asymmetric "
                    "collective pair; make the conversion explicit "
                    "with astype (ZeRO working-dtype contract)"
                    % (ag_axis, rs_dt, ag_dt), ctx))
                break


def _collective_axis(env: DtypeEnv, call: ast.Call) -> Optional[str]:
    """The axis-name string of a collective call (literal, symbol via
    default/binding), or None when untrackable."""
    from .sharding import _axis_operand, _resolve_symbol
    # every _RS_CALLS/_AG_CALLS spelling is in sharding.COLLECTIVES,
    # which knows each one's axis-operand position
    cand = _axis_operand(call)
    if cand is None:
        return None
    if isinstance(cand, ast.Constant) and isinstance(cand.value, str):
        return cand.value
    if isinstance(cand, ast.Name):
        return _resolve_symbol(env.index, env.module, env.fi, cand.id) \
            or ("~" + cand.id)
    return None


# ---------------------------------------------------------------------------
# static dtype flow (the sanitizer cross-check table)
# ---------------------------------------------------------------------------

def static_dtype_flow(paths: Sequence[str],
                      root: Optional[str] = None) -> dict:
    """``{"<relpath>:<qualname>": {var: dtype}}`` — the statically
    derived dtype of every resolvable local in every jit-reachable
    function, for the runtime numerics sanitizer's observed-dtype
    consistency check (``tools.lint.runtime_numerics``), in the PR-6/7
    static-vs-runtime pattern.  Weak literals are omitted (they carry
    no committed dtype); conflicted names are omitted (unknown)."""
    import os
    from .core import collect_files, ModuleInfo as MI, _repo_root

    root = os.path.abspath(root) if root else _repo_root()
    modules = []
    for path in collect_files(paths):
        rel = os.path.relpath(os.path.abspath(path), root)
        try:
            with open(path, encoding="utf-8") as f:
                src = f.read()
        except OSError:
            continue
        try:
            modules.append(MI(path, rel, src))
        except SyntaxError:
            continue
    index = PackageIndex(modules)
    flow: Dict[str, Dict[str, str]] = {}
    for fi in index.functions:
        if not fi.reachable or isinstance(fi.node, ast.Lambda):
            continue
        env = _env_for(index, fi)
        if env is None:
            continue
        table = {n: d for n, d in env.types.items()
                 if d not in (WEAK_FLOAT, WEAK_INT)}
        if table:
            flow["%s:%s" % (fi.module.relpath, fi.qualname)] = table
    return flow


# ---------------------------------------------------------------------------

# cheap textual pre-filter: a module with none of these tokens cannot
# produce a finding (every rule needs dtype evidence or a collective)
_TOKENS = ("float16", "bfloat16", "float64", "half", "double",
           "astype", "preferred_element_type", "reduce_scatter",
           "all_gather", "master", "linspace", "np.array", "np.asarray",
           "onp.array", "onp.asarray", "numpy.array")


def check(module: ModuleInfo, index: PackageIndex) -> List[Finding]:
    findings: List[Finding] = []
    if not any(t in module.source for t in _TOKENS):
        return findings
    for fi in index.functions_in(module):
        if not fi.reachable or isinstance(fi.node, ast.Lambda):
            continue
        _check_function(module, index, fi, findings)
    return findings
